"""Worker for the 2-process cluster-telemetry test (ISSUE 8 fan-in).

Each process forms the jax.distributed cloud, bumps a probe counter by
a node-distinct amount, closes a node-distinct span, logs a
node-distinct line, publishes its snapshot, and records its local
scrape for the parent to compare against the merged ``?cluster=1``
views. Process 0 additionally serves REST; the parent drives the
scrape-merge-kill-stale scenario over HTTP, then drops a stop file.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# fast cadence so the kill→stale transition happens inside the test
# (0.5s beats keep the peer-staleness window at 1.5s — wide enough that
# GIL/scheduler pauses on a busy CI host don't flap peers unhealthy)
os.environ.setdefault("H2O3TPU_HEARTBEAT_INTERVAL_S", "0.5")
os.environ.setdefault("H2O3TPU_CLUSTER_METRICS_INTERVAL_S", "0.2")
os.environ.setdefault("H2O3TPU_CLUSTER_METRICS_STALE_S", "2.0")
# share compiled executables with the other worker processes (identical
# binaries out of jax's persistent cache; numerics unchanged)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0,
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, workdir = sys.argv[1:5]
pid = int(pid)

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=int(nproc), process_id=pid)

from h2o3_tpu import telemetry                # noqa: E402
from h2o3_tpu.telemetry import cluster        # noqa: E402
from h2o3_tpu.utils.log import get_logger     # noqa: E402

# node-distinct telemetry the parent asserts on in the merged views
telemetry.counter("cluster_probe_total").inc(100 * (pid + 1))
with telemetry.span(f"clw.node{pid}"):
    pass
get_logger("clw").warning("clw-log-node%d", pid)
assert cluster.publish(force=True), "snapshot publish failed"

with open(os.path.join(workdir, f"node{pid}.json"), "w") as f:
    json.dump({"node": pid,
               "probe": telemetry.REGISTRY.value("cluster_probe_total")},
              f)

STOP = os.path.join(workdir, "stop")
DEADLINE = time.time() + 180.0

if pid == 0:
    from h2o3_tpu.api.server import start_server
    port = start_server(port=0, background=True)
    with open(os.path.join(workdir, "port.txt"), "w") as f:
        f.write(str(port))
print(f"CLUSTER-WORKER-{pid}-READY", flush=True)

while time.time() < DEADLINE and not os.path.exists(STOP):
    time.sleep(0.05)

# the peer may already be SIGKILLed: a cooperative shutdown would wait
# on the dead coordination channel, so exit hard — KV-sweep-on-shutdown
# has its own single-process unit test (test_cluster_telemetry.py)
print(f"CLUSTER-WORKER-{pid}-DONE", flush=True)
os._exit(0)
