"""Fleet serving resilience (ISSUE 17, serving/fleet.py).

Three layers:

- :class:`ReplicaRouter` unit tests — the pure routing/failover state
  machine on injected providers (jax-free, the bench ``_stub_fleet``
  contract): decision table, heartbeat exclusion, local bias, bounded
  hedging, explicit degradation.
- Single-process registry + REST tests — publish/install round trip,
  governor declines, eviction deregistration, drain semantics, and the
  degraded REST answers (503 + Retry-After, 307 redirect, draining).
- ``multiprocess`` acceptance — a REAL 2-process jax.distributed CPU
  cloud (tests/fleet_worker.py): cross-node routed predictions are
  bit-identical to ``Model.predict``; SIGKILLing the only replica is
  excluded within one heartbeat window, the error burst is bounded by
  hedged failover onto a local install, and the survivor drains clean.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.core import request_ctx, watchdog
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.serving import fleet
from h2o3_tpu.serving.fleet import (FleetUnavailable, ReplicaRouter,
                                    RoutePlan, SERVE_LOCALLY)
from h2o3_tpu.telemetry import REGISTRY

# the fleet registry and scoring engine are process-global by design;
# REST handler threads create keys the thread-local Scope cannot track
pytestmark = pytest.mark.allow_key_leak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_worker.py")
WORKER_TIMEOUT_S = 300.0


# ------------------------------------------------------ router units


def _router(self_pid=0, reps=None, eps=None, dead=(), loads=None,
            draining=False, published=(), bias=2.0):
    reps = reps if reps is not None else {}
    eps = eps if eps is not None else {}
    loads = loads if loads is not None else {}
    return ReplicaRouter(
        self_pid=self_pid,
        replicas_fn=lambda mk: dict(reps.get(mk, {})),
        endpoints_fn=lambda: dict(eps),
        dead_fn=lambda: set(dead),
        loads_fn=lambda: dict(loads),
        draining_fn=lambda: draining,
        published_fn=lambda mk: mk in published,
        local_bias=bias)


def test_plan_local_when_replica_is_local():
    r = _router(reps={"m": {0: {}}}, eps={0: ("h", 1)})
    assert r.plan("m", have_local=True).decision == "local"
    # a bare DKV copy (never registered) also serves locally
    assert _router().plan("m", have_local=True).decision == "local"


def test_plan_proxies_to_least_loaded_remote():
    r = _router(reps={"m": {1: {}, 2: {}}},
                eps={1: ("h", 1), 2: ("h", 2)},
                loads={1: 5.0, 2: 1.0})
    p = r.plan("m", have_local=False)
    assert p.decision == "proxy" and p.pid == 2
    assert "_fleet_hop=1" in p.url


def test_plan_excludes_heartbeat_dead_peers():
    r = _router(reps={"m": {1: {}, 2: {}}},
                eps={1: ("h", 1), 2: ("h", 2)},
                loads={1: 0.0, 2: 9.0}, dead={1})
    assert r.plan("m", have_local=False).pid == 2
    # every replica dead, nothing local or published -> none (404)
    r = _router(reps={"m": {1: {}}}, eps={1: ("h", 1)}, dead={1})
    assert r.plan("m", have_local=False).decision == "none"


def test_plan_hop_never_reroutes():
    """Loop prevention: an already-routed request either serves here or
    installs here — it NEVER bounces to a third node."""
    r = _router(reps={"m": {1: {}}}, eps={1: ("h", 1)})
    assert r.plan("m", have_local=True, hop=True).decision == "local"
    assert r.plan("m", have_local=False, hop=True).decision == "install"


def test_plan_local_bias_keeps_marginal_wins_local():
    reps = {"m": {0: {}, 1: {}}}
    eps = {1: ("h", 1)}
    # remote barely less loaded: the bias keeps the request local
    r = _router(reps=reps, eps=eps, loads={0: 3.0, 1: 2.0}, bias=2.0)
    assert r.plan("m", have_local=True).decision == "local"
    # remote idle, local swamped: route away
    r = _router(reps=reps, eps=eps, loads={0: 9.0, 1: 0.0}, bias=2.0)
    p = r.plan("m", have_local=True)
    assert p.decision == "proxy" and p.pid == 1


def test_plan_install_when_only_published():
    r = _router(published={"m"})
    assert r.plan("m", have_local=False).decision == "install"
    assert _router().plan("m", have_local=False).decision == "none"


def test_plan_draining_routes_away_but_still_serves_sole_copy():
    reps = {"m": {0: {}, 1: {}}}
    r = _router(reps=reps, eps={1: ("h", 1)}, draining=True)
    assert r.plan("m", have_local=True).decision == "proxy"
    # draining with NO healthy remote: a held model still answers
    # (the batcher's draining contract turns queued work into 503s)
    r = _router(reps={"m": {0: {}}}, draining=True)
    assert r.plan("m", have_local=True).decision == "local"


def test_plan_redirect_carries_hop_marked_url():
    r = _router(reps={"m": {1: {}}}, eps={1: ("hh", 8080)})
    p = r.plan("m", have_local=False, redirect=True)
    assert p.decision == "redirect"
    assert p.url.startswith("http://hh:8080/3/Predictions/models/")
    assert "_fleet_hop=1" in p.url


def test_hedged_fails_over_to_next_replica():
    r = _router(reps={"m": {1: {}, 2: {}}},
                eps={1: ("h", 1), 2: ("h", 2)},
                loads={1: 0.0, 2: 1.0})
    before = REGISTRY.value("predict_failovers_total",
                            reason="connection")
    calls = []

    def attempt(pid, ep):
        calls.append(pid)
        if pid == 1:
            raise ConnectionRefusedError("replica died")
        return {"ok": pid}

    assert r.hedged("m", attempt) == {"ok": 2}
    assert calls == [1, 2]
    assert REGISTRY.value("predict_failovers_total",
                          reason="connection") == before + 1


def test_hedged_exhaustion_raises_retryable_unavailable():
    r = _router(reps={"m": {1: {}}}, eps={1: ("h", 1)})

    def attempt(pid, ep):
        raise ConnectionRefusedError("down")

    with pytest.raises(FleetUnavailable) as ei:
        r.hedged("m", attempt)
    assert ei.value.retry_after_s > 0


def test_hedged_local_fallback_sentinel():
    r = _router(reps={"m": {1: {}}}, eps={1: ("h", 1)})
    out = r.hedged("m", lambda pid, ep: 1 / 0, local_fallback=True)
    assert out is SERVE_LOCALLY
    # no candidates at all + fallback: straight to the sentinel
    assert _router().hedged("m", lambda pid, ep: 1 / 0,
                            local_fallback=True) is SERVE_LOCALLY


def test_hedged_never_hedges_client_errors():
    """A 4xx-shaped failure (bad rows) would fail identically on every
    replica — it must surface once, not burn the hop budget."""
    r = _router(reps={"m": {1: {}, 2: {}}},
                eps={1: ("h", 1), 2: ("h", 2)})
    calls = []

    def attempt(pid, ep):
        calls.append(pid)
        raise fleet._Passthrough(ValueError("bad rows"))

    with pytest.raises(fleet._Passthrough):
        r.hedged("m", attempt)
    assert len(calls) == 1


def test_hedged_respects_deadline_budget():
    r = _router(reps={"m": {1: {}}}, eps={1: ("h", 1)})
    with pytest.raises(request_ctx.DeadlineExceeded):
        r.hedged("m", lambda pid, ep: {"ok": 1},
                 deadline=time.monotonic() - 0.1)


def test_hedged_bounded_by_max_hops():
    reps = {"m": {p: {} for p in range(1, 9)}}
    eps = {p: ("h", p) for p in range(1, 9)}
    r = _router(reps=reps, eps=eps,
                loads={p: float(p) for p in range(1, 9)})
    calls = []

    def attempt(pid, ep):
        calls.append(pid)
        raise ConnectionRefusedError("down")

    with pytest.raises(FleetUnavailable):
        r.hedged("m", attempt, max_hops=3)
    assert len(calls) == 3


# ------------------------------------------- registry (single process)


def _train_gbm():
    r = np.random.RandomState(21)
    n = 300
    fr = h2o3_tpu.Frame.from_numpy({
        "a": r.randn(n), "b": r.randn(n),
        "y": r.randn(n)})
    from h2o3_tpu.models.gbm import GBMEstimator
    return GBMEstimator(ntrees=3, max_depth=3, seed=2).train(fr, y="y"), fr


@pytest.fixture(scope="module")
def gbm():
    m, fr = _train_gbm()
    yield m, fr
    fleet.reset()


@pytest.fixture(autouse=True)
def _fleet_clean():
    fleet.reset()
    yield
    fleet.reset()


def test_replicate_publish_install_roundtrip(gbm):
    """The tentpole data plane: publish once (idempotent), install on a
    'peer' (same process, DKV copy dropped), predictions unchanged."""
    m, fr = gbm
    base = m.predict(fr).col("predict").to_numpy()
    assert fleet.replicate(m) is True
    assert fleet.publish(m) is False                 # idempotent
    meta = fleet.published(m.key)
    assert meta and meta["parts"] >= 1 and meta["algo"] == m.algo
    assert m.key in fleet.registered_models()
    assert str(m.key) in fleet.stats()["local_replicas"]

    DKV.remove(m.key)
    m2 = fleet.install_published(m.key)
    assert DKV.get(m.key) is m2
    out = m2.predict(fr).col("predict").to_numpy()
    assert np.array_equal(base, out)

    with pytest.raises(KeyError):
        fleet.install_published("no-such-model")


def test_register_declines_over_hbm_reservation(gbm, monkeypatch):
    """Governor-aware registration: a peer over its HBM budget DECLINES
    (returns False, registry untouched) instead of warming into an OOM."""
    m, _fr = gbm
    from h2o3_tpu.core import memgov

    def _no_room(model_key, nbytes):
        raise memgov.MemoryBudgetExceeded(
            f"no room for {model_key} ({nbytes}B)")

    monkeypatch.setattr(memgov.governor, "admit_replica", _no_room)
    assert fleet.register_local(m) is False
    assert str(m.key) not in fleet.stats()["local_replicas"]
    assert m.key not in fleet.registered_models()


def test_scorer_eviction_deregisters_replica(gbm):
    """Engine eviction is a registry event: the evicted scorer's
    replica leaves the routing table (maybe_adopt re-warms elsewhere)."""
    m, _fr = gbm
    from h2o3_tpu.serving.engine import engine
    assert fleet.replicate(m) is True
    assert engine.evict() >= 1
    assert str(m.key) not in fleet.stats()["local_replicas"]
    assert m.key not in fleet.registered_models()


def test_drain_deregisters_and_blocks_new_registrations(gbm):
    m, _fr = gbm
    assert fleet.replicate(m) is True
    fleet.drain()
    st = fleet.stats()
    assert st["draining"] is True
    assert st["local_replicas"] == []
    assert st["endpoint"] is None
    # a draining peer never takes NEW replicas
    assert fleet.register_local(m) is False
    from h2o3_tpu.serving.engine import engine
    assert engine.warm_models() == []


def test_register_fault_site_is_injectable(gbm):
    m, _fr = gbm
    watchdog.inject_fault("replica_register", times=1)
    try:
        with pytest.raises(Exception) as ei:
            fleet.register_local(m)
        assert watchdog.is_infra_error(ei.value)
    finally:
        watchdog.clear_faults()
    assert fleet.register_local(m) is True


def test_batcher_draining_rejects_with_its_own_class():
    from h2o3_tpu.serving.batcher import (BatcherDraining, MicroBatcher,
                                          PendingScore)
    mb = MicroBatcher("fleet-drain-test", lambda b: None,
                      max_rows=4, wait_ms=0.0, queue_depth=4)
    mb.close()
    with pytest.raises(BatcherDraining):
        mb.submit(PendingScore({"x": np.zeros(1)}, 1))


# --------------------------------------------------- degraded REST


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _dead_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _fake_remote_replica(mkey, pid=9):
    """Registry rows for a 'peer' whose REST edge is a closed port."""
    kv = fleet._local_kv
    kv.key_value_set(f"{fleet.KV_PREFIX}rep/{mkey}/{pid}",
                     json.dumps({"pid": pid, "algo": "gbm"}))
    kv.key_value_set(f"{fleet.KV_PREFIX}ep/{pid}",
                     json.dumps({"host": "127.0.0.1",
                                 "port": _dead_port()}))


def _post_rows(port, mkey, opener=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/3/Predictions/models/"
        f"{urllib.parse.quote(str(mkey), safe='')}",
        data=json.dumps({"rows": [{"a": 1.0, "b": 2.0}]}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    open_fn = opener.open if opener else urllib.request.urlopen
    try:
        with open_fn(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_unknown_model_is_404_not_a_hang(port):
    code, _hdrs, body = _post_rows(port, "fleet-no-such-model")
    assert code == 404
    assert "fleet-no-such-model" in body["msg"]


def test_all_replicas_unreachable_503_with_retry_after(port, monkeypatch):
    """Acceptance: every replica down → 503 + Retry-After in H2OErrorV3
    shape, never a hang. The only replica's edge refuses connections and
    this node holds neither a copy nor the published binary."""
    monkeypatch.setenv("H2O3TPU_FLEET_RETRY_AFTER_S", "2")
    _fake_remote_replica("fleet-unreachable-m")
    t0 = time.monotonic()
    code, hdrs, body = _post_rows(port, "fleet-unreachable-m")
    assert code == 503
    assert hdrs.get("Retry-After") == "2"
    assert body["http_status"] == 503          # H2OErrorV3 shape
    assert "no healthy replica" in body["msg"]
    assert time.monotonic() - t0 < 20.0        # bounded, not a hang
    assert REGISTRY.value("rest_rejected_total",
                          reason="fleet_unavailable") >= 1
    assert REGISTRY.value("predict_failovers_total",
                          reason="connection") >= 1


def test_redirect_mode_returns_307_with_location(port, monkeypatch):
    """H2O3TPU_FLEET_REDIRECT=1 turns proxying into a 307 whose
    Location is the replica's hop-marked predict URL."""
    monkeypatch.setenv("H2O3TPU_FLEET_REDIRECT", "1")
    _fake_remote_replica("fleet-redirect-m")

    class _NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    code, hdrs, body = _post_rows(
        port, "fleet-redirect-m",
        opener=urllib.request.build_opener(_NoRedirect))
    assert code == 307
    loc = hdrs.get("Location")
    assert loc and "/3/Predictions/models/fleet-redirect-m" in loc
    assert "_fleet_hop=1" in loc
    assert body["location"] == loc


def test_rest_serves_via_install_when_only_published(port, gbm):
    """A node holding neither the model nor a healthy remote replica
    installs the published binary and answers — node symmetry."""
    m, fr = gbm
    base = m.predict(fr).col("predict").to_numpy()
    fleet.publish(m)
    DKV.remove(m.key)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/3/Predictions/models/"
        f"{urllib.parse.quote(str(m.key), safe='')}",
        data=json.dumps({"rows": [{"a": float(fr.col('a').to_numpy()[0]),
                                   "b": float(fr.col('b').to_numpy()[0])}
                                  ]}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        out = json.loads(r.read())
    assert out["rows_scored"] == 1
    assert out["predictions"]["predict"][0] == float(base[0])
    assert str(m.key) in fleet.stats()["local_replicas"]


# ------------------------------------------------- real 2-process cloud


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(mode, nproc, out):
    """Run one worker pod; returns (returncodes, logs)."""
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, str(nproc), str(i), out, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(nproc)
    ]
    logs = []
    deadline = time.time() + WORKER_TIMEOUT_S
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(deadline - time.time(), 1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + \
                f"\n[TIMEOUT after {WORKER_TIMEOUT_S:.0f}s]"
        logs.append(stdout)
    return [p.returncode for p in procs], logs


def _read(out, pid):
    with open(f"{out}.{pid}") as f:
        return json.load(f)


@pytest.fixture(scope="module")
def fleet_results(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet")
    legs = {}
    for mode in ("serve", "kill"):
        out = str(tmp / f"{mode}.json")
        rcs, logs = _launch(mode, 2, out)
        legs[mode] = {"rcs": rcs, "logs": logs, "out": out}
    return legs


def _logs(leg):
    return "\n".join(f"--- worker {i} log ---\n{lg[-3000:]}"
                     for i, lg in enumerate(leg["logs"]))


@pytest.mark.slow
@pytest.mark.multiprocess
def test_fleet_cross_node_predicts_bit_identical(fleet_results):
    """Node symmetry: the node WITHOUT the model answers predicts via
    the fleet (proxied to the replica), bit-identical to Model.predict,
    under concurrent load, with zero client-visible errors."""
    leg = fleet_results["serve"]
    assert all(rc == 0 for rc in leg["rcs"]), _logs(leg)
    r1 = _read(leg["out"], 1)
    assert r1["errors"] == []
    assert r1["n_ok"] == 32
    assert r1["all_identical"], (r1["preds"], r1["ref"])
    assert r1["routed"]["proxy"] >= 32
    r0 = _read(leg["out"], 0)
    assert r0["replicas"] == [0]
    assert str(r0["stats"]["local_replicas"])  # replica stayed warm


@pytest.mark.slow
@pytest.mark.multiprocess
def test_fleet_sigkill_failover_and_drain(fleet_results):
    """Acceptance: SIGKILL the only replica mid-load. The dead peer is
    excluded within one heartbeat staleness window (+ scheduling slack),
    hedged failover onto a local install bounds the error burst, every
    successful answer stays bit-identical, and the survivor drains."""
    leg = fleet_results["kill"]
    assert leg["rcs"][0] == 0, _logs(leg)
    assert leg["rcs"][1] == -signal.SIGKILL
    r0 = _read(leg["out"], 0)

    # steady state before the kill: all proxied, all correct
    assert r0["phase_a"]["errors"] == [], r0["phase_a"]
    assert r0["phase_a"]["identical"]

    # the burst: bounded errors, correct answers, hedging visible
    pb = r0["phase_b"]
    assert pb["n_ok"] + len(pb["errors"]) == 40
    assert len(pb["errors"]) <= 8, pb["errors"]
    assert pb["identical"]
    assert sum(r0["failovers"].values()) >= 1, r0["failovers"]
    assert r0["local_replica_after"] is True

    # exclusion within one heartbeat window (staleness = interval*3),
    # plus generous CI scheduling slack
    assert r0["detect_s"] < r0["hb_window_s"] + 4.0, r0["detect_s"]

    # post-exclusion: clean, local, correct
    assert r0["phase_c"]["errors"] == [], r0["phase_c"]
    assert r0["phase_c"]["identical"]

    # survivor drained: registry empty + marked, engine cold
    assert r0["stats_after_drain"]["draining"] is True
    assert r0["stats_after_drain"]["local_replicas"] == []
    assert r0["engine_warm_after_drain"] == []
