"""Cluster-scope telemetry plane (ISSUE 8): cross-process metric/trace/
log fan-in over the coordination-service KV store + roofline (MFU/HBM)
accounting.

Tier-1 legs: merge semantics on synthetic peer snapshots (counters
summed, gauges/histograms node-labeled, staleness, Prometheus grammar,
fused traces, ordered logs), the single-process contract (?cluster=1
is exactly the local view), the shutdown KV sweep, node stamping, and
the roofline path — per-fit MFU gauges/capsule annotations plus the
cost_analysis-vs-analytic 2x agreement on loop-free program units.

The ``multiprocess`` leg drives the real thing: a 2-process CPU cloud,
merged scrapes over HTTP, and a SIGKILLed peer degrading to
labeled-stale responses instead of a hang or 500.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import cluster, flight_recorder, roofline
from h2o3_tpu.utils import log as logmod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------- fake KV peer


class _FakeKV:
    def __init__(self):
        self.store = {}
        self.deleted = []

    def key_value_set(self, k, v, allow_overwrite=True):
        self.store[k] = v

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in self.store.items()
                if k.startswith(prefix)]

    def key_value_delete(self, k):
        self.deleted.append(k)
        self.store.pop(k, None)


def _peer_snapshot(node=1, ts=None, probe_name="h2o3tpu_cluz_probe_total",
                   probe_value=200.0):
    return {
        "node": node, "ts": time.time() if ts is None else ts,
        "seq": 1, "host": "peerhost", "pid": 4242,
        "devices": [f"FAKE_CPU_{node}"],
        "metrics": {
            "counters": [{"name": probe_name, "labels": {},
                          "value": probe_value}],
            "gauges": [{"name": "h2o3tpu_cluz_gauge", "labels": {},
                        "value": 7.0}],
            "histograms": [{"name": "h2o3tpu_cluz_seconds", "labels": {},
                            "count": 3, "sum": 0.5,
                            "buckets": [[0.1, 1], [1.0, 3]]}],
        },
        "spans": [{"id": "sp-p1", "parent_id": None, "name": "peer.work",
                   "start_ms": 1000, "duration_ms": 5.0,
                   "device_peak_bytes": 0, "collective_bytes": 0,
                   "meta": {}}],
        "events": [{"seq": 1, "ts_ms": 1001, "kind": "peer",
                    "what": "peer-moment"}],
        "compiles": [{"ts_ms": 1002, "dur_s": 0.01,
                      "event": "xla_compile"}],
        "logs": [{"ts_ms": 1500, "level": "WARNING",
                  "line": "peer-log-line", "node": node}],
        "jobs_inflight": 2,
        "peak_hbm": 12345,
    }


@pytest.fixture()
def two_node(monkeypatch):
    """Pretend this process is node 0 of a 2-process cloud whose peer 1
    publishes over a fake KV client."""
    fake = _FakeKV()
    monkeypatch.setattr(cluster, "_client", lambda: fake)
    monkeypatch.setattr(cluster, "_identity", lambda: (0, 2))
    cluster.reset()
    yield fake
    cluster.reset()


# ---------------------------------------------------- merge semantics


def test_merged_counters_summed_across_nodes(two_node):
    telemetry.counter("cluz_probe_total").inc(100)
    two_node.key_value_set("h2o3tpu/telemetry/1",
                           cluster._encode(_peer_snapshot()))
    col = cluster.collect()
    assert col["stale_nodes"] == []
    m = cluster.merged_metrics(col)
    probe = [c for c in m["counters"]
             if c["name"] == "h2o3tpu_cluz_probe_total"]
    assert len(probe) == 1
    assert probe[0]["value"] == pytest.approx(
        telemetry.REGISTRY.value("cluz_probe_total") + 200.0)


def test_merged_gauges_and_histograms_carry_node_label(two_node):
    telemetry.gauge("cluz_gauge").set(3.0)
    telemetry.histogram("cluz_seconds").observe(0.2)
    two_node.key_value_set("h2o3tpu/telemetry/1",
                           cluster._encode(_peer_snapshot()))
    m = cluster.merged_metrics()
    gz = [g for g in m["gauges"] if g["name"] == "h2o3tpu_cluz_gauge"]
    assert {g["labels"]["node"] for g in gz} == {"0", "1"}
    hs = [h for h in m["histograms"]
          if h["name"] == "h2o3tpu_cluz_seconds"]
    assert {h["labels"]["node"] for h in hs} == {"0", "1"}
    # per-node histograms keep their own bucket vectors
    peer_h = next(h for h in hs if h["labels"]["node"] == "1")
    assert peer_h["count"] == 3 and peer_h["sum"] == 0.5


def test_merged_prometheus_grammar(two_node):
    telemetry.counter("cluz_probe_total").inc(0)
    two_node.key_value_set("h2o3tpu/telemetry/1",
                           cluster._encode(_peer_snapshot()))
    text = cluster.merged_prometheus()
    assert "# TYPE h2o3tpu_cluz_probe_total counter" in text
    assert '# TYPE h2o3tpu_cluz_gauge gauge' in text
    assert 'h2o3tpu_cluz_gauge{node="1"} 7' in text
    assert 'h2o3tpu_cluz_seconds_bucket{node="1",le="+Inf"} 3' in text
    assert 'h2o3tpu_cluz_seconds_count{node="1"} 3' in text


def test_stale_peer_is_labeled_but_still_served(two_node):
    two_node.key_value_set(
        "h2o3tpu/telemetry/1",
        cluster._encode(_peer_snapshot(ts=time.time() - 3600)))
    col = cluster.collect()
    assert col["stale_nodes"] == [1]
    assert 1 in col["nodes"]          # last data serves, labeled stale
    m = cluster.merged_metrics(col)
    assert any(c["name"] == "h2o3tpu_cluz_probe_total"
               for c in m["counters"])


def test_missing_peer_and_kv_failure_never_raise(two_node):
    # peer never published at all
    col = cluster.collect()
    assert col["stale_nodes"] == [1] and 1 not in col["nodes"]

    # the KV read itself blowing up degrades to all-peers-stale
    def _boom(prefix):
        raise RuntimeError("coordination service down")
    two_node.key_value_dir_get = _boom
    col = cluster.collect()
    assert col["stale_nodes"] == [1]


def test_garbled_snapshot_is_a_miss_not_a_crash(two_node):
    two_node.key_value_set("h2o3tpu/telemetry/1", "z:not-base64!!")
    col = cluster.collect()
    assert col["stale_nodes"] == [1]


def test_merged_trace_one_track_group_per_node(two_node):
    snap = _peer_snapshot(ts=time.time() - 3600)     # peer stale
    two_node.key_value_set("h2o3tpu/telemetry/1", cluster._encode(snap))
    with telemetry.span("cluz.local_span"):
        pass
    trace = cluster.merged_trace()
    evs = trace["traceEvents"]
    assert all({"ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    span_evs = [e for e in evs if e.get("cat") == "span"]
    by_name = {e["name"]: e for e in span_evs}
    assert by_name["cluz.local_span"]["pid"] == 0
    assert by_name["peer.work"]["pid"] == 1
    # process_name metadata labels each node's track group; the stale
    # peer says so right in the label
    names = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "node 0" in names[0]
    assert "node 1" in names[1] and "[stale]" in names[1]
    assert trace["otherData"]["stale_nodes"] == [1]
    json.dumps(trace)


def test_merged_logs_timestamp_ordered_with_node_ids(two_node):
    from h2o3_tpu.utils.log import get_logger
    get_logger("cluz").warning("cluz-local-log")
    two_node.key_value_set(
        "h2o3tpu/telemetry/1",
        cluster._encode(_peer_snapshot()))    # peer line ts_ms=1500
    merged = cluster.merged_logs()
    assert any("peer-log-line" in ln for ln in merged["lines"])
    assert any("cluz-local-log" in ln for ln in merged["lines"])
    # the 1970-epoch peer line sorts first; every line carries its node
    assert merged["lines"][0] == "[node 1] peer-log-line"
    ts = [r["ts_ms"] for r in merged["records"]]
    assert ts == sorted(ts)


def test_publish_rate_limit_and_single_process_noop(two_node):
    assert cluster.publish(force=True)
    assert "h2o3tpu/telemetry/0" in two_node.store
    assert cluster._decode(two_node.store["h2o3tpu/telemetry/0"])[
        "node"] == 0
    # inside the interval the piggybacked publish is a no-op
    assert cluster.maybe_publish() is False


def test_publish_is_noop_on_single_process_cloud(monkeypatch):
    monkeypatch.setattr(cluster, "_identity", lambda: (0, 1))
    cluster.reset()
    assert cluster.publish(force=True) is False


# ------------------------------------- single-process contract (REST)


def _assert_handler_identical(fn, params_cluster, params_local):
    # two quick successive direct calls; retry once in case a stray
    # background record lands exactly between the pair
    for _ in range(2):
        a = fn(dict(params_cluster), "")
        b = fn(dict(params_local), "")
        if a == b:
            return
    assert a == b


def test_cluster_views_equal_local_on_single_process():
    """Satellite acceptance: with process_count()==1, ?cluster=1 is
    bit-identical to the local view on all three endpoints."""
    from h2o3_tpu.api.server import _logs, _metrics, _process_trace
    _assert_handler_identical(_metrics, {"cluster": "1"}, {})
    _assert_handler_identical(_process_trace, {"cluster": "1"}, {})
    _assert_handler_identical(_logs, {"cluster": "1"}, {})
    # prometheus leg too
    a = _metrics({"cluster": "1", "format": "prometheus"}, "")
    b = _metrics({"format": "prometheus"}, "")
    assert a["__bytes__"] == b["__bytes__"]


def test_cloud_nodes_carry_metrics_summary():
    """Satellite: /3/Cloud per-node blocks gain the fan-in summary and
    the published process identity (no more default-0 guess)."""
    from h2o3_tpu.api.server import _cloud
    out = _cloud({}, "")
    assert out["nodes"], "no nodes in /3/Cloud"
    for nd in out["nodes"]:
        assert "metrics_summary" in nd
        assert nd["process_index"] == 0
        assert nd["gflops"] > 0
        ms = nd["metrics_summary"]
        assert {"jobs_inflight", "last_publish_age_s", "peak_hbm",
                "stale"} <= set(ms)
        assert ms["stale"] is False


# --------------------------------------------- shutdown KV sweep


def test_shutdown_sweeps_own_coordination_keys(monkeypatch):
    """Satellite: shutdown() deletes this process's heartbeat, roll-call
    and telemetry KV entries so a reformed cloud reads no ghosts."""
    from jax._src import distributed
    from h2o3_tpu.core import cloud as cloud_mod
    fake = _FakeKV()
    monkeypatch.setattr(distributed.global_state, "client", fake)
    cloud_mod._sweep_coordination_keys()
    # the serving fleet (ISSUE 17) and the durable data plane's frame
    # registry (ISSUE 18) sweep their per-process keys here too
    assert set(fake.deleted) == {"h2o3tpu/hb/0", "h2o3tpu/boot/0",
                                 "h2o3tpu/telemetry/0",
                                 "h2o3tpu/fleet/ep/0",
                                 "h2o3tpu/dur/reg/0/"}


# ------------------------------------------------------ node stamping


def test_log_records_and_capsules_stamped_with_node():
    """Satellite: every JSON log record and flight-recorder capsule
    carries the process's node id once cloud.init stamps it."""
    from h2o3_tpu.core.job import DONE, Job
    from h2o3_tpu.utils.log import get_logger
    logmod.set_node(3)
    try:
        get_logger("cluz_node").warning("cluz-node-stamp-probe")
        rec = next(r for r in reversed(logmod.log_records())
                   if "cluz-node-stamp-probe" in r["line"])
        assert rec["node"] == 3

        j = Job("cluz node capsule").start(lambda job: "ok")
        assert j.status == DONE
        cap = flight_recorder.get_capsule(j.key)
        assert cap.to_dict()["node"] == 3
    finally:
        logmod.set_node(0)


def test_json_formatter_includes_node():
    import logging
    logmod.set_node(5)
    try:
        fmt = logmod.JsonFormatter()
        rec = logging.LogRecord("h2o3_tpu.x", logging.INFO, "f", 1,
                                "msg", (), None)
        logmod.ContextFilter().filter(rec)
        assert json.loads(fmt.format(rec))["node"] == 5
    finally:
        logmod.set_node(0)


# --------------------------------------------------------- roofline


def _mk_class_frame(n, f, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(f)}
    cols["y"] = np.array(["a", "b"], object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


def test_device_peaks_nonzero_and_tpu_table():
    p = roofline.device_peaks()
    assert p["flops"] > 0 and p["hbm_bytes_per_s"] > 0
    assert p["devices"] == 8          # the conftest mesh
    assert roofline.peaks_for("TPU v5 lite")["flops"] == 197e12
    assert roofline.peaks_for("TPU v5p")["flops"] == 459e12
    assert roofline.peaks_for("", "cpu")["source"] == "cpu-estimate"


def test_analytic_estimators_positive_and_scaling():
    t1 = roofline.analytic_tree_cost(1000, 10, 50, 6, 65)
    t2 = roofline.analytic_tree_cost(2000, 10, 50, 6, 65)
    assert t2["flops"] == pytest.approx(2 * t1["flops"])
    g = roofline.analytic_glm_cost(1000, 9, 8)
    assert g["flops"] == pytest.approx(2 * 9 * 9 * 1000 * 8)
    d = roofline.analytic_dl_cost(100.0, [8, 16, 2])
    assert d["flops"] > 0 and d["bytes"] > 0


def test_gbm_fit_records_nonzero_mfu_in_gauge_and_capsule():
    """Acceptance: a seeded GBM fit reports nonzero model_fit_mfu in
    the registry AND in its flight-recorder capsule's fit span."""
    fr = _mk_class_frame(600, 5, seed=3)
    from h2o3_tpu.models.gbm import GBMEstimator
    est = GBMEstimator(ntrees=5, max_depth=3, seed=1)
    est.train(fr, y="y")
    assert telemetry.REGISTRY.value("model_fit_mfu", algo="gbm") > 0
    assert telemetry.REGISTRY.value("model_fit_hbm_util",
                                    algo="gbm") > 0
    cap = flight_recorder.get_capsule(est._job.key)
    fit = next(s for s in cap.to_dict()["spans"]
               if s["name"] == "gbm.fit")
    assert fit["meta"]["mfu"] > 0
    assert fit["meta"]["roofline"]["source"] == "analytic"
    assert fit["meta"]["roofline"]["flops"] > 0


def test_dl_fit_records_nonzero_mfu_in_gauge_and_capsule():
    """Acceptance: a DL fit reports nonzero model_fit_mfu too."""
    fr = _mk_class_frame(512, 8, seed=4)
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    est = DeepLearningEstimator(hidden=[8, 8], epochs=0.5, seed=1)
    est.train(fr, y="y")
    assert telemetry.REGISTRY.value("model_fit_mfu",
                                    algo="deeplearning") > 0
    cap = flight_recorder.get_capsule(est._job.key)
    fit = next(s for s in cap.to_dict()["spans"]
               if s["name"] == "deeplearning.fit")
    assert fit["meta"]["mfu"] > 0


def test_histogram_cost_analysis_agrees_with_analytic_2x():
    """Acceptance: on the GBM histogram program unit — ONE loop-free
    level build — Compiled.cost_analysis() (per-device) agrees with the
    analytic matmul count within 2x on CPU."""
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.ops import histogram as H
    from h2o3_tpu.parallel.mesh import get_mesh
    n, F, B, L = 2048, 6, 65, 8
    mesh = get_mesh()
    fn = jax.jit(lambda b, nid, w, g, h: H.histogram(
        b, nid, w, g, h, n_nodes=L, n_bins=B, mesh=mesh))
    ab = jax.ShapeDtypeStruct((n, F), jnp.int8)
    ai = jax.ShapeDtypeStruct((n,), jnp.int32)
    af = jax.ShapeDtypeStruct((n,), jnp.float32)
    ca = fn.lower(ab, ai, af, af, af).compile().cost_analysis()
    entries = ca if isinstance(ca, (list, tuple)) else [ca]
    cost = sum(float(e.get("flops", 0) or 0) for e in entries
               if isinstance(e, dict))
    assert cost > 0
    ndev = roofline.device_peaks()["devices"]
    analytic_per_device = 2.0 * 3 * L * n * F * B / ndev
    ratio = analytic_per_device / cost
    assert 0.5 <= ratio <= 2.0, ratio


def test_dl_step_cost_analysis_agrees_with_analytic_2x():
    """Acceptance: on the DL program unit — one fused train step (the
    scan body XLA counts once) — cost_analysis agrees with the analytic
    6·params·batch count within 2x on CPU."""
    fr = _mk_class_frame(512, 9, seed=5)
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    DeepLearningEstimator(hidden=[16, 16], epochs=0.5, seed=1,
                          mini_batch_size=64).train(fr, y="y")
    kc = roofline.kernel_cost("dl.train_chunk", refresh=True)
    assert kc is not None and kc["flops"] > 0
    ndev = roofline.device_peaks()["devices"]
    per_device_batch = 64 / ndev
    est = roofline.analytic_dl_cost(per_device_batch, [9, 16, 16, 2])
    ratio = est["flops"] / kc["flops"]
    assert 0.5 <= ratio <= 2.0, ratio


def test_kernel_cost_unknown_name_is_none():
    assert roofline.kernel_cost("no.such.kernel") is None


def test_roofline_off_mode(monkeypatch):
    monkeypatch.setenv("H2O3TPU_ROOFLINE", "off")
    fr = _mk_class_frame(300, 4, seed=6)
    from h2o3_tpu.models.gbm import GBMEstimator
    est = GBMEstimator(ntrees=2, max_depth=3, seed=1)
    est.train(fr, y="y")
    cap = flight_recorder.get_capsule(est._job.key)
    fit = next(s for s in cap.to_dict()["spans"]
               if s["name"] == "gbm.fit")
    assert "mfu" not in fit["meta"]


# ----------------------------------------- 2-process fan-in (real kv)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _http_text(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.mark.multiprocess
def test_two_process_fanin_merge_and_sigkill_stale(tmp_path):
    """Acceptance: on a 2-process CPU cloud, /3/Metrics?cluster=1 sums
    both peers' local scrapes, /3/Trace?cluster=1 is one Perfetto trace
    with one track group per process, /3/Logs?cluster=1 merges both
    tails — and a SIGKILLed peer degrades every view to labeled-stale
    within the publish window, never a hang or 500."""
    workdir = str(tmp_path)
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    worker = os.path.join(REPO, "tests", "cluster_worker.py")
    timeout_s = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, "2", str(i), workdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    stop = os.path.join(workdir, "stop")

    def _logs_of():
        out = []
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
            try:
                o, _ = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                o = "<no output>"
            out.append(f"--- worker {i} ---\n{(o or '')[-3000:]}")
        return "\n".join(out)

    try:
        # wait for both workers' local scrapes + the REST port
        deadline = time.time() + timeout_s
        needed = [os.path.join(workdir, f)
                  for f in ("node0.json", "node1.json", "port.txt")]
        while time.time() < deadline:
            if all(os.path.exists(p) for p in needed):
                break
            for p in procs:
                assert p.poll() is None, \
                    f"worker died during bootstrap:\n{_logs_of()}"
            time.sleep(0.1)
        else:
            raise AssertionError(f"cloud never formed:\n{_logs_of()}")
        with open(needed[0]) as f:
            local0 = json.load(f)
        with open(needed[1]) as f:
            local1 = json.load(f)
        with open(needed[2]) as f:
            port = int(f.read().strip())

        # ---- merged metrics == sum of both peers' local scrapes -----
        # poll to a clean steady state first: a transient heartbeat
        # flap during bootstrap may briefly label the peer stale
        deadline = time.time() + 30
        while time.time() < deadline:
            st, out = _http_json(port, "/3/Metrics?cluster=1")
            assert st == 200
            if out["cluster"]["stale_nodes"] == []:
                break
            time.sleep(0.3)
        assert out["cluster"]["process_count"] == 2
        assert out["cluster"]["stale_nodes"] == [], _logs_of()
        probe = next(c for c in out["metrics"]["counters"]
                     if c["name"] == "h2o3tpu_cluster_probe_total")
        assert probe["value"] == pytest.approx(
            local0["probe"] + local1["probe"])      # 100 + 200
        # per-node summaries carry the fan-in identity
        nodes = {n["node"]: n for n in out["cluster"]["nodes"]}
        assert set(nodes) == {0, 1}

        st, text = _http_text(port,
                              "/3/Metrics?cluster=1&format=prometheus")
        assert st == 200
        assert f"h2o3tpu_cluster_probe_total "\
               f"{int(local0['probe'] + local1['probe'])}" in text
        assert 'node="1"' in text

        # ---- one Perfetto trace, one track group per process --------
        st, trace = _http_json(port, "/3/Trace?cluster=1")
        assert st == 200
        span_evs = [e for e in trace["traceEvents"]
                    if e.get("cat") == "span"]
        by_name = {e["name"]: e for e in span_evs}
        assert by_name["clw.node0"]["pid"] == 0
        assert by_name["clw.node1"]["pid"] == 1

        # ---- merged logs with node ids ------------------------------
        st, lg = _http_json(port, "/3/Logs?cluster=1")
        assert st == 200
        assert any("clw-log-node0" in ln for ln in lg["lines"])
        assert any("clw-log-node1" in ln for ln in lg["lines"])

        # ---- SIGKILL the peer: labeled-stale, never a 500 -----------
        procs[1].kill()
        deadline = time.time() + 30
        stale_seen = None
        while time.time() < deadline:
            st, out = _http_json(port, "/3/Metrics?cluster=1")
            assert st == 200                 # never 500, never a hang
            stale_seen = out["cluster"]["stale_nodes"]
            if stale_seen == [1]:
                break
            time.sleep(0.3)
        assert stale_seen == [1], f"peer never went stale:\n{_logs_of()}"
        # the dead peer's LAST data still serves in the merged view
        probe = next(c for c in out["metrics"]["counters"]
                     if c["name"] == "h2o3tpu_cluster_probe_total")
        assert probe["value"] >= local1["probe"]
        st, trace = _http_json(port, "/3/Trace?cluster=1")
        assert st == 200
        assert trace["otherData"]["stale_nodes"] == [1]
        st, lg = _http_json(port, "/3/Logs?cluster=1")
        assert st == 200
        assert lg["cluster"]["stale_nodes"] == [1]

        # clean stop for the survivor
        with open(stop, "w") as f:
            f.write("stop")
        rc = procs[0].wait(timeout=30)
        assert rc == 0, _logs_of()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except Exception:   # noqa: BLE001
                pass
