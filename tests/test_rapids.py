"""Rapids expression engine tests — the pyunit munging suite role
(h2o-py/tests/testdir_munging/)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.rapids import Session, parse, rapids


@pytest.fixture()
def sess():
    return Session()


@pytest.fixture()
def data(sess):
    r = np.random.RandomState(0)
    f = h2o3_tpu.Frame.from_numpy(
        {"a": np.arange(10, dtype=np.float64),
         "b": r.randn(10),
         "g": np.array(list("xyxyxyxyxy"), dtype=object)},
        categorical=["g"], key="data")
    sess.assign("data", f)
    return f


def test_parser():
    ast = parse('(tmp= x (+ (cols_py data [0]) 5))')
    assert ast[0] == ("id", "tmp=")
    assert ast[1] == ("id", "x")


def test_arithmetic(sess, data):
    out = rapids('(+ (cols_py data ["a"]) 5)', sess)
    v = out.col("a").to_numpy()
    np.testing.assert_allclose(v, np.arange(10) + 5)


def test_comparison_and_rows(sess, data):
    out = rapids('(rows data (> (cols_py data ["a"]) 6))', sess)
    assert out.nrows == 3
    np.testing.assert_allclose(out.col("a").to_numpy(), [7, 8, 9])
    # categorical survives the slice
    assert out.col("g").is_categorical


def test_reducers(sess, data):
    assert rapids('(sum (cols_py data ["a"]))', sess) == 45.0
    assert rapids('(mean (cols_py data ["a"]))', sess) == 4.5
    assert abs(rapids('(sd (cols_py data ["a"]))', sess)
               - np.std(np.arange(10), ddof=1)) < 1e-9


def test_assign_and_lookup(sess, data):
    rapids('(tmp= doubled (* (cols_py data ["a"]) 2))', sess)
    out = rapids('(sum doubled)', sess)
    assert out == 90.0


def test_ifelse(sess, data):
    out = rapids('(ifelse (> (cols_py data ["a"]) 4) 1 0)', sess)
    np.testing.assert_allclose(out.col("C1").to_numpy(),
                               (np.arange(10) > 4).astype(float))


def test_cbind_rbind(sess, data):
    out = rapids('(cbind (cols_py data ["a"]) (cols_py data ["b"]))', sess)
    assert out.names == ["a", "b"]
    out2 = rapids('(rbind data data)', sess)
    assert out2.nrows == 20
    assert out2.col("g").domain == ["x", "y"]


def test_groupby_device_aggs(sess, data):
    out = rapids('(GB data ["g"] "mean" "a" "all" "sum" "b" "all" '
                 '"count" "a" "all")', sess)
    df = out.to_pandas().sort_values("g").reset_index(drop=True)
    a = np.arange(10)
    assert list(df["g"]) == ["x", "y"]
    np.testing.assert_allclose(df["mean_a"], [a[::2].mean(), a[1::2].mean()])
    np.testing.assert_allclose(df["nrow"], [5, 5])


def test_groupby_minmax(sess, data):
    out = rapids('(GB data ["g"] "max" "a" "all" "min" "a" "all")', sess)
    df = out.to_pandas().sort_values("g")
    np.testing.assert_allclose(df["max_a"], [8, 9])
    np.testing.assert_allclose(df["min_a"], [0, 1])


def test_sort(sess, data):
    out = rapids('(sort data ["b"] [1])', sess)
    v = out.col("b").to_numpy()
    assert (np.diff(v) >= 0).all()


def test_merge(sess):
    l = h2o3_tpu.Frame.from_numpy(
        {"k": np.array(["a", "b", "c"], dtype=object),
         "v1": np.array([1.0, 2.0, 3.0])}, categorical=["k"])
    r = h2o3_tpu.Frame.from_numpy(
        {"k": np.array(["b", "c", "d"], dtype=object),
         "v2": np.array([20.0, 30.0, 40.0])}, categorical=["k"])
    sess.assign("L", l)
    sess.assign("R", r)
    out = rapids('(merge L R 0 0)', sess)
    df = out.to_pandas().sort_values("k")
    assert list(df["k"]) == ["b", "c"]
    np.testing.assert_allclose(df["v2"], [20.0, 30.0])


def test_string_ops(sess):
    f = h2o3_tpu.Frame.from_numpy(
        {"s": np.array(["Hello", "World", None], dtype=object)},
        categorical=["s"])
    sess.assign("S", f)
    out = rapids('(tolower S)', sess)
    vals = out.to_pandas()["s"].tolist()
    assert vals[:2] == ["hello", "world"]
    n = rapids('(nchar S)', sess)
    v = n.col("s").to_numpy()
    assert v[0] == 5.0 and np.isnan(v[2])


def test_as_factor_numeric_roundtrip(sess, data):
    out = rapids('(as.factor (cols_py data ["a"]))', sess)
    assert out.col("a").is_categorical
    back = rapids('(as.numeric (as.factor (cols_py data ["a"])))', sess)
    np.testing.assert_allclose(back.col("a").to_numpy(), np.arange(10))


def test_na_handling(sess):
    v = np.array([1.0, np.nan, 3.0])
    f = h2o3_tpu.Frame.from_numpy({"x": v})
    sess.assign("N", f)
    assert np.isnan(rapids('(sum N)', sess))
    assert rapids('(sum N 1)', sess) == 4.0       # na_rm
    # AstIsNa names outputs isNA(col) (pyunit_isna contract)
    isna = rapids('(is.na N)', sess).col("isNA(x)").to_numpy()
    np.testing.assert_allclose(isna, [0, 1, 0])
    imp = rapids('(h2o.impute N [0] "mean")', sess)
    np.testing.assert_allclose(imp.col("x").to_numpy(), [1.0, 2.0, 3.0])


def test_unique_table(sess, data):
    t = rapids('(table (cols_py data ["g"]))', sess)
    df = t.to_pandas()
    assert df["Count"].sum() == 10
    u = rapids('(unique (cols_py data ["g"]))', sess)
    assert u.nrows == 2


def test_slice_ranges(sess, data):
    # h2o-py serializes fr[0:5, :] as (rows data [0:5]) — start:count
    out = rapids('(rows data [0:5])', sess)
    assert out.nrows == 5
    assert out.col("a").to_numpy().tolist() == [0, 1, 2, 3, 4]
    # open-ended [2:nan] = rows 2..end
    out = rapids('(rows data [2:nan])', sess)
    assert out.nrows == 8
    # strided [0:5:2] = 5 elements step 2 -> 0,2,4,6,8
    out = rapids('(rows data [0:5:2])', sess)
    assert out.col("a").to_numpy().tolist() == [0, 2, 4, 6, 8]
    # column slice
    out = rapids('(cols_py data [0:2])', sess)
    assert out.names == ["a", "b"]


def test_negative_cols_means_drop(sess, data):
    # h2o-py pop/del sends -(i+1): drop column i, keep the rest
    out = rapids('(cols data -1)', sess)
    assert out.names == ["b", "g"]
    out = rapids('(cols data [-2])', sess)
    assert out.names == ["a", "g"]


def test_categorical_eq_string(sess, data):
    out = rapids('(== (cols_py data ["g"]) "x")', sess)
    v = out.col(out.names[0]).to_numpy()
    assert v.tolist() == [1, 0, 1, 0, 1, 0, 1, 0, 1, 0]
    out = rapids('(!= (cols_py data ["g"]) "x")', sess)
    assert out.col(out.names[0]).to_numpy().tolist() == \
        [0, 1, 0, 1, 0, 1, 0, 1, 0, 1]


def test_rectangle_assign(sess, data):
    # fr[rows, col] = scalar → (:= fr value col rows)
    out = rapids('(:= data 99 [0] [0:3])', sess)
    a = out.col("a").to_numpy()
    assert a[:4].tolist() == [99, 99, 99, 3]
    assert out.ncols == 3 and out.nrows == 10
    # whole-column assign, [] = all rows
    out = rapids('(:= data 7 [1] [])', sess)
    assert np.allclose(out.col("b").to_numpy(), 7.0)
    # string into categorical extends/uses domain
    out = rapids('(:= data "z" [2] [0:2])', sess)
    g = out.col("g")
    assert g.domain is not None and "z" in g.domain
    codes = np.asarray(g.data)[:2]
    assert all(g.domain[c] == "z" for c in codes)
