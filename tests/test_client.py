"""h2o-py-compatible client over real HTTP — the full wire contract:
connect → import_file → generated estimator → train → predict → AutoML."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server, stop_server
from h2o3_tpu import client as h2o


pytestmark = pytest.mark.allow_key_leak  # REST handler threads create keys the thread-local Scope cannot track


@pytest.fixture(scope="module")
def conn():
    port = start_server(port=0, background=True)
    c = h2o.connect(f"http://127.0.0.1:{port}")
    yield c
    stop_server()


@pytest.fixture(scope="module")
def csv_path(tmp_path_factory):
    r = np.random.RandomState(0)
    n = 3000
    df = pd.DataFrame({
        "x0": r.randn(n), "x1": r.randn(n),
        "g": np.array(["a", "b", "c"], object)[r.randint(0, 3, n)],
    })
    logit = df.x0 * 1.3 + (df.g == "b") - df.x1
    df["target"] = np.array(["no", "yes"], object)[
        (r.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)]
    p = tmp_path_factory.mktemp("d") / "c.csv"
    df.to_csv(p, index=False)
    return str(p)


def test_client_import_and_frame(conn, csv_path):
    fr = h2o.import_file(csv_path)
    assert fr.shape == (3000, 4)
    assert set(fr.names) == {"x0", "x1", "g", "target"}
    sub = fr["x0"]
    assert sub.shape[1] == 1


def test_client_generated_estimators_exist(conn):
    names = [n for n in vars(h2o.estimators) if n.startswith("H2O")]
    assert "H2OGradientBoostingEstimator" in names
    assert "H2OXGBoostEstimator" in names
    assert len(names) >= 20


def test_client_train_predict(conn, csv_path):
    fr = h2o.import_file(csv_path)
    est = h2o.estimators.H2OGradientBoostingEstimator(ntrees=8, max_depth=3,
                                                      seed=4)
    m = est.train(y="target", training_frame=fr)
    assert m.algo == "gbm"
    assert m.auc() > 0.7
    preds = m.predict(fr)
    assert preds.nrows == 3000
    assert "p1" in preds.names


def test_client_x_subsets_predictors(conn, csv_path):
    fr = h2o.import_file(csv_path)
    est = h2o.estimators.H2OGradientBoostingEstimator(ntrees=4, max_depth=3,
                                                      seed=2)
    m = est.train(x=["x0"], y="target", training_frame=fr)
    info = m._info()["models"][0]
    assert info["output"]["names"] == ["x0", "target"]
    # h2o-py positional order train(x, y, training_frame) works too
    m2 = h2o.estimators.H2OGradientBoostingEstimator(ntrees=3, seed=2).train(
        ["x0", "x1"], "target", fr)
    assert set(m2._info()["models"][0]["output"]["names"]) == {"x0", "x1", "target"}
    with pytest.raises(ValueError, match="training_frame"):
        est.train(y="target")


def test_client_unknown_param_rejected(conn):
    with pytest.raises(ValueError, match="unknown gbm params"):
        h2o.estimators.H2OGradientBoostingEstimator(bogus_knob=1)


def test_client_xgboost_facade(conn, csv_path):
    fr = h2o.import_file(csv_path)
    m = h2o.estimators.H2OXGBoostEstimator(ntrees=5, eta=0.3).train(
        y="target", training_frame=fr)
    assert m.auc() > 0.65


def test_client_automl(conn, csv_path):
    fr = h2o.import_file(csv_path)
    aml = h2o.H2OAutoML(max_models=2, seed=1, project_name="clienttest")
    leader = aml.train(y="target", training_frame=fr)
    assert leader is not None
    assert len(aml.leaderboard) >= 2


def test_client_frame_expressions(conn, csv_path):
    fr = h2o.import_file(csv_path)
    x0 = fr["x0"]
    doubled = x0 * 2.0
    assert doubled.mean() == pytest.approx(x0.mean() * 2.0, rel=1e-5)
    shifted = 1.0 + x0
    assert shifted.mean() == pytest.approx(x0.mean() + 1.0, rel=1e-4)
    mask = x0 > 0
    frac = mask.mean()
    assert 0.3 < frac < 0.7
    assert x0.abs().min() >= 0
    rows = fr.head(3)
    assert len(rows) == 3 and "target" in rows[0]


def test_custom_metric_func(conn, csv_path):
    """water/udf CFunc role via the in-process API."""
    import h2o3_tpu
    import numpy as np
    from h2o3_tpu.models.gbm import GBMEstimator
    from tests.conftest import make_classification
    X, y = make_classification(n=800, f=4)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["no", "yes"], object)[y]
    frame = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])

    def brier(yv, preds, w):
        ok = ~np.isnan(yv)
        return float(np.mean((preds["p1"][ok] - yv[ok]) ** 2))

    m = GBMEstimator(ntrees=5, max_depth=3, seed=1).train(
        frame, y="y", custom_metric_func=brier)
    assert 0 < m.output["custom_metric"] < 0.25
    assert m.training_metrics["custom"] == m.output["custom_metric"]


def test_client_mojo_pojo_download(tmp_path):
    """REST download endpoints: MOJO zip scores offline, POJO source
    imports with stdlib only."""
    import numpy as np
    from h2o3_tpu import client as h2o
    from h2o3_tpu.genmodel import load_mojo
    h2o.init()
    r = np.random.RandomState(4)
    import csv
    p = str(tmp_path / "c.csv")
    with open(p, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["a", "b", "y"])
        for _ in range(300):
            a, b = r.randn(2)
            w.writerow([a, b, "t" if a + b > 0 else "f"])
    fr = h2o.import_file(p)
    m = h2o.estimators.H2OGradientBoostingEstimator(
        ntrees=5, max_depth=3).train(y="y", training_frame=fr)
    zp = m.download_mojo(str(tmp_path / "m.zip"))
    mojo = load_mojo(zp)
    out = mojo.predict({"a": np.array([1.0]), "b": np.array([1.0])})
    assert 0.0 <= float(out["p1"][0]) <= 1.0
    pp = m.download_pojo(str(tmp_path / "m_pojo.py"))
    src = open(pp).read()
    assert "score0" in src and "import numpy" not in src
