"""Job-level infra-error retry (round-3 VERDICT item #10).

A transient XLA/remote_compile INTERNAL error must not permanently fail
a job (in round 2 one such blip killed an AutoML step for good); user
errors must still fail fast with no retry. Since the fault-tolerance
layer the retry policy is shared (core/watchdog.py): bounded attempts +
exponential backoff from core/config.py.
"""

import pytest

from h2o3_tpu.core import config
from h2o3_tpu.core.job import FAILED, DONE, Job, is_infra_error


class FakeXlaRuntimeError(Exception):
    pass


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    """Keep the watchdog backoff out of the test wallclock."""
    monkeypatch.setattr(config.ARGS, "infra_backoff_base_s", 0.001)
    monkeypatch.setattr(config.ARGS, "infra_backoff_max_s", 0.002)


def test_infra_error_retried():
    calls = {"n": 0}

    def flaky(job):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXlaRuntimeError(
                "INTERNAL: From /job:tpu_worker/replica:0: remote_compile "
                "failed: UNAVAILABLE: socket closed")
        return "ok"

    j = Job("flaky step").start(flaky)
    assert j.status == DONE
    assert j.result == "ok"
    assert calls["n"] == 2


def test_infra_retries_bounded_by_config(monkeypatch):
    """A permanently-dead backend gets exactly infra_max_attempts tries
    (the watchdog policy), then the job fails for good."""
    monkeypatch.setattr(config.ARGS, "infra_max_attempts", 3)
    calls = {"n": 0}

    def always_down(job):
        calls["n"] += 1
        raise FakeXlaRuntimeError("INTERNAL: remote_compile failed")

    with pytest.raises(FakeXlaRuntimeError):
        Job("dead step").start(always_down)
    assert calls["n"] == 3


def test_user_error_fails_fast():
    calls = {"n": 0}

    def bad_params(job):
        calls["n"] += 1
        raise ValueError("unknown GBM params: ['nonsense']")

    with pytest.raises(ValueError):
        Job("user error").start(bad_params)
    assert calls["n"] == 1


def test_background_job_records_failure():
    def always_down(job):
        raise FakeXlaRuntimeError("INTERNAL: remote_compile failed")

    j = Job("bg dead").start(always_down, background=True).join(30)
    assert j.status == FAILED
    assert "remote_compile" in j.exception


def test_is_infra_error_classification():
    assert is_infra_error(FakeXlaRuntimeError("INTERNAL: boom"))
    assert is_infra_error(RuntimeError("UNAVAILABLE: socket closed"))
    assert not is_infra_error(ValueError("INTERNAL: looks alike"))
    assert not is_infra_error(RuntimeError("plain user-visible failure"))


def test_retries_observable_in_telemetry():
    """infra_retries_total{site=job} counts every retry the policy
    grants (README §Fault tolerance metric surface)."""
    from h2o3_tpu import telemetry
    before = telemetry.REGISTRY.value("infra_retries_total", site="job")
    calls = {"n": 0}

    def flaky(job):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXlaRuntimeError("UNAVAILABLE: worker restarting")
        return "ok"

    Job("flaky counted").start(flaky)
    after = telemetry.REGISTRY.value("infra_retries_total", site="job")
    assert after - before == 1
