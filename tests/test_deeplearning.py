"""DeepLearning tests — pyunit_deeplearning* role
(h2o-py/tests/testdir_algos/deeplearning/)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.deeplearning import DeepLearningEstimator


def test_dl_binomial_learns(classif_frame):
    m = DeepLearningEstimator(hidden=[32, 32], epochs=30, seed=42,
                              stopping_rounds=0)
    model = m.train(classif_frame, y="y")
    tm = model.training_metrics
    assert tm["AUC"] > 0.80, tm.to_dict()
    preds = model.predict(classif_frame).to_pandas()
    assert ((preds["p0"] + preds["p1"]).round(4) == 1.0).all()


def test_dl_regression(regress_frame):
    m = DeepLearningEstimator(hidden=[64, 64], epochs=40, seed=3,
                              stopping_rounds=0)
    model = m.train(regress_frame, y="y")
    y = regress_frame.col("y").to_numpy()
    assert model.training_metrics["MSE"] < 0.35 * float(np.var(y))


def test_dl_multinomial():
    r = np.random.RandomState(7)
    n = 3000
    X = r.randn(n, 6)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    f = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(6)},
         "y": np.array(["a", "b", "c"], dtype=object)[y]},
        categorical=["y"])
    model = DeepLearningEstimator(hidden=[32], epochs=25, seed=5,
                                  stopping_rounds=0).train(f, y="y")
    assert model.training_metrics["error_rate"] < 0.2


def test_dl_tanh_and_momentum(classif_frame):
    m = DeepLearningEstimator(hidden=[16], epochs=15, activation="Tanh",
                              adaptive_rate=False, rate=0.05,
                              momentum_start=0.5, momentum_stable=0.9,
                              seed=1, stopping_rounds=0)
    model = m.train(classif_frame, y="y")
    assert model.training_metrics["AUC"] > 0.75


def test_dl_autoencoder():
    r = np.random.RandomState(2)
    X = r.randn(1500, 6)
    X[:, 3] = X[:, 0] + 0.1 * r.randn(1500)     # learnable structure
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    m = DeepLearningEstimator(hidden=[3], epochs=40, autoencoder=True,
                              seed=4, stopping_rounds=0)
    model = m.train(f)
    rec = model.anomaly(f).to_pandas()["reconstruction_error"]
    assert rec.mean() < 1.0          # better than predicting zeros (var=1)
    # anomalous rows reconstruct worse
    Xo = X.copy()
    Xo[:50] += 8.0
    fo = h2o3_tpu.Frame.from_numpy({f"x{i}": Xo[:, i] for i in range(6)})
    rec2 = model.anomaly(fo).to_pandas()["reconstruction_error"]
    assert rec2[:50].mean() > 3 * rec[50:].mean()
