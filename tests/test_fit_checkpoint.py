"""ISSUE 9 — in-fit checkpointing, H2O-parity ``checkpoint=``
continuation, and the self-healing job supervisor.

Three legs, one contract (core/recovery.py FitCheckpointer +
core/job.py supervisor + models/{gbm,drf,deeplearning,glm}.py):

- in-fit snapshots at training-loop host boundaries; resume is
  **bit-identical** to an uninterrupted fit (asserted for GBM, DL, GLM
  via the ``fit_chunk`` fault-injection site, and for GBM again via a
  real SIGKILL in a subprocess);
- ``checkpoint=`` extends a donor model (GBM/DRF/XGBoost forests, DL
  epochs) with H2O-shaped validation errors for non-modifiable knobs;
- the job supervisor re-enters a fit from its snapshot on infra-class
  failures instead of restarting at round 0.

Satellites: corrupt-snapshot quarantine, orphan-tmp sweep, metric
wiring into flight-recorder capsules, the resume_automl snapshot-dir
read-count regression, and README knob/name documentation.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.core import config, recovery, watchdog
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.tree import Tree

WORKER = os.path.join(os.path.dirname(__file__), "fitckpt_worker.py")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.setattr(config.ARGS, "infra_backoff_base_s", 0.001)
    monkeypatch.setattr(config.ARGS, "infra_backoff_max_s", 0.01)
    monkeypatch.delenv("H2O3TPU_FIT_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("H2O3TPU_FIT_CHECKPOINT_EVERY", raising=False)
    monkeypatch.delenv("H2O3TPU_FIT_CHECKPOINT_HOLD_S", raising=False)
    yield
    watchdog.clear_faults()


def _classif_frame(n=2000, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, 5)
    yv = (X[:, 0] + 0.3 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["a", "b"], object)[yv]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


def _forests_equal(a: Tree, b: Tree):
    for f in Tree._fields:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert av.shape == bv.shape, (f, av.shape, bv.shape)
        assert np.array_equal(av, bv), f


# ------------------------------------------------- FitCheckpointer unit


def test_fit_checkpointer_roundtrip_and_cadence(tmp_path):
    fc = recovery.FitCheckpointer(str(tmp_path / "gbm_x.fitsnap"),
                                  "gbm", every=10)
    assert fc.load() is None                       # nothing yet
    assert not fc.maybe_save(5, lambda: {})        # below cadence
    assert fc.maybe_save(10, lambda: {"done": 10, "arr": np.arange(3)})
    assert not fc.maybe_save(15, lambda: {})       # 5 past last save
    assert fc.maybe_save(20, lambda: {"done": 20, "arr": np.arange(4)})
    unit, st = fc.load()
    assert unit == 20 and st["done"] == 20
    assert np.array_equal(st["arr"], np.arange(4))
    # atomic: no tmp debris after a completed save
    assert not os.path.exists(fc.path + ".tmp")
    fc.clear()
    assert fc.load() is None
    assert not os.path.exists(fc.path)


def test_corrupt_snapshot_quarantined(tmp_path):
    """Satellite: a bit-flipped snapshot is renamed *.corrupt, counted,
    and load returns None — never a crash, never a silent wrong model."""
    fc = recovery.FitCheckpointer(str(tmp_path / "gbm_y.fitsnap"),
                                  "gbm", every=1)
    fc.save(7, {"done": 7})
    with open(fc.path, "r+b") as f:
        f.seek(3)
        f.write(b"\xff\xff\xff")                   # bit flips
    c0 = telemetry.REGISTRY.total("snapshot_load_failures_total")
    assert fc.load() is None
    assert telemetry.REGISTRY.total("snapshot_load_failures_total") == c0 + 1
    names = os.listdir(tmp_path)
    assert any(n.endswith(".corrupt") for n in names), names
    assert not os.path.exists(fc.path)             # moved aside


# -------------------------------------- supervisor resume (fault inject)


def test_gbm_infra_fault_resumes_bit_identical(tmp_path):
    """Leg 2+3 acceptance (in-process): an infra-classed failure at the
    chunk boundary after the first snapshot makes the job supervisor
    re-enter the fit from the snapshot; forest, metrics and scoring
    history are bit-identical to an uninterrupted fit, with exactly one
    resume counted — and the counters land in the job's flight-recorder
    capsule. Then the quarantine leg: a garbage snapshot at the same
    fit's path costs the resume, not correctness."""
    fr = _classif_frame()
    kw = dict(ntrees=50, max_depth=3, seed=5, stopping_rounds=2,
              stopping_tolerance=0.0, score_tree_interval=5)
    clean = GBMEstimator(**kw).train(fr, y="y")
    watchdog.inject_fault("fit_chunk", times=1)
    r0 = telemetry.REGISTRY.total("fit_resumes_total")
    w0 = telemetry.REGISTRY.total("fit_checkpoints_written_total")
    b = GBMEstimator(**kw)
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m = b.train(fr, y="y")
    assert telemetry.REGISTRY.total("fit_resumes_total") == r0 + 1
    assert telemetry.REGISTRY.total("fit_checkpoints_written_total") > w0
    _forests_equal(clean.forest, m.forest)
    assert clean.output["scoring_history"] == m.output["scoring_history"]
    assert float(clean.training_metrics["logloss"]) == \
        float(m.training_metrics["logloss"])
    # the snapshot was cleared on completion (dir may be gone entirely)
    assert not [f for f in (os.listdir(tmp_path)
                            if os.path.isdir(tmp_path) else [])
                if f.endswith(recovery.FIT_SUFFIX)]
    # capsule wiring: the job's counter deltas include the new metrics
    from h2o3_tpu.telemetry import flight_recorder
    cap = flight_recorder.get_capsule(b._job.key).to_dict()
    deltas = cap["metric_deltas"]
    assert any("fit_checkpoints_written_total" in k for k in deltas), deltas
    assert any("fit_resumes_total" in k for k in deltas)
    # fit-level quarantine: garbage at the fit's own snapshot path →
    # restart from round 0, same model as the clean run, no resume
    b2 = GBMEstimator(**kw)
    probe = recovery._fit_fingerprint("gbm", b2.params, "y",
                                      clean.output["names"], fr.nrows)
    path = os.path.join(str(tmp_path), f"gbm_{probe}{recovery.FIT_SUFFIX}")
    with open(path, "wb") as f:
        f.write(b"\x80\x04 definitely not a fit snapshot")
    r1 = telemetry.REGISTRY.total("fit_resumes_total")
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m2 = b2.train(fr, y="y")
    assert telemetry.REGISTRY.total("fit_resumes_total") == r1
    assert any(n.endswith(".corrupt") for n in os.listdir(tmp_path))
    _forests_equal(clean.forest, m2.forest)


def test_deeplearning_infra_fault_resumes_bit_identical(tmp_path,
                                                        monkeypatch):
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    fr = _classif_frame()
    kw = dict(hidden=[8], epochs=30, seed=3, stopping_rounds=2)
    clean = DeepLearningEstimator(**kw).train(fr, y="y")
    monkeypatch.setenv("H2O3TPU_FIT_CHECKPOINT_EVERY", "200")
    watchdog.inject_fault("fit_chunk", times=1)
    r0 = telemetry.REGISTRY.total("fit_resumes_total")
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m = DeepLearningEstimator(**kw).train(fr, y="y")
    assert telemetry.REGISTRY.total("fit_resumes_total") == r0 + 1
    for a, b in zip(clean.net, m.net):
        assert np.array_equal(np.asarray(a["W"]), np.asarray(b["W"]))
        assert np.array_equal(np.asarray(a["b"]), np.asarray(b["b"]))
    assert clean.output["scoring_history"] == m.output["scoring_history"]


def test_glm_infra_fault_resumes_bit_identical(tmp_path):
    from h2o3_tpu.models.glm import GLMEstimator
    fr = _classif_frame()
    kw = dict(family="binomial", lambda_=[0.05, 0.01, 0.001],
              solver="l_bfgs", max_iterations=20)
    clean = GLMEstimator(**kw).train(fr, y="y")
    watchdog.inject_fault("fit_chunk", times=1)
    r0 = telemetry.REGISTRY.total("fit_resumes_total")
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m = GLMEstimator(**kw).train(fr, y="y")
    assert telemetry.REGISTRY.total("fit_resumes_total") == r0 + 1
    assert np.array_equal(np.asarray(clean.coef), np.asarray(m.coef))


# ------------------------------------------- H2O-parity checkpoint=


def test_gbm_checkpoint_extends_prefix_bit_equal():
    """Acceptance: checkpoint= extends ntrees with the first N trees
    bit-equal to the donor; incompatible knobs raise H2O-shaped errors."""
    fr = _classif_frame()
    part = GBMEstimator(ntrees=25, max_depth=3, seed=5,
                        sample_rate=1.0).train(fr, y="y")
    res = GBMEstimator(ntrees=50, max_depth=3, seed=5, sample_rate=1.0,
                       checkpoint=part.key).train(fr, y="y")
    assert res.forest.feat.shape[0] == 50
    for f in Tree._fields:
        assert np.array_equal(np.asarray(getattr(part.forest, f)),
                              np.asarray(getattr(res.forest, f))[:25]), f
    # non-modifiable knobs → reference error shape
    for knob, val in (("max_depth", 5), ("nbins", 32),
                      ("sample_rate", 0.7), ("min_rows", 5.0)):
        kw = dict(ntrees=50, seed=5, sample_rate=1.0, max_depth=3,
                  checkpoint=part.key)
        kw[knob] = val
        with pytest.raises(ValueError) as ei:
            GBMEstimator(**kw).train(fr, y="y")
        msg = str(ei.value)
        assert f"ERRR on field: _{knob}" in msg, msg
        assert "cannot be modified if checkpoint is provided" in msg
    # ntrees must exceed the donor's
    with pytest.raises(ValueError, match="must exceed"):
        GBMEstimator(ntrees=25, max_depth=3, seed=5, sample_rate=1.0,
                     checkpoint=part.key).train(fr, y="y")


def test_drf_checkpoint_extends_bit_equal_to_longer_run():
    """DRF continues the bagging PRNG chain AND the OOB accumulators:
    4 + checkpoint-to-10 is bit-equal to a single 10-tree run, metrics
    included."""
    from h2o3_tpu.models.drf import DRFEstimator
    fr = _classif_frame()
    full = DRFEstimator(ntrees=8, max_depth=4, seed=5).train(fr, y="y")
    part = DRFEstimator(ntrees=4, max_depth=4, seed=5).train(fr, y="y")
    res = DRFEstimator(ntrees=8, max_depth=4, seed=5,
                       checkpoint=part.key).train(fr, y="y")
    _forests_equal(full.forest, res.forest)
    assert float(full.training_metrics["AUC"]) == \
        pytest.approx(float(res.training_metrics["AUC"]), abs=1e-9)
    with pytest.raises(ValueError, match="ERRR on field: _mtries"):
        DRFEstimator(ntrees=8, max_depth=4, seed=5, mtries=2,
                     checkpoint=part.key).train(fr, y="y")


def test_xgboost_facade_checkpoint_forwards():
    from h2o3_tpu.models.xgboost import XGBoostEstimator
    fr = _classif_frame()
    part = XGBoostEstimator(ntrees=25, max_depth=3, seed=5).train(fr, y="y")
    res = XGBoostEstimator(ntrees=50, max_depth=3, seed=5,
                           checkpoint=part.key).train(fr, y="y")
    assert res.forest.feat.shape[0] == 50
    for f in Tree._fields:
        assert np.array_equal(np.asarray(getattr(part.forest, f)),
                              np.asarray(getattr(res.forest, f))[:25]), f


def test_dl_checkpoint_continues_epochs_and_optimizer():
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    fr = _classif_frame()
    part = DeepLearningEstimator(hidden=[8], epochs=1, seed=3).train(
        fr, y="y")
    assert part._steps_trained > 0
    # ADADELTA accumulators are live on the donor (restorable state)
    assert float(np.abs(part._opt_state[0]["W"]["eg2"]).sum()) > 0
    cont = DeepLearningEstimator(hidden=[8], epochs=2, seed=3,
                                 checkpoint=part.key).train(fr, y="y")
    assert cont._steps_trained > part._steps_trained
    # continuation differs from a cold 2-epoch run ONLY via restored
    # state; it must differ from the donor (it actually trained more)
    assert not np.array_equal(np.asarray(part.net[0]["W"]),
                              np.asarray(cont.net[0]["W"]))


def test_checkpoint_combo_is_batch_ineligible():
    """Grid leg: a checkpointed combo must never enter the vmapped
    batch path — per-combo fallback preserves donor semantics."""
    from h2o3_tpu.parallel import model_batch
    with pytest.raises(model_batch.BatchIneligible, match="checkpoint"):
        model_batch.train_bucket(
            GBMEstimator, {"checkpoint": "model_gbm_donor"},
            [{"learn_rate": 0.1}, {"learn_rate": 0.2}], None, y="y")


# ---------------------------------------- recovery_dir composition


def test_grid_recovery_resumes_inside_combo(tmp_path, monkeypatch):
    """A combo whose fit died mid-way (snapshot left under
    <recovery_dir>/fit_state) resumes INSIDE the combo when the grid
    walk re-reaches it — not at tree 0."""
    from h2o3_tpu.ml.grid import GridSearch
    d = str(tmp_path / "rec")
    fr = _classif_frame()
    fixed = dict(ntrees=50, max_depth=3, seed=7)
    combos = {"learn_rate": [0.1, 0.2]}
    # reference: the clean 0.2-combo model
    clean = GBMEstimator(**{**fixed, "learn_rate": 0.2}).train(fr, y="y")
    # simulate the kill: run the 0.2 combo under the grid's fit_state
    # scope with retries disabled — the fit dies after its first
    # snapshot, which SURVIVES (the walk never completed)
    monkeypatch.setattr(config.ARGS, "infra_max_attempts", 1)
    watchdog.inject_fault("fit_chunk", times=1)
    with recovery.fit_checkpoint_scope(os.path.join(d, "fit_state")):
        with pytest.raises(Exception):
            GBMEstimator(**{**fixed, "learn_rate": 0.2}).train(fr, y="y")
    snaps = os.listdir(os.path.join(d, "fit_state"))
    assert any(f.endswith(recovery.FIT_SUFFIX) for f in snaps), snaps
    monkeypatch.setattr(config.ARGS, "infra_max_attempts", 3)
    # the resumed walk: sequential (batching off isolates the combo
    # path), recovery_dir composes the fit_state scope automatically
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    r0 = telemetry.REGISTRY.total("fit_resumes_total")
    g = GridSearch(GBMEstimator, combos, recovery_dir=d,
                   **fixed).train(fr, y="y")
    assert telemetry.REGISTRY.total("fit_resumes_total") == r0 + 1
    assert len(g.models) == 2
    resumed = next(m for m in g.models
                   if m.output["grid_params"] == {"learn_rate": 0.2})
    _forests_equal(clean.forest, resumed.forest)
    # the completed walk swept its fit_state snapshots
    assert not os.path.exists(os.path.join(d, "fit_state")) or \
        not os.listdir(os.path.join(d, "fit_state"))


# -------------------------------------------------- satellite sweeps


def test_sweep_orphaned_fit_tmp_and_partial_dirs(tmp_path):
    """Satellite: shutdown()/conftest sweep removes *.tmp debris a kill
    left behind and prunes empty partial snapshot dirs; completed
    snapshots stay (they are resumable state)."""
    d = str(tmp_path / "ck")
    fc = recovery.FitCheckpointer(os.path.join(d, "gbm_z.fitsnap"),
                                  "gbm", 1)
    fc.save(1, {"done": 1})
    with open(fc.path + ".tmp", "wb") as f:     # orphaned tmp (torn kill)
        f.write(b"torn write")
    removed = recovery.sweep_fit_checkpoints()
    assert removed >= 1
    assert not os.path.exists(fc.path + ".tmp")
    assert os.path.exists(fc.path)              # real snapshot untouched
    fc.clear()
    # dir now empty → pruned by the next sweep
    recovery.sweep_fit_checkpoints()
    assert not os.path.exists(d)


def test_resume_automl_snapshot_dir_read_counts(tmp_path, monkeypatch):
    """Satellite regression: step-completion snapshots read each nested
    snapshot dir ONCE (one os.listdir) instead of one os.path.exists
    per model — the pre-fix behavior re-stat'ed the leaderboard dir on
    every step snapshot."""
    from h2o3_tpu.automl import H2OAutoML
    d = str(tmp_path / "rec")
    aml = H2OAutoML(max_models=4, recovery_dir=d, nfolds=0)
    step = "GBM_grid_1"
    os.makedirs(os.path.join(d, step))
    keys = [f"model_gbm_fake{i}" for i in range(6)]
    for k in keys:
        with open(os.path.join(d, step, f"{k}.bin"), "wb") as f:
            f.write(b"x")

    class _FakeModel:
        def __init__(self, key):
            self.key = key

    listdir_calls = []
    exists_calls = []
    real_listdir = os.listdir
    import h2o3_tpu.automl as automl_mod

    def counting_listdir(p):
        listdir_calls.append(p)
        return real_listdir(p)

    real_exists = os.path.exists

    def counting_exists(p):
        exists_calls.append(p)
        return real_exists(p)

    monkeypatch.setattr(automl_mod.os, "listdir", counting_listdir)
    monkeypatch.setattr(automl_mod.os.path, "exists", counting_exists)
    models = [_FakeModel(k) for k in keys]
    aml._on_step_done(step, models, "y", None)
    aml._on_step_done(step, models, "y", None)   # second snapshot: cached
    sub = os.path.join(d, step)
    assert listdir_calls.count(sub) == 1, listdir_calls
    assert not [p for p in exists_calls if p.startswith(sub)], exists_calls
    # and the state recorded the nested snapshot paths, not fresh saves
    state = json.load(open(os.path.join(d, "automl_state.json")))
    assert sorted(state["models"][step]) == \
        sorted(f"{step}/{k}.bin" for k in keys)


def test_readme_documents_checkpoint_contract():
    """Satellite: README §Fault tolerance names the knobs, the
    bit-identity guarantee, and the supervisor decision table."""
    readme = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(readme) as f:
        text = f.read()
    lo = text.index("## Fault tolerance")
    section = text[lo:text.index("\n## ", lo + 1)]
    for needle in ("H2O3TPU_FIT_CHECKPOINT_DIR",
                   "H2O3TPU_FIT_CHECKPOINT_EVERY",
                   "bit-identical", "checkpoint=", "fail fast",
                   "re-enter fit from snapshot", "*.corrupt"):
        assert needle in section, needle


# --------------------------------------- SIGKILL-mid-GBM (acceptance)


@pytest.mark.multiprocess
@pytest.mark.allow_key_leak
def test_sigkill_mid_gbm_fit_resumes_bit_identical(tmp_path):
    """Acceptance: SIGKILL a worker mid-GBM-fit (inside the chunk
    boundary right after its first in-fit snapshot); re-running the fit
    in a fresh process resumes from the snapshot and produces a
    bit-identical forest, metrics, and scoring history vs. an
    uninterrupted reference fit, with fit_resumes_total == 1."""
    ck = str(tmp_path / "ck")
    out_npz = str(tmp_path / "out.npz")
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "H2O3TPU_FIT_CHECKPOINT_DIR",
              "H2O3TPU_FIT_CHECKPOINT_EVERY",
              "H2O3TPU_FIT_CHECKPOINT_HOLD_S"):
        env.pop(k, None)

    # the fit run holds inside the chunk boundary after its first
    # snapshot (H2O3TPU_FIT_CHECKPOINT_HOLD_S in the worker) — the kill
    # deterministically lands MID-FIT
    proc = subprocess.Popen([sys.executable, WORKER, "fit", ck,
                             str(tmp_path / "never.npz")], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 180
    killed = False
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if os.path.isdir(ck) and any(
                    f.endswith(recovery.FIT_SUFFIX)
                    for f in os.listdir(ck)):
                os.kill(proc.pid, signal.SIGKILL)
                killed = True
                break
            time.sleep(0.05)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert killed, (f"worker finished (or never snapshotted) before the "
                    f"kill; rc={proc.returncode}")
    assert any(f.endswith(recovery.FIT_SUFFIX) for f in os.listdir(ck))

    # fresh process: the resumed fit first, then the uninterrupted
    # reference on the same 1-device mesh (one session, shared compiles)
    p = subprocess.run([sys.executable, WORKER, "resume", ck, out_npz],
                       env=env, capture_output=True, text=True,
                       timeout=240)
    assert p.returncode == 0, p.stdout + p.stderr
    out = np.load(out_npz)
    assert float(out["fit_resumes_total"]) == 1.0
    # the reference fit never resumed (the completed resume cleared it)
    assert float(out["fit_resumes_total_after_ref"]) == 1.0
    assert float(out["snapshot_left"]) == 0.0
    for f in Tree._fields + ("f0", "hist_ntrees", "hist_deviance"):
        assert np.array_equal(out["ref_" + f], out["res_" + f]), f
    assert float(out["ref_logloss"]) == float(out["res_logloss"])
    assert float(out["ref_auc"]) == float(out["res_auc"])
