"""Fault-tolerance layer tests — watchdog policy/probe, fault
injection, recovery snapshots, bench subprocess isolation, and the
SIGKILL-mid-AutoML resume contract (ISSUE 2; reference
hex/faulttolerance/Recovery.java + water/HeartBeatThread.java roles).

Everything here runs on the CPU cloud via injected faults — a real TPU
crash is never required to exercise the retry/degradation paths. The
subprocess kill/resume test is marked slow; the injection tests stay in
tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from h2o3_tpu.core import config, watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
FT_WORKER = os.path.join(REPO, "tests", "ft_worker.py")


@pytest.fixture(autouse=True)
def _clean_faults():
    watchdog.clear_faults()
    yield
    watchdog.clear_faults()


# ------------------------------------------------------------ retry policy


def test_backoff_is_exponential_and_bounded():
    p = watchdog.RetryPolicy(max_attempts=10, base_delay_s=1.0,
                             max_delay_s=8.0, jitter=0.0)
    assert [p.delay(k) for k in (1, 2, 3, 4, 5, 6)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]


def test_backoff_jitter_spreads_but_stays_bounded():
    import random
    p = watchdog.RetryPolicy(base_delay_s=1.0, max_delay_s=30.0,
                             jitter=0.25, rng=random.Random(3))
    ds = [p.delay(1) for _ in range(50)]
    assert all(0.75 <= d <= 1.25 for d in ds)
    assert len({round(d, 6) for d in ds}) > 10    # actually jittered


def test_policy_from_config_reads_args(monkeypatch):
    monkeypatch.setattr(config.ARGS, "infra_max_attempts", 5)
    monkeypatch.setattr(config.ARGS, "infra_backoff_base_s", 0.125)
    p = watchdog.policy_from_config()
    assert p.max_attempts == 5
    assert p.base_delay_s == 0.125


def test_policy_env_overrides_win(monkeypatch):
    monkeypatch.setenv("H2O3TPU_INFRA_MAX_ATTEMPTS", "7")
    assert watchdog.policy_from_config().max_attempts == 7


def test_retry_call_recovers_from_infra_blip():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("UNAVAILABLE: worker restarting")
        return "ok"

    p = watchdog.RetryPolicy(max_attempts=3, base_delay_s=1.0,
                             jitter=0.0, sleep=slept.append)
    assert watchdog.retry_call(flaky, policy=p) == "ok"
    assert calls["n"] == 3
    assert slept == [1.0, 2.0]


def test_retry_call_gives_up_after_max_attempts():
    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise RuntimeError("INTERNAL: remote_compile failed")

    p = watchdog.RetryPolicy(max_attempts=3, base_delay_s=0.0,
                             jitter=0.0, sleep=lambda s: None)
    with pytest.raises(RuntimeError):
        watchdog.retry_call(dead, policy=p)
    assert calls["n"] == 3


def test_retry_call_user_error_fails_fast():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("bad params")

    with pytest.raises(ValueError):
        watchdog.retry_call(bad, policy=watchdog.RetryPolicy(
            max_attempts=5, sleep=lambda s: None))
    assert calls["n"] == 1


# ---------------------------------------------------------------- probe


def test_probe_backend_alive():
    rt = watchdog.probe_backend(timeout_s=30.0)
    assert rt < 30.0


def test_probe_failure_injected_and_counted():
    from h2o3_tpu import telemetry
    fails0 = telemetry.REGISTRY.value("backend_probe_failures_total")
    watchdog.inject_fault("probe", times=1)
    with pytest.raises(watchdog.InjectedFault):
        watchdog.probe_backend()
    assert telemetry.REGISTRY.value(
        "backend_probe_failures_total") - fails0 == 1
    # fault consumed: the next probe finds the backend alive again
    assert watchdog.probe_backend(timeout_s=30.0) >= 0.0


def test_probe_with_retry_survives_transient_failure():
    watchdog.inject_fault("probe", times=2)
    p = watchdog.RetryPolicy(max_attempts=3, base_delay_s=0.0,
                             jitter=0.0, sleep=lambda s: None)
    assert watchdog.probe_with_retry(policy=p) >= 0.0
    assert watchdog.fired("probe") == 2


# ------------------------------------------------------- fault injection


def test_env_fault_spec_parsed(monkeypatch):
    monkeypatch.setenv("H2O3TPU_FAULTS",
                       "frame_map:2:INTERNAL:, probe:1")
    monkeypatch.setattr(watchdog, "_env_parsed", False)
    watchdog.clear_faults()
    with pytest.raises(watchdog.InjectedFault, match="INTERNAL"):
        watchdog.maybe_fail("frame_map")
    with pytest.raises(watchdog.InjectedFault):
        watchdog.maybe_fail("frame_map")
    watchdog.maybe_fail("frame_map")           # budget spent: no-op
    with pytest.raises(watchdog.InjectedFault, match="UNAVAILABLE"):
        watchdog.maybe_fail("probe")


def test_injected_fault_classifies_as_infra():
    watchdog.inject_fault("job", times=1)
    with pytest.raises(watchdog.InjectedFault) as ei:
        watchdog.maybe_fail("job")
    assert watchdog.is_infra_error(ei.value)


def test_frame_reduce_fault_retried_by_job(monkeypatch):
    """End-to-end degradation path: a psum dispatch dies with a
    classified infra error mid-job; the job-level watchdog retry reruns
    the work and succeeds — no real TPU crash required."""
    from h2o3_tpu.core.job import DONE, Job
    from h2o3_tpu.parallel.map_reduce import frame_reduce
    monkeypatch.setattr(config.ARGS, "infra_backoff_base_s", 0.001)
    watchdog.inject_fault("frame_reduce", times=1)
    x = np.arange(64.0)

    def work(job):
        return float(frame_reduce(lambda a: a.sum(), x))

    j = Job("fault-injected reduce").start(work)
    assert j.status == DONE
    assert j.result == pytest.approx(float(x.sum()))
    assert watchdog.fired("frame_reduce") == 1


# ------------------------------------------------------------- recovery


def test_recovery_state_atomic_roundtrip(tmp_path):
    from h2o3_tpu.core.recovery import Recovery
    rec = Recovery(str(tmp_path / "r"), state_name="automl_state")
    assert rec.read_state() is None
    rec.write_state({"done_steps": ["GBM_1"], "models": {}})
    assert rec.read_state()["done_steps"] == ["GBM_1"]
    # atomic: no tmp debris next to the state file
    assert os.listdir(rec.dir) == ["automl_state.json"]


def test_recovery_skips_torn_model_snapshot(tmp_path):
    from h2o3_tpu.core.recovery import Recovery
    rec = Recovery(str(tmp_path / "r"))
    with open(os.path.join(rec.dir, "model_torn.bin"), "wb") as f:
        f.write(b"\x80\x04 not a pickle")
    assert rec.load_models(["model_torn.bin"]) == []


def test_recovery_rejects_unserializable_params():
    from h2o3_tpu.core.recovery import ensure_json_safe
    with pytest.raises(ValueError, match="ndarray"):
        ensure_json_safe({"w": np.zeros(3)}, "recovery_dir fixed")


@pytest.mark.allow_key_leak      # train_capped puts keys from job threads
def test_automl_recovery_snapshot_and_resume(tmp_path, classif_frame):
    """Fast resume path (no kill): a finished single-step run leaves a
    complete state; resume restores the model instead of retraining."""
    from h2o3_tpu.automl import H2OAutoML, resume_automl
    d = str(tmp_path / "rec")
    aml = H2OAutoML(max_models=1, seed=4, nfolds=0,
                    include_algos=["glm"], max_runtime_secs=120,
                    recovery_dir=d)
    aml.train(y="y", training_frame=classif_frame)
    assert len(aml.leaderboard.models) == 1
    trained_key = aml.leaderboard.models[0].key
    state = json.load(open(os.path.join(d, "automl_state.json")))
    assert state["done_steps"] == ["GLM_1"]

    res = resume_automl(d, classif_frame)
    assert [m.key for m in res.leaderboard.models] == [trained_key]
    # nothing retrained: the restored model IS the leaderboard
    post = [e for e in res.event_log
            if e["stage"] == "model"]
    assert post == []


# --------------------------------------------- bench subprocess isolation


def _run_bench(tmp_path, extra_env, timeout=120):
    env = dict(os.environ)
    env.update({"H2O3TPU_BENCH_STUB": "1",
                "JAX_PLATFORMS": "cpu",
                "H2O3TPU_INFRA_BACKOFF_BASE_S": "0.05",
                "H2O3TPU_INFRA_BACKOFF_MAX_S": "0.1",
                "H2O3TPU_FAULT_STATE": str(tmp_path / "faultstate")})
    env.update(extra_env)
    p = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, text=True, timeout=timeout)
    # parse only up to the tail-proof summary (which re-prints every
    # line and would double-count)
    stdout = p.stdout.split("# ---- summary")[0]
    lines = [json.loads(ln) for ln in stdout.splitlines()
             if ln.strip().startswith("{")]
    return p, lines


@pytest.mark.allow_key_leak
def test_bench_wedged_config_costs_one_line(tmp_path):
    """Acceptance: an injected wedged backend (a child that never
    finishes) costs exactly one config line — the others still emit —
    and the recorded budget never goes below 0."""
    p, lines = _run_bench(tmp_path, {
        "H2O3TPU_BENCH_BUDGET_S": "90",
        # cap >> any healthy stub config (~1s) but small: the wedged
        # child burns the full cap before the kill, straight wall time
        "H2O3TPU_BENCH_CONFIG_TIMEOUT_S": "5",
        "H2O3TPU_BENCH_TRACE_DIR": str(tmp_path / "traces")})
    assert p.returncode == 0, p.stderr[-2000:]
    by_metric = {}
    for ln in lines:
        by_metric.setdefault(ln["metric"], []).append(ln)
    assert "value" in by_metric["stub config stub_a"][0]
    assert "value" in by_metric["stub config stub_b"][0]
    # every SUCCESSFUL config also banked a Chrome-trace artifact
    trace_line = by_metric["trace stub_a"][0]
    with open(trace_line["trace_path"]) as f:
        trace = json.load(f)
    assert all({"ph", "ts", "pid", "tid"} <= set(e)
               for e in trace["traceEvents"])
    wedge = by_metric["stub_wedge"][0]
    assert "wedged" in wedge["error"]
    # the roofline stub emits the hardware-relative fields (ISSUE 8):
    # every BENCH line carries mfu/hbm_util even without a backend
    rf = next(v[0] for k, v in by_metric.items()
              if k.startswith("roofline"))
    assert rf["mfu"] > 0 and rf["hbm_util"] > 0
    # the stepprof stub (ISSUE 20) proves the profiler's contracts
    # without a backend: bounded ring, straggler identity on synthetic
    # peers, and the benchdiff regression gate's pass/fail split
    sp = next(v[0] for k, v in by_metric.items()
              if k.startswith("stepprof"))
    assert sp["ring_len"] == 8 and sp["straggler"] == 1
    assert sp["skew_ratio"] > 1.5
    assert sp["benchdiff_identical_rc"] == 0
    assert sp["benchdiff_regression_rc"] == 1
    budget = by_metric["budget"][0]
    assert budget["left_s"] >= 0.0
    assert budget["budget_s"] >= 0.0
    for ln in lines:                       # no skipped line went negative
        if "skipped" in ln:
            assert "-" not in ln["skipped"]


@pytest.mark.allow_key_leak
def test_bench_preflight_probe_retries_then_recovers(tmp_path):
    """Transient probe failures (2 injected, shared across probe child
    processes via H2O3TPU_FAULT_STATE) are absorbed by the bounded
    backoff; every config line still emits."""
    p, lines = _run_bench(tmp_path, {
        "H2O3TPU_FAULTS": "probe:2",
        "H2O3TPU_BENCH_BUDGET_S": "90",
        "H2O3TPU_BENCH_CONFIG_TIMEOUT_S": "10"})
    assert p.returncode == 0, p.stderr[-2000:]
    metrics = {ln["metric"] for ln in lines if "value" in ln}
    assert {"stub config stub_a", "stub config stub_b"} <= metrics
    assert p.stderr.count("probe attempt") == 2


@pytest.mark.allow_key_leak
def test_bench_dead_backend_fails_fast_per_config(tmp_path):
    """A permanently dead backend costs error lines, not a hung bench:
    each config fails fast after the probe's bounded backoff."""
    p, lines = _run_bench(tmp_path, {
        "H2O3TPU_FAULTS": "probe:999",
        "H2O3TPU_INFRA_MAX_ATTEMPTS": "2",
        "H2O3TPU_BENCH_BUDGET_S": "60",
        "H2O3TPU_BENCH_CONFIG_TIMEOUT_S": "10"})
    assert p.returncode == 0, p.stderr[-2000:]
    errors = [ln for ln in lines if "error" in ln]
    # one per stub config (incl. grid, treekernel, cloud, roofline,
    # checkpoint, memgov, ingest, serving, sched, slo, fleet,
    # durability, globalfit, stepprof)
    assert len(errors) == 17
    assert all("backend dead" in ln["error"] for ln in errors)
    budget = [ln for ln in lines if ln["metric"] == "budget"][0]
    assert budget["left_s"] >= 0.0


# ------------------------------------------- SIGKILL-mid-AutoML resume


def _ft_frame():
    """MUST match tests/ft_worker.py build_data()."""
    import h2o3_tpu
    r = np.random.RandomState(17)
    n = 1200
    X = r.randn(n, 5)
    logits = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2]
    y = (r.rand(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


@pytest.mark.slow
@pytest.mark.allow_key_leak
def test_sigkill_mid_automl_resume(tmp_path):
    """Acceptance: SIGKILL a worker mid-AutoML, resume_automl() in a
    fresh "cluster" (this process) — the leaderboard ends complete, and
    no step that finished pre-kill retrains."""
    from h2o3_tpu.automl import resume_automl
    d = str(tmp_path / "rec")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.Popen([sys.executable, FT_WORKER, d], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    state_path = os.path.join(d, "automl_state.json")
    deadline = time.time() + 420
    killed = False
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                break                      # finished before we could kill
            if os.path.exists(state_path):
                with open(state_path) as f:
                    st = json.load(f)
                if len(st.get("done_steps", [])) >= 1:
                    os.kill(proc.pid, signal.SIGKILL)
                    killed = True
                    break
            time.sleep(0.5)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert killed, ("worker finished (or never snapshotted) before the "
                    f"kill; rc={proc.returncode}")

    with open(state_path) as f:
        pre = json.load(f)
    pre_steps = set(pre["done_steps"])
    pre_keys = {os.path.basename(f)[:-len(".bin")]
                for fs in pre["models"].values() for f in fs}
    assert pre_steps and pre_keys

    fr = _ft_frame()
    aml = resume_automl(d, fr)
    tab = aml.leaderboard.as_table()
    lead_keys = {m.key for m in aml.leaderboard.models}
    # every pre-kill model survived into the resumed leaderboard
    assert pre_keys <= lead_keys
    # the plan continued: the resumed run reached the max_models budget
    # counting the restored models exactly once
    assert len(tab) >= len(pre_keys) + 1
    assert len(lead_keys) == len(aml.leaderboard.models)   # no dup keys
    # no step retrained twice: steps done pre-kill never ran post-resume
    post_steps = {e["message"].split(" done ")[0]
                  for e in aml.event_log if e["stage"] == "model"}
    assert not (pre_steps & post_steps), (pre_steps, post_steps)
    # and the final state is the union, each step recorded once
    with open(state_path) as f:
        final = json.load(f)
    assert len(final["done_steps"]) == len(set(final["done_steps"]))
    assert pre_steps <= set(final["done_steps"])
