"""Cluster work scheduler (ISSUE 15, parallel/scheduler.py).

Three tiers:

- RunBoard unit tests: the coordinator's lease/complete/reassign state
  machine, dry (no KV, no backend) — the same surface bench.py's
  ``_stub_sched`` leg drives.
- Inline-run tests: ``scheduler.run`` on the single-process pytest
  cloud degrades to the inline executor but still exercises the item
  execution path (failure capture, nesting guard, lease gauge).
- ``multiprocess`` tests: a REAL 2-process jax.distributed CPU cloud
  runs an 8-combo GBM grid through the scheduler; combos must execute
  on BOTH hosts and the result must be bit-identical to the
  single-process scheduler-off reference — including when one host is
  SIGKILLed mid-grid and its leases are reassigned.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from h2o3_tpu.parallel import scheduler
from h2o3_tpu.parallel.scheduler import RunBoard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "sched_worker.py")
WORKER_TIMEOUT_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))


# ------------------------------------------------- RunBoard state machine


def test_runboard_initial_leases_cover_all_items():
    b = RunBoard(8, [0, 1], offset=0)
    assert sorted(i for p in (0, 1) for i in b.assignments(p)) == \
        list(range(8))
    assert b.owner(0) == 0 and b.owner(1) == 1     # round-robin
    assert not b.complete() and b.pending() == list(range(8))


def test_runboard_offset_rotates_first_owner():
    assert RunBoard(4, [0, 1, 2], offset=1).owner(0) == 1
    assert RunBoard(4, [0, 1, 2], offset=2).owner(0) == 2


def test_runboard_result_requires_current_generation():
    b = RunBoard(2, [0, 1])
    assert b.on_result(0, 0, 1)
    assert not b.on_result(0, 0, 1)                # duplicate
    moved = b.on_dead(1)
    assert moved == [(1, 0, 2)]                    # item 1 -> host 0 gen 2
    assert not b.on_result(1, 1, 1)                # stale generation
    assert b.on_result(1, 0, 2)
    assert b.complete()


def test_runboard_dead_peer_reassigns_only_unresulted():
    b = RunBoard(6, [0, 1, 2])
    assert b.on_result(1, 1, 1)                    # host 1 finishes item 1
    moved = b.on_dead(1)
    assert [i for i, _, _ in moved] == [4]         # its other lease only
    assert all(p in (0, 2) for _, p, _ in moved)
    assert b.on_dead(1) == []                      # idempotent
    assert b.alive() == [0, 2]


def test_runboard_no_alive_hosts_raises():
    b = RunBoard(2, [0, 1])
    b.on_dead(0)
    with pytest.raises(RuntimeError):
        b.on_dead(1)


def test_runboard_rejects_degenerate_inputs():
    with pytest.raises(ValueError):
        RunBoard(0, [0])
    with pytest.raises(ValueError):
        RunBoard(1, [])


# ------------------------------------------------- inline (degenerate) run


def test_inline_run_executes_every_item_in_order():
    seen = []

    def execute(i):
        seen.append(i)
        return i * 10

    res = scheduler.run("test:inline", 4, execute)
    assert seen == [0, 1, 2, 3]
    assert {i: r["data"] for i, r in res.items()} == \
        {0: 0, 1: 10, 2: 20, 3: 30}
    assert all(r["ok"] for r in res.values())
    assert scheduler.leases_held() == 0


def test_inline_run_captures_failures_as_results():
    def execute(i):
        if i == 1:
            raise ValueError("boom on 1")
        return i

    res = scheduler.run("test:fail", 3, execute)
    assert res[0]["ok"] and res[2]["ok"]
    assert not res[1]["ok"] and "boom on 1" in res[1]["error"]


def test_nested_run_is_guarded():
    """Work inside a scheduled item runs on ONE host — a nested run()
    must see active() False (and degrade inline) instead of entering
    the SPMD protocol from a single process."""
    states = {}

    def inner(_i):
        return "inner"

    def outer(i):
        states["in_item"] = scheduler.in_item()
        states["active"] = scheduler.active()
        return scheduler.run("test:nested-inner", 1, inner)[0]["data"]

    res = scheduler.run("test:nested-outer", 1, outer)
    assert res[0]["ok"] and res[0]["data"] == "inner"
    assert states["in_item"] is True
    assert states["active"] is False
    assert not scheduler.in_item()


def test_mode_gate(monkeypatch):
    from h2o3_tpu.core import config as _cfg
    monkeypatch.setattr(_cfg.ARGS, "scheduler", "off")
    assert not scheduler.active()
    monkeypatch.setattr(_cfg.ARGS, "scheduler", "on")
    assert scheduler.active()
    monkeypatch.setattr(_cfg.ARGS, "scheduler", "auto")
    assert not scheduler.active()      # single-process pytest cloud


def test_snapshot_counts_runs_and_items():
    s0 = scheduler.snapshot()
    scheduler.run("test:count", 2, lambda i: i)
    s1 = scheduler.snapshot()
    assert s1["runs"] == s0["runs"] + 1
    assert s1["items_done"] == s0["items_done"] + 2


# ------------------------------------------------- real multiprocess cloud


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(mode, nproc, out):
    """Run one worker pod; returns (returncodes, logs)."""
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, str(nproc), str(i), out, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(nproc)
    ]
    logs = []
    deadline = time.time() + WORKER_TIMEOUT_S
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(deadline - time.time(), 1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + \
                f"\n[TIMEOUT after {WORKER_TIMEOUT_S:.0f}s]"
        logs.append(stdout)
    return [p.returncode for p in procs], logs


def _read(out, pid):
    with open(f"{out}.{pid}") as f:
        return json.load(f)


@pytest.fixture(scope="module")
def sched_results(tmp_path_factory):
    """Three legs over the same data + grid: the single-process
    scheduler-off reference, the 2-process scheduled run, and the
    2-process run where host 1 is SIGKILLed mid-grid."""
    tmp = tmp_path_factory.mktemp("sched")
    legs = {}
    for mode, nproc in (("ref", 1), ("run", 2), ("kill", 2)):
        out = str(tmp / f"{mode}.json")
        rcs, logs = _launch(mode, nproc, out)
        legs[mode] = {"rcs": rcs, "logs": logs, "out": out}
    return legs


def _assert_ok(leg, who="every worker"):
    assert all(rc == 0 for rc in leg["rcs"]), (
        f"{who} must exit 0 (rcs={leg['rcs']}):\n"
        + "\n".join(f"--- worker {i} log ---\n{lg[-3000:]}"
                    for i, lg in enumerate(leg["logs"])))


# slow: the three pod legs cost ~30s of 1-core wallclock, and tier-1's
# 870s cap has no room — run with `-m multiprocess` (the RunBoard +
# inline tests above keep the scheduler surface in every tier-1 run)
@pytest.mark.slow
@pytest.mark.multiprocess
def test_sched_grid_spreads_across_both_hosts(sched_results):
    leg = sched_results["run"]
    _assert_ok(leg)
    r0, r1 = _read(leg["out"], 0), _read(leg["out"], 1)
    # per-host lease metrics: combos executed on BOTH processes
    assert r0["items_completed_here"] > 0
    assert r1["items_completed_here"] > 0
    assert r0["items_completed_here"] + r1["items_completed_here"] == 8
    assert r0["sched"]["runs"] == r1["sched"]["runs"] == 1
    assert r0["sched"]["leases_held"] == r1["sched"]["leases_held"] == 0


@pytest.mark.slow
@pytest.mark.multiprocess
def test_sched_grid_bit_identical_to_single_process(sched_results):
    ref, run = sched_results["ref"], sched_results["run"]
    _assert_ok(ref)
    _assert_ok(run)
    grid_ref = _read(ref["out"], 0)["grid"]
    assert len(grid_ref) == 8
    # bit-identical: full-precision floats straight from json
    assert _read(run["out"], 0)["grid"] == grid_ref
    assert _read(run["out"], 1)["grid"] == grid_ref


@pytest.mark.slow
@pytest.mark.multiprocess
def test_sched_sigkill_mid_grid_reassigns_and_matches(sched_results):
    ref, kill = sched_results["ref"], sched_results["kill"]
    _assert_ok(ref)
    # worker 1 SIGKILLed itself mid-grid; worker 0 must still finish
    assert kill["rcs"][0] == 0, (
        "surviving worker failed:\n"
        + "\n".join(f"--- worker {i} log ---\n{lg[-3000:]}"
                    for i, lg in enumerate(kill["logs"])))
    assert kill["rcs"][1] == -signal.SIGKILL
    r0 = _read(kill["out"], 0)
    # the dead host's leases moved here and the result is bit-identical
    assert r0["sched"]["items_reassigned"] >= 1
    assert r0["grid"] == _read(ref["out"], 0)["grid"]
    # no RUNNING job leak: every job reached a terminal state
    assert "RUNNING" not in r0["job_statuses"], r0["job_statuses"]


@pytest.mark.slow
@pytest.mark.multiprocess
def test_sched_no_running_job_leak(sched_results):
    leg = sched_results["run"]
    _assert_ok(leg)
    for pid in (0, 1):
        statuses = _read(leg["out"], pid)["job_statuses"]
        assert "RUNNING" not in statuses, (pid, statuses)
