"""Native C++ CSV tokenizer vs the pandas fallback (conformance)."""

import gzip
import io

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.native import load_csv_parser, parse_csv_bytes


pytestmark = pytest.mark.skipif(load_csv_parser() is None,
                                reason="no native toolchain")


def test_native_basic_types_and_nas():
    data = (b"a,b,c,d\n"
            b"1,2.5,x,2020-01-01\n"
            b"2,NA,y,2020-01-02\n"
            b",3.5,,2020-01-03\n")
    cols, domains = parse_csv_bytes(data)
    assert list(cols) == ["a", "b", "c", "d"]
    np.testing.assert_array_equal(cols["a"][:2], [1.0, 2.0])
    assert np.isnan(cols["a"][2])
    assert np.isnan(cols["b"][1])
    assert cols["c"][0] == "x" and cols["c"][2] is None
    assert domains["c"] == ["x", "y"]
    assert domains["d"][0] == "2020-01-01"


def test_native_quotes_and_escapes():
    data = (b'name,val\n'
            b'"hello, world",1\n'
            b'"say ""hi""",2\n'
            b'plain,3\n')
    cols, domains = parse_csv_bytes(data)
    assert cols["name"][0] == "hello, world"
    assert cols["name"][1] == 'say "hi"'
    assert cols["name"][2] == "plain"
    np.testing.assert_array_equal(cols["val"], [1.0, 2.0, 3.0])


def test_native_crlf_and_blank_lines():
    data = b"a,b\r\n1,2\r\n\r\n3,4\r\n"
    cols, _ = parse_csv_bytes(data)
    np.testing.assert_array_equal(cols["a"], [1.0, 3.0])


def test_native_multithread_matches_single():
    r = np.random.RandomState(0)
    n = 20000
    lines = ["x,y,g"]
    levels = ["aa", "bb", "cc", "dd"]
    for i in range(n):
        lines.append(f"{r.randn():.6f},{r.randint(100)},{levels[r.randint(4)]}")
    data = ("\n".join(lines) + "\n").encode()
    c1, d1 = parse_csv_bytes(data, nthreads=1)
    c8, d8 = parse_csv_bytes(data, nthreads=8)
    np.testing.assert_allclose(c1["x"], c8["x"])
    np.testing.assert_array_equal(c1["g"].astype(str), c8["g"].astype(str))
    assert d1["g"] == d8["g"] == sorted(levels)


def test_import_file_native_matches_pandas(tmp_path):
    import pandas as pd
    r = np.random.RandomState(1)
    n = 5000
    df = pd.DataFrame({
        "num": r.randn(n),
        "int": r.randint(0, 50, n).astype(float),
        "cat": np.array(["u", "v", "w"], object)[r.randint(0, 3, n)],
    })
    df.loc[r.rand(n) < 0.05, "num"] = np.nan
    p = tmp_path / "t.csv"
    df.to_csv(p, index=False)
    fr = h2o3_tpu.import_file(str(p))
    assert fr.shape == (n, 3)
    np.testing.assert_allclose(np.nanmean(fr.col("num").to_numpy()),
                               df["num"].mean(), rtol=1e-6)
    assert fr.col("cat").domain == ["u", "v", "w"]
    # and gz round-trips through the same tokenizer
    pgz = tmp_path / "t.csv.gz"
    with gzip.open(pgz, "wb") as f:
        df.to_csv(io.TextIOWrapper(f), index=False)
    fr2 = h2o3_tpu.import_file(str(pgz))
    assert fr2.shape == (n, 3)
