"""Golden numeric agreement vs independent reference implementations.

The testdir_golden tier of the reference's test pyramid (SURVEY §4:
"numeric agreement vs R reference implementations") — here sklearn and
scipy play the R role: each algorithm must land within a quality band of
an independent implementation on the same data.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame

sklearn = pytest.importorskip("sklearn")


def _make(seed=7, n=2000, f=8):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logits = X[:, 0] * 1.2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (r.rand(n) < 1 / (1 + np.exp(-logits))).astype(int)
    return X, y


def _frame(X, y=None, ycat=True):
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cats = []
    if y is not None:
        if ycat:
            cols["y"] = np.array(["n", "p"], object)[y]
            cats = ["y"]
        else:
            cols["y"] = y.astype(np.float64)
    return Frame.from_numpy(cols, categorical=cats)


def test_gbm_auc_tracks_sklearn():
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.metrics import roc_auc_score
    X, y = _make()
    fr = _frame(X, y)
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=40, max_depth=4, learn_rate=0.1, seed=1).train(
        fr, y="y")
    ours = m.training_metrics["AUC"]
    sk = GradientBoostingClassifier(n_estimators=40, max_depth=4,
                                    learning_rate=0.1, random_state=1)
    sk.fit(X, y)
    theirs = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    assert ours > 0.8
    assert abs(ours - theirs) < 0.06, (ours, theirs)


def test_drf_auc_tracks_sklearn_forest():
    from sklearn.ensemble import RandomForestClassifier
    from sklearn.metrics import roc_auc_score
    X, y = _make(seed=5)
    fr = _frame(X, y)
    from h2o3_tpu.models.drf import DRFEstimator
    m = DRFEstimator(ntrees=40, max_depth=10, seed=1).train(fr, y="y")
    p1 = m.predict(fr).col("p1").to_numpy()
    ours = roc_auc_score(y, p1)
    sk = RandomForestClassifier(n_estimators=40, max_depth=10,
                                random_state=1, max_features="sqrt")
    sk.fit(X, y)
    theirs = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    # in-sample forest AUCs are near-1 for both; ours must keep pace
    assert ours > theirs - 0.05, (ours, theirs)


def test_kmeans_inertia_tracks_sklearn():
    from sklearn.cluster import KMeans as SKKMeans
    r = np.random.RandomState(3)
    centers = r.randn(4, 5) * 4
    X = np.concatenate([centers[i] + r.randn(250, 5)
                        for i in range(4)])
    fr = _frame(X)
    from h2o3_tpu.models.kmeans import KMeansEstimator
    m = KMeansEstimator(k=4, seed=1, standardize=False).train(
        fr, x=list(fr.names))
    ours = m.training_metrics["tot_withinss"]
    sk = SKKMeans(n_clusters=4, n_init=5, random_state=1).fit(X)
    assert ours < sk.inertia_ * 1.05, (ours, sk.inertia_)


def test_pca_variance_matches_sklearn():
    from sklearn.decomposition import PCA as SKPCA
    r = np.random.RandomState(9)
    X = r.randn(500, 6) @ np.diag([3.0, 2.0, 1.5, 1.0, 0.5, 0.1])
    fr = _frame(X)
    from h2o3_tpu.models.pca import PCAEstimator
    m = PCAEstimator(k=3, transform="demean").train(fr, x=list(fr.names))
    sk = SKPCA(n_components=3).fit(X)
    ours = np.abs(np.asarray(m.eigvecs))[:, :3]
    theirs = np.abs(sk.components_.T)
    np.testing.assert_allclose(ours, theirs, atol=5e-3)


def test_isotonic_matches_sklearn():
    from sklearn.isotonic import IsotonicRegression as SKIso
    r = np.random.RandomState(2)
    x = np.sort(r.rand(400) * 10)
    y = np.log1p(x) + 0.3 * r.randn(400)
    fr = Frame.from_numpy({"x": x, "y": y})
    from h2o3_tpu.models.isotonic import IsotonicRegressionEstimator
    m = IsotonicRegressionEstimator().train(fr, x=["x"], y="y")
    ours = m.predict(fr).col("predict").to_numpy()
    theirs = SKIso(out_of_bounds="clip").fit(x, y).predict(x)
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_glrm_reconstruction_beats_truncated_svd():
    from sklearn.decomposition import TruncatedSVD
    r = np.random.RandomState(4)
    W = r.randn(300, 3)
    H = r.randn(3, 8)
    X = W @ H + 0.05 * r.randn(300, 8)
    fr = _frame(X)
    from h2o3_tpu.models.glrm import GLRMEstimator
    m = GLRMEstimator(k=3, transform="none", max_iterations=80,
                      seed=1).train(fr, x=list(fr.names))
    sk = TruncatedSVD(n_components=3).fit(X)
    sk_err = ((X - sk.inverse_transform(sk.transform(X))) ** 2).sum()
    ours = float(m.output.get("objective") or m.output.get("final_obj")
                 or np.nan)
    # GLRM with no regularization must get within 2x of the optimal
    # rank-3 reconstruction (SVD is the global optimum)
    assert np.isfinite(ours) and ours < 2.0 * sk_err + 1e-6, (ours, sk_err)
