"""Categorical subset (bitset) splits vs an exact oracle.

Reference: hex/tree/DTree.java:619-697 findBestSplitPoint sorts category
bins by prediction and scans prefixes — the optimal subset split for
convex losses. Round-2 aliased categories >64 levels (code % nb); these
tests pin the round-3 fidelity contract: real bins up to nbins_cats,
per-node sorted-prefix subset splits, and consistent offline scoring.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBMEstimator


def _highcard_data(n=20000, levels=300, seed=0):
    r = np.random.RandomState(seed)
    code = r.randint(0, levels, n)
    effect = r.randn(levels) * 2.0          # arbitrary w.r.t. code order
    y = effect[code] + 0.1 * r.randn(n)
    return code.astype(float), y, effect


def _oracle_root_gain(code, y, levels):
    """Exact best-subset SSE gain at the root: sort levels by mean(y),
    scan prefixes (optimal for squared loss)."""
    sums = np.bincount(code.astype(int), weights=y, minlength=levels)
    cnts = np.bincount(code.astype(int), minlength=levels).astype(float)
    means = np.where(cnts > 0, sums / np.maximum(cnts, 1), np.inf)
    order = np.argsort(means)               # empties (inf) sort last
    s, c = sums[order], cnts[order]
    cs, cc = np.cumsum(s), np.cumsum(c)
    tot_s, tot_c = cs[-1], cc[-1]
    valid = (cc >= 1) & ((tot_c - cc) >= 1)
    with np.errstate(divide="ignore", invalid="ignore"):
        gain = (cs ** 2 / np.maximum(cc, 1e-12)
                + (tot_s - cs) ** 2 / np.maximum(tot_c - cc, 1e-12)
                - tot_s ** 2 / tot_c)
    gain = np.where(valid, gain, -np.inf)
    return float(gain.max())


def test_root_subset_split_matches_oracle():
    code, y, effect = _highcard_data()
    levels = 300
    fr = Frame.from_numpy({"c": code, "y": y}, categorical=["c"])
    m = GBMEstimator(ntrees=1, max_depth=1, learn_rate=1.0, min_rows=1.0,
                     min_split_improvement=0.0).train(fr, x=["c"], y="y")
    t = m.forest
    assert bool(np.asarray(t.is_split)[0, 0, 0])
    assert bool(np.asarray(t.cat_split)[0, 0, 0])

    # realized gain of the model's actual partition, vs the exact oracle
    words = np.asarray(t.left_words)[0, 0, 0]
    bins = code.astype(int)                 # card <= nbins_cats: bin == code
    goleft = ((words[bins >> 5] >> (bins & 31).astype(np.uint32)) & 1) == 1
    yl, yr = y[goleft], y[~goleft]
    assert len(yl) and len(yr)
    tot = y.sum() ** 2 / len(y)
    realized = (yl.sum() ** 2 / len(yl) + yr.sum() ** 2 / len(yr) - tot)
    oracle = _oracle_root_gain(code, y, levels)
    assert realized >= 0.999 * oracle, (realized, oracle)


def test_highcard_beats_range_splits():
    """A shallow tree must capture a code-order-arbitrary signal —
    impossible with range splits over code order (the round-2 behavior)."""
    r = np.random.RandomState(3)
    n, levels = 8000, 250
    code = r.randint(0, levels, n)
    y = (np.sin(code * 1.7) > 0).astype(float)
    fr = Frame.from_numpy({"c": code.astype(float),
                           "x": r.randn(n), "y": y},
                          categorical=["c", "y"])
    m = GBMEstimator(ntrees=5, max_depth=3).train(fr, x=["c", "x"], y="y")
    auc = m.training_metrics["AUC"]
    assert auc > 0.95, auc


def test_beyond_nbins_cats_groups_adjacent_codes():
    """card > nbins_cats: adjacent codes share a bin (integer divide),
    never arbitrary modulo collisions; training stays functional."""
    r = np.random.RandomState(5)
    n, levels = 6000, 600
    code = r.randint(0, levels, n)
    y = (code < 300).astype(float) + 0.05 * r.randn(n)
    fr = Frame.from_numpy({"c": code.astype(float), "y": y},
                          categorical=["c"])
    m = GBMEstimator(ntrees=2, max_depth=2, nbins_cats=64,
                     learn_rate=1.0).train(fr, x=["c"], y="y")
    # signal aligned with adjacency survives grouping almost unharmed
    assert m.training_metrics["MSE"] < 0.02


def test_mojo_roundtrip_with_cat_splits(tmp_path):
    code, y, _ = _highcard_data(n=3000, levels=220, seed=7)
    dom = [f"L{i:03d}" for i in range(220)]
    fr = Frame.from_numpy({"c": code, "y": y}, categorical=["c"])
    m = GBMEstimator(ntrees=3, max_depth=3).train(fr, x=["c"], y="y")
    p = str(tmp_path / "cat.zip")
    m.download_mojo(p)
    from h2o3_tpu import genmodel
    gm = genmodel.load_mojo(p)
    lvls = fr.col("c").domain
    raw = {"c": np.array([lvls[int(c)] for c in code.astype(int)],
                         object)}
    off = gm.predict(raw)["predict"]
    ins = m.predict(fr).col("predict").to_numpy()
    assert np.abs(off - ins).max() < 1e-5, np.abs(off - ins).max()
