"""Histogram / segment-sum kernel correctness vs numpy references —
the DHistogram/ScoreBuildHistogram test role (h2o-algos
src/test/java/hex/tree/...)."""

import numpy as np
import jax
import jax.numpy as jnp

from h2o3_tpu.ops.histogram import histogram
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh, shard_rows


def _np_histogram(bins, nid, w, g, h, L, B):
    F = bins.shape[1]
    out = np.zeros((L, F, B, 3))
    for i in range(bins.shape[0]):
        for f in range(F):
            out[nid[i], f, bins[i, f], 0] += w[i]
            out[nid[i], f, bins[i, f], 1] += w[i] * g[i]
            out[nid[i], f, bins[i, f], 2] += w[i] * h[i]
    return out


def test_histogram_matches_numpy(rng):
    N, F, B, L = 512, 3, 8, 4
    bins = rng.randint(0, B, (N, F)).astype(np.int32)
    nid = rng.randint(0, L, N).astype(np.int32)
    w = rng.rand(N).astype(np.float32)
    g = rng.randn(N).astype(np.float32)
    h = rng.rand(N).astype(np.float32)
    mesh = get_mesh()
    got = histogram(shard_rows(bins), shard_rows(nid), shard_rows(w),
                    shard_rows(g), shard_rows(h),
                    n_nodes=L, n_bins=B, mesh=mesh, block_rows=64)
    want = _np_histogram(bins, nid, w, g, h, L, B)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=1e-3)


def test_histogram_sharded_equals_unsharded(rng):
    """The psum over 8 shards must equal the single-shard answer —
    the @CloudSize(4)-vs-1 consistency check."""
    N, F, B, L = 1024, 4, 16, 2
    bins = rng.randint(0, B, (N, F)).astype(np.int32)
    nid = rng.randint(0, L, N).astype(np.int32)
    w = np.ones(N, np.float32)
    g = rng.randn(N).astype(np.float32)
    mesh = get_mesh()
    sharded = histogram(shard_rows(bins), shard_rows(nid), shard_rows(w),
                        shard_rows(g), shard_rows(w),
                        n_nodes=L, n_bins=B, mesh=mesh)
    want = _np_histogram(bins, nid, w, g, w, L, B)
    np.testing.assert_allclose(np.asarray(sharded), want, rtol=2e-3, atol=1e-3)


def test_segment_sum(rng):
    N, K, L = 999, 2, 7  # deliberately not divisible by 8
    nid = rng.randint(0, L, N).astype(np.int32)
    vals = rng.randn(N, K).astype(np.float32)
    got = segment_sum(jnp.asarray(nid), jnp.asarray(vals),
                      n_nodes=L, mesh=get_mesh())
    want = np.zeros((L, K), np.float32)
    for i in range(N):
        want[nid[i]] += vals[i]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_gram_model_sharded_matches_dense():
    """TP-axis Gram (ppermute ring over 'model') must equal the dense
    single-device X'WX on a (4 data x 2 model) mesh."""
    import jax
    from h2o3_tpu.ops.gram import gram_model_sharded
    from h2o3_tpu.parallel import mesh as mesh_mod
    devs = jax.devices("cpu")[:8]
    m = mesh_mod.make_mesh(devs, data_axis=4, model_axis=2)
    r = np.random.RandomState(0)
    N, P_ = 64, 6
    X = r.randn(N, P_).astype(np.float32)
    w = r.rand(N).astype(np.float32)
    z = r.randn(N).astype(np.float32)
    xtx, xtz, ws = jax.jit(
        lambda X, w, z: gram_model_sharded(X, w, z, mesh=m),
    )(X, w, z)
    want_xtx = (X * w[:, None]).T @ X
    want_xtz = X.T @ (w * z)
    np.testing.assert_allclose(np.asarray(xtx), want_xtx, rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(xtz), want_xtz, rtol=2e-4,
                               atol=2e-4)
    assert abs(float(ws) - w.sum()) < 1e-3


def test_gram_model_sharded_pads_odd_width():
    """P not divisible by the model axis: outputs must slice back to P."""
    import jax
    from h2o3_tpu.ops.gram import gram_model_sharded
    from h2o3_tpu.parallel import mesh as mesh_mod
    devs = jax.devices("cpu")[:8]
    m = mesh_mod.make_mesh(devs, data_axis=4, model_axis=2)
    r = np.random.RandomState(1)
    N, P_ = 48, 7
    X = r.randn(N, P_).astype(np.float32)
    w = r.rand(N).astype(np.float32)
    z = r.randn(N).astype(np.float32)
    xtx, xtz, ws = jax.jit(
        lambda X, w, z: gram_model_sharded(X, w, z, mesh=m))(X, w, z)
    assert xtx.shape == (7, 7) and xtz.shape == (7,)
    np.testing.assert_allclose(np.asarray(xtx), (X * w[:, None]).T @ X,
                               rtol=2e-4, atol=2e-4)
