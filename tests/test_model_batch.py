"""Model-batched training (parallel/model_batch.py): vmap hyperparameter
combos into ONE compiled program for grid search, AutoML and the GLM
(alpha, lambda) product.

Acceptance contract (ISSUE 4): a numeric-only GBM grid of >= 8 combos
trains through the batched path with exactly one boost-program compile
per shape bucket (asserted via the compile observer), and batched
results match the sequential path's metrics within 1e-5 under fixed
seeds. Satellite regressions ride along: per-model early-stop masks,
canonical-key resume filtering, the Frame.device_matrix cache and the
device-resident ordinal GLM predict path.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.ml.grid import GridSearch
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.glm import GLMEstimator
from h2o3_tpu.parallel import model_batch


def _class_frame(n=400, seed=1, noise=False):
    r = np.random.RandomState(seed)
    a, b, c = r.randn(n), r.randn(n), r.randn(n)
    if noise:
        yv = r.randint(0, 2, n)
    else:
        yv = (a + 0.5 * b + 0.3 * r.randn(n) > 0).astype(int)
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "c": c,
         "y": np.array(["N", "Y"], object)[yv]}, categorical=["y"])


def _misses(fn: str) -> float:
    """Total jit-cache misses recorded for an observed_jit fn across its
    shape-bucket label sets (telemetry/compile_observer.py)."""
    tot = 0.0
    for (nm, lbl), m in list(telemetry.REGISTRY._metrics.items()):
        if nm.endswith("jit_cache_miss_total") and dict(lbl).get("fn") == fn:
            tot += m.value
    return tot


def _by_combo(grid):
    return {tuple(sorted(m.output["grid_params"].items())): m
            for m in grid.models}


def _metric_diff(m1, m2, keys=("AUC", "logloss", "RMSE")):
    d1, d2 = m1.training_metrics.to_dict(), m2.training_metrics.to_dict()
    return max(abs(d1[k] - d2[k]) for k in keys if k in d1 and k in d2)


# ------------------------------------------------- GBM batched tentpole


def test_gbm_numeric_grid_one_compile_per_bucket_and_parity(monkeypatch):
    """The acceptance criterion: 8 numeric-only combos -> ONE
    gbm.boost_scan_batched compile, sequential-equal metrics,
    leaderboard order preserved."""
    fr = _class_frame()
    hyper = {"learn_rate": [0.05, 0.1], "sample_rate": [0.7, 1.0],
             "min_rows": [1.0, 10.0]}          # 8 combos, one shape bucket
    fixed = dict(ntrees=10, max_depth=3, seed=7)

    m0 = _misses("gbm.boost_scan_batched")
    b0 = telemetry.REGISTRY.value("batched_train_batches_total", algo="gbm")
    g_bat = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    assert len(g_bat.models) == 8
    assert telemetry.REGISTRY.value("batched_train_batches_total",
                                    algo="gbm") == b0 + 1
    assert _misses("gbm.boost_scan_batched") - m0 == 1, \
        "expected exactly ONE boost-program compile for the bucket"
    assert telemetry.REGISTRY.value("batched_train_width", algo="gbm") >= 1

    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    g_seq = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    by = _by_combo(g_seq)
    for m in g_bat.models:
        m2 = by[tuple(sorted(m.output["grid_params"].items()))]
        assert _metric_diff(m, m2) < 1e-5
        assert m.forest.feat.shape[0] == m2.forest.feat.shape[0]
        # varimp ordering agrees too (same trees -> same gains)
        assert [v[0] for v in m.output["varimp"]] == \
            [v[0] for v in m2.output["varimp"]]
    # leaderboard order: identical combos in identical order
    assert [m.output["grid_params"] for m in g_bat.sorted_models()] == \
        [m.output["grid_params"] for m in g_seq.sorted_models()]


def test_gbm_batched_early_stop_masks_match_sequential(monkeypatch):
    """Per-model early-stop MASKS (host-side truncation of the stacked
    forest) reproduce the sequential walk's per-model stop points and
    scoring histories exactly."""
    fr = _class_frame(n=200, seed=3, noise=True)   # flat deviance: stops
    hyper = {"learn_rate": [0.5, 0.01], "min_rows": [5.0, 20.0]}
    fixed = dict(ntrees=40, max_depth=3, seed=7, stopping_rounds=2,
                 score_tree_interval=1, stopping_tolerance=1e-2)
    g_bat = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    g_seq = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    by = _by_combo(g_seq)
    stopped_any = False
    for m in g_bat.models:
        m2 = by[tuple(sorted(m.output["grid_params"].items()))]
        assert m.forest.feat.shape[0] == m2.forest.feat.shape[0]
        assert m.output["scoring_history"] == m2.output["scoring_history"]
        assert _metric_diff(m, m2) < 1e-5
        stopped_any |= m.forest.feat.shape[0] < 40
    assert stopped_any, "no model early-stopped; test lost its teeth"


def test_gbm_batched_max_models_cap_discards_extras():
    """max_models caps the grid exactly like the sequential walk; pre-
    trained extras are discarded from the DKV, not leaked."""
    from h2o3_tpu.core.kv import DKV
    fr = _class_frame()
    before = {k for k in DKV.keys() if k.startswith("model_gbm")}
    hyper = {"learn_rate": [0.05, 0.1, 0.15, 0.2]}
    g = GridSearch(GBMEstimator, hyper,
                   search_criteria={"strategy": "Cartesian",
                                    "max_models": 2},
                   ntrees=5, max_depth=3, seed=7).train(fr, y="y")
    assert len(g.models) == 2
    new = {k for k in DKV.keys()
           if k.startswith("model_gbm")} - before
    assert new == {m.key for m in g.models}, \
        "discarded pre-trained models must leave the DKV"


# ------------------------------------------------- GLM batched tentpole


def test_glm_alpha_lambda_product_parity(monkeypatch):
    """The (alpha, lambda) product of a GLM grid solves as one vmapped
    IRLS program per use_l1 partition; metrics match sequential within
    1e-5 (coefs within ADMM jitter)."""
    fr = _class_frame(n=300, seed=2)
    hyper = {"alpha": [0.0, 0.5], "lambda_": [1e-2, 1e-3, 1e-4, 0.0]}
    b0 = telemetry.REGISTRY.value("batched_train_batches_total", algo="glm")
    g_bat = GridSearch(GLMEstimator, hyper,
                       family="binomial").train(fr, y="y")
    assert len(g_bat.models) == 8
    assert telemetry.REGISTRY.value("batched_train_batches_total",
                                    algo="glm") == b0 + 1
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    g_seq = GridSearch(GLMEstimator, hyper,
                       family="binomial").train(fr, y="y")
    by = _by_combo(g_seq)
    for m in g_bat.models:
        m2 = by[tuple(sorted(m.output["grid_params"].items()))]
        assert _metric_diff(m, m2, keys=("AUC", "logloss")) < 1e-5
        # ADMM's inexact inner solves jitter coefs slightly more than
        # the metric surface moves; bound them loosely
        assert float(np.max(np.abs(np.asarray(m.coef)
                                   - np.asarray(m2.coef)))) < 5e-4
        assert m.output["lambda_best"] == m2.output["lambda_best"]


# -------------------------------------------- planner / fallback layer


def test_bucket_planning_structural_knobs_split():
    # same depth bucket (3..6) batches; 12 lands in the 7..10 bucket...
    # (tree.py DEPTH_BUCKETS = (6, 10, 14)): 3,5 -> 6 | 12 -> 14
    combos = [{"max_depth": 3, "learn_rate": 0.1},
              {"max_depth": 5, "learn_rate": 0.2},
              {"max_depth": 12, "learn_rate": 0.1}]
    buckets = model_batch.plan_buckets("gbm", combos)
    assert sorted(b.width for b in buckets) == [1, 2]
    # a structural knob (ntrees) always splits
    combos = [{"ntrees": 10, "learn_rate": 0.1},
              {"ntrees": 20, "learn_rate": 0.1},
              {"ntrees": 10, "learn_rate": 0.2}]
    buckets = model_batch.plan_buckets("gbm", combos)
    assert sorted(b.width for b in buckets) == [1, 2]
    # glm: only alpha/lambda batch
    combos = [{"alpha": 0.1, "lambda_": 0.0},
              {"alpha": 0.9, "lambda_": 1e-3}]
    assert model_batch.plan_buckets("glm", combos)[0].width == 2


def test_combo_key_canonicalizes_json_round_trips():
    # JSON round trips tuples to lists; the resume filter must not care
    a = {"hidden": [200, 200], "rate": 0.1}
    b = {"rate": 0.1, "hidden": (200, 200)}
    assert model_batch.combo_key(a) == model_batch.combo_key(b)
    assert model_batch.combo_key(a) != model_batch.combo_key(
        {"hidden": [200, 100], "rate": 0.1})


def test_resume_skip_done_filter_set_semantics():
    """_skip_done filtering keys combos on canonical tuples — same
    result as the old O(n·m) dict-equality scan."""
    fr = _class_frame(n=200, seed=5)
    hyper = {"alpha": [0.1, 0.5], "lambda_": [1e-3, 1e-4]}
    gs = GridSearch(GLMEstimator, hyper, family="binomial")
    done = [{"alpha": 0.1, "lambda_": 1e-3}, {"alpha": 0.5, "lambda_": 1e-4}]
    grid = gs.train(fr, y="y", _skip_done=done)
    trained = {tuple(sorted(m.output["grid_params"].items()))
               for m in grid.models}
    assert len(grid.models) == 2
    assert trained == {(("alpha", 0.1), ("lambda_", 1e-4)),
                       (("alpha", 0.5), ("lambda_", 1e-3))}


def test_cv_combos_fall_back_sequential():
    """nfolds >= 2 is batch-ineligible; the grid walk falls back and
    still delivers CV'd models."""
    fr = _class_frame(n=200)
    b0 = telemetry.REGISTRY.value("batched_train_batches_total", algo="gbm")
    g = GridSearch(GBMEstimator, {"learn_rate": [0.1, 0.2]}, ntrees=5,
                   max_depth=3, seed=7, nfolds=2).train(fr, y="y")
    assert len(g.models) == 2
    assert all(m.cross_validation_metrics is not None for m in g.models)
    assert telemetry.REGISTRY.value("batched_train_batches_total",
                                    algo="gbm") == b0


def test_unsupported_algo_falls_back_sequential():
    from h2o3_tpu.models.drf import DRFEstimator
    fr = _class_frame(n=200)
    g = GridSearch(DRFEstimator, {"ntrees": [4, 6]}, max_depth=3,
                   seed=7).train(fr, y="y")
    assert len(g.models) == 2


def test_batch_models_knob_off_disables(monkeypatch):
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    assert not model_batch.enabled()
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "auto")
    assert model_batch.enabled()


# ------------------------------------------------------ satellites


def test_frame_device_matrix_cached_and_invalidated():
    fr = _class_frame(n=64)
    m1 = fr.device_matrix(["a", "b"])
    assert fr.device_matrix(["a", "b"]) is m1          # cache hit
    assert fr.device_matrix(["b", "a"]) is not m1      # order is identity
    assert fr.matrix(["a", "b"]) is m1                 # matrix() delegates
    from h2o3_tpu.frame.column import column_from_numpy
    from h2o3_tpu.parallel import mesh as mesh_mod
    col = column_from_numpy("z", np.zeros(64), fr.nrows_padded,
                            mesh_mod.row_sharding())
    fr.add_column(col)                                 # mutation invalidates
    assert fr.device_matrix(["a", "b"]) is not m1


def test_ordinal_predict_stays_on_device():
    """Ordinal GLM scoring computes the cumulative-logit pipeline on
    device with ONE host fetch; probabilities match the closed form."""
    r = np.random.RandomState(11)
    n = 3000
    x = r.randn(n)
    lat = 1.4 * x + r.logistic(size=n)
    y = np.where(lat < -0.8, "l0", np.where(lat < 0.9, "l1", "l2"))
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y}, categorical=["y"])
    m = GLMEstimator(family="ordinal", lambda_=0.0,
                     standardize=False).train(fr, y="y")
    from h2o3_tpu.parallel import mesh as mesh_mod
    f0 = mesh_mod.FETCH_CALLS
    raw = m._score_raw(fr)
    assert mesh_mod.FETCH_CALLS - f0 <= 1, \
        "ordinal predict must fetch ONCE (device-resident pipeline)"
    probs = np.stack([raw[f"p{k}"] for k in range(3)], axis=1)
    assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # closed-form check against the model's own coefficients
    import jax
    X1 = np.asarray(m._design(fr))[:n]
    eta = X1[:, :-1] @ np.asarray(m.coef[:-1])
    alphas = np.asarray(m.output["ordinal_alphas"])
    cum = 1.0 / (1.0 + np.exp(-(alphas[None, :] - eta[:, None])))
    cum = np.concatenate([np.zeros((n, 1)), cum, np.ones((n, 1))], axis=1)
    assert np.allclose(probs, np.diff(cum, axis=1), atol=1e-5)


def test_grid_models_total_counts_both_paths(monkeypatch):
    fr = _class_frame(n=200)
    c0 = telemetry.REGISTRY.value("grid_models_total", algo="glm")
    GridSearch(GLMEstimator, {"alpha": [0.1, 0.5]}, family="binomial",
               lambda_=1e-4).train(fr, y="y")
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    GridSearch(GLMEstimator, {"alpha": [0.1, 0.5]}, family="binomial",
               lambda_=1e-4).train(fr, y="y")
    assert telemetry.REGISTRY.value("grid_models_total",
                                    algo="glm") == c0 + 4
