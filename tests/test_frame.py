"""Frame/Column/rollups tests — mirrors h2o-core fvec unit tests
(h2o-core/src/test/java/water/fvec/FrameTest.java role)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.rollups import rollups


def test_from_numpy_types():
    fr = h2o3_tpu.Frame.from_numpy({
        "ints": np.array([1, 2, 3, 4]),
        "floats": np.array([1.5, 2.5, np.nan, 4.0]),
        "cats": np.array(["a", "b", "a", "c"], dtype=object),
    }, categorical=[])
    assert fr.shape == (4, 3)
    assert fr.col("ints").type == "numeric"
    assert fr.col("floats").type == "numeric"
    assert fr.col("cats").type == "categorical"
    assert fr.col("cats").domain == ["a", "b", "c"]


def test_na_handling():
    fr = h2o3_tpu.Frame.from_numpy({"x": np.array([1.0, np.nan, 3.0])})
    r = rollups(fr.col("x"))
    assert r["na_count"] == 1
    assert r["rows"] == 2
    assert r["mean"] == pytest.approx(2.0)


def test_rollups_match_numpy(rng):
    v = rng.randn(1000) * 3 + 1
    fr = h2o3_tpu.Frame.from_numpy({"x": v})
    r = rollups(fr.col("x"))
    assert r["mean"] == pytest.approx(v.mean(), rel=1e-4)
    assert r["sigma"] == pytest.approx(v.std(ddof=1), rel=1e-3)
    assert r["min"] == pytest.approx(v.min(), rel=1e-5)
    assert r["max"] == pytest.approx(v.max(), rel=1e-5)


def test_padding_is_masked():
    # 5 rows over an 8-device mesh forces padding; stats must ignore it
    fr = h2o3_tpu.Frame.from_numpy({"x": np.arange(5, dtype=float)})
    assert fr.nrows == 5
    assert fr.nrows_padded % 8 == 0
    r = rollups(fr.col("x"))
    assert r["rows"] == 5
    assert r["mean"] == pytest.approx(2.0)


def test_roundtrip_pandas():
    import pandas as pd
    df = pd.DataFrame({"a": [1.0, 2.0], "b": ["x", "y"]})
    fr = h2o3_tpu.Frame.from_pandas(df)
    back = fr.to_pandas()
    assert list(back["a"]) == [1.0, 2.0]
    assert list(back["b"]) == ["x", "y"]


def test_subset_and_summary(classif_frame):
    s = classif_frame.summary()
    assert s["y"]["cardinality"] == 2
    sub = classif_frame[["x0", "y"]]
    assert sub.ncols == 2


def test_stream_import_multi_file_headers(tmp_path):
    """stream_import_csv must skip repeated headers in files 2..N and
    handle mid-stream numeric→categorical promotion."""
    import numpy as np
    from h2o3_tpu.io.stream import stream_import_csv
    p1 = tmp_path / "a.csv"
    p2 = tmp_path / "b.csv"
    p1.write_text("x,g\n1,aa\n2,bb\n")
    p2.write_text("x,g\n3,aa\n4,cc\n")
    fr = stream_import_csv([str(p1), str(p2)])
    assert fr.nrows == 4
    assert fr.col("g").domain == ["aa", "bb", "cc"]
    assert np.allclose(np.sort(fr.col("x").to_numpy()), [1, 2, 3, 4])


def test_stream_promotion_mid_stream(tmp_path):
    import numpy as np
    from h2o3_tpu.io.stream import stream_import_csv
    p = tmp_path / "c.csv"
    # first window numeric, later rows strings — tiny chunk forces
    # multiple windows
    rows = ["v,x"] + [f"{i},{i}" for i in range(50)] + \
        [f"lvl{i},{i}" for i in range(50)]
    p.write_text("\n".join(rows) + "\n")
    fr = stream_import_csv(str(p), chunk_bytes=64)
    assert fr.nrows == 100
    c = fr.col("v")
    assert c.is_categorical
    assert "lvl1" in c.domain and "1" in c.domain
