"""GBM end-to-end tests — the pyunit_gbm* role
(h2o-py/tests/testdir_algos/gbm/)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.gbm import GBMEstimator
from tests.conftest import make_classification, make_regression


def test_gbm_binomial_learns(classif_frame):
    m = GBMEstimator(ntrees=20, max_depth=4, learn_rate=0.2, seed=42)
    model = m.train(classif_frame, y="y")
    tm = model.training_metrics
    assert tm["AUC"] > 0.80, tm.to_dict()
    assert tm["logloss"] < 0.60


def test_gbm_predictions_shape(classif_frame):
    m = GBMEstimator(ntrees=5, max_depth=3, seed=1)
    model = m.train(classif_frame, y="y")
    preds = model.predict(classif_frame)
    assert preds.names == ["predict", "p0", "p1"]
    assert preds.nrows == classif_frame.nrows
    p = preds.to_pandas()
    assert ((p["p0"] + p["p1"]).round(4) == 1.0).all()


def test_gbm_regression(regress_frame):
    m = GBMEstimator(ntrees=30, max_depth=5, learn_rate=0.2, seed=3)
    model = m.train(regress_frame, y="y")
    tm = model.training_metrics
    y = regress_frame.col("y").to_numpy()
    base_mse = float(np.var(y))
    assert tm["MSE"] < 0.3 * base_mse, (tm["MSE"], base_mse)


def test_gbm_multinomial():
    r = np.random.RandomState(7)
    n = 3000
    X = r.randn(n, 5)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(5)},
         "y": np.array(["a", "b", "c"], object)[y]},
        categorical=["y"])
    m = GBMEstimator(ntrees=10, max_depth=4, learn_rate=0.3, seed=5)
    model = m.train(fr, y="y")
    tm = model.training_metrics
    assert tm["logloss"] < 0.5
    preds = model.predict(fr)
    p = preds.to_pandas()
    acc = (p["predict"].to_numpy() == np.array(["a", "b", "c"], object)[y]).mean()
    assert acc > 0.85


def test_gbm_with_categorical_features():
    r = np.random.RandomState(11)
    n = 2000
    cat = r.randint(0, 4, n)
    x1 = r.randn(n)
    y = (cat >= 2).astype(int) ^ (x1 > 0).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"c": np.array(["p", "q", "r", "s"], object)[cat], "x1": x1,
         "y": np.array(["n", "y"], object)[y]},
        categorical=["y"])
    model = GBMEstimator(ntrees=20, max_depth=4, learn_rate=0.3, seed=2).train(fr, y="y")
    assert model.training_metrics["AUC"] > 0.9


def test_gbm_nas_in_features():
    r = np.random.RandomState(13)
    n = 2000
    x = r.randn(n)
    y = (x > 0).astype(int)
    x_na = x.copy()
    x_na[r.rand(n) < 0.3] = np.nan  # NAs uncorrelated with y
    fr = h2o3_tpu.Frame.from_numpy(
        {"x": x_na, "y": np.array(["n", "y"], object)[y]}, categorical=["y"])
    model = GBMEstimator(ntrees=10, max_depth=3, seed=2).train(fr, y="y")
    assert model.training_metrics["AUC"] > 0.8


def test_gbm_varimp(classif_frame):
    model = GBMEstimator(ntrees=10, max_depth=4, seed=9).train(classif_frame, y="y")
    vi = model.output["varimp"]
    assert len(vi) == 8
    names = [v[0] for v in vi]
    # informative features x0..x3 should dominate
    assert set(names[:3]).issubset({"x0", "x1", "x2", "x3"})


def test_gbm_validation_frame():
    X, y = make_classification(n=2000, seed=21)
    Xv, yv = make_classification(n=1000, seed=22)
    tr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[y]}, categorical=["y"])
    va = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": Xv[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[yv]}, categorical=["y"])
    model = GBMEstimator(ntrees=15, max_depth=4, seed=4).train(tr, y="y",
                                                               validation_frame=va)
    assert model.validation_metrics is not None
    assert model.validation_metrics["AUC"] > 0.75


def test_gbm_cv():
    X, y = make_classification(n=1500, seed=31)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[y]}, categorical=["y"])
    model = GBMEstimator(ntrees=10, max_depth=3, nfolds=3, seed=6).train(fr, y="y")
    assert model.cross_validation_metrics is not None
    assert model.cross_validation_metrics["AUC"] > 0.7


def test_gbm_scoring_adapts_test_domains():
    """Unseen/reordered test-time categorical levels must map into the
    training domain (adaptTestForTrain, hex/Model.java:1850)."""
    r = np.random.RandomState(17)
    n = 2000
    lv = np.array(["a", "b", "c"], object)
    cat = r.randint(0, 3, n)
    y = (cat == 2).astype(int)
    tr = h2o3_tpu.Frame.from_numpy(
        {"c": lv[cat], "y": np.array(["n", "y"], object)[y]}, categorical=["y"])
    model = GBMEstimator(ntrees=5, max_depth=2, min_rows=5.0, seed=3).train(tr, y="y")
    # test frame whose domain is a reordered superset: codes differ from train
    te_cat = np.array(["zz_new", "c", "a", "c"], object)
    te = h2o3_tpu.Frame.from_numpy({"c": te_cat})
    p = model.predict(te).to_pandas()
    # rows with level "c" must score high, "a" low, unseen level ~ NA path
    assert p["p1"][1] > 0.55 and p["p1"][3] > 0.55
    assert p["p1"][2] < 0.35
    assert p["p1"][1] == p["p1"][3]


def test_gbm_missing_response_rows_excluded():
    r = np.random.RandomState(5)
    n = 1000
    x = r.randn(n)
    y = np.array(["n", "y"], object)[(x > 0).astype(int)]
    y[:100] = ""  # blank -> NA after interning? use explicit None-ish level
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y}, categorical=["y"])
    # force NA: blank string becomes its own level; instead use numeric resp
    yr = x * 2
    yr[:100] = np.nan
    fr2 = h2o3_tpu.Frame.from_numpy({"x": x, "yr": yr})
    model = GBMEstimator(ntrees=5, max_depth=3, seed=1).train(fr2, y="yr")
    assert model.training_metrics["nobs"] == 900


def test_gbm_early_stopping():
    X, y = make_classification(n=2000, seed=41)
    Xv, yv = make_classification(n=1000, seed=42)
    tr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[y]}, categorical=["y"])
    va = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": Xv[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[yv]}, categorical=["y"])
    model = GBMEstimator(ntrees=200, max_depth=3, learn_rate=0.5,
                         stopping_rounds=2, stopping_tolerance=0.01,
                         score_tree_interval=5, seed=8).train(
        tr, y="y", validation_frame=va)
    ntrees_built = model.forest.feat.shape[0]
    assert ntrees_built < 200, "early stopping never fired"
    assert len(model.output["scoring_history"]) >= 3


def test_gbm_fold_assignment_param_accepted():
    X, y = make_classification(n=800, seed=51)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(8)},
         "y": np.array(["a", "b"], object)[y]}, categorical=["y"])
    model = GBMEstimator(ntrees=5, max_depth=3, nfolds=3, seed=6,
                         fold_assignment="random").train(fr, y="y")
    assert model.cross_validation_metrics is not None
