"""Persist drivers, binary Frame/Model export, checkpoint restart,
grid fault-tolerance recovery (hex/faulttolerance analogue)."""

import os

import numpy as np
import pytest

import h2o3_tpu
from tests.conftest import make_classification


def _frame(n=1500, seed=0):
    X, y = make_classification(n=n, f=5, seed=seed)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["g"] = np.array(["u", "v", "w"], object)[
        np.random.RandomState(seed).randint(0, 3, n)]
    cols["y"] = np.array(["no", "yes"], object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["g", "y"])


def test_frame_save_load_roundtrip(tmp_path):
    fr = _frame()
    uri = str(tmp_path / "fr.h2o3")
    h2o3_tpu.save_frame(fr, uri)
    fr2 = h2o3_tpu.load_frame(uri)
    assert fr2.shape == fr.shape
    assert fr2.names == fr.names
    assert fr2.col("g").domain == fr.col("g").domain
    np.testing.assert_allclose(fr2.col("x0").to_numpy(),
                               fr.col("x0").to_numpy(), rtol=1e-6)
    np.testing.assert_array_equal(fr2.col("g").to_numpy(),
                                  fr.col("g").to_numpy())


def test_frame_save_load_with_nas(tmp_path):
    x = np.array([1.0, np.nan, 3.0, np.nan])
    fr = h2o3_tpu.Frame.from_numpy({"x": x})
    uri = str(tmp_path / "na.h2o3")
    h2o3_tpu.save_frame(fr, uri)
    out = h2o3_tpu.load_frame(uri).col("x").to_numpy()
    np.testing.assert_array_equal(np.isnan(out), np.isnan(x))


def test_model_save_load_scores_identically(tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame()
    m = GBMEstimator(ntrees=6, max_depth=3, seed=5).train(fr, y="y")
    uri = str(tmp_path / "m.bin")
    h2o3_tpu.save_model(m, uri)
    m2 = h2o3_tpu.load_model(uri)
    a = m.predict(fr).col("p1").to_numpy()
    b = m2.predict(fr).col("p1").to_numpy()
    np.testing.assert_allclose(a, b, atol=1e-6)
    assert m2.training_metrics["AUC"] == m.training_metrics["AUC"]


def test_hex_ice_driver(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_ICE_DIR", str(tmp_path / "ice"))
    from h2o3_tpu.io.persist import PersistManager
    pm = PersistManager()
    pm.write("hex://spill/blob.bin", b"cold value")
    assert pm.read("hex://spill/blob.bin") == b"cold value"
    assert pm.exists("hex://spill/blob.bin")
    pm.delete("hex://spill/blob.bin")
    assert not pm.exists("hex://spill/blob.bin")


def test_unknown_scheme_raises():
    with pytest.raises(IOError, match="no persist driver"):
        h2o3_tpu.persist_manager.read("ftp://bucket/key")


def test_gbm_checkpoint_restart_matches_full_run():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame()
    # 10-tree run in one shot vs 4 + checkpoint-restart to 10.
    full = GBMEstimator(ntrees=10, max_depth=3, seed=5,
                        sample_rate=1.0).train(fr, y="y")
    part = GBMEstimator(ntrees=4, max_depth=3, seed=5,
                        sample_rate=1.0).train(fr, y="y")
    resumed = GBMEstimator(ntrees=10, max_depth=3, seed=5, sample_rate=1.0,
                           checkpoint=part.key).train(fr, y="y")
    assert resumed.forest.feat.shape[0] == 10
    # resumed model must beat the 4-tree prefix on training deviance
    assert (resumed.training_metrics["logloss"]
            < part.training_metrics["logloss"] + 1e-9)
    # and land in the same quality regime as the one-shot run
    assert abs(resumed.training_metrics["AUC"]
               - full.training_metrics["AUC"]) < 0.05


def test_gbm_checkpoint_validations():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame()
    part = GBMEstimator(ntrees=4, max_depth=3, seed=5).train(fr, y="y")
    with pytest.raises(ValueError, match="must exceed"):
        GBMEstimator(ntrees=4, checkpoint=part.key, max_depth=3).train(
            fr, y="y")
    with pytest.raises(ValueError, match="max_depth"):
        GBMEstimator(ntrees=8, checkpoint=part.key, max_depth=5).train(
            fr, y="y")


def test_dl_checkpoint_restart():
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    fr = _frame()
    part = DeepLearningEstimator(hidden=[8], epochs=1, seed=3).train(
        fr, y="y")
    # H2O semantics: epochs names the new TOTAL and must exceed the
    # donor's; training CONTINUES (optimizer state + step count restored)
    resumed = DeepLearningEstimator(hidden=[8], epochs=2, seed=3,
                                    checkpoint=part.key).train(fr, y="y")
    assert resumed.training_metrics["logloss"] <= \
        part.training_metrics["logloss"] * 1.2
    assert resumed._steps_trained > part._steps_trained
    with pytest.raises(ValueError, match="hidden"):
        DeepLearningEstimator(hidden=[16], epochs=2,
                              checkpoint=part.key).train(fr, y="y")
    with pytest.raises(ValueError, match="epochs"):
        DeepLearningEstimator(hidden=[8], epochs=1, seed=3,
                              checkpoint=part.key).train(fr, y="y")


def test_grid_recovery_resume(tmp_path):
    from h2o3_tpu.ml.grid import GridSearch, resume_grid
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame()
    d = str(tmp_path / "rec")
    os.makedirs(d)
    # simulate a crash after 2 of 4 combos: run a half grid with
    # recovery on, then widen the recorded hyper space to the full grid
    # (as if the walk died mid-way through it)
    gs = GridSearch(GBMEstimator, {"max_depth": [2, 3],
                                   "learn_rate": [0.1]},
                    recovery_dir=d, ntrees=3, seed=7)
    gs.train(fr, y="y")
    import json
    sp = os.path.join(d, "grid_state.json")
    state = json.loads(open(sp).read())
    assert len(state["done"]) == 2
    state["hyper_params"] = {"max_depth": [2, 3], "learn_rate": [0.1, 0.3]}
    open(sp, "w").write(json.dumps(state))
    # resume on a "fresh cluster": finishes the remaining combos
    grid = resume_grid(d, fr)
    assert len(grid.models) == 4
    done_params = [m.output["grid_params"] for m in grid.models]
    assert len({frozenset(p.items()) for p in done_params}) == 4
    state = json.loads(open(os.path.join(d, "grid_state.json")).read())
    assert len(state["done"]) == 4


def test_arrow_fs_driver_roundtrip(tmp_path):
    """Exercise the cloud-driver code path (h2o-persist-s3/gcs/hdfs role)
    against a local pyarrow filesystem — same driver logic, no egress."""
    from pyarrow import fs as pafs
    from h2o3_tpu.io.persist import _ArrowFsDriver, persist_manager
    d = _ArrowFsDriver("s3")
    d._fs = pafs.LocalFileSystem()          # inject: code path identical
    uri = f"s3://{tmp_path}/obj.bin"
    assert not d.exists(uri)
    d.write(uri, b"payload")
    assert d.exists(uri)
    assert d.read(uri) == b"payload"
    assert any(p.endswith("obj.bin") for p in d.list(f"s3://{tmp_path}"))
    d.delete(uri)
    assert not d.exists(uri)
    # registry resolves cloud schemes to the arrow driver
    assert type(persist_manager.driver_for("gs://bucket/x")).__name__ == \
        "_ArrowFsDriver"
