"""Custom distributions / metrics (water/udf CFunc role).

A custom distribution with gaussian semantics must reproduce the
built-in gaussian bit-for-bit (same gradients compile into the same
boosting program); an asymmetric custom loss must shift predictions the
way its gradient dictates; uploaded custom metrics resolve from
"python:key" references like the reference's CFuncRef.
"""

import jax.numpy as jnp
import numpy as np

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBMEstimator


class GaussianTwin:
    def link(self):
        return "identity"

    def gradient(self, y, f):
        return f - y

    def hessian(self, y, f):
        return jnp.ones_like(f)

    def deviance(self, y, f):
        return (y - f) ** 2

    def init(self, m):
        return m


class OverpredictPenalty:
    """Asymmetric: overprediction costs 9x underprediction → the model
    should predict LOW (near the 10th percentile)."""

    def link(self):
        return "identity"

    def gradient(self, y, f):
        return jnp.where(f > y, 9.0, -1.0)


def _fr(n=3000, seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(n)
    return Frame.from_numpy({"x": x, "y": 3.0 * x + r.randn(n)})


def test_custom_gaussian_matches_builtin():
    fr = _fr()
    ref = h2o3_tpu.upload_custom_distribution(GaussianTwin)
    m1 = GBMEstimator(ntrees=5, max_depth=3, seed=7).train(
        fr, x=["x"], y="y")
    m2 = GBMEstimator(ntrees=5, max_depth=3, seed=7,
                      distribution="custom",
                      custom_distribution_func=ref).train(
        fr, x=["x"], y="y")
    p1 = m1.predict(fr).col("predict").to_numpy()
    p2 = m2.predict(fr).col("predict").to_numpy()
    assert np.abs(p1 - p2).max() < 1e-6


def test_custom_asymmetric_loss_shifts_predictions():
    fr = _fr(seed=3)
    ref = h2o3_tpu.upload_custom_distribution(OverpredictPenalty())
    m = GBMEstimator(ntrees=40, max_depth=3, learn_rate=0.3,
                     distribution="custom",
                     custom_distribution_func=ref).train(
        fr, x=["x"], y="y")
    resid = fr.col("y").to_numpy() - m.predict(fr).col("predict").to_numpy()
    # gradient balances at P(f>y)=0.1 → ~90% of residuals positive
    assert (resid > 0).mean() > 0.75, (resid > 0).mean()


def test_custom_metric_ref_resolution():
    fr = _fr(seed=5)
    ref = h2o3_tpu.upload_custom_metric(
        lambda y, preds, w: float(np.mean(np.abs(y - preds["predict"]))))
    m = GBMEstimator(ntrees=3, max_depth=3).train(
        fr, x=["x"], y="y", custom_metric_func=ref)
    assert m.output["custom_metric"] > 0
    assert m.training_metrics["custom"] == m.output["custom_metric"]


def test_custom_distribution_validation():
    import pytest
    with pytest.raises(ValueError):
        h2o3_tpu.upload_custom_distribution(object())
    with pytest.raises(ValueError):
        GBMEstimator(distribution="custom").train(
            _fr(), x=["x"], y="y")
