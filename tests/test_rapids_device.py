"""Device-resident rapids elementwise/reducer paths (VERDICT r4 #9).

Reference: every rapids prim is an MRTask over chunks
(water/rapids/ast/prims/mungers/, AstGroup.java pattern) — nothing
materializes on the driver. Here: frames >= _DEV_MIN_ROWS run
elementwise prims / sum-min-max-mean / cat string-ops on the device
mesh; below the threshold the exact host-float64 path keeps the small
reference pyunits bit-stable.

Two contracts:
  1. parity — the device path reproduces the host path (f32 tolerance);
  2. scale — at 10M rows none of these prims fetches a column to the
     controller (mesh.FETCH_CALLS stays flat; scalar syncs are allowed).
"""

import numpy as np
import pytest

import h2o3_tpu
import h2o3_tpu.rapids as R
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.rapids import Session, rapids


def _mk(sess, n, key, seed=1):
    r = np.random.RandomState(seed)
    a = r.randn(n) * 4.0
    a[r.rand(n) < 0.05] = np.nan
    b = r.rand(n) * 5.0 + 0.5
    c = r.uniform(0.97, 1.03, n)          # cumprod-safe magnitudes
    g = np.array(["lvl%02d" % i for i in r.randint(0, 12, n)], object)
    fr = h2o3_tpu.Frame.from_numpy({"a": a, "b": b, "c": c, "g": g},
                                   categorical=["g"], key=key)
    sess.assign(key, fr)
    return fr


BINOPS = ["+", "-", "*", "/", "^", "<", "<=", ">", ">=", "==", "!=",
          "&", "|", "intDiv"]
UNOPS_A = ["abs", "floor", "ceiling", "trunc", "sign", "not", "sin",
           "cos", "tanh"]
UNOPS_B = ["exp", "log", "sqrt", "log1p"]     # positive domain
CUMOPS = ["cumsum", "cummax", "cummin"]


def _exprs(key):
    es = [f'({op} (cols_py {key} ["a"]) (cols_py {key} ["b"]))'
          for op in BINOPS]
    es += [f'({op} (cols_py {key} ["a"]) 2.5)' for op in ("+", "*", "<")]
    es += [f'({op} (cols_py {key} ["a"]))' for op in UNOPS_A]
    es += [f'({op} (cols_py {key} ["b"]))' for op in UNOPS_B]
    es += [f'({op} (cols_py {key} ["c"]) 0)' for op in CUMOPS]
    es += ['(cumprod (cols_py %s ["c"]) 0)' % key,
           f'(is.na (cols_py {key} ["a"]))',
           f'(ifelse (> (cols_py {key} ["a"]) 0) '
           f'(cols_py {key} ["b"]) (cols_py {key} ["c"]))']
    return es


REDUCES = ['(sum (cols_py KEY ["b"]))', '(mean (cols_py KEY ["a"]) 1)',
           '(min (cols_py KEY ["b"]))', '(max (cols_py KEY ["a"]) 1)']


@pytest.fixture()
def small(monkeypatch):
    sess = Session()
    _mk(sess, 4096, "sd")
    return sess


@pytest.mark.parametrize("expr", _exprs("sd"))
def test_device_host_parity(small, expr, monkeypatch):
    host = rapids(expr, small)
    monkeypatch.setattr(R, "_DEV_MIN_ROWS", 1)
    dev = rapids(expr, small)
    assert isinstance(dev, type(host))
    hv = {n: host.col(n).to_numpy() for n in host.names}
    dvv = {n: dev.col(n).to_numpy() for n in dev.names}
    assert list(hv) == list(dvv)
    loose = any(k in expr for k in ("cumsum", "cumprod"))
    for n in hv:
        np.testing.assert_allclose(
            dvv[n], hv[n], rtol=2e-3 if loose else 2e-5,
            atol=2e-3 if loose else 1e-5, equal_nan=True, err_msg=expr)


@pytest.mark.parametrize("expr", REDUCES)
def test_reduce_parity(small, expr, monkeypatch):
    e = expr.replace("KEY", "sd")
    host = rapids(e, small)
    monkeypatch.setattr(R, "_DEV_MIN_ROWS", 1)
    dev = rapids(e, small)
    if np.isnan(host):
        assert np.isnan(dev)
    else:
        assert abs(dev - host) <= 2e-4 * max(1.0, abs(host)), e


def test_strop_cat_parity(small, monkeypatch):
    e = '(toupper (cols_py sd ["g"]))'
    host = rapids(e, small)
    monkeypatch.setattr(R, "_DEV_MIN_ROWS", 1)
    dev = rapids(e, small)
    assert dev.col(dev.names[0]).domain == host.col(host.names[0]).domain
    np.testing.assert_array_equal(dev.col(dev.names[0]).to_numpy(),
                                  host.col(host.names[0]).to_numpy())


# one expr per prim family — kept in the shared constant so the
# subprocess script below and any future family additions stay in sync
SCALE_EXPRS = ['(+ (cols_py big ["a"]) (cols_py big ["b"]))',
               '(< (cols_py big ["a"]) 0.5)',
               '(& (cols_py big ["a"]) (cols_py big ["b"]))',
               '(exp (cols_py big ["b"]))',
               '(sign (cols_py big ["a"]))',
               '(cumsum (cols_py big ["c"]) 0)',
               '(is.na (cols_py big ["a"]))',
               '(ifelse (> (cols_py big ["a"]) 0) '
               '(cols_py big ["b"]) (cols_py big ["c"]))',
               '(sum (cols_py big ["b"]))',
               '(mean (cols_py big ["a"]) 1)',
               '(toupper (cols_py big ["g"]))']

_SCALE_SCRIPT = r"""
import numpy as np
import h2o3_tpu
import h2o3_tpu.rapids as R
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.rapids import Session, rapids

n = 10_000_000
r = np.random.RandomState(1)
a = r.randn(n) * 4.0; a[r.rand(n) < 0.05] = np.nan
b = r.rand(n) * 5.0 + 0.5
c = r.uniform(0.97, 1.03, n)
g = np.array(["lvl%02d" % i for i in r.randint(0, 12, n)], object)
sess = Session()
fr = h2o3_tpu.Frame.from_numpy({"a": a, "b": b, "c": c, "g": g},
                               categorical=["g"], key="big")
sess.assign("big", fr)
assert fr.nrows >= R._DEV_MIN_ROWS
rapids('(+ (cols_py big ["a"]) 1)', sess)      # warm lazy op tables
base = mesh_mod.FETCH_CALLS
base_dev = R.DEV_OPS
exprs = __SCALE_EXPRS__
outs = [rapids(e, sess) for e in exprs]
for o in outs:
    if isinstance(o, h2o3_tpu.Frame):
        o.col(o.names[0]).data.block_until_ready()
assert R.DEV_OPS - base_dev >= len(exprs), \
    f"only {R.DEV_OPS - base_dev}/{len(exprs)} prims ran on device"
assert mesh_mod.FETCH_CALLS - base <= 2, \
    f"{mesh_mod.FETCH_CALLS - base} controller fetches at 10M rows"
print("SCALE-OK")
"""


def test_scale_no_controller_materialization():
    """10M rows: elementwise + string-cat + reducers never fetch a
    column to the controller (VERDICT r4 #9 'Done' criterion).

    Runs in a single-device subprocess: the property (DEV_OPS up,
    FETCH_CALLS flat) is mesh-size-independent, and 10M-row 8-way-
    sharded programs on this 1-core CI box serialize their collectives
    into minutes of wallclock (the sharded code path itself is covered
    by the 4096-row parity tests above and dryrun_multichip)."""
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    script = _SCALE_SCRIPT.replace("__SCALE_EXPRS__", repr(SCALE_EXPRS))
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, timeout=540,
                       env=env, cwd=os.path.dirname(
                           os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0 and "SCALE-OK" in r.stdout, \
        (r.stdout + r.stderr)[-2000:]
