"""Row-weight ≡ row-multiplicity invariants for tree training.

Pins the backend-independent contract of the reference's
pyunit_weights_gbm (h2o-py/tests/testdir_algos/gbm/pyunit_weights_gbm.py):
  - uniform weight k + min_rows*k  ≡  no weights
  - weight 0                        ≡  row removed
  - weight 2                        ≡  row duplicated
for GBM and DRF across regression / binomial / multinomial. DRF runs with
sample_rate=1 and mtries=#features: with row sampling on, the PRNG keep
sequence depends on frame length, so the invariant is only exact when
per-row randomness is off (true in the reference too).
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.drf import DRFEstimator
from h2o3_tpu.models.gbm import GBMEstimator


def _cars(n=406, seed=42):
    r = np.random.RandomState(seed)
    cyl = r.choice([3, 4, 5, 6, 8], n, p=[0.01, 0.5, 0.01, 0.21, 0.27])
    disp = (cyl * 40 + r.randn(n) * 25).round(1)
    power = (cyl * 20 + r.randn(n) * 15).round(0)
    weight = (cyl * 500 + r.randn(n) * 300).round(0)
    accel = (25 - cyl + r.randn(n) * 2).round(1)
    year = r.randint(70, 83, n).astype(float)
    econ = (50 - 3.5 * cyl + (year - 70) * 0.5 + r.randn(n) * 3).round(1)
    return {"displacement": disp, "power": power, "weight": weight,
            "acceleration": accel, "year": year, "economy": econ,
            "economy_20mpg": (econ >= 20).astype(float),
            "cylinders": cyl.astype(float)}


X = ["displacement", "power", "weight", "acceleration", "year"]


def _frame(cols, keys, factors=(), extra=None):
    d = {k: cols[k] for k in keys}
    if extra is not None:
        d.update(extra)
    return Frame.from_numpy(d, categorical=list(factors))


def _train(algo, fr, y, dist, min_rows, wcol=None):
    kw = dict(ntrees=5, seed=20, max_depth=4, min_rows=min_rows)
    if wcol:
        kw["weights_column"] = wcol
    if algo is GBMEstimator:
        kw["distribution"] = dist
    else:
        kw.update(sample_rate=1.0, mtries=len(X))
    est = algo(**kw)
    return est.train(x=X, y=y, training_frame=fr)


def _metric(model, y):
    m = model.training_metrics.to_dict()
    return m.get("AUC", m["MSE"])


def _assert_same_model(m1, m2, probe):
    """Identical forests ⇒ identical predictions on any probe frame —
    the strongest form of the invariant (OOB/threshold conventions can
    zero out scalar training metrics, e.g. DRF with sample_rate=1)."""
    p1 = m1.predict(probe)
    p2 = m2.predict(probe)
    name = "predict" if "p1" not in p2.names else "p1"
    a = p1.col(name).to_numpy()
    b = p2.col(name).to_numpy()
    scale = max(float(np.abs(a).max()), 1e-6)
    assert float(np.abs(a - b).max()) < 1e-4 * scale, (a[:5], b[:5])


CASES = [(GBMEstimator, "economy", "gaussian", ()),
         (GBMEstimator, "economy_20mpg", "bernoulli", ("economy_20mpg",)),
         (GBMEstimator, "cylinders", "multinomial", ("cylinders",)),
         (DRFEstimator, "economy", "gaussian", ()),
         (DRFEstimator, "economy_20mpg", "auto", ("economy_20mpg",))]


@pytest.mark.parametrize("algo,y,dist,factors", CASES)
def test_uniform_weights(algo, y, dist, factors):
    cols = _cars()
    f1 = _frame(cols, X + [y], factors)
    f2 = _frame(cols, X + [y], factors,
                {"w": np.full(len(cols[y]), 3.0)})
    m1 = _train(algo, f1, y, dist, 20)
    m2 = _train(algo, f2, y, dist, 60, wcol="w")
    _assert_same_model(m1, m2, f1)
    if algo is GBMEstimator:
        a, b = _metric(m1, y), _metric(m2, y)
        assert abs(a - b) < 1e-4 * max(abs(a), 1e-6), (a, b)


@pytest.mark.parametrize("algo,y,dist,factors", CASES)
def test_zero_weights_are_removed_rows(algo, y, dist, factors):
    cols = _cars()
    keep = np.random.RandomState(7).randint(0, 2, len(cols[y])) == 1
    f1 = _frame({k: v[keep] for k, v in cols.items()}, X + [y], factors)
    f2 = _frame(cols, X + [y], factors, {"w": keep.astype(float)})
    m1 = _train(algo, f1, y, dist, 20)
    m2 = _train(algo, f2, y, dist, 20, wcol="w")
    _assert_same_model(m1, m2, f1)
    if algo is GBMEstimator:
        a, b = _metric(m1, y), _metric(m2, y)
        assert abs(a - b) < 1e-4 * max(abs(a), 1e-6), (a, b)


@pytest.mark.parametrize("algo,y,dist,factors", CASES[:3])
def test_doubled_weights_are_duplicated_rows(algo, y, dist, factors):
    cols = _cars()
    w2 = np.random.RandomState(3).randint(1, 3, len(cols[y])).astype(float)
    dup = np.repeat(np.arange(len(cols[y])), w2.astype(int))
    f1 = _frame({k: v[dup] for k, v in cols.items()}, X + [y], factors)
    f2 = _frame(cols, X + [y], factors, {"w": w2})
    m1 = _train(algo, f1, y, dist, 20)
    m2 = _train(algo, f2, y, dist, 20, wcol="w")
    _assert_same_model(m1, m2, f1)
    a, b = _metric(m1, y), _metric(m2, y)
    assert abs(a - b) < 1e-4 * max(abs(a), 1e-6), (a, b)
