"""Worker for the multi-process cloud test (the reference's
multi-JVM-on-localhost tier, multiNodeUtils.sh:22-27 / @CloudSize(n)).

Each process runs this script with the SAME deterministic data; the
jax.distributed coordinator forms the cloud; training runs SPMD over the
cross-process mesh. Process 0 writes metrics to `outfile` for the parent
test to compare with the single-process run.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# worker processes compile the same kernel shapes — share executables
# through jax's persistent cache (identical binaries; numerics unchanged)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, outfile = sys.argv[1:5]

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
# backend="cpu": the axon TPU plugin may shadow JAX_PLATFORMS; the
# multi-process cloud must form over the per-process CPU devices
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=int(nproc), process_id=int(pid))

import numpy as np                            # noqa: E402


def build_data():
    r = np.random.RandomState(5)
    n = 4000
    a = r.randn(n)
    b = r.randn(n)
    g = r.choice(["u", "v", "w"], n)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(n) * 0.3
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g"])


fr = build_data()

from h2o3_tpu.models.gbm import GBMEstimator     # noqa: E402
from h2o3_tpu.models.glm import GLMEstimator     # noqa: E402

gbm = GBMEstimator(ntrees=10, max_depth=4, seed=3).train(fr, y="y")
glm = GLMEstimator(family="gaussian", lambda_=0.0).train(fr, y="y")

gbm_pred = gbm.predict(fr).col("predict").to_numpy()

# peer health: the heartbeat monitor auto-starts for multi-process
# clouds; give it one interval to publish + read beats, then record
# what this process sees of its peers
import time                                   # noqa: E402
from h2o3_tpu.core import heartbeat           # noqa: E402
heartbeat.monitor.round()
time.sleep(0.1)
info = h2o3_tpu.cluster_info()
result = {
    "process_count": len({d.process_index
                          for d in jax.devices("cpu")}),
    "gbm_mse": float(gbm.training_metrics["MSE"]),
    "gbm_pred_head": [float(v) for v in gbm_pred[:16]],
    "glm_coefficients": {k: float(v) for k, v in glm.coefficients.items()},
    "cloud_healthy": info["cloud_healthy"],
    "heartbeat_running": info["heartbeat"]["running"],
    "peers_seen": sorted(int(p) for p in info["heartbeat"]["peers"]),
    "uptime_ms": info["cloud_uptime_ms"],
}

if int(pid) == 0:
    with open(outfile, "w") as f:
        json.dump(result, f)
print(f"WORKER-{pid}-DONE", flush=True)
# exercise the full teardown path on a REAL multi-process cloud:
# heartbeat stops, mesh resets, the distributed client disconnects
h2o3_tpu.shutdown()
