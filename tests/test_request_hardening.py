"""Request-path hardening tests (ISSUE 3) — admission control, request
deadlines, cooperative cancellation, body bounds, and malformed-request
errors, all driven over real HTTP against the in-process REST server.

Unlike tests/test_rest.py these do NOT opt out of the conftest DKV/Scope
leak check: every key created through the wire (jobs, models, frames put
by handler threads) is cleaned up explicitly, so the leak check guards
the new request paths too.

Everything here is CPU-only and fast; the sustained overload soak is
marked slow.
"""

import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.api import server as api_server
from h2o3_tpu.core import request_ctx
from h2o3_tpu.core.job import (CANCELLED, DONE, Job, JobCancelledException,
                               list_jobs)
from h2o3_tpu.core.kv import DKV

# a deliberately blocking endpoint for overload tests: handlers park on
# this event until the test releases them (registered into the global
# route table like any other endpoint; unmatched by real clients)
_RELEASE = threading.Event()


@api_server.route("GET", "/3/TestBlock")
def _test_block(params, body):
    _RELEASE.wait(timeout=20)
    return {"ok": True}


@pytest.fixture(autouse=True)
def _release_guard():
    """Overload tests clear _RELEASE themselves; always leave it set so
    a stray parked handler cannot outlive its test."""
    _RELEASE.set()
    yield
    _RELEASE.set()


@pytest.fixture(scope="module")
def gated_port(tmp_path_factory):
    """REST server with a tiny admission gate + 1 MB body cap so tier-1
    tests can saturate it with a handful of threads."""
    import os
    env = {"H2O3TPU_REST_MAX_INFLIGHT": "3",
           "H2O3TPU_REST_QUEUE_DEPTH": "2",
           "H2O3TPU_REST_QUEUE_WAIT_S": "0.5",
           "H2O3TPU_REST_MAX_BODY_MB": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        port = api_server.start_server(port=0, background=True)
        yield port
    finally:
        api_server.stop_server()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _req(port, method, path, headers=None, timeout=30, **params):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    if method == "POST":
        data = urllib.parse.urlencode(
            {k: (json.dumps(v) if isinstance(v, (list, dict)) else v)
             for k, v in params.items()}).encode()
    elif params:
        url += ("&" if "?" in url else "?") + urllib.parse.urlencode(params)
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/x-www-form-urlencoded")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, json.loads(body) if body else {}, dict(e.headers)


def _train_frame(key):
    r = np.random.RandomState(9)
    n = 3000
    X = r.randn(n, 4)
    yv = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    return h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["n", "p"], dtype=object)[yv]},
        categorical=["y"], key=key)


def _poll_job(port, key, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        st, j, _ = _req(port, "GET", f"/3/Jobs/{key}")
        assert st == 200, j
        jd = j["jobs"][0]
        if jd["status"] in ("DONE", "FAILED", "CANCELLED"):
            return jd
        time.sleep(0.1)
    raise TimeoutError(key)


# --------------------------------------------------------- admission gate


def test_overload_sheds_503_and_exempt_endpoints_survive(gated_port):
    """Acceptance: a ≥4× max_inflight burst gets clean 503s with
    Retry-After in H2OErrorV3 shape, while /3/Ping, /3/Metrics and
    /3/Jobs keep answering with bounded latency."""
    _RELEASE.clear()
    rej0 = telemetry.REGISTRY.value("rest_rejected_total",
                                    reason="saturated")
    pool = ThreadPoolExecutor(max_workers=12)
    try:
        futs = [pool.submit(_req, gated_port, "GET", "/3/TestBlock",
                            timeout=30) for _ in range(12)]
        time.sleep(0.5)           # burst fully arrived; gate saturated
        # exempt endpoints answer fast while the gate is saturated
        for path in ("/3/Ping", "/3/Metrics", "/3/Jobs"):
            t0 = time.time()
            st, _, _ = _req(gated_port, "GET", path, timeout=10)
            assert st == 200, path
            # "bounded" = answered promptly, never parked behind the
            # 10s queue wait or the 30s burst hold; 5s absorbs GIL
            # contention from the 12-thread burst on a busy CI host
            # (observed 3.7s for /3/Metrics mid-suite) without ever
            # accepting a queued response as a pass
            assert time.time() - t0 < 5.0, \
                f"{path} latency unbounded under overload"
        _RELEASE.set()
        results = [f.result(timeout=30) for f in futs]
    finally:
        _RELEASE.set()
        pool.shutdown(wait=True)
    codes = [st for st, _, _ in results]
    n200, n503 = codes.count(200), codes.count(503)
    assert n200 + n503 == 12, codes
    assert n200 >= 3, codes                  # the in-flight slots finished
    assert n503 >= 12 - 3 - 2, codes         # everything past the queue shed
    for st, body, hdrs in results:
        if st == 503:
            assert hdrs.get("Retry-After"), "503 must carry Retry-After"
            assert body["__meta"]["schema_name"] == "H2OErrorV3"
            assert body["http_status"] == 503
    assert telemetry.REGISTRY.value(
        "rest_rejected_total", reason="saturated") - rej0 >= 7
    # the gate drains: inflight gauge returns to zero
    t0 = time.time()
    while telemetry.REGISTRY.value("rest_inflight_requests") > 0:
        assert time.time() - t0 < 10, "inflight gauge never drained"
        time.sleep(0.05)


@pytest.mark.slow
def test_overload_soak_inflight_stays_bounded(gated_port):
    """Sustained saturation: the inflight gauge never exceeds the gate
    and ping latency stays bounded for the whole soak window."""
    _RELEASE.clear()
    pool = ThreadPoolExecutor(max_workers=24)
    try:
        futs = [pool.submit(_req, gated_port, "GET", "/3/TestBlock",
                            timeout=40) for _ in range(24)]
        t_end = time.time() + 8.0
        while time.time() < t_end:
            assert telemetry.REGISTRY.value("rest_inflight_requests") <= 3
            t0 = time.time()
            st, _, _ = _req(gated_port, "GET", "/3/Ping", timeout=10)
            assert st == 200
            assert time.time() - t0 < 2.0
            time.sleep(0.2)
        _RELEASE.set()
        for f in futs:
            f.result(timeout=40)
    finally:
        _RELEASE.set()
        pool.shutdown(wait=True)


# ------------------------------------------------------ request deadlines


def test_build_completes_inside_generous_deadline(gated_port):
    """A deadlined model build that finishes in time returns 200 with
    the job snapshot refreshed to DONE (no client re-poll needed)."""
    _train_frame("hardening_ok_train")
    st, j, _ = _req(gated_port, "POST", "/3/ModelBuilders/gbm",
                    **{"_timeout_ms": 120000,
                       "training_frame": "hardening_ok_train",
                       "response_column": "y", "ntrees": 5,
                       "max_depth": 5, "seed": 1,
                       "model_id": "hardening_ok_model"})
    try:
        assert st == 200, j
        assert j["job"]["status"] == "DONE", j["job"]
    finally:
        for k in (j.get("job", {}).get("key", {}).get("name"),
                  "hardening_ok_model"):
            if k:
                DKV.remove(k)


def test_deadline_expired_build_408_job_cancelled_no_leak(gated_port):
    """Acceptance: an expired model-build deadline answers 408, the job
    ends CANCELLED (not RUNNING), and every key the build created is
    released — only the job key remains, and the test removes it."""
    _train_frame("hardening_dl_train")
    before = set(DKV.keys())
    dl0 = telemetry.REGISTRY.value("request_deadline_exceeded_total")
    st, j, _ = _req(gated_port, "POST", "/3/ModelBuilders/gbm",
                    timeout=120,
                    **{"_timeout_ms": 300,
                       "training_frame": "hardening_dl_train",
                       "response_column": "y", "ntrees": 400,
                       "max_depth": 5, "seed": 1,
                       "model_id": "hardening_dl_model"})
    assert st == 408, j
    assert j["__meta"]["schema_name"] == "H2OErrorV3"
    jk = j["values"]["job"]
    try:
        # cooperative cancellation lands within one chunk boundary
        jd = _poll_job(gated_port, jk, timeout=90)
        assert jd["status"] == "CANCELLED", jd
        assert telemetry.REGISTRY.value(
            "request_deadline_exceeded_total") > dl0
        # no partial model, no stray keys: the cancelled job's Scope
        # swept everything it created; only its own job key remains
        assert DKV.get_raw("hardening_dl_model") is None
        leaked = set(DKV.keys()) - before - {jk}
        assert not leaked, f"cancelled build leaked keys: {sorted(leaked)}"
        running = [d for d in list_jobs() if d["status"] == "RUNNING"]
        assert not running, running
    finally:
        DKV.remove(jk)


def test_deadline_header_and_malformed_deadline(gated_port):
    st, j, _ = _req(gated_port, "GET", "/3/Cloud",
                    headers={"X-H2O-Deadline-Ms": "30000"})
    assert st == 200 and j["cloud_size"] == 8
    st, j, _ = _req(gated_port, "GET", "/3/Cloud",
                    **{"_timeout_ms": "soon"})
    assert st == 400
    assert j["__meta"]["schema_name"] == "H2OErrorV3"


def test_cancel_mid_gbm_stops_within_chunk_and_releases_keys(gated_port):
    """Satellite: POST /3/Jobs/{key}/cancel mid-fit → CANCELLED within
    one chunk boundary, Scope keys released (only the job key stays)."""
    _train_frame("hardening_cancel_train")
    before = set(DKV.keys())
    st, j, _ = _req(gated_port, "POST", "/3/ModelBuilders/gbm",
                    **{"training_frame": "hardening_cancel_train",
                       "response_column": "y", "ntrees": 400,
                       "max_depth": 5, "seed": 1,
                       "model_id": "hardening_cancel_model"})
    assert st == 200, j
    jk = j["job"]["key"]["name"]
    try:
        st, _, _ = _req(gated_port, "POST", f"/3/Jobs/{jk}/cancel")
        assert st == 200
        t0 = time.time()
        jd = _poll_job(gated_port, jk, timeout=90)
        assert jd["status"] == "CANCELLED", jd
        # the fit observed the cancel at a chunk boundary, not at the end
        assert jd["progress"] < 1.0, jd
        assert time.time() - t0 < 60
        assert DKV.get_raw("hardening_cancel_model") is None
        leaked = set(DKV.keys()) - before - {jk}
        assert not leaked, f"cancelled fit leaked keys: {sorted(leaked)}"
    finally:
        DKV.remove(jk)


# ------------------------------------------- cooperative cancel plumbing


def test_frame_reduce_observes_deadline():
    from h2o3_tpu.parallel.map_reduce import frame_reduce
    with request_ctx.deadline_scope(time.monotonic() - 0.001):
        with pytest.raises(request_ctx.DeadlineExceeded):
            frame_reduce(lambda a: a.sum(), np.arange(64.0))


def test_frame_map_observes_job_cancel():
    from h2o3_tpu.parallel.map_reduce import frame_map
    job = Job("cancel-point probe")
    job.cancel()
    with request_ctx.job_scope(job):
        with pytest.raises(JobCancelledException):
            frame_map(lambda a: a * 2, np.arange(64.0))


def test_job_captures_request_deadline_and_cancels():
    """Job.start re-installs the submission-time deadline on the worker
    thread; the progress-update checkpoint expires it → CANCELLED."""
    with request_ctx.deadline_scope(time.monotonic() + 0.05):
        j = Job("deadline capture probe")

    def work(job):
        t0 = time.time()
        while time.time() - t0 < 20:
            time.sleep(0.01)
            job.update(0.001)
        return "finished"

    j.start(work, background=True).join(30)
    assert j.status == CANCELLED
    assert j.result is None
    assert j.progress_msg == "deadline exceeded"


def test_cancelled_job_releases_scope_keys():
    """Keys a job creates are swept when it ends CANCELLED; a DONE job
    keeps them (water/Scope exit-on-abort role)."""
    started = threading.Event()

    def work(job):
        DKV.put("hardening_partial_key", {"partial": True})
        started.set()
        while True:
            time.sleep(0.01)
            job.update(0.0)

    j = Job("scope release probe")
    j.start(work, background=True)
    assert started.wait(20)
    assert DKV.get_raw("hardening_partial_key") is not None
    j.cancel()
    j.join(30)
    assert j.status == CANCELLED
    assert DKV.get_raw("hardening_partial_key") is None

    def work_done(job):
        DKV.put("hardening_kept_key", {"done": True})
        return "ok"

    j2 = Job("scope keep probe").start(work_done)
    assert j2.status == DONE
    assert DKV.get_raw("hardening_kept_key") is not None
    DKV.remove("hardening_kept_key")


def test_list_jobs_skips_dead_keys(monkeypatch):
    """Satellite: a job key removed between keys() and get() must be
    skipped, not AttributeError on None.to_dict()."""
    real_keys = DKV.keys

    def ghost_keys(prefix=""):
        return iter(list(real_keys(prefix)) + ["job_ghost_removed"])

    monkeypatch.setattr(DKV, "keys", ghost_keys)
    jobs = list_jobs()          # must not raise
    assert all(d["key"]["name"] != "job_ghost_removed" for d in jobs)


# ------------------------------------------------- malformed requests


def test_malformed_json_body_is_400(gated_port):
    req = urllib.request.Request(
        f"http://127.0.0.1:{gated_port}/3/LogAndEcho",
        data=b'{"message": oops', method="POST")
    req.add_header("Content-Type", "application/json")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    body = json.loads(ei.value.read())
    assert body["__meta"]["schema_name"] == "H2OErrorV3"
    assert "JSON" in body["msg"]


def test_malformed_content_length_is_400(gated_port):
    """A non-integer Content-Length used to raise before the dispatch
    try block and drop the connection; now it's a clean 400."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", gated_port, timeout=10)
    try:
        conn.putrequest("POST", "/3/LogAndEcho")
        conn.putheader("Content-Length", "banana")
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 400
        body = json.loads(resp.read())
        assert body["__meta"]["schema_name"] == "H2OErrorV3"
        assert "Content-Length" in body["msg"]
    finally:
        conn.close()


def test_body_over_cap_is_413(gated_port):
    rej0 = telemetry.REGISTRY.value("rest_rejected_total",
                                    reason="body_too_large")
    big = urllib.parse.urlencode(
        {"message": "x" * (2 << 20)}).encode()      # 2 MB > 1 MB cap
    st, j, _ = _req_raw_post(gated_port, "/3/LogAndEcho", big)
    assert st == 413, j
    assert j["__meta"]["schema_name"] == "H2OErrorV3"
    assert telemetry.REGISTRY.value(
        "rest_rejected_total", reason="body_too_large") > rej0


def _req_raw_post(port, path, data):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method="POST")
    req.add_header("Content-Type", "application/x-www-form-urlencoded")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, json.loads(body) if body else {}, dict(e.headers)


def test_postfile_streams_to_disk(gated_port):
    """/3/PostFile accepts a body LARGER than the buffered-body cap —
    it streams to disk in chunks instead of buffering."""
    import os
    payload = b"a,b\n" + b"1,2\n" * (600 << 10)     # ~2.4 MB > 1 MB cap
    st, j, _ = _req_raw_post(gated_port, "/3/PostFile", payload)
    assert st == 200, j
    assert j["total_bytes"] == len(payload)
    assert os.path.exists(j["destination_frame"])
    os.unlink(j["destination_frame"])


# --------------------------------------------------- client disconnects


def test_client_disconnect_counted_not_crashed(gated_port):
    """A client that hangs up mid-request is counted, and the handler
    thread survives to serve the next request."""
    _RELEASE.clear()
    c0 = telemetry.REGISTRY.value("rest_client_disconnects_total")
    s = socket.create_connection(("127.0.0.1", gated_port), timeout=10)
    try:
        # SO_LINGER(0): close sends RST so the parked handler's write
        # deterministically fails instead of landing in a dead buffer
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.sendall(b"GET /3/TestBlock HTTP/1.1\r\n"
                  b"Host: 127.0.0.1\r\n\r\n")
        time.sleep(0.3)          # handler is parked on _RELEASE
    finally:
        s.close()
    _RELEASE.set()
    t0 = time.time()
    while telemetry.REGISTRY.value("rest_client_disconnects_total") <= c0:
        assert time.time() - t0 < 15, "disconnect never counted"
        time.sleep(0.05)
    # the server is still healthy
    st, _, _ = _req(gated_port, "GET", "/3/Ping")
    assert st == 200
