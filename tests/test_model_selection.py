"""ANOVAGLM + ModelSelection tests (testdir_algos/anovaglm,
modelselection pyunit roles)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_selection import (ANOVAGLMEstimator,
                                             ModelSelectionEstimator)


@pytest.fixture(scope="module")
def lin_data():
    r = np.random.RandomState(9)
    n = 600
    X = r.randn(n, 5)
    # x0 strong, x1 moderate, x2 weak-through-interaction, x3/x4 noise
    y = 2.0 * X[:, 0] + 0.8 * X[:, 1] + 1.5 * X[:, 0] * X[:, 2] \
        + r.randn(n) * 0.5
    fr = Frame.from_numpy({f"x{i}": X[:, i] for i in range(5)} | {"y": y})
    return fr


def test_anovaglm_table(lin_data):
    m = ANOVAGLMEstimator(highest_interaction_term=2).train(
        lin_data, y="y", x=["x0", "x1", "x2"])
    tbl = {d["term"]: d for d in m.anova_table}
    assert tbl["x0"]["p_value"] < 1e-6
    assert tbl["x1"]["p_value"] < 1e-6
    assert tbl["x0:x2"]["p_value"] < 1e-6
    # pure-noise interaction should NOT be significant
    assert tbl["x1:x2"]["p_value"] > 0.01
    assert m.training_metrics["r2"] > 0.8


@pytest.mark.parametrize("mode", ["forward", "backward", "maxr"])
def test_model_selection_orders_predictors(lin_data, mode):
    m = ModelSelectionEstimator(mode=mode, max_predictor_number=3).train(
        lin_data, y="y", x=["x0", "x1", "x3", "x4"])
    res = m.result()
    sizes = [d["size"] for d in res]
    assert sizes == sorted(sizes)
    # size-1 best subset must be the strongest predictor x0
    one = [d for d in res if d["size"] == 1]
    if one:
        assert one[0]["predictors"] == ["x0"]
    # r2 must be monotone nondecreasing with size
    r2s = [d["r2"] for d in res]
    assert all(b >= a - 1e-6 for a, b in zip(r2s, r2s[1:]))
    two = [d for d in res if d["size"] == 2]
    if two:
        assert set(two[0]["predictors"]) == {"x0", "x1"}


def test_model_selection_allsubsets(lin_data):
    m = ModelSelectionEstimator(mode="allsubsets",
                                max_predictor_number=2).train(
        lin_data, y="y", x=["x0", "x1", "x3"])
    res = m.result()
    assert [d["size"] for d in res] == [1, 2]
    assert set(res[1]["predictors"]) == {"x0", "x1"}
    # coef accessor
    c = m.coef(2)
    assert set(c) >= {"x0", "x1", "Intercept"}
