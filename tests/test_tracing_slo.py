"""End-to-end distributed tracing + SLO burn-rate engine (ISSUE 16,
telemetry/trace_context.py + telemetry/slo.py).

Tiers:

- trace-context units: traceparent parse/format, contextvar install,
  span stamping (root spans parent under the installed context — THE
  cross-process stitch rule), detach, retroactive spans.
- histogram quantiles: bucket interpolation, merged grids, and the
  ``predict_seconds{phase}`` shared-bucket-grid regression.
- SLO engine: the multi-window burn-rate state machine on a private
  registry with a fake clock (the same surface bench.py's ``_stub_slo``
  leg drives), plus the gauge-rule and capsule surfaces.
- REST: ``X-H2O-Trace-Id`` echo/generation, ``traceparent`` ingress,
  JobV3 ``trace_id``, single-process ``GET /3/Trace?trace_id=``
  stitching, ``GET /3/Trace`` bit-compat, and ``GET /3/Alerts``.
- ``multiprocess``: a REST-initiated scheduled grid on a REAL
  2-process cloud yields ONE stitched trace with causally-parented
  spans from BOTH hosts under the client's trace id.
"""

import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import slo, spans, trace_context
from h2o3_tpu.telemetry.registry import (Histogram, MetricsRegistry,
                                         merged_quantile)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "sched_worker.py")
WORKER_TIMEOUT_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))


# ------------------------------------------------------- trace context


def test_traceparent_parse_roundtrip():
    tc = trace_context.TraceContext("ab" * 16, "sp-00000042",
                                    sampled=True)
    back = trace_context.parse_traceparent(tc.to_traceparent())
    assert back.trace_id == "ab" * 16
    assert back.parent_id == "sp-00000042"
    assert back.sampled


def test_traceparent_accepts_w3c_hex_parent():
    tc = trace_context.parse_traceparent(
        f"00-{'1f' * 16}-{'a' * 16}-00")
    assert tc.trace_id == "1f" * 16
    assert tc.parent_id == "a" * 16
    assert not tc.sampled


def test_traceparent_rejects_malformed():
    for bad in (None, "", "garbage", "00-short-x-01",
                f"00-{'0' * 32}-{'a' * 16}-01",      # all-zero trace id
                f"zz-{'ab' * 16}-{'a' * 16}-01"):
        assert trace_context.parse_traceparent(bad) is None


def test_traceparent_no_parent_placeholder():
    tc = trace_context.new_context()
    assert tc.parent_id is None
    back = trace_context.parse_traceparent(tc.to_traceparent())
    assert back.parent_id is None                    # 0*16 -> None


def test_child_reparents_same_trace():
    tc = trace_context.new_context()
    ch = tc.child("sp-00000007")
    assert ch.trace_id == tc.trace_id
    assert ch.parent_id == "sp-00000007"


def test_format_traceparent_none_without_context():
    assert trace_context.current() is None
    assert trace_context.format_traceparent() is None


def test_trace_scope_installs_and_restores():
    tc = trace_context.new_context()
    with trace_context.trace_scope(tc):
        assert trace_context.current() is tc
        assert trace_context.current_trace_id() == tc.trace_id
        with trace_context.trace_scope(None):         # explicit detach
            assert trace_context.current() is None
        assert trace_context.current() is tc
    assert trace_context.current() is None


# ------------------------------------------------------- span stamping


def test_spans_stamped_with_installed_trace():
    tc = trace_context.TraceContext("cd" * 16, "sp-99999999")
    with trace_context.trace_scope(tc):
        with telemetry.span("tst.root") as root:
            with telemetry.span("tst.child") as child:
                pass
    # root span: no in-process parent -> adopts the context's parent
    # (the cross-process stitch rule); child keeps its LOCAL parent
    assert root.trace_id == "cd" * 16
    assert root.parent_id == "sp-99999999"
    assert child.trace_id == "cd" * 16
    assert child.parent_id == root.id


def test_spans_unstamped_without_trace():
    with telemetry.span("tst.bare") as sp:
        pass
    assert sp.trace_id is None
    assert "trace_id" in sp.to_dict() and sp.to_dict()["trace_id"] is None


def test_detach_makes_next_span_a_root():
    tc = trace_context.TraceContext("ef" * 16, "sp-11111111")
    with telemetry.span("tst.outer") as outer:
        with trace_context.trace_scope(tc), spans.detach():
            with telemetry.span("tst.leased") as leased:
                pass
        with telemetry.span("tst.inner") as inner:
            pass
    # detached: parents under the trace context, not the local outer
    assert leased.parent_id == "sp-11111111"
    assert leased.trace_id == "ef" * 16
    # stack restored after the detach block
    assert inner.parent_id == outer.id


def test_record_finished_retroactive_span():
    t0 = time.time() - 0.5
    sp = spans.record_finished("tst.retro", t0, t0 + 0.25,
                               trace_id="12" * 16,
                               parent_id="sp-00000001", phase="queue")
    assert sp.trace_id == "12" * 16 and sp.parent_id == "sp-00000001"
    assert abs(sp.duration - 0.25) < 1e-6
    tail = telemetry.spans_snapshot(10)
    assert any(s["id"] == sp.id and s["meta"].get("phase") == "queue"
               for s in tail)


def test_job_captures_submitters_trace():
    from h2o3_tpu.core.job import Job
    tc = trace_context.TraceContext("34" * 16, None)
    seen = {}
    with trace_context.trace_scope(tc), telemetry.span("tst.ingress") \
            as ingress:
        job = Job("trace capture probe")

        def work(j):
            cur = trace_context.current()
            seen["trace_id"] = cur.trace_id if cur else None
            seen["parent_id"] = cur.parent_id if cur else None
            return 1

        job.start(work, background=True)
    job.join()
    assert job.status == "DONE"
    # the worker thread ran under the submitter's trace, re-parented
    # beneath the span that was active at Job() creation
    assert seen["trace_id"] == "34" * 16
    assert seen["parent_id"] == ingress.id
    assert job.trace_id == "34" * 16
    assert job.to_dict()["trace_id"] == "34" * 16


# -------------------------------------------------- histogram quantiles


def test_histogram_quantile_interpolates():
    reg = MetricsRegistry()
    h = reg.histogram("tst_q_seconds", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    # rank 2 of 4 falls at the top of the (1,2] bucket
    assert h.quantile(0.5) == pytest.approx(1.5, abs=0.51)
    assert h.quantile(0.0) is not None
    assert h.quantile(1.0) <= 4.0


def test_histogram_quantile_overflow_clamps_to_last_bound():
    reg = MetricsRegistry()
    h = reg.histogram("tst_q2_seconds", buckets=(0.1, 0.5))
    for _ in range(10):
        h.observe(99.0)                  # all in the +Inf overflow
    assert h.quantile(0.99) == 0.5


def test_histogram_quantile_empty_is_none():
    reg = MetricsRegistry()
    h = reg.histogram("tst_q3_seconds", buckets=(0.1, 0.5))
    assert h.quantile(0.5) is None
    assert merged_quantile([], 0.5) is None


def test_merged_quantile_requires_one_bucket_grid():
    reg = MetricsRegistry()
    a = reg.histogram("tst_m_seconds", buckets=(0.1, 0.5), leg="a")
    b = reg.histogram("tst_m_seconds", buckets=(0.1, 0.5, 1.0), leg="b")
    a.observe(0.05)
    b.observe(0.05)
    with pytest.raises(ValueError):
        merged_quantile([a, b], 0.99)
    c = reg.histogram("tst_m_seconds", buckets=(0.1, 0.5), leg="c")
    for _ in range(99):
        c.observe(0.05)
    assert merged_quantile([a, c], 0.5) <= 0.1


def test_predict_seconds_phases_share_one_bucket_grid():
    """Regression (ISSUE 16 satellite): every predict_seconds histogram
    in serving/engine.py must pass buckets=_LATENCY_BUCKETS — a phase
    on a different grid silently breaks the merged p99 the SLO rule
    reports."""
    src = open(os.path.join(
        REPO, "h2o3_tpu", "serving", "engine.py")).read()
    calls = re.findall(
        r'histogram\(\s*"predict_seconds",([^)]*)\)', src)
    assert len(calls) >= 3, "expected queue/device/scatter histograms"
    for args in calls:
        assert "buckets=_LATENCY_BUCKETS" in args.replace(" ", "") \
            .replace("\n", "") or "buckets=_LATENCY_BUCKETS" in args, \
            f"predict_seconds histogram without the shared grid: {args}"


def test_predict_seconds_live_grids_merge():
    """The live registry's predict_seconds histograms (whatever phases
    other tests have populated) must merge without a grid mismatch."""
    hists = [h for h in telemetry.REGISTRY.find("predict_seconds")
             if isinstance(h, Histogram)]
    merged_quantile(hists, 0.99)          # must not raise


# ------------------------------------------------------------ SLO engine


def _latency_engine(clock):
    reg = MetricsRegistry()
    h = reg.histogram("predict_seconds", buckets=(0.1, 0.5, 1.0),
                      phase="device")
    rule = slo.RatioRule("predict_p99_latency", objective=0.99,
                         counts_fn=slo._predict_latency_counts,
                         description="test rule")
    eng = slo.SLOEngine(registry=reg, rules=[rule],
                        now=lambda: clock[0])
    return reg, h, eng


def test_slo_burn_rate_alert_and_recovery():
    clock = [1000.0]
    reg, h, eng = _latency_engine(clock)

    def tick(dt=30.0):
        clock[0] += dt
        return eng.evaluate()

    for _ in range(50):
        h.observe(0.01)
    out = tick()
    assert out["rules"][0]["state"] == "healthy"
    assert out["alerts"] == []
    # fault-injected latency: slow predictions torch both windows
    for _ in range(200):
        h.observe(2.0)
    out = tick()
    assert out["rules"][0]["state"] == "alert"
    assert out["alerts"] and out["alerts"][0]["slo"] == \
        "predict_p99_latency"
    assert out["rules"][0]["burn_5m"] > 1.0
    assert eng.active_alerts()
    # burn-rate gauges published for the scrape
    g5 = reg.gauge("slo_burn_rate", slo="predict_p99_latency",
                   window="5m")
    assert g5.value > 1.0
    assert reg.gauge("slo_alert_active",
                     slo="predict_p99_latency").value == 1.0
    # recovery: healthy traffic displaces the burst beyond both windows
    states = []
    for _ in range(80):
        for _ in range(500):
            h.observe(0.01)
        out = tick(120.0)
        states.append(out["rules"][0]["state"])
        if out["rules"][0]["state"] == "healthy":
            break
    assert "recovery" in states, states   # long window lags the short
    assert states[-1] == "healthy"
    assert out["alerts"] == []
    assert eng.active_alerts() == []
    assert reg.gauge("slo_alert_active",
                     slo="predict_p99_latency").value == 0.0
    trans = sum(int(c.value) for c
                in reg.find("slo_alert_transitions_total"))
    assert trans >= 3                     # alert, recovery, healthy


def test_slo_short_blip_never_alerts():
    """A short burst that torches the 5m window but stays inside the
    1h error budget must visit burning and return to healthy without
    ever alerting — the long window is the confirmation gate."""
    clock = [1000.0]
    reg, h, eng = _latency_engine(clock)
    # an hour of healthy history, sampled every 60s
    for _ in range(60):
        for _ in range(20):
            h.observe(0.01)
        clock[0] += 60
        eng.evaluate()
    # blip: 5 bad — dominates the short window, < 1% of the hour
    for _ in range(5):
        h.observe(2.0)
    clock[0] += 60
    out = eng.evaluate()
    assert out["rules"][0]["state"] == "burning", out["rules"][0]
    assert out["rules"][0]["burn_5m"] > 1.0
    assert out["rules"][0]["burn_1h"] <= 1.0
    # healthy traffic resumes: the short window clears, never alerting
    states = []
    for _ in range(10):
        for _ in range(20):
            h.observe(0.01)
        clock[0] += 60
        states.append(eng.evaluate()["rules"][0]["state"])
    assert "alert" not in states, states
    assert states[-1] == "healthy"


def test_slo_gauge_rule_mfu_floor(monkeypatch):
    reg = MetricsRegistry()
    eng = slo.SLOEngine(
        registry=reg,
        rules=[slo.GaugeRule("fit_mfu_floor", check_fn=slo._mfu_check,
                             description="floor")])
    # floor disabled: vacuously healthy even with a terrible gauge
    monkeypatch.delenv("H2O3TPU_SLO_MFU_FLOOR", raising=False)
    reg.gauge("model_fit_mfu", algo="gbm").set(0.001)
    assert eng.evaluate()["rules"][0]["state"] == "healthy"
    # floor above the gauge: instant alert, instant clear
    monkeypatch.setenv("H2O3TPU_SLO_MFU_FLOOR", "0.5")
    out = eng.evaluate()
    assert out["rules"][0]["state"] == "alert"
    assert out["rules"][0]["worst_algo"] == "gbm"
    reg.gauge("model_fit_mfu", algo="gbm").set(0.9)
    assert eng.evaluate()["rules"][0]["state"] == "healthy"


def test_slo_default_rules_evaluate_on_live_registry():
    """The process-wide engine must evaluate the default rules on
    whatever the live registry holds — never raise, always report."""
    out = slo.evaluate()
    names = {r["slo"] for r in out["rules"]}
    assert names == {"predict_p99_latency", "rest_availability",
                     "heartbeat_health", "fit_mfu_floor",
                     "fleet_routing_availability", "fleet_replica_floor",
                     "data_durability_floor", "fit_step_regression"}
    assert out["windows_s"] == [300.0, 3600.0]
    for r in out["rules"]:
        assert r["state"] in slo.STATES


def test_capsule_stamps_active_slo_alerts(monkeypatch):
    """flight_recorder.finalize() snapshots slo.active_alerts() into
    the capsule (empty when nothing is firing)."""
    from h2o3_tpu.core.job import Job
    clock = [1000.0]
    reg, h, eng = _latency_engine(clock)
    eng.evaluate()                 # baseline sample before the burn
    for _ in range(100):
        h.observe(2.0)
    clock[0] += 30
    eng.evaluate()
    assert eng.active_alerts()
    monkeypatch.setattr(slo, "_ENGINE", eng)
    job = Job("slo capsule probe")
    job.start(lambda j: 1, background=True)
    job.join()
    from h2o3_tpu.telemetry.flight_recorder import get_capsule
    cap = get_capsule(job.key)
    assert cap is not None
    d = cap.to_dict()
    assert d["slo_alerts"] and d["slo_alerts"][0]["slo"] == \
        "predict_p99_latency"


# ------------------------------------------------------------- REST tier


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read(), dict(r.headers)


def _post(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=b"", method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read(), dict(r.headers)


@pytest.mark.allow_key_leak
def test_rest_generates_and_echoes_trace_id(port):
    st, _, hdrs = _get(port, "/3/About")
    assert st == 200
    tid = hdrs.get("X-H2O-Trace-Id")
    assert tid and re.fullmatch(r"[0-9a-f]{32}", tid)
    # a second request gets a DIFFERENT generated trace
    _, _, hdrs2 = _get(port, "/3/About")
    assert hdrs2.get("X-H2O-Trace-Id") != tid


@pytest.mark.allow_key_leak
def test_rest_accepts_traceparent_header(port):
    tid = "5a" * 16
    st, _, hdrs = _get(port, "/3/About",
                       headers={"traceparent":
                                f"00-{tid}-{'0' * 16}-01"})
    assert st == 200
    assert hdrs.get("X-H2O-Trace-Id") == tid
    # malformed traceparent: never an error, a fresh id is generated
    st, _, hdrs = _get(port, "/3/About",
                       headers={"traceparent": "not-a-traceparent"})
    assert st == 200
    got = hdrs.get("X-H2O-Trace-Id")
    assert got and got != tid


@pytest.mark.allow_key_leak
def test_rest_traced_job_and_stitched_trace(port):
    """A REST model build under a traceparent: JobV3 reports the trace
    id, and GET /3/Trace?trace_id= returns ONE causally-stitched trace
    whose spans all carry that id (single-process leg of the
    cross-host acceptance test)."""
    tid = "7b" * 16
    _mk = np.random.RandomState(0)
    n = 200
    X = _mk.randn(n, 3)
    y = (X[:, 0] > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array(["n", "p"], object)[y]
    h2o3_tpu.Frame.from_numpy(cols, categorical=["y"],
                              key="trc_train")
    st, body, hdrs = _post(
        port,
        "/3/ModelBuilders/gbm?training_frame=trc_train"
        "&response_column=y&ntrees=2&max_depth=2&seed=1"
        "&model_id=trc_model",
        headers={"traceparent": f"00-{tid}-{'0' * 16}-01"})
    assert st == 200
    assert hdrs.get("X-H2O-Trace-Id") == tid
    jk = json.loads(body)["job"]["key"]["name"]
    for _ in range(600):
        st, body, _ = _get(port, f"/3/Jobs/{jk}")
        jd = json.loads(body)["jobs"][0]
        if jd["status"] not in ("CREATED", "RUNNING"):
            break
        time.sleep(0.05)
    assert jd["status"] == "DONE"
    # satellite: JobV3 carries the trace id
    assert jd["trace_id"] == tid

    st, body, _ = _get(port, f"/3/Trace?trace_id={tid}")
    assert st == 200
    trace = json.loads(body)
    assert trace["otherData"]["trace_id"] == tid
    assert trace["otherData"]["nodes"] == [0]
    evs = [e for e in trace["traceEvents"]
           if e.get("cat") == "span" and e["ph"] == "X"]
    names = {e["name"] for e in evs}
    # the whole causal chain wears the id: ingress, job, fit
    assert {"rest", "job", "gbm.fit"} <= names, names
    by_id = {e["args"]["span_id"]: e for e in evs}
    # single-process stitching node-qualifies ids and resolves parents
    assert all(e["args"]["span_id"].startswith("n0:") for e in evs)
    job_ev = next(e for e in evs if e["name"] == "job")
    rest_evs = [e for e in evs if e["name"] == "rest"]
    assert job_ev["args"]["parent_id"] in by_id
    assert any(by_id[job_ev["args"]["parent_id"]] is r
               for r in rest_evs)
    # every stitched span carries its node in args
    assert all(e["args"].get("node") == 0 for e in evs)


@pytest.mark.allow_key_leak
def test_rest_trace_without_id_is_bit_compatible(port):
    """GET /3/Trace without trace_id= must be byte-for-byte the
    pre-tracing export: pid-grouped, raw span ids, and NO trace_id key
    in event args."""
    from h2o3_tpu.telemetry import trace_export
    st, body, _ = _get(port, "/3/Trace")
    assert st == 200
    trace = json.loads(body)
    assert "trace_id" not in trace["otherData"]
    for e in trace["traceEvents"]:
        if e.get("cat") == "span":
            assert "trace_id" not in e["args"]
            assert not e["args"]["span_id"].startswith("n")
    # and the route output equals the library export shape
    local = trace_export.process_trace()
    assert set(trace) == set(local)


@pytest.mark.allow_key_leak
def test_rest_alerts_route(port):
    st, body, _ = _get(port, "/3/Alerts")
    assert st == 200
    out = json.loads(body)
    assert {r["slo"] for r in out["rules"]} >= {"predict_p99_latency",
                                               "rest_availability"}
    assert "alerts" in out and "burn_threshold" in out
    # cluster fan-in degrades to the local view on one process (the
    # _cluster_requested contract): same shape, same rule set
    st, body, _ = _get(port, "/3/Alerts?cluster=1")
    assert st == 200
    merged = json.loads(body)
    assert {r["slo"] for r in merged["rules"]} == \
        {r["slo"] for r in out["rules"]}
    assert "alerts" in merged and "burn_threshold" in merged
    # the library-level fan-in (what a multi-host /3/Alerts?cluster=1
    # serves) stamps each rule with its owning node
    from h2o3_tpu.telemetry import cluster
    lib = cluster.merged_alerts()
    assert lib["process_count"] == 1
    assert any(r.get("node") == 0 for r in lib["rules"])
    # the Prometheus scrape exports the slo_* gauges
    st, body, _ = _get(port, "/3/Metrics?format=prometheus")
    assert st == 200
    text = body.decode()
    assert "slo_burn_rate" in text
    assert "slo_alert_active" in text


# ------------------------------------------------- scheduler lease hops


def test_lease_payload_roundtrip_and_back_compat():
    from h2o3_tpu.parallel.scheduler import _lease_payload, _parse_lease
    items = {0: 1, 3: 2}
    tp = f"00-{'ab' * 16}-sp-00000005-01"
    raw = _lease_payload(items, tp)
    got, got_tp = _parse_lease(raw)
    assert got == items and got_tp == tp
    # no traceparent -> the legacy bare dict, parsed back trace-less
    legacy = _lease_payload(items, None)
    assert json.loads(legacy) == {"0": 1, "3": 2}
    got, got_tp = _parse_lease(legacy)
    assert got == items and got_tp is None
    assert _parse_lease(None) == ({}, None)
    assert _parse_lease("") == ({}, None)


def test_serving_members_get_phase_spans_under_own_trace():
    """The micro-batch dispatcher attributes retroactive
    queue/device/scatter spans to each member request's own trace."""
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(1)
    n = 120
    X = r.randn(n, 3)
    y = (X[:, 0] > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array(["n", "p"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    model = GBMEstimator(ntrees=2, max_depth=2, seed=1).train(fr, y="y")
    from h2o3_tpu.serving.engine import engine
    tid = "9c" * 16
    tc = trace_context.TraceContext(tid, None)
    rows = [{"x0": 0.5, "x1": -0.2, "x2": 0.1}]
    try:
        with trace_context.trace_scope(tc), \
                telemetry.span("tst.submit") as submit:
            out, domains, meta = engine.score_rows(model, rows)
        assert meta["batch_rows"] >= 1
        mine = [s for s in telemetry.spans_snapshot(2048)
                if s.get("trace_id") == tid]
        phases = {s["name"] for s in mine}
        assert {"predict.queue", "predict.device",
                "predict.scatter"} <= phases, phases
        # each phase span parents under the submitting span
        for s in mine:
            if s["name"].startswith("predict."):
                assert s["parent_id"] == submit.id
                assert s["meta"]["model"] == model.key
        # the coalesced dispatch span links the member's trace
        dsp = [s for s in telemetry.spans_snapshot(2048)
               if s["name"] == "predict.dispatch"
               and tid in (s["meta"].get("member_traces") or [])]
        assert dsp
    finally:
        engine.reset()


# ----------------------------------------------------- multiprocess leg


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.multiprocess
@pytest.mark.allow_key_leak
def test_cross_host_stitched_trace(tmp_path):
    """Acceptance (ISSUE 16): a REST request with a traceparent header
    triggering a scheduled 2-process grid produces ONE
    /3/Trace?trace_id= Chrome trace with causally-parented spans from
    BOTH hosts and the echoed X-H2O-Trace-Id."""
    out = str(tmp_path / "trace_out")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(i), out, "trace"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    logs = []
    deadline = time.time() + WORKER_TIMEOUT_S
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(deadline - time.time(), 1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + \
                f"\n[TIMEOUT after {WORKER_TIMEOUT_S:.0f}s]"
        logs.append(stdout)
    assert all(rc == 0 for rc in (p.returncode for p in procs)), \
        "\n".join(logs)

    with open(f"{out}.0") as f:
        r0 = json.load(f)
    with open(f"{out}.1") as f:
        r1 = json.load(f)
    tid = "ab" * 16
    assert r0["status"] == "DONE", logs[0]
    assert r0["echoed"] == tid                # X-H2O-Trace-Id echo
    assert r0["job_trace_id"] == tid          # JobV3 satellite
    assert r1["spans_with_trace"] > 0         # lease hop stamped host 1

    trace = r0["trace"]
    assert trace["otherData"]["trace_id"] == tid
    assert sorted(trace["otherData"]["nodes"]) == [0, 1], \
        trace["otherData"]
    evs = [e for e in trace["traceEvents"]
           if e.get("cat") == "span" and e["ph"] == "X"]
    by_id = {e["args"]["span_id"]: e for e in evs}
    items0 = [e for e in evs if e["name"] == "sched.item"
              and e["args"].get("node") == 0]
    items1 = [e for e in evs if e["name"] == "sched.item"
              and e["args"].get("node") == 1]
    assert items0 and items1, {e["name"] for e in evs}
    run0 = [e for e in evs if e["name"] == "sched.run"
            and e["args"].get("node") == 0]
    assert len(run0) == 1
    # THE acceptance bit: a remote host's items parent under the
    # COORDINATOR's sched.run — a cross-process causal link, not a
    # pid-grouped track
    for e in items1:
        assert e["args"]["parent_id"] == run0[0]["args"]["span_id"], \
            (e["args"], run0[0]["args"])
    for e in items0:
        assert e["args"]["parent_id"] == run0[0]["args"]["span_id"]
    # and the whole chain hangs under the client's request: the
    # coordinator's sched.run resolves (transitively) to the rest span
    names = {e["name"] for e in evs}
    assert "rest" in names and "job" in names
    cur = run0[0]
    seen = set()
    while cur["args"]["parent_id"] in by_id and \
            cur["args"]["span_id"] not in seen:
        seen.add(cur["args"]["span_id"])
        cur = by_id[cur["args"]["parent_id"]]
    assert cur["name"] == "rest", cur["name"]
