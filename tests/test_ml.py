"""Grid search / Leaderboard / StackedEnsemble tests — pyunit_grid* /
pyunit_stackedensemble* role."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.ml.ensemble import StackedEnsembleEstimator
from h2o3_tpu.ml.grid import GridSearch
from h2o3_tpu.ml.leaderboard import Leaderboard
from h2o3_tpu.models.drf import DRFEstimator
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.glm import GLMEstimator


def test_grid_cartesian(classif_frame):
    gs = GridSearch(GBMEstimator,
                    {"max_depth": [2, 4], "learn_rate": [0.1, 0.3]},
                    ntrees=8, seed=1)
    grid = gs.train(classif_frame, y="y")
    assert len(grid.models) == 4
    ms = grid.sorted_models("auc")
    aucs = [m.default_metrics["AUC"] for m in ms]
    assert aucs == sorted(aucs, reverse=True)
    assert all("grid_params" in m.output for m in ms)


def test_grid_random_discrete_budget(classif_frame):
    gs = GridSearch(GBMEstimator,
                    {"max_depth": [2, 3, 4, 5], "learn_rate": [0.05, 0.1, 0.2]},
                    search_criteria={"strategy": "RandomDiscrete",
                                     "max_models": 3, "seed": 42},
                    ntrees=5, seed=1)
    grid = gs.train(classif_frame, y="y")
    assert len(grid.models) == 3


def test_grid_failure_recorded(classif_frame):
    gs = GridSearch(GBMEstimator, {"max_depth": [3, -5]}, ntrees=5)
    grid = gs.train(classif_frame, y="y")
    assert len(grid.models) >= 1
    assert len(grid.failures) >= 1 or len(grid.models) == 2


def test_leaderboard_ranks(classif_frame):
    m1 = GBMEstimator(ntrees=15, max_depth=4, seed=1).train(classif_frame, y="y")
    m2 = GLMEstimator(family="binomial").train(classif_frame, y="y")
    lb = Leaderboard("t")
    lb.add(m1, m2)
    tab = lb.as_table()
    assert len(tab) == 2
    assert tab[0]["auc"] >= tab[1]["auc"]
    assert lb.leader.key == tab[0]["model_id"]


def test_stacked_ensemble_beats_or_matches_base(classif_frame):
    m1 = GBMEstimator(ntrees=15, max_depth=3, seed=1, nfolds=3).train(
        classif_frame, y="y")
    m2 = GLMEstimator(family="binomial", nfolds=3).train(classif_frame, y="y")
    se = StackedEnsembleEstimator(base_models=[m1, m2]).train(
        classif_frame, y="y")
    perf = se.model_performance(classif_frame)
    base_best = max(m1.cross_validation_metrics["AUC"],
                    m2.cross_validation_metrics["AUC"])
    assert perf["AUC"] > base_best - 0.03, (perf["AUC"], base_best)
    preds = se.predict(classif_frame).to_pandas()
    assert {"predict", "p0", "p1"} <= set(preds.columns)


def test_stacked_ensemble_requires_cv(classif_frame):
    m1 = GBMEstimator(ntrees=5, seed=1).train(classif_frame, y="y")
    m2 = GLMEstimator(family="binomial").train(classif_frame, y="y")
    with pytest.raises((RuntimeError, ValueError), match="holdout"):
        StackedEnsembleEstimator(base_models=[m1, m2]).train(
            classif_frame, y="y")


def test_stacked_ensemble_regression(regress_frame):
    m1 = GBMEstimator(ntrees=15, max_depth=4, seed=1, nfolds=3).train(
        regress_frame, y="y")
    m2 = GLMEstimator(family="gaussian", nfolds=3).train(regress_frame, y="y")
    se = StackedEnsembleEstimator(base_models=[m1, m2]).train(
        regress_frame, y="y")
    perf = se.model_performance(regress_frame)
    assert perf["MSE"] <= 1.1 * min(m1.cross_validation_metrics["MSE"],
                                    m2.cross_validation_metrics["MSE"])
