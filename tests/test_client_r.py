"""h2o-r client generation (gen_R.py role).

No R runtime exists in the build image (PARITY.md), so the generated
package is validated structurally: files present, every algorithm gets
an exported wrapper, and every generated file balances its delimiters
(the cheap syntax proxy R CMD check would catch).
"""

import os

from h2o3_tpu.api.server import _builders
from h2o3_tpu.client_r import generate_r_package


def _balanced(src: str) -> bool:
    # strip string literals + comments first so quoted braces don't count
    out, i, n = [], 0, len(src)
    while i < n:
        ch = src[i]
        if ch in "\"'":
            q = ch
            i += 1
            while i < n and src[i] != q:
                i += 2 if src[i] == "\\" else 1
            i += 1
        elif ch == "#":
            while i < n and src[i] != "\n":
                i += 1
        else:
            out.append(ch)
            i += 1
    s = "".join(out)
    return all(s.count(a) == s.count(b)
               for a, b in (("(", ")"), ("{", "}"), ("[", "]")))


def test_generate_r_package(tmp_path):
    builders = _builders({}, b"")["model_builders"]
    written = generate_r_package(str(tmp_path), builders)
    assert os.path.exists(tmp_path / "DESCRIPTION")
    assert os.path.exists(tmp_path / "NAMESPACE")
    assert os.path.exists(tmp_path / "R" / "h2o.R")
    ns = open(tmp_path / "NAMESPACE").read()
    assert "export(h2o.gbm)" in ns
    assert "export(h2o.randomForest)" in ns
    assert "export(h2o.init)" in ns
    assert "S3method(as.data.frame, H2OFrame)" in ns
    # one wrapper per registered algorithm
    rfiles = os.listdir(tmp_path / "R")
    assert len(rfiles) == len(builders) + 1      # + core h2o.R
    for p in written:
        if p.endswith(".R"):
            src = open(p).read()
            assert _balanced(src), f"unbalanced delimiters in {p}"
    gbm = open(tmp_path / "R" / "gbm.R").read()
    assert "h2o.gbm <- function" in gbm
    assert '.h2o.train("gbm"' in gbm
    assert "ntrees = 50" in gbm                  # default carried over


def test_r_sources_pass_syntax_validator(tmp_path):
    """Every generated .R file must pass the vendored parse-level
    validator (client_r/rcheck.py — VERDICT r1 item 9's R CMD check
    stand-in)."""
    from h2o3_tpu.client_r.rcheck import check_r_source
    builders = _builders({}, b"")["model_builders"]
    written = generate_r_package(str(tmp_path), builders)
    checked = 0
    for p in written:
        if not str(p).endswith(".R"):
            continue
        errors = check_r_source(open(p).read())
        assert not errors, f"{p}: {errors}"
        checked += 1
    assert checked >= 3


def test_r_validator_catches_errors():
    from h2o3_tpu.client_r.rcheck import check_r_source
    assert check_r_source('f <- function(x { x }')          # missing )
    assert check_r_source('x <- "unterminated')             # bad string
    assert check_r_source('y <- 1 +')                       # dangling op
    assert not check_r_source(
        'h2o.init <- function(url = "http://x") {\n'
        '  resp <- .h2o.get(url, "/3/Cloud")\n'
        '  invisible(resp$cloud_name)\n}\n')


def test_r_package_golden_manifest(tmp_path):
    """Golden snapshot of the generated package surface: file list +
    exported functions per file. Catches silent generator regressions
    (no R runtime to execute — VERDICT r1 item 9)."""
    import json
    import re as _re
    builders = _builders({}, b"")["model_builders"]
    written = generate_r_package(str(tmp_path), builders)
    manifest = {}
    for p in sorted(written):
        rel = os.path.relpath(p, tmp_path)
        if str(p).endswith(".R"):
            funcs = sorted(set(_re.findall(
                r"^([A-Za-z._][A-Za-z0-9._]*)\s*<-\s*function",
                open(p).read(), _re.M)))
            manifest[rel] = funcs
        else:
            manifest[rel] = None
    golden_path = os.path.join(os.path.dirname(__file__), "golden",
                               "r_package_manifest.json")
    if not os.path.exists(golden_path):
        os.makedirs(os.path.dirname(golden_path), exist_ok=True)
        with open(golden_path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
    with open(golden_path) as f:
        golden = json.load(f)
    assert manifest == golden, "generated R package surface changed — " \
        "if intentional, delete tests/golden/r_package_manifest.json"
