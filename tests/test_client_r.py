"""h2o-r client generation (gen_R.py role).

No R runtime exists in the build image (PARITY.md), so the generated
package is validated structurally: files present, every algorithm gets
an exported wrapper, and every generated file balances its delimiters
(the cheap syntax proxy R CMD check would catch).
"""

import os

from h2o3_tpu.api.server import _builders
from h2o3_tpu.client_r import generate_r_package


def _balanced(src: str) -> bool:
    # strip string literals + comments first so quoted braces don't count
    out, i, n = [], 0, len(src)
    while i < n:
        ch = src[i]
        if ch in "\"'":
            q = ch
            i += 1
            while i < n and src[i] != q:
                i += 2 if src[i] == "\\" else 1
            i += 1
        elif ch == "#":
            while i < n and src[i] != "\n":
                i += 1
        else:
            out.append(ch)
            i += 1
    s = "".join(out)
    return all(s.count(a) == s.count(b)
               for a, b in (("(", ")"), ("{", "}"), ("[", "]")))


def test_generate_r_package(tmp_path):
    builders = _builders({}, b"")["model_builders"]
    written = generate_r_package(str(tmp_path), builders)
    assert os.path.exists(tmp_path / "DESCRIPTION")
    assert os.path.exists(tmp_path / "NAMESPACE")
    assert os.path.exists(tmp_path / "R" / "h2o.R")
    ns = open(tmp_path / "NAMESPACE").read()
    assert "export(h2o.gbm)" in ns
    assert "export(h2o.randomForest)" in ns
    assert "export(h2o.init)" in ns
    assert "S3method(as.data.frame, H2OFrame)" in ns
    # one wrapper per registered algorithm
    rfiles = os.listdir(tmp_path / "R")
    assert len(rfiles) == len(builders) + 1      # + core h2o.R
    for p in written:
        if p.endswith(".R"):
            src = open(p).read()
            assert _balanced(src), f"unbalanced delimiters in {p}"
    gbm = open(tmp_path / "R" / "gbm.R").read()
    assert "h2o.gbm <- function" in gbm
    assert '.h2o.train("gbm"' in gbm
    assert "ntrees = 50" in gbm                  # default carried over
