"""XGBoost facade, SVMLight/ARFF ingest, self-bench, TimeLine."""

import numpy as np
import pytest

import h2o3_tpu
from tests.conftest import make_classification


def test_xgboost_facade_param_translation(classif_frame):
    from h2o3_tpu.models.xgboost import XGBoostEstimator
    m = XGBoostEstimator(ntrees=8, eta=0.2, max_depth=4, subsample=0.9,
                         colsample_bytree=0.8, min_child_weight=5,
                         reg_lambda=2.0, booster="gbtree",
                         tree_method="hist", seed=3).train(
        classif_frame, y="y")
    assert m.algo == "gbm"
    assert m.output["facade"] == "xgboost"
    assert m.params["learn_rate"] == 0.2
    assert m.params["sample_rate"] == 0.9
    assert m.params["min_rows"] == 5
    assert m.training_metrics["AUC"] > 0.7


def test_xgboost_facade_registry():
    from h2o3_tpu.models import get_builder
    assert get_builder("xgboost").algo == "xgboost"
    with pytest.raises(ValueError):
        get_builder("xgboost")(definitely_not_a_param=1)


def test_svmlight_parse(tmp_path):
    p = tmp_path / "t.svm"
    p.write_text("1 1:0.5 3:2.0\n-1 2:1.5 # comment\n1 qid:7 1:1.0 4:4.0\n")
    fr = h2o3_tpu.import_file(str(p))
    assert fr.shape == (3, 5)   # C0 label + C1..C4
    np.testing.assert_array_equal(fr.col("C0").to_numpy(), [1, -1, 1])
    np.testing.assert_array_equal(fr.col("C3").to_numpy(), [2.0, 0.0, 0.0])
    np.testing.assert_array_equal(fr.col("C4").to_numpy(), [0.0, 0.0, 4.0])


def test_arff_parse(tmp_path):
    p = tmp_path / "t.arff"
    p.write_text("""% comment
@relation demo
@attribute sepal numeric
@attribute color {red, green, blue}
@attribute note string
@data
5.1,red,'hello'
4.9,blue,?
?,green,world
""")
    fr = h2o3_tpu.import_file(str(p))
    assert fr.shape == (3, 3)
    assert fr.col("color").domain == ["red", "green", "blue"]
    x = fr.col("sepal").to_numpy()
    assert np.isnan(x[2]) and x[0] == pytest.approx(5.1)
    assert fr.col("note").type == "string"


def test_arff_quoted_names_and_values(tmp_path):
    p = tmp_path / "q.arff"
    p.write_text("""@relation q
@attribute 'sepal length' numeric
@attribute label {x, y}
@attribute note string
@data
5.1,x,'a, b'
4.2,y,plain
""")
    fr = h2o3_tpu.import_file(str(p))
    assert "sepal length" in fr.names
    assert fr.col("sepal length").to_numpy()[0] == pytest.approx(5.1)
    assert fr.col("note").to_numpy()[0] == "a, b"


@pytest.mark.allow_key_leak   # REST handler thread creates the model key
def test_xgboost_over_rest(classif_frame):
    """The facade must be drivable through POST /3/ModelBuilders/xgboost
    with XGBoost-style params actually applied."""
    from h2o3_tpu.api.server import ROUTES
    train = next(fn for m, rx, fn in ROUTES
                 if m == "POST" and rx.match("/3/ModelBuilders/xgboost"))
    out = train({"training_frame": classif_frame.key,
                 "response_column": "y", "ntrees": 4, "eta": 0.3,
                 "max_depth": 3, "booster": "gbtree"}, "", algo="xgboost")
    from h2o3_tpu.core.kv import DKV
    job = DKV.get(out["job"]["key"]["name"]).join()
    assert job.status == "DONE", job.exception
    m = job.result
    assert m.params["learn_rate"] == 0.3 and m.params["ntrees"] == 4


def test_self_bench_probes():
    from h2o3_tpu.core.selfcheck import run_self_bench
    out = run_self_bench(sizes={"matmul": 256, "membw": 1 << 18,
                                "transfer": 1 << 18})
    assert out["matmul_f32_gflops"] > 0
    assert out["hbm_read_gbps"] > 0
    assert out["h2d_gbps"] > 0 and out["d2h_gbps"] > 0


def test_timeline_records_jobs(classif_frame):
    from h2o3_tpu.utils import timeline
    from h2o3_tpu.models.gbm import GBMEstimator
    timeline.clear()
    GBMEstimator(ntrees=2, max_depth=2, seed=1).train(classif_frame, y="y")
    evs = timeline.snapshot()
    kinds = [(e["kind"], e["what"].split()[0]) for e in evs]
    assert ("job", "start") in kinds and ("job", "done") in kinds
    # ring keeps order and caps capacity
    for _ in range(3000):
        timeline.record("test", "x")
    evs = timeline.snapshot()
    assert len(evs) == 2048
    assert evs[-1]["seq"] > evs[0]["seq"]


def test_xlsx_parse(tmp_path):
    """Stdlib XLSX ingest: header row, shared strings, inline strings,
    missing cells -> NA, text column interned as categorical."""
    import zipfile
    p = str(tmp_path / "t.xlsx")
    ct = ('<?xml version="1.0"?><Types xmlns="http://schemas.openxmlformats'
          '.org/package/2006/content-types"><Default Extension="xml" '
          'ContentType="application/xml"/></Types>')
    wb = ('<?xml version="1.0"?><workbook xmlns="http://schemas.openxml'
          'formats.org/spreadsheetml/2006/main"><sheets><sheet name="S1" '
          'sheetId="1"/></sheets></workbook>')
    ss = ('<?xml version="1.0"?><sst xmlns="http://schemas.openxmlformats'
          '.org/spreadsheetml/2006/main" count="4" uniqueCount="4">'
          '<si><t>age</t></si><si><t>city</t></si><si><t>sf</t></si>'
          '<si><t>nyc</t></si></sst>')
    sheet = ('<?xml version="1.0"?><worksheet xmlns="http://schemas.openxml'
             'formats.org/spreadsheetml/2006/main"><sheetData>'
             '<row r="1"><c r="A1" t="s"><v>0</v></c>'
             '<c r="B1" t="s"><v>1</v></c></row>'
             '<row r="2"><c r="A2"><v>31.5</v></c>'
             '<c r="B2" t="s"><v>2</v></c></row>'
             '<row r="3"><c r="A3"><v>44</v></c>'
             '<c r="B3" t="s"><v>3</v></c></row>'
             '<row r="4"><c r="B4" t="inlineStr"><is><t>sf</t></is></c>'
             '</row></sheetData></worksheet>')
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("[Content_Types].xml", ct)
        z.writestr("xl/workbook.xml", wb)
        z.writestr("xl/sharedStrings.xml", ss)
        z.writestr("xl/worksheets/sheet1.xml", sheet)
    fr = h2o3_tpu.import_file(p)
    assert fr.names == ["age", "city"]
    assert fr.nrows == 3
    age = fr.col("age").to_numpy()
    assert age[0] == 31.5 and age[1] == 44 and np.isnan(age[2])
    c = fr.col("city")
    assert c.is_categorical
    assert [c.domain[i] for i in np.asarray(c.data)[:3]] == ["sf", "nyc", "sf"]


def test_xls_gated(tmp_path):
    p = tmp_path / "legacy.xls"
    p.write_bytes(b"\xd0\xcf\x11\xe0junk")
    with pytest.raises(ValueError, match="xlsx"):
        h2o3_tpu.import_file(str(p))


def test_scope_tracks_and_keeps_keys():
    """water/Scope.java contract: keys made inside a scope die with it
    unless kept."""
    import numpy as np
    import h2o3_tpu
    from h2o3_tpu.core.kv import DKV
    with h2o3_tpu.Scope() as s:
        fr = h2o3_tpu.Frame.from_numpy({"a": np.arange(8.0)})
        fr2 = h2o3_tpu.Frame.from_numpy({"b": np.arange(8.0)})
        s.keep(fr2.key)
        assert DKV.get(fr.key) is not None
    assert DKV.get(fr.key) is None           # cleaned
    assert DKV.get(fr2.key) is not None      # kept
    DKV.remove(fr2.key)
