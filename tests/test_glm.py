"""GLM tests — the pyunit_glm* role (h2o-py/tests/testdir_algos/glm/),
with numpy/sklearn closed-form oracles (testdir_golden role)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.glm import GLMEstimator


def _frame_reg(n=2000, p=5, seed=0, noise=0.1):
    r = np.random.RandomState(seed)
    X = r.randn(n, p)
    beta = np.arange(1, p + 1, dtype=float)
    y = X @ beta + 0.5 + noise * r.randn(n)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y
    return h2o3_tpu.Frame.from_numpy(cols), X, y, beta


def test_glm_gaussian_matches_ols():
    fr, X, y, beta = _frame_reg()
    m = GLMEstimator(family="gaussian", lambda_=0, standardize=False).train(fr, y="y")
    coefs = m.coefficients
    for i, b in enumerate(beta):
        assert coefs[f"x{i}"] == pytest.approx(b, abs=0.02)
    assert coefs["Intercept"] == pytest.approx(0.5, abs=0.02)
    assert m.training_metrics["r2"] > 0.99


def test_glm_gaussian_standardized_same_predictions():
    fr, X, y, beta = _frame_reg()
    m = GLMEstimator(family="gaussian", lambda_=0, standardize=True).train(fr, y="y")
    pred = m.predict(fr).to_pandas()["predict"].to_numpy()
    assert np.corrcoef(pred, y)[0, 1] ** 2 > 0.99
    # de-standardized coefficient recovery happens via the design-stats
    # round trip; predictions must match regardless


def test_glm_binomial_matches_sklearn():
    from sklearn.linear_model import LogisticRegression
    r = np.random.RandomState(1)
    n, p = 3000, 4
    X = r.randn(n, p)
    logits = X @ np.array([1.0, -2.0, 0.5, 0.0]) + 0.3
    y = (r.rand(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = np.array(["A", "B"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    m = GLMEstimator(family="binomial", lambda_=0, standardize=False).train(fr, y="y")
    sk = LogisticRegression(penalty=None, max_iter=500).fit(X, y)
    coefs = m.coefficients
    for i in range(p):
        assert coefs[f"x{i}"] == pytest.approx(sk.coef_[0][i], abs=0.05)
    assert m.training_metrics["AUC"] > 0.85


def test_glm_lbfgs_agrees_with_irlsm():
    from sklearn.linear_model import LogisticRegression
    r = np.random.RandomState(2)
    n, p = 2000, 3
    X = r.randn(n, p)
    y = (r.rand(n) < 1 / (1 + np.exp(-(X @ np.ones(p))))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y.astype(float)
    fr = h2o3_tpu.Frame.from_numpy(cols)
    # numeric 0/1 response with binomial family
    m1 = GLMEstimator(family="binomial", lambda_=0, solver="irlsm",
                      standardize=False).train(fr, y="y")
    m2 = GLMEstimator(family="binomial", lambda_=0, solver="l_bfgs",
                      standardize=False, max_iterations=200).train(fr, y="y")
    c1, c2 = m1.coefficients, m2.coefficients
    for k in c1:
        assert c1[k] == pytest.approx(c2[k], abs=0.05), k


def test_glm_l1_sparsifies():
    r = np.random.RandomState(9)
    n = 1500
    X = r.randn(n, 6)
    beta = np.array([0.0, 0.0, 0.0, 1.0, 2.0, 3.0])
    y = X @ beta + 0.5 * r.randn(n)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(6)}, "y": y})
    m = GLMEstimator(family="gaussian", alpha=1.0, lambda_=0.3,
                     standardize=True).train(fr, y="y")
    coefs = m.coefficients
    # L1 must zero the null coefficients but keep the strong ones
    assert all(abs(coefs[f"x{i}"]) < 1e-4 for i in range(3)), coefs
    assert all(abs(coefs[f"x{i}"]) > 0.3 for i in (4, 5)), coefs


def test_glm_lambda_search():
    fr, X, y, beta = _frame_reg(n=1000, p=4)
    m = GLMEstimator(family="gaussian", lambda_search=True, nlambdas=8,
                     alpha=0.5).train(fr, y="y")
    assert m.training_metrics["r2"] > 0.9
    assert "lambda_best" in m.output


def test_glm_poisson():
    r = np.random.RandomState(3)
    n = 2000
    x = r.randn(n)
    lam = np.exp(0.5 + 0.8 * x)
    y = r.poisson(lam)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y.astype(float)})
    m = GLMEstimator(family="poisson", lambda_=0, standardize=False).train(fr, y="y")
    c = m.coefficients
    assert c["x"] == pytest.approx(0.8, abs=0.06)
    assert c["Intercept"] == pytest.approx(0.5, abs=0.06)


def test_glm_multinomial():
    r = np.random.RandomState(4)
    n = 3000
    X = r.randn(n, 4)
    logits = np.stack([X @ np.array([1, 0, 0, 0.]),
                       X @ np.array([0, 1, 0, 0.]),
                       X @ np.array([0, 0, 1, 0.])], axis=1)
    y = logits.argmax(axis=1)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["u", "v", "w"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    m = GLMEstimator(family="multinomial", lambda_=0).train(fr, y="y")
    tm = m.training_metrics
    assert tm["error_rate"] < 0.12
    preds = m.predict(fr).to_pandas()
    assert set(preds.columns) == {"predict", "p0", "p1", "p2"}


def test_glm_with_categoricals_and_nas():
    r = np.random.RandomState(5)
    n = 2000
    g = r.randint(0, 3, n)
    x = r.randn(n)
    x[r.rand(n) < 0.1] = np.nan
    y = 2.0 * g + np.nan_to_num(x) + 0.2 * r.randn(n)
    fr = h2o3_tpu.Frame.from_numpy(
        {"g": np.array(["a", "b", "c"], object)[g], "x": x, "y": y})
    m = GLMEstimator(family="gaussian", lambda_=0).train(fr, y="y")
    assert m.training_metrics["r2"] > 0.9
    coefs = m.coefficients
    assert "g.b" in coefs and "g.c" in coefs  # first level dropped


def test_glm_p_values_match_statsmodels_style():
    """compute_p_values: Wald inference vs a closed-form OLS check."""
    import h2o3_tpu
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(7)
    n = 2000
    x0, x1 = r.randn(n), r.randn(n)
    noise_col = r.randn(n)
    y = 3.0 * x0 + 0.0 * noise_col + 1.0 + 0.5 * r.randn(n)
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "x1": x1,
                                    "noise": noise_col, "y": y})
    m = GLMEstimator(family="gaussian", lambda_=0.0, standardize=False,
                     compute_p_values=True).train(fr, y="y")
    tbl = {row["name"]: row for row in m.output["coefficients_table"]}
    # strong predictor: tiny p-value; pure noise: large p-value
    assert tbl["x0"]["p_value"] < 1e-10
    assert tbl["noise"]["p_value"] > 0.01
    # OLS closed-form std error comparison for x0
    X = np.stack([x0, x1, noise_col, np.ones(n)], axis=1)
    beta = np.linalg.lstsq(X, y, rcond=None)[0]
    resid = y - X @ beta
    s2 = (resid ** 2).sum() / (n - 4)
    se = np.sqrt(np.diag(s2 * np.linalg.inv(X.T @ X)))
    assert tbl["x0"]["std_error"] == pytest.approx(se[0], rel=0.15)

    with pytest.raises(ValueError, match="regularization"):
        GLMEstimator(family="gaussian", lambda_=0.5,
                     compute_p_values=True).train(fr, y="y")


def test_glm_p_values_binomial():
    import h2o3_tpu
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(3)
    n = 3000
    x0, noise = r.randn(n), r.randn(n)
    pr = 1 / (1 + np.exp(-(1.5 * x0)))
    y = np.array(["a", "b"], object)[(r.rand(n) < pr).astype(int)]
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "noise": noise, "y": y},
                                   categorical=["y"])
    m = GLMEstimator(family="binomial", lambda_=0.0,
                     compute_p_values=True).train(fr, y="y")
    tbl = {row["name"]: row for row in m.output["coefficients_table"]}
    assert tbl["x0"]["p_value"] < 1e-8
    assert tbl["noise"]["p_value"] > 0.01


def test_glm_on_model_axis_mesh_matches_data_parallel():
    """GLM IRLS over a (4 data x 2 model) mesh (ring Gram) must agree
    with the (8, 1) data-parallel run — SURVEY §2.4 item 6."""
    import jax
    from h2o3_tpu.models.glm import GLMEstimator
    from h2o3_tpu.parallel import mesh as mesh_mod
    r = np.random.RandomState(11)
    fr = h2o3_tpu.Frame.from_numpy({
        **{f"x{i}": r.randn(600) for i in range(5)},
        "g": r.choice(["a", "b", "c", "d"], 600),
        "y": r.randn(600)})
    kw = dict(family="gaussian", lambda_=0.0, standardize=True)
    base = GLMEstimator(**kw).train(fr, y="y")
    old = mesh_mod.get_mesh()
    try:
        m2 = mesh_mod.make_mesh(jax.devices("cpu")[:8], 4, 2)
        mesh_mod.set_global_mesh(m2)
        r2 = np.random.RandomState(11)
        fr2 = h2o3_tpu.Frame.from_numpy({
            **{f"x{i}": r2.randn(600) for i in range(5)},
            "g": r2.choice(["a", "b", "c", "d"], 600),
            "y": r2.randn(600)})
        wide = GLMEstimator(**kw).train(fr2, y="y")
    finally:
        mesh_mod.set_global_mesh(old)
    for k, v in base.coefficients.items():
        assert abs(wide.coefficients[k] - v) < 1e-3, (k, v,
                                                      wide.coefficients[k])
