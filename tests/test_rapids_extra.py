"""New Rapids prims: match/which/levels/cor/strsplit/time ops/etc."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.rapids import rapids


@pytest.fixture()
def fr():
    return h2o3_tpu.Frame.from_numpy(
        {"g": np.asarray(["a", "b", "c", "a", None], dtype=object),
         "x": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0]),
         "y": np.asarray([2.0, 4.0, 6.0, 8.0, 10.0]),
         "t": np.asarray([0.0, 86400000.0, 90000000.0, 3600000.0,
                          1234567890000.0])},
        categorical=["g"], key="rapx")


def test_match_and_levels(fr):
    out = rapids('(match (cols_py rapx "g") ["b" "c"] NaN 0)')
    v = out.col("g").to_numpy()
    assert np.isnan(v[0]) and v[1] == 1 and v[2] == 2 and np.isnan(v[4])
    lv = rapids('(levels (cols_py rapx "g"))')
    assert list(lv.col("levels").to_numpy().astype(str)) == ["0", "1", "2"] \
        or lv.nrows == 3
    assert rapids('(nlevels (cols_py rapx "g"))') == 3
    # per-column flag lists (h2o-py isfactor()/isnumeric() iterate them)
    assert rapids('(is.factor (cols_py rapx "g"))') == [1.0]
    assert rapids('(is.numeric (cols_py rapx "x"))') == [1.0]
    assert rapids('(anyfactor rapx)') == 1.0
    assert rapids('(any.na rapx)') == 1.0


def test_which_ops(fr):
    w = rapids('(h2o.which (> (cols_py rapx "x") 2.5))')
    np.testing.assert_array_equal(w.col("which").to_numpy(), [2, 3, 4])
    # axis=1: per-row argmax; axis=0 (h2o-py idxmax default): per-column
    wm = rapids('(which.max (cols_py rapx ["x" "y"]) 1 1)')
    np.testing.assert_array_equal(wm.col("which.max").to_numpy(),
                                  [1, 1, 1, 1, 1])
    wc = rapids('(which.max (cols_py rapx ["x" "y"]) 1 0)')
    assert wc.nrows == 1
    assert wc.col("x").to_numpy()[0] == 4   # max of x sits in row 4


def test_which_excludes_na():
    h2o3_tpu.Frame.from_numpy({"v": np.asarray([1.0, 0.0, np.nan, 2.0])},
                              key="whichna")
    w = rapids('(h2o.which (cols_py whichna "v"))')
    np.testing.assert_array_equal(w.col("which").to_numpy(), [0, 3])


def test_cor(fr):
    c = rapids('(cor (cols_py rapx "x") (cols_py rapx "y") "everything" '
               '"Pearson")')
    assert c == pytest.approx(1.0)


def test_skew_kurt(fr):
    s = rapids('(skewness (cols_py rapx "x") 1)')
    assert abs(s) < 0.5
    k = rapids('(kurtosis (cols_py rapx "x") 1)')
    assert k > 0


def test_strsplit_countmatches_entropy():
    h2o3_tpu.Frame.from_numpy(
        {"s": np.asarray(["a_b", "c_d_e", None], dtype=object)},
        categorical=["s"], key="strf")
    sp = rapids('(strsplit (cols_py strf "s") "_")')
    assert sp.ncols == 3
    assert sp.col("C1").domain is not None
    cm = rapids('(countmatches (cols_py strf "s") ["_"])')
    v = cm.col("s").to_numpy()
    assert v[0] == 1 and v[1] == 2 and np.isnan(v[2])
    en = rapids('(entropy (cols_py strf "s"))')
    assert en.col("s").to_numpy()[0] > 0


def test_time_ops(fr):
    yr = rapids('(year (cols_py rapx "t"))').col("t").to_numpy()
    assert yr[0] == 1970 and yr[4] == 2009
    dw = rapids('(dayOfWeek (cols_py rapx "t"))').col("t").to_numpy()
    assert dw[0] == 3   # 1970-01-01 was a Thursday (weekday()==3)
    hh = rapids('(hour (cols_py rapx "t"))').col("t").to_numpy()
    assert hh[3] == 1


def test_difflag1(fr):
    d = rapids('(difflag1 (cols_py rapx "x"))').col("x").to_numpy()
    assert np.isnan(d[0])
    np.testing.assert_array_equal(d[1:], [1, 1, 1, 1])


def test_relevel(fr):
    out = rapids('(relevel (cols_py rapx "g") "c")')
    c = out.col("g")
    assert c.domain[0] == "c"
    # row values preserved under the new coding
    dom = np.asarray(c.domain + [None], dtype=object)
    codes = np.asarray(c.data)[: out.nrows].astype(int)
    na = np.asarray(c.na_mask)[: out.nrows]
    vals = dom[np.where(na, len(c.domain), codes)]
    assert list(vals[:4]) == ["a", "b", "c", "a"] and vals[4] is None
