"""Cloud peer-health + fail-fast degradation (ISSUE 7): heartbeat
rounds/misses, `CloudUnhealthyError` at chunk boundaries, the
heartbeat-loss-mid-GBM acceptance (no hang, no leaked RUNNING job,
partial keys swept), hardened bootstrap retries, and shutdown → init
reformation. All tier-1, all via fault injection — no real multi-host
needed — and all UNDER the conftest DKV/Scope leak check."""

import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.core import cloud, heartbeat, watchdog
from h2o3_tpu.core.heartbeat import CloudUnhealthyError
from h2o3_tpu.core.job import DONE, FAILED, RUNNING, Job
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.parallel.map_reduce import frame_map, frame_reduce


@pytest.fixture(autouse=True)
def _clean_monitor():
    """Every test starts and ends with a stopped, healthy monitor and
    no planted faults — an unhealthy flag leaking across tests would
    fail every subsequent frame_reduce."""
    watchdog.clear_faults()
    heartbeat.monitor.stop()
    yield
    watchdog.clear_faults()
    heartbeat.monitor.stop()


# ------------------------------------------------------ heartbeat rounds


def test_heartbeat_round_agreement_updates_peers():
    from h2o3_tpu import telemetry
    heartbeat.monitor.start(interval_s=30, miss_budget=3, timeout_s=10,
                            thread=False)
    before = telemetry.REGISTRY.value("heartbeat_rounds_total")
    assert heartbeat.monitor.round() is True
    st = heartbeat.monitor.status()
    assert st["healthy"]
    assert st["peers"]["0"]["healthy"]
    assert time.time() - st["peers"]["0"]["last_seen"] < 5.0
    assert telemetry.REGISTRY.value("heartbeat_rounds_total") > before


def test_heartbeat_miss_budget_flips_unhealthy_then_recovers():
    from h2o3_tpu import telemetry
    heartbeat.monitor.start(interval_s=30, miss_budget=2, timeout_s=10,
                            thread=False)
    watchdog.inject_fault("heartbeat", times=2)
    assert heartbeat.monitor.round() is False
    assert heartbeat.monitor.healthy()          # 1 miss < budget
    assert heartbeat.monitor.round() is False
    assert not heartbeat.monitor.healthy()      # budget exhausted
    assert "heartbeat misses" in heartbeat.monitor.reason()
    assert watchdog.fired("heartbeat") == 2
    assert telemetry.REGISTRY.value("cloud_peers_healthy") == 0
    # cluster_info + the degraded-mode contract: healthy=False flows out
    assert h2o3_tpu.cluster_info()["cloud_healthy"] is False
    # peers return → next agreement round ends degraded mode
    assert heartbeat.monitor.round() is True
    assert heartbeat.monitor.healthy()
    assert h2o3_tpu.cluster_info()["cloud_healthy"] is True


def test_heartbeat_timeout_is_a_miss():
    """A hung agreement check (wedged backend) is bounded by the
    thread-timeout prober and counted as a miss, never a hang."""
    heartbeat.monitor.start(interval_s=30, miss_budget=1, timeout_s=0.2,
                            thread=False)
    ev = __import__("threading").Event()
    heartbeat.monitor._psum_fn = lambda x: ev.wait(30)  # wedge the round
    heartbeat.monitor._psum_mesh = mesh_mod.get_mesh()
    t0 = time.time()
    assert heartbeat.monitor.round() is False
    assert time.time() - t0 < 5.0
    assert not heartbeat.monitor.healthy()
    ev.set()


# ------------------------------------------------- fail-fast chunk gates


def test_unhealthy_cloud_fails_frame_reduce_fast():
    # monitor thread NOT started: a background agreement round would
    # legitimately mark the (actually fine) CPU cloud healthy again —
    # this unit pins the flag → chunk-boundary contract
    heartbeat.monitor.mark_unhealthy("test: peer 1 presumed dead")
    with pytest.raises(CloudUnhealthyError, match="UNAVAILABLE"):
        frame_reduce(lambda x: x.sum(), jnp.ones(8))
    with pytest.raises(CloudUnhealthyError):
        frame_map(lambda x: x * 2, jnp.ones(8))
    heartbeat.monitor.mark_healthy()
    assert float(frame_reduce(lambda x: x.sum(), jnp.ones(8))) == 8.0


def test_cloud_unhealthy_error_is_infra_class():
    e = CloudUnhealthyError("3 consecutive heartbeat misses", site="t")
    assert watchdog.is_infra_error(e)
    # ...so the shared retry/recovery stack composes with it, but a
    # cancellation never becomes retryable by association
    from h2o3_tpu.core.job import JobCancelledException
    assert not watchdog.is_infra_error(JobCancelledException("k"))


def test_job_retries_when_cloud_recovers():
    """Transient unhealthiness composes with job-level infra retries:
    the first attempt dies on CloudUnhealthyError, the cloud recovers,
    the retry succeeds."""
    calls = []

    def work(j):
        calls.append(1)
        if len(calls) == 1:
            raise CloudUnhealthyError("blip", site="test")
        return "ok"

    policy_env = {"H2O3TPU_INFRA_BACKOFF_BASE_S": "0.01"}
    old = {k: os.environ.get(k) for k in policy_env}
    os.environ.update(policy_env)
    try:
        job = Job("recovering work").start(work)
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    assert job.status == DONE and job.result == "ok"
    assert len(calls) == 2


def test_job_fails_fast_while_cloud_still_unhealthy():
    """No futile retries against a cloud that has not recovered — the
    comeback path is recovery_dir snapshot/resume, not backoff."""
    heartbeat.monitor.mark_unhealthy("still down")
    calls = []

    def work(j):
        calls.append(1)
        heartbeat.check_healthy("test")

    job = Job("doomed work").start(work, background=True).join(30)
    assert job.status == FAILED
    assert len(calls) == 1, "retried against an unhealthy cloud"
    assert "CloudUnhealthyError" in job.exception


# ------------------------------------------------- acceptance: GBM fit


def test_heartbeat_loss_mid_gbm_fails_fast_and_sweeps():
    """ISSUE 7 acceptance: injected heartbeat loss during a running GBM
    fit → the job FAILS with a classified CloudUnhealthyError within one
    heartbeat interval of the next chunk boundary — no hang, no leaked
    RUNNING job, partial keys swept."""
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(9)
    n = 3000
    X = r.randn(n, 4)
    yv = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["n", "p"], dtype=object)[yv]},
        categorical=["y"])
    before = set(DKV.keys())

    heartbeat.monitor.start(interval_s=0.05, miss_budget=2, timeout_s=5)
    est = GBMEstimator(ntrees=400, max_depth=5, seed=1)
    est.train(fr, y="y", background=True)
    job = est._job
    # let the fit reach its boost loop, then kill the heartbeat: the
    # background monitor thread (0.05s interval) burns the miss budget
    deadline = time.time() + 60
    while job.progress <= 0.0 and job.status == RUNNING \
            and time.time() < deadline:
        time.sleep(0.01)
    assert job.status == RUNNING, (job.status, job.exception)
    watchdog.inject_fault("heartbeat", times=10_000)
    while heartbeat.monitor.healthy() and time.time() < deadline:
        time.sleep(0.01)
    t_lost = time.time()
    assert not heartbeat.monitor.healthy()

    job.join(60)
    assert job.status == FAILED, (job.status, job.exception)
    assert "CloudUnhealthyError" in job.exception
    assert "UNAVAILABLE" in job.exception
    # fail-fast: one chunk boundary + one heartbeat interval, not a
    # retry-backoff stall (bounded generously for busy CI hosts)
    assert job.end_time - t_lost < 10.0
    # no leaked RUNNING job, partial keys swept (job key lives in the
    # test scope; telemetry capsules are bounded intentional retention)
    leaked = {k for k in set(DKV.keys()) - before - {job.key, fr.key}
              if not k.endswith("_telemetry")}
    assert not leaked, f"degraded fit leaked keys: {sorted(leaked)}"


# -------------------------------------------------- hardened bootstrap


def test_cloud_init_fault_injection_bounded_retries(monkeypatch):
    """Formation attempts run under the shared RetryPolicy: a flaky
    coordinator costs bounded retries, then a classified error — and
    shutdown() → init() reforms the single-process cloud afterwards."""
    monkeypatch.setenv("H2O3TPU_INFRA_MAX_ATTEMPTS", "2")
    monkeypatch.setenv("H2O3TPU_INFRA_BACKOFF_BASE_S", "0.01")
    h2o3_tpu.shutdown()
    watchdog.inject_fault("cloud_init", times=10)
    try:
        with pytest.raises(watchdog.InjectedFault, match="UNAVAILABLE"):
            h2o3_tpu.init(backend="cpu",
                          coordinator_address="127.0.0.1:1",
                          num_processes=1, process_id=0)
        assert watchdog.fired("cloud_init") == 2   # max_attempts, no more
    finally:
        watchdog.clear_faults()
        info = h2o3_tpu.init(backend="cpu")
    assert info["cloud_size"] == 8 and info["cloud_healthy"]


def test_cloud_timeout_knob(monkeypatch):
    from h2o3_tpu.core import config as _config
    assert cloud._cloud_timeout_s(_config.ARGS) == \
        _config.ARGS.cloud_timeout_s
    monkeypatch.setenv("H2O3TPU_CLOUD_TIMEOUT_S", "7.5")
    assert cloud._cloud_timeout_s(_config.ARGS) == 7.5


def test_shutdown_then_init_reforms_clean():
    """shutdown() tears down heartbeat + mesh + start-time so init()
    REFORMS instead of attaching to stale state (satellite 2)."""
    heartbeat.monitor.start(interval_s=30)
    h2o3_tpu.shutdown()
    assert not heartbeat.monitor.running
    assert mesh_mod._GLOBAL_MESH is None
    assert not cloud._STARTED
    info = h2o3_tpu.init(backend="cpu")
    assert cloud._STARTED
    assert info["cloud_size"] == 8 and info["cloud_healthy"]
    assert 0 <= info["cloud_uptime_ms"] < 60_000


def test_cluster_info_uptime_is_a_delta():
    """Satellite 1 regression: cloud_uptime_ms reported epoch millis
    (~1.7e12); it must be the delta since init()."""
    info = h2o3_tpu.cluster_info()
    assert info["cloud_uptime_ms"] < 24 * 3600 * 1000
    assert info["heartbeat"]["miss_budget"] >= 1
