"""Worker for the pod-global sharded-training acceptance tests (ISSUE
19 — ONE fit data-parallel across every host).

Modes (``sys.argv[5]``):

* ``fit`` — N processes form a cloud; each supplies ONLY its
  ``mesh.owned_rows`` slice to ``Frame.from_numpy_partitioned`` and the
  pod trains one GBM + one GLM over the host-partitioned frame. pid 0
  writes bit-level artifacts (forest digest, float hexes) to `outfile`.
* ``ref`` — ONE process with ``--xla_force_host_platform_device_count=2``
  runs the SAME logical data=2 SPMD program over the legacy replicated
  ingest: the bit-exact reference the ``fit`` pod must match (same mesh
  shape ⇒ same psum tree ⇒ same float addition order).
* ``sigkill`` — both processes start a long global fit; pid 1 SIGKILLs
  itself mid-boost-loop. pid 0's job must FAIL with an infra-classified
  error within one heartbeat window of the loss being observed — no
  hang, no leaked RUNNING job.
* ``bench`` — times the global GBM fit on the partitioned frame and
  reports rows/sec (pid 0), for bench.py's ``globalfit`` config; every
  pid also drops its ``{outfile}.phases.{pid}`` step-profiler split.
* ``profile`` — ISSUE 20: 2-process fit with ONE artificially-delayed
  host (``H2O3TPU_STEPPROF_DELAY_PID``/``_S``); pid 0 queries
  ``GET /3/Models/{id}/profile?cluster=1`` and reports the
  straggler/skew verdict.

Workers that outlive a dead peer exit via ``os._exit`` — the normal
distributed teardown would barrier against the corpse.
"""

import hashlib
import json
import os
import signal
import sys
import time

coord, nproc, pid, outfile = sys.argv[1:5]
mode = sys.argv[5] if len(sys.argv) > 5 else "fit"

os.environ["JAX_PLATFORMS"] = "cpu"
# the reference run folds the pod's device count into one process so
# both runs lower the SAME data=2 SPMD program (bit-parity by program
# identity, not by luck)
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=2"
                           if mode == "ref"
                           else "--xla_force_host_platform_device_count=1")
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
if int(nproc) > 1:
    h2o3_tpu.init(backend="cpu", coordinator_address=coord,
                  num_processes=int(nproc), process_id=int(pid))
else:
    h2o3_tpu.init(backend="cpu")

import numpy as np                            # noqa: E402

from h2o3_tpu.core import recovery as _recovery   # noqa: E402
from h2o3_tpu.models.gbm import GBMEstimator      # noqa: E402
from h2o3_tpu.models.glm import GLMEstimator      # noqa: E402
from h2o3_tpu.parallel import mesh as mesh_mod    # noqa: E402

T0 = time.monotonic()
# deliberately NOT a multiple of hosts*devices: the padded tail must be
# invisible in every statistic (the ISSUE 19 padding-parity contract)
N_ROWS = 4001
# stopping_rounds enables the per-chunk scorer (scoring history is an
# acceptance artifact); tolerance 0 never actually stops a 10-tree fit
GBM_PARAMS = dict(ntrees=10, max_depth=4, seed=3, stopping_rounds=3,
                  stopping_tolerance=0.0, score_tree_interval=5)


def mark(stage):
    print(f"WORKER-{pid}-STAGE {time.monotonic() - T0:7.2f}s {stage}",
          flush=True)


def build_arrays(n=N_ROWS):
    r = np.random.RandomState(11)
    a = r.randn(n)
    b = r.randn(n)
    g = r.choice(["u", "v", "w"], n)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(n) * 0.3
    return {"a": a, "b": b, "g": g, "y": y}


def make_frame():
    """Partitioned ingest from ONLY this process's owned rows (fit /
    sigkill / bench modes) or legacy replicated ingest (ref mode)."""
    full = build_arrays()
    if mode == "ref":
        return h2o3_tpu.Frame.from_numpy(full, categorical=["g"])
    lo, hi = mesh_mod.owned_rows(N_ROWS, block=8)
    local = {k: v[lo:hi] for k, v in full.items()}
    mark(f"owned rows [{lo}, {hi})")
    return h2o3_tpu.Frame.from_numpy_partitioned(
        local, N_ROWS, categorical=["g"])


def forest_digest(forest):
    """blake2b over every stacked tree array — bit-exact forest id.
    Snapshots via recovery.snapshot_host: forest leaves are replicated
    global arrays on a multi-process mesh (not fully addressable)."""
    h = hashlib.blake2b(digest_size=16)
    for name, arr in zip(forest._fields, forest):
        v = np.asarray(_recovery.snapshot_host(arr))
        h.update(name.encode())
        h.update(str(v.dtype).encode())
        h.update(str(v.shape).encode())
        h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


def run_fit():
    fr = make_frame()
    part_cols = sum(1 for c in fr._cols.values()
                    if getattr(c, "_part_cache", None) is not None)
    if int(pid) == 1 or int(nproc) == 1:
        # asymmetric single-process host access (the REST-handler /
        # scheduled-item contract): ONLY this process reads the host
        # view, so it must come from the ingest-seeded cache — a lazy
        # cross-process gather here would wedge the pod (peers are not
        # at this program point)
        hv = fr.col("a").host_view()
        assert hv.shape[0] == N_ROWS and \
            np.array_equal(hv, build_arrays()["a"]), "host_view parity"
        mark("asymmetric host_view ok")
    mark(f"frame up ({part_cols} partitioned cols); training")
    gbm = GBMEstimator(**GBM_PARAMS).train(fr, y="y")
    glm = GLMEstimator(family="gaussian", lambda_=0.0).train(fr, y="y")
    pred = gbm.predict(fr).col("predict").to_numpy()
    gather_keys = 0
    if int(nproc) > 1:
        # the off-mode devolution must not leave dataset-sized gather
        # blobs resident in the coordination service; queried AFTER
        # training so the peer's post-barrier deletes (issued right
        # after its allgather_rows read) have long landed
        from h2o3_tpu.frame import partition as part_mod
        gather_keys = len(list(part_mod._client().key_value_dir_get(
            part_mod.KV_PREFIX + "gather/")))
    result = {
        "mode": mode,
        "process_count": len({d.process_index for d in jax.devices("cpu")}),
        "mesh_data": mesh_mod.get_mesh().shape[mesh_mod.DATA_AXIS],
        "partitioned_cols": part_cols,
        "gather_keys_resident": gather_keys,
        "forest_digest": forest_digest(gbm.forest),
        "gbm_mse_hex": float(gbm.training_metrics["MSE"]).hex(),
        "scoring_history": [
            {"ntrees": int(e["ntrees"]),
             "deviance_hex": float(e["deviance"]).hex()}
            for e in gbm.output["scoring_history"]],
        "gbm_pred_head_hex": [float(v).hex() for v in pred[:32]],
        "glm_coefficients": {k: float(v)
                             for k, v in glm.coefficients.items()},
    }
    if int(pid) == 0:
        with open(outfile, "w") as f:
            json.dump(result, f)
    print(f"WORKER-{pid}-DONE", flush=True)
    h2o3_tpu.shutdown()


def run_bench():
    fr = make_frame()
    ntrees = int(os.environ.get("H2O3TPU_GLOBALFIT_BENCH_NTREES", "30"))
    GBMEstimator(ntrees=5, max_depth=4, seed=3).train(fr, y="y")  # warmup
    t0 = time.time()
    GBMEstimator(ntrees=ntrees, max_depth=4, seed=3).train(fr, y="y")
    dt = max(time.time() - t0, 1e-9)
    # EVERY pid reports its own phase split (telemetry/stepprof.py):
    # bench.py folds these into the per-host compute/collective/host
    # table printed next to the rows/sec line
    try:
        from h2o3_tpu.telemetry import stepprof
        ph = stepprof.last_fit_phases("gbm")
        ph["proc"] = int(pid)
        with open(f"{outfile}.phases.{pid}", "w") as f:
            json.dump(ph, f)
    except Exception as e:   # noqa: BLE001 - table is best-effort
        print(f"WORKER-{pid}-PHASES-FAILED {e}", flush=True)
    if int(pid) == 0:
        with open(outfile, "w") as f:
            json.dump({"mode": mode, "rows_per_sec": N_ROWS * ntrees / dt,
                       "seconds": dt, "ntrees": ntrees,
                       "nrows": N_ROWS}, f)
    print(f"WORKER-{pid}-DONE", flush=True)
    h2o3_tpu.shutdown()


def run_profile():
    """ISSUE 20 acceptance leg: a 2-process global GBM fit with ONE
    artificially-delayed host; ``GET /3/Models/{id}/profile?cluster=1``
    on pid 0 must name the slow host as the straggler and show the fast
    host's collective-wait share rising (it waits at the per-chunk
    barrier probe while the slow host sleeps)."""
    import urllib.request
    from h2o3_tpu.telemetry import cluster, stepprof

    delay_pid = int(os.environ.get("H2O3TPU_STEPPROF_DELAY_PID", "1"))
    delay_s = os.environ.get("H2O3TPU_STEPPROF_DELAY_S", "0.25")
    fr = make_frame()
    # warmup fit with the SAME ntrees: chunk programs compile per chunk
    # size, so an equal-shape warmup makes the profiled fit's compute
    # phase pure chunk work, not XLA compile (identical on every host —
    # it would bury the skew the delay is meant to produce)
    params = dict(GBM_PARAMS, ntrees=30)
    GBMEstimator(**params).train(fr, y="y")
    if int(pid) == delay_pid:
        # per-host injection: the pod-wide env would slow EVERY host
        os.environ["H2O3TPU_STEPPROF_DELAY"] = delay_s
        mark(f"injecting {delay_s}s/chunk delay on pid {pid}")
    mark("warm; training profiled global fit")
    gbm = GBMEstimator(**params).train(fr, y="y")
    os.environ.pop("H2O3TPU_STEPPROF_DELAY", None)
    local = stepprof.profile_for(gbm.key)
    ok = cluster.publish(force=True)
    mark(f"profile published ok={ok}; syncing")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("stepprof-profile-published")

    if int(pid) == 0:
        # the peer's snapshot already sits in the coordination KV (the
        # publish above), so only pid 0 needs to stay up for the fetch
        from h2o3_tpu.api.server import start_server
        port = int(os.environ.get("H2O3TPU_PROFILE_PORT", "54661"))
        start_server(port=port, background=True)
        url = (f"http://127.0.0.1:{port}/3/Models/{gbm.key}"
               f"/profile?cluster=1")
        prof = json.loads(urllib.request.urlopen(url, timeout=30).read())
        from h2o3_tpu.telemetry.registry import REGISTRY
        gauges = {g.name: g.value
                  for g in REGISTRY.find("pod_step_skew_ratio")
                  + REGISTRY.find("pod_straggler_host")}
        result = {
            "mode": mode,
            "delay_pid": delay_pid,
            "model_key": gbm.key,
            "local_phases": local["phases"],
            "chunks": local["chunks"],
            "cluster": prof.get("cluster"),
            "gauges": gauges,
        }
        with open(outfile, "w") as f:
            json.dump(result, f)
    # second barrier BEFORE teardown: shutdown() sweeps this node's KV
    # snapshot first thing, so pid 1 racing into it would delete the
    # very entry pid 0's cluster fetch above still needs to read
    multihost_utils.sync_global_devices("stepprof-profile-fetched")
    print(f"WORKER-{pid}-DONE", flush=True)
    h2o3_tpu.shutdown()


def run_sigkill():
    from h2o3_tpu.core import heartbeat, watchdog
    from h2o3_tpu.core.job import RUNNING, list_jobs
    fr = make_frame()
    mark("frame up; starting long global fit")
    est = GBMEstimator(ntrees=4000, max_depth=5, seed=1)
    est.train(fr, y="y", background=True)
    job = est._job
    deadline = time.monotonic() + 120
    while job.progress <= 0.0 and job.status == RUNNING \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    mark(f"fit in boost loop (progress={job.progress:.3f})")

    if int(pid) == 1:
        # victim: die mid-collective, the unclean way a host dies
        print(f"WORKER-{pid}-KILLING-SELF", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    # survivor (pid 0): the heartbeat monitor flags the dead peer; the
    # fit must FAIL at the next chunk boundary (or the gloo collective
    # errors out first — either way classified infra, never a hang)
    window_s = heartbeat.monitor.interval_s * heartbeat.monitor.miss_budget
    t_lost = None
    while time.monotonic() < deadline:
        if 1 in heartbeat.dead_peers() or not heartbeat.monitor.healthy():
            t_lost = time.monotonic()
            break
        if job.status != RUNNING:
            # gloo surfaced the death before the heartbeat did
            t_lost = time.monotonic()
            break
        time.sleep(0.02)
    mark("peer loss observed; waiting for the job to fail fast")
    job.join(60)
    fail_after_loss_s = (time.monotonic() - t_lost) if t_lost else None
    running_leaks = [j["description"] for j in list_jobs()
                     if j["status"] == RUNNING]
    exc = job.exception or ""
    result = {
        "mode": mode,
        "job_status": job.status,
        "job_exception": exc[-800:],
        "infra_classified": ("CloudUnhealthyError" in exc
                             or any(s in exc
                                    for s in watchdog.INFRA_SIGNS)),
        "heartbeat_window_s": window_s,
        "fail_after_loss_s": fail_after_loss_s,
        "running_leaks": running_leaks,
    }
    with open(outfile, "w") as f:
        json.dump(result, f)
    print(f"WORKER-{pid}-DONE", flush=True)
    os._exit(0)   # teardown would barrier against the dead peer


if mode in ("fit", "ref"):
    run_fit()
elif mode == "bench":
    run_bench()
elif mode == "sigkill":
    run_sigkill()
elif mode == "profile":
    run_profile()
else:
    raise SystemExit(f"unknown mode {mode!r}")
