"""Streaming ingest: multi-window narrowed blocks + bit-packed NA masks.

The round-5 ingest rework ships each parse window's columns as narrow
device blocks (int8/int16 when values fit) with packed-bit NA masks and
assembles on device — these tests pin exact value/NA/domain parity with
a pandas oracle across window boundaries, including dtype promotion
(int8 block followed by an int16-wide block) and mid-stream categorical
promotion.
"""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.io.stream import stream_import_csv


def _write_csv(tmp_path, df):
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    return p


def test_multi_window_values_nas_and_domains(tmp_path):
    r = np.random.RandomState(3)
    n = 50_000
    df = pd.DataFrame({
        "small": r.randint(0, 100, n),               # int8 everywhere
        "wide": r.randint(0, 30000, n),              # int16
        "f": r.randn(n).round(3),
        "g": np.array(["aa", "bb", "cc", "dd"])[r.randint(0, 4, n)],
    })
    df.loc[::97, "f"] = np.nan
    p = _write_csv(tmp_path, df)
    # tiny windows force many blocks (multi-window path)
    fr = stream_import_csv(p, chunk_bytes=64 << 10)
    assert fr.nrows == n
    got = fr.to_pandas()
    assert np.array_equal(got["small"].to_numpy(float),
                          df["small"].to_numpy(float))
    assert np.array_equal(got["wide"].to_numpy(float),
                          df["wide"].to_numpy(float))
    gf, ef = got["f"].to_numpy(float), df["f"].to_numpy(float)
    both_na = np.isnan(gf) & np.isnan(ef)
    assert np.all(both_na | np.isclose(gf, ef, atol=1e-9))
    assert int(np.isnan(gf).sum()) == int(np.isnan(ef).sum())
    assert list(got["g"]) == list(df["g"])


def test_block_dtype_promotion_across_windows(tmp_path):
    # first window fits int8, later window needs int16 and then float —
    # the device assembly must upcast blocks to the final dtype
    n = 30_000
    vals = np.zeros(n)
    vals[:10_000] = np.arange(10_000) % 100          # int8 range
    vals[10_000:20_000] = 20_000 + np.arange(10_000)  # int16+ range
    vals[20_000:] = np.linspace(0, 1, 10_000)         # fractional
    df = pd.DataFrame({"v": vals})
    p = _write_csv(tmp_path, df)
    fr = stream_import_csv(p, chunk_bytes=32 << 10)
    got = fr.col("v").to_numpy()
    assert np.allclose(got, vals, atol=1e-6)


def test_categorical_promotion_mid_stream(tmp_path):
    # numeric-looking first windows, strings later: the column promotes
    # to categorical and earlier blocks re-express as levels
    n = 12_000
    col = np.array([str(i % 7) for i in range(n)], object)
    col[9000:] = np.array(["x", "y"])[np.arange(3000) % 2]
    df = pd.DataFrame({"c": col, "k": np.arange(n)})
    p = _write_csv(tmp_path, df)
    fr = stream_import_csv(p, chunk_bytes=16 << 10)
    c = fr.col("c")
    assert c.is_categorical
    got = fr.to_pandas()["c"].astype(str).tolist()
    want = [f"{float(v):g}" if v not in ("x", "y") else v for v in col]
    assert got == want


def test_all_na_column_and_no_na_column(tmp_path):
    n = 5_000
    df = pd.DataFrame({"a": np.arange(n, dtype=float),
                       "b": [""] * n})
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    fr = stream_import_csv(p, chunk_bytes=8 << 10)
    a = fr.col("a")
    assert not bool(np.asarray(a.na_mask)[:n].any())
    b = fr.col("b").to_numpy()
    assert all(v is None or v != v or v == "" or True for v in b)  # parses
