"""Lazy file-backed frames (water/fvec FileVec role).

import_file(lazy=True) registers a stub with header metadata but parses
nothing; the first DKV.get materializes; the Cleaner evicts unmutated
file-backed frames straight back to their stub (no spill npz)."""

import numpy as np

from h2o3_tpu.core.cleaner import cleaner
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.io.lazy import FileBackedFrame
from h2o3_tpu.io.parser import import_file


def _csv(tmp_path, n=400):
    p = str(tmp_path / "lazy.csv")
    r = np.random.RandomState(0)
    with open(p, "w") as f:
        f.write("a,b,c\n")
        for i in range(n):
            f.write(f"{r.randn():.5f},{r.randint(0, 5)},lvl{r.randint(3)}\n")
    return p


def test_lazy_import_defers_parse(tmp_path):
    p = _csv(tmp_path)
    stub = import_file(p, destination_frame="lazyfr", lazy=True)
    assert isinstance(stub, FileBackedFrame)
    assert stub.names == ["a", "b", "c"]
    assert stub.nrows == 400
    assert isinstance(DKV.get_raw("lazyfr"), FileBackedFrame)
    fr = DKV.get("lazyfr")                 # first touch materializes
    assert isinstance(fr, Frame)
    assert fr.nrows == 400 and fr.names == ["a", "b", "c"]
    assert isinstance(DKV.get_raw("lazyfr"), Frame)
    DKV.remove("lazyfr")


def test_cleaner_evicts_to_source_stub(tmp_path):
    p = _csv(tmp_path)
    fr = import_file(p, destination_frame="evictfr")
    assert fr._source_paths == [p]
    stub = cleaner.spill("evictfr")
    assert isinstance(stub, FileBackedFrame)     # no npz written
    assert isinstance(DKV.get_raw("evictfr"), FileBackedFrame)
    back = DKV.get("evictfr")                    # re-parse on touch
    assert isinstance(back, Frame)
    assert np.allclose(back.col("a").to_numpy(), fr.col("a").to_numpy())
    DKV.remove("evictfr")


def test_mutated_frame_not_evicted_to_source(tmp_path):
    p = _csv(tmp_path)
    fr = import_file(p, destination_frame="mutfr")
    fr.rename_columns(["x", "y", "z"])
    assert fr._source_paths is None
    out = cleaner.spill("mutfr")
    # falls back to a real spill (npz ice copy), not the source stub
    assert not isinstance(out, FileBackedFrame)
    restored = DKV.get("mutfr")
    assert restored.names == ["x", "y", "z"]
    DKV.remove("mutfr")


def test_lazy_parquet_metadata(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    t = pa.table({"q": np.arange(123, dtype=float)})
    p = str(tmp_path / "l.parquet")
    pq.write_table(t, p)
    stub = import_file(p, lazy=True)
    assert stub.names == ["q"] and stub.nrows == 123
    fr = DKV.get(stub.key)
    assert fr.nrows == 123
    DKV.remove(stub.key)
