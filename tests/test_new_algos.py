"""TargetEncoder, Word2Vec, PSVM, Aggregator, Infogram, SegmentModels."""

import numpy as np
import pytest

import h2o3_tpu
from tests.conftest import make_classification


# ---------------------------------------------------------------- te

def _te_frame(n=2000, seed=0):
    r = np.random.RandomState(seed)
    g = np.array(["a", "b", "c", "d"], object)[r.randint(0, 4, n)]
    base = {"a": 0.2, "b": 0.5, "c": 0.7, "d": 0.9}
    p = np.asarray([base[v] for v in g])
    y = (r.rand(n) < p).astype(int)
    folds = r.randint(0, 3, n).astype(float)
    return h2o3_tpu.Frame.from_numpy(
        {"g": g, "x": r.randn(n), "fold": folds,
         "y": np.array(["no", "yes"], object)[y]},
        categorical=["g", "y"])


def test_target_encoder_plain():
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    fr = _te_frame()
    m = TargetEncoderEstimator(noise=0.0).train(fr, y="y", x=["g"])
    out = m.transform(fr)
    assert "g_te" in out.names
    te = out.col("g_te").to_numpy()
    g = fr.col("g").to_numpy()
    # level means should be close to the generating probabilities
    m_a = te[np.asarray(fr.col("g").domain)[g.astype(int)] == "a"].mean()
    m_d = te[np.asarray(fr.col("g").domain)[g.astype(int)] == "d"].mean()
    assert m_a < 0.35 and m_d > 0.75


def test_target_encoder_blending_pulls_to_prior():
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    fr = _te_frame()
    plain = TargetEncoderEstimator(noise=0.0).train(fr, y="y", x=["g"])
    blend = TargetEncoderEstimator(noise=0.0, blending=True,
                                   inflection_point=1e6).train(
        fr, y="y", x=["g"])
    prior = blend.output["prior"]
    tb = blend.transform(fr).col("g_te").to_numpy()
    tp = plain.transform(fr).col("g_te").to_numpy()
    # huge k → encodings collapse to the prior
    assert np.abs(tb - prior).max() < 0.02
    assert np.abs(tp - prior).max() > 0.1


def test_target_encoder_kfold_excludes_own_fold():
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    fr = _te_frame()
    m = TargetEncoderEstimator(noise=0.0, data_leakage_handling="kfold",
                               fold_column="fold").train(fr, y="y", x=["g"])
    tr = m.transform(fr, as_training=True)
    ho = m.transform(fr, as_training=False)
    a = tr.col("g_te").to_numpy()
    b = ho.col("g_te").to_numpy()
    assert not np.allclose(a, b)          # leakage handling changed values
    assert np.abs(a - b).max() < 0.2      # but not wildly


def test_target_encoder_loo():
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    fr = _te_frame(n=300)
    m = TargetEncoderEstimator(noise=0.0, data_leakage_handling="loo").train(
        fr, y="y", x=["g"])
    tr = m.transform(fr, as_training=True).col("g_te").to_numpy()
    ho = m.transform(fr, as_training=False).col("g_te").to_numpy()
    assert not np.allclose(tr, ho)


# ---------------------------------------------------------------- w2v

def test_word2vec_synonyms_and_transform():
    from h2o3_tpu.models.word2vec import Word2VecEstimator
    r = np.random.RandomState(0)
    # two topic clusters; words co-occur within topic
    topics = [["cat", "dog", "pet", "fur"], ["car", "road", "wheel", "drive"]]
    words = []
    for _ in range(400):
        t = topics[r.randint(2)]
        for w in r.choice(t, 6):
            words.append(w)
        words.append(None)   # sentence boundary
    fr = h2o3_tpu.Frame.from_numpy(
        {"words": np.asarray(words, dtype=object)}, categorical=["words"])
    m = Word2VecEstimator(vec_size=16, epochs=10, min_word_freq=2,
                          window_size=3, sent_sample_rate=0.0,
                          seed=42).train(fr)
    assert m.output["vocab_size"] == 8
    syn = m.find_synonyms("cat", count=3)
    assert len(syn) == 3
    # same-topic words should dominate the synonym list
    assert sum(1 for w in syn if w in topics[0]) >= 2
    # transform AVERAGE: one row per sentence
    emb = m.transform(fr, aggregate_method="AVERAGE")
    assert emb.nrows == 400   # NA-terminated input → one row per sentence
    wv = m.to_frame()
    assert wv.nrows == 8 and wv.ncols == 17


# ---------------------------------------------------------------- psvm

def test_psvm_separates_blobs():
    from h2o3_tpu.models.psvm import PSVMEstimator
    r = np.random.RandomState(1)
    n = 600
    X = np.concatenate([r.randn(n // 2, 2) + 2.0, r.randn(n // 2, 2) - 2.0])
    y = np.array(["pos"] * (n // 2) + ["neg"] * (n // 2), dtype=object)
    perm = r.permutation(n)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x0": X[perm, 0], "x1": X[perm, 1], "y": y[perm]},
        categorical=["y"])
    m = PSVMEstimator(hyper_param=1.0, max_iterations=30).train(fr, y="y")
    assert m.training_metrics["AUC"] > 0.95
    assert 0 < m.output["svs_count"] < n
    preds = m.predict(fr)
    assert "decision_function" in preds.names


def test_psvm_rejects_nonbinary():
    from h2o3_tpu.models.psvm import PSVMEstimator
    fr = h2o3_tpu.Frame.from_numpy({"x": np.arange(10.0),
                                    "y": np.arange(10.0)})
    with pytest.raises(ValueError):
        PSVMEstimator().train(fr, y="y")


# ---------------------------------------------------------------- aggregator

def test_aggregator_compresses():
    from h2o3_tpu.models.aggregator import AggregatorEstimator
    r = np.random.RandomState(0)
    X = r.randn(3000, 3)
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    m = AggregatorEstimator(target_num_exemplars=100,
                            rel_tol_num_exemplars=0.7).train(fr)
    agg = m.aggregated_frame
    assert agg.nrows <= 100
    assert agg.nrows >= 10
    counts = agg.col("counts").to_numpy()
    assert counts.sum() == 3000   # every row absorbed exactly once


# ---------------------------------------------------------------- infogram

def test_infogram_core_ranks_signal():
    from h2o3_tpu.models.infogram import InfogramEstimator
    X, y = make_classification(n=1500, f=6, informative=2)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.array(["no", "yes"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    m = InfogramEstimator(ntrees=5, max_depth=3, seed=1).train(fr, y="y")
    table = m.output["infogram_table"]
    top2 = {r["column"] for r in table[:2]}
    assert top2 <= {"x0", "x1", "x2", "x3"}   # informative features rank high
    sf = m.get_admissible_score_frame()
    assert sf.nrows == 6


def test_infogram_fair_flags_proxy():
    from h2o3_tpu.models.infogram import InfogramEstimator
    r = np.random.RandomState(0)
    n = 1500
    prot = r.randn(n)                 # "protected" numeric attribute
    proxy = prot + 0.1 * r.randn(n)   # near-copy of protected
    clean = r.randn(n)                # independent signal
    logit = prot * 1.5 + clean * 1.5
    y = (r.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"prot": prot, "proxy": proxy, "clean": clean,
         "y": np.array(["no", "yes"], object)[y]}, categorical=["y"])
    m = InfogramEstimator(protected_columns=["prot"], ntrees=5, max_depth=3,
                          seed=1).train(fr, y="y")
    t = {r["column"]: r for r in m.output["infogram_table"]}
    # clean adds information beyond protected; proxy adds ~none
    assert t["clean"]["cmi"] > t["proxy"]["cmi"]


# ---------------------------------------------------------------- segments

def test_train_segments():
    from h2o3_tpu.ml.segments import train_segments
    from h2o3_tpu.models.gbm import GBMEstimator
    X, y = make_classification(n=1200, f=4)
    seg = np.array(["s1", "s2"], object)[(np.arange(1200) % 2)]
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["seg"] = seg
    cols["y"] = np.array(["no", "yes"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["seg", "y"])
    sm = train_segments(GBMEstimator, dict(ntrees=3, max_depth=3, seed=1),
                        fr, segment_columns=["seg"], y="y")
    assert len(sm.results) == 2
    assert all(r["status"] == "SUCCEEDED" for r in sm.results)
    res = sm.as_frame()
    assert res.nrows == 2
    # each segment model is retrievable and scores
    from h2o3_tpu.core.kv import DKV
    m0 = DKV.get(sm.results[0]["model_key"])
    assert m0.training_metrics["AUC"] > 0.5
