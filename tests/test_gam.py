"""GAM tests — smooth recovery + pyunit-style behavior checks
(h2o-py/tests/testdir_algos/gam role)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gam import GAMEstimator, bspline_basis, curvature_penalty


def test_bspline_partition_of_unity():
    x = np.linspace(0.0, 1.0, 200)
    B = bspline_basis(x, np.linspace(0, 1, 8))
    np.testing.assert_allclose(B.sum(axis=1), 1.0, atol=1e-9)
    assert (B >= -1e-12).all()
    # NaN rows get a zero basis
    Bn = bspline_basis(np.array([np.nan, 0.5]), np.linspace(0, 1, 8))
    assert Bn[0].sum() == 0.0 and Bn[1].sum() == pytest.approx(1.0)


def test_curvature_penalty_annihilates_linear():
    S = curvature_penalty(10)
    lin = np.arange(10, dtype=float)
    assert lin @ S @ lin == pytest.approx(0.0)
    quad = lin ** 2
    assert quad @ S @ quad > 0


@pytest.fixture(scope="module")
def wiggly():
    r = np.random.RandomState(4)
    n = 800
    x = np.sort(r.uniform(-3, 3, n))
    lin = r.randn(n)
    f = np.sin(1.7 * x) + 0.5 * lin
    y = f + r.randn(n) * 0.15
    return Frame.from_numpy({"x": x, "lin": lin, "y": y}), x, lin, f


def test_gam_gaussian_fits_nonlinearity(wiggly):
    fr, x, lin, f = wiggly
    m = GAMEstimator(gam_columns=["x"], num_knots=[12], scale=[0.01]).train(
        fr, y="y", x=["lin", "x"])
    pred = m.predict(fr).col("predict").to_numpy()
    resid = pred - f
    assert np.sqrt(np.mean(resid ** 2)) < 0.15   # captures sin shape
    # a pure-linear GLM cannot get close
    from h2o3_tpu.models.glm import GLMEstimator
    g = GLMEstimator().train(fr, y="y", x=["lin", "x"])
    glm_rmse = np.sqrt(np.mean((g.predict(fr).col("predict").to_numpy() - f) ** 2))
    assert glm_rmse > 0.4


def test_gam_binomial(wiggly):
    fr, x, lin, f = wiggly
    r = np.random.RandomState(5)
    pr = 1.0 / (1.0 + np.exp(-2.0 * np.sin(1.5 * x)))
    yb = (r.rand(len(x)) < pr).astype(object)
    yb = np.where(yb == 1, "yes", "no").astype(object)
    fr2 = Frame.from_numpy({"x": x, "lin": lin, "cls": yb},
                           categorical=["cls"])
    m = GAMEstimator(gam_columns=["x"], family="binomial",
                     num_knots=[10]).train(fr2, y="cls", x=["lin", "x"])
    assert m.training_metrics["AUC"] > 0.75


def test_gam_scoring_new_frame(wiggly):
    fr, x, lin, f = wiggly
    m = GAMEstimator(gam_columns=["x"], num_knots=[12]).train(
        fr, y="y", x=["lin", "x"])
    # new frame, different row count (+ padding), values beyond knot range
    xs = np.linspace(-4, 4, 101)
    fr2 = Frame.from_numpy({"x": xs, "lin": np.zeros(101)})
    pred = m.predict(fr2).col("predict").to_numpy()
    assert pred.shape == (101,)
    assert np.isfinite(pred).all()


def test_gam_requires_gam_columns():
    with pytest.raises(ValueError):
        GAMEstimator()
    with pytest.raises(ValueError):
        GAMEstimator(gam_columns=["x"], bogus=1)
