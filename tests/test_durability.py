"""Durable data plane (ISSUE 18): frame lineage, mirrored shards,
peer-loss rebuild, and whole-cloud checkpoint/restore.

Tiers:
* pure state machine (DurabilityBoard) + blob codec — jax-free logic;
* in-process lineage / mirror / rebuild / DataLostError contracts under
  the session's 8-virtual-device cloud;
* REST surface: lineage on ``GET /3/Frames/{id}``, ``POST
  /3/CloudCheckpoint``, the 410 DATA_LOST mapping;
* whole-cloud checkpoint → restore, in-process and into a FRESH
  process via ``init(restore_dir=)``;
* the 2-process SIGKILL acceptance test (tests/durability_worker.py):
  kill a peer mid-GBM-fit, survivor rebuilds its frames from mirror and
  resumes the fit bit-identical to an undisturbed reference.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.core import durability
from h2o3_tpu.core.durability import DataLostError, DurabilityBoard
from h2o3_tpu.core.kv import DKV

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "durability_worker.py")
WORKER_TIMEOUT_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))


@pytest.fixture()
def dur_env(monkeypatch, tmp_path):
    """Mirror mode scoped to one test: private mirror dir, clean local
    durability state on both sides."""
    durability.reset()
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "mirror")
    monkeypatch.setenv("H2O3TPU_DUR_DIR", str(tmp_path / "mirror"))
    yield str(tmp_path / "mirror")
    durability.reset()
    durability.sweep_debris()


def _small_frame(seed=0, n=200):
    r = np.random.RandomState(seed)
    return h2o3_tpu.Frame.from_numpy(
        {"a": r.randn(n), "b": r.randn(n), "y": r.randn(n)})


# ------------------------------------------------ knob + typed error


def test_mode_knob_defaults_off(monkeypatch):
    monkeypatch.delenv("H2O3TPU_DATA_DURABILITY", raising=False)
    assert durability.mode() == "off"
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "bogus")
    assert durability.mode() == "off"
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", " Mirror ")
    assert durability.mode() == "mirror"
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "lineage")
    assert durability.mode() == "lineage"


def test_data_lost_error_is_typed_and_non_retryable():
    e = DataLostError("frame_x", "peer died")
    assert e.key == "frame_x"
    assert str(e).startswith("DATA_LOST:")
    assert isinstance(e, RuntimeError)
    from h2o3_tpu.core import watchdog
    assert DataLostError in watchdog.NON_RETRYABLE


def test_blob_codec_roundtrip():
    data = os.urandom(300_000) + b"\x00" * 50_000
    enc = durability._encode(data)
    assert isinstance(enc, str)
    assert durability._decode(enc) == data


# ------------------------------------------- DurabilityBoard machine


def test_board_plans_mirror_over_lineage_on_least_loaded():
    b = DurabilityBoard([0, 1, 2])
    b.register("f1", pid=1, mirrored=True, lineage=True)
    b.register("f2", pid=1, mirrored=False, lineage=True)
    b.register("f3", pid=0, mirrored=True)
    plan = b.on_dead(1, loads={0: 5.0, 2: 1.0})
    # only pid 1's keys are planned; mirror preferred; home = least load
    assert plan == [("f1", 2, "mirror"), ("f2", 2, "lineage")]
    assert b.under_replicated() == ["f1", "f2"]
    assert not b.complete()
    for key, target, _src in plan:
        b.on_rebuilt(key, target)
    assert b.complete()
    assert b.home("f1") == 2 and b.home("f3") == 0
    assert b.on_dead(1) == []          # idempotent per pid


def test_board_marks_unrecoverable_keys_lost():
    b = DurabilityBoard([0, 1])
    b.register("gone", pid=1, mirrored=False, lineage=False)
    assert b.on_dead(1) == []
    assert b.lost() == ["gone"]
    assert b.complete()                # lost keys are terminal, not pending
    with pytest.raises(ValueError):
        b.register("late", pid=1)      # dead pids cannot home keys
    with pytest.raises(ValueError):
        b.on_rebuilt("gone", 1)


# --------------------------------------------------- lineage records


def test_upload_and_derived_lineage(monkeypatch):
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "lineage")
    durability.reset()
    try:
        fr = _small_frame()
        lin = durability.lineage_of(fr)
        assert lin["kind"] == "upload"
        assert not lin["rebuildable_from_lineage"]
        sub = fr[["a", "y"]]
        dlin = durability.lineage_of(sub)
        assert dlin["kind"] == "derived"
        assert dlin["parent"] == fr.key
        assert dlin["ops"] == [{"op": "select",
                                "params": {"columns": ["a", "y"]}}]
        # upload-rooted derived frames are NOT lineage-rebuildable
        assert not dlin["rebuildable_from_lineage"]
        with pytest.raises(DataLostError):
            durability.rebuild_from_lineage("k", dlin)
    finally:
        durability.reset()


def test_source_lineage_rebuilds_bit_identical(monkeypatch, tmp_path):
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "lineage")
    durability.reset()
    csv = tmp_path / "src.csv"
    r = np.random.RandomState(3)
    with open(csv, "w") as f:
        f.write("a,b,y\n")
        for _ in range(120):
            f.write(f"{r.randn():.9f},{r.randn():.9f},{r.randn():.9f}\n")
    try:
        fr = h2o3_tpu.import_file(str(csv))
        key = fr.key
        lin = durability.lineage_of(fr)
        assert lin["kind"] == "source"
        assert lin["rebuildable_from_lineage"]
        assert lin["paths"] == [str(csv)]
        assert lin.get("parse_plan", {}).get("format") == "csv"
        assert lin.get("format_digest") == [durability.file_digest(str(csv))]
        want = durability.frame_digest(fr)
        DKV.remove(key)
        rebuilt = durability.rebuild_from_lineage(key, lin)
        assert rebuilt.key == key and key in DKV
        assert durability.frame_digest(rebuilt) == want
        # a deleted source file makes the chain unreplayable — typed
        DKV.remove(key)
        os.unlink(csv)
        with pytest.raises(DataLostError):
            durability.rebuild_from_lineage(key, lin)
    finally:
        durability.reset()


# ------------------------------------------- mirroring + rebuild


def test_mirror_write_through_and_rebuild(dur_env):
    fr = _small_frame(seed=11)
    key = fr.key
    st = durability.stats()
    assert key in st["mirrored"] and key in st["registry"]
    assert st["mirrored_bytes"] > 0
    from h2o3_tpu.core import memgov
    assert memgov.governor.mirror_bytes() == st["mirrored_bytes"]
    entry = dict(durability.registry()[key])
    assert entry["gen"] == 1 and os.path.exists(entry["uri"])
    want = entry["digest"]
    # simulate peer loss: drop the frame WITHOUT the deliberate-delete
    # hook (which would take the mirror with it)
    with durability._lock:
        durability._registered.discard(key)
    DKV.remove(key)
    assert key not in DKV
    assert durability.rebuild_frame(key, entry)
    assert key in DKV
    assert durability.frame_digest(DKV.get(key)) == want
    from h2o3_tpu import telemetry
    assert telemetry.counter("frame_rebuilds_total",
                             source="mirror").value >= 1


def test_deliberate_remove_drops_mirror_and_registry(dur_env):
    fr = _small_frame(seed=12)
    key = fr.key
    uri = durability.registry()[key]["uri"]
    assert os.path.exists(uri)
    DKV.remove(key)
    assert key not in durability.registry()
    assert not os.path.exists(uri)
    assert durability.mirrored_bytes() == 0


def test_transient_frames_are_never_mirrored(dur_env):
    fr = _small_frame(seed=13)
    before = set(durability.stats()["registry"])
    with durability.suspended():
        tmp = _small_frame(seed=14)
    assert set(durability.stats()["registry"]) == before
    sl = fr.row_slice(0, 50)
    assert sl.key not in durability.stats()["registry"]
    DKV.remove(tmp.key)
    DKV.remove(sl.key)


def test_unrecoverable_key_fails_typed_not_hung(dur_env):
    key = "frame_without_legs"
    entry = {"pid": 0, "nrows": 1, "ncols": 1}    # no gen, no lineage
    assert not durability.rebuild_frame(key, entry)
    assert key in durability.lost_keys()
    with pytest.raises(DataLostError):
        durability.check_lost(key)
    # the data-access chokepoint raises too — jobs fail fast, never hang
    with pytest.raises(DataLostError):
        DKV.get(key)


def test_kv_transport_blob_roundtrip(dur_env, monkeypatch):
    monkeypatch.setenv("H2O3TPU_DUR_TRANSPORT", "kv")
    fr = _small_frame(seed=15)
    entry = dict(durability.registry()[fr.key])
    assert entry["where"] == "kv"
    entry.setdefault("key", fr.key)
    data = durability.fetch_mirror(entry)
    assert len(data) == entry["nbytes"]
    from h2o3_tpu.io.persist import frame_from_bytes
    with durability.suspended():
        fr2 = frame_from_bytes(data, key="kvrt_check")
    try:
        assert durability.frame_digest(fr2) == entry["digest"]
    finally:
        DKV.remove("kvrt_check")


def test_sweep_debris_and_local_keys(dur_env):
    fr = _small_frame(seed=16)
    live_uri = durability.registry()[fr.key]["uri"]
    d = durability.mirror_dir()
    orphan_tmp = os.path.join(d, "dead.framesnap.tmp")
    orphan_blob = os.path.join(d, "unreg_g1.framesnap")
    for p in (orphan_tmp, orphan_blob):
        with open(p, "wb") as f:
            f.write(b"x")
    assert durability.sweep_debris() == 2
    assert os.path.exists(live_uri)          # referenced blobs survive
    assert not os.path.exists(orphan_tmp)
    assert not os.path.exists(orphan_blob)
    # shutdown contract: this process's registry keys + mirrors go away
    durability.sweep_local_keys()
    assert durability.registry() == {}
    assert not os.path.exists(live_uri)
    DKV.remove(fr.key)


def test_sweep_debris_skips_blobs_when_registry_unreadable(
        dur_env, monkeypatch):
    """A flaky/unreachable KV must read as 'liveness unknowable', not
    'no live blobs' — a sweep then would delete other peers' mirrors
    out from under the rebuild path. Only .tmp debris goes."""
    fr = _small_frame(seed=18)
    d = durability.mirror_dir()
    live_uri = durability.registry()[fr.key]["uri"]
    peer_blob = os.path.join(d, "other_peer_g1.framesnap")
    half_tmp = os.path.join(d, "half.framesnap.tmp")
    for p in (peer_blob, half_tmp):
        with open(p, "wb") as f:
            f.write(b"x")

    class _DownKV:
        def key_value_dir_get(self, prefix):
            raise IOError("kv unreachable")

        def key_value_set(self, *a, **k):
            raise IOError("kv unreachable")

        def key_value_delete(self, *a):
            raise IOError("kv unreachable")

    monkeypatch.setattr(durability, "_kv", lambda: _DownKV())
    assert durability.sweep_debris() == 1        # only the tmp
    assert not os.path.exists(half_tmp)
    assert os.path.exists(peer_blob)             # spared: unknowable
    assert os.path.exists(live_uri)
    monkeypatch.undo()
    DKV.remove(fr.key)


def test_local_kv_delete_is_exact_plus_subtree():
    """Coordination-service directory semantics: deleting 'reg/0/iris'
    must not take 'reg/0/iris_test' (destination_frame keys commonly
    share prefixes) — only the exact key and its 'iris/' subtree."""
    kv = durability._LocalKV()
    kv.key_value_set("reg/0/iris", "a")
    kv.key_value_set("reg/0/iris_test", "b")
    kv.key_value_set("reg/0/iris/child", "c")
    kv.key_value_delete("reg/0/iris")
    assert dict(kv.key_value_dir_get("reg/0/")) == {"reg/0/iris_test": "b"}
    kv.key_value_delete("reg/0/")                # dir form still sweeps
    assert kv.key_value_dir_get("reg/0/") == []


def test_remove_spares_prefix_sharing_registrations(dur_env):
    r = np.random.RandomState(19)
    h2o3_tpu.Frame.from_numpy({"a": r.randn(50)}, key="iris")
    fr2 = h2o3_tpu.Frame.from_numpy({"a": r.randn(50)}, key="iris_test")
    uri2 = durability.registry()["iris_test"]["uri"]
    DKV.remove("iris")
    reg = durability.registry()
    assert "iris" not in reg
    assert "iris_test" in reg                    # registration survives
    assert os.path.exists(uri2)                  # mirror survives
    DKV.remove("iris_test")


def test_derived_lineage_rebuild_spares_recovered_parent(
        dur_env, tmp_path):
    """The maybe_rebuild walk recovers 'train' before 'train_sub'; the
    child's lineage replay must reuse the resident parent — not
    re-import and then delete it (mirror, registry row and all) — and
    the rebuilt child must re-register so it regains durability
    coverage on its new home."""
    csv = tmp_path / "par.csv"
    r = np.random.RandomState(7)
    with open(csv, "w") as f:
        f.write("a,b,y\n")
        for _ in range(80):
            f.write(f"{r.randn():.9f},{r.randn():.9f},{r.randn():.9f}\n")
    fr = h2o3_tpu.import_file(str(csv), destination_frame="train")
    sub = fr[["a", "y"]]
    sub_key = sub.key
    want_parent = durability.frame_digest(fr)
    want_child = durability.frame_digest(sub)
    child_entry = dict(durability.registry()[sub_key])
    # peer-loss style drop of the child (no deliberate-delete hooks),
    # then force the lineage leg: no mirror generation in the entry
    with durability._lock:
        durability._registered.discard(sub_key)
    DKV.remove(sub_key)
    for k in ("gen", "uri", "where", "nbytes", "digest"):
        child_entry.pop(k, None)
    assert durability.rebuild_frame(sub_key, child_entry)
    # the recovered parent survived the child's replay
    assert "train" in DKV
    assert durability.frame_digest(DKV.get("train")) == want_parent
    assert "train" in durability.registry()
    assert "train" in durability.stats()["mirrored"]
    # the child is digest-identical AND regained registry + mirror
    assert durability.frame_digest(DKV.get(sub_key)) == want_child
    assert sub_key in durability.registry()
    assert sub_key in durability.stats()["mirrored"]
    from h2o3_tpu import telemetry
    assert telemetry.counter("frame_rebuilds_total",
                             source="lineage").value >= 1
    DKV.remove(sub_key)
    DKV.remove("train")


def test_derived_lineage_rebuild_with_absent_parent(dur_env, tmp_path):
    """When the parent is genuinely gone the replay re-imports it as a
    suspended temporary: the child comes back digest-identical and the
    temporary leaves no DKV entry, registration, or mirror behind."""
    csv = tmp_path / "par2.csv"
    r = np.random.RandomState(8)
    with open(csv, "w") as f:
        f.write("a,y\n")
        for _ in range(60):
            f.write(f"{r.randn():.9f},{r.randn():.9f}\n")
    fr = h2o3_tpu.import_file(str(csv), destination_frame="train2")
    sub = fr.drop(["a"])
    sub_key = sub.key
    want_child = durability.frame_digest(sub)
    child_entry = dict(durability.registry()[sub_key])
    for key in (sub_key, "train2"):
        with durability._lock:
            durability._registered.discard(key)
            durability._mirrored.pop(key, None)
        durability._kv().key_value_delete(
            f"{durability.KV_PREFIX}reg/0/{key}")
        DKV.remove(key)
    for k in ("gen", "uri", "where", "nbytes", "digest"):
        child_entry.pop(k, None)
    assert durability.rebuild_frame(sub_key, child_entry)
    assert durability.frame_digest(DKV.get(sub_key)) == want_child
    assert sub_key in durability.registry()
    assert "train2" not in DKV                   # temp base removed
    assert "train2" not in durability.registry()
    DKV.remove(sub_key)


def test_lost_verdict_is_cluster_wide_and_registry_survives(
        dur_env, monkeypatch):
    """An unrecoverable key's verdict travels: the LOST marker is
    published through the KV (a peer with a cold local set still fails
    typed), and the dead peer's registry row is kept — rewritten
    ``lost: true`` — so frames_under_replicated keeps counting the
    loss instead of the cloud reporting healthy."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.core import heartbeat
    key = "frame_lost_cluster"
    dead_pid = 7
    entry = {"pid": dead_pid, "nrows": 1, "ncols": 1}
    durability._kv().key_value_set(
        f"{durability.KV_PREFIX}reg/{dead_pid}/{key}", json.dumps(entry))
    monkeypatch.setattr(heartbeat, "dead_peers", lambda: [dead_pid])
    monkeypatch.setattr(heartbeat, "healthy_peers", lambda: [0])
    durability._last_rebuild = 0.0
    assert durability.maybe_rebuild() == 0
    # verdict is cluster-wide: wipe the local cache, check_lost still
    # fails typed off the published marker
    with durability._lock:
        durability._lost.discard(key)
    with pytest.raises(DataLostError):
        durability.check_lost(key)
    assert key in durability.lost_keys()
    # the loss record survives in the registry and feeds the SLO gauge
    reg = durability.registry()
    assert reg[key].get("lost") is True
    assert telemetry.gauge("frames_under_replicated").value >= 1
    # later rounds skip the terminal row instead of retrying forever
    durability._last_rebuild = 0.0
    assert durability.maybe_rebuild() == 0
    assert durability.registry()[key].get("lost") is True
    # deliberate removal retires the verdict everywhere
    DKV.remove(key)
    assert key not in durability.lost_keys()
    durability.check_lost(key)                   # no longer raises
    telemetry.gauge("frames_under_replicated").set(0)


# ----------------------------------------------------- SLO + metrics


def test_data_durability_slo_rule():
    from h2o3_tpu import telemetry
    from h2o3_tpu.telemetry import slo
    rules = {r.name: r for r in slo.default_rules()}
    assert "data_durability_floor" in rules
    rule = rules["data_durability_floor"]
    telemetry.gauge("frames_under_replicated").set(0)
    ok, _ = rule.check_fn(telemetry.REGISTRY)
    assert ok
    telemetry.gauge("frames_under_replicated").set(2)
    ok, detail = rule.check_fn(telemetry.REGISTRY)
    assert not ok
    telemetry.gauge("frames_under_replicated").set(0)


# ------------------------------------------------------- REST surface


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _post(port, path):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=b"", method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_rest_frame_carries_lineage(port):
    fr = _small_frame(seed=20)
    status, j = _get(port, f"/3/Frames/{fr.key}")
    assert status == 200
    frj = j["frames"][0]
    assert frj["lineage"]["kind"] == "upload"
    assert frj["lineage"]["mirrored"] is False
    assert frj["lineage"]["rebuildable_from_lineage"] is False


def test_rest_data_lost_maps_to_410(port, monkeypatch):
    monkeypatch.setenv("H2O3TPU_DATA_DURABILITY", "mirror")
    key = "frame_gone_410"
    with durability._lock:
        durability._lost.add(key)
    try:
        status, j = _get(port, f"/3/Frames/{key}")
        assert status == 410
        assert "DATA_LOST" in j["msg"]
        assert j["http_status"] == 410
        from h2o3_tpu import telemetry
        assert telemetry.counter("rest_rejected_total",
                                 reason="data_lost").value >= 1
    finally:
        with durability._lock:
            durability._lost.discard(key)


def test_rest_cloud_checkpoint_roundtrip(port, tmp_path):
    fr = _small_frame(seed=21)
    ckpt = tmp_path / "cloudsnap"
    status, manifest = _post(
        port, f"/3/CloudCheckpoint?dir={ckpt}&quiesce_s=5")
    assert status == 200
    assert manifest["magic"] == durability.CLOUD_MAGIC
    assert any(f["key"] == fr.key for f in manifest["frames"])
    assert manifest["jobs_still_running"] == []
    assert os.path.exists(ckpt / "manifest.json")
    # a checkpoint with no dir is a client error (412), not a 500
    status, j = _post(port, "/3/CloudCheckpoint")
    assert status == 412


# --------------------------------------- whole-cloud checkpoint/restore


def test_cloud_checkpoint_restore_bit_identical(tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(31)
    n = 400
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": r.randn(n), "b": r.randn(n),
         "y": r.randn(n)})
    model = GBMEstimator(ntrees=5, max_depth=3, seed=1).train(fr, y="y")
    want_digest = durability.frame_digest(fr)
    want_pred = model.predict(fr).col("predict").to_numpy().copy()
    fkey, mkey = fr.key, model.key
    ckpt = str(tmp_path / "cloudsnap")
    manifest = durability.cloud_checkpoint(ckpt, quiesce_s=5)
    assert {f["key"] for f in manifest["frames"]} >= {fkey}
    assert {m["key"] for m in manifest["models"]} >= {mkey}
    # wipe, then reform — restore digest-verifies every frame itself
    DKV.remove(fkey)
    DKV.remove(mkey)
    restored = durability.cloud_restore(ckpt)
    assert restored["frames"] >= 1 and restored["models"] >= 1
    fr2, m2 = DKV.get(fkey), DKV.get(mkey)
    assert durability.frame_digest(fr2) == want_digest
    assert np.array_equal(
        m2.predict(fr2).col("predict").to_numpy(), want_pred)
    from h2o3_tpu import telemetry
    hists = telemetry.REGISTRY.find("cloud_restore_seconds")
    assert hists and sum(h.count for h in hists) >= 1


def test_cloud_restore_rejects_garbage(tmp_path):
    with pytest.raises(IOError):
        durability.cloud_restore(str(tmp_path / "nope"))
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "manifest.json").write_text(json.dumps({"magic": "wrong"}))
    with pytest.raises(IOError):
        durability.cloud_restore(str(bad))


@pytest.mark.multiprocess
def test_init_restore_dir_reforms_cloud_in_fresh_process(tmp_path):
    """The disaster-recovery entry point: a BRAND NEW process calls
    ``init(restore_dir=)`` and gets the checkpointed cloud back,
    bit-identical (frames digest-verified, model predictions equal)."""
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(41)
    n = 300
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": r.randn(n), "b": r.randn(n), "y": r.randn(n)})
    model = GBMEstimator(ntrees=4, max_depth=3, seed=2).train(fr, y="y")
    ckpt = str(tmp_path / "cloudsnap")
    durability.cloud_checkpoint(ckpt, quiesce_s=5)
    expect = {
        "frame_key": fr.key, "model_key": model.key,
        "pred_head": [float(v) for v in
                      model.predict(fr).col("predict").to_numpy()[:16]],
    }
    with open(os.path.join(ckpt, "expect.json"), "w") as f:
        json.dump(expect, f)
    script = (
        "import os, sys, json\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=8 "
        "--xla_cpu_use_thunk_runtime=false'\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import h2o3_tpu\n"
        f"info = h2o3_tpu.init(backend='cpu', restore_dir={ckpt!r})\n"
        "assert info['restored']['frames'] >= 1, info\n"
        "assert info['restored']['models'] >= 1, info\n"
        "from h2o3_tpu.core.kv import DKV\n"
        f"exp = json.load(open(os.path.join({ckpt!r}, 'expect.json')))\n"
        "fr = DKV.get(exp['frame_key'])\n"
        "m = DKV.get(exp['model_key'])\n"
        "import numpy as np\n"
        "pred = m.predict(fr).col('predict').to_numpy()[:16]\n"
        "assert [float(v) for v in pred] == exp['pred_head'], "
        "'restored model predictions differ'\n"
        "print('RESTORE-OK')\n"
        "h2o3_tpu.shutdown()\n")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("H2O3TPU_DATA_DURABILITY", None)
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True,
                       timeout=WORKER_TIMEOUT_S)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-3000:]
    assert "RESTORE-OK" in p.stdout


# -------------------------------------- 2-process SIGKILL acceptance


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.multiprocess
def test_sigkill_peer_frames_rebuilt_fit_resumes_bit_identical(
        tmp_path):
    """Kill -9 a peer mid-GBM-fit: the survivor rebuilds its frames
    from the mirror (bit-identical digest), re-homes them, resumes the
    fit from the dead peer's traveling snapshot, and the result equals
    an undisturbed reference fit exactly. tests/durability_worker.py
    holds the per-process script + assertions."""
    out = str(tmp_path / "result.json")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "H2O3TPU_DATA_DURABILITY": "mirror",
        "H2O3TPU_DUR_DIR": str(tmp_path / "mirror"),
        "H2O3TPU_DUR_REBUILD_S": "0.1",
        "H2O3TPU_FIT_CHECKPOINT_DIR": str(tmp_path / "fitsnap"),
        "H2O3TPU_FIT_CHECKPOINT_EVERY": "2",
        # slow the victim's fit around each snapshot so the kill lands
        # deterministically mid-fit (never after completion)
        "H2O3TPU_FIT_CHECKPOINT_HOLD_S": "0.25",
    })
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(i), out],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    # SIGKILL the victim once its fit has published a snapshot
    deadline = time.time() + WORKER_TIMEOUT_S
    fitdir = str(tmp_path / "fitsnap")
    killed = False
    while time.time() < deadline:
        snaps = [f for f in (os.listdir(fitdir)
                             if os.path.isdir(fitdir) else [])
                 if f.endswith(".fitsnap")]
        if snaps:
            procs[1].kill()
            killed = True
            break
        if procs[1].poll() is not None or procs[0].poll() is not None:
            break                    # a worker died early — report below
        time.sleep(0.05)
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(deadline - time.time(), 1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + "\n[TIMEOUT]"
        logs.append(stdout or "")
    joined = "\n".join(f"--- worker {j} ---\n{lg[-3000:]}"
                       for j, lg in enumerate(logs))
    assert killed, f"no fit snapshot ever appeared:\n{joined}"
    assert procs[1].returncode == -9, joined
    assert procs[0].returncode == 0, joined
    with open(out) as f:
        result = json.load(f)
    assert result["digest_match"] is True
    assert result["rebuild_source"] == "mirror"
    assert result["mirror_rebuilds_total"] >= 1
    assert result["bit_identical_fit"] is True
    assert result["resumed_mse"] == result["fresh_mse"]
    assert result["under_replicated"] == 0
