"""Extended Rapids prims — matrix, advmath, repeaters, filters, reshape
(the remaining water/rapids/ast/prims families; wire names match the
reference's AST str() names)."""

import numpy as np

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.rapids import rapids


def _fr(key, cols, **kw):
    return Frame.from_numpy(cols, key=key, **kw)


def test_transpose_and_mmult():
    _fr("mA", {"a": np.array([1.0, 2.0]), "b": np.array([3.0, 4.0])})
    _fr("mB", {"a": np.array([1.0, 0.0]), "b": np.array([0.0, 1.0])})
    t = rapids("(t mA)")
    assert t.nrows == 2 and t.ncols == 2
    np.testing.assert_allclose(t.col("C1").to_numpy(), [1.0, 3.0])
    m = rapids("(x mA mB)")
    np.testing.assert_allclose(m.col("C1").to_numpy(), [1.0, 2.0])
    np.testing.assert_allclose(m.col("C2").to_numpy(), [3.0, 4.0])


def test_hist_and_cut():
    _fr("hv", {"v": np.linspace(0.0, 10.0, 101)})
    h = rapids("(hist hv 5)")
    counts = h.col("counts").to_numpy()
    assert np.nansum(counts) == 101
    assert len(h.col("breaks").to_numpy()) == 6
    c = rapids("(cut hv [0 2.5 5 10] [] 1 1 3)")
    col = c.col("v")
    assert col.is_categorical and len(col.domain) == 3
    codes = np.asarray(col.data)[: c.nrows]
    assert codes[10] == 0 and codes[40] == 1 and codes[90] == 2


def test_fillna_forward():
    _fr("fn", {"v": np.array([1.0, np.nan, np.nan, 4.0, np.nan])})
    out = rapids('(h2o.fillna fn "forward" 0 1)')
    got = out.col("v").to_numpy()
    np.testing.assert_allclose(got[:2], [1.0, 1.0])
    assert np.isnan(got[2])          # maxlen=1 caps the fill run
    np.testing.assert_allclose(got[3:], [4.0, 4.0])


def test_kfold_columns():
    _fr("kf", {"v": np.arange(100, dtype=np.float64)})
    f = rapids("(kfold_column kf 5 42)").col("fold").to_numpy()
    assert set(np.unique(f)) <= set(range(5))
    m = rapids("(modulo_kfold_column kf 4)").col("fold").to_numpy()
    np.testing.assert_allclose(m, np.arange(100) % 4)
    _fr("sk", {"y": np.array(["a"] * 60 + ["b"] * 40, object)},
        categorical=["y"])
    s = rapids("(stratified_kfold_column sk 5 42)").col("fold").to_numpy()
    # each fold must carry ~the class ratio (12 a's, 8 b's)
    ya = s[:60]
    for k in range(5):
        assert 10 <= (ya == k).sum() <= 14


def test_stratified_split():
    _fr("ss", {"y": np.array(["a"] * 80 + ["b"] * 20, object)},
        categorical=["y"])
    out = rapids("(h2o.random_stratified_split ss 0.25 7)")
    col = out.col("test_train_split")
    assert col.domain == ["train", "test"]
    codes = np.asarray(col.data)[: out.nrows]
    assert (codes[:80] == 1).sum() == 20     # 25% of each class
    assert (codes[80:] == 1).sum() == 5


def test_repeaters():
    s = rapids("(seq_len 5)").col("C1").to_numpy()
    np.testing.assert_allclose(s, [1, 2, 3, 4, 5])
    q = rapids("(seq 0 1 0.25)").col("C1").to_numpy()
    np.testing.assert_allclose(q, [0, 0.25, 0.5, 0.75, 1.0])
    _fr("rp", {"v": np.array([7.0, 8.0])})
    r = rapids("(rep_len rp 5)").col("C1").to_numpy()
    np.testing.assert_allclose(r, [7, 8, 7, 8, 7])


def test_distance():
    _fr("dA", {"x": np.array([0.0, 3.0]), "y": np.array([0.0, 4.0])})
    _fr("dB", {"x": np.array([0.0]), "y": np.array([0.0])})
    d = rapids('(distance dA dB "l2")').col("C1").to_numpy()
    np.testing.assert_allclose(d, [0.0, 5.0])


def test_dropdup_and_grep():
    _fr("dd", {"a": np.array([1.0, 1.0, 2.0, 2.0, 3.0]),
               "b": np.array([9.0, 9.0, 8.0, 7.0, 6.0])})
    out = rapids('(dropdup dd ["a"] "first")')
    np.testing.assert_allclose(out.col("b").to_numpy(), [9.0, 8.0, 6.0])
    _fr("gg", {"s": np.array(["apple", "banana", "cherry"], object)},
        categorical=["s"])
    hits = rapids('(grep gg "an" 0 0 0)').col("C1").to_numpy()
    np.testing.assert_allclose(hits, [1.0])
    logical = rapids('(grep gg "an" 0 1 1)').col("C1").to_numpy()
    np.testing.assert_allclose(logical, [1.0, 0.0, 1.0])


def test_strip():
    _fr("st", {"s": np.array(["  hi", "yo  ", "  both  "], object)},
        categorical=["s"])
    l = rapids("(lstrip st)")
    dom = l.col("s").domain
    codes = np.asarray(l.col("s").data)[: l.nrows]
    assert [dom[c] for c in codes] == ["hi", "yo  ", "both  "]


def test_melt_pivot_roundtrip():
    _fr("wide", {"id": np.array([1.0, 2.0]),
                 "p": np.array([10.0, 20.0]),
                 "q": np.array([30.0, 40.0])})
    long = rapids('(melt wide ["id"] ["p" "q"] "variable" "value" 0)')
    assert long.nrows == 4
    vdom = long.col("variable").domain
    assert vdom == ["p", "q"]
    back = rapids('(pivot py_melt_tmp "id" "variable" "value")'
                  .replace("py_melt_tmp", long.key))
    np.testing.assert_allclose(back.col("p").to_numpy(), [10.0, 20.0])
    np.testing.assert_allclose(back.col("q").to_numpy(), [30.0, 40.0])


def test_seq_negative_and_fillna_strings_and_dropdup_na():
    s = rapids("(seq 5 1 -1)").col("C1").to_numpy()
    np.testing.assert_allclose(s, [5, 4, 3, 2, 1])
    _fr("fns", {"v": np.array([np.nan, 2.0, np.nan]),
                "s": np.array(["a", None, "c"], object)},
        strings=["s"])
    out = rapids('(h2o.fillna fns "backward" 0 5)')
    np.testing.assert_allclose(out.col("v").to_numpy(), [2.0, 2.0, np.nan])
    assert list(out.col("s").to_numpy()) == ["a", None, "c"]
    _fr("ddn", {"a": np.array([np.nan, np.nan, 1.0]),
                "b": np.array([1.0, 2.0, 3.0])})
    out = rapids('(dropdup ddn ["a"] "first")')
    np.testing.assert_allclose(out.col("b").to_numpy(), [1.0, 3.0])


def test_fillna_order_and_axis1_guard_and_dropdup_strings():
    _fr("fo", {"s": np.array(["a", "b", "c"], object),
               "v": np.array([1.0, np.nan, 3.0])}, strings=["s"])
    out = rapids('(h2o.fillna fo "forward" 0 5)')
    assert out.names == ["s", "v"]       # column order preserved
    out = rapids('(h2o.fillna fo "forward" 1 5)')
    assert out.names == ["s", "v"]
    _fr("fcat", {"g": np.array(["x", "y", "x"], object)}, categorical=["g"])
    out = rapids('(h2o.fillna fcat "forward" 1 2)')  # zero numeric cols
    assert out.names == ["g"]
    _fr("dds", {"s": np.array(["k", "k", "m", None, None], object),
                "v": np.arange(5, dtype=np.float64)}, strings=["s"])
    out = rapids('(dropdup dds ["s"] "first")')
    np.testing.assert_allclose(out.col("v").to_numpy(), [0.0, 2.0, 3.0])
