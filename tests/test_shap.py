"""TreeSHAP predict_contributions — exactness + local accuracy.

Oracle 1 (local accuracy): contributions + BiasTerm sum to the raw
link-space margin for every row (hex/Model.java contributions contract).
Oracle 2 (exactness): brute-force Shapley values computed by enumerating
all feature subsets with the tree conditional expectation (the EXPVALUE
recursion of Lundberg et al.) on small forests.
"""

import itertools
import math

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.binning import rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ml.shap import forest_contributions
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.drf import DRFEstimator


def _rand_frame(n=400, F=4, seed=3, binary=False):
    r = np.random.RandomState(seed)
    cols = {f"x{i}": r.randn(n) for i in range(F)}
    raw = cols["x0"] * 2.0 + np.sin(cols["x1"]) + 0.3 * r.randn(n)
    if binary:
        cols["y"] = np.where(raw > 0, "yes", "no")
    else:
        cols["y"] = raw
    return Frame.from_numpy(cols)


def _brute_tree_shap(feat, thresh, na_left, is_split, leaf, leaf_w,
                     bins_row, B, F):
    """Exact Shapley via subset enumeration + EXPVALUE recursion."""
    D = feat.shape[0]
    covers = [leaf_w.reshape(1 << d, -1).sum(axis=1) for d in range(D)]
    covers.append(leaf_w)

    def expv(d, l, S):
        if d == D or not is_split[d, l]:
            return float(leaf[l << (D - d)])
        f = int(feat[d, l])
        left, right = 2 * l, 2 * l + 1
        if f in S:
            b = bins_row[f]
            gl = bool(na_left[d, l]) if b == B - 1 else b <= thresh[d, l]
            return expv(d + 1, left if gl else right, S)
        rl, rr = float(covers[d + 1][left]), float(covers[d + 1][right])
        rj = max(rl + rr, 1e-30)
        return (rl * expv(d + 1, left, S) + rr * expv(d + 1, right, S)) / rj

    phi = np.zeros(F)
    feats = list(range(F))
    for i in feats:
        rest = [f for f in feats if f != i]
        for k in range(F):
            wgt = math.factorial(k) * math.factorial(F - k - 1) / math.factorial(F)
            for S in itertools.combinations(rest, k):
                phi[i] += wgt * (expv(0, 0, set(S) | {i}) - expv(0, 0, set(S)))
    return phi


@pytest.fixture(scope="module")
def gbm_reg():
    fr = _rand_frame()
    m = GBMEstimator(ntrees=4, max_depth=3, learn_rate=0.3, seed=7,
                     min_rows=5.0)
    return fr, m.train(y="y", training_frame=fr)


def test_local_accuracy_regression(gbm_reg):
    fr, model = gbm_reg
    contrib = model.predict_contributions(fr)
    names = list(model.output["names"]) + ["BiasTerm"]
    assert list(contrib.names) == names
    total = sum(contrib.col(n).to_numpy() for n in names)
    pred = model.predict(fr).col("predict").to_numpy()
    np.testing.assert_allclose(total, pred, rtol=1e-4, atol=1e-4)


def test_exact_vs_bruteforce(gbm_reg):
    fr, model = gbm_reg
    bm = rebin_for_scoring(model.bm, fr)
    bins = np.asarray(bm.bins)[: fr.nrows]
    B = model.bm.nbins_total
    rows = bins[:6]
    phi = forest_contributions(model.forest, rows, B)
    F = bins.shape[1]
    fo = [np.asarray(getattr(model.forest, f)) for f in
          ("feat", "thresh", "na_left", "is_split", "leaf", "leaf_w")]
    for r in range(rows.shape[0]):
        want = np.zeros(F)
        for t in range(fo[0].shape[0]):
            want += _brute_tree_shap(*(a[t] for a in fo), rows[r], B, F)
        np.testing.assert_allclose(phi[r, :F], want, rtol=1e-4, atol=1e-5)


def test_local_accuracy_binomial():
    fr = _rand_frame(binary=True, seed=11)
    model = GBMEstimator(ntrees=5, max_depth=3, seed=5).train(
        y="y", training_frame=fr)
    contrib = model.predict_contributions(fr)
    total = sum(contrib.col(n).to_numpy() for n in contrib.names)
    p1 = model.predict(fr).col("p1").to_numpy()
    logit = np.log(np.clip(p1, 1e-12, 1) / np.clip(1 - p1, 1e-12, 1))
    np.testing.assert_allclose(total, logit, rtol=1e-3, atol=1e-3)


def test_drf_contributions_sum():
    fr = _rand_frame(seed=19)
    model = DRFEstimator(ntrees=6, max_depth=4, seed=5).train(
        y="y", training_frame=fr)
    contrib = model.predict_contributions(fr)
    total = sum(contrib.col(n).to_numpy() for n in contrib.names)
    pred = model.predict(fr).col("predict").to_numpy()
    np.testing.assert_allclose(total, pred, rtol=1e-4, atol=1e-4)


def test_multinomial_rejected():
    r = np.random.RandomState(2)
    fr = Frame.from_numpy({"a": r.randn(200),
                           "y": r.choice(["u", "v", "w"], 200)})
    model = GBMEstimator(ntrees=2, max_depth=2).train(y="y",
                                                      training_frame=fr)
    with pytest.raises(ValueError, match="regression and binomial"):
        model.predict_contributions(fr)
