"""Telemetry subsystem: registry, spans, compile observer, /3/Metrics —
plus regression tests for the satellite fixes that rode in with it
(DL minibatch clamp, GBM chunk-invariant PRNG, PCA mojo sigma guard,
rapids all-NA device mean).

The overhead contract (TimeLine's "cheap enough to leave on",
water/TimeLine.java:22) is asserted loosely: registry ops during a real
GBM fit x measured per-op cost must stay under 2% of fit wall time.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import registry as reg_mod
from h2o3_tpu.telemetry.compile_observer import observed_jit


def _mk_class_frame(n=300, f=3, seed=0, key=None):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(f)}
    cols["y"] = np.array(["n", "p"], object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"], key=key)


# ------------------------------------------------------------ registry


def test_counter_gauge_histogram_basics():
    c = telemetry.counter("test_basics_total", kind="a")
    v0 = c.value
    c.inc()
    c.inc(2.5)
    assert c.value == v0 + 3.5
    # same (name, labels) -> same instance; different labels -> distinct
    assert telemetry.counter("test_basics_total", kind="a") is c
    assert telemetry.counter("test_basics_total", kind="b") is not c

    g = telemetry.gauge("test_gauge_bytes")
    g.set(10)
    g.set_max(5)
    assert g.value == 10
    g.set_max(20)
    assert g.value == 20

    h = telemetry.histogram("test_hist_seconds")
    h.observe(0.003)
    h.observe(7.0)
    assert h.count == 2
    assert abs(h.sum - 7.003) < 1e-9
    cum = dict(zip(h.bounds, h.cumulative()))
    assert cum[0.005] == 1 and cum[10.0] == 2


def test_registry_prefix_and_value():
    telemetry.counter("test_prefix_total").inc()
    snap = telemetry.snapshot()
    names = {c["name"] for c in snap["counters"]}
    assert "h2o3tpu_test_prefix_total" in names
    assert telemetry.REGISTRY.value("test_prefix_total") >= 1
    assert telemetry.REGISTRY.value("test_never_touched_total") == 0.0


def test_prometheus_exposition_format():
    telemetry.counter("test_prom_total", algo="gbm").inc(3)
    telemetry.histogram("test_prom_seconds").observe(0.2)
    text = telemetry.to_prometheus()
    assert "# TYPE h2o3tpu_test_prom_total counter" in text
    assert 'h2o3tpu_test_prom_total{algo="gbm"} 3' in text
    assert "# TYPE h2o3tpu_test_prom_seconds histogram" in text
    assert 'h2o3tpu_test_prom_seconds_bucket{le="+Inf"} ' in text
    assert "h2o3tpu_test_prom_seconds_count 1" in text


def test_counter_thread_safety():
    c = telemetry.counter("test_threads_total")
    v0 = c.value
    n_threads, per = 8, 5000

    def worker():
        for _ in range(per):
            c.inc()

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == v0 + n_threads * per


# --------------------------------------------------------------- spans


def test_span_nesting_and_ring():
    with telemetry.span("t.outer") as so:
        assert telemetry.current_span_id() == so.id
        with telemetry.span("t.inner", phase=1) as si:
            assert si.parent_id == so.id
        assert telemetry.current_span_id() == so.id
    assert telemetry.current_span_id() is None
    recent = telemetry.spans_snapshot(20)
    by_id = {s["id"]: s for s in recent}
    assert by_id[si.id]["parent_id"] == so.id
    assert by_id[so.id]["parent_id"] is None
    assert by_id[si.id]["meta"].get("phase") == 1
    assert telemetry.REGISTRY.value("spans_total", name="t.outer") >= 1


def test_span_roots_are_per_thread():
    ids = {}

    def worker(tag):
        with telemetry.span(f"t.root_{tag}") as sp:
            ids[tag] = (sp.id, sp.parent_id)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(parent is None for _, parent in ids.values())


def test_timeline_events_carry_span_id():
    from h2o3_tpu.utils import timeline
    with telemetry.span("t.tl") as sp:
        timeline.record("test", "inside-span")
    evs = [e for e in timeline.snapshot()
           if e.get("what") == "inside-span"]
    assert evs and evs[-1]["span_id"] == sp.id


def test_collective_bytes_charged_to_span():
    mesh = None
    from h2o3_tpu.parallel.map_reduce import frame_reduce
    x = jnp.ones((64,), jnp.float32)
    before = telemetry.REGISTRY.value("frame_reduce_total")
    with telemetry.span("t.mr") as sp:
        out = frame_reduce(lambda a: {"s": jnp.sum(a)}, x, mesh=mesh)
    assert float(out["s"]) == 64.0
    assert telemetry.REGISTRY.value("frame_reduce_total") == before + 1
    # 8-device test mesh -> nonzero psum estimate, charged to the span
    assert sp.collective_bytes > 0
    # scope-labeled accounting (ISSUE 19): one process ⇒ every ring
    # link is intra-host; the pod series exists but stays zero
    assert telemetry.REGISTRY.value("collective_bytes_total",
                                    scope="host") > 0
    assert telemetry.REGISTRY.value("collective_bytes_total",
                                    scope="pod") == 0


# ---------------------------------------------------- compile observer


def test_observed_jit_hit_miss_per_shape_bucket():
    @observed_jit("test.obsfn")
    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.ones((3,)))          # miss (fresh compile)
    f(jnp.ones((3,)))          # hit
    f(jnp.ones((4,)))          # miss (new shape bucket)
    miss3 = telemetry.REGISTRY.value("jit_cache_miss_total",
                                     fn="test.obsfn", shapes="3")
    hit3 = telemetry.REGISTRY.value("jit_cache_hit_total",
                                    fn="test.obsfn", shapes="3")
    miss4 = telemetry.REGISTRY.value("jit_cache_miss_total",
                                     fn="test.obsfn", shapes="4")
    assert (miss3, hit3, miss4) == (1, 1, 1)


def test_global_compile_listener_counts():
    before = telemetry.REGISTRY.value("xla_compile_total")

    @jax.jit
    def g(x):
        return jnp.sin(x) + 3

    g(jnp.ones((5,)))
    assert telemetry.REGISTRY.value("xla_compile_total") > before
    assert telemetry.REGISTRY.value("xla_compile_seconds") > 0  # count


# ------------------------------------------------- end-to-end + REST


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def test_metrics_endpoint_after_gbm_fit(port):
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _mk_class_frame(n=300, seed=1)
    ops0 = telemetry.REGISTRY.ops()
    t0 = time.time()
    m = GBMEstimator(ntrees=5, max_depth=3, seed=7).train(fr, y="y")
    fit_wall = time.time() - t0
    ops_fit = telemetry.REGISTRY.ops() - ops0
    assert m.training_metrics["AUC"] > 0.7
    # one MRTask so frame_reduce figures too
    from h2o3_tpu.parallel.map_reduce import frame_reduce
    frame_reduce(lambda a: jnp.sum(a), fr.col("x0").data)

    st, ctype, body = _get(port, "/3/Metrics")
    assert st == 200 and "json" in ctype
    j = json.loads(body)
    counters = {(c["name"], tuple(sorted(c["labels"].items()))): c["value"]
                for c in j["metrics"]["counters"]}
    totals = {}
    for (name, _), v in counters.items():
        totals[name] = totals.get(name, 0) + v
    # the acceptance counters: compiles, MRTask invocations, jobs
    assert totals.get("h2o3tpu_xla_compile_total", 0) > 0
    assert totals.get("h2o3tpu_frame_reduce_total", 0) >= 1
    assert totals.get("h2o3tpu_jobs_completed_total", 0) >= 1
    assert totals.get("h2o3tpu_train_iterations_total", 0) >= 5
    hist_names = {h["name"] for h in j["metrics"]["histograms"]}
    assert "h2o3tpu_job_duration_seconds" in hist_names
    assert "h2o3tpu_model_fit_seconds" in hist_names
    # span tree present with hierarchy
    names = {s["name"] for s in j["spans"]}
    assert "gbm.fit" in names and "job" in names
    fit_span = next(s for s in j["spans"] if s["name"] == "gbm.fit")
    assert fit_span["parent_id"] is not None

    # prometheus exposition of the same registry
    st, ctype, body = _get(port, "/3/Metrics?format=prometheus")
    assert st == 200 and ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE h2o3tpu_xla_compile_total counter" in text
    assert "h2o3tpu_job_duration_seconds_bucket" in text

    # loose overhead bound (acceptance: <2% of fit wall time): ops
    # recorded during the fit x measured per-op cost
    c = telemetry.counter("test_overhead_probe_total")
    t0 = time.time()
    for _ in range(20000):
        c.inc()
    per_op = (time.time() - t0) / 20000
    t0 = time.time()
    for _ in range(500):
        with telemetry.span("t.overhead"):
            pass
    per_span = (time.time() - t0) / 500
    n_spans = telemetry.REGISTRY.value("spans_total", name="gbm.chunk") \
        + telemetry.REGISTRY.value("spans_total", name="gbm.fit")
    est = ops_fit * per_op + n_spans * per_span
    assert est < 0.02 * fit_wall, (est, fit_wall, ops_fit)


def test_watermeter_and_profiler_report_data(port):
    st, _, body = _get(port, "/3/WaterMeterCpuTicks")
    j = json.loads(body)
    assert st == 200 and j["cpu_ticks"], "must report real tick data"
    assert all(len(row) == 4 for row in j["cpu_ticks"])
    st, _, body = _get(port, "/3/Profiler?depth=2")
    j = json.loads(body)
    assert st == 200 and j["nodes"][0]["entries"]
    # span-level profile rides along with real collected span data
    assert any(a["count"] > 0 for a in j["spans"])


# ------------------------------------------------- satellite regressions


def test_dl_fits_tiny_frame():
    """deeplearning.py minibatch floor: <~224-row frames crashed at
    trace time before the padded-row clamp."""
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    r = np.random.RandomState(11)
    n = 150
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": r.randn(n), "b": r.randn(n),
         "y": np.array(["u", "v"], object)[r.randint(0, 2, n)]},
        categorical=["y"])
    m = DeepLearningEstimator(hidden=[4], epochs=1.0, seed=3).train(
        fr, y="y")
    assert m is not None and m.net


def test_gbm_chunking_invariant_sampling():
    """gbm.py per-tree keys come from the GLOBAL tree index: running the
    boost scan as one 4-tree chunk vs 2+2 chunks (what a max_runtime cap
    does to chunk size) must give identical trees."""
    from h2o3_tpu.frame.binning import bin_frame
    from h2o3_tpu.models.distribution import get_distribution
    from h2o3_tpu.models.gbm import _boost_scan
    from h2o3_tpu.models.tree import TreeParams
    r = np.random.RandomState(5)
    n = 400
    fr = h2o3_tpu.Frame.from_numpy(
        {f"x{i}": r.randn(n) for i in range(4)})
    xcols = [f"x{i}" for i in range(4)]
    bm = bin_frame(fr, xcols, nbins=64, nbins_cats=1024)
    N = bm.bins.shape[0]
    yv = (r.randn(n) > 0).astype(np.float32)
    y = jnp.asarray(np.pad(yv, (0, N - n)))
    w = fr.valid_weights()
    margin = jnp.zeros((N,), jnp.float32)
    tp = TreeParams(max_depth=3, min_rows=5.0, nbins_total=bm.nbins_total,
                    cat_feats=tuple(bool(v) for v in bm.is_cat))
    dist = get_distribution("gaussian")
    key = jax.random.PRNGKey(42)
    kw = dict(tp=tp, dist=dist, sample_rate=0.6)

    tr_full, m_full, _ = _boost_scan(bm.bins, bm.nbins, y, w, margin, key,
                                     ntrees=4, tree0=0, **kw)
    tr_a, m_a, _ = _boost_scan(bm.bins, bm.nbins, y, w, margin, key,
                               ntrees=2, tree0=0, **kw)
    tr_b, m_b, _ = _boost_scan(bm.bins, bm.nbins, y, w, m_a, key,
                               ntrees=2, tree0=2, **kw)
    for f in tr_full._fields:
        full = np.asarray(getattr(tr_full, f))
        split = np.concatenate([np.asarray(getattr(tr_a, f)),
                                np.asarray(getattr(tr_b, f))])
        assert np.array_equal(full, split), f
    np.testing.assert_allclose(np.asarray(m_full), np.asarray(m_b),
                               rtol=1e-5, atol=1e-5)


def test_gbm_non_binding_cap_same_forest():
    """End-to-end: a non-binding max_runtime_secs must not change the
    seeded forest."""
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _mk_class_frame(n=300, f=5, seed=9)
    kw = dict(ntrees=4, max_depth=3, seed=123, sample_rate=0.6,
              col_sample_rate_per_tree=0.7)
    a = GBMEstimator(**kw).train(fr, y="y")
    b = GBMEstimator(max_runtime_secs=99999, **kw).train(fr, y="y")
    for f in a.forest._fields:
        assert np.array_equal(np.asarray(getattr(a.forest, f)),
                              np.asarray(getattr(b.forest, f))), f


def test_pca_reference_mojo_constant_column(tmp_path):
    """refmojo.py norm_mul: sigma==0 (constant standardized column) must
    emit 1.0 (DataInfo.java:620), not raise ZeroDivisionError."""
    from h2o3_tpu.genmodel.refmojo import write_reference_pca_mojo
    from h2o3_tpu.models.pca import PCAEstimator
    r = np.random.RandomState(11)
    n = 200
    fr = h2o3_tpu.Frame.from_numpy(
        {"x1": r.randn(n), "c": np.full(n, 3.0), "x2": r.randn(n)})
    m = PCAEstimator(k=2, transform="standardize", seed=3).train(fr)
    p = str(tmp_path / "pca_const.zip")
    m.download_mojo(p, format="reference")
    import zipfile
    with zipfile.ZipFile(p) as z:
        info = z.read("model.ini").decode()
    line = next(l for l in info.splitlines() if l.startswith("normMul"))
    muls = [float(v) for v in
            line.split("=", 1)[1].strip().strip("[]").split(",")]
    assert all(np.isfinite(muls)) and 1.0 in muls


def test_rapids_device_mean_all_na(monkeypatch):
    """rapids _dev_reduce: all-NA column with na.rm returns NaN like the
    host np.nanmean path, not 0.0 from a clamped denominator."""
    import h2o3_tpu.rapids as R
    from h2o3_tpu.rapids import Session, rapids
    sess = Session()
    r = np.random.RandomState(3)
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": np.full(4096, np.nan), "b": r.randn(4096)},
        key="tele_allna")
    sess.assign("tele_allna", fr)
    host = rapids('(mean (cols_py tele_allna ["a"]) 1)', sess)
    monkeypatch.setattr(R, "_DEV_MIN_ROWS", 1)
    dev = rapids('(mean (cols_py tele_allna ["a"]) 1)', sess)
    assert np.isnan(host) and np.isnan(dev)
    # sanity: the valid column still reduces on device
    dv = rapids('(mean (cols_py tele_allna ["b"]) 1)', sess)
    want = float(np.nanmean(np.asarray(fr.col("b").to_numpy())))
    assert abs(dv - want) < 2e-4 * max(1.0, abs(want))
