"""Columnar ingest formats: Parquet / ORC via Arrow, Avro via the
stdlib-only container reader (h2o-parsers/{parquet,orc,avro} roles)."""

import numpy as np
import pytest

from h2o3_tpu.io.parser import import_file


def _write_table(tmp_path, fmt):
    import pyarrow as pa
    n = 500
    r = np.random.RandomState(0)
    x = r.randn(n)
    x[::11] = np.nan
    cat = np.array(["red", "green", "blue"])[r.randint(0, 3, n)]
    table = pa.table({"x": pa.array(x),
                      "n": pa.array(r.randint(0, 100, n).astype(np.int64)),
                      "c": pa.array(cat)})
    p = str(tmp_path / f"t.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, p)
    else:
        import pyarrow.orc as po
        po.write_table(table, p)
    return p, x, cat


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_arrow_formats(tmp_path, fmt):
    p, x, cat = _write_table(tmp_path, fmt)
    fr = import_file(p)
    assert fr.nrows == 500
    got = fr.col("x").to_numpy()
    nn = ~np.isnan(x)
    assert np.allclose(got[nn], x[nn])
    assert np.isnan(got[::11]).all()
    c = fr.col("c")
    assert c.is_categorical and sorted(c.domain) == ["blue", "green", "red"]


def _write_avro(path, codec="null"):
    """Hand-rolled writer: exercises the reader against the avro spec
    (zigzag varints, union-null fields, deflate blocks)."""
    import json
    import struct
    import zlib

    def zz(v):
        v = (v << 1) ^ (v >> 63)
        out = b""
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b | 0x80])
            else:
                out += bytes([b])
                return out

    schema = {"type": "record", "name": "r", "fields": [
        {"name": "a", "type": "double"},
        {"name": "b", "type": ["null", "long"]},
        {"name": "s", "type": "string"}]}
    rows = [(1.5, 7, "x"), (2.5, None, "y"), (-3.0, 42, "x")]
    body = b""
    for a, b, s in rows:
        body += struct.pack("<d", a)
        body += zz(0) + b"" if b is None else zz(1) + zz(b)
        body += zz(len(s.encode())) + s.encode()
    if codec == "deflate":
        comp = zlib.compressobj(wbits=-15)
        body = comp.compress(body) + comp.flush()
    sync = b"0123456789abcdef"
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out = b"Obj\x01" + zz(len(meta))
    for k, v in meta.items():
        out += zz(len(k)) + k.encode() + zz(len(v)) + v
    out += zz(0) + sync
    out += zz(len(rows)) + zz(len(body)) + body + sync
    with open(path, "wb") as f:
        f.write(out)
    return rows


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro(tmp_path, codec):
    p = str(tmp_path / "t.avro")
    rows = _write_avro(p, codec)
    fr = import_file(p)
    assert fr.nrows == len(rows)
    a = fr.col("a").to_numpy()
    assert np.allclose(a, [r[0] for r in rows])
    b = fr.col("b").to_numpy()
    assert b[0] == 7 and np.isnan(b[1]) and b[2] == 42
    s = fr.col("s")
    assert s.is_categorical and s.domain == ["x", "y"]
