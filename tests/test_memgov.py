"""ISSUE 11 — MemoryGovernor: HBM as a governed resource.

Four legs, one contract (core/memgov.py + core/cleaner.py +
core/job.py + models/model.py + api/server.py):

- single budget truth: device ``bytes_limit`` / the
  ``H2O3TPU_HBM_BUDGET_MB`` knob feed ``ops/merge.py``'s out-size cap
  and ``core/cleaner.py``'s ``pressure()``;
- predictive admission: a fit's footprint is estimated and reserved
  BEFORE dispatch — spill cold frames first, then reject with an
  actionable error naming projected vs available bytes; concurrent
  fits share a reservation ledger (bounded wait, then reject);
- OOM escalation ladder: RESOURCE_EXHAUSTED walks purge-jit-cache →
  governor eviction → resume from the in-fit checkpoint, driven
  deterministically on CPU via the ``device_oom`` fault site;
- memory truth: /3/Cloud reports real free/max/swap bytes.

Satellites: the merge-budget regression, the spill/restore CAS races
(run UNDER the conftest leak check), tight-budget bit-identity.
"""

import glob
import os
import threading
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.core import config, memgov, recovery, watchdog
from h2o3_tpu.core.cleaner import SpilledFrame, cleaner
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.core.memgov import MemoryBudgetExceeded, governor
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.tree import Tree

REGISTRY = telemetry.REGISTRY


@pytest.fixture(autouse=True)
def _clean_governor(monkeypatch):
    """Every test starts ungoverned with fast retry backoff and ends
    with no planted faults and an empty reservation ledger."""
    for var in ("H2O3TPU_HBM_BUDGET_MB", "H2O3TPU_MEMGOV",
                "H2O3TPU_MEMGOV_WAIT_S", "H2O3TPU_MERGE_MAX_OUT_BYTES"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(config.ARGS, "infra_backoff_base_s", 0.001)
    monkeypatch.setattr(config.ARGS, "infra_backoff_max_s", 0.01)
    yield
    watchdog.clear_faults()
    assert governor.reserved_bytes() == 0, "reservation leaked"


def _ice_tmp(tmp_path, monkeypatch):
    """Point the hex:// ice driver at tmp_path (test_cleaner.py idiom:
    the driver captures the dir at import, so reload)."""
    monkeypatch.setenv("H2O3_TPU_ICE_DIR", str(tmp_path))
    import importlib

    from h2o3_tpu.io import persist
    importlib.reload(persist)


def _classif_frame(n=2000, seed=0, key=None):
    r = np.random.RandomState(seed)
    X = r.randn(n, 5)
    yv = (X[:, 0] + 0.3 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["a", "b"], object)[yv]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"], key=key)


def _forests_equal(a: Tree, b: Tree):
    for f in Tree._fields:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert av.shape == bv.shape, (f, av.shape, bv.shape)
        assert np.array_equal(av, bv), f


# --------------------------------------------------- budget truth


def test_budget_truth_env_knob(monkeypatch):
    """One budget source: the knob feeds the governor's limit, the
    Cleaner's pressure() and /3/Cloud's snapshot alike; without any
    source the process is ungoverned (pressure 0, never spill-happy)."""
    assert governor.device_limit_bytes() == 0        # CPU: no stats
    assert not governor.governed()
    assert governor.pressure() == 0.0
    assert cleaner.pressure() == 0.0                 # routes through
    Frame.from_numpy({"a": np.arange(50_000.0)})     # something resident
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "1000")
    assert governor.device_limit_bytes() == 1000 << 20
    assert governor.governed()
    assert governor.budget_bytes() == 1000 << 20
    assert 0.0 < governor.pressure() == cleaner.pressure()
    snap = governor.snapshot()
    assert snap["governed"] and snap["budget_bytes"] == 1000 << 20
    assert snap["free_bytes"] == (1000 << 20) - snap["bytes_in_use"]
    monkeypatch.setenv("H2O3TPU_MEMGOV", "off")      # kill switch
    assert not governor.governed()


def test_budget_knob_changes_merge_decision(monkeypatch):
    """Satellite regression: ops/merge.py no longer assumes a private
    16GB device — its out-size cap is half the governor budget, and the
    knob flips a real join between the device and host paths."""
    from h2o3_tpu.ops import merge as merge_mod
    assert merge_mod._merge_out_budget() == 2 << 30  # CPU mesh default
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "1000")
    assert merge_mod._merge_out_budget() == 500 << 20
    monkeypatch.delenv("H2O3TPU_HBM_BUDGET_MB")
    # the decision, not just the number: 70K rows x 3 cols ≈ 1.9MB of
    # join result — on device under the default, host path under a
    # 1MB budget (512KB cap)
    n = 70_000
    k = np.arange(n, dtype=np.int64)
    lf = Frame.from_numpy({"k": k, "v": np.arange(n, dtype=np.float64)})
    rf = Frame.from_numpy({"k": k, "w": np.arange(n, dtype=np.float64)})
    out = merge_mod.device_merge(lf, rf, ["k"], "inner")
    assert out is not None and out.nrows == n
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "1")
    assert merge_mod.device_merge(lf, rf, ["k"], "inner") is None


def test_estimate_fit_bytes_scales():
    fr = _classif_frame()
    x = [f"x{i}" for i in range(5)]
    est = memgov.estimate_fit_bytes("gbm", {"ntrees": 50}, fr, x)
    from h2o3_tpu.core.cleaner import _frame_nbytes
    assert est > _frame_nbytes(fr)        # frame + design matrix + work
    vf = _classif_frame(seed=1)
    est_v = memgov.estimate_fit_bytes("gbm", {"ntrees": 50}, fr, x,
                                      validation_frame=vf)
    assert est_v >= est + _frame_nbytes(vf)


# ---------------------------------------------- predictive admission


def test_tight_budget_gbm_bit_identical_spill_restore(tmp_path,
                                                      monkeypatch):
    """Acceptance: the same GBM under a budget tight enough to force
    admission spills completes bit-identical to the unlimited run, with
    ≥1 spill and ≥1 restore counted."""
    _ice_tmp(tmp_path, monkeypatch)
    fr = _classif_frame()
    kw = dict(ntrees=20, max_depth=3, seed=5)
    clean = GBMEstimator(**kw).train(fr, y="y")
    # three cold decoy frames the admission pass may spill (~1.6MB ea)
    # f32-exact values so spill→restore comparisons are bitwise
    decoys = [Frame.from_numpy(
        {"d": np.random.RandomState(i).randn(400_000)
         .astype(np.float32).astype(np.float64)}) for i in range(3)]
    decoy_vals = [d.col("d").to_numpy() for d in decoys]
    decoy_bytes = sum(d.col("d").data.nbytes for d in decoys)
    time.sleep(0.01)
    DKV.get(fr.key)                       # training frame is warmest
    b = GBMEstimator(**kw)
    proj = memgov.estimate_fit_bytes(
        "gbm", b.params, fr, [f"x{i}" for i in range(5)])
    # a budget the fit only fits under after ~half the decoys spill
    budget = governor.resident_bytes() + proj - decoy_bytes // 2
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB",
                       str((budget + (1 << 20) - 1) >> 20))
    s0 = REGISTRY.total("frame_spills_total")
    r0 = REGISTRY.total("frame_restores_total")
    m = b.train(fr, y="y")
    assert REGISTRY.total("frame_spills_total") >= s0 + 1
    assert any(getattr(DKV.get_raw(d.key), "_is_lazy_stub", False)
               for d in decoys), "admission never spilled a decoy"
    assert governor.spilled_bytes() > 0
    _forests_equal(clean.forest, m.forest)
    assert float(clean.training_metrics["logloss"]) == \
        float(m.training_metrics["logloss"])
    # transparent restore of a spilled decoy, bit-intact
    restored = DKV.get(decoys[0].key)
    assert isinstance(restored, Frame)
    assert REGISTRY.total("frame_restores_total") >= r0 + 1
    np.testing.assert_array_equal(restored.col("d").to_numpy(),
                                  decoy_vals[0])


def test_over_budget_fit_rejected_pre_dispatch(monkeypatch):
    """Acceptance: a fit that cannot fit rejects BEFORE dispatch with
    the actionable shape (projected vs available bytes), counts the
    rejection, and leaks neither a Job nor a reservation — the client
    never sees an opaque XLA RESOURCE_EXHAUSTED."""
    r = np.random.RandomState(0)
    cols = {f"x{i}": r.randn(100_000) for i in range(4)}
    cols["y"] = np.array(["a", "b"], object)[
        (r.randn(100_000) > 0).astype(int)]
    fr = Frame.from_numpy(cols, categorical=["y"])   # ~1.6MB resident
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "1")
    c0 = REGISTRY.total("fit_admission_rejections_total")
    keys0 = set(DKV.keys())
    with pytest.raises(MemoryBudgetExceeded) as ei:
        GBMEstimator(ntrees=5, max_depth=3, seed=1).train(fr, y="y")
    e = ei.value
    assert isinstance(e, ValueError)      # watchdog: never retried
    assert e.projected > 0 and e.budget == 1 << 20
    assert "rejected before dispatch" in str(e)
    assert f"{e.projected} bytes" in str(e)
    assert "H2O3TPU_HBM_BUDGET_MB" in str(e)         # actionable
    assert REGISTRY.total("fit_admission_rejections_total") == c0 + 1
    assert governor.reserved_bytes() == 0
    from h2o3_tpu.core.job import Job
    assert not [k for k in DKV.keys() if k not in keys0
                and isinstance(DKV.get_raw(k), Job)], "job leaked"


def test_reservation_ledger_contention_and_release(monkeypatch):
    """Two individually-admissible fits cannot jointly overshoot: the
    second waits (bounded) on the ledger, rejects with
    reason=contention, and admits once the first releases."""
    gov = memgov.MemoryGovernor()
    gov.bytes_in_use = lambda: 0          # isolate the ledger
    gov.evict_for_admission = lambda needed, exclude=None: 0
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "64")
    monkeypatch.setenv("H2O3TPU_MEMGOV_WAIT_S", "0.2")
    r1 = gov.reserve("fit-a", 48 << 20)
    c0 = REGISTRY.total("fit_admission_rejections_total")
    t0 = time.monotonic()
    with pytest.raises(MemoryBudgetExceeded) as ei:
        gov.reserve("fit-b", 48 << 20)
    assert time.monotonic() - t0 >= 0.15  # waited, then gave up
    assert "reason=contention" in str(ei.value)
    assert REGISTRY.total("fit_admission_rejections_total") == c0 + 1
    # release mid-wait → the blocked fit admits instead of rejecting
    monkeypatch.setenv("H2O3TPU_MEMGOV_WAIT_S", "10")
    rel = threading.Timer(0.05, gov.release, args=(r1,))
    rel.start()
    r2 = gov.reserve("fit-b", 48 << 20)
    assert gov.reserved_bytes() == 48 << 20
    gov.release(r2)
    assert gov.reserved_bytes() == 0


# ------------------------------------------------ OOM escalation ladder


def test_device_oom_ladder_recovers_via_resume(tmp_path):
    """Acceptance: an injected RESOURCE_EXHAUSTED at a chunk boundary
    walks the ladder — jit purge counted, fit resumed from its snapshot
    (exactly one resume) — and the job SUCCEEDS bit-identical."""
    fr = _classif_frame()
    kw = dict(ntrees=50, max_depth=3, seed=5, stopping_rounds=2,
              stopping_tolerance=0.0, score_tree_interval=5)
    clean = GBMEstimator(**kw).train(fr, y="y")
    watchdog.inject_fault("device_oom", times=1)     # → RESOURCE_EXHAUSTED
    o0 = REGISTRY.total("oom_recoveries_total")
    p0 = REGISTRY.value("oom_recoveries_total", stage="purge_jit")
    z0 = REGISTRY.value("oom_recoveries_total", stage="resume")
    r0 = REGISTRY.total("fit_resumes_total")
    b = GBMEstimator(**kw)
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m = b.train(fr, y="y")
    assert b._job.status == "DONE"
    assert REGISTRY.total("oom_recoveries_total") >= o0 + 1
    assert REGISTRY.value("oom_recoveries_total", stage="purge_jit") \
        == p0 + 1
    assert REGISTRY.value("oom_recoveries_total", stage="resume") \
        == z0 + 1
    assert REGISTRY.total("fit_resumes_total") == r0 + 1
    _forests_equal(clean.forest, m.forest)
    assert clean.output["scoring_history"] == m.output["scoring_history"]


def test_repeat_oom_escalates_to_eviction(tmp_path, monkeypatch):
    """Rung 2: a second consecutive OOM drops the per-frame device
    caches and spills cold frames — previously pinned for the process
    lifetime — and the fit still completes bit-identical."""
    _ice_tmp(tmp_path, monkeypatch)
    fr = _classif_frame(seed=7)
    kw = dict(ntrees=30, max_depth=3, seed=5, score_tree_interval=5)
    clean = GBMEstimator(**kw).train(fr, y="y")
    assert fr.device_cache_nbytes() > 0   # pinned bin/matrix caches
    watchdog.inject_fault("device_oom", times=2)
    e0 = REGISTRY.value("oom_recoveries_total", stage="evict")
    with recovery.fit_checkpoint_scope(str(tmp_path)):
        m = GBMEstimator(**kw).train(fr, y="y")
    assert REGISTRY.value("oom_recoveries_total", stage="evict") == e0 + 1
    _forests_equal(clean.forest, m.forest)


# --------------------------------------------- spill/restore CAS races


def test_spill_cas_never_loses_newer_put(tmp_path, monkeypatch):
    """Satellite: a put that lands while the Cleaner is writing ice
    must win — the spill's replace_if CAS refuses, the stale ice file
    is reclaimed, and the bytes-on-ice ledger never moves."""
    _ice_tmp(tmp_path, monkeypatch)
    from h2o3_tpu.io import persist as persist_mod
    fr = Frame.from_numpy({"a": np.arange(4000.0)}, key="cas_victim")
    newer = {}
    orig_save = persist_mod.save_frame

    def racing_save(f, uri):
        orig_save(f, uri)                 # ice written...
        newer["fr"] = Frame.from_numpy(   # ...then a newer put lands
            {"a": np.arange(4000.0) + 1.0}, key="cas_victim")

    monkeypatch.setattr(persist_mod, "save_frame", racing_save)
    def _ice_files():
        # spill uris are generation-suffixed (cas_victim.g<N>.npz) so a
        # stale stub's discard can never unlink a newer stub's ice
        return glob.glob(
            os.path.join(str(tmp_path), "spill", "cas_victim.g*.npz"))

    g0 = governor.spilled_bytes()
    assert cleaner.spill("cas_victim") is None       # CAS refused
    assert DKV.get_raw("cas_victim") is newer["fr"]  # newer put won
    assert governor.spilled_bytes() == g0            # ledger untouched
    assert not _ice_files()
    # and the stub-clobber path: put over a real stub reclaims its ice
    monkeypatch.setattr(persist_mod, "save_frame", orig_save)
    assert isinstance(cleaner.spill("cas_victim"), SpilledFrame)
    assert governor.spilled_bytes() > g0
    assert len(_ice_files()) == 1
    Frame.from_numpy({"a": np.arange(4000.0) + 2.0}, key="cas_victim")
    assert governor.spilled_bytes() == g0            # settled once
    assert not _ice_files()
    np.testing.assert_array_equal(
        DKV.get("cas_victim").col("a").to_numpy(),
        np.arange(4000.0) + 2.0)


def test_spill_restore_race_concurrent_gets(tmp_path, monkeypatch):
    """Satellite: N reader threads hammer DKV.get on a frame while the
    main thread spills it repeatedly — every reader always sees a live,
    bit-intact Frame (never a stub, never a torn restore), and the
    bytes-on-ice ledger settles back to its baseline. Runs UNDER the
    conftest leak check."""
    _ice_tmp(tmp_path, monkeypatch)
    vals = np.random.RandomState(11).randn(8000) \
        .astype(np.float32).astype(np.float64)   # f32-exact: bitwise RT
    fr = Frame.from_numpy({"a": vals}, key="race_fr")
    expect = fr.col("a").to_numpy()
    g0 = governor.spilled_bytes()
    s0 = REGISTRY.total("frame_spills_total")
    r0 = REGISTRY.total("frame_restores_total")
    stop = threading.Event()
    errs = []

    def reader():
        while not stop.is_set():
            try:
                v = DKV.get("race_fr")
                if v is None or getattr(v, "_is_lazy_stub", False):
                    errs.append(f"reader saw {v!r}")
                    return
            except Exception as exc:      # noqa: BLE001
                errs.append(f"reader raised {exc!r}")
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    # generous deadline with an early exit: the fast path breaks out
    # after ~20 spills + 1 observed restore; the long tail covers a
    # loaded CI box where the reader threads are GIL-starved and take
    # seconds to see their first spilled state (the pre-ISSUE-14 flake:
    # a fixed 3.0s window sometimes closed with zero restores banked)
    deadline = time.time() + 15.0
    while time.time() < deadline:
        cleaner.spill("race_fr")
        time.sleep(0.001)
        if (REGISTRY.total("frame_spills_total") >= s0 + 20
                and REGISTRY.total("frame_restores_total") >= r0 + 1):
            break
    stop.set()
    for t in threads:
        t.join(10.0)
    assert not errs, errs[:3]
    assert REGISTRY.total("frame_spills_total") >= s0 + 1
    assert REGISTRY.total("frame_restores_total") >= r0 + 1
    final = DKV.get("race_fr")
    assert isinstance(final, Frame)
    np.testing.assert_array_equal(final.col("a").to_numpy(), expect)
    assert governor.spilled_bytes() == g0  # every ice byte reclaimed


# ------------------------------------------------------- memory truth


def test_cloud_reports_memory_truth(tmp_path, monkeypatch):
    """Satellite: GET /3/Cloud stops reporting free_mem/max_mem/swap_mem
    as 0 — free/max come from the governor budget, swap is the bytes
    the Cleaner holds on ice."""
    _ice_tmp(tmp_path, monkeypatch)
    monkeypatch.setenv("H2O3TPU_HBM_BUDGET_MB", "256")
    Frame.from_numpy({"a": np.arange(50_000.0)}, key="cloud_ice_fr")
    assert cleaner.spill("cloud_ice_fr") is not None
    on_ice = governor.spilled_bytes()
    assert on_ice > 0
    from h2o3_tpu.api.server import _cloud
    out = _cloud({}, "")
    nd = out["nodes"][0]
    assert nd["max_mem"] == 256 << 20
    assert 0 < nd["free_mem"] <= nd["max_mem"]
    assert nd["free_mem"] == nd["max_mem"] - nd["mem_value_size"]
    assert nd["swap_mem"] == on_ice
    # gauges refreshed on the way (flight-recorder capsule surface)
    assert REGISTRY.value("hbm_budget_bytes") == 256 << 20
    assert REGISTRY.value("frames_spilled_bytes") == on_ice
    restored = DKV.get("cloud_ice_fr")    # leave the DKV clean
    assert isinstance(restored, Frame)
