"""Grid asymptotic stopping = ScoreKeeper.stopEarly window semantics.

The reference stops a random grid after 2k+1 models when the metric is
immediately flat (hex/ScoreKeeper.java:278: needs len-1 >= 2k scores,
then compares k-window moving averages) — pyunit_benign_glm_grid pins
len(models) == 5 for stopping_rounds=2, tolerance=0.1.
"""

import numpy as np

import h2o3_tpu
from h2o3_tpu.ml.grid import GridSearch, stop_early_windowed


def test_window_semantics_flat_stops_at_2k_plus_1():
    k, tol = 2, 0.1
    scores = []
    for i in range(10):
        scores.append(0.75)                       # flat AUC
        if stop_early_windowed(scores, k, tol, less_is_better=False):
            break
    assert len(scores) == 2 * k + 1


def test_window_semantics_improving_does_not_stop():
    k, tol = 2, 0.01
    scores = []
    for i in range(8):
        scores.append(1.0 / (i + 1.0))            # logloss, 2x better each
        assert not stop_early_windowed(scores, k, tol,
                                       less_is_better=True)


def test_window_semantics_needs_2k_history():
    assert not stop_early_windowed([1.0, 1.0, 1.0, 1.0], 2, 0.1, True)
    assert stop_early_windowed([1.0] * 5, 2, 0.1, True)


def test_random_grid_flat_metric_trains_exactly_5_models():
    r = np.random.RandomState(1)
    n = 200
    a, b = r.randn(n), r.randn(n)
    y = (a + 0.2 * r.randn(n) > 0).astype(float)
    fr = h2o3_tpu.Frame.from_numpy({"a": a, "b": b, "y": y},
                                   categorical=["y"])
    from h2o3_tpu.models.glm import GLMEstimator
    gs = GridSearch(
        GLMEstimator, {"alpha": [0.01, 0.3, 0.5],
                       "lambda_": [1e-5, 1e-6, 1e-7, 1e-8]},
        search_criteria={"strategy": "RandomDiscrete", "seed": 42,
                         "stopping_metric": "AUTO",
                         "stopping_tolerance": 0.1,
                         "stopping_rounds": 2},
        family="binomial")
    grid = gs.train(fr, y="y")
    # tiny lambdas are metric-indistinguishable ⇒ the window converges
    # at the first legal check: exactly 2k+1 models (reference count)
    assert len(grid.models) == 5, len(grid.models)
