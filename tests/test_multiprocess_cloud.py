"""True multi-process cloud test — the reference's multi-JVM localhost
tier (multiNodeUtils.sh:22-27; SURVEY §4 tier 2 / @CloudSize(n)).

Launches N separate Python processes that form a jax.distributed cloud
(1 CPU device each), train GBM + GLM over the cross-process mesh, and
must match the single-process results.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_worker.py")
N_PROC = 2
# hard per-worker wallclock cap: a wedged worker (half-formed cloud, a
# collective missing a peer) costs one failed test with its logs, never
# a hung tier-1 run
WORKER_TIMEOUT_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))

pytestmark = pytest.mark.multiprocess


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def mp_result(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("mp") / "result.json")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, str(N_PROC), str(i), out],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(N_PROC)
    ]
    logs = []
    deadline = time.time() + WORKER_TIMEOUT_S
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(deadline - time.time(), 1.0))
        except subprocess.TimeoutExpired:
            # one wedged worker means the cloud never formed — kill the
            # whole pod so the OTHER workers' logs (usually the ones
            # naming the missing peer) get captured too
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + \
                f"\n[TIMEOUT after {WORKER_TIMEOUT_S:.0f}s]"
        logs.append(stdout)
    for i, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {i} failed (rc={p.returncode}):\n"
            + "\n".join(f"--- worker {j} log ---\n{lg[-3000:]}"
                        for j, lg in enumerate(logs)))
    with open(out) as f:
        return json.load(f)


def _single_process_reference():
    """Same training in-process (the current pytest cloud)."""
    import h2o3_tpu
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(5)
    n = 4000
    a = r.randn(n)
    b = r.randn(n)
    g = r.choice(["u", "v", "w"], n)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(n) * 0.3
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g"])
    gbm = GBMEstimator(ntrees=10, max_depth=4, seed=3).train(fr, y="y")
    glm = GLMEstimator(family="gaussian", lambda_=0.0).train(fr, y="y")
    return gbm, glm, fr


def test_multiprocess_cloud_forms(mp_result):
    assert mp_result["process_count"] == N_PROC


def test_multiprocess_peer_health(mp_result):
    """The heartbeat monitor runs on every member of a multi-process
    cloud and sees all peers' beats (per-peer last-seen over the
    coordination-service KV store)."""
    assert mp_result["heartbeat_running"]
    assert mp_result["cloud_healthy"]
    assert mp_result["peers_seen"] == list(range(N_PROC))
    assert 0 <= mp_result["uptime_ms"] < 10 * 60 * 1000


def test_multiprocess_gbm_matches_single_process(mp_result):
    gbm, _, fr = _single_process_reference()
    assert abs(mp_result["gbm_mse"]
               - float(gbm.training_metrics["MSE"])) < 1e-4
    pred = gbm.predict(fr).col("predict").to_numpy()[:16]
    np.testing.assert_allclose(mp_result["gbm_pred_head"], pred, atol=1e-4)


def test_multiprocess_glm_matches_single_process(mp_result):
    _, glm, _ = _single_process_reference()
    for k, v in glm.coefficients.items():
        assert abs(mp_result["glm_coefficients"][k] - v) < 1e-3, k
