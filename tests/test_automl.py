"""AutoML tests — pyunit_automl* role (h2o-py/tests/testdir_algos/automl/)."""

import numpy as np

import h2o3_tpu
from h2o3_tpu.automl import H2OAutoML


def test_automl_runs_and_ranks(classif_frame):
    aml = H2OAutoML(max_models=4, nfolds=3, seed=1,
                    include_algos=["glm", "gbm", "drf", "stackedensemble"],
                    max_runtime_secs=600)
    leader = aml.train(y="y", training_frame=classif_frame)
    assert leader is not None
    tab = aml.leaderboard.as_table()
    assert len(tab) >= 3
    aucs = [r["auc"] for r in tab]
    assert aucs == sorted(aucs, reverse=True)
    assert aucs[0] > 0.8
    # leader predicts
    p = aml.predict(classif_frame).to_pandas()
    assert {"predict", "p0", "p1"} <= set(p.columns)


def test_automl_exclude_algos(classif_frame):
    aml = H2OAutoML(max_models=2, nfolds=2, seed=2,
                    include_algos=["gbm"], max_runtime_secs=300)
    aml.train(y="y", training_frame=classif_frame)
    algos = {m.algo for m in aml.leaderboard.models}
    assert algos == {"gbm"}


def test_automl_ensemble_present(classif_frame):
    aml = H2OAutoML(max_models=3, nfolds=3, seed=3,
                    include_algos=["glm", "gbm", "stackedensemble"],
                    max_runtime_secs=600)
    aml.train(y="y", training_frame=classif_frame)
    steps = {m.output.get("automl_step") for m in aml.leaderboard.models}
    assert "StackedEnsemble_BestOfFamily" in steps, steps
