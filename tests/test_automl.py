"""AutoML tests — pyunit_automl* role (h2o-py/tests/testdir_algos/automl/)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.automl import H2OAutoML

# ~520s single-threaded on this container (dozens of model fits); the
# tier-1 gate runs `-m 'not slow'` under a hard wallclock — without the
# marker this one file eats 60% of the budget. allow_key_leak: AutoML
# trains through background job threads the thread-local Scope leak
# check cannot track.
pytestmark = [pytest.mark.slow, pytest.mark.allow_key_leak]


def test_automl_runs_and_ranks(classif_frame):
    aml = H2OAutoML(max_models=4, nfolds=3, seed=1,
                    include_algos=["glm", "gbm", "drf", "stackedensemble"],
                    max_runtime_secs=600)
    leader = aml.train(y="y", training_frame=classif_frame)
    assert leader is not None
    tab = aml.leaderboard.as_table()
    assert len(tab) >= 3
    aucs = [r["auc"] for r in tab]
    assert aucs == sorted(aucs, reverse=True)
    assert aucs[0] > 0.8
    # leader predicts
    p = aml.predict(classif_frame).to_pandas()
    assert {"predict", "p0", "p1"} <= set(p.columns)


def test_automl_exclude_algos(classif_frame):
    aml = H2OAutoML(max_models=2, nfolds=2, seed=2,
                    include_algos=["gbm"], max_runtime_secs=300)
    aml.train(y="y", training_frame=classif_frame)
    algos = {m.algo for m in aml.leaderboard.models}
    assert algos == {"gbm"}


def test_automl_ensemble_present(classif_frame):
    aml = H2OAutoML(max_models=3, nfolds=3, seed=3,
                    include_algos=["glm", "gbm", "stackedensemble"],
                    max_runtime_secs=600)
    aml.train(y="y", training_frame=classif_frame)
    steps = {m.output.get("automl_step") for m in aml.leaderboard.models}
    assert "StackedEnsemble_BestOfFamily" in steps, steps


def test_automl_step_plan_breadth():
    """The modeling plan must expose >=15 distinct steps across providers
    (VERDICT r1 item 5; ai/h2o/automl/modeling/*StepsProvider)."""
    from h2o3_tpu.automl.steps import modeling_plan
    plan = modeling_plan(seed=1)
    ids = [s.id for s in plan]
    assert len(ids) == len(set(ids))
    assert len(ids) >= 15, ids
    kinds = {s.kind for s in plan}
    assert {"default", "grid", "exploitation", "ensemble"} <= kinds
    assert any(s.id == "XRT_1" for s in plan)          # XRT variant
    assert any(s.provider == "XGBoost" for s in plan)


def test_automl_per_model_cap_enforced(classif_frame):
    """max_runtime_secs_per_model must actually bound slow models
    (VERDICT r1 weak #5: silently-ignored params are worse than
    rejections). Builders that honor max_runtime_secs stop GRACEFULLY
    at a chunk boundary and return the partial model — the reference
    Model.Parameters._max_runtime_secs semantic — so the cap manifests
    as a truncated forest, not a cancelled job."""
    from h2o3_tpu.automl.executor import Budget, train_capped
    from h2o3_tpu.models.gbm import GBMEstimator
    budget = Budget(max_models=10, max_runtime_secs=0,
                    per_model_secs=0.02)       # impossibly small cap
    m = train_capped(GBMEstimator(ntrees=400, max_depth=6, seed=1),
                     classif_frame, "y", None, budget)
    n_trees = int(m.forest.feat.shape[0])
    assert n_trees < 400, \
        f"cap ignored: trained the full {n_trees}-tree forest"
