"""Flight recorder, trace export, logging pipeline, RED metrics
(ISSUE 5): per-job telemetry capsules in the DKV, Chrome-trace JSON on
``GET /3/Jobs/{id}/trace`` / ``GET /3/Trace``, the rebuilt utils/log.py
pipeline behind real ``/3/Logs`` handlers, plus the satellite
regressions — the /3/Metrics scrape race, span-relative device peaks,
``get_logger`` hierarchy normalization, and the README metric-name
drift check.
"""

import json
import os
import re
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.core.job import CANCELLED, DONE, Job
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.telemetry import flight_recorder, trace_export
from h2o3_tpu.telemetry.trace_export import COMPILE_TID
from h2o3_tpu.utils import timeline
from h2o3_tpu.utils import log as logmod
from h2o3_tpu.utils.log import get_logger, log_buffer


def _mk_class_frame(n=300, f=3, seed=0, key=None):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(f)}
    cols["y"] = np.array(["n", "p"], object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"], key=key)


# ------------------------------------------------------------- capsules


def test_capsule_captures_spans_events_logs_compiles():
    """A job's capsule holds its span subtree, timeline events, log
    records, compile events, and start/end counter deltas."""
    probe = f"fr-capsule-probe-{os.getpid()}"

    def work(job):
        with telemetry.span("flt.phase", step=1):
            get_logger("flt").info("%s", probe)
            timeline.record("flt", probe)
            # a fresh tiny jit → ≥1 monitored backend compile inside
            # the job, deterministically (small compiles are never
            # persisted to the XLA disk cache)
            jax.jit(lambda x: x * 3 + 1)(jnp.ones((5,))).block_until_ready()
        return "ok"

    j = Job("flt capsule").start(work)
    assert j.status == DONE
    cap = flight_recorder.get_capsule(j.key)
    assert cap is not None
    d = cap.to_dict()
    assert d["status"] == DONE and d["job_key"] == j.key
    names = {s["name"] for s in d["spans"]}
    assert {"job", "flt.phase"} <= names
    # the work span nests under the job root span
    root = next(s for s in d["spans"] if s["name"] == "job")
    phase = next(s for s in d["spans"] if s["name"] == "flt.phase")
    assert phase["parent_id"] == root["id"]
    assert any(e.get("what") == probe for e in d["events"])
    assert any(probe in l["msg"] for l in d["logs"])
    assert len(d["compiles"]) >= 1
    assert all({"ts_ms", "dur_s"} <= set(c) for c in d["compiles"])
    assert d["metric_deltas"].get("h2o3tpu_spans_total", 0) >= 2
    assert d["metric_deltas"].get("h2o3tpu_xla_compile_total", 0) >= 1


def test_cancelled_job_capsule_swept_with_scope():
    """Acceptance: a cancelled job's capsule is swept with its Scope —
    no ``<job>_telemetry`` key survives in the DKV."""
    started = threading.Event()

    def work(job):
        started.set()
        while True:
            time.sleep(0.01)
            job.update(0.0)

    j = Job("flt cancel")
    j.start(work, background=True)
    assert started.wait(20)
    # the capsule exists while the job runs
    assert flight_recorder.capsule_key(j.key) in DKV
    j.cancel()
    j.join(30)
    assert j.status == CANCELLED
    assert flight_recorder.get_capsule(j.key) is None
    assert flight_recorder.capsule_key(j.key) not in DKV
    DKV.remove(j.key)


def test_capsule_retention_ring(monkeypatch):
    """Only the newest H2O3TPU_FLIGHT_RECORDER_KEEP completed capsules
    stay in the DKV; older ones are evicted."""
    monkeypatch.setenv("H2O3TPU_FLIGHT_RECORDER_KEEP", "2")
    flight_recorder.clear()
    jobs = [Job(f"flt keep {i}").start(lambda job: "ok") for i in range(4)]
    assert all(j.status == DONE for j in jobs)
    assert flight_recorder.get_capsule(jobs[0].key) is None
    assert flight_recorder.get_capsule(jobs[1].key) is None
    assert flight_recorder.get_capsule(jobs[2].key) is not None
    assert flight_recorder.get_capsule(jobs[3].key) is not None


def test_capsule_bounded(monkeypatch):
    """A span storm truncates the capsule and counts the drops — the
    capsule is a bounded artifact, never an unbounded one."""
    monkeypatch.setattr(flight_recorder, "MAX_SPANS", 16)

    def work(job):
        for i in range(40):
            with telemetry.span("flt.storm"):
                pass
        return "ok"

    j = Job("flt bounded").start(work)
    cap = flight_recorder.get_capsule(j.key)
    assert cap is not None
    d = cap.to_dict()
    assert len(d["spans"]) == 16
    assert d["dropped"]["spans"] >= 24


def test_nested_foreground_job_captured_by_both():
    """A foreground job started inside another job's work (the grid →
    model-build shape) lands in its own capsule AND its parent's."""
    inner_key = {}

    def inner(job):
        with telemetry.span("flt.inner_work"):
            pass
        return "inner"

    def outer(job):
        ij = Job("flt inner").start(inner)
        inner_key["k"] = ij.key
        return "outer"

    oj = Job("flt outer").start(outer)
    outer_cap = flight_recorder.get_capsule(oj.key).to_dict()
    inner_cap = flight_recorder.get_capsule(inner_key["k"]).to_dict()
    assert any(s["name"] == "flt.inner_work" for s in inner_cap["spans"])
    assert any(s["name"] == "flt.inner_work" for s in outer_cap["spans"])


# ------------------------------------------------------- trace export


def test_build_trace_structure():
    spans = [
        {"id": "sp-1", "parent_id": None, "name": "job", "start_ms": 1000,
         "duration_ms": 100.0, "device_peak_bytes": 0,
         "collective_bytes": 0, "meta": {}},
        {"id": "sp-2", "parent_id": "sp-1", "name": "fit",
         "start_ms": 1010, "duration_ms": 50.0, "device_peak_bytes": 7,
         "collective_bytes": 2.0, "meta": {"algo": "gbm"}},
        {"id": "sp-9", "parent_id": None, "name": "other_root",
         "start_ms": 2000, "duration_ms": 5.0, "device_peak_bytes": 0,
         "collective_bytes": 0, "meta": {}},
    ]
    events = [{"seq": 1, "ts_ms": 1020, "kind": "flt", "what": "moment",
               "span_id": "sp-2"},
              {"seq": 2, "ts_ms": 1021, "kind": "flt", "what": "free"}]
    compiles = [{"ts_ms": 1040, "dur_s": 0.02, "event": "xla_compile"}]
    trace = trace_export.build_trace(spans, events, compiles)
    evs = trace["traceEvents"]
    assert all({"ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    xs = {e["name"]: e for e in evs if e["ph"] == "X" and e["cat"] == "span"}
    # one tree → one tid; the second root gets its own track
    assert xs["fit"]["tid"] == xs["job"]["tid"]
    assert xs["other_root"]["tid"] != xs["job"]["tid"]
    # temporal nesting preserved (child contained in parent)
    assert xs["job"]["ts"] <= xs["fit"]["ts"]
    assert xs["fit"]["ts"] + xs["fit"]["dur"] <= \
        xs["job"]["ts"] + xs["job"]["dur"]
    assert xs["fit"]["args"]["parent_id"] == "sp-1"
    # the instant with a span_id rides its span's track; the free one
    # lands on the timeline track
    inst = {e["name"]: e for e in evs if e["ph"] == "i"}
    assert inst["moment"]["tid"] == xs["fit"]["tid"]
    assert inst["free"]["tid"] == trace_export.TIMELINE_TID
    comp = [e for e in evs if e["cat"] == "compile"]
    assert comp and all(e["tid"] == COMPILE_TID for e in comp)
    json.dumps(trace)   # strictly serializable


def test_process_trace_is_valid():
    with telemetry.span("flt.ring_probe"):
        timeline.record("flt", "ring-probe-moment")
    trace = trace_export.process_trace()
    evs = trace["traceEvents"]
    assert all({"ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    assert any(e["name"] == "flt.ring_probe" for e in evs)
    json.dumps(trace)


def test_write_trace_artifact(tmp_path):
    path = str(tmp_path / "sub" / "trace.json")
    trace_export.write_trace(path, trace_export.process_trace())
    with open(path) as f:
        j = json.load(f)
    assert "traceEvents" in j


# ------------------------------------------------- satellite: registry


def test_metrics_scrape_race_stress():
    """Satellite: snapshot()/to_prometheus()/value()/total() racing
    first-touch metric creation on 8 threads must never raise
    (pre-fix: RuntimeError: dictionary changed size during iteration)."""
    stop = threading.Event()
    errors = []

    def creator(i):
        n = 0
        while not stop.is_set():
            telemetry.counter("flt_race_total",
                              tag=f"t{i}_{n % 200}").inc()
            n += 1

    def scraper():
        while not stop.is_set():
            try:
                telemetry.snapshot()
                telemetry.to_prometheus()
                telemetry.REGISTRY.total("flt_race_total")
                telemetry.REGISTRY.value("flt_race_total", tag="t0_0")
            except Exception as e:   # noqa: BLE001 - the assertion
                errors.append(e)
                return

    threads = [threading.Thread(target=creator, args=(i,))
               for i in range(8)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors, errors


# --------------------------------------------- satellite: span peaks


def test_span_device_peak_is_span_relative(monkeypatch):
    """Satellite: device_peak_bytes reports the high-water RISE during
    the span, not the process-wide max — a span after the global peak
    reports 0."""
    from h2o3_tpu.telemetry import spans as spans_mod
    seq = iter([100, 100, 100, 250])
    monkeypatch.setattr(spans_mod, "_device_peak", lambda: next(seq))
    with spans_mod.span("flt.peak_outer") as so:
        with spans_mod.span("flt.peak_inner") as si:
            pass
    assert si.device_peak_bytes == 0       # no rise during the inner span
    assert so.device_peak_bytes == 150     # the outer span saw the rise


# ------------------------------------------------ satellite: log names


def test_get_logger_normalizes_into_hierarchy():
    """Satellite: bare names become h2o3_tpu.<name> children so every
    logger reaches the configured sinks."""
    assert get_logger("parser").name == "h2o3_tpu.parser"
    assert get_logger("h2o3_tpu.job").name == "h2o3_tpu.job"
    assert get_logger().name == "h2o3_tpu"
    probe = f"fr-bare-name-probe-{os.getpid()}"
    get_logger("flt_bare").info("%s", probe)
    assert any(probe in ln for ln in log_buffer())


def test_log_pipeline_json_file_and_context(tmp_path):
    """JSON-lines formatter + rotating file sink + span/job context
    stamps; per-level rings select by level."""
    logmod.configure(log_dir=str(tmp_path), json_lines=True)
    try:
        with telemetry.span("flt.logspan") as sp:
            get_logger("flt_file").warning("json-file-probe")
        path = logmod.log_file_path()
        assert path and os.path.exists(path)
        with open(path) as f:
            lines = [json.loads(ln) for ln in f if "json-file-probe" in ln]
        assert lines, "file sink missed the record"
        rec = lines[-1]
        assert rec["level"] == "WARNING"
        assert rec["logger"] == "h2o3_tpu.flt_file"
        assert rec["msg"] == "json-file-probe"
        assert rec["span_id"] == sp.id
        # per-level ring
        assert any("json-file-probe" in ln
                   for ln in log_buffer(level="WARNING"))
        assert logmod.level_counts()["WARNING"] >= 1
    finally:
        logmod.configure()          # restore env defaults


def test_log_records_carry_job_id():
    def work(job):
        get_logger("flt_jobctx").info("job-ctx-probe")
        return "ok"

    j = Job("flt logctx").start(work)
    cap = flight_recorder.get_capsule(j.key).to_dict()
    rec = next(l for l in cap["logs"] if "job-ctx-probe" in l["msg"])
    assert rec["job_id"] == j.key


# ------------------------------------------- satellite: metric names


def test_metric_names_documented_in_readme():
    """Satellite drift check: every counter/gauge/histogram literal in
    h2o3_tpu/ must appear in README §Observability — the README
    promises a stable metric surface; keep it honest."""
    rx = re.compile(r'\b(?:counter|gauge|histogram)\(\s*"([a-z0-9_]+)"')
    root = os.path.join(os.path.dirname(__file__), "..", "h2o3_tpu")
    names = set()
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn)) as f:
                    names.update(rx.findall(f.read()))
    assert names, "metric literal scan found nothing — regex rot?"
    readme = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(readme) as f:
        text = f.read()
    lo = text.index("## Observability")
    hi = text.index("\n## ", lo + 1)
    section = text[lo:hi]
    missing = sorted(n for n in names if n not in section)
    assert not missing, (
        f"metric names not documented in README §Observability: "
        f"{missing}")
    # the ISSUE 8 surface is part of the stable contract: the cluster
    # fan-in + roofline names must stay documented even if a refactor
    # moves their instrumentation call sites out of the literal scan
    for required in ("model_fit_mfu", "model_fit_hbm_util",
                     "roofline_fits_total", "cluster_publish_total",
                     "cluster_publish_bytes", "cluster_stale_nodes",
                     "jobs_inflight"):
        assert required in section, required
    # the ISSUE 9 in-fit checkpointing surface is part of the stable
    # contract too (core/recovery.py FitCheckpointer)
    for required in ("fit_checkpoints_written_total", "fit_resumes_total",
                     "fit_checkpoint_seconds",
                     "snapshot_load_failures_total"):
        assert required in section, required
    # the ISSUE 11 memory-governance surface (core/memgov.py) is part
    # of the stable contract too
    for required in ("hbm_budget_bytes", "hbm_bytes_in_use",
                     "frames_spilled_bytes", "frame_spills_total",
                     "frame_restores_total",
                     "fit_admission_rejections_total",
                     "oom_recoveries_total"):
        assert required in section, required
    # the ISSUE 12 chunk-parallel ingest surface (io/stream.py,
    # io/formats.py, io/parser.py) is part of the stable contract too
    for required in ("ingest_bytes_total", "ingest_rows_total",
                     "parse_chunk_seconds"):
        assert required in section, required
    # the ISSUE 14 low-latency serving surface (serving/engine.py,
    # serving/batcher.py) is part of the stable contract too
    for required in ("predict_requests_total", "predict_batch_width",
                     "predict_seconds", "scorer_cache_hits_total",
                     "scorer_cache_misses_total",
                     "scorer_cache_evictions_total",
                     "scorer_cache_bytes"):
        assert required in section, required
    # the ISSUE 15 cluster work-scheduler surface
    # (parallel/scheduler.py) is part of the stable contract too
    for required in ("sched_runs_total", "sched_items_total",
                     "sched_items_completed_total",
                     "sched_items_reassigned_total",
                     "sched_leases_held", "sched_item_seconds"):
        assert required in section, required
    # the ISSUE 16 tracing + SLO surface (telemetry/trace_context.py,
    # telemetry/slo.py) is part of the stable contract too
    for required in ("slo_burn_rate", "slo_alert_active",
                     "slo_alert_transitions_total",
                     "X-H2O-Trace-Id", "traceparent",
                     "/3/Alerts", "trace_id="):
        assert required in section, required
    # the ISSUE 17 fleet serving-resilience surface (serving/fleet.py)
    # is part of the stable contract too
    for required in ("fleet_replicas_healthy", "predict_routed_total",
                     "predict_failovers_total", "replica_warm_seconds"):
        assert required in section, required
    # the ISSUE 18 durable-data-plane surface (core/durability.py)
    # is part of the stable contract too
    for required in ("frames_mirrored_bytes", "frame_rebuilds_total",
                     "frame_rebuild_seconds", "cloud_restore_seconds",
                     "frames_under_replicated"):
        assert required in section, required
    # the ISSUE 20 step-profiling + perf-baseline surface
    # (telemetry/stepprof.py, telemetry/perfbase.py) is part of the
    # stable contract too
    for required in ("model_fit_phase_seconds", "pod_step_skew_ratio",
                     "pod_straggler_host", "fit_step_baseline_ratio",
                     "stepprof_fits_total", "H2O3TPU_STEPPROF",
                     "H2O3TPU_STEPPROF_RING", "benchdiff",
                     "perf_baselines", "/profile"):
        assert required in section, required


# ----------------------------------------------------------- REST tier


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


def _post(port, path, data=b""):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return r.status, r.read()


@pytest.mark.allow_key_leak   # REST handler threads create keys
def test_rest_gbm_trace_golden(port):
    """Acceptance: a GBM fit driven through REST yields Chrome-trace
    JSON at GET /3/Jobs/{id}/trace — every event has ph/ts/pid/tid,
    span nesting is preserved, ≥3 distinct phases, ≥1 compile event."""
    # 17 features: a shape no other test in this process uses, so the
    # boost scan compiles fresh INSIDE the traced job
    _mk_class_frame(n=351, f=17, seed=3, key="flt_trace_train")
    st, body = _post(
        port,
        "/3/ModelBuilders/gbm?training_frame=flt_trace_train"
        "&response_column=y&ntrees=4&max_depth=3&seed=5"
        "&model_id=flt_trace_model")
    assert st == 200
    jk = json.loads(body)["job"]["key"]["name"]
    for _ in range(600):
        st, body = _get(port, f"/3/Jobs/{jk}")
        if json.loads(body)["jobs"][0]["status"] not in ("CREATED",
                                                         "RUNNING"):
            break
        time.sleep(0.05)
    assert json.loads(body)["jobs"][0]["status"] == "DONE"

    st, body = _get(port, f"/3/Jobs/{jk}/trace")
    assert st == 200
    trace = json.loads(body)          # must json.loads cleanly
    evs = trace["traceEvents"]
    assert all({"ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    span_evs = [e for e in evs if e["ph"] == "X" and e["cat"] == "span"]
    names = {e["name"] for e in span_evs}
    assert len(names & {"job", "gbm.fit", "gbm.chunk"}) == 3, names
    # nesting: gbm.fit under job, gbm.chunk under gbm.fit (by parent id
    # AND by temporal containment on one track)
    by_sid = {e["args"]["span_id"]: e for e in span_evs}
    job_ev = next(e for e in span_evs if e["name"] == "job")
    fit_ev = next(e for e in span_evs if e["name"] == "gbm.fit")
    chunk_ev = next(e for e in span_evs if e["name"] == "gbm.chunk")
    assert by_sid[fit_ev["args"]["parent_id"]] is job_ev
    assert by_sid[chunk_ev["args"]["parent_id"]] is fit_ev
    assert job_ev["tid"] == fit_ev["tid"] == chunk_ev["tid"]
    assert job_ev["ts"] <= fit_ev["ts"]
    assert fit_ev["ts"] + fit_ev["dur"] <= \
        job_ev["ts"] + job_ev["dur"] + 1000   # ≤1ms rounding slack
    compiles = [e for e in evs if e["cat"] == "compile"]
    assert len(compiles) >= 1
    assert all(e["tid"] == COMPILE_TID for e in compiles)

    # the raw capsule rides the sibling endpoint
    st, body = _get(port, f"/3/Jobs/{jk}/telemetry")
    assert st == 200
    assert json.loads(body)["status"] == "DONE"

    for k in (jk, "flt_trace_model", "flt_trace_train",
              flight_recorder.capsule_key(jk)):
        DKV.remove(k)


@pytest.mark.allow_key_leak
def test_rest_trace_unknown_job_404(port):
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/3/Jobs/job_nope/trace")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


@pytest.mark.allow_key_leak
def test_rest_process_trace(port):
    with telemetry.span("flt.rest_ring"):
        pass
    st, body = _get(port, "/3/Trace")
    assert st == 200
    trace = json.loads(body)
    evs = trace["traceEvents"]
    assert all({"ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    assert any(e["name"] == "flt.rest_ring" for e in evs)


@pytest.mark.allow_key_leak
def test_rest_logs_roundtrip(port):
    """Satellite acceptance: a logged line round-trips through
    GET /3/Logs and /3/Logs/download (the pre-fix stub returned
    {"log": ""} unconditionally)."""
    probe = f"fr-logs-roundtrip-{os.getpid()}"
    get_logger("flt_rest").warning("%s", probe)
    st, body = _get(port, "/3/Logs")
    assert st == 200
    j = json.loads(body)
    assert any(probe in ln for ln in j["lines"])
    assert probe in j["log"]
    st, body = _get(port, "/3/Logs?level=WARNING&last=50")
    assert st == 200
    assert any(probe in ln for ln in json.loads(body)["lines"])
    st, body = _get(port, "/3/Logs/download")
    assert st == 200
    assert probe in body.decode()


@pytest.mark.slow
@pytest.mark.allow_key_leak
def test_rest_profiler_capture_real(port):
    """POST /3/Profiler/capture: a real bounded jax.profiler window
    (slow: profiler start/stop alone costs ~10s on this jaxlib —
    tier-1 covers the endpoint via the degrade test below)."""
    st, body = _post(port, "/3/Profiler/capture?duration_ms=60")
    assert st == 200
    j = json.loads(body)
    assert "supported" in j
    if j["supported"]:
        assert j["log_dir"] and os.path.isdir(j["log_dir"])


@pytest.mark.allow_key_leak
def test_rest_profiler_capture_degrades(port, monkeypatch):
    """A backend that can't profile answers supported=false with the
    error string — never a 500 (the graceful-degrade contract)."""
    import jax.profiler as _prof
    monkeypatch.setattr(
        _prof, "start_trace",
        lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("profiling unsupported on this backend")))
    st, body = _post(port, "/3/Profiler/capture?duration_ms=60")
    assert st == 200
    j = json.loads(body)
    assert j["supported"] is False
    assert "unsupported" in j["error"]


@pytest.mark.allow_key_leak
def test_rest_request_seconds_histogram(port):
    before = telemetry.REGISTRY.value("rest_request_seconds",
                                      route="/3/Ping", status="200")
    st, _ = _get(port, "/3/Ping")
    assert st == 200
    after = telemetry.REGISTRY.value("rest_request_seconds",
                                     route="/3/Ping", status="200")
    assert after == before + 1


@pytest.mark.allow_key_leak
def test_rest_metrics_never_500_under_creation_storm(port):
    """Acceptance: GET /3/Metrics under ≥8 threads creating fresh label
    sets never returns 500."""
    stop = threading.Event()

    def creator(i):
        n = 0
        while not stop.is_set():
            telemetry.counter("flt_storm_total",
                              tag=f"s{i}_{n % 200}").inc()
            telemetry.histogram("flt_storm_seconds",
                                tag=f"s{i}_{n % 50}").observe(0.001)
            n += 1
            # fresh label sets keep coming, but yield the GIL so the
            # scrapes stay fast — the race is about creation vs
            # iteration, not about starving the scraper
            time.sleep(0.001)

    threads = [threading.Thread(target=creator, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    try:
        for k in range(12):
            path = "/3/Metrics" if k % 2 == 0 else \
                "/3/Metrics?format=prometheus"
            st, _body = _get(port, path)
            assert st == 200
    finally:
        stop.set()
        for t in threads:
            t.join(10)


def test_queue_wait_histogram_observed():
    from h2o3_tpu.api.server import AdmissionGate
    gate = AdmissionGate(max_inflight=1, queue_depth=4, queue_wait_s=5.0)
    before = telemetry.REGISTRY.value("rest_queue_wait_seconds")
    assert gate.enter()
    got = []

    def waiter():
        got.append(gate.enter())

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    gate.leave()
    t.join(10)
    assert got == [True]
    gate.leave()
    assert telemetry.REGISTRY.value("rest_queue_wait_seconds") == before + 1
