"""MOJO export / offline-scoring conformance.

The testdir_javapredict analogue (SURVEY §4): in-cluster predictions and
MOJO (numpy-only offline runtime) predictions must agree to float
precision on the same raw rows.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.genmodel import EasyPredictModelWrapper, load_mojo
from tests.conftest import make_classification, make_regression


def _raw_cols(frame, names):
    from h2o3_tpu.models.generic import _frame_raw_columns
    return _frame_raw_columns(frame, names)


def _roundtrip(model, frame, tmp_path, atol=1e-4):
    path = str(tmp_path / f"{model.algo}.zip")
    model.download_mojo(path)
    mojo = load_mojo(path)
    incluster = model._score_raw(frame)
    offline = mojo.predict(_raw_cols(frame, mojo.names))
    for k in incluster:
        if k not in offline:
            continue
        a = np.asarray(incluster[k], dtype=np.float64)
        b = np.asarray(offline[k], dtype=np.float64)
        assert np.allclose(a, b, atol=atol), (
            f"{model.algo}/{k}: max diff {np.abs(a - b).max()}")
    return mojo


def test_gbm_binomial_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=10, max_depth=4, seed=7).train(
        classif_frame, y="y")
    mojo = _roundtrip(m, classif_frame, tmp_path)
    # EasyPredict single row
    row = {f"x{i}": 0.1 * i for i in range(8)}
    pred = EasyPredictModelWrapper(mojo).predict(row)
    assert pred.label in ("no", "yes")
    assert abs(sum(pred.class_probabilities) - 1.0) < 1e-6


def test_gbm_regression_mojo(regress_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=10, max_depth=4, seed=7).train(
        regress_frame, y="y")
    _roundtrip(m, regress_frame, tmp_path)


def test_gbm_multinomial_mojo(tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(3)
    X = r.randn(600, 4)
    y = (X[:, 0] + 0.7 * X[:, 1] > 0).astype(int) + (X[:, 2] > 1).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["a", "b", "c"], object)[y]}, categorical=["y"])
    m = GBMEstimator(ntrees=6, max_depth=3, seed=7).train(fr, y="y")
    _roundtrip(m, fr, tmp_path)


def test_drf_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.drf import DRFEstimator
    m = DRFEstimator(ntrees=8, max_depth=4, seed=7).train(classif_frame, y="y")
    _roundtrip(m, classif_frame, tmp_path)


def test_glm_mojo_with_categoricals(tmp_path):
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(5)
    n = 800
    x0 = r.randn(n)
    g = np.array(["u", "v", "w"], object)[r.randint(0, 3, n)]
    logit = x0 + (g == "v") * 1.2 - (g == "w") * 0.7
    y = (r.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x0": x0, "g": g, "y": np.array(["n", "y"], object)[y]},
        categorical=["g", "y"])
    m = GLMEstimator(family="binomial", lambda_=0.0).train(fr, y="y")
    _roundtrip(m, fr, tmp_path)


def test_kmeans_mojo(tmp_path):
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(1)
    X = np.concatenate([r.randn(200, 3) + 4, r.randn(200, 3) - 4])
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    m = KMeansEstimator(k=2, seed=3).train(fr)
    _roundtrip(m, fr, tmp_path)


def test_deeplearning_mojo(regress_frame, tmp_path):
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    m = DeepLearningEstimator(hidden=[16], epochs=2, seed=5).train(
        regress_frame, y="y")
    _roundtrip(m, regress_frame, tmp_path, atol=1e-3)


def test_isofor_mojo(tmp_path):
    from h2o3_tpu.models.isofor import IsolationForestEstimator
    r = np.random.RandomState(2)
    X = r.randn(500, 4)
    X[:8] += 6.0  # anomalies
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = IsolationForestEstimator(ntrees=10, seed=3).train(fr)
    _roundtrip(m, fr, tmp_path)


def test_generic_estimator_imports_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.generic import GenericEstimator
    m = GBMEstimator(ntrees=6, max_depth=3, seed=7).train(classif_frame, y="y")
    path = str(tmp_path / "g.zip")
    m.download_mojo(path)
    gm = GenericEstimator(path=path).train(classif_frame, y="y")
    # predictions agree with the source model
    a = m.predict(classif_frame).col("p1").to_numpy()
    b = gm.predict(classif_frame).col("p1").to_numpy()
    assert np.allclose(a, b, atol=1e-5)
    # and it produces metrics like a first-class model
    assert gm.training_metrics is not None
    assert gm.training_metrics["AUC"] > 0.7


def test_generic_without_frame(tmp_path, classif_frame):
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.generic import GenericEstimator
    m = GBMEstimator(ntrees=4, max_depth=3, seed=7).train(classif_frame, y="y")
    path = str(tmp_path / "g2.zip")
    m.download_mojo(path)
    gm = GenericEstimator(path=path).train()
    out = gm.predict(classif_frame)
    assert "p1" in out.names
