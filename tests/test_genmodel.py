"""MOJO export / offline-scoring conformance.

The testdir_javapredict analogue (SURVEY §4): in-cluster predictions and
MOJO (numpy-only offline runtime) predictions must agree to float
precision on the same raw rows.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.genmodel import EasyPredictModelWrapper, load_mojo
from tests.conftest import make_classification, make_regression


def _raw_cols(frame, names):
    from h2o3_tpu.models.generic import _frame_raw_columns
    return _frame_raw_columns(frame, names)


def _roundtrip(model, frame, tmp_path, atol=1e-4):
    path = str(tmp_path / f"{model.algo}.zip")
    model.download_mojo(path)
    mojo = load_mojo(path)
    incluster = model._score_raw(frame)
    offline = mojo.predict(_raw_cols(frame, mojo.names))
    for k in incluster:
        if k not in offline:
            continue
        a = np.asarray(incluster[k], dtype=np.float64)
        b = np.asarray(offline[k], dtype=np.float64)
        if k == "predict" and a.dtype.kind in "fiu" and np.all(a == a.astype(int)):
            # class labels may flip on rows whose probability sits exactly
            # at the decision threshold (float noise) — bound the rate
            assert (a != b).mean() < 5e-3, (
                f"{model.algo}/predict: {(a != b).sum()} label flips")
        else:
            assert np.allclose(a, b, atol=atol), (
                f"{model.algo}/{k}: max diff {np.abs(a - b).max()}")
    return mojo


def test_gbm_binomial_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=10, max_depth=4, seed=7).train(
        classif_frame, y="y")
    mojo = _roundtrip(m, classif_frame, tmp_path)
    # EasyPredict single row
    row = {f"x{i}": 0.1 * i for i in range(8)}
    pred = EasyPredictModelWrapper(mojo).predict(row)
    assert pred.label in ("no", "yes")
    assert abs(sum(pred.class_probabilities) - 1.0) < 1e-6


def test_gbm_regression_mojo(regress_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=10, max_depth=4, seed=7).train(
        regress_frame, y="y")
    _roundtrip(m, regress_frame, tmp_path)


def test_gbm_multinomial_mojo(tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(3)
    X = r.randn(600, 4)
    y = (X[:, 0] + 0.7 * X[:, 1] > 0).astype(int) + (X[:, 2] > 1).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["a", "b", "c"], object)[y]}, categorical=["y"])
    m = GBMEstimator(ntrees=6, max_depth=3, seed=7).train(fr, y="y")
    _roundtrip(m, fr, tmp_path)


def test_drf_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.drf import DRFEstimator
    m = DRFEstimator(ntrees=8, max_depth=4, seed=7).train(classif_frame, y="y")
    _roundtrip(m, classif_frame, tmp_path)


def test_glm_mojo_with_categoricals(tmp_path):
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(5)
    n = 800
    x0 = r.randn(n)
    g = np.array(["u", "v", "w"], object)[r.randint(0, 3, n)]
    logit = x0 + (g == "v") * 1.2 - (g == "w") * 0.7
    y = (r.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x0": x0, "g": g, "y": np.array(["n", "y"], object)[y]},
        categorical=["g", "y"])
    m = GLMEstimator(family="binomial", lambda_=0.0).train(fr, y="y")
    _roundtrip(m, fr, tmp_path)


def test_kmeans_mojo(tmp_path):
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(1)
    X = np.concatenate([r.randn(200, 3) + 4, r.randn(200, 3) - 4])
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    m = KMeansEstimator(k=2, seed=3).train(fr)
    _roundtrip(m, fr, tmp_path)


def test_deeplearning_mojo(regress_frame, tmp_path):
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    m = DeepLearningEstimator(hidden=[16], epochs=2, seed=5).train(
        regress_frame, y="y")
    _roundtrip(m, regress_frame, tmp_path, atol=1e-3)


def test_isofor_mojo(tmp_path):
    from h2o3_tpu.models.isofor import IsolationForestEstimator
    r = np.random.RandomState(2)
    X = r.randn(500, 4)
    X[:8] += 6.0  # anomalies
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = IsolationForestEstimator(ntrees=10, seed=3).train(fr)
    _roundtrip(m, fr, tmp_path)


def test_pca_svd_mojo(tmp_path):
    from h2o3_tpu.models.pca import PCAEstimator, SVDEstimator
    r = np.random.RandomState(4)
    X = r.randn(400, 5) @ r.randn(5, 5)
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(5)})
    m = PCAEstimator(k=3, seed=1).train(fr)
    _roundtrip(m, fr, tmp_path, atol=1e-3)
    s = SVDEstimator(nv=2, seed=1).train(fr)
    _roundtrip(s, fr, tmp_path, atol=1e-3)


def test_isotonic_mojo(tmp_path):
    from h2o3_tpu.models.isotonic import IsotonicRegressionEstimator
    r = np.random.RandomState(2)
    x = np.sort(r.randn(500))
    y = np.tanh(x) + 0.1 * r.randn(500)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y})
    m = IsotonicRegressionEstimator().train(fr, y="y", x=["x"])
    _roundtrip(m, fr, tmp_path)


def test_coxph_mojo(tmp_path):
    from h2o3_tpu.models.coxph import CoxPHEstimator
    r = np.random.RandomState(3)
    n = 400
    x = r.randn(n)
    t = np.exp(1.0 - 0.8 * x + 0.4 * r.randn(n))
    ev = (r.rand(n) < 0.8).astype(float)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "time": t, "event": ev})
    m = CoxPHEstimator(start_column=None, stop_column="time").train(
        fr, y="event", x=["x"])
    path = str(tmp_path / "coxph.zip")
    m.download_mojo(path)
    mojo = load_mojo(path)
    off = mojo.predict({"x": x})["lp"]
    inc = m._score_raw(fr)["lp"]
    assert np.allclose(off, inc, atol=1e-4)


def test_naivebayes_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.naivebayes import NaiveBayesEstimator
    m = NaiveBayesEstimator().train(classif_frame, y="y")
    _roundtrip(m, classif_frame, tmp_path)


def test_uplift_mojo(tmp_path):
    from h2o3_tpu.models.uplift import UpliftDRFEstimator
    r = np.random.RandomState(5)
    n = 800
    x = r.randn(n)
    tr = r.randint(0, 2, n)
    p = 0.3 + 0.2 * tr * (x > 0)
    y = (r.rand(n) < p).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x": x, "treat": np.array(["c", "t"], object)[tr],
         "y": np.array(["no", "yes"], object)[y]},
        categorical=["treat", "y"])
    m = UpliftDRFEstimator(treatment_column="treat", ntrees=5, max_depth=3,
                           seed=1).train(fr, y="y")
    _roundtrip(m, fr, tmp_path)


def test_extisofor_mojo(tmp_path):
    from h2o3_tpu.models.extisofor import ExtendedIsolationForestEstimator
    r = np.random.RandomState(6)
    X = r.randn(500, 3)
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    m = ExtendedIsolationForestEstimator(ntrees=8, seed=2).train(fr)
    _roundtrip(m, fr, tmp_path)


def test_word2vec_mojo(tmp_path):
    from h2o3_tpu.models.word2vec import Word2VecEstimator
    words = (["cat", "dog", "pet", None] * 60)
    fr = h2o3_tpu.Frame.from_numpy(
        {"words": np.asarray(words, dtype=object)}, categorical=["words"])
    m = Word2VecEstimator(vec_size=8, epochs=3, min_word_freq=2,
                          sent_sample_rate=0.0, seed=1).train(fr)
    path = str(tmp_path / "w2v.zip")
    m.download_mojo(path)
    mojo = load_mojo(path)
    out = mojo.predict({"words": np.asarray(["cat", "zzz"], object)})
    assert not np.isnan(out["V1"][0])
    assert np.isnan(out["V1"][1])
    syn = mojo.find_synonyms("cat", 2)
    assert len(syn) == 2


def test_generic_estimator_imports_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.generic import GenericEstimator
    m = GBMEstimator(ntrees=6, max_depth=3, seed=7).train(classif_frame, y="y")
    path = str(tmp_path / "g.zip")
    m.download_mojo(path)
    gm = GenericEstimator(path=path).train(classif_frame, y="y")
    # predictions agree with the source model
    a = m.predict(classif_frame).col("p1").to_numpy()
    b = gm.predict(classif_frame).col("p1").to_numpy()
    assert np.allclose(a, b, atol=1e-5)
    # and it produces metrics like a first-class model
    assert gm.training_metrics is not None
    assert gm.training_metrics["AUC"] > 0.7


def test_generic_without_frame(tmp_path, classif_frame):
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.generic import GenericEstimator
    m = GBMEstimator(ntrees=4, max_depth=3, seed=7).train(classif_frame, y="y")
    path = str(tmp_path / "g2.zip")
    m.download_mojo(path)
    gm = GenericEstimator(path=path).train()
    out = gm.predict(classif_frame)
    assert "p1" in out.names


def test_glrm_mojo(tmp_path):
    from h2o3_tpu.models.glrm import GLRMEstimator
    r = np.random.RandomState(8)
    W = r.randn(300, 2) @ r.randn(2, 5)
    W[r.rand(*W.shape) < 0.05] = np.nan    # missing cells
    fr = h2o3_tpu.Frame.from_numpy({f"x{i}": W[:, i] for i in range(5)})
    m = GLRMEstimator(k=2, max_iterations=30, seed=1).train(fr)
    _roundtrip(m, fr, tmp_path, atol=1e-3)


def test_rulefit_mojo(classif_frame, tmp_path):
    from h2o3_tpu.models.rulefit import RuleFitEstimator
    m = RuleFitEstimator(seed=11, min_rule_length=2, max_rule_length=3,
                         rule_generation_ntrees=12).train(classif_frame, y="y")
    _roundtrip(m, classif_frame, tmp_path)


def test_mojo_contributions_match_incluster(regress_frame, tmp_path):
    """Offline TreeSHAP must equal the in-cluster contributions
    (testdir_javapredict role for predictContributions)."""
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=6, max_depth=3, seed=3).train(regress_frame, y="y")
    path = str(tmp_path / "gbm_shap.zip")
    m.download_mojo(path)
    mojo = load_mojo(path)
    offline = mojo.predict_contributions(_raw_cols(regress_frame, mojo.names))
    incluster = m.predict_contributions(regress_frame)
    for name in incluster.names:
        np.testing.assert_allclose(
            offline[name], incluster.col(name).to_numpy(),
            rtol=1e-4, atol=1e-5)
    # EasyPredict single-row surface
    row = {n: 0.05 * i for i, n in enumerate(mojo.names)}
    contrib = EasyPredictModelWrapper(mojo).predict_contributions(row)
    assert "BiasTerm" in contrib


def _load_pojo(path):
    import importlib.util
    spec = importlib.util.spec_from_file_location("pojo_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pojo_gbm_binomial(classif_frame, tmp_path):
    """Generated-source scorer (POJO role) must match in-cluster scoring
    and import with zero non-stdlib dependencies."""
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=8, max_depth=3, seed=5).train(classif_frame, y="y")
    path = str(tmp_path / "gbm_pojo.py")
    m.download_pojo(path)
    src = open(path).read()
    assert "import numpy" not in src and "import jax" not in src
    mod = _load_pojo(path)
    raw = _raw_cols(classif_frame, mod.NAMES)
    incluster = m._score_raw(classif_frame)
    n = classif_frame.nrows
    for i in range(0, n, max(1, n // 25)):
        row = {k: raw[k][i] for k in raw}
        out = mod.score0(row)
        assert abs(out["p1"] - incluster["p1"][i]) < 1e-5


def test_pojo_gbm_regression_and_drf(regress_frame, classif_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.drf import DRFEstimator
    gm = GBMEstimator(ntrees=6, max_depth=3, seed=5).train(regress_frame, y="y")
    gp = _load_pojo(gm.download_pojo(str(tmp_path / "g.py")))
    raw = _raw_cols(regress_frame, gp.NAMES)
    want = gm._score_raw(regress_frame)["predict"]
    for i in range(0, regress_frame.nrows, 97):
        assert abs(gp.score0({k: raw[k][i] for k in raw})["predict"]
                   - want[i]) < 1e-4
    dm = DRFEstimator(ntrees=6, max_depth=4, seed=5).train(classif_frame, y="y")
    dp = _load_pojo(dm.download_pojo(str(tmp_path / "d.py")))
    raw = _raw_cols(classif_frame, dp.NAMES)
    want = dm._score_raw(classif_frame)["p1"]
    for i in range(0, classif_frame.nrows, 97):
        assert abs(dp.score0({k: raw[k][i] for k in raw})["p1"]
                   - want[i]) < 1e-5


def test_pojo_glm(tmp_path):
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(5)
    fr = h2o3_tpu.Frame.from_numpy({
        "a": r.randn(300), "b": r.randn(300),
        "c": r.choice(["p", "q", "r"], 300),
        "y": r.randn(300)})
    m = GLMEstimator(family="gaussian", lambda_=0.0).train(fr, y="y")
    mod = _load_pojo(m.download_pojo(str(tmp_path / "glm.py")))
    raw = _raw_cols(fr, mod.NAMES)
    want = m._score_raw(fr)["predict"]
    for i in range(0, 300, 29):
        assert abs(mod.score0({k: raw[k][i] for k in raw})["predict"]
                   - want[i]) < 1e-4


def test_pojo_glm_tweedie(tmp_path):
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(7)
    fr = h2o3_tpu.Frame.from_numpy({
        "a": r.randn(300), "b": r.randn(300),
        "y": np.exp(r.randn(300) * 0.3) * (r.rand(300) > 0.2)})
    m = GLMEstimator(family="tweedie", tweedie_variance_power=1.5,
                     lambda_=0.0).train(fr, y="y")
    mod = _load_pojo(m.download_pojo(str(tmp_path / "glm_tw.py")))
    raw = _raw_cols(fr, mod.NAMES)
    want = m._score_raw(fr)["predict"]
    for i in range(0, 300, 29):
        # tweedie link is exp(eta) — the POJO must not fall back to eta
        assert abs(mod.score0({k: raw[k][i] for k in raw})["predict"]
                   - want[i]) < 1e-4 * max(1.0, abs(want[i]))


def test_pojo_deeplearning_and_kmeans(tmp_path):
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(9)
    fr = h2o3_tpu.Frame.from_numpy({
        "a": r.randn(400), "b": r.randn(400),
        "y": np.where(r.randn(400) > 0, "u", "v")}, categorical=["y"])
    dl = DeepLearningEstimator(hidden=[8, 8], epochs=3.0, seed=2).train(
        fr, y="y")
    mod = _load_pojo(dl.download_pojo(str(tmp_path / "dl.py")))
    raw = _raw_cols(fr, mod.NAMES)
    want = dl._score_raw(fr)["p1"]
    for i in range(0, 400, 57):
        assert abs(mod.score0({k: raw[k][i] for k in raw})["p1"]
                   - want[i]) < 1e-4
    km = KMeansEstimator(k=3, seed=2).train(fr, x=["a", "b"])
    kmod = _load_pojo(km.download_pojo(str(tmp_path / "km.py")))
    kraw = _raw_cols(fr, kmod.NAMES)
    kwant = km._score_raw(fr)["predict"]
    hits = sum(kmod.score0({k: kraw[k][i] for k in kraw})["predict"]
               == kwant[i] for i in range(0, 400, 23))
    assert hits >= 16           # allow boundary-tie flips out of 18
