"""Exact closed-form oracles for the linear/clustering/probabilistic
algorithms — tighter than the sklearn-tolerance golden tests
(testdir_golden role, but with analytically-known answers)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame


def test_glm_gaussian_matches_normal_equations():
    """Unpenalized gaussian GLM must solve X'X b = X'y exactly."""
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(0)
    n, p = 500, 4
    X = r.randn(n, p)
    beta = np.array([1.5, -2.0, 0.5, 3.0])
    y = X @ beta + 0.3 * r.randn(n)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y
    fr = Frame.from_numpy(cols)
    m = GLMEstimator(family="gaussian", lambda_=0.0,
                     standardize=False).train(fr, y="y")
    co = m.coefficients
    X1 = np.concatenate([X, np.ones((n, 1))], axis=1)
    exact = np.linalg.solve(X1.T @ X1, X1.T @ y)
    got = np.array([co[f"x{i}"] for i in range(p)] + [co["Intercept"]])
    assert np.abs(got - exact).max() < 5e-4, got - exact


def test_glm_ridge_matches_closed_form():
    """L2-only GLM: (X'X/n + λI) b = X'y/n on standardized data
    (the reference penalizes standardized coefficients, intercept
    unpenalized)."""
    from h2o3_tpu.models.glm import GLMEstimator
    r = np.random.RandomState(1)
    n, p = 400, 3
    X = r.randn(n, p)
    y = X @ np.array([2.0, -1.0, 0.5]) + 0.2 * r.randn(n)
    lam = 0.7
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y
    fr = Frame.from_numpy(cols)
    m = GLMEstimator(family="gaussian", lambda_=lam, alpha=0.0,
                     standardize=True).train(fr, y="y")
    co = m.coefficients
    mu, sd = X.mean(0), X.std(0)
    Xs = (X - mu) / sd
    X1 = np.concatenate([Xs, np.ones((n, 1))], axis=1)
    pen = np.diag([lam] * p + [0.0])
    exact_std = np.linalg.solve(X1.T @ X1 / n + pen, X1.T @ y / n)
    got_raw = np.array([co[f"x{i}"] for i in range(p)])
    exact_raw = exact_std[:p] / sd
    assert np.abs(got_raw - exact_raw).max() < 5e-3


def test_kmeans_recovers_separated_clusters():
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(2)
    centers = np.array([[0.0, 0.0], [10.0, 10.0], [-10.0, 10.0]])
    X = np.concatenate([c + 0.1 * r.randn(200, 2) for c in centers])
    fr = Frame.from_numpy({"a": X[:, 0], "b": X[:, 1]})
    m = KMeansEstimator(k=3, standardize=False, seed=7,
                        max_iterations=20).train(fr)
    got = np.sort(np.asarray(m.output["centers"]), axis=0)
    exp = np.sort(centers, axis=0)
    assert np.abs(got - exp).max() < 0.05, got


def test_naivebayes_exact_posteriors():
    """Gaussian NB on a two-feature toy set: posteriors from Bayes rule
    with per-class mean/sd must match the model's predictions."""
    from h2o3_tpu.models.naivebayes import NaiveBayesEstimator
    r = np.random.RandomState(3)
    n = 1000
    yv = r.randint(0, 2, n)
    x = np.where(yv == 1, 2.0, -1.0) + r.randn(n)
    fr = Frame.from_numpy({"x": x, "y": yv.astype(float)},
                          categorical=["y"])
    m = NaiveBayesEstimator(laplace=0).train(fr, x=["x"], y="y")
    p1 = m.predict(fr).col("p1").to_numpy()
    # oracle: class-conditional normals with sample moments + priors
    mu = [x[yv == k].mean() for k in (0, 1)]
    sd = [x[yv == k].std(ddof=1) for k in (0, 1)]
    pri = [(yv == k).mean() for k in (0, 1)]

    def pdf(v, k):
        return np.exp(-0.5 * ((v - mu[k]) / sd[k]) ** 2) / sd[k]

    ora = pri[1] * pdf(x, 1) / (pri[0] * pdf(x, 0) + pri[1] * pdf(x, 1))
    assert np.abs(p1 - ora).max() < 1e-3, np.abs(p1 - ora).max()


def test_isotonic_pav_exact():
    """PAV on a hand-checkable sequence."""
    from h2o3_tpu.models.isotonic import IsotonicRegressionEstimator
    xs = np.arange(6, dtype=float)
    ys = np.array([1.0, 3.0, 2.0, 4.0, 6.0, 5.0])
    fr = Frame.from_numpy({"x": xs, "y": ys})
    m = IsotonicRegressionEstimator().train(fr, x=["x"], y="y")
    got = m.predict(fr).col("predict").to_numpy()
    exp = np.array([1.0, 2.5, 2.5, 4.0, 5.5, 5.5])
    assert np.allclose(got, exp), got


def test_pca_matches_numpy_svd():
    from h2o3_tpu.models.pca import PCAEstimator
    r = np.random.RandomState(5)
    X = r.randn(300, 4) @ np.diag([3.0, 2.0, 1.0, 0.5])
    fr = Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = PCAEstimator(k=2, transform="DEMEAN").train(fr)
    Xc = X - X.mean(0)
    _, s, _ = np.linalg.svd(Xc, full_matrices=False)
    exp_var = (s ** 2) / (len(X) - 1)
    got = np.asarray(m.output["importance_rows"][0][:2]) ** 2 \
        if "importance_rows" in m.output else None
    if got is None:
        sdv = np.asarray(m.output.get("std_deviation"))[:2]
        got = sdv ** 2
    # f32 accumulation in the device SVD: ~0.3% relative is its floor
    assert np.abs(got - exp_var[:2]).max() / exp_var[0] < 1e-2
