"""REST API tests — the testdir_apis role: drive the server over real
HTTP the way h2o-py's connection layer does."""

import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server, stop_server


pytestmark = pytest.mark.allow_key_leak  # REST handler threads create keys the thread-local Scope cannot track


@pytest.fixture(scope="module")
def port():
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _req(port, method, path, **params):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    if method in ("POST",):
        data = urllib.parse.urlencode(
            {k: (json.dumps(v) if isinstance(v, (list, dict)) else v)
             for k, v in params.items()}).encode()
    elif params:
        url += "?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/x-www-form-urlencoded")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_job(port, key, timeout=300):
    t0 = time.time()
    while time.time() - t0 < timeout:
        st, j = _req(port, "GET", f"/3/Jobs/{key}")
        assert st == 200, j
        status = j["jobs"][0]["status"]
        if status in ("DONE", "FAILED", "CANCELLED"):
            return j["jobs"][0]
        time.sleep(0.3)
    raise TimeoutError(key)


def test_landing_page_and_meters(port):
    url = f"http://127.0.0.1:{port}/"
    with urllib.request.urlopen(url) as resp:
        assert resp.status == 200
        assert "text/html" in resp.headers["Content-Type"]
        body = resp.read().decode()
        assert "h2o3-tpu cloud" in body
    st, j = _req(port, "GET", "/3/WaterMeterCpuTicks")
    assert st == 200
    assert isinstance(j["cpu_ticks"], list)


def test_cloud_up(port):
    st, j = _req(port, "GET", "/3/Cloud")
    assert st == 200
    assert j["cloud_size"] == 8
    assert j["cloud_healthy"]
    # uptime is a DELTA since init(), not epoch milliseconds (the
    # pre-ISSUE-7 bug reported ~1.7e12); the test session is minutes old
    assert 0 <= j["cloud_uptime_millis"] < 4 * 3600 * 1000
    assert all(n["healthy"] for n in j["nodes"])
    assert j["bad_nodes"] == 0


def test_frames_roundtrip(port):
    r = np.random.RandomState(0)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x": r.randn(50), "g": np.array(["a", "b"], dtype=object)[
            r.randint(0, 2, 50)]},
        categorical=["g"], key="rest_test_frame")
    st, j = _req(port, "GET", "/3/Frames")
    assert st == 200
    names = [f["frame_id"]["name"] for f in j["frames"]]
    assert "rest_test_frame" in names
    st, j = _req(port, "GET", "/3/Frames/rest_test_frame")
    assert st == 200
    f0 = j["frames"][0]
    assert f0["rows"] == 50 and f0["num_columns"] == 2
    st, j = _req(port, "GET", "/3/Frames/rest_test_frame/summary")
    assert st == 200
    assert any("mean" in c for c in j["frames"][0]["columns"])


def test_frame_not_found(port):
    st, j = _req(port, "GET", "/3/Frames/nope")
    assert st == 404


def test_train_and_predict_over_rest(port):
    r = np.random.RandomState(1)
    n = 2000
    X = r.randn(n, 4)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["n", "p"], dtype=object)[y]},
        categorical=["y"], key="rest_train")
    st, j = _req(port, "POST", "/3/ModelBuilders/gbm",
                 training_frame="rest_train", response_column="y",
                 ntrees=5, max_depth=3, seed=1, model_id="rest_gbm_model")
    assert st == 200, j
    job = _wait_job(port, j["job"]["key"]["name"])
    assert job["status"] == "DONE", job
    st, j = _req(port, "GET", "/3/Models/rest_gbm_model")
    assert st == 200
    md = j["models"][0]
    assert md["algo"] == "gbm"
    assert md["output"]["training_metrics"]["AUC"] > 0.7
    st, j = _req(port, "POST",
                 "/3/Predictions/models/rest_gbm_model/frames/rest_train")
    assert st == 200
    pred_key = j["predictions_frame"]["name"]
    st, j = _req(port, "GET", f"/3/Frames/{pred_key}")
    assert st == 200
    assert "predict" in j["frames"][0]["column_names"]


def test_model_builders_listing(port):
    st, j = _req(port, "GET", "/3/ModelBuilders")
    assert st == 200
    assert "gbm" in j["model_builders"]
    names = {p["name"] for p in j["model_builders"]["gbm"]["parameters"]}
    assert "ntrees" in names and "learn_rate" in names


def test_rapids_over_rest(port):
    h2o3_tpu.Frame.from_numpy({"v": np.arange(20, dtype=np.float64)},
                              key="rapids_rest")
    st, j = _req(port, "POST", "/99/Rapids",
                 ast='(sum (cols_py rapids_rest ["v"]))')
    assert st == 200
    assert j["scalar"] == 190.0
    st, j = _req(port, "POST", "/99/Rapids",
                 ast='(tmp= rr2 (* (cols_py rapids_rest ["v"]) 2))')
    assert st == 200
    assert j["frame"]["rows"] == 20


def test_parse_endpoint(port, tmp_path):
    csv = tmp_path / "mini.csv"
    csv.write_text("a,b\n1,x\n2,y\n3,x\n")
    st, j = _req(port, "POST", "/3/ParseSetup",
                 source_frames=json.dumps([str(csv)]))
    assert st == 200
    assert j["column_names"] == ["a", "b"]
    st, j = _req(port, "POST", "/3/Parse",
                 source_frames=json.dumps([str(csv)]),
                 destination_frame="mini_hex")
    assert st == 200
    _wait_job(port, j["job"]["key"]["name"])
    st, j = _req(port, "GET", "/3/Frames/mini_hex")
    assert st == 200
    assert j["frames"][0]["rows"] == 3


def test_jobs_listing_and_delete(port):
    st, j = _req(port, "GET", "/3/Jobs")
    assert st == 200
    assert isinstance(j["jobs"], list)
    st, _ = _req(port, "DELETE", "/3/Frames/rapids_rest")
    assert st == 200
    st, j = _req(port, "GET", "/3/Frames/rapids_rest")
    assert st == 404


def test_flow_ui_served(port):
    """/flow/index.html serves the notebook app; landing page links it
    (h2o-web Flow-serving role)."""
    import urllib.request
    base = f"http://127.0.0.1:{port}"
    html = urllib.request.urlopen(base + "/flow/index.html",
                                  timeout=30).read().decode()
    assert "runCell" in html and "importFiles" in html
    html2 = urllib.request.urlopen(base + "/flow", timeout=30).read().decode()
    assert "runCell" in html2
    root = urllib.request.urlopen(base + "/", timeout=30).read().decode()
    assert "/flow/index.html" in root
