"""RuleFit tests (h2o-py/tests/testdir_algos/rulefit role)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.rulefit import RuleFitEstimator


@pytest.fixture(scope="module")
def rule_data():
    """Response driven by an interaction rule: x0>0 AND x1<0 → +3."""
    r = np.random.RandomState(11)
    n = 1200
    X = r.randn(n, 4)
    y = 3.0 * ((X[:, 0] > 0) & (X[:, 1] < 0)) + 0.5 * X[:, 2] \
        + r.randn(n) * 0.3
    fr = Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)} | {"y": y})
    return fr, X, y


def test_rulefit_regression_finds_rule(rule_data):
    fr, X, y = rule_data
    m = RuleFitEstimator(max_rule_length=3, min_rule_length=2,
                         rule_generation_ntrees=20, seed=42).train(
        fr, y="y", x=["x0", "x1", "x2", "x3"])
    assert m.training_metrics["RMSE"] < 1.0   # vs sd(y) ~ 1.6
    imp = m.rule_importance
    assert len(imp) > 0
    # top rules should recover the planted signal (x0/x1 interaction).
    # The exact winner is seed-path sensitive (depth-bucketed tree
    # programs consume RNG keys per COMPILED level, so rule sets shifted
    # when DEPTH_BUCKETS landed) — require an informative feature in the
    # top rules rather than both, with RMSE above asserting overall fit
    top = " ".join(d["rule"] for d in imp[:5])
    assert "x0" in top or "x1" in top
    # predictions on a fresh frame
    fr2 = Frame.from_numpy({f"x{i}": X[:100, i] for i in range(4)})
    pred = m.predict(fr2).col("predict").to_numpy()
    assert pred.shape == (100,)
    assert np.isfinite(pred).all()


def test_rulefit_binomial(rule_data):
    fr, X, y = rule_data
    cls = np.where(y > np.median(y), "hi", "lo").astype(object)
    fr2 = Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)}
                           | {"cls": cls}, categorical=["cls"])
    m = RuleFitEstimator(rule_generation_ntrees=15, seed=1).train(
        fr2, y="cls", x=["x0", "x1", "x2", "x3"])
    assert m.training_metrics["AUC"] > 0.8


def test_rulefit_max_num_rules_and_linear_only(rule_data):
    fr, X, y = rule_data
    m = RuleFitEstimator(max_num_rules=5, rule_generation_ntrees=10,
                         seed=2).train(fr, y="y")
    assert len(m.rule_importance) <= 5
    lin = RuleFitEstimator(model_type="linear", seed=2).train(fr, y="y")
    assert all(d["rule"].startswith("linear(") for d in lin.rule_importance)
