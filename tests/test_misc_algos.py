"""NaiveBayes / Isotonic / Quantile tests — pyunit_nb* / pyunit_isotonic* /
pyunit_quantile* role."""

import numpy as np

import h2o3_tpu
from h2o3_tpu.frame.quantiles import column_quantiles, frame_quantiles
from h2o3_tpu.models.isotonic import IsotonicRegressionEstimator
from h2o3_tpu.models.naivebayes import NaiveBayesEstimator


def test_naive_bayes_gaussian(classif_frame):
    m = NaiveBayesEstimator().train(classif_frame, y="y")
    assert m.training_metrics["AUC"] > 0.75, m.training_metrics.to_dict()
    p = m.predict(classif_frame).to_pandas()
    assert ((p["p0"] + p["p1"]).round(4) == 1.0).all()


def test_naive_bayes_categorical_features():
    r = np.random.RandomState(3)
    n = 4000
    g = r.randint(0, 4, n)
    noise = r.randint(0, 4, n)
    y = (g >= 2) ^ (r.rand(n) < 0.1)
    f = h2o3_tpu.Frame.from_numpy(
        {"g": np.array(list("abcd"), dtype=object)[g],
         "noise": np.array(list("wxyz"), dtype=object)[noise],
         "y": np.array(["n", "p"], dtype=object)[y.astype(int)]},
        categorical=["g", "noise", "y"])
    m = NaiveBayesEstimator(laplace=1.0).train(f, y="y")
    assert m.training_metrics["AUC"] > 0.85


def test_isotonic_monotone_fit():
    r = np.random.RandomState(0)
    n = 3000
    x = r.uniform(0, 10, n)
    y = np.log1p(x) + 0.2 * r.randn(n)
    f = h2o3_tpu.Frame.from_numpy({"x": x, "y": y})
    m = IsotonicRegressionEstimator().train(f, x=["x"], y="y")
    pred = m.predict(f).to_pandas()["predict"].to_numpy()
    order = np.argsort(x)
    assert (np.diff(pred[order]) >= -1e-9).all()     # monotone
    assert m.training_metrics["MSE"] < 0.06


def test_quantiles_match_numpy():
    r = np.random.RandomState(1)
    v = r.lognormal(0, 1, 50_000)
    f = h2o3_tpu.Frame.from_numpy({"v": v})
    probs = [0.1, 0.5, 0.9, 0.99]
    got = column_quantiles(f.col("v"), probs)
    ref = np.quantile(v, probs)
    np.testing.assert_allclose(got, ref, rtol=1e-3)


def test_quantiles_with_nas():
    r = np.random.RandomState(2)
    v = r.randn(10_000)
    v[::7] = np.nan
    f = h2o3_tpu.Frame.from_numpy({"v": v})
    got = column_quantiles(f.col("v"), [0.5])
    ref = np.nanquantile(v, 0.5)
    assert abs(got[0] - ref) < 2e-3

def test_frame_quantiles_table():
    r = np.random.RandomState(4)
    f = h2o3_tpu.Frame.from_numpy({"a": r.randn(5000), "b": r.rand(5000),
                                   "c": np.array(["x", "y"], dtype=object)[
                                       r.randint(0, 2, 5000)]},
                                  categorical=["c"])
    t = frame_quantiles(f, probs=[0.25, 0.5, 0.75])
    assert set(t) == {"probs", "a", "b"}
    assert abs(t["b"][1] - 0.5) < 0.02


def test_quantile_combine_methods():
    v = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0, 11.0])
    f = h2o3_tpu.Frame.from_numpy({"v": v})
    c = f.col("v")
    # rank for p=0.5 on 10 values is 4.5 → low=5, high=6
    assert abs(column_quantiles(c, [0.5], combine_method="low")[0] - 5.0) < 1e-3
    assert abs(column_quantiles(c, [0.5], combine_method="high")[0] - 6.0) < 1e-3
    assert abs(column_quantiles(c, [0.5], combine_method="average")[0] - 5.5) < 1e-3
    assert abs(column_quantiles(c, [0.5])[0] - 5.5) < 1e-3


def test_isotonic_out_of_bounds_na():
    r = np.random.RandomState(1)
    x = r.uniform(0, 10, 500)
    y = x + 0.1 * r.randn(500)
    f = h2o3_tpu.Frame.from_numpy({"x": x, "y": y})
    m = IsotonicRegressionEstimator(out_of_bounds="na").train(f, x=["x"], y="y")
    f2 = h2o3_tpu.Frame.from_numpy({"x": np.array([-5.0, 5.0, 50.0])})
    p = m.predict(f2).to_pandas()["predict"].to_numpy()
    assert np.isnan(p[0]) and np.isnan(p[2]) and not np.isnan(p[1])
