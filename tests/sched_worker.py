"""Worker for the cluster work-scheduler multiprocess tests (ISSUE 15,
parallel/scheduler.py).

Every process runs this same script (the SPMD contract): forms a
jax.distributed CPU cloud, then trains an 8-combo GBM grid that the
scheduler fans across the hosts. Modes (argv[5]):

- ``ref``  — single process, scheduler OFF: the bit-parity reference.
- ``run``  — N processes, scheduler auto (on): the fan-out leg.
- ``kill`` — like ``run``, but process 1 SIGKILLs itself after
  completing its first scheduled item; the coordinator must detect the
  dead peer, reassign its remaining leases, and finish bit-identical.

Each surviving process writes ``outfile.<pid>`` with the grid result
(full-precision metrics), its scheduler counters, and its job statuses.
"""

import json
import os
import signal
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# singleton items (one per combo) so an 8-combo grid provably spreads
# across BOTH hosts; the batched path is covered by single-process tier-1
os.environ["H2O3TPU_BATCH_MODELS"] = "off"
# fast dead-peer detection for the kill leg (staleness = interval * 3)
os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"] = "0.25"
os.environ["H2O3TPU_SCHEDULER_POLL_S"] = "0.05"
# all five worker processes (ref + run×2 + kill×2) compile the SAME
# GBM kernel shapes — share the executables across the sequential legs
# (identical binaries, so bit-parity is unaffected by who compiled)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, outfile, mode = sys.argv[1:6]
nproc, pid = int(nproc), int(pid)

os.environ["H2O3TPU_SCHEDULER"] = "off" if mode == "ref" else "auto"

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
if nproc > 1:
    h2o3_tpu.init(backend="cpu", coordinator_address=coord,
                  num_processes=nproc, process_id=pid)
else:
    h2o3_tpu.init(backend="cpu")

import numpy as np                            # noqa: E402

from h2o3_tpu.parallel import scheduler       # noqa: E402

if mode == "kill" and pid == 1:
    # publish exactly one result, then die without warning — the
    # coordinator must reassign this host's remaining leases
    _orig_execute = scheduler._execute_one

    def _execute_then_die(*args, **kwargs):
        res = _orig_execute(*args, **kwargs)
        os.kill(os.getpid(), signal.SIGKILL)
        return res

    scheduler._execute_one = _execute_then_die


def build_data():
    """MUST match tests/test_scheduler.py expectations (same rows as
    tests/mp_worker.py build_data)."""
    r = np.random.RandomState(5)
    n = 4000
    a = r.randn(n)
    b = r.randn(n)
    g = r.choice(["u", "v", "w"], n)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(n) * 0.3
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g"])


fr = build_data()

from h2o3_tpu.ml.grid import GridSearch       # noqa: E402
from h2o3_tpu.models.gbm import GBMEstimator  # noqa: E402

HYPER = {"learn_rate": [0.05, 0.1],
         "sample_rate": [0.7, 1.0],
         "min_rows": [5.0, 10.0]}             # 8 combos, one shape
grid = GridSearch(GBMEstimator, HYPER, ntrees=3, max_depth=3,
                  seed=3).train(fr, y="y")

# full-precision walk-order leaderboard: the bit-parity payload (repr
# round-trips exactly through json)
rows = [[json.dumps(m.output.get("grid_params"), sort_keys=True),
         float(m.training_metrics["MSE"])] for m in grid.models]

from h2o3_tpu import telemetry                # noqa: E402
from h2o3_tpu.core.job import list_jobs      # noqa: E402

result = {
    "pid": pid,
    "grid": rows,
    "sched": scheduler.snapshot(),
    "items_completed_here": telemetry.REGISTRY.value(
        "sched_items_completed_total", host=str(pid)),
    "job_statuses": sorted(j["status"] for j in list_jobs()),
}
with open(f"{outfile}.{pid}", "w") as f:
    json.dump(result, f)
print(f"SCHED-WORKER-{pid}-DONE", flush=True)

if mode == "kill":
    # peer 1 is dead: a collective or the distributed-shutdown barrier
    # would wait on it forever — results are on disk, leave hard
    os._exit(0)
h2o3_tpu.shutdown()
