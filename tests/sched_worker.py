"""Worker for the cluster work-scheduler multiprocess tests (ISSUE 15,
parallel/scheduler.py).

Every process runs this same script (the SPMD contract): forms a
jax.distributed CPU cloud, then trains an 8-combo GBM grid that the
scheduler fans across the hosts. Modes (argv[5]):

- ``ref``  — single process, scheduler OFF: the bit-parity reference.
- ``run``  — N processes, scheduler auto (on): the fan-out leg.
- ``kill`` — like ``run``, but process 1 SIGKILLs itself after
  completing its first scheduled item; the coordinator must detect the
  dead peer, reassign its remaining leases, and finish bit-identical.
- ``trace`` — the ISSUE 16 distributed-tracing leg: process 0 drives
  the SAME grid through REST with a ``traceparent`` header and fetches
  ``GET /3/Trace?trace_id=``; process 1 trains directly (the SPMD
  partner). The stitched trace must hold causally-parented spans from
  BOTH hosts under the client's trace id.

Each surviving process writes ``outfile.<pid>`` with the grid result
(full-precision metrics), its scheduler counters, and its job statuses.
"""

import json
import os
import signal
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# singleton items (one per combo) so an 8-combo grid provably spreads
# across BOTH hosts; the batched path is covered by single-process tier-1
os.environ["H2O3TPU_BATCH_MODELS"] = "off"
# fast dead-peer detection for the kill leg (staleness = interval * 3)
os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"] = "0.25"
os.environ["H2O3TPU_SCHEDULER_POLL_S"] = "0.05"
# all five worker processes (ref + run×2 + kill×2) compile the SAME
# GBM kernel shapes — share the executables across the sequential legs
# (identical binaries, so bit-parity is unaffected by who compiled)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, outfile, mode = sys.argv[1:6]
nproc, pid = int(nproc), int(pid)

os.environ["H2O3TPU_SCHEDULER"] = "off" if mode == "ref" else "auto"

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
if nproc > 1:
    h2o3_tpu.init(backend="cpu", coordinator_address=coord,
                  num_processes=nproc, process_id=pid)
else:
    h2o3_tpu.init(backend="cpu")

import numpy as np                            # noqa: E402

from h2o3_tpu.parallel import scheduler       # noqa: E402

if mode == "kill" and pid == 1:
    # publish exactly one result, then die without warning — the
    # coordinator must reassign this host's remaining leases
    _orig_execute = scheduler._execute_one

    def _execute_then_die(*args, **kwargs):
        res = _orig_execute(*args, **kwargs)
        os.kill(os.getpid(), signal.SIGKILL)
        return res

    scheduler._execute_one = _execute_then_die


def build_data():
    """MUST match tests/test_scheduler.py expectations (same rows as
    tests/mp_worker.py build_data)."""
    r = np.random.RandomState(5)
    n = 4000
    a = r.randn(n)
    b = r.randn(n)
    g = r.choice(["u", "v", "w"], n)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(n) * 0.3
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g"])


fr = build_data()

from h2o3_tpu.ml.grid import GridSearch       # noqa: E402
from h2o3_tpu.models.gbm import GBMEstimator  # noqa: E402

HYPER = {"learn_rate": [0.05, 0.1],
         "sample_rate": [0.7, 1.0],
         "min_rows": [5.0, 10.0]}             # 8 combos, one shape

if mode == "trace":
    import time
    import urllib.parse
    import urllib.request

    from h2o3_tpu import telemetry
    from h2o3_tpu.telemetry import cluster

    TRACE_ID = "ab" * 16

    if pid == 0:
        # REST-initiated leg: the handler launches a background job
        # whose grid train enters scheduler.run — the same SPMD point
        # process 1 reaches directly below
        from h2o3_tpu.api.server import start_server
        port = start_server(port=0, background=True)
        tp = f"00-{TRACE_ID}-{'0' * 16}-01"
        url = (f"http://127.0.0.1:{port}/99/Grid/gbm"
               f"?training_frame={urllib.parse.quote(str(fr.key))}"
               f"&response_column=y&ntrees=3&max_depth=3&seed=3"
               f"&hyper_parameters="
               f"{urllib.parse.quote(json.dumps(HYPER))}")
        req = urllib.request.Request(url, data=b"", method="POST",
                                     headers={"traceparent": tp})
        with urllib.request.urlopen(req) as r:
            echoed = r.headers.get("X-H2O-Trace-Id")
            jk = json.loads(r.read())["job"]["key"]["name"]
        status = "?"
        for _ in range(1200):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/3/Jobs/{jk}") as r:
                jd = json.loads(r.read())["jobs"][0]
            status = jd["status"]
            if status not in ("CREATED", "RUNNING"):
                break
            time.sleep(0.1)
        # the stitched trace needs BOTH hosts' span rings: poll until
        # process 1's published snapshot carries its leased items
        trace = {}
        for _ in range(100):
            cluster.publish(force=True)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/3/Trace"
                    f"?trace_id={TRACE_ID}") as r:
                trace = json.loads(r.read())
            if sorted(trace.get("otherData", {})
                      .get("nodes", [])) == [0, 1]:
                break
            time.sleep(0.2)
        result = {"pid": pid, "status": status, "echoed": echoed,
                  "job_trace_id": jd.get("trace_id"),
                  "trace": trace}
    else:
        # offset this process's span-id counter so its span ids cannot
        # collide with the COORDINATOR's sched.run id — cross-node
        # parent resolution in trace_export prefers a same-node owner
        for _ in range(512):
            with telemetry.span("trace_test.pad"):
                pass
        GridSearch(GBMEstimator, HYPER, ntrees=3, max_depth=3,
                   seed=3).train(fr, y="y")
        # keep publishing until process 0 banked its stitched trace
        for _ in range(300):
            cluster.publish(force=True)
            if os.path.exists(f"{outfile}.0"):
                break
            time.sleep(0.2)
        result = {"pid": pid,
                  "sched": scheduler.snapshot(),
                  "spans_with_trace": sum(
                      1 for s in telemetry.spans_snapshot(2048)
                      if s.get("trace_id") == TRACE_ID)}
    with open(f"{outfile}.{pid}", "w") as f:
        json.dump(result, f)
    print(f"SCHED-WORKER-{pid}-DONE", flush=True)
    if pid == 0:
        # the coordination service lives in THIS process: exiting while
        # peer 1 still polls it turns the socket close into a fatal
        # UNAVAILABLE in that process (pjrt distributed client CHECK) —
        # hold on until the peer has banked its result
        for _ in range(600):
            if os.path.exists(f"{outfile}.1"):
                break
            time.sleep(0.1)
    # skip the distributed-shutdown barrier: results are on disk, and
    # the processes finish at different times by design
    os._exit(0)

grid = GridSearch(GBMEstimator, HYPER, ntrees=3, max_depth=3,
                  seed=3).train(fr, y="y")

# full-precision walk-order leaderboard: the bit-parity payload (repr
# round-trips exactly through json)
rows = [[json.dumps(m.output.get("grid_params"), sort_keys=True),
         float(m.training_metrics["MSE"])] for m in grid.models]

from h2o3_tpu import telemetry                # noqa: E402
from h2o3_tpu.core.job import list_jobs      # noqa: E402

result = {
    "pid": pid,
    "grid": rows,
    "sched": scheduler.snapshot(),
    "items_completed_here": telemetry.REGISTRY.value(
        "sched_items_completed_total", host=str(pid)),
    "job_statuses": sorted(j["status"] for j in list_jobs()),
}
with open(f"{outfile}.{pid}", "w") as f:
    json.dump(result, f)
print(f"SCHED-WORKER-{pid}-DONE", flush=True)

if mode == "kill":
    # peer 1 is dead: a collective or the distributed-shutdown barrier
    # would wait on it forever — results are on disk, leave hard
    os._exit(0)
h2o3_tpu.shutdown()
