"""GLM option-surface tests: families, COD solver, constraints,
interactions (VERDICT r1 item 6; reference hex/glm/GLM.java surface).

Oracles are closed-form / simulation-recovery checks (statsmodels is not
available in this image; sklearn where it helps).
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.glm import GLMEstimator


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(11)


def test_negativebinomial_recovers_coefficients(rng):
    n = 20000
    x0 = rng.randn(n) * 0.5
    x1 = rng.randn(n) * 0.5
    eta = 0.4 + 0.8 * x0 - 0.5 * x1
    mu = np.exp(eta)
    theta = 0.5            # var = mu + theta*mu^2
    # NB via gamma-poisson mixture
    lam = rng.gamma(shape=1 / theta, scale=mu * theta)
    y = rng.poisson(lam).astype(float)
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "x1": x1, "y": y})
    m = GLMEstimator(family="negativebinomial", theta=theta,
                     lambda_=0.0, standardize=False).train(fr, y="y")
    c = m.coefficients
    assert abs(c["x0"] - 0.8) < 0.05
    assert abs(c["x1"] + 0.5) < 0.05
    assert abs(c["Intercept"] - 0.4) < 0.05


def test_quasibinomial_numeric_response(rng):
    n = 8000
    x0 = rng.randn(n)
    p1 = 1 / (1 + np.exp(-(0.3 + 1.2 * x0)))
    y = (rng.rand(n) < p1).astype(float)      # numeric 0/1, NOT enum
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "y": y})
    m = GLMEstimator(family="quasibinomial", lambda_=0.0,
                     standardize=False).train(fr, y="y")
    assert abs(m.coefficients["x0"] - 1.2) < 0.15


def test_fractionalbinomial_fractional_response(rng):
    n = 8000
    x0 = rng.randn(n)
    mu = 1 / (1 + np.exp(-(0.2 + 0.9 * x0)))
    y = np.clip(mu + rng.randn(n) * 0.05, 0.0, 1.0)   # fractions in [0,1]
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "y": y})
    m = GLMEstimator(family="fractionalbinomial", lambda_=0.0,
                     standardize=False).train(fr, y="y")
    assert abs(m.coefficients["x0"] - 0.9) < 0.1


def test_coordinate_descent_matches_irlsm(rng):
    n = 5000
    X = rng.randn(n, 4)
    y = X @ np.array([1.0, -2.0, 0.5, 0.0]) + rng.randn(n) * 0.3
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    fr = h2o3_tpu.Frame.from_numpy(cols)
    m_ir = GLMEstimator(family="gaussian", solver="irlsm", lambda_=0.0,
                        standardize=False).train(fr, y="y")
    m_cd = GLMEstimator(family="gaussian", solver="coordinate_descent",
                        lambda_=0.0, standardize=False).train(fr, y="y")
    for k in m_ir.coefficients:
        assert abs(m_ir.coefficients[k] - m_cd.coefficients[k]) < 1e-3, k


def test_non_negative_constraint(rng):
    n = 5000
    X = rng.randn(n, 3)
    # true beta has a negative component the constraint must clip to 0
    y = X @ np.array([1.0, -2.0, 0.5]) + rng.randn(n) * 0.3
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = y
    fr = h2o3_tpu.Frame.from_numpy(cols)
    m = GLMEstimator(family="gaussian", non_negative=True, lambda_=0.0,
                     standardize=False).train(fr, y="y")
    c = m.coefficients
    assert c["x1"] >= -1e-6          # clipped at zero
    assert abs(c["x0"] - 1.0) < 0.1
    assert c["x1"] < 0.05


def test_beta_constraints_box(rng):
    n = 5000
    X = rng.randn(n, 2)
    y = X @ np.array([2.0, -1.0]) + rng.randn(n) * 0.2
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m = GLMEstimator(family="gaussian", lambda_=0.0, standardize=False,
                     beta_constraints={"x0": (0.0, 0.5)}).train(fr, y="y")
    c = m.coefficients
    assert -1e-6 <= c["x0"] <= 0.5 + 1e-6
    assert abs(c["x1"] + 1.0) < 0.2   # unconstrained coef still fits


def test_interactions_num_num(rng):
    n = 10000
    a = rng.randn(n)
    b = rng.randn(n)
    y = 1.0 + 0.5 * a - 0.25 * b + 2.0 * a * b + rng.randn(n) * 0.1
    fr = h2o3_tpu.Frame.from_numpy({"a": a, "b": b, "y": y})
    m = GLMEstimator(family="gaussian", lambda_=0.0, standardize=False,
                     interactions=["a", "b"]).train(fr, y="y")
    c = m.coefficients
    assert abs(c["a_b"] - 2.0) < 0.05
    assert abs(c["a"] - 0.5) < 0.05
    # scoring path expands the same interactions
    pred = m.predict(fr).col("predict").to_numpy()
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05


def test_interactions_cat_num(rng):
    n = 10000
    g = rng.choice(["u", "v"], n)
    x = rng.randn(n)
    slope = np.where(g == "u", 1.5, -1.5)
    y = slope * x + rng.randn(n) * 0.1
    fr = h2o3_tpu.Frame.from_numpy({"g": g, "x": x, "y": y},
                                   categorical=["g"])
    m = GLMEstimator(family="gaussian", lambda_=0.0, standardize=False,
                     interactions=["g", "x"]).train(fr, y="y")
    pred = m.predict(fr).col("predict").to_numpy()
    assert float(np.mean((pred - y) ** 2)) < 0.05


def test_ordinal_proportional_odds(rng):
    n = 12000
    x = rng.randn(n)
    eta = 1.4 * x
    # 3 ordered levels via latent logistic with thresholds -0.8, 0.9
    u = rng.logistic(size=n)
    lat = eta + u
    # level names chosen so lexicographic interning preserves the
    # ordinal order (the reference likewise uses domain order as the
    # ordinal order)
    y = np.where(lat < -0.8, "l0_low", np.where(lat < 0.9, "l1_mid",
                                                "l2_high"))
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y}, categorical=["y"])
    m = GLMEstimator(family="ordinal", lambda_=0.0,
                     standardize=False).train(fr, y="y")
    pred = m.predict(fr)
    assert {"p0", "p1", "p2"} <= set(pred.names)
    probs = np.stack([pred.col(f"p{k}").to_numpy() for k in range(3)], 1)
    assert np.allclose(probs.sum(1), 1.0, atol=1e-5)
    acc = float((pred.col("predict").to_numpy()
                 == np.asarray(fr.col("y").data)[:n]).mean())
    assert acc > 0.5            # near the Bayes rate for this noise level
    # parameter recovery is the sharper check
    assert abs(float(m.coef[0]) - 1.4) < 0.1
    alphas = m.output["ordinal_alphas"]
    assert abs(alphas[0] + 0.8) < 0.1 and abs(alphas[1] - 0.9) < 0.1


def test_glm_offset_column(rng):
    n = 8000
    x0 = rng.randn(n)
    off = rng.randn(n) * 0.5
    y = 2.0 + 1.5 * x0 + off + rng.randn(n) * 0.2
    fr = h2o3_tpu.Frame.from_numpy({"x0": x0, "off": off, "y": y})
    m = GLMEstimator(family="gaussian", lambda_=0.0, standardize=False,
                     offset_column="off").train(fr, y="y")
    c = m.coefficients
    # with the offset absorbed, the slope/intercept are recovered and
    # "off" is NOT a coefficient
    assert "off" not in c
    assert abs(c["x0"] - 1.5) < 0.05
    assert abs(c["Intercept"] - 2.0) < 0.05
    pred = m.predict(fr).col("predict").to_numpy()
    assert float(np.mean((pred - y) ** 2)) < 0.1
