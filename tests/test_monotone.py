"""GBM monotone constraints (hex/tree/Constraints parity)."""

import numpy as np
import pytest

import h2o3_tpu


def _frame(n=4000, seed=0):
    r = np.random.RandomState(seed)
    x0 = r.randn(n)
    x1 = r.randn(n)
    # upward trend with genuinely non-monotone wiggles in x0
    y = 2.0 * x0 + 2.5 * np.sin(3 * x0) + x1 + 0.5 * r.randn(n)
    return h2o3_tpu.Frame.from_numpy({"x0": x0, "x1": x1, "y": y})


def _monotonicity_violations(model, direction=1, n_grid=60):
    grid = np.linspace(-3, 3, n_grid)
    fr = h2o3_tpu.Frame.from_numpy({"x0": grid,
                                    "x1": np.zeros(n_grid)})
    pred = model.predict(fr).col("predict").to_numpy()
    d = np.diff(pred) * direction
    return int((d < -1e-6).sum()), pred


def test_monotone_increasing_enforced():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame()
    free = GBMEstimator(ntrees=30, max_depth=4, seed=3).train(fr, y="y")
    viol_free, _ = _monotonicity_violations(free)
    mono = GBMEstimator(ntrees=30, max_depth=4, seed=3,
                        monotone_constraints={"x0": 1}).train(fr, y="y")
    viol_mono, pred = _monotonicity_violations(mono)
    assert viol_mono == 0, f"{viol_mono} monotonicity violations"
    # the unconstrained model wiggles on this data (sanity of the probe)
    assert viol_free > 0
    # constrained model still learns the trend
    assert pred[-1] - pred[0] > 5.0
    assert mono.training_metrics["r2"] > 0.7


def test_monotone_decreasing_enforced():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame(seed=5)
    m = GBMEstimator(ntrees=20, max_depth=4, seed=1,
                     monotone_constraints={"x0": -1}).train(fr, y="y")
    viol, pred = _monotonicity_violations(m, direction=-1)
    assert viol == 0


def test_monotone_binomial():
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(1)
    n = 3000
    x0 = r.randn(n)
    p = 1 / (1 + np.exp(-(1.5 * x0 + np.sin(4 * x0))))
    fr = h2o3_tpu.Frame.from_numpy(
        {"x0": x0, "x1": r.randn(n),
         "y": np.array(["n", "p"], object)[(r.rand(n) < p).astype(int)]},
        categorical=["y"])
    m = GBMEstimator(ntrees=25, max_depth=4, seed=2,
                     monotone_constraints={"x0": 1}).train(fr, y="y")
    grid = np.linspace(-3, 3, 50)
    gf = h2o3_tpu.Frame.from_numpy({"x0": grid, "x1": np.zeros(50)})
    p1 = m.predict(gf).col("p1").to_numpy()
    assert (np.diff(p1) < -1e-6).sum() == 0
    assert m.training_metrics["AUC"] > 0.7


def test_monotone_validation():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame(n=500)
    with pytest.raises(ValueError, match="not in predictors"):
        GBMEstimator(ntrees=2, monotone_constraints={"zz": 1}).train(
            fr, y="y")
    cols = {"g": np.array(["a", "b"], object)[
        np.random.RandomState(0).randint(0, 2, 500)],
        "y": np.random.RandomState(0).randn(500)}
    fr2 = h2o3_tpu.Frame.from_numpy(cols, categorical=["g"])
    with pytest.raises(ValueError, match="numeric"):
        GBMEstimator(ntrees=2, monotone_constraints={"g": 1}).train(
            fr2, y="y")


def test_monotone_via_xgboost_facade():
    from h2o3_tpu.models.xgboost import XGBoostEstimator
    fr = _frame(seed=7)
    m = XGBoostEstimator(ntrees=15, monotone_constraints={"x0": 1}).train(
        fr, y="y")
    viol, _ = _monotonicity_violations(m)
    assert viol == 0
