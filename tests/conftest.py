"""Test harness: force an 8-virtual-device CPU mesh.

The analogue of the reference's multi-JVM-on-localhost test clouds
(multiNodeUtils.sh + @CloudSize(n), water/runner/H2ORunner.java:27): tests
exercise the same sharded/psum code paths the TPU pod runs, on 8 virtual
CPU devices.
"""

import os

# --xla_cpu_use_thunk_runtime=false: the new CPU thunk runtime in this
# jaxlib intermittently segfaults inside backend_compile_and_load after
# a few hundred compilations in one process (observed twice mid-suite,
# different tests each time); the legacy runtime is stable.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"
if "--xla_cpu_use_thunk_runtime" not in _flags:
    _flags += " --xla_cpu_use_thunk_runtime=false"
os.environ["XLA_FLAGS"] = _flags.strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="session")
def _cloud():
    """Boot the cloud once per session (stall_till_cloudsize analogue)."""
    import h2o3_tpu
    cpu = jax.devices("cpu")
    jax.config.update("jax_default_device", cpu[0])
    h2o3_tpu.init(backend="cpu")
    info = h2o3_tpu.cluster_info()
    assert info["cloud_size"] == 8, info
    yield
    h2o3_tpu.shutdown()


@pytest.fixture(autouse=True)
def _check_keys(request):
    """Leak check — the water/runner/CheckKeysTask analogue: every key a
    test (or its function-scoped fixtures) creates must be gone from the
    DKV when the test ends, and the Scope stack must balance.

    The fixture brackets the test in a Scope, so keys created on the
    test's own thread are swept automatically; anything still present
    afterwards (e.g. keys put by background threads, which thread-local
    Scope tracking cannot see) fails the test. Tests that intentionally
    leave keys — REST servers creating objects on handler threads,
    cross-test module state — opt out with @pytest.mark.allow_key_leak
    (which also skips the sweep)."""
    if request.node.get_closest_marker("allow_key_leak"):
        yield
        return
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.core.scope import Scope, _stack
    baseline = set(DKV.keys())
    depth = len(_stack())
    Scope().__enter__()
    try:
        yield
    finally:
        # unwind this fixture's scope plus any scope the test entered
        # and failed to exit (each exit sweeps its tracked keys)
        unbalanced = len(_stack()) - depth - 1
        while len(_stack()) > depth:
            _stack()[-1].__exit__(None, None, None)
        # flight-recorder capsules (<job>_telemetry) are INTENTIONAL
        # retained artifacts — bounded by H2O3TPU_FLIGHT_RECORDER_KEEP,
        # created on worker threads the thread-local Scope cannot see.
        # Sweep them between tests but don't flag them as leaks (a
        # CANCELLED job's capsule is still asserted swept by its own
        # Scope in tests/test_flight_recorder.py).
        from h2o3_tpu.telemetry.flight_recorder import TELEMETRY_SUFFIX
        leaked = [k for k in DKV.keys() if k not in baseline
                  and not k.endswith(TELEMETRY_SUFFIX)]
        for k in list(DKV.keys()):
            if k not in baseline and k.endswith(TELEMETRY_SUFFIX):
                DKV.remove(k)
        # orphaned FitCheckpointer debris (ISSUE 9): a test that killed
        # or failed a checkpointed fit may leave *.fitsnap.tmp files or
        # an empty partial snapshot dir behind — sweep them so one
        # test's crash-sim cannot poison a later resume test
        from h2o3_tpu.core import recovery as _recovery
        _recovery.sweep_fit_checkpoints()
        # orphaned Cleaner ice files (ISSUE 11): a test that spilled a
        # frame and then removed or clobbered its key without touching
        # the stub leaves hex://spill/*.npz debris — sweep files no
        # live stub references so spills cannot accumulate across the
        # suite (mirrors the *.fitsnap.tmp sweep above)
        _sweep_orphan_spills(baseline)
        # orphaned mirror blobs (ISSUE 18): a durability-mode test that
        # crashed mid-write leaves *.framesnap.tmp debris, and a test
        # that dropped keys without the remove hook leaves unregistered
        # *.framesnap blobs — sweep both (mirrors the fitsnap.tmp and
        # spill-npz sweeps above)
        from h2o3_tpu.core import durability as _durability
        _durability.sweep_debris()
        for k in leaked:    # sweep so one leak cannot cascade
            # a leaked RUNNING job is a live worker thread that would
            # keep writing keys after the sweep — cancel it (observed
            # cooperatively at the next chunk boundary) and wait
            # briefly before removing its key
            v = DKV.get_raw(k)
            if getattr(v, "status", None) == "RUNNING" \
                    and hasattr(v, "cancel"):
                v.cancel()
                try:
                    v.join(10.0)
                except Exception:
                    pass
            DKV.remove(k)
    assert unbalanced <= 0, \
        f"{unbalanced} Scope(s) entered but never exited"
    assert not leaked, \
        f"{len(leaked)} DKV key(s) leaked: {sorted(leaked)[:10]}"


@pytest.fixture(autouse=True)
def _check_trace_context():
    """Trace-context leak check (ISSUE 16): a test that installs a
    TraceContext (trace_scope / install) must uninstall it — a leaked
    context would silently stamp every later test's spans with a stale
    trace id. Mirrors the DKV/Scope sweep: defensively reset, then
    fail the test that leaked."""
    from h2o3_tpu.telemetry import trace_context
    yield
    leaked = trace_context.current()
    trace_context._reset()
    assert leaked is None, \
        f"TraceContext leaked across test boundary: {leaked.to_dict()}"


def _sweep_orphan_spills(baseline) -> None:
    """Delete spill npz files in the ice dir that no in-DKV stub still
    references (hex://spill/* — io/persist.py _IceDriver layout)."""
    import glob
    import tempfile
    from h2o3_tpu.core.kv import DKV
    ice_root = os.environ.get(
        "H2O3_TPU_ICE_DIR",
        os.path.join(tempfile.gettempdir(), "h2o3_tpu_ice"))
    files = glob.glob(os.path.join(ice_root, "spill", "*.npz"))
    if not files:
        return
    live = set()
    for k in list(DKV.keys()):
        v = DKV.get_raw(k)
        uri = getattr(v, "uri", None)
        if getattr(v, "_is_lazy_stub", False) and uri:
            live.add(os.path.basename(uri))
        del v
    for p in files:
        if os.path.basename(p) not in live:
            try:
                os.unlink(p)
            except OSError:
                pass


@pytest.fixture()
def rng():
    return np.random.RandomState(42)


def make_classification(n=4000, f=8, seed=0, informative=4):
    """Synthetic binary problem with known signal (TestFrameCatalog role)."""
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logits = X[:, :informative] @ r.uniform(0.5, 2.0, informative)
    p = 1 / (1 + np.exp(-logits))
    y = (r.rand(n) < p).astype(int)
    return X, y


def make_regression(n=4000, f=8, seed=0, noise=0.1):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 2) + 0.5 * X[:, 2] * X[:, 3]
    y = y + noise * r.randn(n)
    return X, y


@pytest.fixture()
def classif_frame():
    import h2o3_tpu
    X, y = make_classification()
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


@pytest.fixture()
def regress_frame():
    import h2o3_tpu
    X, y = make_regression()
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = y
    return h2o3_tpu.Frame.from_numpy(cols)
