"""Test harness: force an 8-virtual-device CPU mesh.

The analogue of the reference's multi-JVM-on-localhost test clouds
(multiNodeUtils.sh + @CloudSize(n), water/runner/H2ORunner.java:27): tests
exercise the same sharded/psum code paths the TPU pod runs, on 8 virtual
CPU devices.
"""

import os

# --xla_cpu_use_thunk_runtime=false: the new CPU thunk runtime in this
# jaxlib intermittently segfaults inside backend_compile_and_load after
# a few hundred compilations in one process (observed twice mid-suite,
# different tests each time); the legacy runtime is stable.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"
if "--xla_cpu_use_thunk_runtime" not in _flags:
    _flags += " --xla_cpu_use_thunk_runtime=false"
os.environ["XLA_FLAGS"] = _flags.strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="session")
def _cloud():
    """Boot the cloud once per session (stall_till_cloudsize analogue)."""
    import h2o3_tpu
    cpu = jax.devices("cpu")
    jax.config.update("jax_default_device", cpu[0])
    h2o3_tpu.init(backend="cpu")
    info = h2o3_tpu.cluster_info()
    assert info["cloud_size"] == 8, info
    yield
    h2o3_tpu.shutdown()


@pytest.fixture()
def rng():
    return np.random.RandomState(42)


def make_classification(n=4000, f=8, seed=0, informative=4):
    """Synthetic binary problem with known signal (TestFrameCatalog role)."""
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    logits = X[:, :informative] @ r.uniform(0.5, 2.0, informative)
    p = 1 / (1 + np.exp(-logits))
    y = (r.rand(n) < p).astype(int)
    return X, y


def make_regression(n=4000, f=8, seed=0, noise=0.1):
    r = np.random.RandomState(seed)
    X = r.randn(n, f)
    y = X[:, 0] * 2.0 + np.sin(X[:, 1] * 2) + 0.5 * X[:, 2] * X[:, 3]
    y = y + noise * r.randn(n)
    return X, y


@pytest.fixture()
def classif_frame():
    import h2o3_tpu
    X, y = make_classification()
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


@pytest.fixture()
def regress_frame():
    import h2o3_tpu
    X, y = make_regression()
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = y
    return h2o3_tpu.Frame.from_numpy(cols)
