"""Training-step profiler acceptance (ISSUE 20): per-chunk phase
timing as a wall-clock partition, bounded rings, straggler/skew
verdicts on per-host snapshots, the perf-baseline regression guard,
and scripts/benchdiff.py's offline gate.

The multiprocess leg spawns a REAL 2-process gloo pod
(tests/globalfit_worker.py ``profile`` mode) with ONE artificially
delayed host and asserts ``GET /3/Models/{id}/profile?cluster=1``
names that host as the straggler.
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "globalfit_worker.py")
BENCHDIFF = os.path.join(REPO, "scripts", "benchdiff.py")

from h2o3_tpu.telemetry import stepprof  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_registry():
    stepprof.reset()
    yield
    stepprof.reset()


def _load_benchdiff():
    spec = importlib.util.spec_from_file_location("benchdiff", BENCHDIFF)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ unit tier


def test_ring_is_bounded_and_chunks_counted(monkeypatch):
    monkeypatch.setenv("H2O3TPU_STEPPROF_RING", "16")
    prof = stepprof.start("gbm", nrows=4000)
    assert prof is not None
    for _ in range(100):
        stepprof.chunk_begin()
        stepprof.compute_done(None)
        stepprof.chunk_end(trees=5)
    d = stepprof.finish(prof, model_key="m_ring", seconds=None)
    assert len(d["ring"]) == 16          # bounded
    assert d["chunks"] == 100            # but every chunk counted
    assert stepprof.profile_for("m_ring")["chunks"] == 100


def test_phase_partition_never_exceeds_wall_clock():
    prof = stepprof.start("gbm", nrows=100)
    for _ in range(3):
        stepprof.chunk_begin()
        time.sleep(0.01)                 # inside the compute window
        stepprof.compute_done(None)
        stepprof.chunk_end()
    time.sleep(0.02)                     # trailing host gap
    d = stepprof.finish(prof, model_key="m_part")
    assert sum(d["phases"].values()) <= d["seconds"] + 1e-3
    assert d["phases"]["compute"] >= 0.02        # 3 x 10ms windows
    assert d["phases"]["host"] >= 0.015          # the trailing gap


def test_delay_knob_charges_host_on_the_slow_chunk(monkeypatch):
    """The fault-injected slow chunk: H2O3TPU_STEPPROF_DELAY sleeps in
    chunk_end and the time lands in that chunk's host phase — the
    straggler signature the pod leg detects cross-host."""
    prof = stepprof.start("gbm", nrows=100)
    stepprof.chunk_begin()
    stepprof.compute_done(None)
    stepprof.chunk_end()
    monkeypatch.setenv("H2O3TPU_STEPPROF_DELAY", "0.08")
    stepprof.chunk_begin()
    stepprof.compute_done(None)
    stepprof.chunk_end()
    monkeypatch.delenv("H2O3TPU_STEPPROF_DELAY")
    d = stepprof.finish(prof, model_key="m_delay")
    fast, slow = d["ring"]
    assert slow["phases"]["host"] >= 0.075
    assert slow["phases"]["host"] > fast["phases"]["host"] + 0.05


def test_phase_cm_and_marks():
    prof = stepprof.start("glm", nrows=10)
    with stepprof.phase("checkpoint"):
        time.sleep(0.02)
    stepprof.mark("put_sharded_seconds", 0.5)
    d = stepprof.finish(prof, model_key="m_cm")
    assert d["phases"]["checkpoint"] >= 0.015
    assert d["marks"]["put_sharded_seconds"] == 0.5
    # marks are annotations, NOT partition members
    assert sum(d["phases"].values()) <= d["seconds"] + 1e-3


def test_profile_registry_lookup_and_miss():
    prof = stepprof.start("gbm")
    stepprof.finish(prof, model_key="m_hit")
    assert stepprof.profile_for("m_hit")["algo"] == "gbm"
    with pytest.raises(KeyError):
        stepprof.profile_for("m_nope")
    assert stepprof.last_fit_phases("gbm")["chunks"] == 0
    assert stepprof.last_fit_phases("deeplearning") == {}


def test_disabled_knob_makes_weave_free(monkeypatch):
    monkeypatch.setenv("H2O3TPU_STEPPROF", "off")
    assert stepprof.start("gbm") is None
    # the woven calls must all be no-ops without an active profile
    stepprof.chunk_begin()
    assert stepprof.compute_done("x") == "x"
    stepprof.chunk_end()
    assert stepprof.finish(None) is None


def test_snapshot_bounds_published_payload():
    for i in range(20):
        prof = stepprof.start("gbm")
        for _ in range(40):
            stepprof.chunk_begin()
            stepprof.chunk_end()
        stepprof.finish(prof, model_key=f"m_{i}")
    snap = stepprof.snapshot()
    assert len(snap["fits"]) == stepprof.SNAPSHOT_FITS
    assert all(len(f["ring"]) <= stepprof.SNAPSHOT_RING
               for f in snap["fits"])
    assert snap["fits"][0]["model_key"] == "m_19"      # newest first


# ------------------------------------------------------- skew verdicts


def _host(proc, host, compute, collective, checkpoint=0.0):
    return {"proc": proc,
            "seconds": host + compute + collective + checkpoint,
            "phases": {"host": host, "compute": compute,
                       "collective": collective,
                       "checkpoint": checkpoint}}


def test_compute_skew_names_the_straggler():
    """Synthetic 2-peer snapshots: the slow host accrues SELF time, the
    fast host accrues collective wait at the barrier probe."""
    skew = stepprof.compute_skew({
        "0": _host(0, host=0.5, compute=2.0, collective=7.5),
        "1": _host(1, host=4.0, compute=5.5, collective=0.5)})
    assert skew["straggler"] == "1"
    assert skew["straggler_proc"] == 1
    assert skew["skew_ratio"] == pytest.approx(9.5 / 2.5, rel=1e-3)
    assert skew["hosts"]["0"]["collective_share"] > 0.7
    assert skew["hosts"]["1"]["collective_share"] < 0.1


def test_compute_skew_balanced_and_empty():
    skew = stepprof.compute_skew({
        "0": _host(0, host=1.0, compute=4.0, collective=1.0),
        "1": _host(1, host=1.0, compute=4.0, collective=1.0)})
    assert skew["skew_ratio"] == pytest.approx(1.0)
    empty = stepprof.compute_skew({})
    assert empty["straggler"] is None and empty["skew_ratio"] == 0.0


def test_cluster_profile_single_process_sets_gauges():
    """On a 1-process cloud cluster_profile degrades to the local view:
    one host, skew 1.0, gauges published."""
    prof = stepprof.start("gbm", nrows=100)
    stepprof.chunk_begin()
    stepprof.compute_done(None)
    stepprof.chunk_end()
    stepprof.finish(prof, model_key="m_solo")
    from h2o3_tpu.telemetry import cluster
    cluster.publish(force=True)
    out = stepprof.cluster_profile("m_solo")
    assert out["model_key"] == "m_solo"
    assert len(out["hosts"]) == 1
    assert out["straggler_proc"] == 0
    from h2o3_tpu.telemetry.registry import REGISTRY
    assert [g.value for g in REGISTRY.find("pod_straggler_host")] == [0.0]


# --------------------------------------------------- perfbase baselines


def test_perfbase_ratio_and_slo_rule(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_PERF_BASELINE_DIR", str(tmp_path))
    from h2o3_tpu.telemetry import perfbase
    prof = {"seconds": 2.0, "chunks": 4,
            "phases": {"host": 0.5, "compute": 1.5}}
    assert perfbase.record_fit("gbm", 5000, prof, mfu=0.01) == 1.0
    # 2x step-time regression vs the stored best
    prof2 = {"seconds": 4.0, "chunks": 4,
             "phases": {"host": 1.0, "compute": 3.0}}
    assert perfbase.record_fit("gbm", 5000, prof2) == 2.0
    doc = perfbase.load(perfbase.baseline_key("gbm", 5000))
    assert doc["best_step_seconds"] == 0.5       # best is sticky
    assert len(doc["history"]) == 2
    # the default SLO rule fires on the gauge the record just set
    from h2o3_tpu.telemetry import slo
    from h2o3_tpu.telemetry.registry import REGISTRY
    rule = {r.name: r for r in slo.default_rules()}["fit_step_regression"]
    ok, detail = rule.check_fn(REGISTRY)
    assert not ok and detail["worst_algo"] == "gbm"
    assert detail["max_ratio"] == 2.0


def test_perfbase_shape_buckets_isolate_baselines(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_PERF_BASELINE_DIR", str(tmp_path))
    from h2o3_tpu.telemetry import perfbase
    assert perfbase.shape_bucket(4001) == "r4096"
    assert perfbase.shape_bucket(4096) == "r4096"
    assert perfbase.shape_bucket(4097) == "r8192"
    slow = {"seconds": 10.0, "chunks": 1, "phases": {}}
    fast = {"seconds": 0.1, "chunks": 1, "phases": {}}
    perfbase.record_fit("gbm", 100, slow)
    # a different shape bucket never compares against the 100-row best
    assert perfbase.record_fit("gbm", 1_000_000, fast) == 1.0


def test_perfbase_ignores_chunkless_fits(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_PERF_BASELINE_DIR", str(tmp_path))
    from h2o3_tpu.telemetry import perfbase
    assert perfbase.record_fit("gbm", 10, {"seconds": 1.0,
                                           "chunks": 0}) is None
    assert os.listdir(str(tmp_path)) == []


# ----------------------------------------------------------- benchdiff


def test_benchdiff_flags_30pct_regression_and_passes_identical(tmp_path):
    bd = _load_benchdiff()
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps([
        {"metric": "fit_step", "value": 1.0, "unit": "seconds",
         "phases": {"host": 0.2, "compute": 0.8}},
        {"metric": "gbm_rate", "value": 1000.0, "unit": "rows/sec"}]))
    new.write_text(json.dumps([
        {"metric": "fit_step", "value": 1.3, "unit": "seconds",
         "phases": {"host": 0.2, "compute": 1.1}},
        {"metric": "gbm_rate", "value": 990.0, "unit": "rows/sec"}]))
    assert bd.main([str(old), str(old)]) == 0       # identical passes
    assert bd.main([str(old), str(new)]) == 1       # +30% seconds fails
    res = bd.compare(bd.load_metrics(str(old)), bd.load_metrics(str(new)))
    assert res["regressions"] == ["fit_step"]
    fail = next(r for r in res["rows"] if r["regressed"])
    assert fail["phase_deltas"]["compute"] == pytest.approx(0.3)


def test_benchdiff_direction_heuristic(tmp_path):
    """rows/sec dropping 30% is a regression; seconds dropping 30% is
    an improvement — unit direction decides the sign."""
    bd = _load_benchdiff()
    old = tmp_path / "o.json"
    new = tmp_path / "n.json"
    old.write_text(json.dumps([
        {"metric": "rate", "value": 1000.0, "unit": "rows/sec"},
        {"metric": "lat", "value": 1.0, "unit": "seconds"}]))
    new.write_text(json.dumps([
        {"metric": "rate", "value": 700.0, "unit": "rows/sec"},
        {"metric": "lat", "value": 0.7, "unit": "seconds"}]))
    res = bd.compare(bd.load_metrics(str(old)), bd.load_metrics(str(new)))
    by = {r["metric"]: r["regressed"] for r in res["rows"]}
    assert by == {"rate": True, "lat": False}


def test_benchdiff_parses_bench_artifact_tails(tmp_path):
    """The committed BENCH_*.json format: config entries whose `tail`
    embeds JSON metric lines; parsing stops at the summary marker and
    an all-error artifact diffs as a vacuous pass."""
    bd = _load_benchdiff()
    art = tmp_path / "BENCH_x.json"
    art.write_text(json.dumps(
        {"n": 5, "cmd": "bench", "rc": 0, "tail":
         'noise\n{"metric": "gbm cfg", "value": 5.0, "unit": "rows/sec"}'
         '\n# ---- summary\n{"metric": "gbm cfg", "value": 9.9, '
         '"unit": "rows/sec"}'}))
    m = bd.load_metrics(str(art))
    assert m == [{"metric": "gbm cfg", "value": 5.0,
                  "unit": "rows/sec"}]    # first wins, summary ignored
    assert bd.main([str(art), str(art)]) == 0
    # the committed r05 artifact (all-error round) stays a clean pass
    r05 = os.path.join(REPO, "BENCH_r05.json")
    assert bd.main([r05, r05]) == 0
    assert bd.main(["/nonexistent.json", str(art)]) == 2


# --------------------------------------------- the real 2-process leg


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.multiprocess
def test_pod_profile_names_the_delayed_straggler(tmp_path):
    """Acceptance: 2-process GBM global fit, pid 1 artificially delayed
    per chunk. /3/Models/{id}/profile?cluster=1 on pid 0 must name pid
    1 as the straggler, with pid 0's collective-wait share above the
    straggler's (the fast host waits at the barrier probe), and the
    pod_straggler_host gauge must carry the same verdict."""
    out = str(tmp_path / "profile.json")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"H2O3TPU_STEPPROF_DELAY_PID": "1",
                "H2O3TPU_STEPPROF_DELAY_S": "0.5",
                "H2O3TPU_PROFILE_PORT": str(_free_port())})
    procs = [subprocess.Popen(
        [sys.executable, WORKER, coord, "2", str(i), out, "profile"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    logs = []
    deadline = time.time() + 240
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=max(deadline - time.time(),
                                                  1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + "\n[TIMEOUT]"
        logs.append(stdout)
    joined = "\n".join(f"--- worker {j} ---\n{lg[-3000:]}"
                       for j, lg in enumerate(logs))
    assert all(p.returncode == 0 for p in procs), joined
    with open(out) as f:
        res = json.load(f)
    cl = res["cluster"]
    assert cl is not None, joined
    assert len(cl["hosts"]) == 2, cl
    assert cl["straggler_proc"] == 1, cl
    # skew = max/min self-time: the injected 0.5s/chunk delay must make
    # pid 1's self-time measurably larger.  The bound is modest because
    # the timeshared 1-core container runs both hosts' real compute
    # back-to-back, diluting the ratio.
    assert cl["skew_ratio"] > 1.1, cl
    # the fast host's collective-wait share rises above the straggler's
    hosts = {h["proc"]: h for h in cl["hosts"].values()}
    assert hosts[0]["collective_share"] > hosts[1]["collective_share"], cl
    # gauge names carry the registry's export prefix (h2o3tpu_...)
    gauges = {k.rsplit("pod_", 1)[-1]: v for k, v in res["gauges"].items()}
    assert gauges["straggler_host"] == 1.0, res
    assert gauges["step_skew_ratio"] > 1.1, res
    assert res["chunks"] >= 2, res
