"""Low-latency scoring tier (ISSUE 14): compiled scorer cache,
continuous micro-batching, and the row-payload predict fast path.

The acceptance contract:
- row-payload predictions are BIT-IDENTICAL to ``Model.predict`` on the
  same rows (both paths dispatch the model's one compiled program,
  ``Model._serve_jit`` — identical traced program, identical XLA
  fusions), across GBM/DRF/GLM/DL, categorical domains, NAs, and
  calibrated probabilities;
- the compile observer sees exactly ONE fresh compile per (model, row
  bucket) across a concurrent request storm;
- the bounded predict queue raises QueueSaturated (→ 503) instead of
  blocking, and expired deadlines fail in-queue (→ 408) without
  spending a device dispatch;
- the scorer cache registers with the memory governor and survives
  eviction by re-registering on the next request.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.core import request_ctx
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.serving.batcher import (MicroBatcher, PendingScore,
                                      QueueSaturated)
from h2o3_tpu.serving.engine import engine
from h2o3_tpu.serving.rows import (ServingUnsupported, domains_of,
                                   parse_rows, serving_schema)
from h2o3_tpu.telemetry import REGISTRY

# the engine's scorer cache and batcher threads are process-global by
# design (like the DKV); REST handler threads create keys the
# thread-local Scope cannot track
pytestmark = pytest.mark.allow_key_leak

N_ROWS = 240


def _frame(resp):
    r = np.random.RandomState(14)
    cols = {}
    x1 = r.randn(N_ROWS)
    x1[::17] = np.nan                       # numeric NAs
    cols["x1"] = x1
    cols["x2"] = r.randn(N_ROWS) * 3 + 1
    cols["x3"] = r.randint(0, 50, N_ROWS).astype(np.float64)
    cols["c1"] = np.array([["a", "b", "c", "d"][i % 4]
                           for i in range(N_ROWS)], dtype=object)
    cols["c2"] = np.array([["u", "v"][i % 2]
                           for i in range(N_ROWS)], dtype=object)
    if resp == "bin":
        yv = (np.nan_to_num(x1) + cols["x2"] * 0.2
              + r.randn(N_ROWS) > 0.5).astype(int)
        cols["y"] = np.array(["no", "yes"], dtype=object)[yv]
    elif resp == "mul":
        yv = r.randint(0, 3, N_ROWS)
        cols["y"] = np.array(["r", "g", "b"], dtype=object)[yv]
    else:
        cols["y"] = cols["x2"] * 0.5 + r.randn(N_ROWS)
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["c1", "c2", "y"]
                                     if resp != "reg"
                                     else ["c1", "c2"])


def _train(tag):
    algo, resp = tag.split("-")
    fr = _frame(resp)
    x = [c for c in fr.names if c != "y"]
    if algo == "gbm":
        from h2o3_tpu.models.gbm import GBMEstimator
        m = GBMEstimator(ntrees=5, max_depth=3, seed=1).train(
            fr, y="y", x=x)
    elif algo == "drf":
        from h2o3_tpu.models.drf import DRFEstimator
        m = DRFEstimator(ntrees=5, max_depth=3, seed=1).train(
            fr, y="y", x=x)
    elif algo == "glm":
        from h2o3_tpu.models.glm import GLMEstimator
        m = GLMEstimator(seed=1).train(fr, y="y", x=x)
    else:
        from h2o3_tpu.models.deeplearning import DeepLearningEstimator
        m = DeepLearningEstimator(hidden=[6], epochs=1, seed=1).train(
            fr, y="y", x=x)
    return m, fr


def _rows_of(model, fr, lo=0, hi=None):
    """JSON-shaped row payloads reproducing fr[lo:hi] exactly —
    including NAs (None) — in the model's serving schema."""
    schema = serving_schema(model)
    hi = fr.nrows if hi is None else hi
    cache = {nm: fr.col(nm).to_numpy() for nm, _ in schema
             if nm in fr.names}
    rows = []
    for i in range(lo, hi):
        r = {}
        for nm, dom in schema:
            if nm not in cache:
                continue
            v = float(cache[nm][i])
            if np.isnan(v):
                r[nm] = None
            elif dom is not None:
                r[nm] = dom[int(v)]
            else:
                r[nm] = v
        rows.append(r)
    return rows


def _assert_bit_identical(tag, base_frame, out, domains):
    for name in base_frame.names:
        a = base_frame.col(name).to_numpy()
        b = np.asarray(out[name])
        assert np.array_equal(np.asarray(a, dtype=np.float64),
                              np.asarray(b, dtype=np.float64),
                              equal_nan=True), (
            f"{tag}/{name}: max diff "
            f"{np.nanmax(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)))}")
    # the predict column's domain must be the training response domain
    dom = base_frame.col("predict").domain \
        if base_frame.col("predict").domain else None
    assert domains.get("predict") == dom


# ------------------------------------------------- bit-parity sweep


CASES = ["gbm-bin", "gbm-mul", "gbm-reg", "drf-mul", "drf-reg",
         "glm-bin", "glm-mul", "dl-bin", "dl-reg"]


@pytest.fixture(scope="module", params=CASES)
def served_case(request):
    m, fr = _train(request.param)
    return request.param, m, fr


def test_row_payload_bit_identical(served_case):
    """Acceptance: the row-payload fast path (parse → micro-batch →
    compiled dispatch → scatter) returns bit-identical columns to
    ``Model.predict`` on the same rows — cats, NAs, probabilities,
    class labels, everything."""
    tag, m, fr = served_case
    base = m.predict(fr)
    out, domains, meta = engine.score_rows(m, _rows_of(m, fr))
    assert meta["batch_rows"] >= fr.nrows
    _assert_bit_identical(tag, base, out, domains)
    DKV.remove(base.key)


def test_calibrated_probabilities_bit_identical():
    """Platt-calibrated cal_p0/cal_p1 flow through the shared
    ``_finish_predict`` tail — bit-identical on both paths."""
    from h2o3_tpu.ml.calibration import Calibrator
    m, fr = _train("gbm-bin")
    m.calibrator = Calibrator("plattscaling", (1.3, -0.2))
    base = m.predict(fr)
    assert "cal_p1" in base.names
    out, domains, _ = engine.score_rows(m, _rows_of(m, fr))
    assert "cal_p1" in out and "cal_p0" in out
    _assert_bit_identical("gbm-cal", base, out, domains)
    DKV.remove(base.key)


def test_unseen_level_scores_as_na():
    """A categorical level unseen at training time maps to NA (-1 code)
    — same prediction as an explicitly missing value (the reference's
    adaptTestForTrain contract)."""
    m, fr = _train("gbm-bin")
    rows = _rows_of(m, fr, 0, 1)
    row_na = dict(rows[0], c1=None)
    row_unseen = dict(rows[0], c1="never-seen-level")
    out_na, _, _ = engine.score_rows(m, [row_na])
    out_un, _, _ = engine.score_rows(m, [row_unseen])
    for k in out_na:
        np.testing.assert_array_equal(out_na[k], out_un[k])


def test_mojo_cross_check():
    """Serving-tier predictions agree with the offline MOJO runtime to
    float precision on the same raw rows (testdir_javapredict role)."""
    from h2o3_tpu.genmodel import load_mojo
    m, fr = _train("gbm-bin")
    rows = _rows_of(m, fr, 0, 64)
    out, _, _ = engine.score_rows(m, rows)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/serving_gbm.zip"
        m.download_mojo(path)
        mojo = load_mojo(path)
    doms = dict(serving_schema(m))
    raw = {}
    for nm in mojo.names:
        vals = [r.get(nm) for r in rows]
        if doms.get(nm) is None:
            raw[nm] = np.array([np.nan if v is None else float(v)
                                for v in vals], dtype=np.float64)
        else:
            raw[nm] = np.array(vals, dtype=object)
    offline = mojo.predict(raw)
    for k in ("p0", "p1"):
        a = np.asarray(out[k], dtype=np.float64)
        b = np.asarray(offline[k], dtype=np.float64)
        assert np.allclose(a, b, atol=1e-4), (
            k, float(np.abs(a - b).max()))


# -------------------------------------------- one compile per bucket


def test_one_compile_per_bucket_under_storm():
    """Acceptance: a concurrent request storm compiles each (model, row
    bucket) exactly ONCE — every further hit on a bucket is an
    executable-cache hit, visible in the compile observer's
    jit_cache_{miss,hit}_total{fn="serving.gbm"} counters."""

    def _misses():
        with REGISTRY._lock:
            return sum(
                m.value for (nm, _), m in REGISTRY._metrics.items()
                if nm.endswith("jit_cache_miss_total")
                and getattr(m, "labels", {}).get("fn") == "serving.gbm")

    def _hits():
        with REGISTRY._lock:
            return sum(
                m.value for (nm, _), m in REGISTRY._metrics.items()
                if nm.endswith("jit_cache_hit_total")
                and getattr(m, "labels", {}).get("fn") == "serving.gbm")

    m, fr = _train("gbm-bin")          # fresh model: empty jit cache
    rows = _rows_of(m, fr, 0, 3)
    base = m.predict(fr)
    expect = {nm: base.col(nm).to_numpy()[:3] for nm in base.names}
    DKV.remove(base.key)
    m0, h0 = _misses(), _hits()
    errors = []

    def _client():
        for _ in range(6):
            try:
                out, _, _ = engine.score_rows(m, rows)
            except BaseException as e:   # noqa: BLE001 - assert after join
                errors.append(e)
                return
            for k, v in expect.items():
                if not np.array_equal(np.asarray(out[k], np.float64),
                                      np.asarray(v, np.float64),
                                      equal_nan=True):
                    errors.append(AssertionError(f"{k} drifted"))
                    return

    threads = [threading.Thread(target=_client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[0]
    buckets = engine._scorers[m.key].buckets
    assert buckets, "storm must have populated row buckets"
    # registration warm-up + storm compiled exactly len(buckets)
    # programs for this (fresh) model — one per padded row bucket
    assert _misses() - m0 == len(buckets), (buckets, _misses() - m0)
    assert _hits() - h0 > 0, "storm must hit the executable cache"
    # the scorer-cache counters tell the same story
    assert REGISTRY.value("scorer_cache_hits_total",
                          algo="gbm", path="compiled") > 0


# ------------------------------------- backpressure and deadlines


def test_queue_saturation_raises_not_blocks():
    """A full bounded queue raises QueueSaturated immediately (the REST
    tier maps it to 503 + Retry-After) — never blocks the caller."""
    started = threading.Event()

    def _stuck(batch):
        started.set()
        time.sleep(5.0)
        for p in batch:
            p.finish(result=None)

    mb = MicroBatcher("sat-test", _stuck, max_rows=4, wait_ms=0.0,
                      queue_depth=2)
    try:
        cols = {"x1": np.zeros(1)}
        mb.submit(PendingScore(cols, 1))
        started.wait(2.0)              # dispatcher now stuck in _stuck
        mb.submit(PendingScore(cols, 1))
        mb.submit(PendingScore(cols, 1))
        with pytest.raises(QueueSaturated):
            mb.submit(PendingScore(cols, 1))
    finally:
        mb.close(join=False)


def test_expired_deadline_fails_in_queue():
    """An expired request deadline fails with DeadlineExceeded (→ 408)
    BEFORE spending a device dispatch."""
    dispatched = []
    mb = MicroBatcher("dl-test", lambda b: dispatched.append(b),
                      max_rows=4, wait_ms=0.0, queue_depth=4)
    try:
        p = PendingScore({"x1": np.zeros(1)}, 1,
                         deadline=time.monotonic() - 1.0)
        mb.submit(p)
        assert p.wait(5.0)
        assert isinstance(p.error, request_ctx.DeadlineExceeded)
        assert not dispatched
    finally:
        mb.close()


def test_score_rows_honors_request_deadline():
    """engine.score_rows inherits the ambient request deadline
    (request_ctx) — an already-expired one raises DeadlineExceeded."""
    m, fr = _train("gbm-reg")
    rows = _rows_of(m, fr, 0, 2)
    engine.register(m)                 # warm-up outside the deadline
    with request_ctx.deadline_scope(time.monotonic() - 0.5):
        with pytest.raises(request_ctx.DeadlineExceeded):
            engine.score_rows(m, rows)
    out, _, _ = engine.score_rows(m, rows)      # healthy afterwards
    assert len(out["predict"]) == 2


# --------------------------------------------- memgov integration


def test_eviction_and_reregistration():
    """The scorer cache is a memgov auxiliary cache: eviction drops
    compiled scorers (counted), the next request transparently
    re-registers, and the governor's ladder can reach it."""
    from h2o3_tpu.core import memgov
    m, fr = _train("glm-bin")
    rows = _rows_of(m, fr, 0, 4)
    engine.score_rows(m, rows)
    assert m.key in engine._scorers
    assert engine.cache_nbytes() > 0
    assert memgov.aux_cache_bytes() >= engine.cache_nbytes()
    e0 = REGISTRY.total("scorer_cache_evictions_total")
    freed = engine.evict()
    assert freed > 0
    assert m.key not in engine._scorers
    assert REGISTRY.total("scorer_cache_evictions_total") > e0
    out, _, _ = engine.score_rows(m, rows)      # re-registers
    assert m.key in engine._scorers
    assert len(out["predict"]) == 4


def test_serving_unsupported_algo():
    class _Fake:
        algo = "kmeans"
    with pytest.raises(ServingUnsupported):
        serving_schema(_Fake())


def test_parse_rows_errors():
    schema = [("x1", None), ("c1", ["a", "b"])]
    with pytest.raises(ValueError, match="non-empty"):
        parse_rows(schema, [])
    with pytest.raises(ValueError, match="expects a number"):
        parse_rows(schema, [{"x1": "not-a-number"}])
    cols = parse_rows(schema, [{"x1": 1.5, "c1": "b"}, {}])
    assert cols["x1"][0] == 1.5 and np.isnan(cols["x1"][1])
    assert cols["c1"][0] == 1 and cols["c1"][1] == -1
    assert domains_of(schema) == {"c1": ["a", "b"]}


# --------------------------------- chunked bulk scoring (satellite)


def test_chunked_predict_bit_identical():
    """predict_in_chunks == predict, bit-exact, at any chunk size — the
    row_slice sub-frames reproduce the parent's device bytes."""
    m, fr = _train("gbm-mul")
    base = m.predict(fr)
    for chunk_rows in (64, 100):
        ch = m.predict_in_chunks(fr, chunk_rows=chunk_rows)
        for nm in base.names:
            np.testing.assert_array_equal(
                base.col(nm).to_numpy(), ch.col(nm).to_numpy(),
                err_msg=f"chunk_rows={chunk_rows}/{nm}")
        DKV.remove(ch.key)
    DKV.remove(base.key)


def test_chunked_predict_observes_deadline():
    """Satellite (a): the chunked bulk-scoring loop calls cancel_point
    at every chunk boundary — an expired request deadline aborts the
    predict within one chunk instead of scoring the full frame."""
    m, fr = _train("glm-reg")
    with request_ctx.deadline_scope(time.monotonic() - 0.5):
        with pytest.raises(request_ctx.DeadlineExceeded):
            m.predict_in_chunks(fr, chunk_rows=32)


def test_chunked_predict_observes_job_cancel():
    from h2o3_tpu.core.job import Job, JobCancelledException
    m, fr = _train("glm-reg")
    job = Job("cancelled bulk predict")
    job.cancel()
    with request_ctx.job_scope(job):
        with pytest.raises(JobCancelledException):
            m.predict_in_chunks(fr, chunk_rows=32)


# ------------------------------------------------------- REST tier


@pytest.fixture(scope="module")
def port():
    from h2o3_tpu.api.server import start_server, stop_server
    p = start_server(port=0, background=True)
    yield p
    stop_server()


def _req(port, method, path, **params):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    if method == "POST":
        data = urllib.parse.urlencode(
            {k: (json.dumps(v) if isinstance(v, (list, dict)) else v)
             for k, v in params.items()}).encode()
    elif params:
        url += "?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type",
                       "application/x-www-form-urlencoded")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_row_payload_predict(port):
    """POST /3/Predictions/models/{mid} with inline JSON rows returns
    per-column predictions matching Model.predict — labels from the
    training domain, probabilities bit-identical."""
    m, fr = _train("gbm-bin")
    rows = _rows_of(m, fr, 0, 8)
    st, j = _req(port, "POST", f"/3/Predictions/models/{m.key}",
                 rows=rows)
    assert st == 200, j
    assert j["model_id"] == m.key and j["rows_scored"] == 8
    base = m.predict(fr)
    dom = m.output["domain"]
    want_labels = [dom[int(v)] for v in
                   base.col("predict").to_numpy()[:8]]
    assert j["predictions"]["predict"] == want_labels
    np.testing.assert_array_equal(
        np.asarray(j["predictions"]["p1"], dtype=np.float64),
        base.col("p1").to_numpy()[:8])
    assert j["batch"]["batch_rows"] >= 8
    DKV.remove(base.key)


def test_rest_row_payload_errors(port):
    st, j = _req(port, "POST", "/3/Predictions/models/no_such_model",
                 rows=[{"x1": 1}])
    assert st == 404
    m, _ = _train("glm-bin")
    st, j = _req(port, "POST", f"/3/Predictions/models/{m.key}")
    assert st == 412 and "rows" in j["msg"]
    st, j = _req(port, "POST", f"/3/Predictions/models/{m.key}",
                 rows=[{"x1": "banana"}])
    assert st == 412 and "expects a number" in j["msg"]


def test_rest_async_bulk_predict_chunked(port, monkeypatch):
    """Satellite (a): /4/Predictions scores through predict_in_chunks —
    forced to multiple chunks here — and the banked predictions frame
    is bit-identical to Model.predict."""
    monkeypatch.setenv("H2O3TPU_PREDICT_CHUNK_ROWS", "64")
    m, fr = _train("drf-reg")
    st, j = _req(port, "POST",
                 f"/4/Predictions/models/{m.key}/frames/{fr.key}")
    assert st == 200, j
    key = j["key"]["name"]
    t0 = time.time()
    while time.time() - t0 < 120:
        st, jj = _req(port, "GET", f"/3/Jobs/{key}")
        assert st == 200
        job = jj["jobs"][0]
        if job["status"] in ("DONE", "FAILED", "CANCELLED"):
            break
        time.sleep(0.2)
    assert job["status"] == "DONE", job
    preds = DKV.get(job["dest"]["name"])
    base = m.predict(fr)
    for nm in base.names:
        np.testing.assert_array_equal(base.col(nm).to_numpy(),
                                      preds.col(nm).to_numpy())
    DKV.remove(base.key)
