"""Device-side sort / merge vs host oracles.

The Rapids sort and single-key merge run on device above
DEVICE_SORT_MIN_ROWS (water/rapids/RadixOrder + BinaryMerge roles);
these tests pin exact agreement with numpy lexsort / pandas merge at a
size that takes the device path.
"""

import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ops.sort import DEVICE_SORT_MIN_ROWS, device_sort
from h2o3_tpu.rapids import _device_merge

N = DEVICE_SORT_MIN_ROWS + 1234


def test_device_sort_matches_lexsort():
    r = np.random.RandomState(0)
    a = r.randint(0, 50, N).astype(float)
    # f32: device columns store float32, so the host oracle must sort
    # the same representation
    b = r.randn(N).astype(np.float32).astype(float)
    b[::97] = np.nan
    fr = Frame.from_numpy({"a": a, "b": b, "v": np.arange(N, dtype=float)})
    out = device_sort(fr, ["a", "b"], [True, False])
    assert out is not None
    got_a = out.col("a").to_numpy()[:N]
    got_b = out.col("b").to_numpy()[:N]
    # oracle: stable lexsort, descending b, NaN last within group
    bk = np.where(np.isnan(b), np.inf, -b)
    order = np.lexsort((bk, a))
    assert np.array_equal(got_a, a[order])
    exp_b = b[order]
    both_nan = np.isnan(got_b) & np.isnan(exp_b)
    assert np.all(both_nan | (got_b == exp_b))


def test_device_sort_ignores_padding_rows():
    r = np.random.RandomState(1)
    a = r.randint(0, 9, N).astype(float)
    fr = Frame.from_numpy({"a": a})
    out = device_sort(fr, ["a"], [True])
    assert out is not None
    assert out.nrows == N
    got = out.col("a").to_numpy()[:N]
    assert np.array_equal(got, np.sort(a, kind="stable"))


def _cmp_merge(got, exp, keys, cols):
    g = got.to_pandas().sort_values(cols, na_position="last") \
        .reset_index(drop=True)
    e = exp.sort_values(cols, na_position="last").reset_index(drop=True)
    assert len(g) == len(e), (len(g), len(e))
    for col in cols:
        ga = pd.to_numeric(g[col], errors="coerce").to_numpy(float)
        ea = pd.to_numeric(e[col], errors="coerce").to_numpy(float)
        nn = ~(np.isnan(ga) & np.isnan(ea))
        assert np.allclose(ga[nn], ea[nn]), col


def test_device_merge_multikey():
    """Two-key join with NAs in a key column: NA keys never match
    (Merge.java semantics) and multi-key equality is exact."""
    r = np.random.RandomState(7)
    k1 = r.randint(0, 200, N).astype(float)
    k2 = r.randint(0, 5, N).astype(float)
    k1[::101] = np.nan
    nr = N // 3
    rk1 = r.randint(100, 300, nr).astype(float)
    rk2 = r.randint(0, 5, nr).astype(float)
    lf = Frame.from_numpy({"k1": k1, "k2": k2,
                           "lv": np.arange(N, dtype=float)})
    rf = Frame.from_numpy({"k1": rk1, "k2": rk2,
                           "rv": np.arange(nr, dtype=float)})
    ldf = lf.to_pandas()
    rdf = rf.to_pandas()
    from h2o3_tpu.ops.merge import device_merge
    for how in ("inner", "left"):
        got = device_merge(lf, rf, ["k1", "k2"], how)
        assert got is not None
        rr = rdf.dropna(subset=["k1", "k2"])
        ll = ldf.dropna(subset=["k1", "k2"]) if how == "inner" else ldf
        exp = ll.merge(rr, how=how, on=["k1", "k2"])
        _cmp_merge(got, exp, ["k1", "k2"], ["k1", "k2", "lv", "rv"])


def test_device_merge_categorical_key_domain_remap():
    """Categorical keys with DIFFERENT domains remap right→left; unseen
    right levels never match.

    Cardinality must be realistic: a 4-level key made this join
    quadratic (66K x 16K rows -> 208M output rows), which starved the
    XLA CPU collective rendezvous into a 40s termination abort on the
    8-virtual-device mesh (the round-4 crash). 512 levels keeps the
    result ~2M rows while still exercising remap + unseen levels;
    device_merge now budget-checks and refuses quadratic blowups."""
    r = np.random.RandomState(8)
    card = 512
    ldom = ["L%03d" % i for i in range(card)]          # L000..L511
    rdom = ["L%03d" % i for i in range(1, card + 1)]   # L512 unseen
    lcode = r.randint(0, card, N)
    rcode = r.randint(0, card, N // 4)
    lf = Frame.from_numpy(
        {"k": lcode.astype(np.int32), "lv": np.arange(N, dtype=float)},
        categorical=["k"], domains={"k": ldom})
    rf = Frame.from_numpy(
        {"k": rcode.astype(np.int32), "rv": np.arange(N // 4, dtype=float)},
        categorical=["k"], domains={"k": rdom})
    from h2o3_tpu.ops.merge import device_merge
    got = device_merge(lf, rf, ["k"], "inner")
    assert got is not None
    llab = np.array(ldom, object)[lcode]
    rlab = np.array(rdom, object)[rcode]
    ldf = pd.DataFrame({"k": llab, "lv": np.arange(N, dtype=float)})
    rdf = pd.DataFrame({"k": rlab, "rv": np.arange(N // 4, dtype=float)})
    exp = ldf.merge(rdf, how="inner", on="k")
    g = got.to_pandas().sort_values(["k", "lv", "rv"]).reset_index(drop=True)
    e = exp.sort_values(["k", "lv", "rv"]).reset_index(drop=True)
    assert len(g) == len(e)
    assert list(g["k"]) == list(e["k"])
    assert np.allclose(g["lv"], e["lv"]) and np.allclose(g["rv"], e["rv"])


def test_device_merge_budget_guard_refuses_quadratic_join(monkeypatch):
    """A low-cardinality key whose join result would dwarf the device
    budget must fall back to the host path (return None), never abort
    the process — the round-4 crash regression pin. The budget is
    pinned via env so the assertion holds on any mesh platform."""
    monkeypatch.setenv("H2O3TPU_MERGE_MAX_OUT_BYTES", str(1 << 30))
    r = np.random.RandomState(9)
    lcode = r.randint(0, 4, N)
    rcode = r.randint(0, 4, N // 4)
    dom = ["a", "b", "c", "d"]
    lf = Frame.from_numpy(
        {"k": lcode.astype(np.int32), "lv": np.arange(N, dtype=float)},
        categorical=["k"], domains={"k": dom})
    rf = Frame.from_numpy(
        {"k": rcode.astype(np.int32), "rv": np.arange(N // 4, dtype=float)},
        categorical=["k"], domains={"k": dom})
    from h2o3_tpu.ops.merge import device_merge
    assert device_merge(lf, rf, ["k"], "inner") is None


def test_device_merge_int_keys_exact_above_f32():
    """int32 keys beyond the f32-exact range (2^24) must still join
    exactly — the device path compares ints as ints."""
    base = 20_000_000
    lk = base + np.arange(N)
    rk = base + np.arange(0, N, 7)
    lf = Frame.from_numpy({"k": lk.astype(np.int64),
                           "lv": np.arange(N, dtype=float)})
    rf = Frame.from_numpy({"k": rk.astype(np.int64),
                           "rv": np.arange(len(rk), dtype=float)})
    from h2o3_tpu.ops.merge import device_merge
    got = device_merge(lf, rf, ["k"], "inner")
    assert got is not None
    # every 7th left row matches exactly once
    assert got.nrows == len(rk)


def test_device_merge_inner_and_left():
    r = np.random.RandomState(2)
    lk = r.randint(0, 1000, N).astype(float)
    rk = r.randint(500, 1500, N // 3).astype(float)
    lf = Frame.from_numpy({"k": lk, "lv": np.arange(N, dtype=float)})
    rf = Frame.from_numpy({"k": rk, "rv": np.arange(len(rk), dtype=float)})
    ldf = pd.DataFrame({"k": lk, "lv": np.arange(N, dtype=float)})
    rdf = pd.DataFrame({"k": rk, "rv": np.arange(len(rk), dtype=float)})
    for how in ("inner", "left"):
        got = _device_merge(lf, rf, how)
        assert got is not None
        exp = ldf.merge(rdf, how=how)
        g = got.to_pandas().sort_values(["k", "lv", "rv"],
                                        na_position="last").reset_index(drop=True)
        e = exp.sort_values(["k", "lv", "rv"],
                            na_position="last").reset_index(drop=True)
        assert len(g) == len(e), (how, len(g), len(e))
        for col in ("k", "lv", "rv"):
            ga = g[col].to_numpy()
            ea = e[col].to_numpy()
            nn = ~(np.isnan(ga) & np.isnan(ea))
            assert np.allclose(ga[nn], ea[nn]), (how, col)
