"""Device-side sort / merge vs host oracles.

The Rapids sort and single-key merge run on device above
DEVICE_SORT_MIN_ROWS (water/rapids/RadixOrder + BinaryMerge roles);
these tests pin exact agreement with numpy lexsort / pandas merge at a
size that takes the device path.
"""

import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ops.sort import DEVICE_SORT_MIN_ROWS, device_sort
from h2o3_tpu.rapids import _device_merge

N = DEVICE_SORT_MIN_ROWS + 1234


def test_device_sort_matches_lexsort():
    r = np.random.RandomState(0)
    a = r.randint(0, 50, N).astype(float)
    # f32: device columns store float32, so the host oracle must sort
    # the same representation
    b = r.randn(N).astype(np.float32).astype(float)
    b[::97] = np.nan
    fr = Frame.from_numpy({"a": a, "b": b, "v": np.arange(N, dtype=float)})
    out = device_sort(fr, ["a", "b"], [True, False])
    assert out is not None
    got_a = out.col("a").to_numpy()[:N]
    got_b = out.col("b").to_numpy()[:N]
    # oracle: stable lexsort, descending b, NaN last within group
    bk = np.where(np.isnan(b), np.inf, -b)
    order = np.lexsort((bk, a))
    assert np.array_equal(got_a, a[order])
    exp_b = b[order]
    both_nan = np.isnan(got_b) & np.isnan(exp_b)
    assert np.all(both_nan | (got_b == exp_b))


def test_device_sort_ignores_padding_rows():
    r = np.random.RandomState(1)
    a = r.randint(0, 9, N).astype(float)
    fr = Frame.from_numpy({"a": a})
    out = device_sort(fr, ["a"], [True])
    assert out is not None
    assert out.nrows == N
    got = out.col("a").to_numpy()[:N]
    assert np.array_equal(got, np.sort(a, kind="stable"))


def test_device_merge_inner_and_left():
    r = np.random.RandomState(2)
    lk = r.randint(0, 1000, N).astype(float)
    rk = r.randint(500, 1500, N // 3).astype(float)
    lf = Frame.from_numpy({"k": lk, "lv": np.arange(N, dtype=float)})
    rf = Frame.from_numpy({"k": rk, "rv": np.arange(len(rk), dtype=float)})
    ldf = pd.DataFrame({"k": lk, "lv": np.arange(N, dtype=float)})
    rdf = pd.DataFrame({"k": rk, "rv": np.arange(len(rk), dtype=float)})
    for how in ("inner", "left"):
        got = _device_merge(lf, rf, how)
        assert got is not None
        exp = ldf.merge(rdf, how=how)
        g = got.to_pandas().sort_values(["k", "lv", "rv"],
                                        na_position="last").reset_index(drop=True)
        e = exp.sort_values(["k", "lv", "rv"],
                            na_position="last").reset_index(drop=True)
        assert len(g) == len(e), (how, len(g), len(e))
        for col in ("k", "lv", "rv"):
            ga = g[col].to_numpy()
            ea = e[col].to_numpy()
            nn = ~(np.isnan(ga) & np.isnan(ea))
            assert np.allclose(ga[nn], ea[nn]), (how, col)
