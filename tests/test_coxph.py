"""CoxPH tests — vs a plain-numpy Newton reference (the testdir_algos/
coxph pyunit role: numeric agreement with R survival::coxph)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.coxph import CoxPHEstimator, concordance_index


def _sim_surv(n=400, seed=7, p=3):
    r = np.random.RandomState(seed)
    X = r.randn(n, p)
    beta = np.array([0.8, -0.5, 0.3][:p])
    u = r.rand(n)
    t = -np.log(u) / (0.1 * np.exp(X @ beta))
    cens = r.exponential(scale=np.median(t) * 2.0, size=n)
    stop = np.minimum(t, cens)
    event = (t <= cens).astype(float)
    # discretize some times to force ties
    stop = np.round(stop, 1) + 0.1
    return X, stop, event, beta


def _numpy_cox_nll_breslow(beta, X, stop, event):
    """O(n^2) reference: exact Breslow partial likelihood."""
    eta = X @ beta
    r = np.exp(eta)
    ll = 0.0
    for i in np.flatnonzero(event > 0):
        risk = r[stop >= stop[i]].sum()
        ll += eta[i] - np.log(risk)
    return -ll


def _numpy_cox_fit(X, stop, event, ties="breslow", iters=200):
    from scipy.optimize import minimize
    if ties == "breslow":
        f = lambda b: _numpy_cox_nll_breslow(b, X, stop, event)
    else:
        def f(b):
            eta = X @ b
            r = np.exp(eta)
            ll = 0.0
            for t in np.unique(stop[event > 0]):
                d = np.flatnonzero((stop == t) & (event > 0))
                R = r[stop >= t].sum()
                T = r[d].sum()
                ll += eta[d].sum()
                for k in range(len(d)):
                    ll -= np.log(R - k / len(d) * T)
            return -ll
    res = minimize(f, np.zeros(X.shape[1]), method="BFGS",
                   options={"maxiter": iters})
    return res.x


@pytest.fixture(scope="module")
def surv_frame():
    X, stop, event, beta = _sim_surv()
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
        "stop": stop, "event": event})
    return fr, X, stop, event


@pytest.mark.parametrize("ties", ["breslow", "efron"])
def test_coxph_matches_numpy_newton(surv_frame, ties):
    fr, X, stop, event = surv_frame
    m = CoxPHEstimator(stop_column="stop", ties=ties).train(
        fr, y="event", x=["x0", "x1", "x2"])
    ref = _numpy_cox_fit(X, stop, event, ties=ties)
    got = np.array([m.coef[i] for i in range(3)])
    np.testing.assert_allclose(got, ref, atol=5e-3)
    assert m.training_metrics["concordance"] > 0.6
    assert m.output["loglik"] > m.output["null_loglik"]


def test_coxph_predict_lp_and_se(surv_frame):
    fr, X, stop, event = surv_frame
    m = CoxPHEstimator(stop_column="stop").train(
        fr, y="event", x=["x0", "x1", "x2"])
    pred = m.predict(fr)
    lp = pred.col("lp").to_numpy()
    assert lp.shape == (fr.nrows,)
    assert abs(np.average(lp)) < 0.5  # centered
    tbl = m.output["coefficients_table"]
    assert len(tbl) == 3
    for row in tbl:
        assert np.isfinite(row["se_coef"]) and row["se_coef"] > 0
        assert row["exp_coef"] == pytest.approx(np.exp(row["coef"]))


def test_coxph_strata_and_start():
    r = np.random.RandomState(3)
    n = 300
    X = r.randn(n, 2)
    g = r.randint(0, 3, n)
    t = -np.log(r.rand(n)) / (0.1 * np.exp(X @ [0.7, -0.4] + 0.5 * g))
    stop = np.round(np.minimum(t, 30.0), 1) + 0.1
    event = (t <= 30.0).astype(float)
    start = np.zeros(n)
    fr = Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1],
         "grp": np.array([f"g{i}" for i in g], object),
         "start": start, "stop": stop, "event": event},
        categorical=["grp"])
    m = CoxPHEstimator(stop_column="stop", start_column="start",
                       stratify_by=["grp"]).train(
        fr, y="event", x=["x0", "x1"])
    # stratified fit should still recover signs and beat null
    assert m.coef[0] > 0 and m.coef[1] < 0
    assert m.output["loglik"] > m.output["null_loglik"]


def test_concordance_index_perfect_and_random():
    t = np.arange(1.0, 101.0)
    e = np.ones(100)
    assert concordance_index(t, e, -t) == pytest.approx(1.0)
    assert concordance_index(t, e, t) == pytest.approx(0.0)
    assert concordance_index(t, e, np.zeros(100)) == pytest.approx(0.5)
