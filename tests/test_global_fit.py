"""Pod-global sharded training (ISSUE 19): host-partitioned frames,
the ``H2O3TPU_GLOBAL_FIT`` knob, padding parity on uneven row counts,
and the true 2-process acceptance legs — a global GBM fit over a
host-partitioned frame must bit-match the single-process reference,
GLM coefficients within 1e-10, and a SIGKILLed peer mid-global-fit
must fail the survivor's job fast with no RUNNING leak.

Single-process tests run in the ordinary tier-1 cloud (8 CPU devices,
conftest); the real pods are ``pytest.mark.multiprocess`` and spawn
``tests/globalfit_worker.py``.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.parallel import mesh as mesh_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "globalfit_worker.py")
WORKER_TIMEOUT_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300"))


# ------------------------------------------------------- knob parsing


def test_global_fit_mode_parsing(monkeypatch):
    for raw, want in [("on", "on"), ("OFF", "off"), ("auto", "auto"),
                      ("bogus", "auto"), ("", "auto")]:
        monkeypatch.setenv("H2O3TPU_GLOBAL_FIT", raw)
        assert mesh_mod.global_fit_mode() == want, raw
    monkeypatch.delenv("H2O3TPU_GLOBAL_FIT")
    assert mesh_mod.global_fit_mode() == "auto"      # config default
    monkeypatch.setenv("H2O3TPU_GLOBAL_FIT", "off")
    assert not mesh_mod.global_fit_enabled()
    monkeypatch.setenv("H2O3TPU_GLOBAL_FIT", "on")
    assert mesh_mod.global_fit_enabled()


# ------------------------------------- shard-homing contract (1 proc)


def test_partition_bounds_cover_all_rows_single_process():
    n = 517                      # deliberately n % (devices*block) != 0
    npad = mesh_mod.padded_rows(n, block=8)
    lo, hi = mesh_mod.partition_bounds(npad)
    assert (lo, hi) == (0, npad)
    assert mesh_mod.owned_rows(n, block=8) == (0, n)


def test_put_partitioned_matches_put_sharded_single_process():
    n = 517
    npad = mesh_mod.padded_rows(n, block=8)
    x = np.zeros(npad, dtype=np.float32)
    x[:n] = np.random.RandomState(0).randn(n)
    sh = mesh_mod.row_sharding()
    a = mesh_mod.put_sharded(x, sh)
    b = mesh_mod.put_partitioned(x, sh, (npad,))
    assert a.dtype == b.dtype and a.shape == b.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------- padding parity on uneven row counts


def _uneven_arrays(n=517):
    r = np.random.RandomState(7)
    a = r.randn(n)
    a[::97] = np.nan                               # NA handling
    b = (r.randint(-50, 50, n)).astype(np.float64)  # int-narrowed col
    g = r.choice(["x", "y", "z"], n).astype(object)
    g[5] = None                                    # categorical NA
    y = np.nan_to_num(a) * 2.0 - b * 0.1 + r.randn(n) * 0.3
    return {"a": a, "b": b, "g": g, "y": y}


def _both_frames(n=517, pad_to=None):
    arrays = _uneven_arrays(n)
    legacy = h2o3_tpu.Frame.from_numpy(
        arrays, categorical=["g"], pad_to=pad_to)
    part = h2o3_tpu.Frame.from_numpy_partitioned(
        dict(arrays), n, categorical=["g"], pad_to=pad_to)
    return legacy, part


def test_partitioned_ingest_bit_identical_uneven_rows():
    """Single process, nrows not a multiple of devices*block: the
    partitioned ingest must produce byte-identical device data, NA
    masks, dtypes, domains and host views — pad rows included."""
    legacy, part = _both_frames()
    for name in legacy.names:
        cl, cp = legacy.col(name), part.col(name)
        assert cl.type == cp.type and cl.domain == cp.domain, name
        assert cl.data.dtype == cp.data.dtype, name
        np.testing.assert_array_equal(
            np.asarray(cl.data), np.asarray(cp.data), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(cl.na_mask), np.asarray(cp.na_mask), err_msg=name)
        np.testing.assert_array_equal(
            cl.host_view(), cp.host_view(), err_msg=name)


def test_partitioned_ingest_codes_non_str_objects():
    """Object columns holding non-str values (ints read back from a
    python list, say) must code through their str() form like the
    replicated auto-factorize path — not silently become NA because the
    merged domain interned str(u) levels."""
    n = 517
    r = np.random.RandomState(5)
    g = r.randint(1, 10, n).astype(object)        # non-str objects
    g[7] = None                                   # the only genuine NA
    arrays = {"g": g, "y": r.randn(n)}
    legacy = h2o3_tpu.Frame.from_numpy(dict(arrays))
    part = h2o3_tpu.Frame.from_numpy_partitioned(dict(arrays), n)
    cl, cp = legacy.col("g"), part.col("g")
    assert cl.type == cp.type == "categorical"
    assert cl.domain == cp.domain
    np.testing.assert_array_equal(np.asarray(cl.data), np.asarray(cp.data))
    np.testing.assert_array_equal(np.asarray(cl.na_mask),
                                  np.asarray(cp.na_mask))
    # exactly one NA (the None) — the pre-fix symptom was all-NA codes
    assert int(np.asarray(cp.na_mask)[:n].sum()) == 1


def test_partitioned_host_view_is_seeded_at_ingest():
    """host_view()/prefetch_host() run in single-process contexts (REST
    handlers, scheduled items) that must never issue a collective: the
    full f64 host cache is seeded AT INGEST, the one guaranteed
    collective point."""
    _, part = _both_frames()
    for name in part.names:
        c = part.col(name)
        if getattr(c, "_part_cache", None) is not None:
            assert getattr(c, "_host_cache", None) is not None, name


def test_partitioned_ingest_off_knob_is_identity_single_process(
        monkeypatch):
    monkeypatch.setenv("H2O3TPU_GLOBAL_FIT", "off")
    legacy, part = _both_frames()
    for name in legacy.names:
        np.testing.assert_array_equal(
            np.asarray(legacy.col(name).data),
            np.asarray(part.col(name).data), err_msg=name)


def test_weighted_mean_ignores_pad_rows():
    """The masked rollup reduction (NA-masked sum + valid-row count):
    pad rows must be invisible — exactly — on both ingest paths and
    under extra ``pad_to`` padding."""
    import jax.numpy as jnp

    from h2o3_tpu.parallel.map_reduce import frame_reduce
    n = 517
    np_b = _uneven_arrays(n)["b"]
    vals = {}
    for tag, pad_to in [("tight", None), ("wide", 2048)]:
        legacy, part = _both_frames(n, pad_to=pad_to)
        for kind, fr in [("legacy", legacy), ("part", part)]:
            col = fr.col("b")
            w = (~col.na_mask).astype(jnp.float32)
            xz = jnp.where(col.na_mask, 0.0,
                           col.data.astype(jnp.float32))
            sw, swx = frame_reduce(
                lambda wl, xl: (jnp.sum(wl), jnp.sum(xl)), w, xz)
            vals[(tag, kind)] = (float(sw), float(swx))
            assert fr.mean("b") == pytest.approx(float(np_b.mean()),
                                                 rel=1e-5)
        assert vals[(tag, "legacy")] == vals[(tag, "part")], tag
    # the NA-masked count sees exactly the n real rows in every layout
    assert all(v[0] == float(n) for v in vals.values()), vals
    want = float(np.asarray(_uneven_arrays(n)["b"],
                            dtype=np.float32).sum(dtype=np.float64))
    for v in vals.values():
        assert abs(v[1] - want) < 1e-2 * max(abs(want), 1.0)


def test_histogram_pad_parity_uneven_rows():
    """GBM histogram: rows with w == 0 (mesh padding) contribute
    nothing, regardless of how much padding the layout carries."""
    from h2o3_tpu.ops.histogram import histogram
    from h2o3_tpu.parallel.mesh import get_mesh, shard_rows
    r = np.random.RandomState(3)
    n, L, B = 517, 4, 16
    mesh = get_mesh()
    bins_r = r.randint(0, B, size=(n, 2)).astype(np.int32)
    nid_r = r.randint(0, L, size=n).astype(np.int32)
    w_r = np.ones(n, dtype=np.float32)
    g_r = r.randn(n).astype(np.float32)
    h_r = np.abs(r.randn(n)).astype(np.float32)

    def _hist(npad, pad_fill):
        pad = npad - n
        rf = np.random.RandomState(pad_fill)
        fills = (rf.randint(0, B, size=(pad, 2)).astype(np.int32),
                 rf.randint(0, L, size=pad).astype(np.int32),
                 np.zeros(pad, dtype=np.float32),          # w == 0 always
                 rf.randn(pad).astype(np.float32),
                 rf.randn(pad).astype(np.float32))
        args = [np.concatenate([a, f])
                for a, f in zip((bins_r, nid_r, w_r, g_r, h_r), fills)]
        return np.asarray(histogram(
            shard_rows(args[0]), shard_rows(args[1]), shard_rows(args[2]),
            shard_rows(args[3]), shard_rows(args[4]),
            n_nodes=L, n_bins=B, mesh=mesh))

    npad = mesh_mod.padded_rows(n, block=8)
    # same padded shape, different garbage under the w==0 pad rows:
    # bit-exact — zero-weight rows contribute nothing at all
    a = _hist(npad, pad_fill=1)
    b = _hist(npad, pad_fill=2)
    np.testing.assert_array_equal(a, b)
    # a wider layout only re-blocks the scan (f32 reassociation), it
    # never lets pad rows leak mass in: counts exact, moments tight
    c = _hist(2048, pad_fill=3)
    np.testing.assert_array_equal(a[..., 0], c[..., 0])
    np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-5)
    # the w-plane tallies exactly the n real rows
    assert float(a[..., 0].sum()) == float(n) * bins_r.shape[1]


def test_gbm_fit_uneven_rows_partitioned_matches_legacy():
    from h2o3_tpu.models.gbm import GBMEstimator
    legacy, part = _both_frames()
    m1 = GBMEstimator(ntrees=5, max_depth=3, seed=3).train(legacy, y="y")
    m2 = GBMEstimator(ntrees=5, max_depth=3, seed=3).train(part, y="y")
    for f1, f2 in zip(m1.forest, m2.forest):
        np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    assert float(m1.training_metrics["MSE"]) \
        == float(m2.training_metrics["MSE"])


def test_glm_gram_uneven_rows_partitioned_matches_legacy():
    from h2o3_tpu.models.glm import GLMEstimator
    n = 517
    r = np.random.RandomState(13)
    arrays = {"a": r.randn(n), "b": r.randn(n)}
    arrays["y"] = 2.0 * arrays["a"] - arrays["b"] + r.randn(n) * 0.1
    legacy = h2o3_tpu.Frame.from_numpy(dict(arrays))
    part = h2o3_tpu.Frame.from_numpy_partitioned(dict(arrays), n)
    g1 = GLMEstimator(family="gaussian", lambda_=0.0).train(legacy, y="y")
    g2 = GLMEstimator(family="gaussian", lambda_=0.0).train(part, y="y")
    assert g1.coefficients == g2.coefficients     # same gram, same solve
    # pads carry zero weight: the gram solve agrees with the dense
    # normal-equations reference over ONLY the real rows
    X = np.column_stack([arrays["a"], arrays["b"], np.ones(n)])
    ref, *_ = np.linalg.lstsq(X, arrays["y"], rcond=None)
    got = [g1.coefficients["a"], g1.coefficients["b"],
           g1.coefficients["Intercept"]]
    np.testing.assert_allclose(got, ref, atol=5e-4)


# --------------------------------------------- the real 2-process legs


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pod(tmp_path, mode, nproc, extra_env=None):
    out = str(tmp_path / f"{mode}.json")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, str(nproc), str(i), out, mode],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(nproc)
    ]
    logs = []
    deadline = time.time() + WORKER_TIMEOUT_S
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=max(deadline - time.time(),
                                                  1.0))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            stdout, _ = p.communicate()
            stdout = (stdout or "") + \
                f"\n[TIMEOUT after {WORKER_TIMEOUT_S:.0f}s]"
        logs.append(stdout)
    joined = "\n".join(f"--- worker {j} ({mode}) ---\n{lg[-3000:]}"
                       for j, lg in enumerate(logs))
    for i, p in enumerate(procs):
        if mode == "sigkill" and i == 1:
            assert p.returncode not in (0, None), \
                f"victim survived its own SIGKILL:\n{joined}"
            continue
        assert p.returncode == 0, \
            f"worker {i} ({mode}) failed rc={p.returncode}:\n{joined}"
    with open(out) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def acceptance(tmp_path_factory):
    """fit pod (2 procs, host-partitioned) + ref run (1 proc, 2
    devices): the SAME data=2 SPMD program, so bit-parity is a program
    identity, not a tolerance."""
    tmp = tmp_path_factory.mktemp("globalfit")
    fit = _run_pod(tmp, "fit", 2)
    ref = _run_pod(tmp, "ref", 1)
    return fit, ref


@pytest.mark.multiprocess
def test_global_fit_trains_on_host_partitioned_frame(acceptance):
    fit, ref = acceptance
    assert fit["process_count"] == 2
    assert fit["mesh_data"] == ref["mesh_data"] == 2
    # every column's device data is host-partitioned, none replicated
    assert fit["partitioned_cols"] == 4
    assert ref["partitioned_cols"] == 0


@pytest.mark.multiprocess
def test_global_gbm_bit_matches_single_process_reference(acceptance):
    fit, ref = acceptance
    assert fit["forest_digest"] == ref["forest_digest"]
    assert fit["gbm_mse_hex"] == ref["gbm_mse_hex"]
    assert fit["scoring_history"] == ref["scoring_history"]
    assert fit["scoring_history"], "no scoring history recorded"
    assert fit["gbm_pred_head_hex"] == ref["gbm_pred_head_hex"]


@pytest.mark.multiprocess
def test_global_glm_coefficients_match_reference(acceptance):
    fit, ref = acceptance
    assert set(fit["glm_coefficients"]) == set(ref["glm_coefficients"])
    for k, v in ref["glm_coefficients"].items():
        assert abs(fit["glm_coefficients"][k] - v) < 1e-10, k


@pytest.mark.multiprocess
def test_sigkill_mid_global_fit_fails_fast_no_running_leak(
        tmp_path_factory):
    res = _run_pod(tmp_path_factory.mktemp("globalfit_kill"), "sigkill", 2,
                   extra_env={"H2O3TPU_HEARTBEAT_INTERVAL_S": "0.25",
                              "H2O3TPU_HEARTBEAT_MISS_BUDGET": "2"})
    assert res["job_status"] == "FAILED", res
    assert res["infra_classified"], res["job_exception"]
    # fail-fast: within one heartbeat window of observing the loss,
    # plus one chunk dispatch (bounded generously for busy CI hosts)
    assert res["fail_after_loss_s"] is not None
    assert res["fail_after_loss_s"] < max(10.0,
                                          4 * res["heartbeat_window_s"]), res
    assert res["running_leaks"] == [], res


@pytest.mark.multiprocess
def test_global_fit_host_caches_and_gather_blobs_2proc(acceptance):
    """The fit pod's worker makes an ASYMMETRIC host_view() call (only
    pid 1) before training — proof the host cache was seeded at ingest
    and single-process host access needs no peer participation (a lazy
    collective there would wedge the pod and fail the whole fixture)."""
    fit, _ = acceptance
    # no ingest gather blobs may survive the exchange either (the
    # off-mode devolution path deletes them right after the barrier)
    assert fit["gather_keys_resident"] == 0


@pytest.mark.multiprocess
def test_global_fit_off_devolves_to_replicated_2proc(tmp_path_factory,
                                                     acceptance):
    """H2O3TPU_GLOBAL_FIT=off on a 2-process cloud: partitioned ingest
    devolves to the legacy replicated layout via the control-plane row
    allgather — same SPMD program as the reference, so the fit still
    bit-matches, no column is host-partitioned, and the dataset-sized
    gather blobs are deleted from the coordination service as soon as
    every peer has read them."""
    off = _run_pod(tmp_path_factory.mktemp("globalfit_off"), "fit", 2,
                   extra_env={"H2O3TPU_GLOBAL_FIT": "off"})
    _, ref = acceptance
    assert off["partitioned_cols"] == 0
    assert off["gather_keys_resident"] == 0
    assert off["forest_digest"] == ref["forest_digest"]
    assert off["gbm_mse_hex"] == ref["gbm_mse_hex"]
    for k, v in ref["glm_coefficients"].items():
        assert abs(off["glm_coefficients"][k] - v) < 1e-10, k
