"""PCA / SVD / GLRM tests — pyunit_pca* / pyunit_svd* / pyunit_glrm* role."""

import numpy as np

import h2o3_tpu
from h2o3_tpu.models.glrm import GLRMEstimator
from h2o3_tpu.models.pca import PCAEstimator, SVDEstimator


def _lowrank(n=1200, p=6, k=2, seed=0, noise=0.05):
    r = np.random.RandomState(seed)
    A = r.randn(n, k)
    Y = r.randn(k, p)
    return A @ Y + noise * r.randn(n, p)


def test_pca_variance_explained():
    X = _lowrank()
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    m = PCAEstimator(k=2, transform="demean").train(f)
    cum = m.output["cum_pct_variance"]
    assert cum[1] > 0.95, cum
    scores = m.predict(f).to_pandas()
    assert list(scores.columns) == ["PC1", "PC2"]
    # principal scores are uncorrelated
    cc = np.corrcoef(scores["PC1"], scores["PC2"])[0, 1]
    assert abs(cc) < 0.05


def test_pca_vs_numpy():
    X = _lowrank(seed=3)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    m = PCAEstimator(k=3, transform="demean").train(f)
    Xc = X - X.mean(axis=0)
    ref = np.linalg.svd(Xc, full_matrices=False)[1] ** 2 / (len(X) - 1)
    got = np.asarray(m.output["std_deviation"]) ** 2
    np.testing.assert_allclose(got, ref[:3], rtol=0.05)


def test_pca_randomized_close_to_exact():
    X = _lowrank(seed=5)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    exact = PCAEstimator(k=2, transform="demean").train(f)
    rand = PCAEstimator(k=2, transform="demean", pca_method="Randomized",
                        seed=1).train(f)
    np.testing.assert_allclose(rand.output["std_deviation"],
                               exact.output["std_deviation"], rtol=0.05)


def test_svd_orthogonal_v():
    X = _lowrank(seed=7)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(6)})
    m = SVDEstimator(nv=3).train(f)
    V = np.asarray(m.output["v"])
    np.testing.assert_allclose(V.T @ V, np.eye(3), atol=1e-4)
    d = np.asarray(m.output["d"])
    assert (np.diff(d) <= 1e-6).all()   # descending


def test_glrm_reconstructs_lowrank():
    X = _lowrank(n=800, p=5, k=2, seed=9, noise=0.02)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(5)})
    m = GLRMEstimator(k=2, max_iterations=30, seed=2).train(f)
    rec = m.reconstruct(f).to_pandas().to_numpy()
    rel = np.linalg.norm(rec - X) / np.linalg.norm(X)
    assert rel < 0.05, rel


def test_glrm_handles_missing_cells():
    X = _lowrank(n=600, p=5, k=2, seed=11, noise=0.02)
    Xna = X.copy()
    r = np.random.RandomState(0)
    holes = r.rand(*X.shape) < 0.15
    Xna[holes] = np.nan
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": Xna[:, i] for i in range(5)})
    m = GLRMEstimator(k=2, max_iterations=40, seed=3).train(f)
    rec = m.reconstruct(f).to_pandas().to_numpy()
    # held-out (missing) cells reconstructed from low-rank structure
    err = np.abs(rec[holes] - X[holes]).mean()
    assert err < 0.25, err
