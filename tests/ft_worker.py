"""Worker for the fault-tolerance resume test: runs an AutoML plan with
a recovery_dir and gets SIGKILLed by the parent mid-plan
(tests/test_fault_tolerance.py). The parent then resume_automl()s from
the snapshots — the hex/faulttolerance/Recovery.java contract.

Deterministic data: build_data() here and in the parent test must stay
identical (the resume trains on "the same" frame a fresh cluster would
re-import after a crash).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

recovery_dir = sys.argv[1]

import numpy as np                            # noqa: E402

import h2o3_tpu                               # noqa: E402

h2o3_tpu.init(backend="cpu")


def build_data():
    r = np.random.RandomState(17)
    n = 1200
    X = r.randn(n, 5)
    logits = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2]
    y = (r.rand(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


from h2o3_tpu.automl import H2OAutoML         # noqa: E402

fr = build_data()
aml = H2OAutoML(max_models=8, seed=11, nfolds=0,
                include_algos=["glm", "gbm", "drf"],
                max_runtime_secs=600, recovery_dir=recovery_dir)
aml.train(y="y", training_frame=fr)
print("FT-WORKER-DONE", flush=True)
