"""PDP, permutation importance, calibration, export_file, SQL ingest."""

import os
import sqlite3

import numpy as np
import pytest

import h2o3_tpu
from tests.conftest import make_classification


@pytest.fixture(scope="module")
def gbm_and_frame():
    X, y = make_classification(n=2500, f=5, seed=3, informative=2)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.array(["no", "yes"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=12, max_depth=3, seed=1).train(fr, y="y")
    return m, fr


def test_partial_dependence(gbm_and_frame):
    from h2o3_tpu.ml.explain import partial_dependence
    m, fr = gbm_and_frame
    pdp = partial_dependence(m, fr, ["x0"], nbins=8)
    t = pdp["x0"]
    assert len(t["values"]) == len(t["mean_response"]) > 3
    # x0 is informative with positive sign → pdp trend upward overall
    assert t["mean_response"][-1] > t["mean_response"][0]
    assert all(s >= 0 for s in t["std_response"])


def test_permutation_varimp(gbm_and_frame):
    from h2o3_tpu.ml.explain import permutation_varimp
    m, fr = gbm_and_frame
    table = permutation_varimp(m, fr, seed=1)
    names = [r[0] for r in table]
    assert set(names) == {f"x{i}" for i in range(5)}
    # informative features (x0/x1) should out-rank pure noise
    top2 = set(names[:2])
    assert top2 & {"x0", "x1"}
    # scaled importances normalized
    assert table[0][2] == pytest.approx(1.0)


def test_calibration_platt_and_isotonic():
    from h2o3_tpu.models.gbm import GBMEstimator
    X, y = make_classification(n=3000, f=4, seed=9)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["no", "yes"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    for method in ("PlattScaling", "IsotonicRegression"):
        m = GBMEstimator(ntrees=10, max_depth=3, seed=2,
                         calibrate_model=True, calibration_frame=fr,
                         calibration_method=method).train(fr, y="y")
        preds = m.predict(fr)
        assert "cal_p1" in preds.names
        cp = preds.col("cal_p1").to_numpy()
        assert np.all((cp >= 0) & (cp <= 1))
        # calibrated probs track the labels at least as a sanity signal
        p1 = preds.col("p1").to_numpy()
        assert abs(np.corrcoef(cp, p1)[0, 1]) > 0.9


def test_calibration_requires_frame():
    from h2o3_tpu.models.gbm import GBMEstimator
    X, y = make_classification(n=400, f=3, informative=2)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array(["no", "yes"], object)[y]
    fr = h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])
    with pytest.raises(ValueError, match="calibration_frame"):
        GBMEstimator(ntrees=2, calibrate_model=True).train(fr, y="y")


def test_export_file_roundtrip(tmp_path):
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": np.asarray([1.5, np.nan, 3.0]),
         "g": np.asarray(["u", "v", None], dtype=object)},
        categorical=["g"])
    p = str(tmp_path / "out.csv")
    h2o3_tpu.export_file(fr, p)
    back = h2o3_tpu.import_file(p)
    assert back.shape == (3, 2)
    np.testing.assert_array_equal(np.isnan(back.col("a").to_numpy()),
                                  [False, True, False])
    with pytest.raises(IOError, match="exists"):
        h2o3_tpu.export_file(fr, p)
    h2o3_tpu.export_file(fr, p, force=True)


def test_sql_ingest(tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (a REAL, b TEXT)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(1.0, "x"), (2.5, "y"), (None, None)])
    conn.commit()
    conn.close()
    fr = h2o3_tpu.import_sql_table(f"sqlite:///{db}", "t")
    assert fr.shape == (3, 2)
    a = fr.col("a").to_numpy()
    assert a[1] == 2.5 and np.isnan(a[2])
    assert fr.col("b").domain == ["x", "y"]
    fr2 = h2o3_tpu.import_sql_select(
        f"sqlite:///{db}", "SELECT a FROM t WHERE a IS NOT NULL")
    assert fr2.shape == (2, 1)
    with pytest.raises(IOError, match="no built-in driver"):
        h2o3_tpu.import_sql_select("postgres://h/db", "SELECT 1")


def test_leaf_node_assignment(gbm_and_frame):
    m, fr = gbm_and_frame
    la = m.predict_leaf_node_assignment(fr)
    assert la.nrows == fr.nrows
    assert la.ncols == 12     # one column per tree
    v = la.col("T1.C1" if "T1.C1" in la.names else "T1").to_numpy()
    assert v.min() >= 0 and v.max() < 2 ** 3   # depth-3 leaves


def test_model_metrics_endpoint(gbm_and_frame):
    from h2o3_tpu.api.server import ROUTES
    m, fr = gbm_and_frame
    h = next(fn for mth, rx, fn in ROUTES
             if mth == "POST" and rx.match(f"/3/ModelMetrics/models/{m.key}/frames/{fr.key}"))
    out = h({}, "", mid=m.key, fid=fr.key)
    assert out["model_metrics"][0]["AUC"] > 0.5
