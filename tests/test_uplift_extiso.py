"""UpliftDRF + ExtendedIsolationForest tests (testdir_algos/uplift,
isoforextended pyunit roles)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.extisofor import ExtendedIsolationForestEstimator
from h2o3_tpu.models.uplift import UpliftDRFEstimator, auuc


@pytest.fixture(scope="module")
def uplift_data():
    """x0>0 defines responders-to-treatment; x1 is a prognostic factor."""
    r = np.random.RandomState(21)
    n = 2000
    X = r.randn(n, 3)
    treat = r.randint(0, 2, n)
    base = 0.2 + 0.2 * (X[:, 1] > 0)
    lift = 0.35 * ((X[:, 0] > 0) & (treat == 1))
    y = (r.rand(n) < base + lift).astype(int)
    fr = Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
         "treatment": np.where(treat == 1, "treatment", "control").astype(object),
         "conversion": np.where(y == 1, "yes", "no").astype(object)},
        categorical=["treatment", "conversion"])
    return fr, X, treat, y


@pytest.mark.parametrize("metric", ["kl", "euclidean"])
def test_uplift_drf_detects_heterogeneity(uplift_data, metric):
    fr, X, treat, y = uplift_data
    m = UpliftDRFEstimator(treatment_column="treatment", ntrees=20,
                           max_depth=4, uplift_metric=metric,
                           seed=7).train(fr, y="conversion")
    raw = m._score_raw(fr)
    up = raw["uplift_predict"]
    # true uplift is 0.35 for x0>0, 0 otherwise
    hi = up[X[:, 0] > 0.3].mean()
    lo = up[X[:, 0] < -0.3].mean()
    assert hi - lo > 0.15
    assert (raw["p_y1_ct1"] >= 0).all() and (raw["p_y1_ct1"] <= 1).all()
    d = m.training_metrics.to_dict()
    assert d["auuc"] > 0


def test_uplift_requires_treatment():
    with pytest.raises(ValueError):
        UpliftDRFEstimator()


def test_auuc_ranks_informed_above_random():
    r = np.random.RandomState(3)
    n = 4000
    tr = r.randint(0, 2, n).astype(float)
    true_up = np.where(r.rand(n) < 0.5, 0.4, 0.0)
    y = (r.rand(n) < 0.2 + true_up * tr).astype(float)
    informed = auuc(true_up + r.randn(n) * 0.01, y, tr)
    random = auuc(r.randn(n), y, tr)
    assert informed["auuc"] > random["auuc"]


def test_extended_isolation_forest_flags_outliers():
    r = np.random.RandomState(5)
    X = r.randn(500, 4)
    X[:8] += 6.0   # planted anomalies
    fr = Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = ExtendedIsolationForestEstimator(ntrees=60, sample_size=128,
                                         extension_level=1, seed=9).train(fr)
    s = m._score_raw(fr)["anomaly_score"]
    assert s[:8].mean() > s[8:].mean() + 0.1
    # scoring a new frame works and extension_level is validated
    s2 = m.predict(fr).col("anomaly_score").to_numpy()
    np.testing.assert_allclose(s2, s)
    with pytest.raises(ValueError):
        ExtendedIsolationForestEstimator(extension_level=10).train(fr)
