"""GBM interaction constraints (GlobalInteractionConstraints parity)."""

import numpy as np
import pytest

import h2o3_tpu


def _tree_paths_features(model):
    """Set of features used on each root-to-node path of every tree."""
    feat = np.asarray(model.forest.feat)       # [T, D, Lmax]
    is_split = np.asarray(model.forest.is_split)
    T, D, _ = feat.shape
    paths = []
    for t in range(T):
        # walk all nodes level by level, tracking path feature sets
        node_feats = {0: set()}
        for d in range(D):
            nxt = {}
            for node, fs in node_feats.items():
                if node < is_split.shape[2] and is_split[t, d, node]:
                    f = int(feat[t, d, node])
                    nf = fs | {f}
                    paths.append(nf)
                    nxt[2 * node] = nf
                    nxt[2 * node + 1] = nf
                else:
                    nxt[2 * node] = fs
            node_feats = nxt
    return paths


def test_interaction_constraints_respected():
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(0)
    n = 3000
    X = r.randn(n, 4)
    # response needs x0*x1 and x2*x3 interactions
    y = X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3] + 0.1 * r.randn(n)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    fr = h2o3_tpu.Frame.from_numpy(cols)
    m = GBMEstimator(ntrees=10, max_depth=4, seed=1,
                     interaction_constraints=[["x0", "x1"],
                                              ["x2", "x3"]]).train(fr, y="y")
    allowed = [{0, 1}, {2, 3}]
    for path in _tree_paths_features(m):
        assert any(path <= a for a in allowed), f"path {path} crosses sets"


def test_interaction_constraints_unlisted_singleton():
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(1)
    n = 2000
    X = r.randn(n, 3)
    y = X[:, 0] * X[:, 1] + X[:, 2] + 0.1 * r.randn(n)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(3)}, "y": y})
    m = GBMEstimator(ntrees=8, max_depth=3, seed=1,
                     interaction_constraints=[["x0", "x1"]]).train(fr, y="y")
    # x2 unlisted → singleton: may never share a path with x0/x1
    for path in _tree_paths_features(m):
        assert path <= {0, 1} or path <= {2}, f"bad path {path}"


def test_interaction_constraints_multinomial():
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(2)
    n = 2400
    X = r.randn(n, 4)
    cls = (X[:, 0] * X[:, 1] > 0).astype(int) + (X[:, 2] > 0.8).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)},
         "y": np.array(["a", "b", "c"], object)[cls]}, categorical=["y"])
    m = GBMEstimator(ntrees=6, max_depth=3, seed=1,
                     interaction_constraints=[["x0", "x1"],
                                              ["x2", "x3"]]).train(fr, y="y")
    allowed = [{0, 1}, {2, 3}]
    for path in _tree_paths_features(m):
        assert any(path <= a for a in allowed), f"path {path} crosses sets"


def test_interaction_constraints_validation():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = h2o3_tpu.Frame.from_numpy({"a": np.arange(100.0),
                                    "y": np.arange(100.0)})
    with pytest.raises(ValueError, match="not in predictors"):
        GBMEstimator(ntrees=2, interaction_constraints=[["zz"]]).train(
            fr, y="y")
