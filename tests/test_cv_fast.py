"""CV fold-masking fast path vs the subset-frame slow path.

The fast path (ml/cv.py) trains fold models on the parent frame with
held-out rows weight-masked and the main model's bin edges shared —
one compiled program across folds. These tests pin that it produces
the same CV surface (holdout metrics, fold models, kept predictions)
as the slow per-fold-subset path, and that leave-one-out CV
(nfolds == nrows, the pyunit_cv_cars_gbm boundary case) completes.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.drf import DRFEstimator
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.glm import GLMEstimator


def _frame(n=320, seed=0):
    r = np.random.RandomState(seed)
    a, b = r.randn(n), r.randn(n)
    y = (a + 0.5 * b + 0.3 * r.randn(n) > 0).astype(float)
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "y": y}, categorical=["y"])


@pytest.mark.parametrize("cls,params", [
    (GBMEstimator, dict(ntrees=5, max_depth=3)),
    (DRFEstimator, dict(ntrees=5, max_depth=3)),
    (GLMEstimator, dict(family="binomial", lambda_=0.0)),
])
def test_fast_matches_slow_path(cls, params, monkeypatch):
    fr = _frame()
    m_fast = cls(nfolds=4, fold_assignment="modulo", seed=7,
                 **params).train(fr, y="y")
    monkeypatch.setattr(cls, "cv_fold_masking", False)
    m_slow = cls(nfolds=4, fold_assignment="modulo", seed=7,
                 **params).train(fr, y="y")
    for m in (m_fast, m_slow):
        assert m.cross_validation_metrics is not None
        assert len(m.output["cv_model_keys"]) == 4
    # fold bin edges differ slightly (shared full-data sketch vs
    # per-fold sketch), so CV holdout AUC agrees closely but not bit-
    # exactly for trees; GLM shares the design entirely
    a_fast = float(m_fast.cross_validation_metrics["AUC"])
    a_slow = float(m_slow.cross_validation_metrics["AUC"])
    tol = 1e-5 if cls is GLMEstimator else 0.05
    assert abs(a_fast - a_slow) < tol, (a_fast, a_slow)
    # per-fold summary rows populated for every fold
    rows = m_fast.output["cv_summary_rows"]
    assert rows and all(len(r) == 2 + 1 + 4 for r in rows)


def test_fast_cv_deterministic():
    fr = _frame(seed=3)
    m1 = GBMEstimator(ntrees=5, nfolds=5, fold_assignment="modulo",
                      seed=11).train(fr, y="y")
    m2 = GBMEstimator(ntrees=5, nfolds=5, fold_assignment="modulo",
                      seed=11).train(fr, y="y")
    assert float(m1.cross_validation_metrics["AUC"]) == \
        float(m2.cross_validation_metrics["AUC"])


def test_leave_one_out_cv_completes():
    n = 48
    fr = _frame(n=n, seed=5)
    m = GBMEstimator(ntrees=3, max_depth=2, nfolds=n,
                     fold_assignment="modulo", seed=1).train(fr, y="y")
    assert len(m.output["cv_model_keys"]) == n
    assert np.isfinite(float(m.cross_validation_metrics["logloss"]))


def test_fast_cv_keep_predictions_cover_all_rows():
    fr = _frame(n=200, seed=9)
    m = GBMEstimator(ntrees=4, nfolds=4, fold_assignment="modulo", seed=2,
                     keep_cross_validation_predictions=True,
                     keep_cross_validation_models=True).train(fr, y="y")
    keys = m.output["cv_predictions_keys"]
    assert len(keys) == 4
    from h2o3_tpu.core.kv import DKV
    total = np.zeros(200)
    for k in keys:
        pf = DKV.get(k)
        p1 = pf.col("p1").to_numpy()
        total += (p1 != 0).astype(float)
    # every row held out exactly once ⇒ nonzero p1 in exactly one fold
    # frame (p1 == 0 exactly is measure-zero for a trained model)
    assert total.max() <= 1.0 and total.mean() > 0.95


def test_fast_cv_with_user_weights():
    """User weights_column composes with the fold mask."""
    r = np.random.RandomState(4)
    n = 240
    a = r.randn(n)
    y = (a + 0.3 * r.randn(n) > 0).astype(float)
    w = r.randint(1, 4, n).astype(float)
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": a, "w": w, "y": y}, categorical=["y"])
    m = GBMEstimator(ntrees=4, nfolds=3, weights_column="w",
                     fold_assignment="modulo", seed=6).train(
                         fr, x=["a"], y="y")
    assert np.isfinite(float(m.cross_validation_metrics["AUC"]))
