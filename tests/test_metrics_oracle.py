"""Metrics layer vs exact oracles.

The 400-bin AUC scheme (hex/AUC2.java:24) is exact when every row's
score falls in its own bin — these tests construct such scores so the
device one-pass metrics can be compared against sklearn / closed-form
numpy at float precision, pinning the actual arithmetic rather than a
loose ±0.06 band (round-2 verdict: golden checks too loose).
"""

import numpy as np
import pytest

from h2o3_tpu.models.metrics import (AUC_NBINS, binomial_metrics,
                                     multinomial_metrics,
                                     regression_metrics)


def _bin_centered_scores(n, seed):
    """n distinct scores, one per AUC bin — binned AUC == exact AUC."""
    assert n <= AUC_NBINS
    r = np.random.RandomState(seed)
    bins = r.choice(AUC_NBINS, size=n, replace=False)
    return (bins + 0.5) / AUC_NBINS, r


def test_auc_exact_vs_sklearn():
    from sklearn.metrics import log_loss, roc_auc_score
    p, r = _bin_centered_scores(320, seed=1)
    y = (r.rand(320) < p).astype(np.float32)
    if y.min() == y.max():          # degenerate draw guard
        y[0] = 1 - y[0]
    mm = binomial_metrics(p, y)
    assert abs(mm["AUC"] - roc_auc_score(y, p)) < 1e-5
    assert abs(mm["logloss"] - log_loss(y, p)) < 1e-5
    assert abs(mm["Gini"] - (2 * roc_auc_score(y, p) - 1)) < 2e-5


def test_auc_weighted_exact():
    """Integer weights ≡ row duplication — the backend-independent
    invariant (pyunit_weights_gbm contract, applied to metrics)."""
    from sklearn.metrics import roc_auc_score
    p, r = _bin_centered_scores(200, seed=7)
    y = (r.rand(200) < 0.5).astype(np.float32)
    y[0], y[1] = 0.0, 1.0
    w = r.randint(1, 4, 200).astype(np.float32)
    mm = binomial_metrics(p, y, w)
    rep = np.repeat(np.arange(200), w.astype(int))
    assert abs(mm["AUC"] - roc_auc_score(y[rep], p[rep])) < 1e-5


def test_max_f1_exact():
    from sklearn.metrics import f1_score
    p, r = _bin_centered_scores(150, seed=3)
    y = (r.rand(150) < p).astype(np.float32)
    y[0], y[1] = 0.0, 1.0
    mm = binomial_metrics(p, y)
    # oracle: scan every distinct-score threshold
    best = max(f1_score(y, (p >= t).astype(int))
               for t in np.unique(p))
    assert abs(mm["max_f1"] - best) < 1e-5


def test_regression_metrics_closed_form():
    r = np.random.RandomState(5)
    n = 1000
    y = r.randn(n) * 3 + 1
    pred = y + r.randn(n) * 0.5
    mm = regression_metrics(pred, y)
    resid = y - pred
    assert abs(mm["MSE"] - np.mean(resid ** 2)) < 1e-4
    assert abs(mm["mae"] - np.mean(np.abs(resid))) < 1e-4
    assert abs(mm["r2"] - (1 - np.mean(resid ** 2) / np.var(y))) < 1e-4


def test_regression_metrics_weighted_duplication():
    r = np.random.RandomState(6)
    n = 400
    y = r.randn(n)
    pred = y + r.randn(n) * 0.3
    w = r.randint(1, 5, n).astype(np.float32)
    mw = regression_metrics(pred, y, w)
    rep = np.repeat(np.arange(n), w.astype(int))
    md = regression_metrics(pred[rep], y[rep])
    for k in ("MSE", "mae", "r2"):
        assert abs(mw[k] - md[k]) < 1e-4, k


def test_multinomial_logloss_exact():
    from sklearn.metrics import log_loss
    r = np.random.RandomState(9)
    n, K = 500, 4
    logits = r.randn(n, K)
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    y = np.array([r.choice(K, p=probs[i]) for i in range(n)],
                 np.float32)
    mm = multinomial_metrics(probs.astype(np.float32), y,
                             domain=[str(k) for k in range(K)])
    want = log_loss(y, probs, labels=list(range(K)))
    assert abs(mm["logloss"] - want) < 1e-4


def test_gbm_stump_matches_exact_cart_oracle():
    """A depth-1 gaussian GBM stump with learn_rate=1 must pick the SSE-
    optimal (feature, threshold) among all candidates and predict the
    side means — brute-force CART oracle on integer features (distinct
    values ≤ nbins ⇒ binning is lossless)."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBMEstimator
    r = np.random.RandomState(2)
    n = 3000
    a = r.randint(0, 12, n).astype(float)
    b = r.randint(0, 9, n).astype(float)
    y = (a >= 7).astype(float) * 2.1 + 0.3 * b + 0.05 * r.randn(n)

    fr = Frame.from_numpy({"a": a, "b": b, "y": y})
    m = GBMEstimator(ntrees=1, max_depth=1, learn_rate=1.0, min_rows=1.0,
                     nbins=64, min_split_improvement=0.0,
                     sample_rate=1.0).train(fr, x=["a", "b"], y="y")
    pred = m.predict(fr).col("predict").to_numpy()

    # oracle: best single split over every (feature, value) pair
    best_sse, best_pred = np.inf, None
    for x in (a, b):
        for t in np.unique(x)[:-1]:
            left = x <= t
            p = np.where(left, y[left].mean(), y[~left].mean())
            sse = float(((y - p) ** 2).sum())
            if sse < best_sse:
                best_sse, best_pred = sse, p
    model_sse = float(((y - pred) ** 2).sum())
    # the stump must realize the oracle's SSE (same split, same means)
    assert model_sse <= best_sse * (1 + 1e-5), (model_sse, best_sse)
    assert np.abs(np.sort(np.unique(pred.round(5))) -
                  np.sort(np.unique(best_pred.round(5)))).max() < 1e-3


def test_quantiles_match_numpy_on_exact_grid():
    """Frame quantiles on data where the requested probs hit exact data
    points — interpolation-free, so any scheme must agree with numpy."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.quantiles import column_quantiles
    vals = np.arange(101, dtype=float)          # 0..100
    r = np.random.RandomState(4)
    fr = Frame.from_numpy({"x": r.permutation(vals)})
    got = column_quantiles(fr.col("x"), [0.0, 0.25, 0.5, 0.75, 1.0])
    want = [0.0, 25.0, 50.0, 75.0, 100.0]
    assert np.abs(np.asarray(got).ravel() - want).max() < 1e-6
