"""Worker for the durable-data-plane SIGKILL acceptance test (ISSUE 18
— the reference's node-loss recovery tier).

Two processes form a cloud with ``H2O3TPU_DATA_DURABILITY=mirror``:

* pid 1 ingests a deterministic frame (write-through mirrored into the
  shared ``H2O3TPU_DUR_DIR``), then starts a checkpointed GBM fit whose
  traveling snapshots land in the shared fit-checkpoint dir. The parent
  SIGKILLs it after the first snapshot appears.
* pid 0 waits for the heartbeat monitor to declare pid 1 dead, runs the
  recovery supervisor, and asserts: the frame is rebuilt bit-identically
  from its mirror, re-homed locally, visible in
  ``frame_rebuilds_total{source=mirror}``; the interrupted fit resumes
  from the dead peer's snapshot and finishes bit-identical to an
  undisturbed reference fit; no RUNNING job leaks.

Exits via ``os._exit`` — the normal distributed teardown would barrier
against the dead peer.
"""

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, outfile = sys.argv[1:5]

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=int(nproc), process_id=int(pid))

import numpy as np                            # noqa: E402

from h2o3_tpu.core import durability, heartbeat  # noqa: E402
from h2o3_tpu.models.gbm import GBMEstimator     # noqa: E402
from h2o3_tpu.parallel import mesh as mesh_mod   # noqa: E402

GBM_PARAMS = dict(ntrees=80, max_depth=3, learn_rate=0.1, seed=7)
DEADLINE_S = float(os.environ.get("H2O3TPU_MP_TIMEOUT_S", "300")) - 30.0
T0 = time.monotonic()


def build_data():
    r = np.random.RandomState(23)
    n = 1500
    a = r.randn(n)
    b = r.randn(n)
    c = r.randn(n)
    y = 1.5 * a - 0.5 * b + np.sin(c) + r.randn(n) * 0.2
    return h2o3_tpu.Frame.from_numpy({"a": a, "b": b, "c": c, "y": y})


def mark(stage):
    print(f"WORKER-{pid}-STAGE {time.monotonic() - T0:7.2f}s {stage}",
          flush=True)


def wait_for(pred, what, timeout_s=60.0):
    mark(f"waiting: {what}")
    end = min(time.monotonic() + timeout_s, T0 + DEADLINE_S)
    while time.monotonic() < end:
        if pred():
            mark(f"done: {what}")
            return
        time.sleep(0.1)
    raise TimeoutError(f"pid {pid}: timed out waiting for {what}")


if int(pid) == 1:
    # -- victim: ingest (mirrored) + checkpointed fit, then be killed
    with mesh_mod.local_mesh_scope():
        fr = build_data()
        assert fr.key in durability.stats()["mirrored"], \
            "write-through mirror did not register the frame"
        mark("frame mirrored; starting checkpointed fit")
        # the parent SIGKILLs this process once the fit's first
        # traveling snapshot lands in the shared checkpoint dir
        GBMEstimator(**GBM_PARAMS).train(fr, y="y")
    # only reached if the parent's kill never landed — that is a test
    # failure upstream; report and exit cleanly
    print(f"WORKER-{pid}-UNEXPECTED-SURVIVAL", flush=True)
    os._exit(1)

# -- survivor (pid 0): recover, resume, and reference-check

# the victim registers exactly one frame in the coordination KV
wait_for(lambda: len(durability.registry(1)) == 1,
         "peer 1's registry entry")
(frame_key, entry), = durability.registry(1).items()
want_digest = entry["digest"]
assert entry.get("gen"), f"peer frame was not mirrored: {entry}"

# heartbeat declares the SIGKILLed peer dead once its beat goes stale
wait_for(lambda: 1 in heartbeat.dead_peers(), "heartbeat death of pid 1",
         timeout_s=120.0)

# run the recovery supervisor until the frame is re-homed here — the
# heartbeat piggyback races this same call; both paths are idempotent
# and the parent sets H2O3TPU_DUR_REBUILD_S low enough to retry fast
from h2o3_tpu.core.kv import DKV              # noqa: E402
wait_for(lambda: durability.maybe_rebuild() >= 0 and frame_key in DKV,
         "rebuild of the lost frame")

from h2o3_tpu import telemetry                # noqa: E402
fr = DKV.get(frame_key)
with mesh_mod.local_mesh_scope():
    got_digest = durability.frame_digest(fr)
mark("frame rebuilt + digest checked")
assert got_digest == want_digest, \
    f"rebuilt frame is not bit-identical: {got_digest} != {want_digest}"
mirror_rebuilds = telemetry.counter(
    "frame_rebuilds_total", source="mirror").value
assert mirror_rebuilds >= 1, "rebuild not visible in frame_rebuilds_total"

# resume the dead peer's fit: same (algo, params, y, x, nrows) →
# same fingerprint → the traveling snapshot it wrote is picked up
os.environ.pop("H2O3TPU_FIT_CHECKPOINT_HOLD_S", None)
# local_work_scope: these fits run purely on local devices (the
# scheduler work-item pattern) — the dead peer must not fail them
with heartbeat.local_work_scope(), mesh_mod.local_mesh_scope():
    resumed = GBMEstimator(**GBM_PARAMS).train(fr, y="y")
    resumed_pred = resumed.predict(fr).col("predict").to_numpy()
mark("resumed fit done")

# undisturbed reference: same data + params, checkpointing off
os.environ.pop("H2O3TPU_FIT_CHECKPOINT_DIR", None)
with heartbeat.local_work_scope(), mesh_mod.local_mesh_scope():
    fresh = GBMEstimator(**GBM_PARAMS).train(fr, y="y")
    fresh_pred = fresh.predict(fr).col("predict").to_numpy()
mark("reference fit done")
assert np.array_equal(resumed_pred, fresh_pred), \
    "resumed fit is not bit-identical to the undisturbed reference"

running = [k for k in DKV.keys()
           if getattr(DKV.get_raw(k), "status", None) == "RUNNING"]
assert not running, f"RUNNING job leak after recovery: {running}"

result = {
    "frame_key": frame_key,
    "digest_match": True,
    "rebuild_source": "mirror",
    "mirror_rebuilds_total": float(mirror_rebuilds),
    "resumed_mse": float(resumed.training_metrics["MSE"]),
    "fresh_mse": float(fresh.training_metrics["MSE"]),
    "bit_identical_fit": True,
    "under_replicated": telemetry.gauge("frames_under_replicated").value,
}
with open(outfile, "w") as f:
    json.dump(result, f)
print(f"WORKER-{pid}-DONE", flush=True)
os._exit(0)
