"""Fused Pallas tree kernels (ops/pallas/treekernel.py) — ISSUE 6.

Acceptance contract: in interpret mode on CPU the fused histogram +
split + partition level pass is BIT-EXACT against the XLA path on the
same mesh (f32 accumulation with the same row-block structure, shared
split-scan code, integer routing), across the binning edge-case sweep;
a seeded GBM forest trained with the kernels equals the XLA forest
tree-for-tree; the batched-grid compile discipline (one boost-program
compile per shape bucket) holds with the kernel layer active.
Satellites ride along: the H2O3TPU_PALLAS knob + import guard with a
single logged fallback, the pallas_* telemetry counters (and their
flight-recorder capture), the bin-major tile view, and the bin_frame
cache."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

import h2o3_tpu
from h2o3_tpu import telemetry
from h2o3_tpu.frame.binning import bin_frame, rebin_for_scoring
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.tree import Tree, TreeScalars
from h2o3_tpu.ops import pallas as plx
from h2o3_tpu.ops.pallas import treekernel as tk
from h2o3_tpu.parallel.mesh import get_mesh, padded_rows, put_sharded, \
    row_sharding

OUT_NAMES = ("hist", "gain", "feat", "thresh", "na_left", "left_val",
             "right_val", "leftmask", "split", "new_nid")


def _scalars(min_rows=3.0, lam=1.0, msi=1e-5, depth_limit=30):
    return TreeScalars(jnp.float32(min_rows), jnp.float32(lam),
                       jnp.float32(msi), jnp.int32(depth_limit))


def _assert_level_parity(bins, w, g, h, cm, nb, is_cat, constraints,
                         lo, hi, sc, *, depth, L, B, mesh=None,
                         block_rows=256):
    """Run levels 0..depth via BOTH paths (each path routes with its own
    nids) and assert every output of every level is bit-identical. Each
    path's whole sweep is ONE jitted program — eager shard_map dispatch
    per level would dominate the suite's wall clock."""
    mesh = mesh or get_mesh()

    @jax.jit
    def sweep_xla(bins, w, g, h, cm, nb, lo, hi):
        outs, prev = [], None
        nid = jnp.zeros((bins.shape[0],), jnp.int32)
        for d in range(depth + 1):
            out = tk.xla_level(
                bins, nid, w, g, h, prev, cm, nb, is_cat, constraints,
                lo, hi, sc, d=d, n_nodes=2 ** d, n_bins=B,
                block_rows=block_rows, mesh=mesh)
            outs.append(out)
            prev, nid = out[0], out[-1]
        return outs

    @jax.jit
    def sweep_fused(bins, w, g, h, cm, nb, lo, hi):
        stats = jnp.stack([w, w * g, w * h], axis=1).astype(jnp.float32)
        outs, prev = [], None
        nid = jnp.zeros((bins.shape[0],), jnp.int32)
        for d in range(depth + 1):
            out = tk.fused_level(
                bins, nid, stats, prev, cm, nb, is_cat, constraints,
                lo, hi, sc, d=d, n_nodes=2 ** d, n_bins=B,
                block_rows=block_rows, mesh=mesh, interpret=True)
            outs.append(out)
            prev, nid = out[0], out[-1]
        return outs

    all_x = sweep_xla(bins, w, g, h, cm, nb, lo, hi)
    all_p = sweep_fused(bins, w, g, h, cm, nb, lo, hi)
    for d, (out_x, out_p) in enumerate(zip(all_x, all_p)):
        for name, a, b in zip(OUT_NAMES, out_x, out_p):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"level {d} output '{name}' diverged")


def _level_inputs(n=600, F=4, B=17, seed=0, na_frac=0.1):
    r = np.random.RandomState(seed)
    npad = padded_rows(n)
    bins_np = r.randint(0, B - 1, (npad, F))
    bins_np[r.rand(npad, F) < na_frac] = B - 1          # NA lane
    bins = put_sharded(jnp.asarray(bins_np.astype(np.int8)),
                       row_sharding())
    w = np.zeros(npad, np.float32)
    w[:n] = (r.rand(n) > 0.05).astype(np.float32)
    g = r.randn(npad).astype(np.float32)
    h = r.rand(npad).astype(np.float32) + 0.1
    nb = jnp.full((F,), B - 1, jnp.int32)
    return (bins, jnp.asarray(w), jnp.asarray(g), jnp.asarray(h), nb,
            r)


# ------------------------------------------------ kernel-level parity


def test_parity_numeric_multilevel():
    bins, w, g, h, nb, _ = _level_inputs()
    F = bins.shape[1]
    _assert_level_parity(
        bins, w, g, h, jnp.ones((F,), bool), nb, None, None,
        jnp.full((1,), -jnp.inf, jnp.float32),
        jnp.full((1,), jnp.inf, jnp.float32),
        _scalars(), depth=2, L=4, B=17)


def test_parity_categorical_subset_splits():
    bins, w, g, h, nb, r = _level_inputs(seed=3, B=9)
    F = bins.shape[1]
    is_cat = jnp.asarray(np.array([True, False, True, False]))
    _assert_level_parity(
        bins, w, g, h, jnp.ones((F,), bool), nb, is_cat, None,
        jnp.full((1,), -jnp.inf, jnp.float32),
        jnp.full((1,), jnp.inf, jnp.float32),
        _scalars(), depth=2, L=4, B=9)


def test_parity_constraints_and_depth_limit():
    bins, w, g, h, nb, _ = _level_inputs(seed=5)
    F = bins.shape[1]
    cons = jnp.asarray(np.array([1, -1, 0, 0], np.int8))
    # [1]-shaped bounds broadcast at every level (grow_tree only grows
    # them alongside its own constraint propagation)
    lo = jnp.full((1,), -0.5, jnp.float32)
    hi = jnp.full((1,), 0.5, jnp.float32)
    # depth_limit=2 masks the d=2 level's splits in BOTH paths
    _assert_level_parity(
        bins, w, g, h, jnp.ones((F,), bool), nb, None, cons, lo, hi,
        _scalars(depth_limit=2), depth=2, L=4, B=17)


def test_parity_per_node_col_mask():
    """DRF's [L, F] mtries mask flows through both split scans."""
    bins, w, g, h, nb, r = _level_inputs(seed=7)
    F = bins.shape[1]
    L = 4
    cm = jnp.asarray(r.rand(L, F) > 0.4) | (
        jnp.arange(F)[None, :] == 0)     # never fully featureless
    sc = _scalars()
    mesh = get_mesh()
    lo = jnp.full((1,), -jnp.inf, jnp.float32)
    hi = jnp.full((1,), jnp.inf, jnp.float32)
    cm1 = jnp.ones((F,), bool)

    @jax.jit
    def run(bins, w, g, h, cm):
        # two shared warmup levels, then a d=2 level through BOTH
        # paths with the per-node mask
        stats = jnp.stack([w, w * g, w * h], axis=1).astype(jnp.float32)
        nid = jnp.zeros((bins.shape[0],), jnp.int32)
        prev = None
        for d in range(2):
            out = tk.xla_level(bins, nid, w, g, h, prev, cm1, nb, None,
                               None, lo, hi, sc, d=d, n_nodes=2 ** d,
                               n_bins=17, block_rows=256, mesh=mesh)
            prev, nid = out[0], out[-1]
        kw = dict(d=2, n_nodes=L, n_bins=17, block_rows=256, mesh=mesh)
        out_x = tk.xla_level(bins, nid, w, g, h, prev, cm, nb, None,
                             None, lo, hi, sc, **kw)
        out_p = tk.fused_level(bins, nid, stats, prev, cm, nb, None,
                               None, lo, hi, sc, interpret=True, **kw)
        return out_x, out_p

    out_x, out_p = run(bins, w, g, h, cm)
    for name, a, b in zip(OUT_NAMES, out_x, out_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"output '{name}'")


def test_parity_single_device_fully_fused():
    """On a 1-shard mesh the whole level is ONE pallas_call (the
    tentpole kernel); same bitwise contract."""
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                 ("data", "model"))
    r = np.random.RandomState(11)
    n, F, B = 512, 3, 9
    bins = jnp.asarray(r.randint(0, B, (n, F)).astype(np.int8))
    w = jnp.asarray((r.rand(n) > 0.1).astype(np.float32))
    g = jnp.asarray(r.randn(n).astype(np.float32))
    h = jnp.asarray((r.rand(n) + 0.1).astype(np.float32))
    nb = jnp.full((F,), B - 1, jnp.int32)
    _assert_level_parity(
        bins, w, g, h, jnp.ones((F,), bool), nb, None, None,
        jnp.full((1,), -jnp.inf, jnp.float32),
        jnp.full((1,), jnp.inf, jnp.float32),
        _scalars(), depth=2, L=4, B=B, mesh=mesh1, block_rows=128)


# --------------------------------------- binning edge-case sweep parity


def _edge_case_bm(case):
    if case == "nbins1":
        fr = h2o3_tpu.Frame.from_numpy(
            {"a": np.random.RandomState(0).randn(64),
             "b": np.arange(64, dtype=float)})
        return bin_frame(fr, ["a", "b"], nbins=1)
    if case == "single_row":
        fr = h2o3_tpu.Frame.from_numpy({"a": np.array([1.5]),
                                        "b": np.array([-2.0])})
        return bin_frame(fr, ["a", "b"], nbins=8)
    if case == "all_na":
        fr = h2o3_tpu.Frame.from_numpy(
            {"a": np.full(50, np.nan),
             "b": np.random.RandomState(1).randn(50)})
        return bin_frame(fr, ["a", "b"], nbins=8)
    if case == "constant":
        fr = h2o3_tpu.Frame.from_numpy(
            {"a": np.full(50, 3.25),
             "b": np.random.RandomState(2).randn(50)})
        return bin_frame(fr, ["a", "b"], nbins=8)
    if case == "unseen_levels":
        tr = h2o3_tpu.Frame.from_numpy(
            {"c": np.random.RandomState(3).choice(["a", "b"], 60),
             "x": np.random.RandomState(4).randn(60)},
            categorical=["c"])
        bm = bin_frame(tr, ["c", "x"], nbins=8)
        sc_fr = h2o3_tpu.Frame.from_numpy(
            {"c": np.random.RandomState(5).choice(["a", "b", "c", "d"],
                                                  40),
             "x": np.random.RandomState(6).randn(40)},
            categorical=["c"])
        return rebin_for_scoring(bm, sc_fr)    # unseen levels → NA bin
    raise AssertionError(case)


@pytest.mark.parametrize("case", ["nbins1", "single_row", "all_na",
                                  "constant", "unseen_levels"])
def test_binning_edge_case_parity(case):
    bm = _edge_case_bm(case)
    r = np.random.RandomState(42)
    npad = bm.bins.shape[0]
    w = np.zeros(npad, np.float32)
    w[: bm.nrows] = 1.0
    g = jnp.asarray(r.randn(npad).astype(np.float32))
    h = jnp.asarray(np.ones(npad, np.float32))
    is_cat = (jnp.asarray(np.asarray(bm.is_cat, bool))
              if bm.is_cat.any() else None)
    F = bm.nfeatures
    _assert_level_parity(
        bm.bins, jnp.asarray(w), g, h, jnp.ones((F,), bool), bm.nbins,
        is_cat, None, jnp.full((1,), -jnp.inf, jnp.float32),
        jnp.full((1,), jnp.inf, jnp.float32),
        _scalars(min_rows=1.0), depth=1, L=2, B=bm.nbins_total)


# ------------------------------------------------- seeded forest parity


def _mixed_frame(n=700, seed=0):
    r = np.random.RandomState(seed)
    X = r.randn(n, 4)
    X[r.rand(n) < 0.05, 0] = np.nan
    cat = r.choice(["a", "b", "c", "d"], n)
    y = (X[:, 1] + (cat == "a") * 1.5 + 0.3 * r.randn(n) > 0).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["c"] = cat
    cols["y"] = np.array(["N", "Y"], object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["c", "y"])


def _forests_equal(m1, m2):
    for f in Tree._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(m1.forest, f)),
            np.asarray(getattr(m2.forest, f)),
            err_msg=f"forest field '{f}' diverged")


def test_seeded_gbm_forest_parity_interpret(monkeypatch):
    """Acceptance: the fused-kernel GBM forest equals the XLA forest
    tree-for-tree (NAs + categorical subset splits included)."""
    fr = _mixed_frame()
    params = dict(ntrees=4, max_depth=4, seed=11)
    monkeypatch.setenv("H2O3TPU_PALLAS", "off")
    m_x = GBMEstimator(**params).train(fr, y="y")
    launches0 = telemetry.REGISTRY.total("pallas_kernel_launches_total")
    monkeypatch.setenv("H2O3TPU_PALLAS", "interpret")
    m_p = GBMEstimator(**params).train(fr, y="y")
    _forests_equal(m_x, m_p)
    assert m_x.training_metrics["AUC"] == m_p.training_metrics["AUC"]
    # satellite: launch counter moved while the kernels were active
    assert telemetry.REGISTRY.total(
        "pallas_kernel_launches_total") > launches0


def test_seeded_drf_forest_parity_interpret(monkeypatch):
    """The mtries (per-node column mask) path through the shared
    grow_tree, kernels vs XLA."""
    from h2o3_tpu.models.drf import DRFEstimator
    fr = _mixed_frame(n=400, seed=2)
    params = dict(ntrees=3, max_depth=4, seed=5)
    monkeypatch.setenv("H2O3TPU_PALLAS", "off")
    m_x = DRFEstimator(**params).train(fr, y="y")
    monkeypatch.setenv("H2O3TPU_PALLAS", "interpret")
    m_p = DRFEstimator(**params).train(fr, y="y")
    _forests_equal(m_x, m_p)


# --------------------------------------------- knob + import guard


def test_decide_table():
    assert plx.decide("auto", "tpu", 1, True) == ("native", None)
    assert plx.decide("auto", "cpu", 8, True) == ("off",
                                                  "non_tpu_backend")
    assert plx.decide("off", "tpu", 1, True) == ("off", "knob_off")
    assert plx.decide("interpret", "cpu", 8, True) == ("interpret", None)
    assert plx.decide("on", "cpu", 1, True) == ("native", None)
    # unavailable pallas wins over every knob except explicit off
    assert plx.decide("auto", "tpu", 1, False) == \
        ("off", "pallas_unavailable")
    assert plx.decide("interpret", "cpu", 1, False) == \
        ("off", "pallas_unavailable")
    assert plx.decide("bogus", "tpu", 1, True) == ("off", "unknown_knob")


def test_knob_off_single_logged_fallback(monkeypatch):
    """off → XLA with ONE logged fallback (no per-tree/per-fit spam);
    every decision still counts in pallas_fallbacks_total{reason}."""
    from h2o3_tpu.utils.log import log_buffer
    monkeypatch.setenv("H2O3TPU_PALLAS", "off")
    plx._LOGGED_REASONS.clear()
    c0 = telemetry.REGISTRY.value("pallas_fallbacks_total",
                                  reason="knob_off")
    n_logged0 = sum("falling back to XLA" in ln for ln in log_buffer())
    assert plx.resolve_tree_mode() == "off"
    assert plx.resolve_tree_mode() == "off"
    assert telemetry.REGISTRY.value("pallas_fallbacks_total",
                                    reason="knob_off") == c0 + 2
    n_logged = sum("falling back to XLA" in ln for ln in log_buffer())
    assert n_logged - n_logged0 == 1, "fallback must log exactly once"


def test_knob_off_zero_behavior_change(monkeypatch):
    """off and auto (non-TPU backend) are the SAME XLA program — forests
    bit-identical."""
    fr = _mixed_frame(n=300, seed=9)
    params = dict(ntrees=3, max_depth=3, seed=3)
    monkeypatch.setenv("H2O3TPU_PALLAS", "off")
    m_off = GBMEstimator(**params).train(fr, y="y")
    monkeypatch.setenv("H2O3TPU_PALLAS", "auto")
    m_auto = GBMEstimator(**params).train(fr, y="y")
    _forests_equal(m_off, m_auto)


def test_import_guard_unavailable(monkeypatch):
    """A missing jax.experimental.pallas resolves to the XLA path with a
    counted fallback — never an ImportError."""
    monkeypatch.setenv("H2O3TPU_PALLAS", "interpret")
    monkeypatch.setattr(plx, "available", lambda: False)
    c0 = telemetry.REGISTRY.value("pallas_fallbacks_total",
                                  reason="pallas_unavailable")
    assert plx.resolve_tree_mode() == "off"
    assert telemetry.REGISTRY.value(
        "pallas_fallbacks_total",
        reason="pallas_unavailable") == c0 + 1


def test_flight_recorder_captures_pallas_counters(monkeypatch):
    """Satellite: the pallas_* counters flow into the job capsule's
    start→end metric deltas like every other counter."""
    from h2o3_tpu.core.job import Job
    from h2o3_tpu.telemetry import flight_recorder
    fr = _mixed_frame(n=200, seed=13)
    monkeypatch.setenv("H2O3TPU_PALLAS", "interpret")

    def work(job):
        GBMEstimator(ntrees=2, max_depth=3, seed=1).train(fr, y="y")
        return "ok"

    j = Job("pallas capsule probe").start(work)
    cap = flight_recorder.get_capsule(j.key).to_dict()
    assert any("pallas_kernel_launches_total" in k
               for k in cap["metric_deltas"]), cap["metric_deltas"]


# ------------------------------------- batched-grid compile discipline


def test_batched_grid_one_compile_with_kernels_active(monkeypatch):
    """ISSUE 6 acceptance: the vmapped shape-bucket trainer composes
    with the kernel layer — one boost-program compile for the bucket,
    results matching the sequential walk (both interpret)."""
    from h2o3_tpu.ml.grid import GridSearch
    monkeypatch.setenv("H2O3TPU_PALLAS", "interpret")
    r = np.random.RandomState(1)
    n = 300
    a, b = r.randn(n), r.randn(n)
    yv = (a + 0.5 * b + 0.3 * r.randn(n) > 0).astype(int)
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "y": np.array(["N", "Y"], object)[yv]},
        categorical=["y"])
    hyper = {"learn_rate": [0.05, 0.1], "min_rows": [1.0, 10.0]}
    fixed = dict(ntrees=4, max_depth=3, seed=7)

    def _misses():
        tot = 0.0
        for (nm, lbl), m in list(telemetry.REGISTRY._metrics.items()):
            if nm.endswith("jit_cache_miss_total") and \
                    dict(lbl).get("fn") == "gbm.boost_scan_batched":
                tot += m.value
        return tot

    m0 = _misses()
    g_bat = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    assert len(g_bat.models) == 4
    assert _misses() - m0 == 1, \
        "one compile per shape bucket, kernels active"
    monkeypatch.setenv("H2O3TPU_BATCH_MODELS", "off")
    g_seq = GridSearch(GBMEstimator, hyper, **fixed).train(fr, y="y")
    by = {tuple(sorted(m.output["grid_params"].items())): m
          for m in g_seq.models}
    for m in g_bat.models:
        m2 = by[tuple(sorted(m.output["grid_params"].items()))]
        d1 = m.training_metrics.to_dict()
        d2 = m2.training_metrics.to_dict()
        for k in ("AUC", "logloss"):
            assert abs(d1[k] - d2[k]) < 1e-5


# --------------------------------------------------- layout + caches


def test_tile_view_geometry_and_cache():
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": np.random.RandomState(0).randn(100),
         "b": np.random.RandomState(1).randn(100)})
    bm = bin_frame(fr, ["a", "b"], nbins=8)
    tv = bm.tile_view(64)
    assert tv.rows == 64
    assert tv.bins.shape[0] == tv.ntiles * 64
    assert tv.bins.shape[0] >= bm.bins.shape[0]
    assert tv.tile_shape == (64, 2)
    assert tv.nbins_total == bm.nbins_total     # NA lane folded in
    assert bm.tile_view(64) is tv               # cached per rows
    auto = bm.tile_view()                       # VMEM-sized default
    assert auto.rows % 8 == 0 or auto.rows == bm.bins.shape[0]
    # pickling drops the cache, not the matrix
    import pickle
    bm2 = pickle.loads(pickle.dumps(bm))
    assert bm2._tile_cache == {}


def test_bin_frame_cached_per_config_and_invalidated():
    r = np.random.RandomState(3)
    fr = h2o3_tpu.Frame.from_numpy({"a": r.randn(120), "b": r.randn(120)})
    bm1 = bin_frame(fr, ["a", "b"], nbins=8)
    assert bin_frame(fr, ["a", "b"], nbins=8) is bm1       # cache hit
    assert bin_frame(fr, ["a", "b"], nbins=16) is not bm1  # config keyed
    assert bin_frame(fr, ["a"], nbins=8) is not bm1
    # weights key by CONTENT (each fit rebuilds the host mirror array)
    wts = np.ones(120)
    bmw = bin_frame(fr, ["a", "b"], nbins=8, weights=wts)
    assert bmw is not bm1
    assert bin_frame(fr, ["a", "b"], nbins=8,
                     weights=np.ones(120)) is bmw
    assert bin_frame(fr, ["a", "b"], nbins=8,
                     weights=np.full(120, 2.0)) is not bmw
    # column mutation invalidates, like the device_matrix cache
    from h2o3_tpu.frame.column import column_from_numpy
    from h2o3_tpu.parallel import mesh as mesh_mod
    fr.add_column(column_from_numpy("z", np.zeros(120), fr.nrows_padded,
                                    mesh_mod.row_sharding()))
    assert bin_frame(fr, ["a", "b"], nbins=8) is not bm1
    # scoring rebins bypass the cache (train-matrix keyed, not frame)
    fr2 = h2o3_tpu.Frame.from_numpy({"a": r.randn(50), "b": r.randn(50)})
    bm_s1 = rebin_for_scoring(bm1, fr2)
    bm_s2 = rebin_for_scoring(bm1, fr2)
    assert bm_s1 is not bm_s2
