"""Worker for the SIGKILL-mid-GBM fit-checkpoint test
(tests/test_fit_checkpoint.py; pattern of tests/ft_worker.py).

Modes (argv[1]):
  fit     — GBM fit with in-fit checkpointing into argv[2]; the parent
            SIGKILLs this process while it holds inside the chunk
            boundary right after its first snapshot
            (H2O3TPU_FIT_CHECKPOINT_HOLD_S widens the kill window)
  resume  — the same fit again with the same checkpoint dir: it must
            resume from the snapshot the killed run left, THEN train
            the uninterrupted reference fit in the same (1-device)
            session; both results dump to argv[3] with ref_/res_
            prefixes plus the resume counters

Deterministic data: build_data() must stay identical across modes (the
resumed "cluster" trains on the same frame a restarted driver would
re-import).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# the fit and resume legs compile the same kernel shapes — share the
# executables through jax's persistent cache (identical binaries)
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

mode = sys.argv[1]
ckpt_dir = sys.argv[2]
out_path = sys.argv[3]

os.environ["H2O3TPU_FIT_CHECKPOINT_DIR"] = ckpt_dir
os.environ["H2O3TPU_FIT_CHECKPOINT_EVERY"] = "25"
if mode == "fit":
    os.environ["H2O3TPU_FIT_CHECKPOINT_HOLD_S"] = "600"

import numpy as np                            # noqa: E402

import h2o3_tpu                               # noqa: E402

h2o3_tpu.init(backend="cpu")


def build_data():
    r = np.random.RandomState(23)
    n = 4000
    X = r.randn(n, 6)
    logits = X[:, 0] * 1.2 - X[:, 1] + 0.4 * X[:, 2]
    y = (r.rand(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return h2o3_tpu.Frame.from_numpy(cols, categorical=["y"])


from h2o3_tpu import telemetry                # noqa: E402
from h2o3_tpu.models.gbm import GBMEstimator  # noqa: E402
from h2o3_tpu.models.tree import Tree         # noqa: E402

fr = build_data()


def train_once():
    # scored path (early stopping on, never binding at tol=0):
    # exercises scoring history + stopper state through the snapshot
    return GBMEstimator(ntrees=50, max_depth=3, seed=5,
                        stopping_rounds=2, stopping_tolerance=0.0,
                        score_tree_interval=5).train(fr, y="y")


def dump(prefix, model, out):
    for f in Tree._fields:
        out[prefix + f] = np.asarray(getattr(model.forest, f))
    out[prefix + "f0"] = np.asarray(model.f0)
    hist = model.output["scoring_history"]
    out[prefix + "hist_ntrees"] = np.asarray([h["ntrees"] for h in hist])
    out[prefix + "hist_deviance"] = np.asarray(
        [h["deviance"] for h in hist])
    out[prefix + "logloss"] = np.float64(
        model.training_metrics["logloss"])
    out[prefix + "auc"] = np.float64(model.training_metrics["AUC"])


if mode == "fit":
    train_once()                               # parent kills mid-fit
    print("FITCKPT-WORKER-DONE fit", flush=True)
    sys.exit(0)

# mode == "resume": the resumed fit FIRST (the killed run's snapshot is
# live), then — its completion cleared the snapshot — the uninterrupted
# reference on the same 1-device mesh
out = {}
resumed = train_once()
out["fit_resumes_total"] = np.float64(
    telemetry.REGISTRY.total("fit_resumes_total"))
out["fit_checkpoints_written_total"] = np.float64(
    telemetry.REGISTRY.total("fit_checkpoints_written_total"))
out["snapshot_left"] = np.float64(sum(
    f.endswith(".fitsnap") for f in os.listdir(ckpt_dir)))
dump("res_", resumed, out)
reference = train_once()
out["fit_resumes_total_after_ref"] = np.float64(
    telemetry.REGISTRY.total("fit_resumes_total"))
dump("ref_", reference, out)
np.savez(out_path, **out)
print("FITCKPT-WORKER-DONE resume", flush=True)
