"""Compilable-Java POJO round trips (VERDICT r4 demand #7).

The contract is hex/Model.java toJava(): a .java class extending
hex.genmodel.GenModel with score0(double[] data, double[] preds)
(hex/genmodel/GenModel.java:363). No JVM ships in this image, so each
emitted source is (a) structurally checked for javac shape, (b)
re-read by an INDEPENDENT parser (JavaPojoScorer extracts the Java
constants from the source text) whose own numpy walk must reproduce
the in-cluster predictions — the same two-sided validation
tests/test_reference_mojo.py applies to reference-MOJO bytes.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel.pojo_java import (JavaPojoScorer, check_java_shape,
                                         java_pojo_source)

N = 400


def _frame(seed=0, multiclass=False):
    r = np.random.RandomState(seed)
    g = r.choice(["lo", "mid", "hi"], N)
    a = r.randn(N)
    b = r.randn(N) * 2 + 1
    a[::17] = np.nan
    eta = 1.2 * a - 0.7 * b + (g == "hi") * 1.5
    if multiclass:
        y = np.array(["u", "v", "w"], object)[
            np.clip((eta + r.randn(N)).astype(int) % 3, 0, 2)]
    else:
        y = np.where(eta + r.randn(N) > 0, "yes", "no")
    return Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g", "y"])


def _data_rows(fr, names):
    """double[] rows the way GenModel.score0 receives them: categorical
    cells as level-index doubles, NaN for NA."""
    cols = []
    for n in names:
        c = fr.col(n)
        if c.is_categorical:
            codes = np.asarray(c.to_numpy_codes(), float) \
                if hasattr(c, "to_numpy_codes") else None
            if codes is None:
                from h2o3_tpu.rapids import _cat_codes
                codes = _cat_codes(fr, n).astype(float)
                codes[codes < 0] = np.nan
            cols.append(codes)
        else:
            cols.append(np.asarray(c.to_numpy(), float))
    return np.stack(cols, axis=1)


def _check(src, cls=None):
    probs = check_java_shape(src, cls)
    assert not probs, probs


def test_gbm_binomial_java_pojo_round_trip():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame(1)
    m = GBMEstimator(ntrees=12, max_depth=4, seed=3,
                     distribution="bernoulli").train(fr, y="y")
    src = java_pojo_source(m, class_name="gbm_pojo")
    _check(src, "gbm_pojo")
    sc = JavaPojoScorer(src)
    data = _data_rows(fr, m.output['names'])
    f0 = float(np.asarray(m.f0))
    p1_java = np.array([
        1.0 / (1.0 + np.exp(-(f0 + sum(sc.margins(row)))))
        for row in data[:80]])
    pred = m.predict(fr).col("p1").to_numpy()[:80]
    assert np.allclose(p1_java, pred, atol=1e-5), \
        np.abs(p1_java - pred).max()


def test_gbm_multinomial_java_pojo_round_trip():
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame(2, multiclass=True)
    m = GBMEstimator(ntrees=9, max_depth=3, seed=5,
                     distribution="multinomial").train(fr, y="y")
    src = java_pojo_source(m, class_name="gbm_multi")
    _check(src, "gbm_multi")
    sc = JavaPojoScorer(src)
    data = _data_rows(fr, m.output['names'])
    K = 3
    f0 = np.asarray(m.f0, float)
    pf = m.predict(fr)
    got_cols = [pf.col(n).to_numpy()[:60] for n in pf.names[1:]]
    for i, row in enumerate(data[:60]):
        marg = np.asarray(sc.margins(row))
        z = f0 + np.array([marg[k::K].sum() for k in range(K)])
        p = np.exp(z - z.max())
        p = p / p.sum()
        for k in range(K):
            assert abs(p[k] - got_cols[k][i]) < 1e-5


def test_drf_regression_java_pojo_round_trip():
    from h2o3_tpu.models.drf import DRFEstimator
    r = np.random.RandomState(4)
    a, b = r.randn(N), r.randn(N)
    fr = Frame.from_numpy({"a": a, "b": b,
                           "y": 2 * a - b + r.randn(N) * 0.1})
    m = DRFEstimator(ntrees=10, max_depth=5, seed=7).train(fr, y="y")
    src = java_pojo_source(m, class_name="drf_pojo")
    _check(src, "drf_pojo")
    sc = JavaPojoScorer(src)
    data = _data_rows(fr, m.output['names'])
    pred = m.predict(fr).col("predict").to_numpy()[:80]
    got = np.array([np.mean(sc.margins(row)) for row in data[:80]])
    assert np.allclose(got, pred, atol=1e-5)


def test_glm_binomial_java_pojo_round_trip():
    from h2o3_tpu.models.glm import GLMEstimator
    fr = _frame(6)
    m = GLMEstimator(family="binomial", lambda_=1e-4).train(fr, y="y")
    src = java_pojo_source(m, class_name="glm_pojo")
    _check(src, "glm_pojo")
    sc = JavaPojoScorer(src)
    data = _data_rows(fr, m.output['names'])
    p1 = np.array([1.0 / (1.0 + np.exp(-sc.glm_eta(row)))
                   for row in data[:100]])
    pred = m.predict(fr).col("p1").to_numpy()[:100]
    assert np.allclose(p1, pred, atol=1e-4), np.abs(p1 - pred).max()


def test_java_pojo_rejects_unsupported_algo():
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(8)
    fr = Frame.from_numpy({"a": r.randn(N), "b": r.randn(N)})
    m = KMeansEstimator(k=3, seed=1).train(fr)
    with pytest.raises(ValueError, match="gbm/drf/glm"):
        java_pojo_source(m)


def test_rest_models_java_serves_java_source():
    """GET /3/Models.java/{m} returns javac-shaped source for tree
    algos (the reference endpoint contract)."""
    from h2o3_tpu.api.server import _model_pojo
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.models.gbm import GBMEstimator
    fr = _frame(9)
    m = GBMEstimator(ntrees=5, max_depth=3, seed=1,
                     distribution="bernoulli").train(fr, y="y")
    DKV.put(m.key, m)
    out = _model_pojo({}, None, mid=m.key)
    assert out["__ctype__"].startswith("text/x-java")
    src = out["__bytes__"].decode()
    assert not check_java_shape(src), check_java_shape(src)
