"""KMeans tests — pyunit_kmeans* role (h2o-py/tests/testdir_algos/kmeans/)."""

import numpy as np

import h2o3_tpu
from h2o3_tpu.models.kmeans import KMeansEstimator


def _blobs(n_per=500, k=3, f=4, seed=0, spread=0.3):
    r = np.random.RandomState(seed)
    centers = r.randn(k, f) * 4
    X = np.vstack([centers[i] + spread * r.randn(n_per, f) for i in range(k)])
    y = np.repeat(np.arange(k), n_per)
    return X, y, centers


def test_kmeans_recovers_blobs():
    X, y, _ = _blobs()
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = KMeansEstimator(k=3, seed=42, max_iterations=20).train(f)
    tm = m.training_metrics
    assert tm["betweenss"] / tm["totss"] > 0.9, tm.to_dict()
    pred = m.predict(f).to_pandas()["predict"].to_numpy()
    # cluster labels must be a permutation-consistent refinement of truth
    for cls in range(3):
        vals, cnt = np.unique(pred[y == cls], return_counts=True)
        assert cnt.max() / cnt.sum() > 0.95


def test_kmeans_inits_agree():
    X, y, _ = _blobs(seed=3)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    results = {}
    for init in ("Furthest", "PlusPlus", "Random"):
        m = KMeansEstimator(k=3, init=init, seed=7, max_iterations=25).train(f)
        results[init] = m.training_metrics["tot_withinss"]
    vals = list(results.values())
    assert max(vals) < 2.0 * min(vals) + 1e-9, results


def test_kmeans_categorical_onehot():
    r = np.random.RandomState(5)
    n = 900
    g = r.randint(0, 3, n)
    f = h2o3_tpu.Frame.from_numpy(
        {"num": r.randn(n) + g * 5,
         "cat": np.array(["a", "b", "c"], dtype=object)[g]},
        categorical=["cat"])
    m = KMeansEstimator(k=3, seed=1, max_iterations=15).train(f)
    assert m.output["k"] == 3
    assert len(m.output["centers"]) == 3
    # coef space: 1 numeric + 3 one-hot levels
    assert len(m.output["coef_names"]) == 4


def test_kmeans_estimate_k():
    X, y, _ = _blobs(k=3, seed=9)
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = KMeansEstimator(k=8, estimate_k=True, seed=11,
                        max_iterations=20).train(f)
    assert 2 <= m.output["k"] <= 4, m.output["k"]


def test_kmeans_constrained_minimum_sizes():
    """cluster_size_constraints (hex/kmeans/KMeans.java:26 constrained
    variant): every cluster must end with at least its minimum rows."""
    import numpy as np
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(4)
    # lopsided blobs: unconstrained k-means would starve the far blob
    X = np.concatenate([r.randn(380, 2), r.randn(20, 2) + 8.0])
    fr = h2o3_tpu.Frame.from_numpy({"a": X[:, 0], "b": X[:, 1]})
    m = KMeansEstimator(k=3, cluster_size_constraints=[100, 100, 100],
                        seed=1, max_iterations=10).train(fr)
    sizes = m.output.get("sizes") or [
        int(v) for v in np.asarray(m.training_metrics["centroid_stats"]["size"])]
    assert all(s >= 100 for s in sizes), sizes
    import pytest
    with pytest.raises(ValueError):
        KMeansEstimator(k=2, estimate_k=True,
                        cluster_size_constraints=[5, 5]).train(fr)
