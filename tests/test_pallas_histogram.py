"""Pallas histogram kernel vs the XLA one-hot-matmul reference.

Runs the kernel in interpreter mode on CPU (the TPU path compiles the
same program natively)."""

import numpy as np
import jax.numpy as jnp
import pytest

from h2o3_tpu.ops.histogram import _local_histogram
from h2o3_tpu.ops.pallas_histogram import pallas_local_histogram


@pytest.mark.parametrize("L,B,F,N", [(1, 17, 4, 300), (8, 33, 7, 1000),
                                     (32, 65, 12, 2048)])
def test_pallas_matches_xla_histogram(L, B, F, N):
    r = np.random.RandomState(0)
    bins = jnp.asarray(r.randint(0, B, (N, F)).astype(np.int32))
    nid = jnp.asarray(r.randint(0, L, N).astype(np.int32))
    w = r.rand(N).astype(np.float32)
    w[r.rand(N) < 0.1] = 0.0   # padding-row zeros
    g = r.randn(N).astype(np.float32)
    h = r.rand(N).astype(np.float32)
    stats = jnp.stack([jnp.asarray(w), jnp.asarray(w * g),
                       jnp.asarray(w * h)], axis=1)
    ref = _local_histogram(bins, nid, stats, L, B, block_rows=256)
    out = pallas_local_histogram(bins, nid, stats, L, B, block_rows=256,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)
