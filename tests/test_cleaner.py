"""Cleaner — LRU frame spill to ice + transparent DKV restore.

The water/Cleaner.java role: cold Values swap to disk under memory
pressure; DKV.get swaps them back in.
"""

import numpy as np

import h2o3_tpu
from h2o3_tpu.core.cleaner import Cleaner, SpilledFrame
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.frame.frame import Frame


def _frame(key, n=500, seed=0):
    r = np.random.RandomState(seed)
    return Frame.from_numpy(
        {"a": r.randn(n), "b": r.choice(["x", "y", None], n)},
        categorical=["b"], key=key)


def test_spill_and_transparent_restore(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_ICE_DIR", str(tmp_path))
    import importlib
    from h2o3_tpu.io import persist
    importlib.reload(persist)   # pick up the ice dir override
    cl = Cleaner()
    fr = _frame("spillme", seed=3)
    before = fr.col("a").to_numpy()
    bcodes = np.asarray(fr.col("b").data)[: fr.nrows].copy()
    cl.spill("spillme")
    assert isinstance(DKV.get_raw("spillme"), SpilledFrame)
    restored = DKV.get("spillme")          # transparent swap-in
    assert isinstance(restored, Frame)
    np.testing.assert_allclose(restored.col("a").to_numpy(), before)
    np.testing.assert_array_equal(
        np.asarray(restored.col("b").data)[: restored.nrows], bcodes)
    assert restored.col("b").domain == ["x", "y"]
    assert cl.spilled_count == 1


def test_lru_picks_coldest(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_ICE_DIR", str(tmp_path))
    import importlib
    from h2o3_tpu.io import persist
    importlib.reload(persist)
    cl = Cleaner()
    DKV.clear()                            # isolate LRU ordering
    _frame("cold_fr", seed=1)
    _frame("warm_fr", seed=2)
    DKV.get("warm_fr")                     # touch → newest access time
    spilled = cl.spill_coldest(1)
    assert spilled == ["cold_fr"]
    assert isinstance(DKV.get_raw("cold_fr"), SpilledFrame)
    assert isinstance(DKV.get_raw("warm_fr"), Frame)


def test_pressure_status():
    cl = Cleaner()
    st = cl.status()
    assert 0.0 <= st["pressure"] <= 1.5
    assert st["threshold"] == 0.85


def test_jit_cache_policy_without_memory_stats(monkeypatch):
    """VERDICT r3 weak #6/#10 guard: on a backend that reports NO memory
    stats (the axon plugin returns None), a session of repeated frame
    create/remove_all cycles must still periodically drop the jit
    executable caches — and the session must complete without growth in
    the DKV."""
    from h2o3_tpu.api import server as srv

    cleared = {"n": 0}
    import jax

    real_clear = jax.clear_caches

    def fake_clear():
        cleared["n"] += 1
        real_clear()

    class _Dev:
        def memory_stats(self):
            return None                      # the axon behavior

    monkeypatch.setattr(jax, "clear_caches", fake_clear)
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_Dev()])
    srv._RMALL_COUNT = 0
    for i in range(100):
        _frame(f"cycle_{i}", n=64)
        srv._dkv_del_all({}, None)
        assert "cycle_%d" % i not in DKV
    # every-10th cadence → 10 clears over 100 cycles
    assert cleared["n"] == 10, cleared
    assert len([k for k in DKV.keys() if k.startswith("cycle_")]) == 0


def test_resource_exhausted_job_retry_frees_caches(monkeypatch):
    """A job hitting RESOURCE_EXHAUSTED retries once AFTER purging the
    device caches (core/job.py free_device_memory path)."""
    from h2o3_tpu.core import job as jobmod

    freed = {"n": 0}
    monkeypatch.setattr(jobmod, "free_device_memory",
                        lambda reason="": freed.__setitem__("n",
                                                           freed["n"] + 1))
    calls = {"n": 0}

    def work(j):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted)")
        return "ok"

    j = jobmod.Job("re-test").start(work)
    assert j.result == "ok"
    assert calls["n"] == 2
    assert freed["n"] == 1
