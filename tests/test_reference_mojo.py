"""Reference-format MOJO round trip.

download_mojo(format="reference") must emit the ACTUAL reference zip
layout (model.ini / domains / SharedTreeMojoModel v1.40 tree blobs);
score_reference_mojo decodes it with a byte-faithful port of the
reference scoreTree reader (hex/genmodel/algos/tree/
SharedTreeMojoModel.java:129) — predictions must match in-cluster
scoring, proving the blobs honor the reference contract.
"""

import zipfile

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel.refmojo import score_reference_mojo
from h2o3_tpu.models.drf import DRFEstimator
from h2o3_tpu.models.gbm import GBMEstimator


def _data(n=2500, seed=4, levels=40):
    r = np.random.RandomState(seed)
    code = r.randint(0, levels, n)
    x1 = r.randn(n)
    x2 = r.randn(n)
    x2[::13] = np.nan
    y = (np.sin(code * 1.1) + x1 * 0.7 + np.nan_to_num(x2) * 0.2
         + 0.1 * r.randn(n))
    dom = [f"cat_{i}" for i in range(levels)]
    return code, x1, x2, y, dom


def _frame(code, x1, x2, y, binom=False):
    arr = {"c": code.astype(float), "x1": x1, "x2": x2}
    arr["y"] = (y > 0).astype(float) if binom else y
    return Frame.from_numpy(arr, categorical=["c"] + (["y"] if binom
                                                     else []))


def _raw_rows(fr, code, x1, x2):
    lv = fr.col("c").domain
    return {"c": np.array([lv[int(i)] for i in code], object),
            "x1": x1, "x2": x2}


def test_layout(tmp_path):
    code, x1, x2, y, dom = _data()
    fr = _frame(code, x1, x2, y)
    m = GBMEstimator(ntrees=4, max_depth=4).train(fr, x=["c", "x1", "x2"],
                                                  y="y")
    p = str(tmp_path / "ref.zip")
    m.download_mojo(p, format="reference")
    with zipfile.ZipFile(p) as z:
        names = set(z.namelist())
        assert "model.ini" in names
        assert "trees/t00_000.bin" in names
        assert any(n.startswith("domains/") for n in names)
        ini = z.read("model.ini").decode()
        assert "algo = gbm" in ini and "mojo_version = 1.40" in ini


@pytest.mark.parametrize("binom", [False, True])
def test_gbm_roundtrip(tmp_path, binom):
    code, x1, x2, y, dom = _data()
    fr = _frame(code, x1, x2, y, binom=binom)
    m = GBMEstimator(ntrees=6, max_depth=4).train(fr, x=["c", "x1", "x2"],
                                                  y="y")
    p = str(tmp_path / "ref.zip")
    m.download_mojo(p, format="reference")
    margins, info = score_reference_mojo(p, _raw_rows(fr, code, x1, x2))
    total = margins[:, 0] + float(info["init_f"])
    if binom:
        pref = 1.0 / (1.0 + np.exp(-total))
        ours = m.predict(fr).col("p1").to_numpy()
    else:
        pref = total
        ours = m.predict(fr).col("predict").to_numpy()
    assert np.abs(pref - ours).max() < 1e-4, np.abs(pref - ours).max()


def test_gbm_multinomial_roundtrip(tmp_path):
    r = np.random.RandomState(7)
    n = 1500
    code = r.randint(0, 25, n)
    x1 = r.randn(n)
    cls = (np.sin(code * 0.9) + x1 > 0.5).astype(int) + \
        (np.cos(code) > 0.8).astype(int)
    fr = Frame.from_numpy({"c": code.astype(float), "x1": x1,
                           "y": cls.astype(float)},
                          categorical=["c", "y"])
    m = GBMEstimator(ntrees=4, max_depth=3).train(fr, x=["c", "x1"], y="y")
    p = str(tmp_path / "ref.zip")
    m.download_mojo(p, format="reference")
    lv = fr.col("c").domain
    margins, info = score_reference_mojo(
        p, {"c": np.array([lv[int(i)] for i in code], object), "x1": x1})
    f0 = np.asarray(m.f0)
    e = np.exp(margins + f0[None, :])
    pref = e / e.sum(axis=1, keepdims=True)
    ours = np.stack([m.predict(fr).col(f"p{k}").to_numpy()
                     for k in range(margins.shape[1])], axis=1)
    assert np.abs(pref - ours).max() < 1e-4


@pytest.mark.parametrize("family", ["gaussian", "binomial", "poisson"])
def test_glm_roundtrip(tmp_path, family):
    from h2o3_tpu.models.glm import GLMEstimator
    code, x1, x2, y, dom = _data(n=1200, seed=11, levels=12)
    if family == "binomial":
        yv = (y > 0).astype(float)
    elif family == "poisson":
        yv = np.floor(np.exp(np.clip(y, -2, 2))).astype(float)
    else:
        yv = y
    fr = Frame.from_numpy({"c": code.astype(float), "x1": x1, "x2": x2,
                           "y": yv},
                          categorical=["c"] + (["y"] if family == "binomial"
                                               else []))
    m = GLMEstimator(family=family, lambda_=0.0).train(
        fr, x=["c", "x1", "x2"], y="y")
    p = str(tmp_path / "refglm.zip")
    m.download_mojo(p, format="reference")
    from h2o3_tpu.genmodel.refmojo import score_reference_glm_mojo
    mu, info = score_reference_glm_mojo(p, _raw_rows(fr, code, x1, x2))
    assert info["algo"] == "glm" and info["mojo_version"] == "1.00"
    ours = (m.predict(fr).col("p1" if family == "binomial" else
                              "predict").to_numpy())
    assert np.abs(mu - ours).max() < 2e-4, np.abs(mu - ours).max()


def test_glm_roundtrip_na_rows(tmp_path):
    """NA categorical + NA numeric rows must score identically — the
    cat_modes=cardinality sentinel reproduces the all-zero NA block."""
    from h2o3_tpu.models.glm import GLMEstimator
    code, x1, x2, y, dom = _data(n=800, seed=3, levels=8)
    fr = Frame.from_numpy({"c": code.astype(float), "x1": x1, "x2": x2,
                           "y": y}, categorical=["c"])
    m = GLMEstimator(family="gaussian", lambda_=0.0).train(
        fr, x=["c", "x1", "x2"], y="y")
    p = str(tmp_path / "refglm.zip")
    m.download_mojo(p, format="reference")
    from h2o3_tpu.genmodel.refmojo import score_reference_glm_mojo
    rows = _raw_rows(fr, code, x1, x2)
    rows["c"] = rows["c"].copy()
    rows["c"][::7] = None                       # NA categorical
    codes_na = code.astype(float).copy()
    codes_na[::7] = np.nan
    fr2 = Frame.from_numpy({"c": codes_na, "x1": x1, "x2": x2, "y": y},
                           categorical=["c"])
    mu, _ = score_reference_glm_mojo(p, rows)
    ours = m.predict(fr2).col("predict").to_numpy()
    assert np.abs(mu - ours).max() < 2e-4, np.abs(mu - ours).max()


def test_drf_roundtrip(tmp_path):
    code, x1, x2, y, dom = _data(seed=9)
    fr = _frame(code, x1, x2, y)
    m = DRFEstimator(ntrees=5, max_depth=5, sample_rate=1.0,
                     mtries=3).train(fr, x=["c", "x1", "x2"], y="y")
    p = str(tmp_path / "ref.zip")
    m.download_mojo(p, format="reference")
    margins, info = score_reference_mojo(p, _raw_rows(fr, code, x1, x2))
    pref = margins[:, 0] / int(info["n_trees"])
    ours = m.predict(fr).col("predict").to_numpy()
    assert np.abs(pref - ours).max() < 1e-4, np.abs(pref - ours).max()


def test_kmeans_roundtrip(tmp_path):
    """KMeansMojoReader kv contract: cluster assignments from the zip
    must match in-cluster predict."""
    from h2o3_tpu.genmodel.refmojo import (score_reference_kmeans_mojo,
                                           write_reference_kmeans_mojo)
    from h2o3_tpu.models.kmeans import KMeansEstimator
    r = np.random.RandomState(9)
    n = 1200
    X = np.concatenate([r.randn(n // 3, 3) + c for c in (-4, 0, 4)])
    fr = Frame.from_numpy({f"x{i}": X[:, i] for i in range(3)})
    m = KMeansEstimator(k=3, seed=7).train(fr)
    p = str(tmp_path / "km.zip")
    write_reference_kmeans_mojo(m, p)
    got, info = score_reference_kmeans_mojo(
        p, {f"x{i}": X[:, i] for i in range(3)})
    ours = m.predict(fr).col("predict").to_numpy()[: len(X)]
    assert info["algo"] == "kmeans"
    assert np.array_equal(got, ours.astype(got.dtype))


def test_deeplearning_roundtrip(tmp_path):
    """DeeplearningMojoReader kv contract: the decoded forward pass
    (cats-first layout, row-major weights) must match in-cluster
    scoring probabilities."""
    from h2o3_tpu.genmodel.refmojo import (score_reference_dl_mojo,
                                           write_reference_dl_mojo)
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    r = np.random.RandomState(11)
    n = 1500
    code = r.randint(0, 5, n)
    x1 = r.randn(n)
    yv = ((code >= 2).astype(float) + x1 > 0.8).astype(int)
    fr = Frame.from_numpy(
        {"c": code.astype(np.int32), "x1": x1,
         "y": yv.astype(np.int32)},
        categorical=["c", "y"],
        domains={"c": [f"L{i}" for i in range(5)], "y": ["n", "p"]})
    m = DeepLearningEstimator(hidden=[8, 8], epochs=3, seed=3,
                              activation="Tanh").train(
        fr, x=["c", "x1"], y="y")
    p = str(tmp_path / "dl.zip")
    write_reference_dl_mojo(m, p)
    rows = {"c": np.array([f"L{i}" for i in code], object), "x1": x1}
    out, info = score_reference_dl_mojo(p, rows)
    probs = np.exp(out) / np.exp(out).sum(axis=1, keepdims=True)
    ours = m._score_raw(fr)["p1"][: n]
    assert info["algo"] == "deeplearning"
    np.testing.assert_allclose(probs[:, 1], ours, atol=2e-4)


def test_reference_fixture_decodes(tmp_path):
    """Inverse validation (their bytes → our decoder): the reference
    repo's own GBM MOJO fixture (h2o-genmodel test resources, mojo
    version 1.20 — ScoreTree2 grammar, same as 1.40) must decode with
    the same reader our round-trip uses, closing the no-JVM gap as far
    as this image allows."""
    import os
    fixture = ("/root/reference/h2o-genmodel/src/test/resources/"
               "hex/genmodel/mojo.zip")
    if not os.path.exists(fixture):
        pytest.skip("reference fixture not present")
    r = np.random.RandomState(1)
    with zipfile.ZipFile(fixture) as z:
        ini = z.read("model.ini").decode()
    cols = []
    sec = None
    for ln in ini.splitlines():
        ln = ln.strip()
        if ln.startswith("["):
            sec = ln
            continue
        if sec == "[columns]" and ln:
            cols.append(ln)
    init_f = float([ln.split("=")[1] for ln in ini.splitlines()
                    if ln.startswith("init_f")][0])
    n_feat = int([ln.split("=")[1] for ln in ini.splitlines()
                  if ln.startswith("n_features")][0])
    feat_cols = cols[:n_feat]
    rows = {c: r.randn(16) * 2 for c in feat_cols}
    margins, info = score_reference_mojo(fixture, rows)
    assert info["algo"] == "gbm"
    preds = init_f + margins[:, 0]
    assert np.all(np.isfinite(preds))
    # regression on a positive target (init_f ≈ 46.5): the decoded
    # forest must move predictions around the training mean, not
    # collapse to init_f (i.e. the blobs were actually walked)
    assert np.std(margins[:, 0]) > 0.0
    # decode must be deterministic
    m2, _ = score_reference_mojo(fixture, rows)
    assert np.array_equal(margins, m2)


# ---- round-trip coverage for the round-5 additions: isofor, word2vec,
# coxph, glrm (VERDICT r4 demand #8) — each uses the same two-sided
# scheme: our writer emits the reference zip, an independently-ported
# reader decodes it, and the decode must reproduce in-cluster results.


def test_isofor_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import score_reference_isofor_mojo
    from h2o3_tpu.models.isofor import IsolationForestEstimator
    r = np.random.RandomState(11)
    n = 1200
    x1 = r.randn(n)
    x2 = r.randn(n)
    x1[-20:] += 6.0                     # planted anomalies
    fr = Frame.from_numpy({"x1": x1, "x2": x2})
    m = IsolationForestEstimator(ntrees=20, max_depth=6,
                                 seed=5).train(fr)
    p = str(tmp_path / "isofor.zip")
    m.download_mojo(p, format="reference")
    with zipfile.ZipFile(p) as z:
        ini = z.read("model.ini").decode()
        assert "algo = isolationforest" in ini
        assert "min_path_length" in ini and "max_path_length" in ini
    got, info = score_reference_isofor_mojo(
        p, {"x1": x1, "x2": x2})
    want = m._score_raw(fr)
    assert np.allclose(got["mean_length"], want["mean_length"],
                       atol=1e-4), \
        np.abs(got["mean_length"] - want["mean_length"]).max()
    assert np.allclose(got["predict"], want["predict"], atol=1e-4)
    # planted anomalies must score high through the MOJO path too
    assert got["predict"][-20:].mean() > got["predict"][:-20].mean()


def test_word2vec_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import read_reference_word2vec_mojo
    from h2o3_tpu.models.word2vec import Word2VecEstimator
    r = np.random.RandomState(3)
    words = ["alpha", "beta", "gamma", "delta", "epsi"]
    text = np.array([words[i] for i in r.randint(0, 5, 4000)],
                    dtype=object)
    fr = Frame.from_numpy({"text": text}, strings=["text"])
    m = Word2VecEstimator(vec_size=16, epochs=1,
                          min_word_freq=1).train(fr)
    p = str(tmp_path / "w2v.zip")
    m.download_mojo(p, format="reference")
    emb, info = read_reference_word2vec_mojo(p)
    assert int(info["vec_size"]) == 16
    assert set(emb) == set(m.vocab)
    for i, w in enumerate(m.vocab):
        assert np.allclose(emb[w],
                           np.asarray(m.vectors[i], np.float32),
                           atol=1e-6)


def test_coxph_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import score_reference_coxph_mojo
    from h2o3_tpu.models.coxph import CoxPHEstimator
    r = np.random.RandomState(9)
    n = 800
    age = r.rand(n) * 40 + 30
    grp = r.choice(["a", "b", "c"], n)
    risk = 0.03 * age + (grp == "c") * 0.8
    t = -np.log(r.rand(n)) / np.exp(risk - 2.5)
    ev = (r.rand(n) < 0.7).astype(float)
    fr = Frame.from_numpy(
        {"age": age, "grp": grp, "stop": t, "event": ev},
        categorical=["grp"])
    m = CoxPHEstimator(stop_column="stop").train(
        fr, x=["age", "grp"], y="event")
    p = str(tmp_path / "coxph.zip")
    m.download_mojo(p, format="reference")
    lp, info = score_reference_coxph_mojo(
        p, {"age": age, "grp": grp})
    want = m._score_raw(fr)["lp"]
    assert np.allclose(lp, want, atol=1e-4), np.abs(lp - want).max()


def test_glrm_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import read_reference_glrm_mojo
    from h2o3_tpu.models.glrm import GLRMEstimator
    r = np.random.RandomState(6)
    n = 500
    base = r.randn(n, 2)
    fr = Frame.from_numpy({
        "x1": base @ [1.0, 0.2], "x2": base @ [-0.5, 1.0],
        "x3": base @ [0.3, 0.3],
        "g": np.array(["u", "v"], object)[(base[:, 0] > 0).astype(int)]},
        categorical=["g"])
    m = GLRMEstimator(k=2, seed=2).train(fr)
    p = str(tmp_path / "glrm.zip")
    m.download_mojo(p, format="reference")
    dec, info = read_reference_glrm_mojo(p)
    assert dec["archetypes"].shape == (2, np.asarray(m.Y).shape[1])
    # decoded archetypes must equal ours under the cats-first
    # permutation the writer applied
    doms = m.di_stats["domains"]
    blocks, j = [], 0
    for d in doms:
        w = max(len(d), 1) if d is not None else 1
        blocks.append(list(range(j, j + w)))
        j += w
    cats_i = [i for i, d in enumerate(doms) if d is not None]
    nums_i = [i for i, d in enumerate(doms) if d is None]
    perm = [c for i in cats_i for c in blocks[i]] + \
        [c for i in nums_i for c in blocks[i]]
    assert np.allclose(dec["archetypes"],
                       np.asarray(m.Y, np.float64)[:, perm], atol=1e-6)
    assert len(dec["losses"]) == len(m.features)
    assert dec["permutation"] == cats_i + nums_i


def test_pca_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import score_reference_pca_mojo
    from h2o3_tpu.models.pca import PCAEstimator
    r = np.random.RandomState(11)
    n = 600
    x1 = r.randn(n) * 3 + 1
    x2 = x1 * 0.5 + r.randn(n)
    g = np.array(["p", "q", "s"], object)[r.randint(0, 3, n)]
    fr = Frame.from_numpy({"x1": x1, "g": g, "x2": x2}, categorical=["g"])
    m = PCAEstimator(k=2, transform="standardize", seed=3).train(fr)
    p = str(tmp_path / "pca.zip")
    m.download_mojo(p, format="reference")
    got = score_reference_pca_mojo(p, {"x1": x1, "g": g, "x2": x2})
    raw = m._score_raw(fr)
    want = np.stack([raw["PC1"], raw["PC2"]], axis=1)
    assert np.allclose(got, want, atol=2e-3), np.abs(got - want).max()


def test_targetencoder_roundtrip(tmp_path):
    from h2o3_tpu.genmodel.refmojo import score_reference_te_mojo
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    r = np.random.RandomState(13)
    n = 1200
    g1 = np.array(["a", "b", "c", "d"], object)[r.randint(0, 4, n)]
    g2 = np.array(["u", "v"], object)[r.randint(0, 2, n)]
    yv = ((g1 == "a") * 0.5 + (g2 == "v") * 0.2
          + r.rand(n) < 0.55).astype(int)
    fr = Frame.from_numpy(
        {"g1": g1, "g2": g2,
         "y": np.array(["no", "yes"], object)[yv]},
        categorical=["g1", "g2", "y"])
    for blending in (False, True):
        m = TargetEncoderEstimator(
            blending=blending, inflection_point=15.0, smoothing=25.0,
            noise=0.0).train(fr, x=["g1", "g2"], y="y")
        p = str(tmp_path / f"te_{blending}.zip")
        m.download_mojo(p, format="reference")
        got = score_reference_te_mojo(p, {"g1": g1, "g2": g2})
        want = m.transform(fr, as_training=False, noise=0.0)
        for col in ("g1_te", "g2_te"):
            np.testing.assert_allclose(
                got[col], want.col(col).to_numpy(), atol=1e-6,
                err_msg=f"{col} blending={blending}")
