"""DRF + IsolationForest tests — pyunit_drf* / pyunit_isofor* role
(h2o-py/tests/testdir_algos/{rf,isoforest}/)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models.drf import DRFEstimator
from h2o3_tpu.models.isofor import IsolationForestEstimator
from tests.conftest import make_classification


def test_drf_binomial_learns(classif_frame):
    m = DRFEstimator(ntrees=30, max_depth=8, seed=42)
    model = m.train(classif_frame, y="y")
    tm = model.training_metrics          # OOB metrics
    assert tm["AUC"] > 0.75, tm.to_dict()
    val = model.model_performance(classif_frame)
    assert val["AUC"] > tm["AUC"] - 0.05   # in-bag score >= OOB


def test_drf_predictions(classif_frame):
    m = DRFEstimator(ntrees=10, max_depth=6, seed=1)
    model = m.train(classif_frame, y="y")
    preds = model.predict(classif_frame)
    assert preds.names == ["predict", "p0", "p1"]
    p = preds.to_pandas()
    assert ((p["p0"] + p["p1"]).round(4) == 1.0).all()
    assert p["p1"].between(0, 1).all()


def test_drf_regression(regress_frame):
    m = DRFEstimator(ntrees=30, max_depth=10, seed=3)
    model = m.train(regress_frame, y="y")
    tm = model.training_metrics
    y = regress_frame.col("y").to_numpy()
    assert tm["MSE"] < 0.6 * float(np.var(y))


def test_drf_multinomial():
    r = np.random.RandomState(11)
    n = 3000
    X = r.randn(n, 5)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    f = h2o3_tpu.Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(5)},
         "y": np.array(["a", "b", "c"], dtype=object)[y]},
        categorical=["y"])
    model = DRFEstimator(ntrees=20, max_depth=8, seed=5).train(f, y="y")
    assert model.training_metrics["error_rate"] < 0.25
    preds = model.predict(f).to_pandas()
    assert set(preds["predict"].unique()) <= {"a", "b", "c"}


def test_drf_varimp(classif_frame):
    model = DRFEstimator(ntrees=15, max_depth=6, seed=2).train(
        classif_frame, y="y")
    vi = model.varimp_table
    assert len(vi) == 8
    top = {name for name, *_ in vi[:4]}
    # informative features are x0..x3
    assert len(top & {"x0", "x1", "x2", "x3"}) >= 3, vi


def test_isolation_forest_separates_outliers():
    r = np.random.RandomState(0)
    inliers = r.randn(2000, 4)
    outliers = r.randn(40, 4) * 0.5 + 6.0
    X = np.vstack([inliers, outliers])
    f = h2o3_tpu.Frame.from_numpy({f"x{i}": X[:, i] for i in range(4)})
    m = IsolationForestEstimator(ntrees=40, seed=7).train(f)
    s = m.predict(f).to_pandas()
    assert {"predict", "mean_length"} <= set(s.columns)
    inl = s["predict"][:2000].mean()
    out = s["predict"][2000:].mean()
    assert out > inl + 0.1, (inl, out)
