"""Chunk-parallel ingest pipeline (ISSUE 12).

The contract under test: the parallel path (splitter → tokenizer pool →
in-order merge → double-buffered transfer) is BIT-identical to the
sequential workers=1 fallback — same device bits, dtypes, NA masks and
categorical domains — because both drivers consume the same windows in
the same order through the same accumulators. Plus the satellites:
quote-aware splitting at chunk boundaries, multi-file glob / .csv.gz
parity, export→re-import roundtrip, the Parquet row-group-parallel fast
path with sensible arrow typing, the REST parse plan, and the ingest
telemetry counters.
"""

import gzip
import json

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.io.chunking import quote_aware_cut
from h2o3_tpu.io.stream import stream_import_csv


def _frame_bits(fr):
    """Full bit-level identity: column order, logical rows, and per
    column (type, dtype, raw data bytes, raw mask bytes, domain)."""
    cols = {}
    for nm in fr._order:
        c = fr._cols[nm]
        d = np.asarray(c.data)
        m = None if c.na_mask is None else np.asarray(c.na_mask)
        cols[nm] = (c.type, str(d.dtype), d.tobytes(),
                    None if m is None else m.tobytes(),
                    tuple(c.domain) if c.domain else None)
    return list(fr._order), fr.nrows, cols


def _assert_bit_identical(a, b):
    oa, ra, ca = _frame_bits(a)
    ob, rb, cb = _frame_bits(b)
    assert oa == ob and ra == rb
    for nm in oa:
        for i, part in enumerate(("type", "dtype", "data bits",
                                  "na mask bits", "domain")):
            assert ca[nm][i] == cb[nm][i], (nm, part)


def _mixed_df(n=40_000, seed=5):
    r = np.random.RandomState(seed)
    df = pd.DataFrame({
        "i8": r.randint(-100, 100, n),
        "i16": r.randint(0, 30_000, n),
        "f": r.randn(n).round(4),
        "g": np.array(["aa", "bb", "cc", "dd"])[r.randint(0, 4, n)],
    })
    df.loc[::71, "f"] = np.nan
    return df


def test_parallel_bit_identical_to_sequential(tmp_path):
    df = _mixed_df()
    p = str(tmp_path / "mixed.csv")
    df.to_csv(p, index=False)
    # tiny windows force many chunks; 4 workers force out-of-order
    # tokenize completion that the in-order merge must serialize
    seq = stream_import_csv(p, chunk_bytes=32 << 10, workers=1)
    par = stream_import_csv(p, chunk_bytes=32 << 10, workers=4)
    assert seq.nrows == len(df)
    _assert_bit_identical(seq, par)
    got = par.to_pandas()
    assert np.array_equal(got["i8"].to_numpy(float),
                          df["i8"].to_numpy(float))
    gf, ef = got["f"].to_numpy(float), df["f"].to_numpy(float)
    assert np.array_equal(np.isnan(gf), np.isnan(ef))
    assert np.allclose(gf[~np.isnan(ef)], ef[~np.isnan(ef)], atol=1e-9)


def test_multi_file_glob_and_gzip_parity(tmp_path):
    df = _mixed_df(n=9_000, seed=7)
    parts = [df.iloc[:3_000], df.iloc[3_000:6_000], df.iloc[6_000:]]
    parts[0].to_csv(tmp_path / "part_0.csv", index=False)
    with gzip.open(tmp_path / "part_1.csv.gz", "wt") as f:
        parts[1].to_csv(f, index=False)
    parts[2].to_csv(tmp_path / "part_2.csv", index=False)
    whole = str(tmp_path / "whole.csv")
    df.to_csv(whole, index=False)
    glob = str(tmp_path / "part_*")
    seq = stream_import_csv(glob, chunk_bytes=16 << 10, workers=1)
    par = stream_import_csv(glob, chunk_bytes=16 << 10, workers=4)
    one = stream_import_csv(whole, chunk_bytes=16 << 10, workers=4)
    assert seq.nrows == len(df)
    # glob parallel == glob sequential == single concatenated file:
    # repeated headers of files 2..N are stripped by the splitter, and
    # per-file window boundaries must not leak into the final bits
    _assert_bit_identical(seq, par)
    _assert_bit_identical(par, one)


def test_splitter_never_cuts_mid_quote():
    # a window ending inside an open quoted field must cut BEFORE it
    assert quote_aware_cut(b'a,b\n"x,\ny') == 4
    # RFC4180 "" escapes toggle parity twice: the embedded newline at
    # odd parity is skipped, the record-final newline is kept
    buf = b'v\n"a""b\nc",9\n'
    assert quote_aware_cut(buf) == len(buf)
    # no record boundary at all -> 0 (caller carries the remainder)
    assert quote_aware_cut(b'"open field, no close') == 0
    assert quote_aware_cut(b"no newline here") == 0


def test_quoted_fields_across_chunk_boundaries(tmp_path):
    # embedded separators AND embedded newlines inside quoted fields,
    # with windows so small the naive splitter would land mid-quote
    # every few records (the S2 regression)
    n = 4_000
    r = np.random.RandomState(11)
    vals = []
    for i in range(n):
        k = i % 4
        if k == 0:
            vals.append(f"plain{i}")
        elif k == 1:
            vals.append(f"with,comma,{i}")
        elif k == 2:
            vals.append(f"line1\nline2 {i}")
        else:
            vals.append(f"both,\n{i}")
    df = pd.DataFrame({"s": vals, "x": r.randint(0, 1000, n)})
    p = str(tmp_path / "quoted.csv")
    df.to_csv(p, index=False)
    seq = stream_import_csv(p, chunk_bytes=1 << 10, workers=1)
    par = stream_import_csv(p, chunk_bytes=1 << 10, workers=4)
    _assert_bit_identical(seq, par)
    assert par.nrows == n
    got = par.to_pandas()
    assert got["s"].astype(str).tolist() == vals    # pandas oracle
    assert np.array_equal(got["x"].to_numpy(float),
                          df["x"].to_numpy(float))
    # the eager native path (import_file) agrees on values too
    eager = h2o3_tpu.import_file(p).to_pandas()
    assert eager["s"].astype(str).tolist() == vals


def test_export_reimport_roundtrip(tmp_path):
    from h2o3_tpu.io.parser import export_file
    n = 3_000
    r = np.random.RandomState(13)
    s = np.array(["plain", "with,comma", 'with "quote"', "ok"],
                 object)[r.randint(0, 4, n)]
    df = pd.DataFrame({"s": s, "f": r.randn(n).round(4),
                       "i": r.randint(0, 50, n)})
    df.loc[::37, "s"] = np.nan          # NA strings
    df.loc[::53, "f"] = np.nan
    p = str(tmp_path / "orig.csv")
    df.to_csv(p, index=False)
    fr = stream_import_csv(p, chunk_bytes=8 << 10, workers=4)
    out = str(tmp_path / "export.csv")
    export_file(fr, out)
    back = stream_import_csv(out, chunk_bytes=8 << 10, workers=4)
    # row order survives, so first-seen categorical interning reproduces
    # the same domain and codes; NAs and quoted fields round-trip
    _assert_bit_identical(fr, back)
    got = back.to_pandas()
    gs = got["s"].astype(object).where(got["s"].notna(), np.nan)
    es = df["s"]
    assert all((a != a and b != b) or a == b for a, b in zip(gs, es))
    gf, ef = got["f"].to_numpy(float), df["f"].to_numpy(float)
    assert np.array_equal(np.isnan(gf), np.isnan(ef))
    assert np.allclose(gf[~np.isnan(ef)], ef[~np.isnan(ef)], atol=1e-9)


def test_parquet_row_group_parallel_parity(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from h2o3_tpu.io.formats import parse_parquet
    n = 10_000
    r = np.random.RandomState(17)
    f = r.randn(n)
    f[::41] = np.nan                       # NaN payloads -> NA
    tbl = pa.table({
        "i": pa.array(r.randint(-5_000, 5_000, n)),
        "f": pa.array(f),
        "s": pa.array(np.array(["x", "y", None, "z"],
                               object)[r.randint(0, 4, n)]),
        "b": pa.array([None if i % 97 == 0 else bool(i % 3)
                       for i in range(n)]),
        "t": pa.array(r.randint(0, 2_000_000_000, n).astype(
            "datetime64[s]")),
    })
    p = str(tmp_path / "mixed.parquet")
    pq.write_table(tbl, p, row_group_size=1_234)   # 9 row groups
    seq = parse_parquet(p, workers=1)
    par = parse_parquet(p, workers=4)
    _assert_bit_identical(seq, par)
    # arrow typing (S1): bool -> two-level categorical, timestamp -> time
    b = par.col("b")
    assert b.is_categorical and b.domain == ["false", "true"]
    assert bool(np.asarray(b.na_mask)[:n].any())
    assert par.col("t").type == "time"
    assert par.col("s").is_categorical
    gf = par.col("f").to_numpy()
    assert np.array_equal(np.isnan(gf), np.isnan(f))


def test_parse_setup_parquet_types(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from h2o3_tpu.io.parser import parse_setup
    tbl = pa.table({
        "i": pa.array([1, 2, 3]),
        "f": pa.array([0.5, 1.5, None]),
        "s": pa.array(["a", "b", "a"]),
        "b": pa.array([True, False, True]),
        "t": pa.array(np.array([0, 1, 2], "datetime64[ms]")),
    })
    p = str(tmp_path / "setup.parquet")
    pq.write_table(tbl, p)
    setup = parse_setup(p)
    assert setup["types"] == {"i": "numeric", "f": "numeric",
                              "s": "categorical", "b": "categorical",
                              "t": "time"}


@pytest.mark.allow_key_leak
def test_rest_parse_plan(tmp_path):
    import urllib.parse
    import urllib.request

    from h2o3_tpu.api.server import start_server, stop_server
    csv = tmp_path / "plan.csv"
    csv.write_text("a,b\n1,x\n2,y\n")
    port = start_server(port=0, background=True)
    try:
        def _post(path, **params):
            data = urllib.parse.urlencode(params).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=data,
                method="POST")
            req.add_header("Content-Type",
                           "application/x-www-form-urlencoded")
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read())
        j = _post("/3/ParseSetup", source_frames=json.dumps([str(csv)]))
        plan = j["parse_plan"]
        assert plan["mode"] in ("sequential", "chunk-parallel")
        assert plan["workers"] >= 1 and plan["files"] == 1
        assert plan["formats"] == ["csv"] and plan["chunk_bytes"] > 0
        # glob sources: setup samples the first matched file, the plan
        # counts every match (the S3 multi-file surface over REST)
        (tmp_path / "plan2.csv").write_text("a,b\n3,z\n")
        j = _post("/3/ParseSetup",
                  source_frames=json.dumps([str(tmp_path / "plan*.csv")]))
        assert j["parse_plan"]["files"] == 2
        assert j["column_names"] == ["a", "b"]
        j = _post("/3/Parse", source_frames=json.dumps([str(csv)]),
                  destination_frame="plan_hex")
        assert j["parse_plan"]["files"] == 1
        assert "job" in j
    finally:
        stop_server()


def test_ingest_telemetry_counters(tmp_path):
    from h2o3_tpu import telemetry
    df = _mixed_df(n=5_000, seed=23)
    p = str(tmp_path / "tele.csv")
    df.to_csv(p, index=False)
    nbytes = __import__("os").path.getsize(p)
    reg = telemetry.REGISTRY
    b0 = reg.value("ingest_bytes_total", format="csv")
    r0 = reg.value("ingest_rows_total")
    stage0 = {s: reg.value("parse_chunk_seconds", stage=s)
              for s in ("tokenize", "merge", "transfer")}
    fr = stream_import_csv(p, chunk_bytes=16 << 10, workers=2)
    assert reg.value("ingest_bytes_total", format="csv") - b0 == nbytes
    assert reg.value("ingest_rows_total") - r0 == fr.nrows == len(df)
    for s in ("tokenize", "merge", "transfer"):
        assert reg.value("parse_chunk_seconds", stage=s) > stage0[s], s
