"""Worker for the fleet serving-resilience multiprocess tests
(ISSUE 17, serving/fleet.py).

Every process runs this same script (the SPMD contract): forms a
2-process jax.distributed CPU cloud, trains one GBM, then exercises the
replica registry + health-routed predictions. Modes (argv[5]):

- ``serve`` — process 0 publishes the model's device-independent binary
  and serves a warm replica; process 1 (which holds NO local copy)
  drives concurrent row-payload predicts through its OWN REST edge —
  node symmetry: the fleet router proxies every request to the replica
  and the answers must be bit-identical to ``Model.predict``.
- ``kill`` — process 1 is the only replica; process 0 proxies a load
  through it, then SIGKILLs it mid-stream (via the ``.killflag`` file).
  The survivor must hedge the burst to a local install of the published
  binary (bounded errors, answers still bit-identical), see the dead
  peer excluded within one heartbeat staleness window, and drain clean.

Each surviving process writes ``outfile.<pid>`` with its predictions,
routing counters, and fleet stats (full-precision floats via json).
"""

import json
import os
import signal
import sys
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# fast dead-peer detection for the kill leg (staleness = interval * 3)
os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"] = "0.25"
# fresh load reads + quick adoption during the short test window
os.environ["H2O3TPU_FLEET_LOAD_TTL_S"] = "0.2"
os.environ["H2O3TPU_FLEET_ADOPT_S"] = "0.5"
# both legs compile the SAME GBM kernel shapes — share the executables
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.environ.get("TMPDIR", "/tmp"), "h2o3tpu-test-xlacache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

coord, nproc, pid, outfile, mode = sys.argv[1:6]
nproc, pid = int(nproc), int(pid)

import jax                                    # noqa: E402
jax.config.update("jax_default_device", None)

import h2o3_tpu                               # noqa: E402
h2o3_tpu.init(backend="cpu", coordinator_address=coord,
              num_processes=nproc, process_id=pid)

import numpy as np                            # noqa: E402

from h2o3_tpu import telemetry                # noqa: E402
from h2o3_tpu.core.kv import DKV              # noqa: E402
from h2o3_tpu.serving import fleet            # noqa: E402
from h2o3_tpu.serving.rows import serving_schema   # noqa: E402

N_ROWS = 2000
N_PAYLOAD = 16


def build_data():
    r = np.random.RandomState(17)
    a = r.randn(N_ROWS)
    b = r.randn(N_ROWS)
    g = r.choice(["u", "v", "w"], N_ROWS)
    y = 2.0 * a - b + (g == "u") * 1.5 + r.randn(N_ROWS) * 0.3
    return h2o3_tpu.Frame.from_numpy(
        {"a": a, "b": b, "g": g, "y": y}, categorical=["g"])


def rows_of(model, fr, hi):
    """JSON-shaped payloads reproducing fr[:hi] exactly (the
    tests/test_serving.py _rows_of idiom, numerics + categoricals)."""
    schema = serving_schema(model)
    cache = {nm: fr.col(nm).to_numpy() for nm, _ in schema
             if nm in fr.names}
    rows = []
    for i in range(hi):
        r = {}
        for nm, dom in schema:
            if nm not in cache:
                continue
            v = float(cache[nm][i])
            if np.isnan(v):
                r[nm] = None
            elif dom is not None:
                r[nm] = dom[int(v)]
            else:
                r[nm] = v
        rows.append(r)
    return rows


fr = build_data()

from h2o3_tpu.models.gbm import GBMEstimator  # noqa: E402

model = GBMEstimator(ntrees=3, max_depth=3, seed=7).train(fr, y="y")
MKEY = str(model.key)

# the bit-parity reference: Model.predict on the SAME rows, computed
# SPMD (both processes participate) BEFORE any replica moves
base = model.predict(fr).col("predict").to_numpy()
REF = [float(v) for v in base[:N_PAYLOAD]]
ROWS = rows_of(model, fr, N_PAYLOAD)

from h2o3_tpu.api.server import start_server  # noqa: E402

port = start_server(port=0, background=True)


def post_rows(to_port, timeout=15.0):
    """One row-payload predict; returns (status, predictions|msg)."""
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{to_port}/3/Predictions/models/{MKEY}",
        data=json.dumps({"rows": ROWS}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, {"retry_after": e.headers.get("Retry-After"),
                        "body": e.read().decode("utf-8", "replace")[:300]}
    except Exception as e:   # noqa: BLE001 - connection refused etc.
        return -1, {"error": f"{type(e).__name__}: {e}"}


def drive(n, threads):
    """n predicts against OUR edge across `threads` workers; returns
    (ok_preds, errors) — every 200's predict column, every non-200."""
    ok, errors, lock = [], [], threading.Lock()

    def _one():
        code, out = post_rows(port)
        with lock:
            if code == 200:
                ok.append([float(v) for v in out["predictions"]["predict"]])
            else:
                errors.append({"code": code, "out": out})

    for lo in range(0, n, threads):
        ts = [threading.Thread(target=_one)
              for _ in range(min(threads, n - lo))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    return ok, errors


def wait_for(cond, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    raise RuntimeError(f"timed out waiting for {what}")


def routed_counters():
    return {d: telemetry.REGISTRY.value("predict_routed_total", decision=d)
            for d in ("local", "proxy", "redirect", "install", "none")}


def failover_counters():
    return {r: telemetry.REGISTRY.value("predict_failovers_total", reason=r)
            for r in ("connection", "timeout", "http_5xx", "error")}


result = {"pid": pid, "ref": REF, "port": port}

# Publish is an SPMD point on a live cloud (the device-lowering pickle
# allgathers any cross-process sharded array), so BOTH processes call
# it here — only then does ownership diverge per mode.
fleet.publish(model)

if mode == "serve":
    if pid == 0:
        # the replica host: serve from an INSTALLED copy of the
        # published binary (the exact path an adopting peer runs —
        # numpy constants, engine pre-warmed)
        DKV.remove(MKEY)
        fleet.install_published(MKEY)
        # hold until the client banked its result (the coordination
        # service lives here); then drain through normal shutdown
        wait_for(lambda: os.path.exists(f"{outfile}.1"), 120,
                 "client outfile")
        result["replicas"] = sorted(fleet.replicas(MKEY))
        result["stats"] = fleet.stats()
    else:
        # the routing-only node: NO local copy — node symmetry says its
        # REST edge must still answer, via the fleet
        DKV.remove(MKEY)
        wait_for(lambda: 0 in fleet.replicas(MKEY)
                 and 0 in fleet.endpoints(), 60, "replica 0 in registry")
        ok, errors = drive(32, threads=4)
        result.update({
            "n_ok": len(ok), "errors": errors,
            "preds": ok[-1] if ok else None,
            "all_identical": all(p == REF for p in ok),
            "routed": routed_counters(),
        })
    with open(f"{outfile}.{pid}", "w") as f:
        json.dump(result, f)
    print(f"FLEET-WORKER-{pid}-DONE", flush=True)
    h2o3_tpu.shutdown()
    sys.exit(0)

# ---- kill mode ----

killflag = f"{outfile}.killflag"

if pid == 1:
    # the ONLY replica: serve until process 0 raises the kill flag,
    # then die without warning
    DKV.remove(MKEY)
    fleet.install_published(MKEY)
    while not os.path.exists(killflag):
        time.sleep(0.05)
    os.kill(os.getpid(), signal.SIGKILL)

# pid 0: routes everything through the doomed replica
DKV.remove(MKEY)
wait_for(lambda: 1 in fleet.replicas(MKEY) and 1 in fleet.endpoints(),
         60, "replica 1 in registry")

# phase A — steady state: every predict proxies to the replica
ok_a, err_a = drive(12, threads=3)

# phase B — SIGKILL the replica mid-stream; hedged failover must bound
# the burst by falling back to a local install of the published binary
with open(killflag, "w") as f:
    f.write("die")
t_kill = time.monotonic()
ok_b, err_b = drive(40, threads=4)

# the heartbeat must exclude the dead peer within one staleness window
wait_for(lambda: 1 in fleet._dead_set(), 15, "dead-peer exclusion")
t_detect = time.monotonic() - t_kill

# phase C — post-exclusion: routing never offers the dead peer again
ok_c, err_c = drive(6, threads=2)

result.update({
    "phase_a": {"n_ok": len(ok_a), "errors": err_a,
                "identical": all(p == REF for p in ok_a)},
    "phase_b": {"n_ok": len(ok_b), "errors": err_b,
                "identical": all(p == REF for p in ok_b)},
    "phase_c": {"n_ok": len(ok_c), "errors": err_c,
                "identical": all(p == REF for p in ok_c)},
    "detect_s": t_detect,
    "hb_window_s": (float(os.environ["H2O3TPU_HEARTBEAT_INTERVAL_S"])
                    * 3),
    "routed": routed_counters(),
    "failovers": failover_counters(),
    "local_replica_after": MKEY in fleet.stats()["local_replicas"],
})

# the survivor drains clean: replicas deregistered, engine emptied,
# registry marked draining — queued work would 503, nothing hangs
fleet.drain()
result["stats_after_drain"] = fleet.stats()
from h2o3_tpu.serving.engine import engine    # noqa: E402
result["engine_warm_after_drain"] = engine.warm_models()

with open(f"{outfile}.{pid}", "w") as f:
    json.dump(result, f)
print(f"FLEET-WORKER-{pid}-DONE", flush=True)
# peer 1 is dead: the distributed-shutdown barrier would wait forever —
# results are on disk, leave hard (the sched_worker kill-leg contract)
os._exit(0)
