"""Generic model — import an external MOJO as a first-class model.

Reference: hex/generic/Generic.java — wraps a MOJO file in the Model API
so it can predict, be measured, sit on leaderboards, and serve REST like
any in-cluster model.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.model import Model, ModelBuilder


def _frame_raw_columns(frame: Frame, names) -> Dict[str, np.ndarray]:
    """Frame → dict of raw host columns (levels decoded for categoricals)."""
    out = {}
    for n in names:
        c = frame.col(n)
        if c.is_categorical:
            codes = _fetch_np(c.data)[: c.nrows]
            na = _fetch_np(c.na_mask)[: c.nrows]
            dom = np.asarray(c.domain or [], dtype=object)
            vals = np.empty(c.nrows, dtype=object)
            ok = ~na & (codes >= 0) & (codes < len(dom))
            vals[ok] = dom[codes[ok]]
            vals[~ok] = None
            out[n] = vals
        else:
            out[n] = c.to_numpy()
    return out


class GenericModel(Model):
    algo = "generic"

    def __init__(self, params, output, mojo):
        super().__init__(params, output)
        self.mojo = mojo

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        data = _frame_raw_columns(frame, self.mojo.names)
        return self.mojo.predict(data)

    def model_performance(self, frame: Frame):
        from h2o3_tpu.models import metrics as mm
        import jax.numpy as jnp
        y = self.output.get("response")
        if y is None or y not in frame:
            raise ValueError("response column unavailable for metrics")
        out = self._score_raw(frame)
        cat = self.output["category"]
        n = frame.nrows
        w = np.asarray(frame.valid_weights())[:n]
        if cat == "Binomial":
            from h2o3_tpu.models.model import adapt_domain
            yv = adapt_domain(frame.col(y), self.output["domain"])
            w = w * (yv >= 0)
            return mm.binomial_metrics(jnp.asarray(out["p1"]),
                                       jnp.asarray(np.maximum(yv, 0).astype(np.float32)),
                                       jnp.asarray(w.astype(np.float32)))
        if cat == "Multinomial":
            from h2o3_tpu.models.model import adapt_domain
            yv = adapt_domain(frame.col(y), self.output["domain"])
            w = w * (yv >= 0)
            K = int(self.output.get("nclasses", 2))
            p = np.stack([out[f"p{k}"] for k in range(K)], axis=1)
            return mm.multinomial_metrics(jnp.asarray(p),
                                          jnp.asarray(np.maximum(yv, 0)),
                                          jnp.asarray(w.astype(np.float32)),
                                          domain=self.output["domain"])
        yv = frame.col(y).to_numpy()
        ok = np.isfinite(yv)
        return mm.regression_metrics(jnp.asarray(out["predict"][ok]),
                                     jnp.asarray(yv[ok]),
                                     jnp.asarray(w[ok].astype(np.float32)))


@register
class GenericEstimator(ModelBuilder):
    """h2o-py H2OGenericEstimator surface: train() "imports" the MOJO."""

    algo = "generic"
    supervised = False
    DEFAULTS = {"path": None, "model_key": None}

    def __init__(self, **params):
        if "path" not in params and "model_key" not in params:
            raise ValueError("GenericEstimator requires path=<mojo zip>")
        super().__init__(**params)

    def _fit(self, frame: Optional[Frame], x: Sequence[str],
             y: Optional[str], job, validation_frame=None) -> Model:
        from h2o3_tpu.genmodel import load_mojo
        mojo = load_mojo(self.params["path"])
        output = {
            "category": mojo.category,
            "response": mojo.meta.get("response"),
            "names": mojo.names,
            "domain": mojo.domain,
            "nclasses": mojo.nclasses,
            "default_threshold": mojo.meta.get("default_threshold", 0.5),
            "source_algo": mojo.algo,
        }
        model = GenericModel(self.params, output, mojo)
        if frame is not None and output["response"] in (frame.names if frame else []):
            model.training_metrics = model.model_performance(frame)
        return model

    def train(self, training_frame: Optional[Frame] = None, y=None, x=None,
              validation_frame=None, background: bool = False,
              dest_key: Optional[str] = None):
        if training_frame is None:
            job_frame = None
            # bypass resolve_x (no frame to resolve against)
            from h2o3_tpu.core.job import Job
            job = Job("generic import", work=1.0)
            self._job = job
            job.start(lambda j: self._fit(None, [], None, j),
                      background=background)
            if background:
                return job
            if job.status == "FAILED":
                raise RuntimeError(job.exception)
            return job.result
        return super().train(training_frame, y=y, x=x,
                             validation_frame=validation_frame,
                             background=background, dest_key=dest_key)
