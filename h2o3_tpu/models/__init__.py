"""Model layer — algorithm registry + estimator exports.

The registry is the analogue of the reference's ServiceLoader algorithm
registration (hex/api/RegisterAlgos.java:17-43): every ModelBuilder
registers under its algo name so REST / grid search / AutoML can
instantiate builders by name.
"""

from typing import Dict

_REGISTRY: Dict[str, type] = {}
_COMPLETE = False   # _REGISTRY may be partially filled by direct imports
                    # of @register-decorated modules; only _auto_register
                    # makes it complete


def register(cls):
    _REGISTRY[cls.algo] = cls
    return cls


def _auto_register():
    """Populate the registry from the standard estimator modules."""
    global _COMPLETE
    from h2o3_tpu.models.aggregator import AggregatorEstimator
    from h2o3_tpu.models.coxph import CoxPHEstimator
    from h2o3_tpu.models.deeplearning import DeepLearningEstimator
    from h2o3_tpu.models.drf import DRFEstimator
    from h2o3_tpu.models.extisofor import ExtendedIsolationForestEstimator
    from h2o3_tpu.models.gam import GAMEstimator
    from h2o3_tpu.models.gbm import GBMEstimator
    from h2o3_tpu.models.generic import GenericEstimator
    from h2o3_tpu.models.glm import GLMEstimator
    from h2o3_tpu.models.glrm import GLRMEstimator
    from h2o3_tpu.models.infogram import InfogramEstimator
    from h2o3_tpu.models.isofor import IsolationForestEstimator
    from h2o3_tpu.models.isotonic import IsotonicRegressionEstimator
    from h2o3_tpu.models.kmeans import KMeansEstimator
    from h2o3_tpu.models.model_selection import (ANOVAGLMEstimator,
                                                 ModelSelectionEstimator)
    from h2o3_tpu.models.naivebayes import NaiveBayesEstimator
    from h2o3_tpu.models.pca import PCAEstimator, SVDEstimator
    from h2o3_tpu.models.psvm import PSVMEstimator
    from h2o3_tpu.models.rulefit import RuleFitEstimator
    from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
    from h2o3_tpu.models.uplift import UpliftDRFEstimator
    from h2o3_tpu.models.word2vec import Word2VecEstimator
    from h2o3_tpu.models.xgboost import XGBoostEstimator
    for cls in (AggregatorEstimator, ANOVAGLMEstimator, CoxPHEstimator,
                DeepLearningEstimator,
                DRFEstimator, GAMEstimator, GBMEstimator, GenericEstimator,
                GLMEstimator, GLRMEstimator, InfogramEstimator,
                IsolationForestEstimator,
                IsotonicRegressionEstimator, KMeansEstimator,
                ModelSelectionEstimator, NaiveBayesEstimator, PCAEstimator,
                PSVMEstimator, RuleFitEstimator, SVDEstimator,
                TargetEncoderEstimator,
                ExtendedIsolationForestEstimator, UpliftDRFEstimator,
                Word2VecEstimator, XGBoostEstimator):
        _REGISTRY[cls.algo] = cls
    _COMPLETE = True   # only after every import succeeded — a transient
                       # ImportError must not poison the registry


def get_builder(algo: str):
    """Builder class by algo name (ModelBuilder.make analogue)."""
    if not _COMPLETE:
        _auto_register()
    key = algo.lower().replace("_", "")
    if key not in _REGISTRY:
        raise ValueError(f"unknown algo '{algo}'; have {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def all_algos():
    if not _COMPLETE:
        _auto_register()
    return sorted(_REGISTRY)
