"""Infogram — admissible-ML feature screening.

Reference: h2o-admissibleml (hex/Infogram/Infogram.java, 2735 LoC):
for every predictor compute
  - relevance ("total information"): normalized variable importance from
    a model on all predictors;
  - cmi ("net information"): normalized conditional mutual information
    of the predictor with the response given the rest — estimated from
    cross-validated model performance deltas.
Core infogram: conditioning set = the other predictors; fair/safety
infogram: conditioning set = the protected_columns, and predictors are
screened for safety (low cmi w.r.t. protected info).
Admissible features clear both thresholds; output is the
relevance/cmi table the h2o-py client plots.

TPU: every probe model is a shallow GBM on the mesh; the per-feature
loop is job-parallel orchestration (reference runs these as parallel
model builds too).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.model import Model, ModelBuilder, infer_category
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.infogram")


def _probe_logloss(frame, feats, y, ntrees, depth, seed) -> float:
    """Deviance of a shallow GBM using ``feats`` (the CMI estimator's
    model-performance probe)."""
    from h2o3_tpu.models.gbm import GBMEstimator
    m = GBMEstimator(ntrees=ntrees, max_depth=depth, seed=seed).train(
        frame, y=y, x=list(feats))
    tm = m.training_metrics.to_dict()
    for k in ("logloss", "mean_per_class_error", "MSE"):
        if tm.get(k) is not None:
            return float(tm[k])
    return float("nan")


class InfogramModel(Model):
    algo = "infogram"

    def __init__(self, params, output):
        super().__init__(params, output)

    @property
    def admissible_features(self) -> List[str]:
        return self.output["admissible_features"]

    def get_admissible_score_frame(self) -> Frame:
        t = self.output["infogram_table"]
        return Frame.from_numpy({
            "column": np.asarray([r["column"] for r in t], dtype=object),
            "admissible": np.asarray(
                [1.0 if r["admissible"] else 0.0 for r in t]),
            "admissible_index": np.asarray(
                [r["admissible_index"] for r in t]),
            "relevance_index": np.asarray([r["relevance"] for r in t]),
            "safety_index": np.asarray([r["cmi"] for r in t]),
        }, categorical=["column"])

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("Infogram is a screening model")

    def model_performance(self, frame: Frame):
        return None


@register
class InfogramEstimator(ModelBuilder):
    """h2o-py H2OInfogram surface (h2o-py/h2o/estimators/infogram.py)."""

    algo = "infogram"

    DEFAULTS = dict(
        protected_columns=None, safety_index_threshold=0.1,
        relevance_index_threshold=0.1, net_information_threshold=-1.0,
        total_information_threshold=-1.0, ntop=50, seed=-1,
        ntrees=10, max_depth=5, ignored_columns=None, nfolds=0,
        fold_assignment="auto", weights_column=None, fold_column=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown Infogram params: {sorted(unknown)}")
        merged.update(params)
        if int(merged.get("nfolds") or 0) >= 2:
            raise ValueError("Infogram is a screening model; generic CV is "
                             "not applicable (nfolds must be 0)")
        super().__init__(**merged)

    def resolve_x(self, frame, x, y):
        x = super().resolve_x(frame, x, y)
        protected = set(self.params.get("protected_columns") or [])
        return [n for n in x if n not in protected]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        protected = list(p.get("protected_columns") or [])
        ntrees, depth = int(p["ntrees"]), int(p["max_depth"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0x1F06

        # relevance: varimp of the full model over all predictors
        from h2o3_tpu.models.gbm import GBMEstimator
        full = GBMEstimator(ntrees=ntrees, max_depth=depth, seed=seed).train(
            frame, y=y, x=list(x))
        vi = {name: rel for name, _, rel, _ in
              (full.output.get("varimp") or [])}
        relevance = np.asarray([vi.get(f, 0.0) for f in x])
        job.update(0.3, "relevance done")

        # cap the probe budget at the ntop most relevant predictors
        # (the reference's top-N screening bound); the rest score cmi=0
        ntop = int(p["ntop"])
        probe_set = set(np.asarray(list(x))[np.argsort(-relevance)[:ntop]])

        # cmi probes
        nf = len(x)
        cmi_raw = np.zeros(nf)
        if protected:
            # fair infogram: gain of adding x_i to the protected set
            base = _probe_logloss(frame, protected, y, ntrees, depth, seed)
            for i, f in enumerate(x):
                if f not in probe_set:
                    continue
                li = _probe_logloss(frame, protected + [f], y, ntrees,
                                    depth, seed)
                cmi_raw[i] = max(base - li, 0.0)
                job.update(0.6 / nf, f"cmi {f}")
        else:
            # core infogram: drop-one loss increase given the rest
            base = _probe_logloss(frame, x, y, ntrees, depth, seed)
            for i, f in enumerate(x):
                if f not in probe_set:
                    continue
                rest = [c for c in x if c != f]
                if not rest:
                    cmi_raw[i] = 1.0
                    continue
                li = _probe_logloss(frame, rest, y, ntrees, depth, seed)
                cmi_raw[i] = max(li - base, 0.0)
                job.update(0.6 / nf, f"cmi {f}")
        cmi = cmi_raw / max(cmi_raw.max(), 1e-12)

        rel_thr = float(p["relevance_index_threshold"])
        if float(p["total_information_threshold"]) >= 0:
            rel_thr = float(p["total_information_threshold"])
        saf_thr = float(p["safety_index_threshold"])
        if float(p["net_information_threshold"]) >= 0:
            saf_thr = float(p["net_information_threshold"])

        table = []
        for i, f in enumerate(x):
            adm = bool(relevance[i] >= rel_thr and cmi[i] >= saf_thr)
            table.append({
                "column": f, "relevance": float(relevance[i]),
                "cmi": float(cmi[i]), "cmi_raw": float(cmi_raw[i]),
                "admissible": adm,
                "admissible_index": float(
                    np.hypot(relevance[i], cmi[i]) / np.sqrt(2.0)),
            })
        table.sort(key=lambda r: -r["admissible_index"])
        admissible = [r["column"] for r in table if r["admissible"]][:ntop]

        output = {"category": infer_category(frame, y), "response": y,
                  "names": list(x), "domain": frame.col(y).domain,
                  "infogram_table": table,
                  "admissible_features": admissible,
                  "protected_columns": protected}
        return InfogramModel(p, output)
