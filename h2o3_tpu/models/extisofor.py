"""Extended Isolation Forest — random-hyperplane isolation trees.

Reference: hex/tree/isoforextended/ (~800 LoC) — like IsolationForest
but each split is a random oblique hyperplane ``x·w < b`` with
``extension_level + 1`` nonzero components in w (extension_level = 0
reduces to axis-parallel splits), removing the axis-aligned scoring
bias (Hariri et al.). Scores share the c(n) normalization with
IsolationForest.

TPU redesign: a tree level is one [N, F]·[F] contraction per node batch
— node normals are gathered by the row's node id and the projection is
a masked elementwise product-sum, so the whole forest is dense f32 math
with no gathers over data. The split offset b is drawn uniformly inside
the node sample's projection range, approximated by the global
projection range per node normal (host-free, one pass).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh


class ExtTree(NamedTuple):
    normals: jax.Array    # [D, Lmax, F]
    offsets: jax.Array    # [D, Lmax]
    is_split: jax.Array   # [D, Lmax] bool
    leaf: jax.Array       # [2^D] c(count) correction


from h2o3_tpu.models.isofor import _avg_path_correction  # noqa: E402 (shared c(n))


@partial(jax.jit, static_argnames=("depth", "ext"))
def _grow_ext_tree(X, lo, hi, w, key, *, depth: int, ext: int):
    """One extended isolation tree. X: [N, F] standardized; lo/hi: [F]
    per-feature value ranges (split-offset support)."""
    mesh = get_mesh()
    N, F = X.shape
    Lmax = 2 ** (depth - 1) if depth > 0 else 1
    nid = jnp.zeros((N,), jnp.int32)
    normals = jnp.zeros((depth, Lmax, F), jnp.float32)
    offsets = jnp.zeros((depth, Lmax), jnp.float32)
    is_splits = jnp.zeros((depth, Lmax), bool)
    k = min(ext + 1, F)
    for d in range(depth):
        L = 2 ** d
        key, kn, km, kb = jax.random.split(key, 4)
        from h2o3_tpu.models.tree import _mtries_mask
        Wn = jax.random.normal(kn, (L, F))
        # keep exactly ext+1 random components per node
        Wn = jnp.where(_mtries_mask(km, L, F, k), Wn, 0.0)
        # offset b = w·p for a random point p in the value box
        pu = jax.random.uniform(kb, (L, F))
        pnt = lo[None, :] + pu * (hi - lo)[None, :]
        b = jnp.sum(Wn * pnt, axis=1)
        cnt = segment_sum(nid, w[:, None], n_nodes=L, mesh=mesh)[:, 0]
        split = cnt > 1.0
        normals = normals.at[d, :L].set(Wn)
        offsets = offsets.at[d, :L].set(b)
        is_splits = is_splits.at[d, :L].set(split)
        Wr = normals[d][nid]                     # [N, F]
        proj = jnp.sum(X * Wr, axis=1)
        goleft = jnp.where(is_splits[d][nid], proj < offsets[d][nid], True)
        nid = 2 * nid + jnp.where(goleft, 0, 1)
    leaf_cnt = segment_sum(nid, w[:, None], n_nodes=2 ** depth, mesh=mesh)[:, 0]
    return ExtTree(normals, offsets, is_splits,
                   _avg_path_correction(leaf_cnt))


def _ext_path_length(tree: ExtTree, X):
    N = X.shape[0]
    D = tree.normals.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    plen = jnp.zeros((N,), jnp.float32)
    for d in range(D):
        isp = tree.is_split[d][nid]
        plen = plen + isp.astype(jnp.float32)
        Wr = tree.normals[d][nid]
        proj = jnp.sum(X * Wr, axis=1)
        goleft = jnp.where(isp, proj < tree.offsets[d][nid], True)
        nid = 2 * nid + jnp.where(goleft, 0, 1)
    return plen + tree.leaf[nid]


@jax.jit
def _ext_forest_mean_length(stacked: ExtTree, X):
    def step(acc, tree):
        return acc + _ext_path_length(tree, X), None
    tot, _ = jax.lax.scan(step, jnp.zeros((X.shape[0],), jnp.float32), stacked)
    return tot / stacked.normals.shape[0]


def _feature_matrix(frame: Frame, names, means=None):
    """Dense [Npad, F] with NA → column-mean imputation."""
    cols = []
    out_means = []
    for i, n in enumerate(names):
        c = frame.col(n)
        v = c.numeric_view()
        if means is None:
            from h2o3_tpu.frame.rollups import rollups
            mu = rollups(c)["mean"] or 0.0
        else:
            mu = means[i]
        out_means.append(mu)
        cols.append(jnp.where(jnp.isnan(v), mu, v))
    return jnp.stack(cols, axis=1), out_means


class ExtendedIsolationForestModel(Model):
    algo = "extendedisolationforest"

    def __init__(self, params, output, forest: ExtTree, c_norm: float,
                 means, features):
        super().__init__(params, output)
        self.forest = forest
        self.c_norm = c_norm
        self.means = means
        self.features = features

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        X, _ = _feature_matrix(frame, self.features, self.means)
        ml = np.asarray(_ext_forest_mean_length(self.forest, X))[: frame.nrows]
        score = 2.0 ** (-ml / max(self.c_norm, 1e-12))
        return {"anomaly_score": score, "mean_length": ml}

    def model_performance(self, frame: Frame):
        raw = self._score_raw(frame)
        return {"mean_score": float(raw["anomaly_score"].mean()),
                "mean_length": float(raw["mean_length"].mean())}


class ExtendedIsolationForestEstimator(ModelBuilder):
    """h2o-py H2OExtendedIsolationForestEstimator surface
    (h2o-py/h2o/estimators/extended_isolation_forest.py)."""

    algo = "extendedisolationforest"
    supervised = False

    DEFAULTS = dict(
        ntrees=100, sample_size=256, extension_level=0, seed=-1,
        ignored_columns=None, score_tree_interval=0,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(
                f"unknown ExtendedIsolationForest params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        x = [n for n in x if not frame.col(n).is_categorical] or list(x)
        X, means = _feature_matrix(frame, x)
        ext = int(p["extension_level"])
        if not 0 <= ext <= len(x) - 1:
            raise ValueError(
                f"extension_level must be in [0, {len(x) - 1}]")
        lo = jnp.min(X, axis=0)
        hi = jnp.max(X, axis=0)
        w = frame.valid_weights()
        n = frame.nrows
        psi = int(p["sample_size"])
        bag_rate = min(1.0, psi / max(n, 1))
        depth = int(np.ceil(np.log2(max(psi, 2))))
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xE1F
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        trees = []
        for t in range(ntrees):
            key, kb, kt = jax.random.split(key, 3)
            keep = jax.random.bernoulli(kb, bag_rate, shape=w.shape)
            trees.append(_grow_ext_tree(X, lo, hi,
                                        w * keep.astype(jnp.float32), kt,
                                        depth=depth, ext=ext))
            job.update(1.0 / ntrees, f"tree {t + 1}/{ntrees}")
        forest = ExtTree(*(jnp.stack([getattr(t, f) for t in trees])
                           for f in ExtTree._fields))
        c_norm = float(_avg_path_correction(jnp.asarray(float(psi))))
        output = {"category": ModelCategory.ANOMALY, "response": None,
                  "names": list(x), "domain": None}
        model = ExtendedIsolationForestModel(p, output, forest, c_norm,
                                             means, list(x))
        model.training_metrics = model.model_performance(frame)
        return model
