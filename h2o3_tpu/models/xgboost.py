"""XGBoost-compatible facade over the native histogram GBM.

Reference: h2o-extensions/xgboost (hex/tree/xgboost/XGBoost.java:43) —
in H2O the "XGBoost" algo converts Frames to DMatrix and drives the
native C++ library over JNI with a Rabit allreduce tracker
(rabit/RabitTrackerH2O.java). Per SURVEY §2.4 item 4 the whole native
subsystem collapses on TPU: our hist-GBM already IS the
histogram-method gradient booster with psum as the allreduce, so the
extension reduces to a parameter-translation layer (the reference's
own hist trees and ours share the XGBoost-style Newton-gain split
criterion, models/tree.py).

Param mapping (hex/schemas/XGBoostV3 names → GBM):
  ntrees/nrounds → ntrees          eta/learn_rate → learn_rate
  max_depth → max_depth            reg_lambda → reg_lambda
  subsample/sample_rate → sample_rate
  colsample_bytree/col_sample_rate_per_tree → col_sample_rate_per_tree
  min_rows/min_child_weight → min_rows
  max_bins → nbins                 gamma/min_split_improvement → m_s_i
Accepted-but-inert knobs (booster variants, DART, GPU ids) follow the
reference's behavior of ignoring what the backend doesn't support.
"""

from __future__ import annotations

from typing import Optional, Sequence

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.gbm import GBMEstimator
from h2o3_tpu.models.model import ModelBuilder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.xgboost")

_DIRECT = {"ntrees", "max_depth", "seed", "nfolds", "weights_column",
           "max_runtime_secs",
           "fold_column", "fold_assignment", "ignored_columns",
           "stopping_rounds", "stopping_metric", "stopping_tolerance",
           "distribution", "min_rows", "learn_rate", "sample_rate",
           "reg_lambda", "col_sample_rate_per_tree", "nbins",
           # H2O-parity checkpoint restart: the donor is the inner
           # GBMModel (the facade trains native hist-GBM), so ntrees
           # extension and the non-modifiable-knob validation flow
           # through models/gbm.py unchanged
           "checkpoint"}

_ALIASES = {
    "nrounds": "ntrees",
    "eta": "learn_rate",
    "learn_rate": "learn_rate",
    "subsample": "sample_rate",
    "colsample_bytree": "col_sample_rate_per_tree",
    "min_child_weight": "min_rows",
    "max_bins": "nbins",
    "gamma": "min_split_improvement",
    "min_split_improvement": "min_split_improvement",
    "reg_lambda": "reg_lambda",
    "lambda_": "reg_lambda",
    "monotone_constraints": "monotone_constraints",
    "calibrate_model": "calibrate_model",
    "calibration_frame": "calibration_frame",
    "calibration_method": "calibration_method",
    "interaction_constraints": "interaction_constraints",
}

# accepted for wire compatibility, no effect on the TPU backend
_INERT = {"booster", "tree_method", "grow_policy", "backend", "gpu_id",
          "dmatrix_type", "categorical_encoding", "score_tree_interval",
          "colsample_bylevel", "col_sample_rate", "reg_alpha",
          "scale_pos_weight", "max_leaves", "sample_type",
          "normalize_type", "rate_drop", "one_drop", "skip_drop",
          "nthread", "save_matrix_directory",
          "max_delta_step"}


@register
class XGBoostEstimator(ModelBuilder):
    """h2o-py H2OXGBoostEstimator surface
    (h2o-py/h2o/estimators/xgboost.py) mapped onto the native TPU GBM."""

    algo = "xgboost"

    @classmethod
    def accepted_params(cls) -> set:
        return _DIRECT | set(_ALIASES) | _INERT

    def __init__(self, **params):
        gbm_params = {}
        ignored = []
        for k, v in params.items():
            if k in _ALIASES:
                gbm_params[_ALIASES[k]] = v
            elif k in _DIRECT:
                gbm_params[k] = v
            elif k in _INERT:
                ignored.append(k)
            else:
                raise ValueError(f"unknown XGBoost param: {k}")
        if ignored:
            log.info("XGBoost params accepted but inert on TPU backend: %s",
                     sorted(ignored))
        self._gbm = GBMEstimator(**gbm_params)
        super().__init__(**params)

    def set_max_runtime(self, secs: float) -> None:
        self.params["max_runtime_secs"] = float(secs)
        self._gbm.params["max_runtime_secs"] = float(secs)

    def train(self, training_frame: Frame, y: Optional[str] = None,
              x: Optional[Sequence[str]] = None,
              validation_frame: Optional[Frame] = None,
              background: bool = False, dest_key: Optional[str] = None):
        model = self._gbm.train(training_frame, y=y, x=x,
                                validation_frame=validation_frame,
                                background=background, dest_key=dest_key)
        if not background:
            model.output["facade"] = "xgboost"
        return model
