"""Word2Vec — skip-gram with hierarchical softmax, TPU-batched.

Reference: hex/word2vec/Word2Vec.java:15 + WordVectorTrainer.java
(HOGWILD skip-gram over word chunks) + HBWTree.java (Huffman binary
tree for the hierarchical softmax). Input contract is the reference's:
one string column of pre-tokenized words, sentences delimited by NA
rows; params vec_size / window_size / epochs / min_word_freq /
init_learning_rate / sent_sample_rate; outputs word vectors, synonym
search, and transform(frame, aggregate_method=NONE|AVERAGE).

TPU redesign: vocabulary + Huffman coding happen once on host; training
runs as jitted mini-batches — for a batch of (center, context) pairs the
HS loss is a masked sum over the context word's tree path, and
jax.grad's scatter-adds update the two embedding matrices. The
reference's per-node HOGWILD race (WordVectorTrainer) becomes exact
batched SGD; lr decays linearly like the reference's alpha schedule.
"""

from __future__ import annotations

import heapq
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.model import Model, ModelBuilder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.word2vec")


def _build_huffman(counts: np.ndarray):
    """Huffman tree over word counts → per-word (points, codes) paths
    (HBWTree.java role). Returns [V, Lmax] int32 points (internal-node
    ids), [V, Lmax] int8 codes, [V] path lengths."""
    V = len(counts)
    heap = [(int(c), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = np.zeros(2 * V - 1, dtype=np.int64)
    binary = np.zeros(2 * V - 1, dtype=np.int8)
    nxt = V
    while len(heap) > 1:
        c1, i1 = heapq.heappop(heap)
        c2, i2 = heapq.heappop(heap)
        parent[i1] = nxt
        parent[i2] = nxt
        binary[i2] = 1
        heapq.heappush(heap, (c1 + c2, nxt))
        nxt += 1
    root = nxt - 1
    paths, codes = [], []
    for wi in range(V):
        pt, cd = [], []
        node = wi
        while node != root:
            pt.append(parent[node] - V)   # internal node id in [0, V-1)
            cd.append(binary[node])
            node = parent[node]
        paths.append(pt[::-1])
        codes.append(cd[::-1])
    Lmax = max((len(p) for p in paths), default=1)
    P = np.zeros((V, Lmax), dtype=np.int32)
    C = np.zeros((V, Lmax), dtype=np.int8)
    M = np.zeros((V, Lmax), dtype=bool)
    for i, (pt, cd) in enumerate(zip(paths, codes)):
        P[i, : len(pt)] = pt
        C[i, : len(cd)] = cd
        M[i, : len(pt)] = True
    return P, C, M


@partial(jax.jit, donate_argnums=(0, 1))
def _sgd_step(W_in, W_out, centers, points, codes, mask, lr):
    """One skip-gram HS mini-batch step (WordVectorTrainer fprop/bprop)."""

    def loss_fn(win, wout):
        v = win[centers]                        # [B, D]
        u = wout[points]                        # [B, L, D]
        dots = jnp.einsum("bd,bld->bl", v, u)
        # code 0 → target 1 (go left), code 1 → target 0
        sgn = 1.0 - 2.0 * codes
        logp = jax.nn.log_sigmoid(sgn * dots)
        return -jnp.sum(jnp.where(mask, logp, 0.0)) / centers.shape[0]

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(W_in, W_out)
    W_in = W_in - lr * grads[0]
    W_out = W_out - lr * grads[1]
    return W_in, W_out, loss


class Word2VecModel(Model):
    algo = "word2vec"

    def __init__(self, params, output, vectors: np.ndarray,
                 vocab: List[str]):
        super().__init__(params, output)
        self.vectors = vectors       # [V, D] float32
        self.vocab = vocab
        self._index = {w: i for i, w in enumerate(vocab)}

    def find_synonyms(self, word: str, count: int = 20) -> Dict[str, float]:
        """Cosine-similarity neighbors (Word2VecModel.findSynonyms)."""
        if word not in self._index:
            return {}
        v = self.vectors[self._index[word]]
        norms = np.linalg.norm(self.vectors, axis=1) * \
            max(np.linalg.norm(v), 1e-12)
        sims = self.vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            if self.vocab[i] == word:
                continue
            out[self.vocab[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "NONE") -> Frame:
        """Embed a words column (Word2VecModel.transform): NONE → one
        vector row per word; AVERAGE → mean vector per NA-delimited
        sequence."""
        from h2o3_tpu.models.generic import _frame_raw_columns
        words = _frame_raw_columns(frame, [frame.names[0]])[frame.names[0]]
        D = self.vectors.shape[1]
        if aggregate_method.upper() == "NONE":
            out = np.full((len(words), D), np.nan, dtype=np.float32)
            for i, w in enumerate(words):
                j = self._index.get(w if isinstance(w, str) else None)
                if j is not None:
                    out[i] = self.vectors[j]
        else:  # AVERAGE
            rows, acc, cnt = [], np.zeros(D, np.float32), 0
            seen_tokens = False
            for w in words:
                if w is None or (isinstance(w, float) and np.isnan(w)):
                    rows.append(acc / cnt if cnt else np.full(D, np.nan))
                    acc, cnt, seen_tokens = np.zeros(D, np.float32), 0, False
                    continue
                seen_tokens = True
                j = self._index.get(w)
                if j is not None:
                    acc = acc + self.vectors[j]
                    cnt += 1
            if seen_tokens:   # flush only an unterminated trailing sentence
                rows.append(acc / cnt if cnt else np.full(D, np.nan))
            out = np.stack(rows)
        return Frame.from_numpy({f"C{i + 1}": out[:, i] for i in range(D)})

    def to_frame(self) -> Frame:
        """Word → vector frame (Word2VecModel.toFrame)."""
        cols = {"Word": np.asarray(self.vocab, dtype=object)}
        for i in range(self.vectors.shape[1]):
            cols[f"V{i + 1}"] = self.vectors[:, i]
        return Frame.from_numpy(cols, categorical=["Word"])

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("use transform()/find_synonyms()")

    def model_performance(self, frame: Frame):
        return None


@register
class Word2VecEstimator(ModelBuilder):
    """h2o-py H2OWord2vecEstimator surface
    (h2o-py/h2o/estimators/word2vec.py)."""

    algo = "word2vec"
    supervised = False

    DEFAULTS = dict(
        vec_size=100, window_size=5, sent_sample_rate=1e-3, epochs=5,
        min_word_freq=5, init_learning_rate=0.025, seed=-1,
        # small mini-batches: the reference's WordVectorTrainer applies one
        # HOGWILD update per (center, context) pair, so embedding quality
        # tracks sequential update count — large batches collapse a small
        # corpus into too few SGD steps for topics to separate
        batch_size=64, ignored_columns=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown Word2Vec params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def resolve_x(self, frame, x, y):
        return list(frame.names)   # the words column is the input

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        from h2o3_tpu.models.generic import _frame_raw_columns
        words = _frame_raw_columns(frame, [frame.names[0]])[frame.names[0]]
        # vocabulary over non-NA tokens
        toks = [w for w in words
                if isinstance(w, str)]
        uniq, counts = np.unique(np.asarray(toks, dtype=object),
                                 return_counts=True)
        keep = counts >= int(p["min_word_freq"])
        vocab = [str(u) for u in uniq[keep]]
        vcount = counts[keep].astype(np.int64)
        if len(vocab) < 2:
            raise ValueError("word2vec needs >= 2 vocabulary words "
                             "(after min_word_freq)")
        index = {w: i for i, w in enumerate(vocab)}
        total = vcount.sum()

        # sentences → id sequences with frequent-word subsampling
        # (WordVectorTrainer sent_sample_rate semantics)
        rng = np.random.RandomState(int(p["seed"]) if int(p["seed"]) >= 0
                                    else 0xABCD)
        samp = float(p["sent_sample_rate"])
        freq = vcount / total
        keep_prob = (np.minimum(1.0, (np.sqrt(freq / samp) + 1) * samp / freq)
                     if samp > 0 else np.ones_like(freq))
        sentences: List[List[int]] = []
        cur: List[int] = []
        for w in words:
            if not isinstance(w, str):
                if cur:
                    sentences.append(cur)
                cur = []
                continue
            j = index.get(w)
            if j is None:
                continue
            if keep_prob[j] >= 1.0 or rng.rand() < keep_prob[j]:
                cur.append(j)
        if cur:
            sentences.append(cur)

        P, C, M = _build_huffman(vcount)
        V, D = len(vocab), int(p["vec_size"])
        key = jax.random.PRNGKey(abs(int(p["seed"])) or 7)
        W_in = (jax.random.uniform(key, (V, D), jnp.float32) - 0.5) / D
        W_out = jnp.zeros((max(V - 1, 1), D), jnp.float32)
        P_dev, C_dev, M_dev = (jnp.asarray(P), jnp.asarray(C, jnp.float32),
                               jnp.asarray(M))

        # (center, context) pair list per epoch
        win = int(p["window_size"])
        centers, contexts = [], []
        for sent in sentences:
            L = len(sent)
            for i, c in enumerate(sent):
                for j in range(max(0, i - win), min(L, i + win + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(sent[j])
        if not centers:
            raise ValueError("no training pairs (sentences too short?)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        B = int(p["batch_size"])
        lr0 = float(p["init_learning_rate"])
        epochs = int(p["epochs"])
        n_pairs = len(centers)
        steps_total = max(epochs * ((n_pairs + B - 1) // B), 1)
        step = 0
        loss_hist = []
        for ep in range(epochs):
            perm = rng.permutation(n_pairs)
            for s in range(0, n_pairs, B):
                idx = perm[s: s + B]
                if len(idx) < B:    # pad to static shape (repeat wraps)
                    idx = np.concatenate([idx, perm[: B - len(idx)]])
                lr = lr0 * max(1.0 - step / steps_total, 1e-4)
                W_in, W_out, loss = _sgd_step(
                    W_in, W_out, jnp.asarray(centers[idx]),
                    P_dev[contexts[idx]], C_dev[contexts[idx]],
                    M_dev[contexts[idx]], jnp.float32(lr))
                step += 1
            loss_hist.append(float(loss))
            job.update(1.0 / epochs, f"epoch {ep + 1}/{epochs}")

        output = {"category": "WordEmbedding", "response": None,
                  "names": list(frame.names), "domain": None,
                  "vocab_size": V, "vec_size": D,
                  "epoch_loss": loss_hist}
        return Word2VecModel(p, output, np.asarray(W_in), vocab)
