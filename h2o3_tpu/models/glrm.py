"""GLRM — generalized low-rank models via alternating minimization.

Reference: hex/glrm/GLRM.java:52 — X ≈ A·Y with per-column losses and
regularizers on A (row factors) and Y (archetypes); alternating proximal
updates (updateX/updateY MRTasks), init via SVD/PlusPlus.

TPU redesign: with quadratic loss both half-steps are ridge solves that
map to MXU matmuls:
  A ← X Yᵀ (Y Yᵀ + γ_x I)⁻¹      (row-sharded; each row independent)
  Y ← (AᵀA + γ_y I)⁻¹ Aᵀ X       (AᵀA/AᵀX are psum-reduced Grams)
L1 regularizers apply as soft-threshold proximal steps after the solve;
NonNegative projects. Missing cells carry weight 0 (the reference's NA
handling), implemented with a per-cell observation mask — updates then
use 3 masked-matmul Grams per side instead of the closed form.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory
from h2o3_tpu.parallel.mesh import get_mesh, row_sharding


def _prox(M, reg: str, gamma: float):
    if reg == "l1":
        return jnp.sign(M) * jnp.maximum(jnp.abs(M) - gamma, 0.0)
    if reg == "nonnegative":
        return jnp.maximum(M, 0.0)
    return M   # none / quadratic (handled in the ridge solve)


def _solve_A(Xd, mask, Y, k: int, lam: float):
    """Per-row masked ridge: (Y M_r Yᵀ + λI) a_r = Y M_r x_r, batched."""
    YM = jnp.einsum("kp,np->nkp", Y, mask)            # [N,k,P]
    G = jnp.einsum("nkp,jp->nkj", YM, Y)              # [N,k,k]
    G = G + lam * jnp.eye(k, dtype=jnp.float32)[None]
    b = jnp.einsum("nkp,np->nk", YM, Xd)
    return jnp.linalg.solve(G, b[..., None])[..., 0]


@partial(jax.jit, static_argnames=("k", "regx", "regy", "gx", "gy"))
def _als_step(Xd, mask, A, Y, *, k: int, regx: str, regy: str,
              gx: float, gy: float):
    """One alternating step with per-cell observation mask."""
    lam_x = gx if regx == "quadratic" else 1e-6
    lam_y = gy if regy == "quadratic" else 1e-6
    A = _prox(_solve_A(Xd, mask, Y, k, lam_x), regx, gx)
    # --- Y update: per-column ridge (columns independent given mask).
    AM = jnp.einsum("nk,np->nkp", A, mask)            # [N,k,P]
    Gy = jnp.einsum("nkp,nj->pkj", AM, A)             # [P,k,k] psum'd by XLA
    Gy = Gy + lam_y * jnp.eye(k, dtype=jnp.float32)[None]
    by = jnp.einsum("nkp,np->pk", AM, Xd)             # [P,k]
    Ycols = jnp.linalg.solve(Gy, by[..., None])[..., 0]   # [P,k]
    Y = _prox(Ycols.T, regy, gy)
    # objective on observed cells
    R = (Xd - A @ Y) * mask
    obj = jnp.sum(R * R)
    return A, Y, obj


def _cell_mask(frame: Frame, di) -> jax.Array:
    """[Npad, P] observation mask: 0 on padding rows and NA cells."""
    n = frame.nrows
    N = di.X.shape[0]
    mask = np.ones((N, di.P), np.float32)
    mask[n:] = 0.0
    ptr = 0
    for i, name in enumerate(di.names):
        c = frame.col(name)
        width = len(di.domains[i] or []) if di.is_cat[i] else 1
        na = _fetch_np(c.na_mask)
        if na.any():
            mask[na, ptr:ptr + width] = 0.0
        ptr += width
    return jax.device_put(mask, row_sharding(get_mesh()))


class GLRMModel(Model):
    algo = "glrm"

    def __init__(self, params, output, Y, di_stats, features, transform):
        super().__init__(params, output)
        self.Y = Y                       # [k, P] archetypes
        self.di_stats = di_stats
        self.features = features
        self.transform = transform

    def _design(self, frame: Frame):
        return build_datainfo(frame, self.features,
                              standardize=(self.transform == "standardize"),
                              use_all_factor_levels=True,
                              stats_override=self.di_stats)

    def _factorize(self, frame: Frame):
        """Masked A-solve on a new frame: imputed NA cells stay excluded."""
        di = self._design(frame)
        mask = _cell_mask(frame, di)
        k = self.Y.shape[0]
        A = _solve_A(di.X, mask, self.Y, k, 1e-6)
        return di, A

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        _, A = self._factorize(frame)
        A = np.asarray(A)[: frame.nrows]
        return {f"Arch{i + 1}": A[:, i] for i in range(A.shape[1])}

    def reconstruct(self, frame: Frame) -> Frame:
        di, A = self._factorize(frame)
        R = np.asarray(A @ self.Y)[: frame.nrows]
        return Frame.from_numpy({n: R[:, i]
                                 for i, n in enumerate(di.coef_names)})

    def model_performance(self, frame: Frame):
        return self.training_metrics


class GLRMEstimator(ModelBuilder):
    """h2o-py H2OGeneralizedLowRankEstimator-compatible surface."""

    algo = "glrm"
    supervised = False

    DEFAULTS = dict(
        k=1, loss="Quadratic", regularization_x="None",
        regularization_y="None", gamma_x=0.0, gamma_y=0.0,
        max_iterations=50, transform="none", init="SVD", seed=-1,
        ignored_columns=None, recover_svd=False,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown GLRM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        transform = str(p["transform"]).lower()
        di = build_datainfo(frame, x, standardize=(transform == "standardize"),
                            use_all_factor_levels=True)
        k = min(int(p["k"]), di.P)
        n = frame.nrows
        N = di.X.shape[0]
        # observation mask: padding rows 0; NA cells 0 (NAs were imputed in
        # the design matrix, so recover the cell mask from source columns)
        mask = _cell_mask(frame, di)

        regx = str(p["regularization_x"]).lower()
        regy = str(p["regularization_y"]).lower()
        gx, gy = float(p["gamma_x"]), float(p["gamma_y"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0x6124
        key = jax.random.PRNGKey(seed)

        if str(p["init"]).upper() == "SVD":
            from h2o3_tpu.ops.gram import gram
            xtx, _, _ = gram(di.X, frame.valid_weights(),
                             jnp.zeros(N, jnp.float32), mesh=mesh)
            _, evecs = jnp.linalg.eigh(xtx)
            Y = evecs[:, ::-1][:, :k].T
        else:
            Y = 0.1 * jax.random.normal(key, (k, di.P), jnp.float32)
        A = jnp.zeros((N, k), jnp.float32)

        prev = np.inf
        obj = np.inf
        iters = int(p["max_iterations"])
        for it in range(iters):
            A, Y, obj_d = _als_step(di.X, mask, A, Y, k=k, regx=regx,
                                    regy=regy, gx=gx, gy=gy)
            obj = float(obj_d)
            job.update(1.0 / iters, f"iter {it + 1}: obj={obj:.4g}")
            if prev - obj < 1e-6 * max(abs(prev), 1.0):
                break
            prev = obj

        output = {"category": ModelCategory.DIMREDUCTION, "response": None,
                  "names": list(x), "domain": None,
                  "archetypes": np.asarray(Y).tolist(),
                  "coef_names": di.coef_names,
                  "objective": obj, "iterations": it + 1}
        model = GLRMModel(p, output, Y, stats_of(di), list(x), transform)
        nobs = float(np.asarray(jnp.sum(mask)))
        model.training_metrics = ModelMetrics(
            "GLRM", n, obj / max(nobs, 1.0), objective=obj)
        return model
