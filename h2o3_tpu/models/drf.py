"""DRF — distributed random forest on the shared tree machinery.

Reference: hex/tree/drf/DRF.java:30 on the SharedTree skeleton.
Differences from GBM that this file reproduces:
- each tree is an independent regression tree on the raw response
  (indicator per class for classification), trained on a bagged row
  sample (sample_rate, default 0.632) — no shrinkage, no margins;
- per-NODE column subsampling of exactly `mtries` columns
  (DRF.java mtries: -1 → sqrt(p) classification / p/3 regression);
- prediction = average of per-tree leaf means (votes);
- training metrics are OOB: every row is scored only by the trees whose
  bag excluded it (DRF.java OOB scoring via Sample/Score).

TPU redesign: the whole forest is ONE compiled ``lax.scan`` over trees
(`_bag_scan`) — per tree: bag mask, grow_tree with (g=-y, h=1) so the
Newton leaf value is the bag-weighted mean of y, and OOB accumulator
updates — all on device; rows stay sharded on the mesh 'data' axis
throughout, and one model costs one dispatch.
"""

from __future__ import annotations

import time

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.binning import BinnedMatrix, bin_frame, rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   adapt_domain, checkpoint_error,
                                   infer_category, resolve_checkpoint_model,
                                   validate_checkpoint_params)
from h2o3_tpu.models.tree import (Tree, TreeParams, bucket_depth,
                                  exact_f32_for, grow_tree, predict_forest,
                                  scalars_of, stack_trees)
from h2o3_tpu.ops import pallas as pallas_ops
from h2o3_tpu.parallel.mesh import get_mesh, row_sharding
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.drf")

MAX_COMPLETE_DEPTH = 14  # complete-tree layout: histograms are 2^d·F·B·3


@partial(jax.jit,
         static_argnames=("tp", "sample_rate", "mtries", "n_class",
                          "ntrees"))
def _bag_scan(bins, nb, ys, w, key, depth_limit, *, tp: TreeParams,
              sample_rate: float, mtries: int, n_class: int, ntrees: int):
    """The whole forest as ONE compiled ``lax.scan`` over trees.

    The per-tree Python loop cost one dispatch + one host gains sync per
    tree — leave-one-out CV (pyunit_cv_carsRF boundary: nfolds == nrows)
    multiplied that into 20K tunnel round trips and a 600s timeout. The
    scan leaves one dispatch per MODEL. The key chain reproduces the
    sequential `key, sub = split(key)` of the loop exactly, so forests
    are bit-identical to the unfused path."""
    N = w.shape[0]
    oob_sum = jnp.zeros((N, n_class), jnp.float32)
    oob_cnt = jnp.zeros((N,), jnp.float32)

    def gen(carry, _):
        k, s = jax.random.split(carry)
        return k, s

    key_out, subs = jax.lax.scan(gen, key, None, length=ntrees)

    def step(carry, sub):
        osum, ocnt = carry
        tr, osum, ocnt, gains = _bag_body(
            bins, nb, ys, w, osum, ocnt, sub, depth_limit, tp=tp,
            sample_rate=sample_rate, mtries=mtries, n_class=n_class)
        return (osum, ocnt), (tr, gains)

    (oob_sum, oob_cnt), (trees, gains) = jax.lax.scan(
        step, (oob_sum, oob_cnt), subs)
    # [T, K, ...] per-scan-step stacked class trees → flat [T*K, ...]
    forest = Tree(*(a.reshape((-1,) + a.shape[2:]) for a in trees))
    # key_out: the evolved key chain — the chunked capped path threads
    # it so chunked and single-scan forests are bit-identical for the
    # same seed (a NON-binding max_runtime_secs must not change results)
    return forest, oob_sum, oob_cnt, jnp.sum(gains, axis=0), key_out


def _bag_body(bins, nb, ys, w, oob_sum, oob_cnt, key, depth_limit, *,
              tp: TreeParams, sample_rate: float, mtries: int,
              n_class: int):
    mesh = get_mesh()
    sc = scalars_of(tp)._replace(depth_limit=depth_limit)
    kb, kc1, kc2, kt = jax.random.split(key, 4)
    keep = jax.random.bernoulli(kb, sample_rate, shape=w.shape)
    wbag = w * keep.astype(jnp.float32)
    oob = (w > 0) & ~keep
    F = bins.shape[1]
    # per-tree column sampling (col_sample_rate_per_tree), one col forced
    if tp.col_sample_rate < 1.0:
        col_mask = (jax.random.bernoulli(kc1, tp.col_sample_rate, (F,))
                    | (jnp.arange(F) == jax.random.randint(kc2, (), 0, F)))
    else:
        col_mask = jnp.ones((F,), bool)
    trees = []
    gains_tot = jnp.zeros((F,), jnp.float32)
    for k in range(n_class):
        kt, sub = jax.random.split(kt)
        yk = ys[:, k]
        # g=-y, h=1 ⇒ leaf value = Σ w·y / (Σ w + λ): the bagged leaf mean
        tree, nid, gains = grow_tree(bins, nb, wbag, -yk, jnp.ones_like(yk),
                                     col_mask, params=tp, mesh=mesh,
                                     mtries=mtries, key=sub, scalars=sc)
        trees.append(tree)
        gains_tot = gains_tot + gains
        pred = tree.leaf[nid]          # routing nid is bag-independent
        oob_sum = oob_sum.at[:, k].add(jnp.where(oob, pred, 0.0))
    oob_cnt = oob_cnt + oob.astype(jnp.float32)
    return stack_trees(trees), oob_sum, oob_cnt, gains_tot


class DRFModel(Model):
    algo = "drf"

    def __init__(self, params, output, forest: Tree, bm: BinnedMatrix,
                 ntrees: int):
        super().__init__(params, output)
        self.forest = forest           # [T*K, D, Lmax]
        self.bm = bm
        self.ntrees = ntrees

    def _mean_votes(self, bm: BinnedMatrix):
        """Per-class average tree output [N, K]."""
        B = self.bm.nbins_total
        K = max(1, self.output.get("nclasses", 1)
                if self.output["category"] != ModelCategory.REGRESSION else 1)
        if self.output["category"] == ModelCategory.BINOMIAL:
            K = 1
        T = self.forest.feat.shape[0] // K
        # explicit reciprocal multiply, NOT division: XLA rewrites
        # x / <constant> into x * reciprocal inside a jitted program
        # but keeps true division in eager mode, a 1-ULP drift that
        # breaks the serving bit-identity contract (README §Serving) —
        # with the multiply spelled out, both paths run the same op
        inv_t = jnp.float32(1.0 / T)
        outs = []
        for k in range(K):
            f = Tree(*(a.reshape((T, K) + a.shape[1:])[:, k]
                       for a in self.forest))
            outs.append(predict_forest(f, bm.bins, B) * inv_t)
        return jnp.stack(outs, axis=1)

    def _probs(self, bm: BinnedMatrix):
        cat = self.output["category"]
        votes = self._mean_votes(bm)
        if cat == ModelCategory.BINOMIAL:
            p1 = jnp.clip(votes[:, 0], 0.0, 1.0)
            return jnp.stack([1.0 - p1, p1], axis=1)
        s = jnp.sum(votes, axis=1, keepdims=True)
        return jnp.clip(votes, 0.0, 1.0) / jnp.maximum(s, 1e-12)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        bm = rebin_for_scoring(self.bm, frame)
        # the model's ONE compiled scoring program — the same
        # executable the serving tier dispatches, so row-payload
        # predictions match bit-for-bit (Model._serve_jit)
        return self._serve_finish(np.asarray(self._serve_jit()(bm.bins)),
                                  frame.nrows)

    def _score_dev(self, frame: Frame):
        """Device-resident holdout scoring for ml/cv.py light mode —
        see GBMModel._score_dev (one batched fetch per CV sweep instead
        of a blocking ~100ms tunnel sync per fold)."""
        bm = rebin_for_scoring(self.bm, frame)
        cat = self.output["category"]
        if cat == ModelCategory.REGRESSION:
            return self._mean_votes(bm)[:, 0]
        p = self._probs(bm)
        if cat == ModelCategory.BINOMIAL:
            return p[:, 1]
        return p

    def _serve_dev(self, bins):
        """Device half of the serving fast path (serving/engine.py jits
        this per row bucket): EXACTLY the device math of ``_score_raw``
        on a pre-binned matrix."""
        import types
        bm = types.SimpleNamespace(bins=bins)
        if self.output["category"] == ModelCategory.REGRESSION:
            return self._mean_votes(bm)
        return self._probs(bm)

    def _serve_finish(self, fetched: np.ndarray, n: int) -> Dict[str, np.ndarray]:
        """Host half of the serving fast path: the exact host tail of
        ``_score_raw`` applied to the fetched device output."""
        cat = self.output["category"]
        if cat == ModelCategory.REGRESSION:
            return {"predict": fetched[:n, 0]}
        p = fetched[:n]
        if cat == ModelCategory.BINOMIAL:
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (p[:, 1] >= t).astype(np.int32),
                    "p0": p[:, 0], "p1": p[:, 1]}
        out = {"predict": p.argmax(axis=1).astype(np.int32)}
        for k in range(p.shape[1]):
            out[f"p{k}"] = p[:, k]
        return out

    def predict_leaf_node_assignment(self, frame: Frame) -> Frame:
        """Per-tree terminal node ids (h2o-py predict_leaf_node_assignment
        with type=Node_ID); per-class columns T{t}.C{k} for multinomial."""
        from h2o3_tpu.models.tree import leaf_assignment_frame
        return leaf_assignment_frame(self, frame)

    def feature_frequencies(self, frame: Frame) -> Frame:
        """Per-row feature usage counts on decision paths
        (h2o-py model.feature_frequencies / SharedTreeModel)."""
        from h2o3_tpu.models.tree import feature_frequencies_frame
        return feature_frequencies_frame(self, frame)

    def predict_contributions(self, frame: Frame) -> Frame:
        """TreeSHAP contributions; rows sum to the (unclipped) averaged
        vote — the reference DRF contributions contract."""
        from h2o3_tpu.ml.shap import contributions_frame
        return contributions_frame(self, frame, scale=1.0 / self.ntrees)

    def model_performance(self, frame: Frame, mask_weights=None):
        """``mask_weights``: see GBMModel.model_performance (CV fast
        path holdout metrics on the parent frame)."""
        y = self.output["response"]
        bm = rebin_for_scoring(self.bm, frame)
        w = frame.valid_weights()
        wc = self.params.get("weights_column")
        if wc and wc in frame:
            v = frame.col(wc).numeric_view()
            w = w * jnp.where(jnp.isnan(v), 0.0, v)
        if mask_weights is not None:
            w = w * jnp.asarray(mask_weights, jnp.float32)
        cat = self.output["category"]
        if cat == ModelCategory.REGRESSION:
            yv = frame.col(y).numeric_view()
            w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
            yv = jnp.where(jnp.isnan(yv), 0.0, yv)
            return mm.regression_metrics(self._mean_votes(bm)[:, 0], yv, w)
        yv = adapt_domain(frame.col(y), self.output["domain"])
        yv = np.pad(yv, (0, bm.bins.shape[0] - frame.nrows), constant_values=-1)
        w = w * jnp.asarray((yv >= 0).astype(np.float32))
        yv = np.maximum(yv, 0)
        p = self._probs(bm)
        if cat == ModelCategory.BINOMIAL:
            return mm.binomial_metrics(p[:, 1], jnp.asarray(yv.astype(np.float32)), w)
        return mm.multinomial_metrics(p, jnp.asarray(yv), w,
                                      domain=self.output["domain"])

    @property
    def varimp_table(self) -> List:
        return self.output.get("varimp") or []


class DRFEstimator(ModelBuilder):
    """h2o-py H2ORandomForestEstimator-compatible surface."""

    cv_fold_masking = True   # ml/cv.py fast path: folds = masked weights

    algo = "drf"

    DEFAULTS = dict(
        max_runtime_secs=0.0,
        ntrees=50, max_depth=20, min_rows=1.0, nbins=20, nbins_cats=1024,
        mtries=-1, sample_rate=0.632, col_sample_rate_per_tree=1.0,
        min_split_improvement=1e-5, seed=-1, nfolds=0,
        weights_column=None, fold_column=None, fold_assignment="auto",
        keep_cross_validation_models=True,
        keep_cross_validation_predictions=False,
        keep_cross_validation_fold_assignment=False,
        ignored_columns=None, stopping_rounds=0, stopping_metric="auto",
        stopping_tolerance=1e-3, binomial_double_trees=False,
        distribution="auto", calibrate_model=False,
        calibration_frame=None, calibration_method="PlattScaling",
        histogram_type="auto", checkpoint=None,
    )

    # SharedTree checkpoint-non-modifiable parameters (hex/tree/
    # SharedTree CHECKPOINT_NON_MODIFIABLE_FIELDS + DRF's own knobs)
    CHECKPOINT_NON_MODIFIABLE = (
        "max_depth", "min_rows", "nbins", "nbins_cats", "sample_rate",
        "mtries", "histogram_type", "binomial_double_trees")

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown DRF params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        category = infer_category(frame, y)
        ht = str(p.get("histogram_type", "auto")).lower()
        ht = {"auto": "quantiles", "quantilesglobal": "quantiles",
              "uniformadaptive": "uniform"}.get(ht, ht)
        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        w = self._cv_masked_weights(w, frame)
        rc = frame.col(y)
        wh_host = self._host_weights(frame, y)     # host mirror of w
        resp_na_host = np.isnan(rc.to_numpy())
        if resp_na_host.any():
            w = w * jnp.asarray(np.pad(
                (~resp_na_host).astype(np.float32),
                (0, frame.nrows_padded - frame.nrows)))
        # checkpoint restart (SharedTree _checkpoint semantics): reuse
        # the donor's bin edges so its trees stay valid, continue the
        # PRNG key chain, and append trees up to the new ntrees
        ckpt = None
        ck = p.get("checkpoint")
        if ck is not None:
            ckpt = resolve_checkpoint_model("drf", ck, DRFModel)
            if ckpt.output["response"] != y:
                raise checkpoint_error(
                    "drf", "response_column",
                    "Field _response_column cannot be modified if "
                    "checkpoint is provided (checkpoint response "
                    f"mismatch: {ckpt.output['response']!r} vs {y!r})")
            if list(ckpt.bm.names) != list(x):
                raise checkpoint_error(
                    "drf", "ignored_columns",
                    "The predictor set cannot be modified if checkpoint "
                    "is provided (checkpoint feature set mismatch)")
            if ckpt.output["category"] != category:
                raise checkpoint_error(
                    "drf", "response_column",
                    "checkpoint model category mismatch "
                    f"({ckpt.output['category']} vs {category})")
            validate_checkpoint_params("drf", ckpt.params, p,
                                       self.CHECKPOINT_NON_MODIFIABLE)

        shared_bm = getattr(self, "_cv_shared_bm", None)
        if ckpt is not None:
            bm = rebin_for_scoring(ckpt.bm, frame)
        elif shared_bm is not None:
            bm = shared_bm
        else:
            bm = bin_frame(frame, x, nbins=p["nbins"],
                           nbins_cats=p["nbins_cats"], histogram_type=ht,
                           weights=wh_host)

        depth = int(p["max_depth"])
        # complete-tree layout: a level costs 2^d histogram node slots
        # whether or not rows reach them, so cap depth by the DATA size
        # too — the reference's depth-20 default on a 400-row pyunit
        # frame would otherwise build 8K-node histograms of emptiness.
        # log2(n)+3 leaves room for moderately unbalanced trees (a
        # min_rows=1 spine deeper than that is approximated, as it
        # already was by MAX_COMPLETE_DEPTH). Padded count keeps CV
        # folds on one compiled shape.
        # log2(n)+3 leaves room for moderately unbalanced trees; light
        # CV fold fits (near-LOO sweeps, models discarded after their
        # holdout scoring) drop to +1 — a complete tree of that depth
        # already has a slot per row, and the slack quadruples forest
        # HBM on pyunit-sized frames
        slack = 1 if getattr(self, "_cv_light", False) else 3
        data_cap = int(np.ceil(np.log2(max(frame.nrows_padded, 4)))) \
            + slack
        eff = min(depth, MAX_COMPLETE_DEPTH, data_cap)
        if eff < depth:
            log.warning("DRF max_depth=%d capped to %d (complete-tree TPU "
                        "layout, %d rows)", depth, eff, frame.nrows)
            depth = eff
        # compile at the depth BUCKET (never past the caps) and mask
        # splits beyond the actual depth — candidates of nearby depths
        # share one compiled forest program (tree.py DEPTH_BUCKETS)
        compile_depth = min(bucket_depth(depth), MAX_COMPLETE_DEPTH,
                            data_cap)
        F = len(x)
        mtries = int(p["mtries"])
        if mtries == -1:
            mtries = (max(1, int(np.sqrt(F)))
                      if category != ModelCategory.REGRESSION
                      else max(1, F // 3))
        elif mtries <= 0:
            mtries = F
        w, w_scale = self._normalize_uniform_weights(w, wh_host)

        tp = TreeParams(
            max_depth=compile_depth,
            min_rows=float(p["min_rows"]) / w_scale,
            learn_rate=1.0, reg_lambda=0.0,
            min_split_improvement=float(p["min_split_improvement"])
            / w_scale,
            col_sample_rate=float(p["col_sample_rate_per_tree"]),
            nbins_total=bm.nbins_total,
            cat_feats=tuple(bool(v) for v in bm.is_cat),
            exact_f32=exact_f32_for(bm),
            pallas=pallas_ops.resolve_tree_mode())

        # target matrix ys [Npad, K]: indicators for classification
        N = bm.bins.shape[0]
        if category == ModelCategory.REGRESSION:
            K = 1
            yv = np.nan_to_num(rc.to_numpy()).astype(np.float32)
            ys = np.pad(yv, (0, N - frame.nrows))[:, None]
            y_int = None
        else:
            codes = np.nan_to_num(rc.to_numpy()).astype(np.int32)  # host
            codes = np.pad(codes, (0, N - frame.nrows))
            K = 1 if category == ModelCategory.BINOMIAL else rc.cardinality
            if K == 1:
                ys = (codes == 1).astype(np.float32)[:, None]
            else:
                ys = (codes[:, None] == np.arange(K)[None, :]).astype(np.float32)
            y_int = jax.device_put(codes, row_sharding(mesh))
        ys = jax.device_put(ys, row_sharding(mesh))

        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xD2F
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        prior_T = 0
        if ckpt is not None:
            prior_T = ckpt.forest.feat.shape[0] // max(K, 1)
            if ntrees <= prior_T:
                raise checkpoint_error(
                    "drf", "ntrees",
                    f"If checkpoint is provided, ntrees ({ntrees}) must "
                    f"exceed the checkpoint model's tree count "
                    f"({prior_T})")
            # _bag_scan's key carry is split once per tree, so prior_T
            # host-side splits reproduce the evolved chain exactly: the
            # appended trees are bit-equal to trees prior_T.. of a
            # single longer run with the same seed
            for _ in range(prior_T):
                key, _sub = jax.random.split(key)
            ntrees = ntrees - prior_T
        output = {"category": category, "response": y, "names": list(x),
                  "nclasses": rc.cardinality if rc.is_categorical else 1,
                  "domain": rc.domain}
        # max_runtime_secs (Model.Parameters): graceful stop at a
        # 25-tree chunk boundary, keeping the forest built so far —
        # without a cap the forest trains as ONE fused scan (the LOO-CV
        # fast path needs exactly one dispatch per fold model)
        _cap = float(p.get("max_runtime_secs") or 0.0)
        if _cap > 0:
            _deadline = time.time() + _cap
            # chunk shrinks with per-tree cost so the deadline can bind
            # (see GBM: a 25-tree chunk at depth bucket >=10 outruns an
            # AutoML slice before the first boundary check)
            _cost = (2.0 ** tp.max_depth / 64.0) * (bm.nbins_total / 65.0) \
                * max(1.0, bm.bins.shape[0] / 5_242_880.0)
            _chunk = max(1, min(25, int(round(25.0 / max(_cost, 1.0)))))
            chunks, osum_acc, ocnt_acc, gains_acc = [], None, None, None
            done = 0
            while done < ntrees:
                kk = min(_chunk, ntrees - done)
                tr_c, osum, ocnt, g_c, key = _bag_scan(
                    bm.bins, bm.nbins, ys, w, key, jnp.int32(depth),
                    tp=tp, sample_rate=float(p["sample_rate"]),
                    mtries=mtries, n_class=K, ntrees=kk)
                chunks.append(tr_c)
                osum_acc = osum if osum_acc is None else osum_acc + osum
                ocnt_acc = ocnt if ocnt_acc is None else ocnt_acc + ocnt
                gains_acc = g_c if gains_acc is None else gains_acc + g_c
                done += kk
                job.update(kk / ntrees, f"tree {done}/{ntrees}")
                if time.time() > _deadline and done < ntrees:
                    log.info("max_runtime_secs: DRF stopping at %d/%d "
                             "trees", done, ntrees)
                    break
            forest = (chunks[0] if len(chunks) == 1 else
                      Tree(*(jnp.concatenate([getattr(c, f)
                                              for c in chunks])
                             for f in Tree._fields)))
            oob_sum, oob_cnt, gains_dev = osum_acc, ocnt_acc, gains_acc
            ntrees = done
        else:
            forest, oob_sum, oob_cnt, gains_dev, _ = _bag_scan(
                bm.bins, bm.nbins, ys, w, key, jnp.int32(depth), tp=tp,
                sample_rate=float(p["sample_rate"]), mtries=mtries,
                n_class=K, ntrees=ntrees)
            job.update(1.0, f"{ntrees} trees")
        if ckpt is not None:
            if ckpt.forest.feat.shape[1:] != forest.feat.shape[1:]:
                raise checkpoint_error(
                    "drf", "training_frame",
                    "checkpoint restart requires a compatible training "
                    "frame (donor tree layout "
                    f"{tuple(ckpt.forest.feat.shape[1:])} vs "
                    f"{tuple(forest.feat.shape[1:])})")
            forest = Tree(*(jnp.concatenate([getattr(ckpt.forest, f),
                                             getattr(forest, f)])
                            for f in Tree._fields))
            prior_oob = getattr(ckpt, "_oob", None)
            if prior_oob is not None and \
                    tuple(prior_oob[0].shape) == tuple(oob_sum.shape):
                # OOB accumulators continue: training metrics of the
                # combined forest are what one longer run would report
                oob_sum = oob_sum + jnp.asarray(prior_oob[0])
                oob_cnt = oob_cnt + jnp.asarray(prior_oob[1])
            else:
                log.warning("drf checkpoint: donor carries no matching "
                            "OOB accumulators; OOB training metrics "
                            "reflect only the appended trees")
            ntrees = ntrees + prior_T
        model = DRFModel(p, output, forest, bm, ntrees)
        if getattr(self, "_cv_light", False):
            # near-LOO CV fold fit (ml/cv.py): skip OOB metrics / varimp
            # / calibration — hundreds of folds of those frills (several
            # blocking device syncs each) were the pyunit_cv_carsRF
            # timeout; the fold model itself is discarded right after
            # its holdout scoring (its padded forest would otherwise
            # accumulate into ResourceExhausted). The merged-holdout CV
            # metric is the contract.
            model.output["default_threshold"] = 0.5
            model.output["varimp"] = []
            return model
        gains_total = np.asarray(gains_dev)
        # host-lowered OOB accumulators ride the model so a checkpoint=
        # restart can CONTINUE them (pickled device-independent)
        model._oob = (np.asarray(oob_sum), np.asarray(oob_cnt))

        # OOB training metrics (rows never out-of-bag drop out via weight)
        w_oob = w * (oob_cnt > 0).astype(jnp.float32)
        mean_oob = oob_sum / jnp.maximum(oob_cnt[:, None], 1.0)
        if category == ModelCategory.REGRESSION:
            yv = jnp.asarray(np.pad(np.nan_to_num(rc.to_numpy()).astype(np.float32),
                                    (0, N - frame.nrows)))
            model.training_metrics = mm.regression_metrics(
                mean_oob[:, 0], yv, w_oob)
        elif category == ModelCategory.BINOMIAL:
            p1 = jnp.clip(mean_oob[:, 0], 0.0, 1.0)
            model.training_metrics = mm.binomial_metrics(
                p1, (y_int == 1).astype(jnp.float32), w_oob)
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        else:
            s = jnp.sum(mean_oob, axis=1, keepdims=True)
            probs = jnp.clip(mean_oob, 0.0, 1.0) / jnp.maximum(s, 1e-12)
            model.training_metrics = mm.multinomial_metrics(
                probs, y_int, w_oob, domain=rc.domain)

        vi = gains_total
        order = np.argsort(-vi)
        tot = vi.sum() or 1.0
        model.output["varimp"] = [
            (x[i], float(vi[i]), float(vi[i] / max(vi.max(), 1e-12)),
             float(vi[i] / tot)) for i in order]
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        from h2o3_tpu.ml.calibration import maybe_calibrate
        maybe_calibrate(model, p, category)
        return model
