"""ANOVAGLM + ModelSelection — GLM wrapper algorithms.

Reference: hex/anovaglm/ANOVAGLM.java:1 (~1.1K LoC) — trains the GLM on
predictor subsets formed by dropping each term, derives type-III-style
significance from deviance differences (likelihood-ratio chi-square);
hex/modelselection/ (~1.9K LoC) — best-subset GLM search with modes
maxr / allsubsets / forward / backward, reporting the best model per
predictor-count.

TPU note: each candidate fit is one GLM (einsum Gram + solve per IRLS
step, models/glm.py), so a whole subset sweep is a sequence of small
jitted programs against the SAME row-sharded design columns; nothing new
moves host→device between candidates.
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.glm import GLMEstimator
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory, infer_category
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.model_selection")


def _chi2_sf(x: float, df: int) -> float:
    """Survival function of chi-square (regularized upper gamma)."""
    from scipy.stats import chi2
    return float(chi2.sf(max(x, 0.0), max(df, 1)))


def _fit_glm(frame, x, y, family, **kw) -> Model:
    return GLMEstimator(family=family, **kw).train(frame, y=y, x=list(x))


def _resid_deviance(m: Model, frame: Frame) -> float:
    mm = m.training_metrics
    d = mm.to_dict()
    if "mean_residual_deviance" in d:
        return d["mean_residual_deviance"] * d["nobs"]
    return d["logloss"] * d["nobs"] * 2.0


class ANOVAGLMModel(Model):
    algo = "anovaglm"

    def __init__(self, params, output, full_model: Model):
        super().__init__(params, output)
        self.full_model = full_model

    def _score_raw(self, frame):
        return self.full_model._score_raw(frame)

    def model_performance(self, frame):
        return self.full_model.model_performance(frame)

    @property
    def anova_table(self) -> List[dict]:
        return self.output["anova_table"]


class ANOVAGLMEstimator(ModelBuilder):
    """h2o-py H2OANOVAGLMEstimator surface
    (h2o-py/h2o/estimators/anovaglm.py). Likelihood-ratio ANOVA: each
    term's significance from the deviance gain of adding it last."""

    algo = "anovaglm"

    DEFAULTS = dict(
        family="auto", link=None, lambda_=0.0, alpha=0.0,
        standardize=True, max_iterations=50, tweedie_power=1.5,
        highest_interaction_term=2, seed=-1, nfolds=0,
        weights_column=None, fold_column=None, ignored_columns=None,
        fold_assignment="auto",
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        if "Lambda" in params:
            params["lambda_"] = params.pop("Lambda")
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown ANOVAGLM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        category = infer_category(frame, y)
        family = p["family"]
        if family == "auto":
            family = {"Binomial": "binomial",
                      "Regression": "gaussian"}.get(category)
            if family is None:
                raise ValueError(f"ANOVAGLM: unsupported category {category}")
        glm_kw = dict(lambda_=p["lambda_"], alpha=p["alpha"],
                      standardize=p["standardize"],
                      max_iterations=p["max_iterations"],
                      weights_column=p.get("weights_column"))

        # interaction terms up to highest_interaction_term via products
        terms: List[tuple] = [(n,) for n in x]
        if int(p["highest_interaction_term"]) >= 2:
            numeric = [n for n in x if not frame.col(n).is_categorical]
            terms += list(combinations(numeric, 2))

        work = frame
        term_cols: Dict[tuple, List[str]] = {}
        for t in terms:
            if len(t) == 1:
                term_cols[t] = [t[0]]
            else:
                nm = ":".join(t)
                if nm not in work:
                    import h2o3_tpu.frame.column as colmod
                    v = (work.col(t[0]).to_numpy()
                         * work.col(t[1]).to_numpy())
                    from h2o3_tpu.parallel import mesh as mesh_mod
                    c = colmod.column_from_numpy(
                        nm, v, work.nrows_padded, mesh_mod.row_sharding())
                    work.add_column(c)
                term_cols[t] = [nm]

        all_cols = [c for cols in term_cols.values() for c in cols]
        full = _fit_glm(work, all_cols, y, family, **glm_kw)
        dev_full = _resid_deviance(full, work)
        n_done = 0
        table: List[dict] = []
        for t in terms:
            reduced_cols = [c for c in all_cols if c not in term_cols[t]]
            red = _fit_glm(work, reduced_cols, y, family, **glm_kw)
            dev_red = _resid_deviance(red, work)
            # df of the term = number of expanded coefficients it adds
            df = (frame.col(t[0]).cardinality - 1
                  if len(t) == 1 and frame.col(t[0]).is_categorical
                  else 1)
            lr = max(dev_red - dev_full, 0.0)
            table.append({"term": ":".join(t), "df": df,
                          "deviance": lr, "p_value": _chi2_sf(lr, df)})
            n_done += 1
            job.update(1.0 / (len(terms) + 1), f"term {n_done}/{len(terms)}")

        output = {"category": category, "response": y, "names": list(x),
                  "domain": frame.col(y).domain, "anova_table": table,
                  "full_deviance": dev_full}
        model = ANOVAGLMModel(p, output, full)
        model.training_metrics = full.training_metrics
        return model


class ModelSelectionModel(Model):
    algo = "modelselection"

    def __init__(self, params, output, best_models: Dict[int, Model]):
        super().__init__(params, output)
        self.best_models = best_models

    def _score_raw(self, frame):
        k = max(self.best_models)
        return self.best_models[k]._score_raw(frame)

    def model_performance(self, frame):
        k = max(self.best_models)
        return self.best_models[k].model_performance(frame)

    def result(self) -> List[dict]:
        return self.output["best_per_size"]

    def coef(self, size: int) -> Dict[str, float]:
        return self.best_models[size].coefficients


class ModelSelectionEstimator(ModelBuilder):
    """h2o-py H2OModelSelectionEstimator surface
    (h2o-py/h2o/estimators/model_selection.py): best-subset GLM per
    predictor count, modes maxr/allsubsets/forward/backward."""

    algo = "modelselection"

    DEFAULTS = dict(
        mode="maxr", max_predictor_number=0, min_predictor_number=1,
        family="auto", link=None, lambda_=0.0, alpha=0.0,
        standardize=True, max_iterations=50, seed=-1, nfolds=0,
        weights_column=None, fold_column=None, ignored_columns=None,
        fold_assignment="auto", p_values_threshold=0.0,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        if "Lambda" in params:
            params["lambda_"] = params.pop("Lambda")
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(
                f"unknown ModelSelection params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _r2(self, m: Model) -> float:
        d = m.training_metrics.to_dict()
        return d.get("r2", -d.get("logloss", np.inf))

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        category = infer_category(frame, y)
        family = p["family"]
        if family == "auto":
            family = {"Binomial": "binomial",
                      "Regression": "gaussian"}.get(category, "gaussian")
        glm_kw = dict(lambda_=p["lambda_"], alpha=p["alpha"],
                      standardize=p["standardize"],
                      max_iterations=p["max_iterations"],
                      weights_column=p.get("weights_column"))
        mode = str(p["mode"]).lower()
        kmax = int(p["max_predictor_number"]) or len(x)
        kmax = min(kmax, len(x))
        kmin = max(1, int(p["min_predictor_number"]))

        best_models: Dict[int, Model] = {}
        best_sets: Dict[int, List[str]] = {}

        def fit(subset) -> Model:
            return _fit_glm(frame, list(subset), y, family, **glm_kw)

        if mode == "allsubsets":
            if len(x) > 16:
                raise ValueError("allsubsets limited to <=16 predictors")
            for k in range(kmin, kmax + 1):
                best, bs = None, None
                for sub in combinations(x, k):
                    m = fit(sub)
                    if best is None or self._r2(m) > self._r2(best):
                        best, bs = m, list(sub)
                best_models[k], best_sets[k] = best, bs
                job.update(1.0 / (kmax - kmin + 1), f"size {k}")
        elif mode == "backward":
            cur = list(x)
            m = fit(cur)
            if len(cur) <= kmax:
                best_models[len(cur)], best_sets[len(cur)] = m, list(cur)
            while len(cur) > kmin:
                best, bs = None, None
                for drop in cur:
                    sub = [c for c in cur if c != drop]
                    mm_ = fit(sub)
                    if best is None or self._r2(mm_) > self._r2(best):
                        best, bs = mm_, sub
                cur = bs
                if len(cur) <= kmax:
                    best_models[len(cur)], best_sets[len(cur)] = best, cur
                job.update(1.0 / len(x), f"size {len(cur)}")
        else:   # forward and maxr (maxr = forward + replacement sweep)
            cur: List[str] = []
            while len(cur) < kmax:
                best, bs = None, None
                for add in [c for c in x if c not in cur]:
                    sub = cur + [add]
                    mm_ = fit(sub)
                    if best is None or self._r2(mm_) > self._r2(best):
                        best, bs = mm_, sub
                cur = bs
                if mode == "maxr" and len(cur) > 1:
                    # replacement sweep: try swapping each member for each
                    # non-member while it improves (hex/modelselection maxr)
                    improved = True
                    while improved:
                        improved = False
                        for i_, member in enumerate(list(cur)):
                            for cand in [c for c in x if c not in cur]:
                                sub = list(cur)
                                sub[i_] = cand
                                mm_ = fit(sub)
                                if self._r2(mm_) > self._r2(best):
                                    best, cur, improved = mm_, sub, True
                if len(cur) >= kmin:
                    best_models[len(cur)] = best
                    best_sets[len(cur)] = list(cur)
                job.update(1.0 / kmax, f"size {len(cur)}")

        table = [{"size": k, "predictors": best_sets[k],
                  "r2": self._r2(best_models[k])}
                 for k in sorted(best_models)]
        output = {"category": category, "response": y, "names": list(x),
                  "domain": frame.col(y).domain, "best_per_size": table}
        model = ModelSelectionModel(p, output, best_models)
        kbest = max(best_models)
        model.training_metrics = best_models[kbest].training_metrics
        return model
