"""CoxPH — Cox proportional hazards with Efron/Breslow tie handling.

Reference: hex/coxph/CoxPH.java:28 (~2027 LoC) — counting-process
(start/stop) survival input, strata, Efron (default) or Breslow ties,
Newton iterations with per-iteration distributed accumulation MRTasks,
concordance + baseline hazard outputs.

TPU redesign: the partial log-likelihood needs risk-set sums
``sum_{j: start_j < t <= stop_j} w_j exp(eta_j)`` at every event time.
The reference accumulates these in per-chunk scatter loops; here all
risk-set structure (sort orders, tie groups, within-group event ranks,
per-group gather indices) is computed ONCE on host from the time columns
only, and the whole objective becomes gathers + ``jnp.cumsum`` +
``segment_sum`` over the row-sharded design matrix — so beta optimization
is jitted Newton steps with `jax.grad`/`jax.hessian` on a scalar
objective (P is small: tabular survival). Weighted Efron uses the
per-event-rank denominator ``log(R_g - (k/d_g) T_g)`` which reduces to
exact Efron for unit weights.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import Model, ModelBuilder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.coxph")


def _risk_structure(start: np.ndarray, stop: np.ndarray, event: np.ndarray,
                    strata: np.ndarray):
    """Host-side precomputation of all index structure for the partial
    likelihood (the part the reference recomputes in CoxPHTask each
    Newton pass — here it depends only on times, so once is enough).

    Returns dict of numpy arrays; all -1 indices mean "nothing to gather"
    (their gathered value is masked out).
    """
    n = len(stop)
    # sort rows by (stratum, -stop) → within-stratum suffix sums of
    # exp(eta) over {stop >= t} become prefix sums of the permuted array
    ord_stop = np.lexsort((-stop, strata))
    # same for start times: {start >= t}
    ord_start = np.lexsort((-start, strata))
    s_stop = strata[ord_stop]
    block_first_stop = np.r_[True, s_stop[1:] != s_stop[:-1]]
    block_id_stop = np.cumsum(block_first_stop) - 1
    s_start = strata[ord_start]
    block_first_start = np.r_[True, s_start[1:] != s_start[:-1]]

    # tie groups: unique (stratum, stop) among EVENT rows
    ev = np.flatnonzero(event > 0)
    if len(ev) == 0:
        raise ValueError("CoxPH requires at least one event")
    key = np.lexsort((stop[ev], strata[ev]))
    ev_sorted = ev[key]
    t_ev, s_ev = stop[ev_sorted], strata[ev_sorted]
    new_grp = np.r_[True, (t_ev[1:] != t_ev[:-1]) | (s_ev[1:] != s_ev[:-1])]
    gid_sorted = np.cumsum(new_grp) - 1
    G = gid_sorted[-1] + 1
    # rank of each event within its tie group (0-based) and group sizes
    rank_sorted = np.arange(len(ev_sorted)) - \
        np.maximum.accumulate(np.where(new_grp, np.arange(len(ev_sorted)), 0))
    d_g = np.bincount(gid_sorted, minlength=G).astype(np.float64)

    # per-row (full length) event group id / rank; non-events get group 0
    # with mask 0
    gid_row = np.zeros(n, np.int32)
    rank_row = np.zeros(n, np.int32)
    gid_row[ev_sorted] = gid_sorted
    rank_row[ev_sorted] = rank_sorted

    # per-group gather positions into the two sorted cumsum arrays:
    # R_g = (# rows with stop >= t_g within stratum) → last position in
    # ord_stop whose (stratum==s_g, stop >= t_g)
    grp_t = t_ev[new_grp]
    grp_s = s_ev[new_grp]
    # positions in stop order: count of rows with same stratum & stop>=t
    pos_stop = np.empty(G, np.int64)
    pos_start = np.empty(G, np.int64)
    # prefix: index of first row of each stratum in each order
    stratum_start_stop = {}
    for i in np.flatnonzero(block_first_stop):
        stratum_start_stop[s_stop[i]] = i
    stratum_start_start = {}
    for i in np.flatnonzero(block_first_start):
        stratum_start_start[s_start[i]] = i
    # counts per stratum
    for g in range(G):
        s = grp_s[g]
        t = grp_t[g]
        b0 = stratum_start_stop[s]
        blk = np.flatnonzero(s_stop == s)
        # stop sorted descending within stratum: rows with stop >= t
        cnt = np.searchsorted(-stop[ord_stop[blk]], -t, side="right")
        pos_stop[g] = b0 + cnt - 1  # inclusive prefix index; -1 if none
        if s in stratum_start_start:
            b1 = stratum_start_start[s]
            blk1 = np.flatnonzero(s_start == s)
            cnt1 = np.searchsorted(-start[ord_start[blk1]], -t, side="right")
            pos_start[g] = b1 + cnt1 - 1 if cnt1 > 0 else -1
        else:
            pos_start[g] = -1
        if pos_stop[g] < stratum_start_stop[s]:
            pos_stop[g] = -1

    # block starts for segmented cumsum: subtract cumsum at block start - 1
    blk_start_of_pos_stop = np.array(
        [stratum_start_stop[grp_s[g]] for g in range(G)], np.int64)
    blk_start_of_pos_start = np.array(
        [stratum_start_start.get(grp_s[g], 0) for g in range(G)], np.int64)

    return dict(
        ord_stop=ord_stop.astype(np.int32),
        ord_start=ord_start.astype(np.int32),
        gid_row=gid_row, rank_row=rank_row,
        d_g=d_g.astype(np.float32), n_groups=int(G),
        pos_stop=pos_stop.astype(np.int32),
        pos_start=pos_start.astype(np.int32),
        blk0_stop=blk_start_of_pos_stop.astype(np.int32),
        blk0_start=blk_start_of_pos_start.astype(np.int32),
    )


@partial(jax.jit, static_argnames=("n_groups", "efron"))
def _cox_nll(beta, X, w, event, gid_row, rank_row, d_g,
             ord_stop, ord_start, pos_stop, pos_start, blk0_stop, blk0_start,
             *, n_groups: int, efron: bool):
    """Negative weighted partial log-likelihood; one device program.

    Risk sums via segmented cumsum over the two sort orders; tie sums via
    one segment_sum keyed by tie-group id.
    """
    eta = X @ beta
    # center for numeric safety (invariant to partial likelihood)
    wsum = jnp.maximum(jnp.sum(w), 1e-12)
    eta = eta - jnp.sum(w * eta) / wsum
    r = w * jnp.exp(eta)

    def seg_prefix(order, pos, blk0):
        c = jnp.cumsum(r[order])
        tot = jnp.where(pos >= 0, c[jnp.maximum(pos, 0)], 0.0)
        head = jnp.where(blk0 > 0, c[jnp.maximum(blk0 - 1, 0)], 0.0)
        return tot - jnp.where(pos >= 0, head, 0.0)

    risk_stop = seg_prefix(ord_stop, pos_stop, blk0_stop)     # Σ r, stop>=t
    risk_start = seg_prefix(ord_start, pos_start, blk0_start)  # Σ r, start>=t
    R_g = risk_stop - risk_start                               # risk set sums

    # tie sums T_g = Σ over event rows in group of r
    evf = event.astype(r.dtype)
    T_g = jax.ops.segment_sum(r * evf, gid_row, num_segments=n_groups)

    Rg_row = R_g[gid_row]
    Tg_row = T_g[gid_row]
    dg_row = d_g[gid_row]
    if efron:
        frac = rank_row.astype(r.dtype) / jnp.maximum(dg_row, 1.0)
        denom = Rg_row - frac * Tg_row
    else:
        denom = Rg_row
    denom = jnp.maximum(denom, 1e-30)
    ll = jnp.sum(w * evf * (eta - jnp.log(denom)))
    return -ll


class CoxPHModel(Model):
    algo = "coxph"

    def __init__(self, params, output, coef: np.ndarray, di_stats: dict,
                 features: List[str]):
        super().__init__(params, output)
        self.coef = coef
        self.di_stats = di_stats
        self.features = features

    def _lp(self, frame: Frame):
        di = build_datainfo(frame, self.features, standardize=False,
                            use_all_factor_levels=False,
                            stats_override=self.di_stats)
        eta = di.X @ jnp.asarray(self.coef, jnp.float32)
        return eta - self.output["eta_mean"]

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        """lp (centered linear predictor), like the reference's predict."""
        return {"lp": np.asarray(self._lp(frame))[: frame.nrows]}

    def model_performance(self, frame: Frame):
        stop_c = self.params["stop_column"]
        y = self.output["response"]
        lp = np.asarray(self._lp(frame))[: frame.nrows]
        times = frame.col(stop_c).to_numpy()
        ev = frame.col(y).to_numpy().astype(float)
        c = concordance_index(times, ev, lp)
        n = int(np.isfinite(times).sum())
        return mm.ModelMetrics("CoxPH", n, float(np.mean(lp ** 2)),
                               concordance=c,
                               loglik=self.output.get("loglik"))


def concordance_index(time: np.ndarray, event: np.ndarray,
                      lp: np.ndarray, max_pairs: int = 4_000_000) -> float:
    """Harrell's C over comparable pairs (i an event, t_i < t_j); ties in
    lp count 1/2 (the reference's Concordance output)."""
    ok = np.isfinite(time) & np.isfinite(lp) & np.isfinite(event)
    time, event, lp = time[ok], event[ok], lp[ok]
    n = len(time)
    ev_idx = np.flatnonzero(event > 0)
    if len(ev_idx) == 0 or n < 2:
        return 0.5
    if len(ev_idx) * n > max_pairs:  # subsample events for bound work
        rng = np.random.RandomState(0)
        ev_idx = rng.choice(ev_idx, size=max(1, max_pairs // n),
                            replace=False)
    conc = ties = tot = 0.0
    for i in ev_idx:
        cmp_mask = time > time[i]
        m = cmp_mask.sum()
        if m == 0:
            continue
        conc += float((lp[i] > lp[cmp_mask]).sum())
        ties += float((lp[i] == lp[cmp_mask]).sum())
        tot += float(m)
    return float((conc + 0.5 * ties) / tot) if tot > 0 else 0.5


class CoxPHEstimator(ModelBuilder):
    """h2o-py H2OCoxProportionalHazardsEstimator surface
    (h2o-py/h2o/estimators/coxph.py). Response y = event indicator
    (0/1 or 2-level categorical); ``stop_column`` = event/censor time;
    optional ``start_column`` (counting-process) and ``stratify_by``."""

    algo = "coxph"
    supervised = True

    DEFAULTS = dict(
        start_column=None, stop_column=None, stratify_by=None,
        ties="efron", max_iterations=20, lre_min=9.0,
        weights_column=None, ignored_columns=None, nfolds=0,
        fold_column=None, seed=-1,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown CoxPH params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def resolve_x(self, frame, x, y):
        x = super().resolve_x(frame, x, y)
        drop = {self.params.get("start_column"),
                self.params.get("stop_column")}
        drop |= set(self.params.get("stratify_by") or [])
        return [n for n in x if n not in drop]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        stop_c = p["stop_column"]
        if stop_c is None:
            raise ValueError("CoxPH requires stop_column")
        n = frame.nrows

        stop = frame.col(stop_c).to_numpy()[:n].astype(np.float64)
        start = (frame.col(p["start_column"]).to_numpy()[:n].astype(np.float64)
                 if p["start_column"] else np.full(n, -np.inf))
        yc = frame.col(y)
        if yc.is_categorical:
            ev = _fetch_np(yc.data)[:n].astype(np.float64)
        else:
            ev = yc.to_numpy()[:n].astype(np.float64)
        ev = np.nan_to_num(ev)

        strata = np.zeros(n, np.int64)
        for sc in (p["stratify_by"] or []):
            c = frame.col(sc)
            codes = _fetch_np(c.data)[:n].astype(np.int64)
            strata = strata * max(c.cardinality, 1) + np.maximum(codes, 0)

        rs = _risk_structure(start, stop, ev, strata)

        di = build_datainfo(frame, x, standardize=False,
                            use_all_factor_levels=False)
        npad = di.X.shape[0]
        w = np.asarray(frame.valid_weights()).copy()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).to_numpy()
            w[:n] *= np.nan_to_num(wc, nan=0.0)
        ok = np.isfinite(stop) & np.isfinite(ev)
        w[:n] *= ok.astype(np.float32)

        def padded(a, fill=0):
            return jnp.asarray(np.pad(a, (0, npad - len(a)),
                                      constant_values=fill))

        args = (di.X, jnp.asarray(w), padded(ev.astype(np.float32)),
                padded(rs["gid_row"]), padded(rs["rank_row"]),
                jnp.asarray(rs["d_g"]),
                jnp.asarray(np.pad(rs["ord_stop"],
                                   (0, npad - n), constant_values=npad - 1)),
                jnp.asarray(np.pad(rs["ord_start"],
                                   (0, npad - n), constant_values=npad - 1)),
                jnp.asarray(rs["pos_stop"]), jnp.asarray(rs["pos_start"]),
                jnp.asarray(rs["blk0_stop"]), jnp.asarray(rs["blk0_start"]))
        # padding rows have w=0 so their exp(eta) never enters a cumsum
        # position that a group gathers (groups only index real rows)...
        # except through cumsum positions past n — guard: order arrays pad
        # with the LAST index repeated; r there is w*exp=0.

        efron = str(p["ties"]).lower() != "breslow"
        P = di.X.shape[1]
        nll = partial(_cox_nll, n_groups=rs["n_groups"], efron=efron)

        grad_fn = jax.jit(jax.grad(nll), static_argnames=())
        hess_fn = jax.jit(jax.hessian(nll))

        beta = jnp.zeros((P,), jnp.float32)
        loglik0 = -float(nll(beta, *args))
        loglik = loglik0
        for it in range(int(p["max_iterations"])):
            g = grad_fn(beta, *args)
            H = hess_fn(beta, *args)
            step = jnp.linalg.solve(H + 1e-6 * jnp.eye(P), g)
            # halving line search (reference Newton with step control)
            lam = 1.0
            f_old = -loglik
            for _ in range(10):
                cand = beta - lam * step
                f_new = float(nll(cand, *args))
                if np.isfinite(f_new) and f_new <= f_old:
                    break
                lam *= 0.5
            beta = beta - lam * step
            new_ll = -float(nll(beta, *args))
            job.update(1.0 / int(p["max_iterations"]), f"newton {it + 1}")
            if abs(new_ll - loglik) < 10.0 ** (-float(p["lre_min"])) * \
                    max(abs(loglik), 1.0):
                loglik = new_ll
                break
            loglik = new_ll

        H = np.asarray(hess_fn(beta, *args), np.float64)
        try:
            cov = np.linalg.inv(H + 1e-8 * np.eye(P))
            se = np.sqrt(np.maximum(np.diag(cov), 0.0))
        except np.linalg.LinAlgError:
            se = np.full(P, np.nan)

        beta_np = np.asarray(beta, np.float64)
        eta = np.asarray(di.X @ beta)[:n]
        wn = w[:n]
        eta_mean = float((eta * wn).sum() / max(wn.sum(), 1e-12))

        coef_table = [
            {"name": nm, "coef": float(b), "exp_coef": float(np.exp(b)),
             "se_coef": float(s),
             "z_coef": float(b / s) if s > 0 else float("nan")}
            for nm, b, s in zip(di.coef_names, beta_np, se)]

        # weighted design-column means: the reference MOJO derives
        # lpBase as coef . x_mean (CoxPHMojoModel.computeLpBase), and
        # by linearity coef . x_mean == eta_mean — recorded here so
        # export can emit x_mean_cat/x_mean_num without training data
        xmean = np.asarray(jnp.asarray(w) @ di.X, np.float64) / \
            max(float(np.sum(w)), 1e-12)
        output = {"category": "CoxPH", "response": y, "names": list(x),
                  "x_mean_design": [float(v) for v in xmean],
                  "coef_names": di.coef_names, "domain": None,
                  "loglik": loglik, "null_loglik": loglik0,
                  "lre": float(abs(loglik - loglik0)),
                  "coefficients_table": coef_table,
                  "n_events": int(ev[ok].sum()), "n": int(ok.sum()),
                  "eta_mean": eta_mean, "ties": p["ties"]}
        model = CoxPHModel(p, output, beta_np, stats_of(di), list(x))
        model.training_metrics = model.model_performance(frame)
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model

    @property
    def coefficients(self):
        return None
