"""Model / ModelBuilder abstractions — the hex.Model / hex.ModelBuilder layer.

Reference: hex/Model.java (parameters/output/scoring, adaptTestForTrain at
Model.java:1850, BigScore bulk scorer at Model.java:2085) and
hex/ModelBuilder.java:25 (trainModel at :374 launches a Driver Job;
cross-validation orchestration at :603). Here the same lifecycle:

    builder = GBMEstimator(**params)
    model   = builder.train(frame, y="col", x=[...])   # Job-wrapped
    preds   = model.predict(frame)                      # Frame of predictions
    mm      = model.model_performance(frame)            # ModelMetrics

Categorical response/feature adaptation follows adaptTestForTrain: test
categorical codes are remapped into training domains (unseen level → NA).
"""

from __future__ import annotations

import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core.job import Job
from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.model")

# per-model compiled scoring programs (Model._serve_jit) — weak-keyed
# so an evicted/deleted model releases its executables
_SERVE_JIT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class ModelCategory:
    BINOMIAL = "Binomial"
    MULTINOMIAL = "Multinomial"
    REGRESSION = "Regression"
    CLUSTERING = "Clustering"
    DIMREDUCTION = "DimReduction"
    ANOMALY = "AnomalyDetection"


def infer_category(frame: Frame, y: Optional[str]) -> str:
    """Response-type sniffing (reference ModelBuilder.init distribution
    inference)."""
    if y is None:
        return ModelCategory.CLUSTERING
    c = frame.col(y)
    if c.is_categorical:
        return (ModelCategory.BINOMIAL if c.cardinality == 2
                else ModelCategory.MULTINOMIAL)
    return ModelCategory.REGRESSION


def adapt_domain(test_col, train_domain: List[str]) -> np.ndarray:
    """Map test categorical codes into the training domain; unseen → -1
    (NA). The adaptTestForTrain domain-mapping pass (hex/Model.java:1850).
    """
    if test_col.domain == train_domain:
        codes = _fetch_np(test_col.data)[: test_col.nrows].copy()
        codes[_fetch_np(test_col.na_mask)[: test_col.nrows]] = -1
        return codes
    lut = {lvl: i for i, lvl in enumerate(train_domain)}
    mapping = np.array([lut.get(lvl, -1) for lvl in (test_col.domain or [])],
                       dtype=np.int32)
    codes = _fetch_np(test_col.data)[: test_col.nrows]
    out = mapping[codes] if len(mapping) else np.full(test_col.nrows, -1, np.int32)
    out = out.copy()
    out[_fetch_np(test_col.na_mask)[: test_col.nrows]] = -1
    return out


def checkpoint_error(algo: str, field: str, message: str) -> ValueError:
    """H2O-shaped checkpoint validation error
    (water.exceptions.H2OModelBuilderIllegalArgumentException as
    h2o-py surfaces it: ``Illegal argument(s) for <ALGO> model ...
    Details: ERRR on field: _<field>: <message>``)."""
    return ValueError(
        f"Illegal argument(s) for {algo.upper()} model: "
        f"Details: ERRR on field: _{field}: {message}")


def validate_checkpoint_params(algo: str, donor_params: Dict,
                               params: Dict, fields) -> None:
    """Reject changes to checkpoint-non-modifiable parameters with the
    reference's error shape (hex/util/CheckpointUtils
    getAndValidateCheckpointModel: "Field _x cannot be modified if
    checkpoint is provided!")."""
    for f in fields:
        old = donor_params.get(f)
        new = params.get(f)
        if old != new:
            raise checkpoint_error(
                algo, f,
                f"Field _{f} cannot be modified if checkpoint is "
                f"provided (checkpoint model: {old!r}, request: {new!r})")


def resolve_checkpoint_model(algo: str, ck, model_cls):
    """Fetch + type-check the donor model behind ``checkpoint=`` (a
    Model instance or its DKV key)."""
    from h2o3_tpu.core.kv import DKV
    donor = ck if isinstance(ck, model_cls) else DKV.get(str(ck))
    if donor is None or getattr(donor, "algo", None) != algo:
        raise checkpoint_error(
            algo, "checkpoint",
            f"Checkpoint model '{getattr(ck, 'key', ck)}' not found "
            f"or not a {algo} model")
    return donor


class EarlyStopper:
    """Metric-based early stopping (reference hex/ScoreKeeper.stopEarly +
    the stopping_rounds/stopping_tolerance contract of SharedTree).

    Lower-is-better metric; stops when the best of the last ``rounds``
    scoring events fails to improve on the prior best by a relative
    ``tol``.
    """

    def __init__(self, rounds: int, tol: float = 1e-3):
        self.rounds = int(rounds)
        self.tol = float(tol)
        self.history: List[float] = []

    @property
    def enabled(self) -> bool:
        return self.rounds > 0

    def should_stop(self, value: float) -> bool:
        self.history.append(float(value))
        if not self.enabled or len(self.history) <= self.rounds:
            return False
        recent = min(self.history[-self.rounds:])
        before = min(self.history[: -self.rounds])
        denom = abs(before) if before else 1.0
        return (before - recent) / denom < self.tol


class Model:
    """Trained-model base (hex/Model.java)."""

    algo: str = "base"

    def __init__(self, params: dict, output: dict, key: Optional[str] = None):
        self.key = key or make_key(f"model_{self.algo}")
        self.params = params
        self.output = output           # domains, names, varimp, history...
        self.training_metrics = None
        self.validation_metrics = None
        self.cross_validation_metrics = None
        DKV.put(self.key, self)

    # subclasses implement raw scoring on a Frame
    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _serve_jit(self):
        """The model's ONE compiled scoring program: ``jax.jit`` of
        ``_serve_dev``, cached per model instance. Both ``_score_raw``
        (on its no-offset path) and the serving tier score through THIS
        object, so row-payload predictions are bit-identical to
        ``Model.predict`` by construction — identical traced program,
        identical XLA fusions — rather than by hoping eager op-by-op
        execution matches a fused program (it does not: XLA rewrites
        e.g. divide-by-constant into reciprocal multiplies only inside
        a jitted program). Cached OUTSIDE the instance dict (weak-keyed
        module map) so models stay picklable for checkpoints."""
        fn = _SERVE_JIT_CACHE.get(self)
        if fn is None:
            import jax
            fn = jax.jit(self._serve_dev)
            _SERVE_JIT_CACHE[self] = fn
        return fn

    def _finish_predict(self, cols: Dict[str, np.ndarray]):
        """Shared post-processing of raw score columns: predict-column
        domain labeling and calibrated probabilities. ONE implementation
        for ``predict``, the chunked bulk path, and the serving tier —
        the bit-identity contract of README §Serving rides on all three
        funneling through here. Returns ``(out, domains)``."""
        out: Dict[str, np.ndarray] = {}
        domains: Dict[str, List[str]] = {}
        for name, arr in cols.items():
            out[name] = arr
            if name == "predict" and self.output.get("domain"):
                domains[name] = self.output["domain"]
        cal = getattr(self, "calibrator", None)
        if cal is not None and "p1" in out:
            # calibrated probability columns (CalibrationHelper scoring)
            cp1 = cal.apply(np.asarray(out["p1"], dtype=np.float64))
            out["cal_p0"] = 1.0 - cp1
            out["cal_p1"] = cp1
        return out, domains

    def predict(self, frame: Frame) -> Frame:
        """Bulk scoring → prediction Frame (BigScore, hex/Model.java:2085)."""
        out, domains = self._finish_predict(self._score_raw(frame))
        return Frame.from_numpy(out, domains=domains)

    def predict_in_chunks(self, frame: Frame, job=None,
                          chunk_rows: Optional[int] = None) -> Frame:
        """Bulk scoring with chunk-boundary cancellation — the BigScore
        MRTask contract (water/Job.java stop_requested() polled per
        chunk): a cancelled or deadline-expired bulk predict frees its
        worker within one chunk instead of after the full frame. Used
        by the async ``/4/Predictions`` job path; bit-identical to
        ``predict`` (every per-chunk op is row-local, and the shared
        ``_finish_predict`` tail runs once over the reassembled
        columns)."""
        import os as _os
        from h2o3_tpu.core import request_ctx
        if chunk_rows is None:
            chunk_rows = int(_os.environ.get(
                "H2O3TPU_PREDICT_CHUNK_ROWS", 262144))
        n = frame.nrows
        if chunk_rows <= 0 or n <= chunk_rows:
            request_ctx.cancel_point("predict.chunk")
            if job is not None:
                job.update(0.9)
            return self.predict(frame)
        parts: List[Dict[str, np.ndarray]] = []
        for lo in range(0, n, chunk_rows):
            request_ctx.cancel_point("predict.chunk")
            hi = min(lo + chunk_rows, n)
            sub = frame.row_slice(lo, hi)
            try:
                parts.append(self._score_raw(sub))
            finally:
                sub.drop_device_caches()
            if job is not None:
                job.update(0.05 + 0.85 * (hi / n))
        merged = {nm: np.concatenate([p[nm] for p in parts])
                  for nm in parts[0]}
        out, domains = self._finish_predict(merged)
        return Frame.from_numpy(out, domains=domains)

    def model_performance(self, frame: Frame):
        raise NotImplementedError

    def download_mojo(self, path: str, format: str = "native") -> str:
        """Export this model as a MOJO zip for offline scoring
        (Model.getMojo + hex/genmodel readers; see h2o3_tpu/genmodel/).

        format="native" (default): the npz fast path our offline
        readers consume. format="reference": the reference MOJO zip
        layout (model.ini + domains/ + SharedTreeMojoModel v1.40 tree
        blobs; GlmMojoReader v1.00 kv block for GLM) so the reference
        genmodel runtime can score the model — GBM/DRF/GLM.
        """
        if format == "reference":
            from h2o3_tpu.genmodel import refmojo
            writers = {
                "glm": refmojo.write_reference_glm_mojo,
                "kmeans": refmojo.write_reference_kmeans_mojo,
                "deeplearning": refmojo.write_reference_dl_mojo,
                "isolationforest": refmojo.write_reference_isofor_mojo,
                "word2vec": refmojo.write_reference_word2vec_mojo,
                "coxph": refmojo.write_reference_coxph_mojo,
                "glrm": refmojo.write_reference_glrm_mojo,
                "pca": refmojo.write_reference_pca_mojo,
                "targetencoder": refmojo.write_reference_te_mojo,
                "gbm": refmojo.write_reference_mojo,
                "drf": refmojo.write_reference_mojo,
            }
            w = writers.get(self.algo)
            if w is None:
                raise ValueError(
                    "reference-format MOJO export supports "
                    f"{sorted(writers)} (got {self.algo})")
            return w(self, path)
        from h2o3_tpu.genmodel.export import mojo_artifacts
        from h2o3_tpu.genmodel.mojo import write_mojo
        meta, arrays = mojo_artifacts(self)
        return write_mojo(path, meta, arrays)

    def download_pojo(self, path: str) -> str:
        """Export a standalone source-code scorer (Model.toJava POJO
        role; a stdlib-only Python module here — see genmodel/pojo.py)."""
        from h2o3_tpu.genmodel.pojo import export_pojo
        return export_pojo(self, path)

    @property
    def default_metrics(self):
        return (self.cross_validation_metrics or self.validation_metrics
                or self.training_metrics)

    def to_dict(self) -> dict:
        return {
            "model_id": self.key,
            "algo": self.algo,
            "params": {k: v for k, v in self.params.items()
                       if isinstance(v, (int, float, str, bool, list, type(None)))},
            "output": {k: v for k, v in self.output.items()
                       if isinstance(v, (int, float, str, bool, list, dict, type(None)))},
            "training_metrics": self.training_metrics.to_dict() if self.training_metrics else None,
            "validation_metrics": self.validation_metrics.to_dict() if self.validation_metrics else None,
            "cross_validation_metrics": (self.cross_validation_metrics.to_dict()
                                         if self.cross_validation_metrics else None),
        }


class ModelBuilder:
    """Training lifecycle base (hex/ModelBuilder.java:25).

    ``train`` = trainModel (ModelBuilder.java:374): wraps ``_fit`` in a Job
    with progress; n-fold CV (computeCrossValidation, ModelBuilder.java:603)
    is implemented generically in ml/cv.py and invoked when nfolds >= 2.
    """

    algo: str = "base"
    supervised: bool = True
    # fold_column implies CV for normal builders; encoders use the fold
    # column for leakage handling instead (TargetEncoder)
    cv_from_fold_column: bool = True

    def __init__(self, **params):
        self.params = params
        self._job: Optional[Job] = None

    @classmethod
    def accepted_params(cls) -> set:
        """Parameter names this builder accepts (REST schema filter);
        DEFAULTS-based by convention, overridable by facades."""
        return set(getattr(cls, "DEFAULTS", {}))

    def set_max_runtime(self, secs: float) -> None:
        """Install a wallclock cap when the builder accepts one (the
        AutoML executor's time slicing; facades forward to their inner
        builder, which __init__ constructed before the cap existed)."""
        if "max_runtime_secs" in self.accepted_params():
            self.params["max_runtime_secs"] = float(secs)

    # -- subclass contract --------------------------------------------
    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job: Job, validation_frame: Optional[Frame] = None) -> Model:
        raise NotImplementedError

    # -- shared weight plumbing (one impl; GBM/DRF/GLM all use these) --
    def _cv_masked_weights(self, w, frame: Frame):
        """CV fast path (ml/cv.py): fold models train on the PARENT
        frame with held-out rows weight-masked — no per-fold frame or
        bin rebuild, one compiled program across folds."""
        fold_mask = getattr(self, "_cv_fold_mask", None)
        if fold_mask is None:
            return w
        import jax.numpy as jnp
        fm = np.zeros(frame.nrows_padded, np.float32)
        fm[: frame.nrows] = fold_mask.astype(np.float32)
        return w * jnp.asarray(fm)

    def _host_weights(self, frame: Frame, y: Optional[str]) -> np.ndarray:
        """HOST mirror of the effective training weights: user weight
        column × CV fold mask × response-NA exclusion, [frame.nrows]
        float32. ONE implementation — GBM/DRF mirror the device vector
        with this, and uniformity detection classifies it; all reads
        come from cached host views, so no device sync (a per-fold
        fetch dominates leave-one-out CV)."""
        wc_name = self.params.get("weights_column")
        if wc_name and wc_name in frame:
            wh = np.nan_to_num(
                frame.col(wc_name).to_numpy()).astype(np.float32)
        else:
            wh = np.ones(frame.nrows, np.float32)
        fold_mask = getattr(self, "_cv_fold_mask", None)
        if fold_mask is not None:
            wh = wh * fold_mask.astype(np.float32)
        if y is not None and y in frame and \
                frame.col(y).type not in ("string", "uuid"):
            wh = wh * (~np.isnan(frame.col(y).to_numpy())).astype(np.float32)
        return wh

    def _normalize_uniform_weights(self, w, wh_host: np.ndarray):
        """(w', scale): a constant weight column rescales to exactly 1.0
        so 'uniform weights ≡ no weights' holds bit-for-bit
        (pyunit_weights_gbm asserts 1e-5-relative metric equality, which
        f32 rounding of w*k misses). Callers divide every ABSOLUTE
        training threshold (min_rows, min_split_improvement,
        reg_lambda) by the returned scale — that reproduces raw-weight
        reference semantics exactly in real arithmetic. ``wh_host`` is
        the _host_weights mirror of ``w``."""
        pos = wh_host[wh_host > 0]
        if pos.size and pos.min() == pos.max() and float(pos[0]) != 1.0:
            s = float(pos[0])
            return w / s, s
        return w, 1.0

    # -- public train --------------------------------------------------
    def resolve_x(self, frame: Frame, x: Optional[Sequence[str]],
                  y: Optional[str]) -> List[str]:
        ignored = set(self.params.get("ignored_columns") or [])
        drop = ignored | ({y} if y else set())
        drop |= {self.params.get("weights_column"),
                 self.params.get("fold_column"),
                 self.params.get("offset_column")}
        if x is None:
            x = [n for n in frame.names if n not in drop]
        else:
            x = [n if isinstance(n, str) else frame.names[n] for n in x]
            x = [n for n in x if n not in drop]
        # strings can't enter math paths (reference drops them with a warning)
        return [n for n in x if frame.col(n).type != "string"]

    def train(self, training_frame: Frame, y: Optional[str] = None,
              x: Optional[Sequence[str]] = None,
              validation_frame: Optional[Frame] = None,
              background: bool = False,
              dest_key: Optional[str] = None,
              custom_metric_func=None) -> Model:
        """``custom_metric_func`` is the water/udf CFunc role: a callable
        ``fn(y_values, preds_dict, weights) -> float`` evaluated on the
        training frame and attached to training_metrics as 'custom'."""
        x = self.resolve_x(training_frame, x, y)
        nfolds = int(self.params.get("nfolds") or 0)
        # an explicit fold column triggers CV regardless of nfolds
        # (hex/ModelBuilder.java computeCrossValidation entry conditions)
        if self.params.get("fold_column") and nfolds < 2 \
                and self.cv_from_fold_column:
            nfolds = 2      # actual count comes from the fold column
        # predictive admission (core/memgov.py): estimate the fit's
        # device footprint and reserve it BEFORE the job dispatches —
        # an over-budget fit first spills cold frames, then rejects
        # here with an actionable error naming projected vs available
        # bytes (never an opaque XLA RESOURCE_EXHAUSTED minutes in).
        # The reservation releases when the job ends, whatever status.
        from h2o3_tpu.core import memgov as _memgov
        _rsv = _memgov.governor.admit_fit(self.algo, self.params,
                                          training_frame, x,
                                          validation_frame)
        # the model key must exist BEFORE training starts: the real h2o-py
        # captures job.dest at submission time (h2o-py/h2o/job.py:48)
        if not dest_key:
            dest_key = make_key(f"model_{self.algo}")
        try:
            job = Job(f"{self.algo} train", work=1.0, dest=dest_key)
        except BaseException:
            _memgov.governor.release(_rsv)
            raise
        job.add_finalizer(lambda: _memgov.governor.release(_rsv))
        self._job = job
        # capture the in-fit checkpoint directory on the CALLER thread:
        # a background job runs on a fresh thread whose context would
        # not inherit the grid/AutoML fit_checkpoint_scope contextvar
        from h2o3_tpu.core import recovery as _recovery
        _fit_ckpt_dir = _recovery.fit_checkpoint_dir()

        def _run(j: Job) -> Model:
            t0 = time.time()
            # CV-contract validation errors surface as FAILED jobs so
            # clients see them while polling (hex/ModelBuilder error
            # handling; pyunit_cv_cars_* expect EnvironmentError from
            # H2OJob.poll)
            if nfolds == 1 or nfolds < 0:
                raise ValueError(
                    "nfolds must be either 0 or >1 (got %d)" % nfolds)
            if nfolds > training_frame.nrows:
                raise ValueError(
                    "nfolds (%d) cannot exceed the number of rows (%d)"
                    % (nfolds, training_frame.nrows))
            if self.params.get("fold_column") and \
                    int(self.params.get("nfolds") or 0) > 0:
                raise ValueError(
                    "only one of nfolds or fold_column may be specified")
            if self.params.get("fold_column") and \
                    str(self.params.get("fold_assignment", "auto")
                        or "auto").lower() != "auto":
                raise ValueError(
                    "fold_assignment is incompatible with fold_column "
                    "(hex/ModelBuilder fold-spec validation)")
            from h2o3_tpu import telemetry
            from h2o3_tpu.telemetry import roofline, stepprof
            with telemetry.span(f"{self.algo}.fit", algo=self.algo,
                                nfolds=nfolds), \
                    _recovery.fit_checkpoint_scope(_fit_ckpt_dir):
                rf_probe = roofline.fit_probe(self.algo)
                # step profiler: the chunk loops charge their phase
                # windows against this profile; finish registers the
                # per-fit ledger for /3/Models/{id}/profile, the
                # capsule, and the perf-regression baseline
                _sp = stepprof.start(self.algo,
                                     nrows=training_frame.nrows)
                t_fit = time.time()
                try:
                    if nfolds >= 2:
                        from h2o3_tpu.ml.cv import train_with_cv
                        model = train_with_cv(
                            self, training_frame, x, y, nfolds, j,
                            validation_frame=validation_frame)
                    else:
                        model = self._fit(
                            training_frame, x, y, j,
                            validation_frame=validation_frame)
                except BaseException:
                    stepprof.finish(_sp)   # never leave a live profile
                    raise
                # roofline accounting INSIDE the span: the MFU/HBM
                # numbers annotate the fit span and therefore land in
                # the job's flight-recorder capsule (never raises)
                _rf = roofline.record_model_fit(
                    self, model, training_frame, x,
                    seconds=time.time() - t_fit, probe=rf_probe)
                stepprof.finish(_sp, model_key=dest_key,
                                seconds=time.time() - t_fit,
                                mfu=(_rf or {}).get("mfu"))
            telemetry.histogram("model_fit_seconds",
                                algo=self.algo).observe(time.time() - t0)
            if custom_metric_func is not None and y is not None:
                # "python:key" CFunc references (water/udf/CFuncRef)
                from h2o3_tpu.core.udf import resolve_udf
                cmf = resolve_udf(custom_metric_func)
                yv = training_frame.col(y).to_numpy()   # enum → float codes
                preds = model._score_raw(training_frame)
                wv = np.ones(training_frame.nrows)
                wc = self.params.get("weights_column")
                if wc and wc in training_frame:
                    wv = np.nan_to_num(training_frame.col(wc).to_numpy())
                val = float(cmf(yv, preds, wv))
                if model.training_metrics is not None and \
                        hasattr(model.training_metrics, "extra"):
                    model.training_metrics.extra["custom"] = val
                model.output["custom_metric"] = val
            model.output["run_time"] = time.time() - t0
            if dest_key and model.key != dest_key:
                # rename into the pre-announced job dest key
                DKV.remove(model.key)
                model.key = dest_key
                DKV.put(dest_key, model)
            log.info("%s trained in %.2fs -> %s", self.algo,
                     time.time() - t0, model.key)
            return model

        job.start(_run, background=background)
        if background:
            return job  # poll via /3/Jobs
        if job.status == "FAILED":
            raise RuntimeError(job.exception)
        return job.result
