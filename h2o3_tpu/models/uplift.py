"""Uplift DRF — treatment-effect random forests.

Reference: hex/tree/uplift/UpliftDRF.java:1 (~700 LoC) — binomial
response + 2-level treatment column; split criterion maximizes the
divergence gain between treatment and control response distributions
(KL / Euclidean / ChiSquared, Rzepakowski-Jaroszewicz), leaves predict
``uplift = P(y=1|treated) - P(y=1|control)``; metrics are AUUC/Qini
(hex/ModelMetricsBinomialUplift).

TPU redesign: per level the (leaf, col, bin) stats come from TWO calls
of the matmul histogram (ops/histogram.py) — one with treatment-masked
weights, one with control-masked weights ({count, positives} each); the
divergence gain scan is vectorized over all nodes exactly like
models/tree.py ``_best_splits``. Routing, mtries, bagging reuse the DRF
machinery.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.binning import BinnedMatrix, bin_frame, rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory, adapt_domain
from h2o3_tpu.models.tree import (Tree, _mtries_mask, predict_forest,
                                  zero_catsplit,
                                  row_feature_values, stack_trees)
from h2o3_tpu.ops.histogram import histogram
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.uplift")


def _smooth_p(pos, n):
    return (pos + 1.0) / (n + 2.0)   # Laplace-smoothed response rate


def _divergence(pt, pc, metric: str):
    if metric == "euclidean":
        return 2.0 * (pt - pc) ** 2
    if metric == "chi_squared":
        pc_ = jnp.clip(pc, 1e-7, 1 - 1e-7)
        return (pt - pc) ** 2 / pc_ + (pt - pc) ** 2 / (1 - pc_)
    # KL (reference default)
    pt_ = jnp.clip(pt, 1e-7, 1 - 1e-7)
    pc_ = jnp.clip(pc, 1e-7, 1 - 1e-7)
    return (pt_ * jnp.log(pt_ / pc_)
            + (1 - pt_) * jnp.log((1 - pt_) / (1 - pc_)))


def _best_uplift_splits(ht, hc, nb, col_mask, min_rows: float, metric: str):
    """Vectorized divergence-gain scan over (node, feature, bin, NA-dir).

    ht/hc: [L, F, B, 3] {count, positives, _} for treatment / control.
    """
    B = ht.shape[2]
    nt, yt = ht[..., 0], ht[..., 1]
    nc, yc = hc[..., 0], hc[..., 1]
    cnt_t = jnp.cumsum(nt[:, :, : B - 1], axis=2)
    cyt = jnp.cumsum(yt[:, :, : B - 1], axis=2)
    cnt_c = jnp.cumsum(nc[:, :, : B - 1], axis=2)
    cyc = jnp.cumsum(yc[:, :, : B - 1], axis=2)
    na = (nt[:, :, B - 1], yt[:, :, B - 1], nc[:, :, B - 1], yc[:, :, B - 1])
    tot_t = cnt_t[:, :, -1] + na[0]
    tot_yt = cyt[:, :, -1] + na[1]
    tot_c = cnt_c[:, :, -1] + na[2]
    tot_yc = cyc[:, :, -1] + na[3]
    d_node = _divergence(_smooth_p(tot_yt, tot_t),
                         _smooth_p(tot_yc, tot_c), metric)
    n_all = tot_t + tot_c

    def gain_of(lt, lyt, lc, lyc):
        rt = tot_t[:, :, None] - lt
        ryt = tot_yt[:, :, None] - lyt
        rc = tot_c[:, :, None] - lc
        ryc = tot_yc[:, :, None] - lyc
        nl, nr = lt + lc, rt + rc
        dl = _divergence(_smooth_p(lyt, lt), _smooth_p(lyc, lc), metric)
        dr = _divergence(_smooth_p(ryt, rt), _smooth_p(ryc, rc), metric)
        g = (nl * dl + nr * dr) / jnp.maximum(n_all[:, :, None], 1.0) \
            - d_node[:, :, None]
        ok = (nl >= min_rows) & (nr >= min_rows) & (lt > 0) & (lc > 0) \
            & (rt > 0) & (rc > 0)
        return jnp.where(ok, g, -jnp.inf)

    g_nar = gain_of(cnt_t, cyt, cnt_c, cyc)
    g_nal = gain_of(cnt_t + na[0][:, :, None], cyt + na[1][:, :, None],
                    cnt_c + na[2][:, :, None], cyc + na[3][:, :, None])
    t_ids = jnp.arange(B - 1, dtype=jnp.int32)
    valid_t = t_ids[None, :] <= (nb[:, None] - 2)
    cm = col_mask if col_mask.ndim == 2 else col_mask[None, :]
    mask = valid_t[None, :, :] & cm[:, :, None]
    g_nar = jnp.where(mask, g_nar, -jnp.inf)
    g_nal = jnp.where(mask, g_nal, -jnp.inf)
    stacked = jnp.stack([g_nar, g_nal], axis=-1)
    L = stacked.shape[0]
    flat = stacked.reshape(L, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    na_left = (best % 2).astype(bool)
    best_t = ((best // 2) % (B - 1)).astype(jnp.int32)
    best_f = (best // (2 * (B - 1))).astype(jnp.int32)
    return best_gain, best_f, best_t, na_left


@partial(jax.jit, static_argnames=("depth", "B", "mtries", "metric",
                                   "min_rows"))
def _grow_uplift_tree(bins, nb, w, y, treat, key, *, depth: int, B: int,
                      mtries: int, metric: str, min_rows: float = 10.0):
    """One uplift tree fully on device; returns Tree (leaf=uplift) plus
    per-leaf treated/control response rates."""
    mesh = get_mesh()
    F = bins.shape[1]
    Lmax = 2 ** (depth - 1) if depth > 0 else 1
    N = bins.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    wt = w * treat
    wc = w * (1.0 - treat)
    feats = jnp.zeros((depth, Lmax), jnp.int32)
    threshs = jnp.full((depth, Lmax), B, jnp.int32)
    na_lefts = jnp.zeros((depth, Lmax), bool)
    is_splits = jnp.zeros((depth, Lmax), bool)
    ones = jnp.ones_like(y)
    for d in range(depth):
        L = 2 ** d
        ht = histogram(bins, nid, wt, y, ones, n_nodes=L, n_bins=B, mesh=mesh)
        hc = histogram(bins, nid, wc, y, ones, n_nodes=L, n_bins=B, mesh=mesh)
        key, sub = jax.random.split(key)
        cm = (_mtries_mask(sub, L, F, mtries) if 0 < mtries < F
              else jnp.ones((1, F), bool))
        bg, bf, bt, bnal = _best_uplift_splits(ht, hc, nb, cm, min_rows,
                                               metric)
        split = bg > 1e-9
        feats = feats.at[d, :L].set(jnp.where(split, bf, 0))
        threshs = threshs.at[d, :L].set(jnp.where(split, bt, B))
        na_lefts = na_lefts.at[d, :L].set(jnp.where(split, bnal, False))
        is_splits = is_splits.at[d, :L].set(split)
        f_r = feats[d][nid]
        t_r = threshs[d][nid]
        nal_r = na_lefts[d][nid]
        isp_r = is_splits[d][nid]
        b_r = row_feature_values(bins, f_r)
        isna = b_r == (B - 1)
        goleft = jnp.where(isp_r, jnp.where(isna, nal_r, b_r <= t_r), True)
        nid = 2 * nid + jnp.where(goleft, 0, 1)
    nleaf = 2 ** depth
    st_t = segment_sum(nid, jnp.stack([wt, wt * y], axis=1),
                       n_nodes=nleaf, mesh=mesh)
    st_c = segment_sum(nid, jnp.stack([wc, wc * y], axis=1),
                       n_nodes=nleaf, mesh=mesh)
    p_t = _smooth_p(st_t[:, 1], st_t[:, 0])
    p_c = _smooth_p(st_c[:, 1], st_c[:, 0])
    tree = Tree(feats, threshs, na_lefts, is_splits, p_t - p_c,
                st_t[:, 0] + st_c[:, 0],
                *zero_catsplit(feats.shape[0], feats.shape[1]))
    return tree, p_t, p_c


def auuc(uplift_pred: np.ndarray, y: np.ndarray, treat: np.ndarray,
         nbins: int = 1000, auuc_type: str = "qini") -> Dict[str, float]:
    """AUUC / Qini from the cumulative uplift curve
    (hex/AUUC.java semantics: rows sorted by predicted uplift desc;
    curve types qini / lift / gain per hex/AUUC.AUUCType)."""
    order = np.argsort(-uplift_pred, kind="stable")
    y, tr = y[order], treat[order]
    n = len(y)
    idx = np.linspace(0, n, min(nbins, n) + 1).astype(int)[1:]
    cy_t = np.cumsum(y * tr)
    cn_t = np.cumsum(tr)
    cy_c = np.cumsum(y * (1 - tr))
    cn_c = np.cumsum(1 - tr)

    def curve_at(k: int, kind: str) -> float:
        nt, nc = cn_t[k], cn_c[k]
        rt = cy_t[k] / nt if nt > 0 else 0.0
        rc = cy_c[k] / nc if nc > 0 else 0.0
        if kind == "qini":
            return cy_t[k] - (cy_c[k] * nt / nc if nc > 0 else 0.0)
        if kind == "lift":
            return rt - rc
        return (rt - rc) * (nt + nc)   # gain

    kind = auuc_type if auuc_type in ("qini", "lift", "gain") else "qini"
    vals = np.asarray([curve_at(k, kind) for k in idx - 1])
    qini = np.asarray([curve_at(k, "qini") for k in idx - 1])
    auuc_v = float(vals.mean())
    # random-targeting baseline endpoint (on the qini curve)
    q_final = curve_at(n - 1, "qini")
    qini_coef = float(qini.mean() - q_final / 2.0)
    return {"auuc": auuc_v, "qini": qini_coef, "auuc_type": kind,
            "uplift_top_decile": float(vals[max(len(vals) // 10 - 1, 0)])}


class UpliftDRFModel(Model):
    algo = "upliftdrf"

    def __init__(self, params, output, forest: Tree, leaf_pt, leaf_pc,
                 bm: BinnedMatrix):
        super().__init__(params, output)
        self.forest = forest
        self.leaf_pt = leaf_pt      # [T, 2^D]
        self.leaf_pc = leaf_pc
        self.bm = bm

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        bm = rebin_for_scoring(self.bm, frame)
        B = self.bm.nbins_total
        T = self.forest.feat.shape[0]
        n = frame.nrows
        # tree leaves are p_t - p_c by construction, so uplift falls out
        # of the two class-rate scans without a third forest walk
        pt = np.asarray(predict_forest(
            self.forest._replace(leaf=self.leaf_pt), bm.bins, B))[:n] / T
        pc = np.asarray(predict_forest(
            self.forest._replace(leaf=self.leaf_pc), bm.bins, B))[:n] / T
        return {"uplift_predict": pt - pc, "p_y1_ct1": pt, "p_y1_ct0": pc}

    def model_performance(self, frame: Frame):
        raw = self._score_raw(frame)
        y = adapt_domain(frame.col(self.output["response"]),
                         self.output["domain"])[: frame.nrows]
        tr = adapt_domain(frame.col(self.params["treatment_column"]),
                          self.output["treatment_domain"])[: frame.nrows]
        ok = (y >= 0) & (tr >= 0)
        nbins = int(self.params.get("auuc_nbins") or -1)
        atype = str(self.params.get("auuc_type") or "auto").lower()
        a = auuc(raw["uplift_predict"][ok], y[ok].astype(float),
                 tr[ok].astype(float),
                 nbins=nbins if nbins > 0 else 1000,
                 auuc_type="qini" if atype == "auto" else atype)
        return mm.ModelMetrics("BinomialUplift", int(ok.sum()),
                               float(np.mean(raw["uplift_predict"] ** 2)),
                               **a)


class UpliftDRFEstimator(ModelBuilder):
    """h2o-py H2OUpliftRandomForestEstimator surface
    (h2o-py/h2o/estimators/uplift_random_forest.py)."""

    algo = "upliftdrf"

    DEFAULTS = dict(
        ntrees=50, max_depth=10, min_rows=10.0, nbins=64, nbins_cats=64,
        mtries=-2, sample_rate=0.632, seed=-1,
        treatment_column=None, uplift_metric="auto",
        auuc_type="auto", auuc_nbins=-1,
        ignored_columns=None, nfolds=0, fold_assignment="auto",
        weights_column=None, fold_column=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown UpliftDRF params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)
        if not self.params.get("treatment_column"):
            raise ValueError("UpliftDRF requires treatment_column")

    def resolve_x(self, frame, x, y):
        x = super().resolve_x(frame, x, y)
        return [n for n in x if n != self.params["treatment_column"]]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        rc = frame.col(y)
        tc = frame.col(p["treatment_column"])
        if not (rc.is_categorical and rc.cardinality == 2):
            raise ValueError("UpliftDRF needs a 2-level categorical response")
        if not (tc.is_categorical and tc.cardinality == 2):
            raise ValueError("UpliftDRF needs a 2-level treatment column")
        metric = str(p["uplift_metric"]).lower().replace("chisquared",
                                                         "chi_squared")
        if metric == "auto":
            metric = "kl"
        if metric not in ("kl", "euclidean", "chi_squared"):
            raise ValueError(f"unknown uplift_metric '{p['uplift_metric']}'; "
                             "use KL, Euclidean or ChiSquared")
        n = frame.nrows
        w = frame.valid_weights()
        if p.get("weights_column") and p["weights_column"] in frame:
            wc_ = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc_), 0.0, wc_)
        from h2o3_tpu.parallel.mesh import fetch_replicated as _f
        bm = bin_frame(frame, x, nbins=p["nbins"], nbins_cats=p["nbins_cats"],
                       weights=_f(w)[:n])
        npad = bm.bins.shape[0]
        yv = adapt_domain(rc, rc.domain)
        trv = adapt_domain(tc, tc.domain)
        ok = (yv >= 0) & (trv >= 0)
        w = w * jnp.asarray(np.pad(ok.astype(np.float32), (0, npad - n)))
        y_dev = jnp.asarray(np.pad(np.maximum(yv, 0).astype(np.float32),
                                   (0, npad - n)))
        t_dev = jnp.asarray(np.pad(np.maximum(trv, 0).astype(np.float32),
                                   (0, npad - n)))

        F = len(x)
        mtries = int(p["mtries"])
        if mtries == -1:
            mtries = max(int(np.sqrt(F)), 1)
        elif mtries == -2:
            mtries = F   # all columns (reference UpliftDRF default -2)
        depth = int(p["max_depth"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xD00D
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        trees, pts, pcs = [], [], []
        for t in range(ntrees):
            key, kb, kt = jax.random.split(key, 3)
            keep = jax.random.bernoulli(kb, float(p["sample_rate"]),
                                        shape=w.shape)
            tr_, pt_, pc_ = _grow_uplift_tree(
                bm.bins, bm.nbins, w * keep.astype(jnp.float32), y_dev,
                t_dev, kt, depth=depth, B=bm.nbins_total, mtries=mtries,
                metric=metric, min_rows=float(p["min_rows"]))
            trees.append(tr_)
            pts.append(pt_)
            pcs.append(pc_)
            job.update(1.0 / ntrees, f"tree {t + 1}/{ntrees}")
        forest = stack_trees(trees)
        output = {"category": "BinomialUplift", "response": y,
                  "names": list(x), "domain": rc.domain,
                  "treatment_domain": tc.domain, "nclasses": 2}
        model = UpliftDRFModel(p, output, forest, jnp.stack(pts),
                               jnp.stack(pcs), bm)
        model.training_metrics = model.model_performance(frame)
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
