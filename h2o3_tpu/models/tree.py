"""Shared tree machinery — level-wise histogram tree growing on TPU.

Reference: the SharedTree skeleton (hex/tree/SharedTree.java:29,481):
per level, ScoreBuildHistogram2 routes rows to leaves and fills
DHistograms, then DTree.findBestSplitPoint scans bins for best gain
(hex/tree/DTree.java:619-697), leaves get Newton values (GammaPass).

TPU-first redesign (SURVEY §7 hard part #1/#2):
- trees are COMPLETE binary trees of static depth D: level d has 2^d
  node slots (padded; empty nodes have zero histograms and never split).
  Static shapes ⇒ one compiled program for the whole tree.
- per level: matmul histogram (ops/histogram.py) → vectorized gain scan
  over (feature, threshold, NA-direction) → argmax → elementwise
  row-routing update of the node-id vector. No host roundtrips.
- split criterion is the Newton gain on (g, h) — the XGBoost-style
  generalization of the reference's {w,wY,wYY} SSE gain; with
  g = residual, h = 1 it reduces exactly to the reference's variance
  reduction.
- NA handling: NAs live in the last bin; both NA-left and NA-right are
  scored, best kept — mirroring DHistogram's NA bucket semantics.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.ops.histogram import histogram
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.ops.split_scan import best_splits


class TreeScalars(NamedTuple):
    """Traced per-call training knobs. These previously rode inside the
    static TreeParams, so every distinct (min_rows, reg_lambda, msi)
    combination — e.g. every AutoML/grid candidate — forced a fresh XLA
    compilation; as traced scalars one compiled program serves them all
    (structure-affecting fields stay static in TreeParams).

    ``depth_limit`` extends the trick to max_depth: programs compile at
    a BUCKETED static depth (DEPTH_BUCKETS) and mask splits past the
    traced actual depth, so AutoML/grid candidates of depths 3..6 (or
    7..10, 11..14) all share one compiled boosting program instead of
    paying a fresh 20-40s XLA compile each."""
    min_rows: jax.Array
    reg_lambda: jax.Array
    msi: jax.Array
    depth_limit: jax.Array = None


def scalars_of(params: "TreeParams") -> "TreeScalars":
    return TreeScalars(jnp.float32(params.min_rows),
                       jnp.float32(params.reg_lambda),
                       jnp.float32(params.min_split_improvement),
                       jnp.int32(params.max_depth))


# static compile-depth buckets: levels past the actual depth cost one
# masked row-pass each, so the padding overhead is bounded by
# bucket/actual while compile count drops from one-per-depth to
# one-per-bucket (AutoML trains depths {3..15} in one session)
DEPTH_BUCKETS = (6, 10, 14)


def bucket_depth(d: int) -> int:
    for b in DEPTH_BUCKETS:
        if d <= b:
            return b
    return d


class Tree(NamedTuple):
    """One complete tree; arrays padded to Lmax = 2^(D-1) internal slots."""
    feat: jax.Array       # [D, Lmax] int32 split feature
    thresh: jax.Array     # [D, Lmax] int32 split bin (go left if bin <= t)
    na_left: jax.Array    # [D, Lmax] bool
    is_split: jax.Array   # [D, Lmax] bool
    leaf: jax.Array       # [2^D] float32 leaf values
    leaf_w: jax.Array     # [2^D] float32 training row weight per leaf
                          # (node covers for TreeSHAP pool up from these;
                          # the reference stores them as node weights in
                          # hex/tree/CompressedTree for contributions)
    cat_split: jax.Array  # [D, Lmax] bool — split is a category SUBSET
                          # (bitset) split, not a bin-range split
    left_words: jax.Array  # [D, Lmax, W] uint32 — bit b of word k set ⇔
                          # bin 32k+b goes LEFT (DTree.java:619-697
                          # bitset splits, static-shape bit-packed)


def zero_catsplit(D: int, Lmax: int):
    """(cat_split, left_words) placeholders for builders that never make
    categorical subset splits (isolation forests, uplift)."""
    return (jnp.zeros((D, Lmax), bool),
            jnp.zeros((D, Lmax, 1), jnp.uint32))


@dataclasses.dataclass(frozen=True)
class TreeParams:
    max_depth: int = 5
    min_rows: float = 10.0
    learn_rate: float = 0.1
    reg_lambda: float = 1.0          # hessian regularization (reference min_rows+pred smoothing)
    min_split_improvement: float = 1e-5
    col_sample_rate: float = 1.0     # per-split column sampling is per-tree here
    nbins_total: int = 65            # B incl. NA bin
    block_rows: int = 4096
    cat_feats: tuple = ()            # per-feature is-categorical flags —
                                     # schema-static, activates the
                                     # sorted-prefix subset-split path
    exact_f32: bool = False          # true-f32 LEAF-value sums (vs TPU
                                     # bf16x3) on small problems where
                                     # pyunits assert 1e-5 metric
                                     # equality. Histograms stay bf16x3
                                     # (HIGHEST inside the level loop
                                     # multiplies compile time); split
                                     # ties may still flip across row
                                     # orders — uniform-weight
                                     # normalization covers the exact-
                                     # equality contracts instead
    pallas: str = "off"              # fused level-loop backend:
                                     # "off" = XLA, "native"/"interpret"
                                     # = ops/pallas/treekernel. STATIC
                                     # on purpose: the knob decision
                                     # must be part of the jit key so a
                                     # mid-process flip recompiles
                                     # instead of reusing a stale
                                     # program (ops/pallas.resolve_tree_mode)

    @property
    def has_cats(self) -> bool:
        return any(self.cat_feats)


def exact_f32_for(bm) -> bool:
    """True-f32 LEAF-sum mode for pyunit-scale problems: TPU bf16x3
    residue (~1e-5 relative) in leaf values fails reference
    metric-equality assertions, and a single leaf matmul at HIGHEST is
    free below this size (histograms are excluded — see TreeParams)."""
    return (bm.bins.shape[0] * bm.bins.shape[1] * bm.nbins_total
            <= (1 << 26))


def row_feature_values(bins, f_r):
    """``bins[i, f_r[i]]`` without a gather.

    On TPU ``take_along_axis`` lowers to a gather (~11 ms per call on 1M
    rows, v5e); the masked feature-sum is pure VPU broadcast work (<1 ms)
    — this select runs once per tree level, so it dominates routing cost.
    """
    iota = jnp.arange(bins.shape[1], dtype=jnp.int32)
    return jnp.sum(jnp.where(f_r[:, None] == iota[None, :], bins, 0), axis=1)


def _best_splits(hist, nb, col_mask, params: TreeParams,
                 constraints=None, lo=None, hi=None, scalars=None,
                 is_cat=None):
    """Vectorized DTree.findBestSplitPoint over all nodes of a level —
    thin adapter over the shared implementation (ops/split_scan.py),
    which the fused Pallas kernels evaluate too so both tree backends
    stay bit-exact by construction. See ops.split_scan.best_splits for
    the full contract."""
    sc = scalars if scalars is not None else scalars_of(params)
    return best_splits(
        hist, nb, col_mask, min_rows=sc.min_rows,
        reg_lambda=sc.reg_lambda,
        is_cat=is_cat if (params.has_cats and is_cat is not None)
        else None,
        constraints=constraints, lo=lo, hi=hi)


def _pack_leftmask(leftmask, W: int):
    """[L, B-1] bool → [L, W] uint32 bitset words (bit b of word k ⇔
    bin 32k+b). One-hot matmul keeps it gather-free."""
    Bm1 = leftmask.shape[1]
    bpos = jnp.arange(Bm1, dtype=jnp.uint32)
    contrib = leftmask.astype(jnp.uint32) << (bpos % 32)[None, :]
    seg = (bpos // 32)[:, None] == jnp.arange(W, dtype=jnp.uint32)[None, :]
    return jnp.sum(contrib[:, :, None] * seg[None].astype(jnp.uint32),
                   axis=1)


def _level_goleft(feat_d, thresh_d, nal_d, isp_d, cat_d, lw_d, nid, bins,
                  B: int):
    """Row routing for one tree level — shared by training, scoring,
    leaf assignment and path counting (the DecidedNode assignment pass).
    Numeric splits compare bin <= t; categorical subset splits test the
    row's bin bit in the node's packed left-set."""
    f_r = feat_d[nid]
    t_r = thresh_d[nid]
    nal_r = nal_d[nid]
    isp_r = isp_d[nid]
    b_r = row_feature_values(bins, f_r).astype(jnp.int32)
    isna = b_r == (B - 1)
    go_num = b_r <= t_r
    W = lw_d.shape[1]
    cs_r = cat_d[nid]
    widx = (b_r >> 5).astype(jnp.uint32)
    # select the row's bitset word WITHOUT an [N, W] u32 intermediate:
    # TPU tiling pads the minor dim to 128, so [50M, 4] u32 becomes a
    # 25.7GB allocation (observed gbm-full compile OOM). A static loop
    # of per-word [N] gathers fuses into selects instead.
    word = jnp.zeros_like(b_r, dtype=jnp.uint32)
    for k in range(W):
        word = word | jnp.where(widx == jnp.uint32(k), lw_d[nid, k],
                                jnp.uint32(0))
    inset = ((word >> (b_r & 31).astype(jnp.uint32)) & 1) == 1
    go_split = jnp.where(cs_r, inset, go_num)
    goleft = jnp.where(isp_r, jnp.where(isna, nal_r, go_split), True)
    return 2 * nid + jnp.where(goleft, 0, 1)


def _mtries_mask(key, L: int, F: int, mtries: int):
    """Exactly-mtries-per-node column mask [L, F] — the reference DRF
    per-split column subsample (hex/tree/DTree.java UndecidedNode scoreCols,
    mtries semantics of hex/tree/drf/DRF.java:30)."""
    u = jax.random.uniform(key, (L, F))
    rank = jnp.argsort(jnp.argsort(u, axis=1), axis=1)
    return rank < mtries


def grow_tree(bins, nb, w, g, h, col_mask, *, params: TreeParams, mesh,
              mtries: int = 0, key=None, constraints=None,
              interaction_sets=None, scalars=None):
    """Grow one tree; returns (Tree, final_leaf_id_per_row).

    bins [Npad, F] int32 row-sharded; w zero on padding rows; col_mask [F]
    bool (per-tree column sampling, reference col_sample_rate_per_tree).
    mtries > 0 additionally samples exactly-mtries columns per NODE per
    level (DRF semantics) using `key`. ``constraints`` [F] in {-1,0,+1}
    activates monotone constraints: per-node value bounds propagate to
    children through the split midpoint and leaves are clipped into
    them (the reference's hex/tree/Constraints machinery).
    ``interaction_sets`` [S, F] bool activates interaction constraints
    (GBM interaction_constraints / hex/tree/GlobalInteractionConstraints):
    once a node splits on feature f, its subtree may only use features
    sharing an interaction set with every feature on the path — tracked
    as a per-node allowed mask.
    """
    D = params.max_depth
    sc = scalars if scalars is not None else scalars_of(params)
    B = params.nbins_total
    F = bins.shape[1]
    Lmax = 2 ** (D - 1) if D > 0 else 1
    N = bins.shape[0]
    nid = jnp.zeros((N,), jnp.int32)

    feats = jnp.zeros((D, Lmax), jnp.int32)
    threshs = jnp.full((D, Lmax), B, jnp.int32)
    na_lefts = jnp.zeros((D, Lmax), bool)
    is_splits = jnp.zeros((D, Lmax), bool)
    is_cat = (jnp.asarray(np.asarray(params.cat_feats, dtype=bool))
              if params.has_cats else None)
    W = max(1, (B - 1 + 31) // 32) if params.has_cats else 1
    cat_splits = jnp.zeros((D, Lmax), bool)
    left_words = jnp.zeros((D, Lmax, W), jnp.uint32)
    gain_by_feat = jnp.zeros((F,), jnp.float32)  # relative varimp (hex/VarImp)
    lo = jnp.full((1,), -jnp.inf, jnp.float32)
    hi = jnp.full((1,), jnp.inf, jnp.float32)
    allowed = jnp.ones((1, F), bool)   # per-node feature set (interactions)
    pair_allow = None                  # lazy [F, F] compatibility matrix

    # exact_f32 scopes to the LEAF value sums only: HIGHEST-precision
    # matmuls inside the level loop multiply XLA compile time (6-pass
    # f32 emulation unrolled through the boosting scan — observed 600s+
    # pyunit wallclock vs 90s), while the leaf segment_sum is a single
    # small matmul whose exactness the weight≡duplication metric
    # contracts actually observe
    prec = jax.lax.Precision.HIGHEST if params.exact_f32 else None
    # fused Pallas level loop (ops/pallas/treekernel.py): histogram +
    # split scan + row partition in one pass over the bin-major tiles,
    # selected per fit via the STATIC params.pallas knob. The stats
    # block {w, w·g, w·h} is level-invariant, so it is built once here
    # (the XLA path rebuilds the same values inside ops/histogram.py).
    use_fused = params.pallas in ("native", "interpret")
    if use_fused:
        from h2o3_tpu.ops.pallas.treekernel import fused_level
        stats3 = jnp.stack([w, w * g, w * h], axis=1).astype(jnp.float32)
    prev_hist = None
    for d in range(D):
        L = 2 ** d
        cm = col_mask
        if mtries > 0 and mtries < F:
            key, sub = jax.random.split(key)
            cm = _mtries_mask(sub, L, F, mtries) & col_mask[None, :]
        if interaction_sets is not None:
            cm = (cm if cm.ndim == 2 else cm[None, :]) & allowed
        if use_fused:
            (hist, bg, bf, bt, bnal, blv, brv, leftmask, split,
             nid_next) = fused_level(
                bins, nid, stats3, prev_hist, cm, nb, is_cat,
                constraints, lo, hi, sc, d=d, n_nodes=L, n_bins=B,
                block_rows=params.block_rows, mesh=mesh,
                interpret=(params.pallas == "interpret"))
        else:
            if prev_hist is None:
                hist = histogram(bins, nid, w, g, h, n_nodes=L, n_bins=B,
                                 mesh=mesh, block_rows=params.block_rows)
            else:
                # sibling subtraction: histogram only the LEFT children
                # (even node slots), derive right = parent − left. Halves
                # the histogram matmul at every level ≥ 1 (the
                # LightGBM/XGBoost smaller-child trick, made static-shape
                # by always picking left; the reference recomputes both
                # children, hex/tree/ScoreBuildHistogram2.java).
                even = (nid % 2 == 0).astype(jnp.float32)
                lh = histogram(bins, nid >> 1, w * even, g, h,
                               n_nodes=L // 2, n_bins=B, mesh=mesh,
                               block_rows=params.block_rows)
                rh = prev_hist - lh
                # f32 cancellation guard: w and h are nonnegative sums,
                # so clamp tiny negative residue (|err| ≲ parent·2^-23);
                # g may be legitimately negative and stays as computed
                rh = rh.at[..., 0].set(jnp.maximum(rh[..., 0], 0.0))
                rh = rh.at[..., 2].set(jnp.maximum(rh[..., 2], 0.0))
                hist = jnp.stack([lh, rh], axis=1).reshape(L, *lh.shape[1:])
            bg, bf, bt, bnal, blv, brv, leftmask = _best_splits(
                hist, nb, cm, params, constraints=constraints, lo=lo,
                hi=hi, scalars=sc, is_cat=is_cat)
            split = bg > sc.msi
            if sc.depth_limit is not None:
                # depth-bucketed program: levels past the ACTUAL depth
                # never split (one compiled program per DEPTH_BUCKET,
                # not per depth)
                split = split & (jnp.int32(d) < sc.depth_limit)
            nid_next = None
        prev_hist = hist
        feats = feats.at[d, :L].set(jnp.where(split, bf, 0))
        threshs = threshs.at[d, :L].set(jnp.where(split, bt, B))
        na_lefts = na_lefts.at[d, :L].set(jnp.where(split, bnal, False))
        is_splits = is_splits.at[d, :L].set(split)
        if params.has_cats and is_cat is not None:
            cs = is_cat[bf] & split
            cat_splits = cat_splits.at[d, :L].set(cs)
            words = _pack_leftmask(leftmask, W)
            left_words = left_words.at[d, :L].set(
                jnp.where(cs[:, None], words, 0))
        gain_by_feat = gain_by_feat + jnp.sum(
            jnp.where(split, jnp.maximum(bg, 0.0), 0.0)[:, None]
            * (bf[:, None] == jnp.arange(F, dtype=jnp.int32)[None, :]),
            axis=0)

        # interaction-set propagation (XGBoost/GlobalInteractionConstraints
        # rule): children may use any feature sharing a set with the
        # split feature, intersected with the path's allowance.
        # pair_allow[i, j] = features i and j share a set — one [F, F]
        # precompute, then a per-level [L, F] gather.
        if interaction_sets is not None:
            if pair_allow is None:
                pair_allow = jnp.einsum(
                    "sf,sg->fg", interaction_sets.astype(jnp.float32),
                    interaction_sets.astype(jnp.float32)) > 0
            child_allow = pair_allow[bf]                   # [L, F]
            child_allow = allowed & jnp.where(split[:, None], child_allow,
                                              True)
            allowed = jnp.repeat(child_allow, 2, axis=0)   # children 2l,2l+1

        # bound propagation (Constraints.childBounds role): on a
        # constrained split the midpoint of the child values caps the
        # low side / high side; unconstrained splits inherit
        if constraints is not None:
            c_split = constraints[bf].astype(jnp.float32) * split
            mid = 0.5 * (blv + brv)
            lo_l = lo
            hi_l = jnp.where(c_split > 0, jnp.minimum(hi, mid), hi)
            lo_l = jnp.where(c_split < 0, jnp.maximum(lo, mid), lo_l)
            lo_r = jnp.where(c_split > 0, jnp.maximum(lo, mid), lo)
            hi_r = jnp.where(c_split < 0, jnp.minimum(hi, mid), hi)
            # interleave children: node l → children 2l, 2l+1
            lo = jnp.stack([lo_l, lo_r], axis=1).reshape(-1)
            hi = jnp.stack([hi_l, hi_r], axis=1).reshape(-1)
        # route rows (the reference's DecidedNode assignment pass);
        # the fused kernel already partitioned inside its second phase
        if nid_next is not None:
            nid = nid_next
        else:
            nid = _level_goleft(feats[d], threshs[d], na_lefts[d],
                                is_splits[d], cat_splits[d],
                                left_words[d], nid, bins, B)

    # leaf Newton values from final assignment (GammaPass analogue)
    nleaf = 2 ** D
    stats = jnp.stack([w, w * g, w * h], axis=1)
    leaf_stats = segment_sum(nid, stats, n_nodes=nleaf, mesh=mesh,
                             block_rows=params.block_rows, precision=prec)
    G, H = leaf_stats[:, 1], leaf_stats[:, 2]
    leaf = jnp.where(leaf_stats[:, 0] > 0,
                     -G / (H + sc.reg_lambda + 1e-10), 0.0)
    if constraints is not None:
        leaf = jnp.clip(leaf, lo, hi)   # leaves honor propagated bounds
    tree = Tree(feats, threshs, na_lefts, is_splits, leaf,
                leaf_stats[:, 0], cat_splits, left_words)
    return tree, nid, gain_by_feat


def predict_tree(tree: Tree, bins, B: int):
    """Route binned rows through one tree → leaf values [N]."""
    return tree.leaf[_route(tree, bins, B)]


def stack_trees(trees) -> Tree:
    """Stack per-iteration Trees into [T, ...] arrays for scan-predict."""
    return Tree(*(jnp.stack([getattr(t, f) for t in trees])
                  for f in Tree._fields))


def concat_forests(chunks) -> Tree:
    """Concatenate [T_i, ...] forest chunks along the tree axis — the
    chunked-scan and model-batched training paths both assemble their
    final forest through this."""
    chunks = list(chunks)
    if len(chunks) == 1:
        return chunks[0]
    return Tree(*(jnp.concatenate([getattr(c, f) for c in chunks])
                  for f in Tree._fields))


def unstack_model_trees(batched: Tree, m: int, keep=None) -> Tree:
    """Slice model ``m``'s forest out of a model-batched [M, T, ...]
    stacked Tree (parallel/model_batch vmap axis), optionally truncated
    to its first ``keep`` trees (per-model early stop)."""
    sl = slice(None) if keep is None else slice(int(keep))
    return Tree(*(a[m, sl] for a in batched))


def _route(tree: Tree, bins, B: int):
    """Terminal node id per row for one tree — the single routing
    implementation shared by scoring and leaf assignment."""
    N = bins.shape[0]
    D = tree.feat.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    for d in range(D):
        nid = _level_goleft(tree.feat[d], tree.thresh[d], tree.na_left[d],
                            tree.is_split[d], tree.cat_split[d],
                            tree.left_words[d], nid, bins, B)
    return nid


@partial(jax.jit, static_argnames=("B", "F"))
def feature_path_counts(stacked: Tree, bins, B: int, F: int):
    """Per-row counts of feature usage along decision paths, summed over
    all trees [N, F] — hex/tree SharedTreeModel feature_frequencies
    (h2o-py model.feature_frequencies)."""

    def step(counts, tree):
        N = bins.shape[0]
        D = tree.feat.shape[0]
        nid = jnp.zeros((N,), jnp.int32)
        for d in range(D):
            f_r = tree.feat[d][nid]
            isp_r = tree.is_split[d][nid]
            onehot = (f_r[:, None] ==
                      jnp.arange(F, dtype=jnp.int32)[None, :])
            counts = counts + jnp.where(isp_r[:, None] & onehot, 1, 0)
            nid = _level_goleft(tree.feat[d], tree.thresh[d],
                                tree.na_left[d], tree.is_split[d],
                                tree.cat_split[d], tree.left_words[d],
                                nid, bins, B)
        return counts, None

    counts0 = jnp.zeros((bins.shape[0], F), jnp.int32)
    counts, _ = jax.lax.scan(step, counts0, stacked)
    return counts


def feature_frequencies_frame(model, frame):
    """Per-feature usage counts as a Frame (h2o-py feature_frequencies)."""
    from h2o3_tpu.frame.binning import rebin_for_scoring
    from h2o3_tpu.frame.frame import Frame
    bm = rebin_for_scoring(model.bm, frame)
    F = bm.bins.shape[1]
    counts = np.asarray(feature_path_counts(
        model.forest, bm.bins, model.bm.nbins_total, F))[: frame.nrows]
    return Frame.from_numpy({bm.names[j]: counts[:, j].astype(np.float64)
                             for j in range(F)})


@partial(jax.jit, static_argnames=("B",))
def leaf_assignments(stacked: Tree, bins, B: int):
    """Per-tree terminal leaf id for every row [N, T] — the
    predict_leaf_node_assignment path (hex/Model.java scoreLeafNode
    /h2o-py predict_leaf_node_assignment with type Node_ID)."""

    def step(_, tree):
        return None, _route(tree, bins, B)

    _, out = jax.lax.scan(step, None, stacked)
    return out.T          # [N, T]


def leaf_assignment_frame(model, frame):
    """Shared GBM/DRF predict_leaf_node_assignment: columns are T{t} for
    single-output forests and T{t}.C{k} per class for stacked per-class
    forests (h2o naming)."""
    from h2o3_tpu.frame.binning import rebin_for_scoring
    from h2o3_tpu.frame.frame import Frame
    bm = rebin_for_scoring(model.bm, frame)
    ids = np.asarray(leaf_assignments(model.forest, bm.bins,
                                      model.bm.nbins_total))[: frame.nrows]
    # forests compile at the DEPTH BUCKET (tree.py DEPTH_BUCKETS) with a
    # traced limit masking deeper splits; the walk therefore returns ids
    # at the bucket depth D — shift back to the REQUESTED depth's id
    # space (rows route left through masked levels, so the shift is an
    # exact inverse)
    D = int(model.forest.feat.shape[1])
    d_req = min(int(model.params.get("max_depth") or D), D)
    if d_req < D:
        ids = ids >> (D - d_req)
    category = model.output.get("category")
    K = (model.output.get("nclasses", 1)
         if category == "Multinomial" else 1)
    # classification columns carry a .C{k} suffix even for binomial
    # (SharedTreeModel.java:326 — suffix dropped only when the per-iter
    # tree-key array has a single entry, i.e. regression)
    suffixed = category in ("Binomial", "Multinomial")
    cols = {}
    for j in range(ids.shape[1]):
        name = (f"T{j // K + 1}.C{j % K + 1}" if suffixed
                else f"T{j + 1}")
        cols[name] = ids[:, j].astype(np.float64)
    return Frame.from_numpy(cols)


@partial(jax.jit, static_argnames=("B",))
def predict_forest(stacked: Tree, bins, B: int):
    """Sum of all trees' outputs via lax.scan over the tree axis.

    The compressed-forest scoring path (hex/tree/CompressedTree.java walk
    inside BigScore, hex/Model.java:2085) as one jitted program.
    """

    def step(acc, tree):
        return acc + predict_tree(tree, bins, B), None

    init = jnp.zeros((bins.shape[0],), jnp.float32)
    total, _ = jax.lax.scan(step, init, stacked)
    return total
