"""Naive Bayes — count/moment-based conditional probabilities.

Reference: hex/naivebayes/NaiveBayes.java:26 — one MRTask accumulates
per-class counts for categoricals and per-class mean/variance for
numerics; laplace smoothing; Gaussian likelihood for numerics; min_sdev /
min_prob floors.

TPU redesign: all sufficient statistics come from ONE segment_sum over
the class id (psum across the mesh): for numerics {w, w·x, w·x²} per
(class, feature); for categoricals the (class × level) contingency table
via one-hot matmul. Scoring is a dense [N,K] log-likelihood matmul.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   adapt_domain, infer_category)
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def __init__(self, params, output, stats):
        super().__init__(params, output)
        self.stats = stats   # dict: priors, num (mu/sd per class), cat tables

    def _loglik(self, frame: Frame):
        s = self.stats
        K = len(s["priors"])
        n = frame.nrows
        ll = np.log(np.maximum(s["priors"], 1e-12))[None, :].repeat(n, 0)
        eps = float(self.params.get("eps_sdev") or 0.0)
        min_sd = max(float(self.params.get("min_sdev") or 1e-3), 1e-6)
        for j, name in enumerate(s["num_names"]):
            x = np.asarray(frame.col(name).numeric_view())[:n]
            mu = s["num_mu"][j]            # [K]
            sd = np.maximum(s["num_sd"][j], min_sd) + eps
            t = (x[:, None] - mu[None, :]) / sd[None, :]
            contrib = -0.5 * t * t - np.log(sd)[None, :]
            ll += np.where(np.isnan(x)[:, None], 0.0, contrib)
        min_p = max(float(self.params.get("min_prob") or 1e-3), 1e-10)
        for j, name in enumerate(s["cat_names"]):
            codes = adapt_domain(frame.col(name), s["cat_domains"][j])
            tab = s["cat_tables"][j]       # [K, card] conditional probs
            probs = np.maximum(tab, min_p)
            safe = np.maximum(codes, 0)
            contrib = np.log(probs[:, safe]).T     # [n, K]
            ll += np.where((codes < 0)[:, None], 0.0, contrib)
        return ll

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        ll = self._loglik(frame)
        p = np.exp(ll - ll.max(axis=1, keepdims=True))
        p = p / p.sum(axis=1, keepdims=True)
        if p.shape[1] == 2:
            # binomial labels honor the default threshold like every other
            # binomial model (reference BigScore threshold semantics)
            t = self.output.get("default_threshold", 0.5)
            out = {"predict": (p[:, 1] >= t).astype(np.int32)}
        else:
            out = {"predict": p.argmax(axis=1).astype(np.int32)}
        for k in range(p.shape[1]):
            out[f"p{k}"] = p[:, k]
        return out

    def model_performance(self, frame: Frame):
        y = self.output["response"]
        ll = self._loglik(frame)
        p = np.exp(ll - ll.max(axis=1, keepdims=True))
        p = p / p.sum(axis=1, keepdims=True)
        yv = adapt_domain(frame.col(y), self.output["domain"])
        ok = yv >= 0
        w = np.asarray(frame.valid_weights())[: frame.nrows] * ok
        yv = np.maximum(yv, 0)
        if p.shape[1] == 2:
            return mm.binomial_metrics(jnp.asarray(p[:, 1]),
                                       jnp.asarray(yv.astype(np.float32)),
                                       jnp.asarray(w.astype(np.float32)))
        return mm.multinomial_metrics(jnp.asarray(p), jnp.asarray(yv),
                                      jnp.asarray(w.astype(np.float32)),
                                      domain=self.output["domain"])


class NaiveBayesEstimator(ModelBuilder):
    """h2o-py H2ONaiveBayesEstimator-compatible surface."""

    algo = "naivebayes"

    DEFAULTS = dict(
        laplace=0.0, min_sdev=1e-3, eps_sdev=0.0, min_prob=1e-3,
        eps_prob=0.0, seed=-1, nfolds=0, fold_column=None,
        fold_assignment="auto", ignored_columns=None, weights_column=None,
        compute_metrics=True,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown NaiveBayes params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        category = infer_category(frame, y)
        if category == ModelCategory.REGRESSION:
            raise ValueError("NaiveBayes requires a categorical response")
        rc = frame.col(y)
        K = rc.cardinality
        n = frame.nrows
        N = frame.nrows_padded
        codes = _fetch_np(rc.data)[:n].astype(np.int32)
        na = _fetch_np(rc.na_mask)[:n]
        codes[na] = 0
        cls = jnp.asarray(np.pad(codes, (0, N - n)))
        w = frame.valid_weights()
        w = w * jnp.asarray(np.pad((~na).astype(np.float32), (0, N - n)))
        lap = float(p["laplace"])

        num_names = [c for c in x if not frame.col(c).is_categorical]
        cat_names = [c for c in x if frame.col(c).is_categorical]
        # numeric moments per class in one pass
        num_mu, num_sd = [], []
        if num_names:
            cols = []
            for name in num_names:
                v = frame.col(name).numeric_view()
                valid = ~jnp.isnan(v)
                v0 = jnp.where(valid, v, 0.0)
                cols += [w * valid, w * v0, w * v0 * v0]
            vals = jnp.stack(cols, axis=1)
            sums = np.asarray(segment_sum(cls, vals, n_nodes=K, mesh=mesh))
            for j in range(len(num_names)):
                cw, cx, cxx = sums[:, 3 * j], sums[:, 3 * j + 1], sums[:, 3 * j + 2]
                mu = cx / np.maximum(cw, 1e-12)
                var = cxx / np.maximum(cw, 1e-12) - mu * mu
                num_mu.append(mu)
                num_sd.append(np.sqrt(np.maximum(var, 1e-12)))
        # categorical contingency tables: segment over class*card+code
        cat_tables, cat_domains = [], []
        for name in cat_names:
            c = frame.col(name)
            card = max(c.cardinality, 1)
            cc = c.data.astype(jnp.int32)
            wna = w * (~c.na_mask).astype(jnp.float32)
            idx = cls * card + jnp.clip(cc, 0, card - 1)
            tab = np.asarray(segment_sum(idx.astype(jnp.int32), wna[:, None],
                                         n_nodes=K * card, mesh=mesh))
            tab = tab.reshape(K, card)
            tab = (tab + lap) / np.maximum(
                tab.sum(axis=1, keepdims=True) + lap * card, 1e-12)
            cat_tables.append(tab)
            cat_domains.append(c.domain)

        prior_w = np.asarray(segment_sum(cls, w[:, None], n_nodes=K,
                                         mesh=mesh))[:, 0]
        priors = prior_w / max(prior_w.sum(), 1e-12)
        job.update(1.0, "stats done")

        stats = {"priors": priors, "num_names": num_names,
                 "num_mu": num_mu, "num_sd": num_sd,
                 "cat_names": cat_names, "cat_tables": cat_tables,
                 "cat_domains": cat_domains}
        output = {"category": category, "response": y, "names": list(x),
                  "nclasses": K, "domain": rc.domain,
                  "priors": priors.tolist()}
        model = NaiveBayesModel(p, output, stats)
        model.training_metrics = model.model_performance(frame)
        if category == ModelCategory.BINOMIAL:
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
