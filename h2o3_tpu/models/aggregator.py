"""Aggregator — exemplar-based dataset compression.

Reference: hex/aggregator/Aggregator.java (~600 LoC): radius-based
agglomeration — rows within ``radius`` of an exemplar are absorbed into
it (counts accumulate), others become new exemplars; the radius is
scaled until the exemplar count lands near ``target_num_exemplars``
(within rel_tol_num_exemplars). Output is an aggregated frame of
exemplar rows plus a ``counts`` column.

TPU redesign: rows are standardized once into a device matrix; each
candidate radius runs a batched sweep where distances of a whole batch
against the current exemplar set are one matmul; only the
new-exemplar selection inside a batch is a (short) host loop. The
radius search is a geometric escalation like the reference's
aggregate_radius_scale growth.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.datainfo import build_datainfo
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.model import Model, ModelBuilder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.aggregator")


def _sweep(Xh: np.ndarray, radius: float, max_exemplars: int):
    """One agglomeration pass at a fixed radius. Returns (exemplar row
    indices, counts, assignment)."""
    n = Xh.shape[0]
    r2 = radius * radius
    ex_idx: List[int] = [0]
    assign = np.full(n, -1, dtype=np.int64)
    assign[0] = 0
    B = 4096
    x2 = (Xh * Xh).sum(axis=1)
    for s in range(0, n, B):
        batch = Xh[s: s + B]
        E = Xh[np.asarray(ex_idx)]
        # ||x-e||² = x² + e² - 2 x·e — keeps the temp at [B, E]
        d2 = (x2[s: s + B][:, None] + x2[np.asarray(ex_idx)][None, :]
              - 2.0 * batch @ E.T)
        best = d2.argmin(axis=1)
        bestd = d2[np.arange(len(batch)), best]
        within = bestd <= r2
        assign[s: s + B][within] = best[within]
        # rows beyond radius: greedily promote to exemplars
        far = np.where(~within)[0]
        for i in far:
            gi = s + i
            if assign[gi] >= 0:
                continue
            E_new = Xh[np.asarray(ex_idx[len(E):])] if len(ex_idx) > len(E) \
                else None
            if E_new is not None and len(E_new):
                d2n = (x2[gi] + x2[np.asarray(ex_idx[len(E):])]
                       - 2.0 * E_new @ Xh[gi])
                j = d2n.argmin()
                if d2n[j] <= r2:
                    assign[gi] = len(E) + j
                    continue
            ex_idx.append(gi)
            assign[gi] = len(ex_idx) - 1
            if len(ex_idx) > max_exemplars:
                return None, None, None   # radius too small
    counts = np.bincount(assign, minlength=len(ex_idx))
    return np.asarray(ex_idx), counts, assign


class AggregatorModel(Model):
    algo = "aggregator"

    def __init__(self, params, output, exemplar_frame_key: str,
                 exemplar_assignment: np.ndarray):
        super().__init__(params, output)
        self.exemplar_frame_key = exemplar_frame_key
        self.exemplar_assignment = exemplar_assignment

    @property
    def aggregated_frame(self) -> Frame:
        from h2o3_tpu.core.kv import DKV
        return DKV.get(self.exemplar_frame_key)

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("Aggregator produces aggregated_frame")

    def model_performance(self, frame: Frame):
        return None


@register
class AggregatorEstimator(ModelBuilder):
    """h2o-py H2OAggregatorEstimator surface
    (h2o-py/h2o/estimators/aggregator.py)."""

    algo = "aggregator"
    supervised = False

    DEFAULTS = dict(
        target_num_exemplars=5000, rel_tol_num_exemplars=0.5,
        transform="normalize", categorical_encoding="auto",
        ignored_columns=None, seed=-1,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown Aggregator params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        standardize = str(p["transform"]).lower() in ("normalize",
                                                      "standardize")
        di = build_datainfo(frame, x, standardize=standardize,
                            use_all_factor_levels=True)
        n = frame.nrows
        Xh = np.asarray(di.X)[:n].astype(np.float64)

        target = int(p["target_num_exemplars"])
        tol = float(p["rel_tol_num_exemplars"])
        lo_ok = max(int(target * (1 - tol)), 1)
        if n <= target:
            ex_idx = np.arange(n)
            counts = np.ones(n, dtype=np.int64)
            assign = np.arange(n)
        else:
            # geometric radius escalation, then accept first radius whose
            # exemplar count falls in [lo_ok, target]
            radius = 0.05 * np.sqrt(di.P)
            ex_idx = counts = assign = None
            for _ in range(40):
                res = _sweep(Xh, radius, max_exemplars=max(4 * target, 100))
                if res[0] is not None and len(res[0]) <= target:
                    ex_idx, counts, assign = res
                    if len(ex_idx) >= lo_ok:
                        break
                    radius /= 1.5   # too few exemplars — shrink
                else:
                    radius *= 2.0   # too many — grow
                job.update(0.02, f"radius {radius:.3g}")
            if ex_idx is None:
                res = _sweep(Xh, radius, max_exemplars=n + 1)
                ex_idx, counts, assign = res

        # aggregated output frame: original-space exemplar rows + counts
        from h2o3_tpu.models.generic import _frame_raw_columns
        raw = _frame_raw_columns(frame, x)
        cols: Dict[str, np.ndarray] = {}
        cats = []
        for name in x:
            v = raw[name][ex_idx]
            cols[name] = v
            if frame.col(name).is_categorical:
                cats.append(name)
        cols["counts"] = counts.astype(np.float64)
        agg = Frame.from_numpy(cols, categorical=cats)

        output = {"category": "Clustering", "response": None,
                  "names": list(x), "domain": None,
                  "num_exemplars": int(len(ex_idx)),
                  "output_frame": agg.key}
        model = AggregatorModel(p, output, agg.key, assign)
        return model
