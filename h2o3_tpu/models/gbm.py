"""GBM — gradient boosting machine, the flagship TPU algorithm.

Reference: hex/tree/gbm/GBM.java:32 (buildNextKTrees at :464) on the
SharedTree skeleton (hex/tree/SharedTree.java:481 scoreAndBuildTrees):
per iteration compute residuals (ComputePredAndRes), grow K trees via
histogram MRTasks, set leaf gammas (GammaPass), update margins.

TPU redesign: the whole per-iteration pipeline — gradients → D histogram
levels → splits → routing → leaf values → margin update — is ONE jitted
program (`_boost_step`); the Python loop over iterations just feeds it.
Rows stay sharded over the mesh 'data' axis; the only collectives are the
psums inside ops/histogram.py. Nothing leaves the device between trees.

Multinomial: K margin columns, K trees per iteration, softmax gradients —
the reference's per-class tree loop (GBM.java buildNextKTrees "ktrees").
"""

from __future__ import annotations

import time

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core import recovery as _recovery
from h2o3_tpu.core.watchdog import maybe_fail
from h2o3_tpu.frame.binning import BinnedMatrix, bin_frame, rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.distribution import Distribution, get_distribution
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   checkpoint_error, infer_category,
                                   resolve_checkpoint_model,
                                   validate_checkpoint_params)
from h2o3_tpu.models.tree import (Tree, TreeParams, TreeScalars,
                                  bucket_depth, concat_forests,
                                  exact_f32_for, grow_tree,
                                  predict_forest, predict_tree,
                                  stack_trees, unstack_model_trees)
from h2o3_tpu.ops import pallas as pallas_ops
from h2o3_tpu.parallel.mesh import (get_mesh, put_sharded,
                                    row_sharding)
from h2o3_tpu import telemetry
from h2o3_tpu.telemetry import observed_jit, stepprof
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.gbm")

# SharedTree checkpoint-non-modifiable parameters (hex/tree/SharedTree
# CHECKPOINT_NON_MODIFIABLE_FIELDS): structural knobs a restart cannot
# change without invalidating the donor model's trees/bin edges
CHECKPOINT_NON_MODIFIABLE = ("max_depth", "min_rows", "nbins",
                             "nbins_cats", "sample_rate")


def _tree_host(t: Tree) -> dict:
    """Device-independent (numpy) image of a stacked forest — the
    FitCheckpointer snapshot payload."""
    return {f: np.asarray(getattr(t, f)) for f in Tree._fields}


def _tree_dev(d: dict) -> Tree:
    return Tree(*(jnp.asarray(d[f]) for f in Tree._fields))


def _tree_keys(key, tree0, ntrees: int):
    """Per-tree PRNG keys derived from the GLOBAL tree index
    (fold_in(key, tree0+i)), not from a per-chunk split: chunk size is a
    scheduling artifact (max_runtime_secs shrinks it, row scale shrinks
    it) and must never change seeded sampling results. ``tree0`` rides
    as a traced scalar so every chunk boundary shares one program."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        tree0 + jnp.arange(ntrees, dtype=jnp.int32))


def _sample_columns(k1, k2, F: int, rate):
    """Per-tree column sampling mask (col_sample_rate_per_tree), with one
    column always forced in so a tree can never go featureless. ``rate``
    is a TRACED scalar (rate >= 1 keeps every column: bernoulli(1) is
    always True) so grid/AutoML candidates share one compilation."""
    mask = jax.random.bernoulli(k1, jnp.clip(rate, 0.0, 1.0), shape=(F,))
    return mask | (jnp.arange(F) == jax.random.randint(k2, (), 0, F))


def _boost_step(bins, nb, y, w, margin, key, constraints=None,
                interaction_sets=None, *,
                tp: TreeParams, dist: Distribution, sample_rate: float):
    """One boosting iteration, fully on device (per-tree loop path —
    used when early stopping / validation tracking needs the host
    between trees; otherwise _boost_scan fuses the whole loop)."""
    return _boost_step_jit(bins, nb, y, w, margin, key,
                           _knobs_of(tp, sample_rate), constraints,
                           interaction_sets, tp=_neutral_tp(tp),
                           dist=dist)


@partial(jax.jit, static_argnames=("tp", "dist"))
def _boost_step_jit(bins, nb, y, w, margin, key, knobs, constraints=None,
                    interaction_sets=None, *,
                    tp: TreeParams, dist: Distribution):
    return _boost_step_impl(bins, nb, y, w, margin, key, knobs,
                            tp=tp, dist=dist,
                            constraints=constraints,
                            interaction_sets=interaction_sets)


def _boost_scan(bins, nb, y, w, margin, key, constraints=None,
                interaction_sets=None, *,
                tp: TreeParams, dist: Distribution, sample_rate: float,
                ntrees: int, tree0: int = 0):
    return _boost_scan_jit(bins, nb, y, w, margin, key, tree0,
                           _knobs_of(tp, sample_rate), constraints,
                           interaction_sets, tp=_neutral_tp(tp),
                           dist=dist, ntrees=ntrees)


@observed_jit("gbm.boost_scan")
@partial(jax.jit, static_argnames=("tp", "dist", "ntrees"))
def _boost_scan_jit(bins, nb, y, w, margin, key, tree0, knobs,
                    constraints=None, interaction_sets=None, *,
                    tp: TreeParams, dist: Distribution, ntrees: int):
    """All ``ntrees`` boosting iterations as ONE compiled program.

    ``lax.scan`` over per-tree PRNG keys removes the per-tree
    host↔device round trip of the Python loop (the dominant overhead on
    a remote-attached chip); static tree shapes make the stacked Tree
    output exactly what predict_forest consumes.
    """
    keys = _tree_keys(key, tree0, ntrees)

    def step(margin, k):
        tree, margin, gains = _boost_step_impl(
            bins, nb, y, w, margin, k, knobs, tp=tp, dist=dist,
            constraints=constraints,
            interaction_sets=interaction_sets)
        return margin, (tree, gains)

    margin, (trees, gains) = jax.lax.scan(step, margin, keys)
    return trees, margin, jnp.sum(gains, axis=0)


def _boost_scan_scored(bins, nb, y, w, margin, key,
                       vbins, vy, vw, vmargin,
                       constraints=None, interaction_sets=None, *,
                       tp: TreeParams, dist: Distribution,
                       sample_rate: float, ntrees: int, B: int,
                       use_val: bool, tree0: int = 0):
    return _boost_scan_scored_jit(
        bins, nb, y, w, margin, key, tree0, vbins, vy, vw, vmargin,
        _knobs_of(tp, sample_rate), constraints, interaction_sets,
        tp=_neutral_tp(tp), dist=dist, ntrees=ntrees, B=B,
        use_val=use_val)


@observed_jit("gbm.boost_scan_scored")
@partial(jax.jit, static_argnames=("tp", "dist", "ntrees", "B", "use_val"))
def _boost_scan_scored_jit(bins, nb, y, w, margin, key, tree0,
                           vbins, vy, vw, vmargin, knobs,
                           constraints=None, interaction_sets=None, *,
                           tp: TreeParams, dist: Distribution,
                           ntrees: int, B: int, use_val: bool):
    """``ntrees`` fused boosting steps + ONE device-side deviance score.

    This is how early stopping stays on the fused path: deviance is a
    cheap elementwise+reduce next to histogram tree growth, so every
    scan step emits it; the host reads back one small vector per
    25-tree chunk, applies the score_tree_interval/stopping_rounds
    policy, and truncates the stacked forest at the stop point (the
    reference scores between trees on the driver node,
    hex/tree/SharedTree.java:481 — here the scores ride inside the
    compiled program). With ``use_val`` the validation margin is
    carried through the scan too."""
    keys = _tree_keys(key, tree0, ntrees)

    def step(carry, k):
        margin, vmargin = carry
        tree, margin, gains = _boost_step_impl(
            bins, nb, y, w, margin, k, knobs, tp=tp, dist=dist,
            constraints=constraints,
            interaction_sets=interaction_sets)
        if use_val:
            vmargin = vmargin + predict_tree(tree, vbins, B)
            dev = jnp.sum(vw * dist.deviance(vy, vmargin)) \
                / jnp.maximum(jnp.sum(vw), 1e-12)
        else:
            dev = jnp.sum(w * dist.deviance(y, margin)) \
                / jnp.maximum(jnp.sum(w), 1e-12)
        return (margin, vmargin), (tree, gains, dev)

    (margin, vmargin), (trees, gains, devs) = jax.lax.scan(
        step, (margin, vmargin), keys)
    return trees, margin, vmargin, gains, devs


def _boost_scan_batched(bins, nb, y, w, margins, keys, knobs_b,
                        constraints=None, interaction_sets=None, *,
                        tp: TreeParams, dist: Distribution, ntrees: int,
                        tree0: int = 0):
    return _boost_scan_batched_jit(bins, nb, y, w, margins, keys, tree0,
                                   knobs_b, constraints, interaction_sets,
                                   tp=_neutral_tp(tp), dist=dist,
                                   ntrees=ntrees)


@observed_jit("gbm.boost_scan_batched")
@partial(jax.jit, static_argnames=("tp", "dist", "ntrees"))
def _boost_scan_batched_jit(bins, nb, y, w, margins, keys, tree0, knobs_b,
                            constraints=None, interaction_sets=None, *,
                            tp: TreeParams, dist: Distribution,
                            ntrees: int):
    """Model-batched boosting: ``vmap`` over the MODEL axis of a whole
    grid/AutoML shape bucket — ``knobs_b`` [M, 7] numeric knob vectors,
    ``keys`` [M, 2] per-model PRNG keys, ``margins`` [M, Npad] — with
    ``bins``/``y``/``w`` broadcast (shared, un-vmapped). One compiled
    program trains M models where the sequential walk paid M dispatch/
    readback round trips (the driver-bound outer loop of ml/grid.py).

    Every step also emits the training deviance so the host can apply
    per-model early-stop MASKS (truncate each model's stacked forest at
    its stop point) instead of the sequential path's Python breaks.
    Returns ([M, T, ...] stacked trees, [M, Npad] margins, [M, T, F]
    gains, [M, T] deviances)."""
    keys_t = jax.vmap(lambda k: _tree_keys(k, tree0, ntrees))(keys)

    def one(margin, tkeys, knobs):
        def step(margin, k):
            tree, margin, gains = _boost_step_impl(
                bins, nb, y, w, margin, k, knobs, tp=tp, dist=dist,
                constraints=constraints,
                interaction_sets=interaction_sets)
            dev = jnp.sum(w * dist.deviance(y, margin)) \
                / jnp.maximum(jnp.sum(w), 1e-12)
            return margin, (tree, gains, dev)

        margin, (trees, gains, devs) = jax.lax.scan(step, margin, tkeys)
        return trees, margin, gains, devs

    return jax.vmap(one)(margins, keys_t, knobs_b)


def _boost_scan_multi(bins, nb, y_int, w, margins, key,
                      vbins, vy_int, vw, vmargins,
                      interaction_sets=None, *, tp: TreeParams,
                      sample_rate: float, n_class: int, ntrees: int,
                      B: int, use_val: bool, tree0: int = 0):
    return _boost_scan_multi_jit(
        bins, nb, y_int, w, margins, key, tree0, vbins, vy_int, vw,
        vmargins, _knobs_of(tp, sample_rate), interaction_sets,
        tp=_neutral_tp(tp), n_class=n_class, ntrees=ntrees, B=B,
        use_val=use_val)


@observed_jit("gbm.boost_scan_multi")
@partial(jax.jit, static_argnames=("tp", "n_class", "ntrees", "B",
                                   "use_val"))
def _boost_scan_multi_jit(bins, nb, y_int, w, margins, key, tree0,
                          vbins, vy_int, vw, vmargins, knobs,
                          interaction_sets=None, *, tp: TreeParams,
                          n_class: int, ntrees: int, B: int,
                          use_val: bool):
    """Fused multinomial boosting: ``ntrees`` iterations x K class trees
    in one compiled scan + device-side multinomial deviance.

    Round 1 ran a Python loop with a host sync per tree
    (VERDICT weak #3); the scan removes all per-tree round trips, so
    multinomial boosting matches the binomial fused path's throughput
    profile."""
    keys = _tree_keys(key, tree0, ntrees)

    def step(carry, kk):
        margins, vmargins = carry
        trees, margins, vmargins, gains = _boost_step_multi_impl(
            bins, nb, y_int, w, margins, kk, knobs, tp=tp,
            n_class=n_class,
            interaction_sets=interaction_sets,
            vbins=vbins if use_val else None, vmargins=vmargins, B=B)
        if use_val:
            m_, w_, y_ = vmargins, vw, vy_int
        else:
            m_, w_, y_ = margins, w, y_int
        py = jnp.take_along_axis(jax.nn.softmax(m_, axis=1),
                                 y_[:, None], axis=1)[:, 0]
        dev = jnp.sum(-2.0 * w_ * jnp.log(jnp.clip(py, 1e-7, 1.0))) \
            / jnp.maximum(jnp.sum(w_), 1e-12)
        return (margins, vmargins), (trees, gains, dev)

    (margins, vmargins), (trees, gains, devs) = jax.lax.scan(
        step, (margins, vmargins), keys)
    return trees, margins, vmargins, gains, devs


def _knobs_of(tp: TreeParams, sample_rate: float):
    """Traced training knobs: [sample_rate, col_sample_rate, learn_rate,
    min_rows, reg_lambda, min_split_improvement, max_depth]. Keeping
    these OUT of the static jit key means one compiled boosting program
    serves every grid/AutoML candidate of the same depth-BUCKET/nbins
    (max_depth rides as the traced depth_limit; the program compiles at
    bucket_depth(max_depth))."""
    return jnp.asarray([sample_rate, tp.col_sample_rate, tp.learn_rate,
                        tp.min_rows, tp.reg_lambda,
                        tp.min_split_improvement,
                        float(tp.max_depth)], jnp.float32)


def _neutral_tp(tp: TreeParams) -> TreeParams:
    """Structural-only TreeParams for the jit static key (numeric knobs
    travel as traced values; depth compiles at its bucket)."""
    return TreeParams(max_depth=bucket_depth(tp.max_depth), min_rows=0.0,
                      learn_rate=0.0, reg_lambda=0.0,
                      min_split_improvement=0.0, col_sample_rate=1.0,
                      nbins_total=tp.nbins_total,
                      block_rows=tp.block_rows,
                      cat_feats=tp.cat_feats,
                      exact_f32=tp.exact_f32,   # static: changes the program
                      pallas=tp.pallas)         # static: kernel backend


def _boost_step_impl(bins, nb, y, w, margin, key, knobs, *, tp, dist,
                     constraints=None, interaction_sets=None):
    """Unjitted body shared by _boost_step and _boost_scan."""
    mesh = get_mesh()
    g = dist.grad(y, margin)
    h = dist.hess(y, margin)
    kr, kc1, kc2 = jax.random.split(key, 3)
    keep = jax.random.bernoulli(kr, jnp.clip(knobs[0], 0.0, 1.0),
                                shape=w.shape)
    ws = w * keep.astype(jnp.float32)
    F = bins.shape[1]
    col_mask = _sample_columns(kc1, kc2, F, knobs[1])
    sc = TreeScalars(knobs[3], knobs[4], knobs[5],
                     knobs[6].astype(jnp.int32))
    tree, nid, gains = grow_tree(bins, nb, ws, g, h, col_mask,
                                 params=tp, mesh=mesh,
                                 constraints=constraints,
                                 interaction_sets=interaction_sets,
                                 scalars=sc)
    tree = tree._replace(leaf=knobs[2] * tree.leaf)
    margin = margin + tree.leaf[nid]
    return tree, margin, gains


def _boost_step_multi(bins, nb, y_int, w, margins, key,
                      interaction_sets=None, *, tp: TreeParams,
                      sample_rate: float, n_class: int):
    """One multinomial iteration: K trees on softmax gradients.
    (Plain-python wrapper; callers inside jit trace the impl, callers
    outside get per-call dispatch — only the scan paths are hot.)"""
    trees, margins, _, gains = _boost_step_multi_impl(
        bins, nb, y_int, w, margins, key, _knobs_of(tp, sample_rate),
        tp=_neutral_tp(tp), n_class=n_class,
        interaction_sets=interaction_sets)
    return trees, margins, gains


def _boost_step_multi_impl(bins, nb, y_int, w, margins, key, knobs, *,
                           tp: TreeParams, n_class: int,
                           interaction_sets=None,
                           vbins=None, vmargins=None, B=None):
    """Unjitted multinomial body (K class trees per iteration); when
    ``vbins`` is given the validation margins are advanced too."""
    mesh = get_mesh()
    p = jax.nn.softmax(margins, axis=1)
    kr, kc1, kc2 = jax.random.split(key, 3)
    keep = jax.random.bernoulli(kr, jnp.clip(knobs[0], 0.0, 1.0),
                                shape=w.shape)
    ws = w * keep.astype(jnp.float32)
    F = bins.shape[1]
    col_mask = _sample_columns(kc1, kc2, F, knobs[1])
    sc = TreeScalars(knobs[3], knobs[4], knobs[5],
                     knobs[6].astype(jnp.int32))
    trees = []
    gains_tot = jnp.zeros((F,), jnp.float32)
    new_margins = margins
    for k in range(n_class):
        yk = (y_int == k).astype(jnp.float32)
        gk = p[:, k] - yk
        hk = p[:, k] * (1.0 - p[:, k])
        tree, nid, gains = grow_tree(bins, nb, ws, gk, hk, col_mask,
                                     params=tp, mesh=mesh,
                                     interaction_sets=interaction_sets,
                                     scalars=sc)
        tree = tree._replace(leaf=knobs[2] * tree.leaf)
        new_margins = new_margins.at[:, k].add(tree.leaf[nid])
        if vbins is not None:
            vmargins = vmargins.at[:, k].add(predict_tree(tree, vbins, B))
        trees.append(tree)
        gains_tot = gains_tot + gains
    return stack_trees(trees), new_margins, vmargins, gains_tot


def _stop_point(devs, done, k, score_interval, stopper,
                scoring_history) -> int:
    """Apply the interval/stopping policy to a chunk's per-tree
    deviances; returns how many of the chunk's trees to keep."""
    for t_local in range(k):
        t_glob = done + t_local + 1
        if t_glob % score_interval == 0:
            devf = float(devs[t_local])
            scoring_history.append({"ntrees": t_glob, "deviance": devf})
            if stopper.should_stop(devf):
                return t_local + 1
    return k


def _build_constraints(p, x, frame, category):
    """Monotone constraints vector (GBM.java monotone_constraints;
    numeric features only, like the reference's validation)."""
    mc = p.get("monotone_constraints") or {}
    if isinstance(mc, (list, tuple)):
        # h2o-py serializes this as KeyValue pairs
        # ([{'key': col, 'value': ±1}, ...], water/api/schemas3/KeyValueV3)
        mc = {kv["key"]: kv["value"] for kv in mc}
    if not mc:
        return None
    unknown_cols = set(mc) - set(x)
    if unknown_cols:
        raise ValueError(f"monotone_constraints columns not in "
                         f"predictors: {sorted(unknown_cols)}")
    bad = [c for c in mc if frame.col(c).is_categorical]
    if bad:
        raise ValueError("monotone_constraints require numeric "
                         f"columns; categorical: {sorted(bad)}")
    if category == ModelCategory.MULTINOMIAL:
        raise ValueError("monotone_constraints are not supported "
                         "for multinomial distributions")
    arr = np.zeros(len(x), np.int8)
    for c, d in mc.items():
        arr[x.index(c)] = int(np.sign(d))
    return jnp.asarray(arr)


def _build_interaction_sets(p, x):
    """Interaction-constraint set matrix (GBM interaction_constraints;
    hex/tree/GlobalInteractionConstraints): listed groups may interact
    internally; unlisted features become singleton sets."""
    ic = p.get("interaction_constraints")
    if not ic:
        return None
    unknown_cols = {c for grp in ic for c in grp} - set(x)
    if unknown_cols:
        raise ValueError("interaction_constraints columns not in "
                         f"predictors: {sorted(unknown_cols)}")
    listed = {c for grp in ic for c in grp}
    groups = [list(grp) for grp in ic]
    groups += [[c] for c in x if c not in listed]
    S = np.zeros((len(groups), len(x)), bool)
    for si, grp in enumerate(groups):
        for c in grp:
            S[si, x.index(c)] = True
    return jnp.asarray(S)


class GBMModel(Model):
    algo = "gbm"

    def __init__(self, params, output, forest: Tree, bm: BinnedMatrix,
                 f0: np.ndarray, dist_name: str):
        super().__init__(params, output)
        self.forest = forest          # [T(*K), D, Lmax] stacked
        self.bm = bm                  # training binning spec (edges reused to score)
        self.f0 = f0
        self.dist_name = dist_name

    # margin(s) on a binned matrix
    def _margins(self, bm: BinnedMatrix, offset=None):
        B = bm.nbins_total
        K = self.output.get("nclasses", 2)
        if self.output["category"] == ModelCategory.MULTINOMIAL:
            T = self.forest.feat.shape[0] // K
            outs = []
            for k in range(K):
                f = Tree(*(a.reshape((T, K) + a.shape[1:])[:, k]
                           for a in self.forest))
                outs.append(predict_forest(f, bm.bins, B))
            m = self.f0[None, :] + jnp.stack(outs, axis=1)
            return m if offset is None else m + offset[:, None]
        m = self.f0 + predict_forest(self.forest, bm.bins, B)
        return m if offset is None else m + offset

    def _frame_offset(self, frame: Frame, npad: int):
        """Per-row margin offset from the frame's offset_column
        (hex/Model scoring applies the offset at predict time too)."""
        oc = self.params.get("offset_column")
        if not oc or oc not in frame:
            return None
        o = np.nan_to_num(frame.col(oc).to_numpy()).astype(np.float32)
        return jnp.asarray(np.pad(o, (0, npad - len(o))))

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        bm = rebin_for_scoring(self.bm, frame)
        n = frame.nrows
        off = self._frame_offset(frame, bm.bins.shape[0])
        if off is None:
            # the model's ONE compiled scoring program — the same
            # executable the serving tier dispatches, so row-payload
            # predictions match bit-for-bit (Model._serve_jit)
            return self._serve_finish(_fetch_np(self._serve_jit()(bm.bins)),
                                      n)
        marg = self._margins(bm, off)
        cat = self.output["category"]
        if cat == ModelCategory.BINOMIAL:
            dist = get_distribution("bernoulli")
            p1 = _fetch_np(dist.link_inv(marg))[:n]
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (p1 >= t).astype(np.int32),
                    "p0": 1.0 - p1, "p1": p1}
        if cat == ModelCategory.MULTINOMIAL:
            p = _fetch_np(jax.nn.softmax(marg, axis=1))[:n]
            out = {"predict": p.argmax(axis=1).astype(np.int32)}
            for k in range(p.shape[1]):
                out[f"p{k}"] = p[:, k]
            return out
        dist = get_distribution(self.dist_name, **self.params)
        return {"predict": _fetch_np(dist.link_inv(marg))[:n]}

    def _score_dev(self, frame: Frame):
        """Device-resident holdout scoring for the near-LOO CV sweep
        (ml/cv.py light mode): the padded device array the CV merge
        needs (p1 / [N,K] probs / prediction) with NO host sync, so
        hundreds of fold scores pipeline through the async dispatch
        queue and the sweep pays one batched fetch at the end."""
        bm = rebin_for_scoring(self.bm, frame)
        marg = self._margins(bm, self._frame_offset(frame,
                                                    bm.bins.shape[0]))
        cat = self.output["category"]
        if cat == ModelCategory.BINOMIAL:
            return get_distribution("bernoulli").link_inv(marg)
        if cat == ModelCategory.MULTINOMIAL:
            return jax.nn.softmax(marg, axis=1)
        return get_distribution(self.dist_name, **self.params).link_inv(marg)

    def _serve_dev(self, bins):
        """Device half of the serving fast path (serving/engine.py jits
        this per row bucket): EXACTLY the device math of ``_score_raw``
        on a pre-binned matrix. Offset models take the engine's eager
        fallback, so no offset input rides here."""
        import types
        bm = types.SimpleNamespace(bins=bins,
                                   nbins_total=self.bm.nbins_total)
        marg = self._margins(bm)
        cat = self.output["category"]
        if cat == ModelCategory.BINOMIAL:
            return get_distribution("bernoulli").link_inv(marg)
        if cat == ModelCategory.MULTINOMIAL:
            return jax.nn.softmax(marg, axis=1)
        return get_distribution(self.dist_name, **self.params).link_inv(marg)

    def _serve_finish(self, fetched: np.ndarray, n: int) -> Dict[str, np.ndarray]:
        """Host half of the serving fast path: the exact host tail of
        ``_score_raw`` applied to the fetched device output."""
        cat = self.output["category"]
        if cat == ModelCategory.BINOMIAL:
            p1 = fetched[:n]
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (p1 >= t).astype(np.int32),
                    "p0": 1.0 - p1, "p1": p1}
        if cat == ModelCategory.MULTINOMIAL:
            p = fetched[:n]
            out = {"predict": p.argmax(axis=1).astype(np.int32)}
            for k in range(p.shape[1]):
                out[f"p{k}"] = p[:, k]
            return out
        return {"predict": fetched[:n]}

    def predict_leaf_node_assignment(self, frame: Frame) -> Frame:
        """Per-tree terminal node ids (h2o-py predict_leaf_node_assignment
        with type=Node_ID); per-class columns T{t}.C{k} for multinomial."""
        from h2o3_tpu.models.tree import leaf_assignment_frame
        return leaf_assignment_frame(self, frame)

    def feature_frequencies(self, frame: Frame) -> Frame:
        """Per-row feature usage counts on decision paths
        (h2o-py model.feature_frequencies / SharedTreeModel)."""
        from h2o3_tpu.models.tree import feature_frequencies_frame
        return feature_frequencies_frame(self, frame)

    def staged_predict_proba(self, frame: Frame) -> Frame:
        """Cumulative per-stage probabilities (h2o-py
        staged_predict_proba; SharedTreeModel staged scoring): column
        T{t}.C1 after t trees for binomial (p0, matching the reference's
        first-class convention), T{t} for regression."""
        bm = rebin_for_scoring(self.bm, frame)
        n = frame.nrows
        cat = self.output["category"]
        B = bm.nbins_total
        cols = {}
        # stage margins accumulate on device; ONE host fetch at the end
        # (a per-tree fetch costs a full tunnel round trip each)
        if cat == ModelCategory.MULTINOMIAL:
            K = self.output.get("nclasses", 2)
            T = self.forest.feat.shape[0] // K
            margins = jnp.broadcast_to(
                jnp.asarray(self.f0)[None, :],
                (bm.bins.shape[0], K)).astype(jnp.float32)
            stages = []
            for t in range(T):
                for k in range(K):
                    tr = Tree(*(a[t * K + k] for a in self.forest))
                    margins = margins.at[:, k].add(
                        predict_tree(tr, bm.bins, B))
                stages.append(jax.nn.softmax(margins, axis=1))
            probs = _fetch_np(jnp.stack(stages))[:, :n]     # [T, n, K]
            for t in range(T):
                for k in range(K):
                    cols[f"T{t + 1}.C{k + 1}"] = probs[t, :, k]
            return Frame.from_numpy(cols)
        T = self.forest.feat.shape[0]
        margin = jnp.full((bm.bins.shape[0],), self.f0, jnp.float32)
        dist = get_distribution(
            "bernoulli" if cat == ModelCategory.BINOMIAL else
            self.dist_name, **self.params)
        off = self._frame_offset(frame, bm.bins.shape[0])
        if off is not None:
            margin = margin + off
        stages = []
        for t in range(T):
            tr = Tree(*(a[t] for a in self.forest))
            margin = margin + predict_tree(tr, bm.bins, B)
            stages.append(dist.link_inv(margin))
        mus = _fetch_np(jnp.stack(stages))[:, :n]           # [T, n]
        for t in range(T):
            if cat == ModelCategory.BINOMIAL:
                cols[f"T{t + 1}.C1"] = 1.0 - mus[t]         # p0 convention
            else:
                cols[f"T{t + 1}"] = mus[t]
        return Frame.from_numpy(cols)

    def predict_contributions(self, frame: Frame) -> Frame:
        """TreeSHAP contributions (h2o-py predict_contributions): feature
        columns + BiasTerm, summing to the raw link-space margin."""
        from h2o3_tpu.ml.shap import contributions_frame
        if self.output["category"] == ModelCategory.MULTINOMIAL:
            raise ValueError("predict_contributions supports only "
                             "regression and binomial models "
                             "(got Multinomial)")
        return contributions_frame(self, frame, bias_offset=float(self.f0))

    def model_performance(self, frame: Frame, mask_weights=None):
        """``mask_weights`` (padded [nrows_padded] float) restricts the
        metric pass to a row subset — the CV fast path scores fold
        holdouts on the parent frame without building a subset frame."""
        y = self.output["response"]
        bm = rebin_for_scoring(self.bm, frame)
        marg = self._margins(bm, self._frame_offset(frame,
                                                    bm.bins.shape[0]))
        w = frame.valid_weights()
        wc_name = self.params.get("weights_column")
        if wc_name and wc_name in frame:
            wc = frame.col(wc_name).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        if mask_weights is not None:
            w = w * jnp.asarray(mask_weights, jnp.float32)
        cat = self.output["category"]
        if cat in (ModelCategory.BINOMIAL, ModelCategory.MULTINOMIAL):
            from h2o3_tpu.models.model import adapt_domain
            yv = adapt_domain(frame.col(y), self.output["domain"])
            yv = np.pad(yv, (0, bm.bins.shape[0] - frame.nrows),
                        constant_values=-1)
            w = w * jnp.asarray((yv >= 0).astype(np.float32))  # NA response out
            yv = np.maximum(yv, 0)
            if cat == ModelCategory.BINOMIAL:
                p = get_distribution("bernoulli").link_inv(marg)
                return mm.binomial_metrics(p, jnp.asarray(yv.astype(np.float32)), w)
            p = jax.nn.softmax(marg, axis=1)
            return mm.multinomial_metrics(p, jnp.asarray(yv), w,
                                          domain=self.output["domain"])
        dist = get_distribution(self.dist_name, **self.params)
        yv = frame.col(y).numeric_view()
        w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
        yv = jnp.where(jnp.isnan(yv), 0.0, yv)
        return mm.regression_metrics(dist.link_inv(marg), yv, w,
                                     deviance_fn=lambda yy, pp: dist.deviance(yy, marg))

    @property
    def varimp_table(self) -> List:
        vi = self.output.get("varimp") or []
        return vi


class GBMEstimator(ModelBuilder):
    """h2o-py H2OGradientBoostingEstimator-compatible surface
    (h2o-py/h2o/estimators/gbm.py)."""

    algo = "gbm"
    cv_fold_masking = True   # ml/cv.py fast path: folds = masked weights

    DEFAULTS = dict(
        max_runtime_secs=0.0,
        ntrees=50, max_depth=5, min_rows=10.0, learn_rate=0.1,
        sample_rate=1.0, col_sample_rate_per_tree=1.0,
        nbins=64, nbins_cats=1024, distribution="auto",
        custom_distribution_func=None,
        # reg_lambda=0: the reference GammaPass has no ridge term
        # (hex/tree/gbm/GBM.java leaf gamma = sum g / sum h); the
        # xgboost facade passes its own lambda
        min_split_improvement=1e-5, seed=-1, reg_lambda=0.0,
        nfolds=0, weights_column=None, fold_column=None,
        offset_column=None, fold_assignment="auto",
        keep_cross_validation_models=True,
        keep_cross_validation_predictions=False,
        keep_cross_validation_fold_assignment=False,
        ignored_columns=None, tweedie_power=1.5, quantile_alpha=0.5,
        huber_alpha=0.9, stopping_rounds=0, stopping_metric="auto",
        stopping_tolerance=1e-3, score_tree_interval=0, checkpoint=None,
        monotone_constraints=None, interaction_constraints=None,
        calibrate_model=False, calibration_frame=None,
        calibration_method="PlattScaling",
        check_constant_response=True,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown GBM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _resolve_distribution(self, category: str) -> str:
        d = self.params["distribution"]
        if d != "auto":
            return d
        return {"Binomial": "bernoulli", "Multinomial": "multinomial",
                "Regression": "gaussian"}[category]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        category = infer_category(frame, y)
        dist_name = self._resolve_distribution(category)
        # light mode (ml/cv.py large-nfolds folds): skip varimp/metric
        # device syncs — leave-one-out CV pays per-fold for each one
        light = bool(getattr(self, "_cv_light", False))

        # checkpoint restart (SharedTree _checkpoint via
        # hex/util/CheckpointUtils + ReconstructTreeState): reuse the
        # prior model's binning so its trees stay valid, resume margins
        # from its predictions, and append trees up to the new ntrees.
        ckpt: Optional[GBMModel] = None
        ck = p.get("checkpoint")
        if ck is not None:
            ckpt = resolve_checkpoint_model("gbm", ck, GBMModel)
            if ckpt.output["response"] != y:
                raise checkpoint_error(
                    "gbm", "response_column",
                    "Field _response_column cannot be modified if "
                    "checkpoint is provided (checkpoint response "
                    f"mismatch: {ckpt.output['response']!r} vs {y!r})")
            if list(ckpt.bm.names) != list(x):
                raise checkpoint_error(
                    "gbm", "ignored_columns",
                    "The predictor set cannot be modified if checkpoint "
                    "is provided (checkpoint feature set mismatch)")
            if ckpt.output["category"] != category:
                raise checkpoint_error(
                    "gbm", "response_column",
                    "checkpoint model category mismatch "
                    f"({ckpt.output['category']} vs {category})")
            if ckpt.dist_name != dist_name:
                raise checkpoint_error(
                    "gbm", "distribution",
                    "Field _distribution cannot be modified if "
                    "checkpoint is provided: distribution cannot change "
                    f"across checkpoint restart ({ckpt.dist_name} vs "
                    f"{dist_name})")
            validate_checkpoint_params("gbm", ckpt.params, p,
                                       CHECKPOINT_NON_MODIFIABLE)

        # device weights + an equal HOST mirror (_host_weights): every
        # host-side consumer (bin sketch, init means, priors) reads the
        # mirror instead of syncing the device — a CV sweep calls _fit
        # once per fold, and per-fold fetches dominate leave-one-out CV
        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        w = self._cv_masked_weights(w, frame)
        # rows with a missing response are excluded from training and
        # training metrics (reference ModelBuilder drops them)
        rc = frame.col(y)
        if p.get("check_constant_response", True) and not rc.is_categorical:
            yh = rc.to_numpy()
            vals = yh[~np.isnan(yh)]
            if vals.size and float(vals.min()) == float(vals.max()):
                raise ValueError(
                    "Response cannot be constant - check your response "
                    "column, or set check_constant_response=False")
        wh_host = self._host_weights(frame, y)
        resp_na_host = np.isnan(rc.to_numpy())   # cached host view
        if resp_na_host.any():
            w = w * jnp.asarray(np.pad(
                (~resp_na_host).astype(np.float32),
                (0, frame.nrows_padded - frame.nrows)))

        shared_bm = getattr(self, "_cv_shared_bm", None)
        if ckpt is not None:
            bm = rebin_for_scoring(ckpt.bm, frame)
        elif shared_bm is not None:
            # CV fold models reuse the main model's full-data bin edges
            # (deliberate: per-fold edge re-sketches cost more than the
            # sketch approximation is worth; the histogram is adaptive
            # per node anyway)
            bm = shared_bm
        else:
            # weighted edges: the row-weight ≡ row-multiplicity contract
            # (pyunit_weights_gbm) must hold through the bin sketch too
            bm = bin_frame(frame, x, nbins=p["nbins"],
                           nbins_cats=p["nbins_cats"], weights=wh_host)

        w, w_scale = self._normalize_uniform_weights(w, wh_host)
        if w_scale != 1.0:
            wh_host = wh_host / np.float32(w_scale)

        tp = TreeParams(
            max_depth=int(p["max_depth"]),
            min_rows=float(p["min_rows"]) / w_scale,
            learn_rate=float(p["learn_rate"]),
            reg_lambda=float(p["reg_lambda"]) / w_scale,
            min_split_improvement=float(p["min_split_improvement"])
            / w_scale,
            col_sample_rate=float(p["col_sample_rate_per_tree"]),
            nbins_total=bm.nbins_total,
            cat_feats=tuple(bool(v) for v in bm.is_cat),
            # 10M+ rows: bigger histogram row blocks — 4096-row blocks
            # put a 12K-iteration inner scan in every tree at 50M and
            # underfeed the MXU contraction
            block_rows=16384 if bm.bins.shape[0] > 8_388_608 else 4096,
            exact_f32=exact_f32_for(bm),
            pallas=pallas_ops.resolve_tree_mode())

        constraints = _build_constraints(p, x, frame, category)
        interaction_sets = _build_interaction_sets(p, x)

        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xDEC0DE
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        # max_runtime_secs (Model.Parameters._max_runtime_secs): a
        # GRACEFUL stop at the next chunk boundary keeping the trees
        # built so far — the reference returns the partial model, it
        # does not discard it
        _cap = float(p.get("max_runtime_secs") or 0.0)
        _deadline = (time.time() + _cap) if _cap > 0 else None
        # deadline granularity: the stop can only fire at a chunk
        # boundary, so capped fits shrink the chunk as per-tree cost
        # grows (complete-tree layout: ~2^depth * nbins per tree) —
        # a 25-deep-tree chunk at depth bucket 10 runs ~20-80s, far
        # past a ~30s AutoML slice. Uncapped fits keep 25 (no extra
        # program shapes on the pyunit paths).
        # row scale bounds single-program runtime: a 25-tree fused scan
        # at 50M rows runs minutes inside ONE XLA program and trips the
        # tunnel worker's execution watchdog ("TPU worker process
        # crashed") — chunks shrink past ~5M padded rows so each
        # program stays ~tens of seconds. <=5M keeps 25 (pyunits and
        # the flagship bench shapes are untouched).
        _rows_scale = max(1.0, bm.bins.shape[0] / 5_242_880.0)
        if _deadline is not None:
            _cost = (2.0 ** tp.max_depth / 64.0) * (bm.nbins_total / 65.0) \
                * _rows_scale
            _chunk = max(1, min(25, int(round(25.0 / max(_cost, 1.0)))))
        else:
            _chunk = max(1, min(25, int(round(25.0 / _rows_scale))))
        prior_T = 0
        if ckpt is not None:
            K_ck = (ckpt.output.get("nclasses", 1)
                    if ckpt.output["category"] == ModelCategory.MULTINOMIAL
                    else 1)
            prior_T = ckpt.forest.feat.shape[0] // K_ck
            if ntrees <= prior_T:
                raise checkpoint_error(
                    "gbm", "ntrees",
                    f"If checkpoint is provided, ntrees ({ntrees}) must "
                    f"exceed the checkpoint model's tree count "
                    f"({prior_T})")
            ntrees = ntrees - prior_T
        output = {"category": category, "response": y, "names": list(x),
                  "nclasses": rc.cardinality if rc.is_categorical else 1,
                  "domain": rc.domain}
        trees: List[Tree] = []
        gains_total = np.zeros(len(x), np.float32)
        from h2o3_tpu.models.model import EarlyStopper
        stopper = EarlyStopper(int(p["stopping_rounds"]),
                               float(p["stopping_tolerance"]))
        score_interval = int(p["score_tree_interval"]) or 5
        scoring_history: List[dict] = []
        # in-fit checkpointer (core/recovery.py): every K trees the
        # chunk host boundary persists device-independent partial state
        # (forest so far, margins, PRNG-independent counters, early-stop
        # + scoring history) so a killed fit resumes bit-identically.
        # CV fold fits skip it — their params fingerprint would collide
        # and fold models are discarded after holdout scoring anyway.
        fc = fc_state = None
        if not light and getattr(self, "_cv_fold_mask", None) is None:
            fc = _recovery.fit_checkpointer("gbm", p, y, x, frame.nrows,
                                            default_every=25)
            if fc is not None:
                _loaded = fc.load()
                if _loaded is not None:
                    fc_state = _loaded[1]
        # early stopping watches the validation set when given, else training
        # (reference ScoreKeeper semantics, hex/tree/SharedTree.java)
        vbm = val_y = val_w = None
        if validation_frame is not None and stopper.enabled:
            vbm = rebin_for_scoring(bm, validation_frame)
            val_w = validation_frame.valid_weights()
            vc = validation_frame.col(y)
            if vc.is_categorical:
                from h2o3_tpu.models.model import adapt_domain
                vy = adapt_domain(vc, rc.domain)
                vy = np.pad(vy, (0, vbm.bins.shape[0] - validation_frame.nrows),
                            constant_values=-1)
                val_w = val_w * jnp.asarray((vy >= 0).astype(np.float32))
                val_y = jnp.asarray(np.maximum(vy, 0).astype(np.float32))
            else:
                vy = vc.numeric_view()
                val_w = val_w * jnp.where(jnp.isnan(vy), 0.0, 1.0)
                val_y = jnp.where(jnp.isnan(vy), 0.0, vy)

        if category == ModelCategory.MULTINOMIAL:
            from h2o3_tpu.models.model import adapt_domain
            K = rc.cardinality
            yv = np.nan_to_num(rc.to_numpy()).astype(np.int32)  # host cache
            yv = np.pad(yv, (0, bm.bins.shape[0] - frame.nrows))
            y_dev = put_sharded(yv, row_sharding(mesh))
            # weighted class priors over rows that actually train, from
            # the host weight mirror (no device sync)
            counts = np.bincount(yv[: frame.nrows], weights=wh_host,
                                 minlength=K).astype(np.float64)
            pri = np.clip(counts / max(counts.sum(), 1e-12), 1e-10, 1.0)
            if ckpt is not None:
                f0 = ckpt.f0
                margins = jax.device_put(ckpt._margins(bm).astype(jnp.float32),
                                         row_sharding(mesh))
            else:
                f0 = np.log(pri).astype(np.float32)
                margins = jnp.broadcast_to(
                    jnp.asarray(f0)[None, :],
                    (bm.bins.shape[0], K)).astype(jnp.float32)
                margins = put_sharded(margins, row_sharding(mesh))
            if vbm is None:
                val_margins = None
            elif ckpt is not None:   # resume incl. the prior forest's part
                val_margins = ckpt._margins(vbm).astype(jnp.float32)
            else:
                val_margins = jnp.broadcast_to(
                    jnp.asarray(f0)[None, :],
                    (vbm.bins.shape[0], K)).astype(jnp.float32)
            # fused scan path: chunks of score_interval trees (25 when
            # no stopper), ONE host sync + scalar deviance per chunk
            use_val = vbm is not None
            if use_val:
                vb_, vy_, vw_, vm_ = (vbm.bins, val_y.astype(jnp.int32),
                                      val_w, val_margins)
            else:   # dummies — static use_val=False keeps them untraced
                vb_ = jnp.zeros((1, bm.bins.shape[1]), bm.bins.dtype)
                vy_ = jnp.zeros((1,), jnp.int32)
                vw_ = jnp.zeros((1,), jnp.float32)
                vm_ = jnp.zeros((1, K), jnp.float32)
            chunks_m: List[Tree] = []
            done = 0
            if fc_state is not None and fc_state.get("path") == "multi":
                done = int(fc_state["done"])
                if fc_state["trees"] is not None:
                    chunks_m.append(_tree_dev(fc_state["trees"]))
                margins = put_sharded(jnp.asarray(fc_state["margins"]),
                                      row_sharding(mesh))
                vm_ = jnp.asarray(fc_state["vm"])
                gains_total = fc_state["gains_total"].copy()
                stopper.history = list(fc_state["stop_hist"])
                scoring_history = list(fc_state["scoring_history"])
            while done < ntrees:
                kk = min(_chunk, ntrees - done)
                _ct0 = time.time()
                stepprof.chunk_begin()
                with telemetry.span("gbm.chunk", trees=kk):
                    tr_k, margins, vm_, gains, devs = _boost_scan_multi(
                        bm.bins, bm.nbins, y_dev, w, margins, key,
                        vb_, vy_, vw_, vm_, interaction_sets, tp=tp,
                        sample_rate=float(p["sample_rate"]), n_class=K,
                        ntrees=kk, B=bm.nbins_total, use_val=use_val,
                        tree0=prior_T + done)
                    stepprof.compute_done((margins, vm_, devs))
                telemetry.histogram("train_chunk_seconds",
                                    algo="gbm").observe(time.time() - _ct0)
                telemetry.counter("train_iterations_total",
                                  algo="gbm").inc(kk)
                stepprof.chunk_end(trees=kk)
                keep = (_stop_point(np.asarray(devs), done, kk,
                                    score_interval, stopper,
                                    scoring_history)
                        if stopper.enabled else kk)
                # scan stacks per-iter [K,...] trees → [kk, K, ...]
                chunks_m.append(Tree(*(
                    a[:keep].reshape((keep * K,) + a.shape[2:])
                    for a in tr_k)))
                if not light:
                    gains_total += np.asarray(gains)[:keep].sum(axis=0)
                done += keep
                job.update(kk / ntrees, f"tree {done}/{ntrees}")
                if keep < kk:
                    # early stop: the fit completes right after; a crash
                    # past this point replays from the last boundary and
                    # stops at the same tree (deterministic stopper)
                    break
                if fc is not None:
                    _d, _mg, _vm = done, margins, vm_
                    fc.maybe_save(done, lambda: {
                        "path": "multi", "done": _d,
                        "trees": (_tree_host(concat_forests(chunks_m))
                                  if chunks_m else None),
                        "margins": _recovery.snapshot_host(_mg),
                        "vm": _recovery.snapshot_host(_vm),
                        "gains_total": gains_total.copy(),
                        "stop_hist": list(stopper.history),
                        "scoring_history": list(scoring_history)})
                maybe_fail("fit_chunk")
                maybe_fail("device_oom")
                if _deadline and time.time() > _deadline:
                    log.info("max_runtime_secs: GBM stopping at %d/%d "
                             "trees", done, ntrees)
                    break
            forest = concat_forests(chunks_m)
            if ckpt is not None:
                forest = Tree(*(jnp.concatenate([getattr(ckpt.forest, f),
                                                 getattr(forest, f)])
                                for f in Tree._fields))
            model = GBMModel(p, output, forest, bm, f0, "multinomial")
            if not light:
                probs = jax.nn.softmax(model._margins(bm), axis=1)
                model.training_metrics = mm.multinomial_metrics(
                    probs, y_dev, w, domain=rc.domain)
        else:
            if category == ModelCategory.BINOMIAL:
                dist = get_distribution("bernoulli")
            else:
                dist = get_distribution(dist_name, **p)
            yv = np.nan_to_num(rc.to_numpy()).astype(np.float32)
            # host weighted mean from the weight mirror — no device
            # sync (w is numerically equal, host caches are replicated)
            mean_y = (float(np.sum(yv * wh_host))
                      / max(float(np.sum(wh_host)), 1e-12))
            yv = np.pad(yv, (0, bm.bins.shape[0] - frame.nrows))
            y_dev = put_sharded(yv, row_sharding(mesh))
            # offset_column: per-row base margin (GBM.java offset
            # handling; init_f solved WITH the offset in place)
            off = None
            if p.get("offset_column") and p["offset_column"] in frame:
                onp = np.nan_to_num(
                    frame.col(p["offset_column"]).to_numpy()
                ).astype(np.float32)
                onp = np.pad(onp, (0, bm.bins.shape[0] - frame.nrows))
                off = put_sharded(jnp.asarray(onp), row_sharding(mesh))
            if ckpt is not None:
                f0 = ckpt.f0
                margin = put_sharded(
                    ckpt._margins(bm).astype(jnp.float32), row_sharding(mesh))
                if off is not None:
                    margin = margin + off
            elif off is None:
                f0 = np.float32(dist.init_margin(mean_y))
                margin = jnp.full((bm.bins.shape[0],), f0, jnp.float32)
                margin = put_sharded(margin, row_sharding(mesh))
            else:
                # Newton solve of the offset-adjusted init
                # (DistributionFactory init task role)
                c = jnp.float32(dist.init_margin(mean_y))
                for _ in range(25):
                    gsum = jnp.sum(w * dist.grad(y_dev, off + c))
                    hsum = jnp.sum(w * dist.hess(y_dev, off + c))
                    c = c - gsum / jnp.maximum(hsum, 1e-12)
                f0 = np.float32(c)
                margin = off + f0
            output["init_f"] = float(f0)
            voff = None
            if vbm is not None and p.get("offset_column") and \
                    p["offset_column"] in validation_frame:
                vo = np.nan_to_num(validation_frame.col(
                    p["offset_column"]).to_numpy()).astype(np.float32)
                voff = jnp.asarray(np.pad(
                    vo, (0, vbm.bins.shape[0] - len(vo))))
            if vbm is None:
                val_margin = None
            elif ckpt is not None:   # resume incl. the prior forest's part
                val_margin = ckpt._margins(vbm).astype(jnp.float32)
                if voff is not None:
                    val_margin = val_margin + voff
            else:
                val_margin = jnp.full((vbm.bins.shape[0],), f0, jnp.float32)
                if voff is not None:
                    val_margin = val_margin + voff
            if not stopper.enabled:   # vbm only exists when stopping is on
                # boosting loop as compiled scans over tree chunks — the
                # per-tree host round trip (dominant on a remote chip)
                # amortizes over CHUNK trees, while the inter-chunk
                # job.update keeps progress reporting + cancellation live
                chunks = []
                done = 0
                if fc_state is not None and fc_state.get("path") == "plain":
                    done = int(fc_state["done"])
                    if fc_state["trees"] is not None:
                        chunks.append(_tree_dev(fc_state["trees"]))
                    margin = put_sharded(jnp.asarray(fc_state["margin"]),
                                         row_sharding(mesh))
                    gains_total = fc_state["gains_total"].copy()
                while done < ntrees:
                    k = min(_chunk, ntrees - done)
                    _ct0 = time.time()
                    stepprof.chunk_begin()
                    with telemetry.span("gbm.chunk", trees=k):
                        tr_k, margin, gains = _boost_scan(
                            bm.bins, bm.nbins, y_dev, w, margin, key,
                            constraints, interaction_sets, tp=tp,
                            dist=dist, sample_rate=float(p["sample_rate"]),
                            ntrees=k, tree0=prior_T + done)
                        stepprof.compute_done((margin, gains))
                    telemetry.histogram(
                        "train_chunk_seconds",
                        algo="gbm").observe(time.time() - _ct0)
                    telemetry.counter("train_iterations_total",
                                      algo="gbm").inc(k)
                    stepprof.chunk_end(trees=k)
                    chunks.append(tr_k)
                    if not light:
                        gains_total += np.asarray(gains)
                    done += k
                    job.update(k / ntrees, f"tree {done}/{ntrees}")
                    if fc is not None:
                        _d, _mg = done, margin
                        fc.maybe_save(done, lambda: {
                            "path": "plain", "done": _d,
                            "trees": (_tree_host(concat_forests(chunks))
                                      if chunks else None),
                            "margin": _recovery.snapshot_host(_mg),
                            "gains_total": gains_total.copy()})
                    maybe_fail("fit_chunk")
                    maybe_fail("device_oom")
                    if _deadline and time.time() > _deadline:
                        log.info("max_runtime_secs: GBM stopping at "
                                 "%d/%d trees", done, ntrees)
                        break
                forest = concat_forests(chunks)
            else:
                # early stopping WITHOUT leaving the fused path: chunks
                # of score_interval trees, deviance computed inside the
                # compiled program, host checks one scalar per chunk
                use_val = vbm is not None
                if use_val:
                    vb_, vy_, vw_, vm_ = (vbm.bins, val_y, val_w,
                                          val_margin)
                else:
                    vb_ = jnp.zeros((1, bm.bins.shape[1]), bm.bins.dtype)
                    vy_ = jnp.zeros((1,), jnp.float32)
                    vw_ = jnp.zeros((1,), jnp.float32)
                    vm_ = jnp.zeros((1,), jnp.float32)
                chunks = []
                done = 0
                if fc_state is not None and fc_state.get("path") == "scored":
                    done = int(fc_state["done"])
                    if fc_state["trees"] is not None:
                        chunks.append(_tree_dev(fc_state["trees"]))
                    margin = put_sharded(jnp.asarray(fc_state["margin"]),
                                         row_sharding(mesh))
                    vm_ = jnp.asarray(fc_state["vm"])
                    gains_total = fc_state["gains_total"].copy()
                    stopper.history = list(fc_state["stop_hist"])
                    scoring_history = list(fc_state["scoring_history"])
                while done < ntrees:
                    k = min(_chunk, ntrees - done)
                    _ct0 = time.time()
                    stepprof.chunk_begin()
                    with telemetry.span("gbm.chunk", trees=k):
                        tr_k, margin, vm_, gains, devs = \
                            _boost_scan_scored(
                                bm.bins, bm.nbins, y_dev, w, margin, key,
                                vb_, vy_, vw_, vm_,
                                constraints, interaction_sets, tp=tp,
                                dist=dist,
                                sample_rate=float(p["sample_rate"]),
                                ntrees=k, B=bm.nbins_total,
                                use_val=use_val, tree0=prior_T + done)
                        stepprof.compute_done((margin, vm_, devs))
                    telemetry.histogram(
                        "train_chunk_seconds",
                        algo="gbm").observe(time.time() - _ct0)
                    telemetry.counter("train_iterations_total",
                                      algo="gbm").inc(k)
                    stepprof.chunk_end(trees=k)
                    keep = _stop_point(np.asarray(devs), done, k,
                                       score_interval, stopper,
                                       scoring_history)
                    chunks.append(Tree(*(a[:keep] for a in tr_k)))
                    gains_total += np.asarray(gains)[:keep].sum(axis=0)
                    done += keep
                    job.update(k / ntrees, f"tree {done}/{ntrees}")
                    if keep < k:
                        break
                    if fc is not None:
                        _d, _mg, _vm = done, margin, vm_
                        fc.maybe_save(done, lambda: {
                            "path": "scored", "done": _d,
                            "trees": (_tree_host(concat_forests(chunks))
                                      if chunks else None),
                            "margin": _recovery.snapshot_host(_mg),
                            "vm": _recovery.snapshot_host(_vm),
                            "gains_total": gains_total.copy(),
                            "stop_hist": list(stopper.history),
                            "scoring_history": list(scoring_history)})
                    maybe_fail("fit_chunk")
                    maybe_fail("device_oom")
                    if _deadline and time.time() > _deadline:
                        log.info("max_runtime_secs: GBM stopping at "
                                 "%d/%d trees", done, ntrees)
                        break
                forest = concat_forests(chunks)
            if ckpt is not None:
                forest = Tree(*(jnp.concatenate([getattr(ckpt.forest, f),
                                                 getattr(forest, f)])
                                for f in Tree._fields))
            model = GBMModel(p, output, forest, bm, f0, dist_name)
            if light:
                model.output["default_threshold"] = 0.5
            elif category == ModelCategory.BINOMIAL:
                pfin = dist.link_inv(model._margins(bm, off))
                model.training_metrics = mm.binomial_metrics(pfin, y_dev, w)
                model.output["default_threshold"] = \
                    model.training_metrics["max_f1_threshold"]
            else:
                # recompute margins from the (possibly stop-truncated)
                # forest — `margin` may include discarded trees
                mfin = model._margins(bm, off)
                model.training_metrics = mm.regression_metrics(
                    dist.link_inv(mfin), y_dev, w,
                    deviance_fn=lambda yy, pp: dist.deviance(yy, mfin))

        if fc is not None:
            # training finished: a completed model must never resume
            fc.clear()
        model.output["scoring_history"] = scoring_history
        if light:
            model.output["varimp"] = None
        else:
            # scaled relative importance (hex/VarImp semantics)
            vi = gains_total
            order = np.argsort(-vi)
            tot = vi.sum() or 1.0
            model.output["varimp"] = [
                (x[i], float(vi[i]), float(vi[i] / max(vi.max(), 1e-12)),
                 float(vi[i] / tot)) for i in order]
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        from h2o3_tpu.ml.calibration import maybe_calibrate
        maybe_calibrate(model, p, category)
        return model


# ---- model-batched training (parallel/model_batch.py trainer) ----------


def fit_gbm_batched(builder_cls, params_list: List[dict], frame: Frame,
                    y: Optional[str] = None, x: Optional[Sequence[str]] = None,
                    validation_frame: Optional[Frame] = None) -> List[Model]:
    """Train a whole shape bucket of GBM hyperparameter combos as ONE
    vmapped boosting program (_boost_scan_batched): the shared preamble
    (binning, weights, init margin) runs once, per-model numeric knobs
    stack into a [M, 7] matrix, and the host touches the device once per
    tree CHUNK for the whole bucket instead of once per model per chunk.

    Raises parallel.model_batch.BatchIneligible for anything the vmapped
    program cannot express (CV, checkpoints, multinomial, runtime caps,
    validation-frame early stopping) — the caller falls back to the
    sequential per-combo path, so semantics are always preserved.
    Models return in ``params_list`` order with the same outputs the
    sequential path produces (metrics, varimp, scoring history,
    threshold), matching it within float tolerance."""
    from h2o3_tpu.parallel.model_batch import BATCHABLE_KNOBS, BatchIneligible

    builders = [builder_cls(**p) for p in params_list]
    M = len(builders)
    b0 = builders[0]
    p0 = b0.params
    batchable = BATCHABLE_KNOBS["gbm"]
    for b in builders[1:]:
        for k, v in b.params.items():
            if k not in batchable and v != p0.get(k):
                raise BatchIneligible(f"structural param '{k}' varies")
    for b in builders:
        p = b.params
        if int(p.get("nfolds") or 0) >= 2 or p.get("fold_column"):
            raise BatchIneligible("cross-validation")
        if p.get("checkpoint") is not None:
            raise BatchIneligible("checkpoint restart")
        if p.get("custom_distribution_func"):
            raise BatchIneligible("custom distribution")
        if float(p.get("max_runtime_secs") or 0.0) > 0:
            raise BatchIneligible("per-model runtime cap")
    depths = [int(b.params["max_depth"]) for b in builders]
    if len({bucket_depth(d) for d in depths}) != 1:
        raise BatchIneligible("max_depth spans compile depth buckets")

    mesh = get_mesh()
    x = b0.resolve_x(frame, x, y)
    category = infer_category(frame, y)
    if category == ModelCategory.MULTINOMIAL:
        raise BatchIneligible("multinomial (per-class tree loop)")
    dist_name = b0._resolve_distribution(category)
    stopper_on = int(p0["stopping_rounds"]) > 0
    if stopper_on and validation_frame is not None:
        # validation-side stopping carries a second margin through the
        # scan — sequential path handles it; not vmapped (yet)
        raise BatchIneligible("validation-frame early stopping")

    # ---- shared preamble (identical to the sequential _fit) ----------
    w = frame.valid_weights()
    if p0.get("weights_column"):
        wc = frame.col(p0["weights_column"]).numeric_view()
        w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
    rc = frame.col(y)
    if p0.get("check_constant_response", True) and not rc.is_categorical:
        yh = rc.to_numpy()
        vals = yh[~np.isnan(yh)]
        if vals.size and float(vals.min()) == float(vals.max()):
            raise ValueError(
                "Response cannot be constant - check your response "
                "column, or set check_constant_response=False")
    wh_host = b0._host_weights(frame, y)
    resp_na_host = np.isnan(rc.to_numpy())
    if resp_na_host.any():
        w = w * jnp.asarray(np.pad(
            (~resp_na_host).astype(np.float32),
            (0, frame.nrows_padded - frame.nrows)))
    bm = bin_frame(frame, x, nbins=p0["nbins"],
                   nbins_cats=p0["nbins_cats"], weights=wh_host)
    w, w_scale = b0._normalize_uniform_weights(w, wh_host)
    if w_scale != 1.0:
        wh_host = wh_host / np.float32(w_scale)

    def _tp_of(p):
        return TreeParams(
            max_depth=int(p["max_depth"]),
            min_rows=float(p["min_rows"]) / w_scale,
            learn_rate=float(p["learn_rate"]),
            reg_lambda=float(p["reg_lambda"]) / w_scale,
            min_split_improvement=float(p["min_split_improvement"])
            / w_scale,
            col_sample_rate=float(p["col_sample_rate_per_tree"]),
            nbins_total=bm.nbins_total,
            cat_feats=tuple(bool(v) for v in bm.is_cat),
            block_rows=16384 if bm.bins.shape[0] > 8_388_608 else 4096,
            exact_f32=exact_f32_for(bm),
            pallas=pallas_ops.resolve_tree_mode())

    tps = [_tp_of(b.params) for b in builders]
    tp0 = tps[0]                 # shared static program (depth buckets)
    knobs_b = jnp.stack([_knobs_of(tps[m],
                                   float(builders[m].params["sample_rate"]))
                         for m in range(M)])
    keys = jnp.stack([jax.random.PRNGKey(
        int(b.params["seed"]) if int(b.params["seed"]) >= 0 else 0xDEC0DE)
        for b in builders])
    constraints = _build_constraints(p0, x, frame, category)
    interaction_sets = _build_interaction_sets(p0, x)
    ntrees = int(p0["ntrees"])
    score_interval = int(p0["score_tree_interval"]) or 5
    from h2o3_tpu.models.model import EarlyStopper
    stoppers = [EarlyStopper(int(p0["stopping_rounds"]),
                             float(p0["stopping_tolerance"]))
                for _ in range(M)]
    histories: List[List[dict]] = [[] for _ in range(M)]

    if category == ModelCategory.BINOMIAL:
        dist = get_distribution("bernoulli")
    else:
        dist = get_distribution(dist_name, **p0)
    yv = np.nan_to_num(rc.to_numpy()).astype(np.float32)
    mean_y = (float(np.sum(yv * wh_host))
              / max(float(np.sum(wh_host)), 1e-12))
    yv = np.pad(yv, (0, bm.bins.shape[0] - frame.nrows))
    y_dev = put_sharded(yv, row_sharding(mesh))
    off = None
    if p0.get("offset_column") and p0["offset_column"] in frame:
        onp = np.nan_to_num(
            frame.col(p0["offset_column"]).to_numpy()).astype(np.float32)
        onp = np.pad(onp, (0, bm.bins.shape[0] - frame.nrows))
        off = put_sharded(jnp.asarray(onp), row_sharding(mesh))
    if off is None:
        f0 = np.float32(dist.init_margin(mean_y))
        margin1 = jnp.full((bm.bins.shape[0],), f0, jnp.float32)
    else:
        c = jnp.float32(dist.init_margin(mean_y))
        for _ in range(25):
            gsum = jnp.sum(w * dist.grad(y_dev, off + c))
            hsum = jnp.sum(w * dist.hess(y_dev, off + c))
            c = c - gsum / jnp.maximum(hsum, 1e-12)
        f0 = np.float32(c)
        margin1 = off + f0
    margins = jnp.zeros((M, bm.bins.shape[0]), jnp.float32) + margin1

    # chunked batched scans: same chunk policy as the sequential path
    # (no deadline — runtime-capped fits are ineligible above), so the
    # global-tree-index PRNG keys and stop points line up exactly
    _rows_scale = max(1.0, bm.bins.shape[0] / 5_242_880.0)
    _chunk = max(1, min(25, int(round(25.0 / _rows_scale))))
    chunk_trees: List[List[Tree]] = [[] for _ in range(M)]
    gains_tot = np.zeros((M, len(x)), np.float32)
    stopped = [False] * M
    done = 0
    while done < ntrees and not all(stopped):
        k = min(_chunk, ntrees - done)
        alive = M - sum(stopped)
        _ct0 = time.time()
        stepprof.chunk_begin()
        with telemetry.span("gbm.chunk", trees=k, batch=M):
            tr_b, margins, gains_b, devs_b = _boost_scan_batched(
                bm.bins, bm.nbins, y_dev, w, margins, keys, knobs_b,
                constraints, interaction_sets, tp=tp0, dist=dist,
                ntrees=k, tree0=done)
            stepprof.compute_done((margins, devs_b))
        telemetry.histogram("train_chunk_seconds",
                            algo="gbm").observe(time.time() - _ct0)
        telemetry.counter("train_iterations_total",
                          algo="gbm").inc(k * alive)
        stepprof.chunk_end(trees=k, batch=M)
        devs_h = np.asarray(devs_b) if stopper_on else None
        gains_h = np.asarray(gains_b)
        for m in range(M):
            if stopped[m]:
                continue           # masked out, not a Python break: the
                #                    program still ran its lane; results
                #                    past the stop point are discarded
            keep = (_stop_point(devs_h[m], done, k, score_interval,
                                stoppers[m], histories[m])
                    if stopper_on else k)
            chunk_trees[m].append(unstack_model_trees(tr_b, m, keep))
            gains_tot[m] += gains_h[m, :keep].sum(axis=0)
            if keep < k:
                stopped[m] = True
        done += k

    # ---- per-model unstack into ordinary Model objects ---------------
    output_base = {"category": category, "response": y, "names": list(x),
                   "nclasses": rc.cardinality if rc.is_categorical else 1,
                   "domain": rc.domain, "init_f": float(f0)}
    from h2o3_tpu.ml.calibration import maybe_calibrate
    models: List[Model] = []
    t_done = time.time()
    for m in range(M):
        p = builders[m].params
        forest = concat_forests(chunk_trees[m])
        model = GBMModel(p, dict(output_base), forest, bm, f0, dist_name)
        if category == ModelCategory.BINOMIAL:
            pfin = dist.link_inv(model._margins(bm, off))
            model.training_metrics = mm.binomial_metrics(pfin, y_dev, w)
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        else:
            mfin = model._margins(bm, off)
            model.training_metrics = mm.regression_metrics(
                dist.link_inv(mfin), y_dev, w,
                deviance_fn=lambda yy, pp, _m=mfin: dist.deviance(yy, _m))
        model.output["scoring_history"] = histories[m]
        vi = gains_tot[m]
        order = np.argsort(-vi)
        tot = vi.sum() or 1.0
        model.output["varimp"] = [
            (x[i], float(vi[i]), float(vi[i] / max(vi.max(), 1e-12)),
             float(vi[i] / tot)) for i in order]
        if validation_frame is not None:
            model.validation_metrics = \
                model.model_performance(validation_frame)
        maybe_calibrate(model, p, category)
        model.output["run_time"] = time.time() - t_done
        models.append(model)
    return models
