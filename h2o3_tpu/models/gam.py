"""GAM — generalized additive models: spline basis expansion + GLM core.

Reference: hex/gam/GAM.java:50 (~4.4K LoC) — per gam_column builds a
cubic-spline basis with num_knots knots, a curvature penalty matrix
scaled by ``scale``, centers the basis for identifiability, then runs the
GLM IRLS machinery on [linear features | spline blocks] with the block
penalty added to the Gram.

TPU redesign: the basis is a P-spline block (cubic B-splines on
quantile-spaced knots + second-difference curvature penalty — the
standard Eilers–Marx construction, numerically equivalent in effect to
the reference's cubic regression splines). Basis construction is a
host-side one-off; the fit is the same one-einsum-Gram-per-IRLS-step
program as GLM (SURVEY §3.4), with the penalty entering the replicated
solve. Spline blocks are dense [N, nb] f32 — MXU-friendly.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.glm import Family
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   adapt_domain, infer_category)
from h2o3_tpu.ops.gram import gram
from h2o3_tpu.parallel.mesh import get_mesh, row_sharding
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.gam")


def bspline_basis(x: np.ndarray, knots: np.ndarray, degree: int = 3):
    """Cox–de Boor B-spline basis [n, nb] over a clamped-extended knot
    grid; NaN rows → zero basis (mean-imputed by centering later)."""
    h = knots[1] - knots[0] if len(knots) > 1 else 1.0
    ext = np.concatenate([knots[0] - h * np.arange(degree, 0, -1), knots,
                          knots[-1] + h * np.arange(1, degree + 1)])
    nb = len(ext) - degree - 1
    xc = np.clip(x, knots[0], knots[-1])
    ok = np.isfinite(x)
    xc = np.where(ok, xc, knots[0])
    B = np.zeros((len(x), nb + degree))
    # degree-0: indicator of the knot span
    for j in range(nb + degree):
        lo, hi = ext[j], ext[j + 1] if j + 1 < len(ext) else ext[-1]
        B[:, j] = (xc >= lo) & (xc < hi)
    # last point belongs to the final non-empty span
    B[xc >= knots[-1], :] = 0
    last = np.searchsorted(ext, knots[-1], side="right") - 1
    B[xc >= knots[-1], last] = 1.0
    for d in range(1, degree + 1):
        Bn = np.zeros((len(x), nb + degree - d))
        for j in range(nb + degree - d):
            den1 = ext[j + d] - ext[j]
            den2 = ext[j + d + 1] - ext[j + 1]
            t1 = ((xc - ext[j]) / den1) * B[:, j] if den1 > 0 else 0.0
            t2 = ((ext[j + d + 1] - xc) / den2) * B[:, j + 1] if den2 > 0 else 0.0
            Bn[:, j] = t1 + t2
        B = Bn
    B[~ok, :] = 0.0
    return B


def curvature_penalty(nb: int) -> np.ndarray:
    """S = D2'D2, the P-spline second-difference curvature penalty."""
    D = np.zeros((nb - 2, nb))
    for i in range(nb - 2):
        D[i, i], D[i, i + 1], D[i, i + 2] = 1.0, -2.0, 1.0
    return D.T @ D


@partial(jax.jit, static_argnames=("family", "link"))
def _pirls_iter(X1, coef, y, w, Pmat, family: str, link: str, tweedie_power):
    """One penalized-IRLS step: Gram (psum over mesh) + penalized solve."""
    fam = Family(family, tweedie_power, link)
    eta = X1 @ coef
    mu = fam.linkinv(eta)
    d = fam.dmu_deta(eta, mu)
    var = fam.variance(mu)
    z = eta + (y - mu) / jnp.where(jnp.abs(d) < 1e-10, 1e-10, d)
    w_irls = w * d * d / jnp.maximum(var, 1e-10)
    dev = jnp.sum(w * fam.deviance(y, mu))
    xtx, xtz, _ = gram(X1, w_irls, z, mesh=get_mesh())
    nobs = jnp.maximum(jnp.sum(w), 1.0)
    A = xtx / nobs + Pmat
    L = jax.scipy.linalg.cho_factor(A + 1e-7 * jnp.eye(A.shape[0]))
    new_coef = jax.scipy.linalg.cho_solve(L, xtz / nobs)
    return new_coef, jnp.max(jnp.abs(new_coef - coef)), dev


class GAMModel(Model):
    algo = "gam"

    def __init__(self, params, output, coef, family: Family, di_stats,
                 features, gam_spec: List[dict]):
        super().__init__(params, output)
        self.coef = coef
        self.family = family
        self.di_stats = di_stats
        self.features = features
        self.gam_spec = gam_spec   # per gam col: knots, basis means

    def _design(self, frame: Frame):
        di = build_datainfo(frame, self.features,
                            standardize=self.params.get("standardize", True),
                            use_all_factor_levels=False,
                            stats_override=self.di_stats)
        blocks = [di.X]
        for spec in self.gam_spec:
            xnp = frame.col(spec["col"]).to_numpy()
            B = bspline_basis(np.pad(xnp, (0, di.X.shape[0] - len(xnp)),
                                     constant_values=np.nan),
                              spec["knots"])[:, 1:]
            B = B - spec["means"][None, :]
            blocks.append(jnp.asarray(B, jnp.float32))
        ones = jnp.ones((di.X.shape[0], 1), jnp.float32)
        return jnp.concatenate(blocks + [ones], axis=1)

    def _eta(self, frame: Frame):
        return self._design(frame) @ jnp.asarray(self.coef, jnp.float32)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        n = frame.nrows
        cat = self.output["category"]
        mu = np.asarray(self.family.linkinv(self._eta(frame)))[:n]
        if cat == ModelCategory.BINOMIAL:
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (mu >= t).astype(np.int32),
                    "p0": 1.0 - mu, "p1": mu}
        return {"predict": mu}

    def model_performance(self, frame: Frame):
        y = self.output["response"]
        cat = self.output["category"]
        eta = self._eta(frame)
        w = frame.valid_weights()
        npad = eta.shape[0]
        if cat == ModelCategory.BINOMIAL:
            yv = adapt_domain(frame.col(y), self.output["domain"])
            yv = np.pad(yv, (0, npad - frame.nrows), constant_values=-1)
            w = w * jnp.asarray((yv >= 0).astype(np.float32))
            p = self.family.linkinv(eta)
            return mm.binomial_metrics(
                p, jnp.asarray(np.maximum(yv, 0).astype(np.float32)), w)
        yv = frame.col(y).numeric_view()
        w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
        yv = jnp.where(jnp.isnan(yv), 0.0, yv)
        return mm.regression_metrics(
            self.family.linkinv(eta), yv, w,
            deviance_fn=lambda a, b: self.family.deviance(a, b))


class GAMEstimator(ModelBuilder):
    """h2o-py H2OGeneralizedAdditiveEstimator surface
    (h2o-py/h2o/estimators/gam.py)."""

    algo = "gam"

    DEFAULTS = dict(
        gam_columns=None, num_knots=None, scale=None, bs=None,
        family="auto", link=None, lambda_=0.0, alpha=0.0,
        standardize=True, max_iterations=50, beta_epsilon=1e-4,
        tweedie_power=1.5, seed=-1, nfolds=0, fold_assignment="auto",
        weights_column=None, fold_column=None, ignored_columns=None,
        keep_gam_cols=False,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        if "Lambda" in params:
            params["lambda_"] = params.pop("Lambda")
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown GAM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)
        if not self.params.get("gam_columns"):
            raise ValueError("GAM requires gam_columns")

    def resolve_x(self, frame, x, y):
        x = super().resolve_x(frame, x, y)
        gc = set(self.params["gam_columns"] or [])
        return [n for n in x if n not in gc]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        category = infer_category(frame, y)
        fam_name = p["family"]
        if fam_name == "auto":
            fam_name = {"Binomial": "binomial",
                        "Regression": "gaussian"}.get(category)
            if fam_name is None:
                raise ValueError(f"GAM: unsupported category {category}")
        fam = Family(fam_name, float(p["tweedie_power"]), p["link"])

        gam_cols: List[str] = list(p["gam_columns"])
        nk = p["num_knots"] or [10] * len(gam_cols)
        scales = p["scale"] or [1.0] * len(gam_cols)

        di = build_datainfo(frame, x, standardize=bool(p["standardize"]),
                            use_all_factor_levels=False)
        npad = di.X.shape[0]
        blocks = [di.X]
        gam_spec: List[dict] = []
        pen_blocks: List[np.ndarray] = [np.zeros((di.X.shape[1],
                                                  di.X.shape[1]))]
        coef_names = list(di.coef_names)
        for gc, k, sc in zip(gam_cols, nk, scales):
            xnp = frame.col(gc).to_numpy()
            qs = np.nanquantile(xnp, np.linspace(0, 1, int(k)))
            knots = np.unique(qs)
            if len(knots) < 4:
                knots = np.linspace(np.nanmin(xnp), np.nanmax(xnp) + 1e-6, 4)
            B = bspline_basis(np.pad(xnp, (0, npad - len(xnp)),
                                     constant_values=np.nan), knots)
            # drop the first basis column: the full basis sums to 1
            # (partition of unity) so after centering it is exactly
            # collinear with the intercept AND in the curvature penalty's
            # null space — dropping one column restores identifiability
            # (the reference instead centers via an orthogonal transform)
            B = B[:, 1:]
            means = B[: frame.nrows].mean(axis=0)
            B = B - means[None, :]
            gam_spec.append({"col": gc, "knots": knots, "means": means,
                             "scale": float(sc)})
            blocks.append(jnp.asarray(B, jnp.float32))
            pen_blocks.append(
                float(sc) * curvature_penalty(B.shape[1] + 1)[1:, 1:])
            coef_names += [f"{gc}_spline_{i}" for i in range(B.shape[1])]

        ones = jnp.ones((npad, 1), jnp.float32)
        X1 = jax.device_put(jnp.concatenate(blocks + [ones], axis=1),
                            row_sharding(mesh))
        Pfull = np.zeros((X1.shape[1], X1.shape[1]), np.float32)
        off = 0
        for blk in pen_blocks:
            m = blk.shape[0]
            Pfull[off:off + m, off:off + m] = blk
            off += m
        # elastic-net on linear coefs (reference GLM lambda on non-spline)
        lam = float(p["lambda_"] if not isinstance(p["lambda_"], (list, tuple))
                    else p["lambda_"][0])
        for i in range(di.X.shape[1]):
            Pfull[i, i] += lam * (1.0 - float(p["alpha"] or 0.0))
        Pmat = jnp.asarray(Pfull)

        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        rc = frame.col(y)
        if category == ModelCategory.BINOMIAL:
            yraw = adapt_domain(rc, rc.domain)
            yv = np.pad(np.maximum(yraw, 0).astype(np.float32),
                        (0, npad - frame.nrows))
            w = w * jnp.asarray(np.pad((yraw >= 0).astype(np.float32),
                                       (0, npad - frame.nrows)))
        else:
            yn = rc.to_numpy()
            w = w * jnp.asarray(np.pad((~np.isnan(yn)).astype(np.float32),
                                       (0, npad - frame.nrows)))
            yv = np.pad(np.nan_to_num(yn).astype(np.float32),
                        (0, npad - frame.nrows))
        y_dev = jax.device_put(yv, row_sharding(mesh))

        coef = jnp.zeros((X1.shape[1],), jnp.float32)
        dev = np.inf
        for it in range(int(p["max_iterations"])):
            coef, delta, dev = _pirls_iter(X1, coef, y_dev, w, Pmat,
                                           fam.name, fam.link,
                                           jnp.float32(fam.p))
            job.update(1.0 / int(p["max_iterations"]), f"pirls {it + 1}")
            if float(delta) < float(p["beta_epsilon"]):
                break

        output = {"category": category, "response": y, "names": list(x),
                  "gam_columns": gam_cols, "coef_names": coef_names,
                  "domain": rc.domain,
                  "nclasses": rc.cardinality if rc.is_categorical else 1,
                  "residual_deviance": float(dev)}
        model = GAMModel(p, output, np.asarray(coef), fam, stats_of(di),
                         list(x), gam_spec)
        mu = fam.linkinv(X1 @ coef)
        if category == ModelCategory.BINOMIAL:
            model.training_metrics = mm.binomial_metrics(mu, y_dev, w)
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        else:
            model.training_metrics = mm.regression_metrics(
                mu, y_dev, w, deviance_fn=lambda a, b: fam.deviance(a, b))
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
