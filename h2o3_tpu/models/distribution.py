"""Distribution / loss family shared by GBM, DRF and DeepLearning.

Reference: hex/Distribution.java:10 + hex/LinkFunction — one class per
family (gaussian, bernoulli, multinomial, poisson, gamma, tweedie,
laplace, quantile, huber) providing link/deviance/gradient used across
GBM/GLM/DL. Here each family supplies, on the *margin* scale f:

- ``grad``/``hess``: d/df and d²/df² of the per-row deviance — tree
  boosting consumes these (Newton leaf -G/H generalizes the reference's
  per-family GammaPass, hex/tree/gbm/GBM.java:520).
- ``init_margin``: prior f0 (SharedTree init, hex/tree/SharedTree.java).
- ``link_inv``: margin → prediction.
- ``deviance``: mean training loss for scoring history.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp

EPS = 1e-7  # float32-safe: 1 - 1e-7 != 1.0


@dataclasses.dataclass(frozen=True)
class Distribution:
    name: str
    grad: Callable     # (y, f) -> g
    hess: Callable     # (y, f) -> h
    init_margin: Callable  # (mean_y) -> f0  (scalar, host)
    link_inv: Callable     # f -> prediction
    deviance: Callable     # (y, f) -> per-row deviance


def _sigmoid(f):
    return jnp.clip(1.0 / (1.0 + jnp.exp(-f)), EPS, 1.0 - EPS)


def gaussian() -> Distribution:
    return Distribution(
        "gaussian",
        grad=lambda y, f: f - y,
        hess=lambda y, f: jnp.ones_like(f),
        init_margin=lambda m: m,
        link_inv=lambda f: f,
        deviance=lambda y, f: (y - f) ** 2)


def bernoulli() -> Distribution:
    return Distribution(
        "bernoulli",
        grad=lambda y, f: _sigmoid(f) - y,
        hess=lambda y, f: _sigmoid(f) * (1.0 - _sigmoid(f)),
        init_margin=lambda m: float(jnp.log(max(m, EPS) / max(1.0 - m, EPS))),
        link_inv=_sigmoid,
        deviance=lambda y, f: -2.0 * (y * jnp.log(_sigmoid(f))
                                      + (1 - y) * jnp.log(1 - _sigmoid(f))))


def poisson() -> Distribution:
    return Distribution(
        "poisson",
        grad=lambda y, f: jnp.exp(f) - y,
        hess=lambda y, f: jnp.exp(f),
        init_margin=lambda m: float(jnp.log(max(m, EPS))),
        link_inv=jnp.exp,
        deviance=lambda y, f: 2.0 * (y * jnp.log(jnp.maximum(y, EPS))
                                     - y * f - y + jnp.exp(f)))


def gamma() -> Distribution:
    return Distribution(
        "gamma",
        grad=lambda y, f: 1.0 - y * jnp.exp(-f),
        hess=lambda y, f: y * jnp.exp(-f),
        init_margin=lambda m: float(jnp.log(max(m, EPS))),
        link_inv=jnp.exp,
        deviance=lambda y, f: 2.0 * (y * jnp.exp(-f) - 1.0
                                     - jnp.log(jnp.maximum(y, EPS)) + f))


def tweedie(p: float = 1.5) -> Distribution:
    return Distribution(
        "tweedie",
        grad=lambda y, f: -y * jnp.exp((1 - p) * f) + jnp.exp((2 - p) * f),
        hess=lambda y, f: -(1 - p) * y * jnp.exp((1 - p) * f)
                          + (2 - p) * jnp.exp((2 - p) * f),
        init_margin=lambda m: float(jnp.log(max(m, EPS))),
        link_inv=jnp.exp,
        deviance=lambda y, f: 2.0 * (
            jnp.maximum(y, 0.0) ** (2 - p) / ((1 - p) * (2 - p))
            - y * jnp.exp((1 - p) * f) / (1 - p)
            + jnp.exp((2 - p) * f) / (2 - p)))


def laplace() -> Distribution:
    return Distribution(
        "laplace",
        grad=lambda y, f: jnp.sign(f - y),
        hess=lambda y, f: jnp.ones_like(f),
        init_margin=lambda m: m,   # reference uses median; mean is the jit-cheap prior
        link_inv=lambda f: f,
        deviance=lambda y, f: jnp.abs(y - f))


def quantile(alpha: float = 0.5) -> Distribution:
    return Distribution(
        "quantile",
        grad=lambda y, f: jnp.where(y > f, -alpha, 1.0 - alpha),
        hess=lambda y, f: jnp.ones_like(f),
        init_margin=lambda m: m,
        link_inv=lambda f: f,
        deviance=lambda y, f: jnp.where(y > f, alpha * (y - f),
                                        (1 - alpha) * (f - y)))


def huber(delta: float = 0.9) -> Distribution:
    # reference re-estimates delta from residual quantiles per iteration
    # (GBM.java:479-488); fixed-delta is the static-shape-friendly form.
    return Distribution(
        "huber",
        grad=lambda y, f: jnp.clip(f - y, -delta, delta),
        hess=lambda y, f: jnp.ones_like(f),
        init_margin=lambda m: m,
        link_inv=lambda f: f,
        deviance=lambda y, f: jnp.where(
            jnp.abs(y - f) <= delta, 0.5 * (y - f) ** 2,
            delta * (jnp.abs(y - f) - 0.5 * delta)))


_LINKS = {
    "identity": (lambda f: f, lambda m: m),
    "log": (jnp.exp, lambda m: float(jnp.log(max(m, EPS)))),
    "logit": (_sigmoid,
              lambda m: float(jnp.log(max(m, EPS) / max(1.0 - m, EPS)))),
}


def custom(obj, ref: str) -> Distribution:
    """Wrap an uploaded custom-distribution object (water/udf CFunc /
    hex CustomDistribution role). gradient() compiles straight into the
    boosting scan; hessian defaults to 1 (plain gradient boosting),
    deviance to |gradient| (a monotone progress proxy for early
    stopping when the user supplies none)."""
    link_name = obj.link() if callable(getattr(obj, "link", None)) \
        else "identity"
    if link_name not in _LINKS:
        raise ValueError(f"custom distribution link '{link_name}' must "
                         f"be one of {sorted(_LINKS)}")
    link_inv, default_init = _LINKS[link_name]
    grad = obj.gradient
    hess = (obj.hessian if callable(getattr(obj, "hessian", None))
            else (lambda y, f: jnp.ones_like(f)))
    dev = (obj.deviance if callable(getattr(obj, "deviance", None))
           else (lambda y, f: jnp.abs(grad(y, f))))
    init = (obj.init if callable(getattr(obj, "init", None))
            else default_init)
    return Distribution(f"custom:{ref}", grad=grad, hess=hess,
                        init_margin=init, link_inv=link_inv,
                        deviance=dev)


_FACTORY = {
    "gaussian": gaussian, "bernoulli": bernoulli, "poisson": poisson,
    "gamma": gamma, "laplace": laplace,
}


_CACHE: dict = {}


def get_distribution(name: str, **kw) -> Distribution:
    """Memoized per (name, shape-param): Distribution instances are static
    jit arguments, so a fresh instance per call would recompile every
    boosting program."""
    name = name.lower()
    if name in ("auto", "multinomial"):
        raise ValueError(f"{name} resolved at the algorithm level")
    if name == "custom":
        ref = kw.get("custom_distribution_func")
        if not ref:
            raise ValueError("distribution='custom' requires "
                             "custom_distribution_func (upload via "
                             "h2o3_tpu.upload_custom_distribution)")
        from h2o3_tpu.core.udf import resolve_udf
        obj = resolve_udf(ref)
        # memoize per UPLOADED OBJECT, not per ref string: re-uploading
        # under the same DKV key must not reuse a stale compiled loss,
        # while repeat trains on one upload keep one compiled program
        key = ("custom", str(ref), id(obj))
        if key not in _CACHE:
            _CACHE[key] = custom(obj, str(ref))
        return _CACHE[key]
    if name == "tweedie":
        key = (name, float(kw.get("tweedie_power", 1.5)))
    elif name == "quantile":
        key = (name, float(kw.get("quantile_alpha", 0.5)))
    elif name == "huber":
        key = (name, float(kw.get("huber_alpha", 0.9)))
    else:
        key = (name, 0.0)
    if key not in _CACHE:
        if name == "tweedie":
            _CACHE[key] = tweedie(key[1])
        elif name == "quantile":
            _CACHE[key] = quantile(key[1])
        elif name == "huber":
            _CACHE[key] = huber(key[1])
        else:
            _CACHE[key] = _FACTORY[name]()
    return _CACHE[key]
