"""KMeans — Lloyd's iterations on the mesh, k-means++ / Furthest init.

Reference: hex/kmeans/KMeans.java:26 — LloydsIterationTask (KMeans.java:731)
is an MRTask computing per-row nearest center + accumulating per-cluster
sums; init options Random / PlusPlus / Furthest (KMeans.java Initialization);
categoricals one-hot expanded and numerics standardized via DataInfo.

TPU redesign: the assignment step is ONE [N,P]x[P,K] matmul (MXU) — the
distance trick d² = ‖x‖² − 2x·c + ‖c‖² — and the center update is a
segment_sum + psum over the 'data' axis; one jitted `_lloyd_step` replaces
the whole MRTask. Init rounds reuse the same distance matmul.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.datainfo import DataInfo, build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh


def _dist2(X, centers):
    """[N, K] squared distances via the matmul trick."""
    xc = X @ centers.T
    c2 = jnp.sum(centers * centers, axis=1)
    x2 = jnp.sum(X * X, axis=1, keepdims=True)
    return jnp.maximum(x2 - 2.0 * xc + c2[None, :], 0.0)


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(X, w, centers, *, k: int):
    """One Lloyd's iteration: assign + recompute centers + withinss."""
    mesh = get_mesh()
    d2 = _dist2(X, centers)
    assign = jnp.argmin(d2, axis=1).astype(jnp.int32)
    mind2 = jnp.min(d2, axis=1)
    vals = jnp.concatenate([X * w[:, None], w[:, None],
                            (w * mind2)[:, None]], axis=1)
    sums = segment_sum(assign, vals, n_nodes=k, mesh=mesh)
    counts = sums[:, -2]
    withinss = sums[:, -1]
    new_centers = jnp.where(counts[:, None] > 0,
                            sums[:, :-2] / jnp.maximum(counts[:, None], 1e-12),
                            centers)   # empty cluster keeps its old center
    return new_centers, assign, counts, withinss


@partial(jax.jit, static_argnames=())
def _min_dist2(X, centers):
    return jnp.min(_dist2(X, centers), axis=1)


def _init_centers(X, w, k: int, method: str, key) -> jnp.ndarray:
    """Initial centers. PlusPlus = D² sampling; Furthest = max-distance
    (both host-loop over k with one device reduce per pick, k is small)."""
    n = X.shape[0]
    wn = np.asarray(w)
    valid = np.flatnonzero(wn > 0)
    rng = np.random.RandomState(int(jax.random.randint(key, (), 0, 2**31 - 1)))
    first = int(valid[rng.randint(len(valid))])
    centers = [np.asarray(X[first])]
    if method == "random":
        picks = rng.choice(valid, size=k - 1, replace=False)
        centers += [np.asarray(X[int(i)]) for i in picks]
        return jnp.asarray(np.stack(centers), jnp.float32)
    for _ in range(k - 1):
        d2 = np.asarray(_min_dist2(X, jnp.asarray(np.stack(centers)))) * wn
        if method == "furthest":
            nxt = int(np.argmax(d2))
        else:  # plusplus: sample ∝ d²
            p = d2 / max(d2.sum(), 1e-12)
            nxt = int(rng.choice(n, p=p))
        centers.append(np.asarray(X[nxt]))
    return jnp.asarray(np.stack(centers), jnp.float32)


class KMeansModel(Model):
    algo = "kmeans"

    def __init__(self, params, output, centers_std, di_stats, features,
                 standardize: bool):
        super().__init__(params, output)
        self.centers_std = centers_std     # in standardized space
        self.di_stats = di_stats
        self.features = features
        self.standardize = standardize

    def _design(self, frame: Frame) -> DataInfo:
        return build_datainfo(frame, self.features,
                              standardize=self.standardize,
                              use_all_factor_levels=True,
                              stats_override=self.di_stats)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        di = self._design(frame)
        d2 = _dist2(di.X, self.centers_std)
        assign = np.asarray(jnp.argmin(d2, axis=1))[: frame.nrows]
        return {"predict": assign.astype(np.int32)}

    def model_performance(self, frame: Frame):
        di = self._design(frame)
        w = frame.valid_weights()
        wc = self.params.get("weights_column")
        if wc and wc in frame:
            v = frame.col(wc).numeric_view()
            w = w * jnp.where(jnp.isnan(v), 0.0, v)
        k = self.centers_std.shape[0]
        _, assign, counts, withinss = _lloyd_step(di.X, w, self.centers_std,
                                                  k=k)
        return _clustering_metrics(di.X, w, counts, withinss, get_mesh())


def _clustering_metrics(X, w, counts, withinss, mesh) -> ModelMetrics:
    """ModelMetricsClustering: totss / tot_withinss / betweenss."""
    gsum = segment_sum(jnp.zeros(X.shape[0], jnp.int32),
                       jnp.concatenate([X * w[:, None], w[:, None]], axis=1),
                       n_nodes=1, mesh=mesh)[0]
    tot_w = float(gsum[-1])
    gmean = gsum[:-1] / max(tot_w, 1e-12)
    d2g = jnp.sum((X - gmean[None, :]) ** 2, axis=1)
    totss = float(jnp.sum(w * d2g))
    tot_within = float(jnp.sum(withinss))
    return ModelMetrics(
        "Clustering", int(tot_w), tot_within / max(tot_w, 1e-12),
        totss=totss, tot_withinss=tot_within,
        betweenss=totss - tot_within,
        centroid_stats={"size": np.asarray(counts).tolist(),
                        "within_cluster_sum_of_squares":
                            np.asarray(withinss).tolist()})


class KMeansEstimator(ModelBuilder):
    """h2o-py H2OKMeansEstimator-compatible surface."""

    algo = "kmeans"
    supervised = False
    # supported internally but not a reference H2OKMeansEstimator
    # parameter — hidden from the REST schema so clients can re-create
    # estimators from the parameters list (pyunit_parametersKmeans)
    SCHEMA_HIDDEN_PARAMS = {"weights_column"}

    DEFAULTS = dict(
        k=1, max_iterations=10, init="Furthest", standardize=True,
        seed=-1, estimate_k=False, max_runtime_secs=0,
        cluster_size_constraints=None, user_points=None,
        ignored_columns=None, nfolds=0, fold_column=None, weights_column=None,
        fold_assignment="auto",
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown KMeans params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _run_lloyds_constrained(self, X, w, k, init, key, iters, mins,
                                centers0=None):
        """Lloyd's with minimum-size constraints: device distances, host
        greedy margin-based rebalancing per iteration."""
        centers = centers0 if centers0 is not None \
            else _init_centers(X, w, k, init, key)
        wn = np.asarray(jax.device_get(w))
        valid = wn > 0
        if sum(mins) > int(valid.sum()):
            raise ValueError(
                f"The sum of cluster_size_constraints ({sum(mins)}) "
                f"exceeds the number of training rows "
                f"({int(valid.sum())}).")
        # the greedy margin rebalance is inherently sequential, so the
        # whole loop runs on host from ONE device fetch: a per-iteration
        # device round trip (the previous design) costs more than the
        # entire iris-scale solve on a remote-attached chip
        # (pyunit_constrained_kmeans trains 20 such models)
        Xh = np.asarray(jax.device_get(X), np.float64)
        ch = np.asarray(jax.device_get(centers), np.float64)
        assign = np.where(valid, 0, -1).astype(np.int64)
        for _ in range(max(iters, 1)):
            d2 = ((Xh[:, None, :] - ch[None, :, :]) ** 2).sum(axis=2)
            assign = d2.argmin(axis=1)
            assign[~valid] = -1
            # fill deficits: move rows with the smallest distance margin
            for c in range(k):
                deficit = mins[c] - int((assign == c).sum())
                if deficit <= 0:
                    continue
                margin = d2[:, c] - d2[np.arange(len(assign)),
                                       np.maximum(assign, 0)]
                margin[~valid | (assign == c)] = np.inf
                # only steal from clusters that stay above THEIR minimum
                for r in np.argsort(margin):
                    if deficit <= 0 or not np.isfinite(margin[r]):
                        break
                    src = assign[r]
                    if src >= 0 and (assign == src).sum() <= mins[src]:
                        continue
                    assign[r] = c
                    deficit -= 1
            for c in range(k):
                sel = (assign == c)
                tot = wn[sel].sum()
                if tot > 0:
                    ch[c] = (Xh[sel] * wn[sel, None]).sum(axis=0) / tot
        d2 = ((Xh[:, None, :] - ch[None, :, :]) ** 2).sum(axis=2)
        wss = np.zeros(k)
        counts = np.zeros(k, np.float32)
        for c in range(k):
            sel = assign == c
            wss[c] = float((d2[sel, c] * wn[sel]).sum())
            counts[c] = wn[sel].sum()
        return (jnp.asarray(ch, jnp.float32),
                jnp.asarray(np.maximum(assign, 0)),
                jnp.asarray(counts), jnp.asarray(wss))

    def _run_lloyds(self, X, w, k, init, key, iters):
        centers = _init_centers(X, w, k, init, key)
        assign = counts = withinss = None
        prev = np.inf
        for _ in range(iters):
            centers, assign, counts, withinss = _lloyd_step(X, w, centers, k=k)
            tw = float(jnp.sum(withinss))
            if prev - tw < 1e-7 * max(abs(prev), 1.0):
                break
            prev = tw
        return centers, assign, counts, withinss

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        di = build_datainfo(frame, x, standardize=bool(p["standardize"]),
                            use_all_factor_levels=True)
        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0x63A7
        key = jax.random.PRNGKey(seed)
        init = str(p["init"]).lower()
        iters = int(p["max_iterations"])
        k = int(p["k"])

        user_pts = p.get("user_points")
        if user_pts is not None:
            # user-supplied starting centers (KMeans.java init=User):
            # raw-space points standardized into the design space
            from h2o3_tpu.core.kv import DKV as _DKV
            from h2o3_tpu.parallel.mesh import fetch_replicated
            if isinstance(user_pts, str):
                user_pts = _DKV.get(user_pts.strip('"'))
            # columns match predictors positionally (KMeans.java init=User);
            # run the points through the SAME DataInfo expansion as the
            # training frame so categorical predictors one-hot into the
            # design layout and numerics standardize with training stats
            if len(user_pts.names) != len(x):
                raise ValueError(
                    f"user_points must have one column per predictor "
                    f"({len(x)}), got {len(user_pts.names)}")
            upf = user_pts
            if list(upf.names) != list(x):
                import copy as _copy
                upf = _copy.deepcopy(user_pts).rename_columns(list(x))
            for nm in x:
                if frame.col(nm).is_categorical != \
                        upf.col(nm).is_categorical:
                    kind = ("categorical"
                            if frame.col(nm).is_categorical else "numeric")
                    raise ValueError(
                        f"user_points column for {kind} predictor "
                        f"'{nm}' must be {kind} too")
            udi = build_datainfo(upf, x,
                                 standardize=bool(p["standardize"]),
                                 use_all_factor_levels=True,
                                 stats_override=stats_of(di))
            pts = fetch_replicated(udi.X)[: user_pts.nrows]
            k = pts.shape[0]
            centers0 = jnp.asarray(pts, jnp.float32)
            constraints = p.get("cluster_size_constraints")
            if constraints is not None:
                mins = [int(v) for v in constraints]
                if len(mins) != k:
                    raise ValueError(
                        f"cluster_size_constraints must have k={k} entries")
                centers, assign, counts, withinss = \
                    self._run_lloyds_constrained(
                        di.X, w, k, init, key, iters, mins,
                        centers0=centers0)
            else:
                centers = centers0
                assign = counts = withinss = None
                for _ in range(max(iters, 1)):
                    centers, assign, counts, withinss = _lloyd_step(
                        di.X, w, centers, k=k)
            job.update(1.0, "lloyds done (user init)")
            return self._finish_model(frame, x, y, p, di, w, centers,
                                      assign, counts, withinss, k,
                                      validation_frame)

        constraints = p.get("cluster_size_constraints")
        if constraints is not None:
            # constrained variant (hex/kmeans/KMeans.java:26 / :101 —
            # minimal cluster sizes): Lloyd's with a greedy reassignment
            # that fills under-minimum clusters by smallest distance
            # margin. estimate_k is rejected like the reference
            # (KMeans.java:84).
            if p["estimate_k"]:
                raise ValueError("Cannot estimate k if "
                                 "cluster_size_constraints are provided.")
            mins = [int(v) for v in constraints]
            if len(mins) != k:
                raise ValueError(
                    f"cluster_size_constraints must have k={k} entries")
            centers, assign, counts, withinss = self._run_lloyds_constrained(
                di.X, w, k, init, key, iters, mins)
            job.update(1.0, "constrained lloyds done")
        elif p["estimate_k"]:
            # greedy k sweep: stop when within-SS reduction falls under 20%
            # (the reference's estimate_k heuristic, hex/kmeans/KMeans.java)
            best = None
            prev_tw = None
            for kk in range(1, k + 1):
                key, sub = jax.random.split(key)
                cand = self._run_lloyds(di.X, w, kk, init, sub, iters)
                tw = float(jnp.sum(cand[3]))
                if prev_tw is not None and tw > 0.8 * prev_tw:
                    break
                best, prev_tw, k_used = cand, tw, kk
            centers, assign, counts, withinss = best
            k = k_used
        else:
            centers, assign, counts, withinss = self._run_lloyds(
                di.X, w, k, init, key, iters)
            job.update(1.0, "lloyds done")

        return self._finish_model(frame, x, y, p, di, w, centers, assign,
                                  counts, withinss, k, validation_frame)

    def _finish_model(self, frame, x, y, p, di, w, centers, assign,
                      counts, withinss, k, validation_frame):
        from h2o3_tpu.parallel.mesh import get_mesh as _gm
        # de-standardized centers for reporting (numeric block only)
        cstd = np.asarray(centers)
        c_out = cstd.copy()
        ptr = 0
        num_j = 0
        for i, is_c in enumerate(di.is_cat):
            if is_c:
                ptr += len(di.domains[i] or [])   # all-levels one-hot block
            else:
                if bool(p["standardize"]):
                    c_out[:, ptr] = (cstd[:, ptr] * di.num_sigmas[num_j]
                                     + di.num_means[num_j])
                num_j += 1
                ptr += 1

        output = {"category": ModelCategory.CLUSTERING, "response": None,
                  "names": list(x), "domain": None, "k": k,
                  "centers": c_out.tolist(),
                  "centers_std": cstd.tolist(),
                  "coef_names": di.coef_names}
        model = KMeansModel(p, output, centers, stats_of(di), list(x),
                            bool(p["standardize"]))
        model.training_metrics = _clustering_metrics(di.X, w, counts,
                                                     withinss, _gm())
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
