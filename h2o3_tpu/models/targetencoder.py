"""Target encoding — CV-safe categorical → numeric target statistics.

Reference: h2o-extensions/target-encoder
(ai/h2o/targetencoding/TargetEncoder.java, TargetEncoderModel.java):
per-level {sum_y, count} "encoding maps" built at train time; transform
replaces each encoded categorical with the (optionally blended) level
mean of the response, with leakage control on training data:
  - none:        plain level means
  - loo:         leave-one-out (subtract own row from the level stats)
  - kfold:       per-fold maps; a row's encoding excludes its own fold
Blending (TargetEncoderHelper): lambda = 1/(1+exp(-(n-k)/f)) mixes the
level mean with the global prior (inflection_point k, smoothing f).
Optional uniform noise breaks exact memorization.

TPU-native: the group stats are one segment_sum over (fold, level)
segment ids on the mesh — the AstGroup/MRTask role — and the transform
is a pure gather.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import register
from h2o3_tpu.models.model import Model, ModelBuilder, adapt_domain
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh


def _level_stats(codes: np.ndarray, y: np.ndarray, w: np.ndarray,
                 card: int, folds: Optional[np.ndarray] = None,
                 nfolds: int = 1):
    """Per-(fold, level) {sum_wy, sum_w} via one device segment_sum."""
    mesh = get_mesh()
    seg = codes.astype(np.int64)
    if folds is not None:
        seg = folds.astype(np.int64) * card + seg
    n_seg = card * max(nfolds, 1)
    stats = segment_sum(jnp.asarray(seg.astype(np.int32)),
                        jnp.stack([jnp.asarray((w * y).astype(np.float32)),
                                   jnp.asarray(w.astype(np.float32))], axis=1),
                        n_nodes=int(n_seg), mesh=mesh)
    s = np.asarray(stats, dtype=np.float64)
    return s[:, 0].reshape(max(nfolds, 1), card), \
        s[:, 1].reshape(max(nfolds, 1), card)


def _blend(level_sum, level_cnt, prior, k: float, f: float, blending: bool):
    mean = np.where(level_cnt > 0, level_sum / np.maximum(level_cnt, 1e-12),
                    prior)
    if not blending:
        return mean
    z = np.clip((level_cnt - k) / max(f, 1e-12), -50.0, 50.0)
    lam = 1.0 / (1.0 + np.exp(-z))
    return lam * mean + (1.0 - lam) * prior


class TargetEncoderModel(Model):
    algo = "targetencoder"

    def __init__(self, params, output, enc_maps: Dict[str, dict]):
        super().__init__(params, output)
        # per column: {"sum": [nfolds, card], "cnt": [nfolds, card],
        #              "domain": [...], "prior": float}
        self.enc_maps = enc_maps

    def transform(self, frame: Frame, as_training: bool = False,
                  noise: Optional[float] = None,
                  seed: Optional[int] = None) -> Frame:
        """Append `<col>_te` columns (TargetEncoderModel.transform;
        transformTraining → leakage handling active)."""
        p = self.params
        handling = str(p.get("data_leakage_handling") or "none").lower()
        blending = bool(p.get("blending", False))
        k = float(p.get("inflection_point", 10.0))
        f = float(p.get("smoothing", 20.0))
        noise = float(p.get("noise", 0.01) if noise is None else noise)
        s = int(p.get("seed") or 0) if seed is None else int(seed)
        rng = np.random.RandomState(s & 0xFFFFFFFF)

        new_cols = []
        n = frame.nrows
        fold_col = p.get("fold_column")
        folds = None
        if as_training and handling == "kfold" and fold_col and fold_col in frame:
            folds = frame.col(fold_col).to_numpy().astype(int)[:n]

        for col, m in self.enc_maps.items():
            if col not in frame:
                continue
            dom = m["domain"]
            codes = adapt_domain(frame.col(col), dom)[:n]
            prior = m["prior"]
            tot_sum = m["sum"].sum(axis=0)
            tot_cnt = m["cnt"].sum(axis=0)
            if as_training and handling == "kfold" and folds is not None \
                    and m["sum"].shape[0] > 1:
                # encoding for fold j uses all folds but j
                nf = m["sum"].shape[0]
                te_f = np.stack([
                    _blend(tot_sum - m["sum"][j], tot_cnt - m["cnt"][j],
                           prior, k, f, blending) for j in range(nf)])
                fj = np.clip(folds, 0, nf - 1)
                enc = te_f[fj, np.clip(codes, 0, len(dom) - 1)]
            elif as_training and handling == "loo":
                yv = self._resp_numeric(frame)[:n]
                c = np.clip(codes, 0, len(dom) - 1)
                s = tot_sum[c] - np.where(np.isnan(yv), 0.0, yv)
                cn = tot_cnt[c] - (~np.isnan(yv)).astype(float)
                enc = _blend(s, cn, prior, k, f, blending)
            else:
                te = _blend(tot_sum, tot_cnt, prior, k, f, blending)
                enc = te[np.clip(codes, 0, len(dom) - 1)]
            enc = np.where(codes < 0, prior, enc)   # NA / unseen → prior
            if as_training and noise > 0:
                enc = enc + rng.uniform(-noise, noise, size=enc.shape)
            new_cols.append((f"{col}_te", enc))

        from h2o3_tpu.models.generic import _frame_raw_columns
        cols = _frame_raw_columns(frame, frame.names)
        cats = [nm for nm in frame.names if frame.col(nm).is_categorical]
        for nm, arr in new_cols:
            cols[nm] = arr
        return Frame.from_numpy(cols, categorical=cats)

    def _resp_numeric(self, frame: Frame) -> np.ndarray:
        y = self.output["response"]
        c = frame.col(y)
        if c.is_categorical:
            codes = adapt_domain(c, self.output["domain"])
            return np.where(codes < 0, np.nan, codes.astype(float))
        return c.to_numpy()

    def predict(self, frame: Frame) -> Frame:
        return self.transform(frame, as_training=False)

    def model_performance(self, frame: Frame):
        return None


@register
class TargetEncoderEstimator(ModelBuilder):
    """h2o-py H2OTargetEncoderEstimator surface
    (h2o-py/h2o/estimators/targetencoder.py)."""

    algo = "targetencoder"
    cv_from_fold_column = False      # fold column = leakage handling here

    DEFAULTS = dict(
        blending=False, inflection_point=10.0, smoothing=20.0,
        data_leakage_handling="none", noise=0.01, seed=-1,
        fold_column=None, ignored_columns=None, nfolds=0,
        weights_column=None, fold_assignment="auto",
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown TargetEncoder params: {sorted(unknown)}")
        merged.update(params)
        if int(merged.get("nfolds") or 0) >= 2:
            raise ValueError("TargetEncoder leakage control is "
                             "data_leakage_handling='kfold' + fold_column, "
                             "not generic CV (nfolds must be 0)")
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        n = frame.nrows
        rc = frame.col(y)
        if rc.is_categorical:
            yv = _fetch_np(rc.data)[:n].astype(np.float64)
            yna = _fetch_np(rc.na_mask)[:n]
            yv = np.where(yna, np.nan, yv)
            if rc.cardinality > 2:
                raise ValueError("TargetEncoder supports binomial or "
                                 "numeric responses")
        else:
            yv = rc.to_numpy()
        w = (~np.isnan(yv)).astype(np.float64)
        yv = np.where(np.isnan(yv), 0.0, yv)

        handling = str(p.get("data_leakage_handling") or "none").lower()
        fold_col = p.get("fold_column")
        folds = None
        nfolds = 1
        if handling == "kfold":
            if not fold_col or fold_col not in frame:
                raise ValueError("kfold leakage handling requires fold_column")
            folds = frame.col(fold_col).to_numpy().astype(int)[:n]
            nfolds = int(folds.max()) + 1

        enc_cols = [c for c in x if frame.col(c).is_categorical]
        prior = float((yv * w).sum() / max(w.sum(), 1e-12))
        enc_maps = {}
        for col in enc_cols:
            c = frame.col(col)
            dom = c.domain or []
            codes = _fetch_np(c.data)[:n].astype(np.int64)
            cna = _fetch_np(c.na_mask)[:n]
            wcol = w * (~cna)
            s, cnt = _level_stats(np.where(cna, 0, codes), yv, wcol,
                                  max(len(dom), 1), folds, nfolds)
            enc_maps[col] = {"sum": s, "cnt": cnt, "domain": list(dom),
                             "prior": prior}
            job.update(1.0 / max(len(enc_cols), 1), f"encoded {col}")

        output = {"category": "TargetEncoder", "response": y,
                  "names": enc_cols, "domain": rc.domain,
                  "prior": prior}
        return TargetEncoderModel(p, output, enc_maps)
