"""Isolation Forest — anomaly detection via random isolation trees.

Reference: hex/tree/isofor/IsolationForest.java:33 (random splits on a
row subsample, anomaly score from average isolation depth; output frame
has `predict` (normalized score) and `mean_length`).

TPU redesign: a tree is the same complete-binary-tree layout as
models/tree.py but splits are RANDOM (feature ~ U[F], threshold ~
U[0, nbins(f)-1)) so no histograms are needed — one `lax`-free jitted
pass per tree computes per-level node counts (segment_sum + psum over
the mesh) to mark isolated nodes. Path length of a row = number of
levels traversed while its node was still splitting, plus the standard
c(n) correction at the final leaf (Liu et al.); anomaly score
2^(-E[h]/c(sample_size)).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.binning import BinnedMatrix, bin_frame, rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory
from h2o3_tpu.models.tree import (Tree, row_feature_values,
                                  stack_trees, zero_catsplit)
from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh


def _avg_path_correction(n):
    """c(n): expected remaining path length in an unresolved subsample."""
    h = jnp.log(jnp.maximum(n - 1.0, 1.0)) + 0.5772156649
    c = 2.0 * h - 2.0 * (n - 1.0) / jnp.maximum(n, 1.0)
    return jnp.where(n > 2.0, c, jnp.where(n == 2.0, 1.0, 0.0))


@partial(jax.jit, static_argnames=("depth", "B"))
def _grow_random_tree(bins, nb, w, key, *, depth: int, B: int):
    """One isolation tree: random (feature, threshold) per node; a node
    stops being a 'split' once its bagged row count drops to <= 1."""
    mesh = get_mesh()
    F = bins.shape[1]
    Lmax = 2 ** (depth - 1) if depth > 0 else 1
    N = bins.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    feats = jnp.zeros((depth, Lmax), jnp.int32)
    threshs = jnp.full((depth, Lmax), B, jnp.int32)
    na_lefts = jnp.zeros((depth, Lmax), bool)
    is_splits = jnp.zeros((depth, Lmax), bool)
    for d in range(depth):
        L = 2 ** d
        key, kf, kt, kn = jax.random.split(key, 4)
        f = jax.random.randint(kf, (L,), 0, F)
        # threshold uniform over the feature's real bins [0, nb[f]-2]
        u = jax.random.uniform(kt, (L,))
        t = (u * jnp.maximum(nb[f] - 1, 1).astype(jnp.float32)).astype(jnp.int32)
        nal = jax.random.bernoulli(kn, 0.5, (L,))
        cnt = segment_sum(nid, w[:, None], n_nodes=L, mesh=mesh)[:, 0]
        split = cnt > 1.0
        feats = feats.at[d, :L].set(f)
        threshs = threshs.at[d, :L].set(jnp.where(split, t, B))
        na_lefts = na_lefts.at[d, :L].set(nal)
        is_splits = is_splits.at[d, :L].set(split)
        f_r = feats[d][nid]
        t_r = threshs[d][nid]
        nal_r = na_lefts[d][nid]
        b_r = row_feature_values(bins, f_r)
        isna = b_r == (B - 1)
        goleft = jnp.where(is_splits[d][nid],
                           jnp.where(isna, nal_r, b_r <= t_r), True)
        nid = 2 * nid + jnp.where(goleft, 0, 1)
    leaf_cnt = segment_sum(nid, w[:, None], n_nodes=2 ** depth, mesh=mesh)[:, 0]
    leaf = _avg_path_correction(leaf_cnt)
    return Tree(feats, threshs, na_lefts, is_splits, leaf, leaf_cnt,
                *zero_catsplit(feats.shape[0], feats.shape[1]))


def _tree_path_length(tree: Tree, bins, B: int):
    """Per-row isolation path length through one tree."""
    N = bins.shape[0]
    D = tree.feat.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    plen = jnp.zeros((N,), jnp.float32)
    for d in range(D):
        isp_r = tree.is_split[d][nid]
        plen = plen + isp_r.astype(jnp.float32)
        f_r = tree.feat[d][nid]
        t_r = tree.thresh[d][nid]
        nal_r = tree.na_left[d][nid]
        b_r = row_feature_values(bins, f_r)
        isna = b_r == (B - 1)
        goleft = jnp.where(isp_r, jnp.where(isna, nal_r, b_r <= t_r), True)
        nid = 2 * nid + jnp.where(goleft, 0, 1)
    return plen + tree.leaf[nid]


@partial(jax.jit, static_argnames=("B",))
def _forest_mean_length(stacked: Tree, bins, B: int):
    def step(acc, tree):
        return acc + _tree_path_length(tree, bins, B), None
    init = jnp.zeros((bins.shape[0],), jnp.float32)
    tot, _ = jax.lax.scan(step, init, stacked)
    return tot / stacked.feat.shape[0]


class IsolationForestModel(Model):
    algo = "isolationforest"

    def __init__(self, params, output, forest: Tree, bm: BinnedMatrix,
                 c_norm: float):
        super().__init__(params, output)
        self.forest = forest
        self.bm = bm
        self.c_norm = c_norm   # c(sample_size) — score normalizer

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        bm = rebin_for_scoring(self.bm, frame)
        ml = _forest_mean_length(self.forest, bm.bins, self.bm.nbins_total)
        n = frame.nrows
        ml = np.asarray(ml)[:n]
        mn = self.output.get("min_path_length")
        mx = self.output.get("max_path_length")
        if mn is not None and mx is not None and mx > mn:
            # reference normalization (IsolationForestModel
            # .normalizePathLength): (max - total) / (max - min)
            T = self.forest.feat.shape[0]
            score = (mx - ml * T) / (mx - mn)
        else:
            # 2^(-l/c) original-paper score: pre-stats fallback
            score = 2.0 ** (-ml / max(self.c_norm, 1e-12))
        return {"predict": score, "mean_length": ml}

    def model_performance(self, frame: Frame):
        raw = self._score_raw(frame)
        return {"mean_score": float(raw["predict"].mean()),
                "mean_length": float(raw["mean_length"].mean())}


class IsolationForestEstimator(ModelBuilder):
    """h2o-py H2OIsolationForestEstimator-compatible surface."""

    algo = "isolationforest"
    supervised = False

    DEFAULTS = dict(
        ntrees=50, sample_size=256, sample_rate=-1.0, max_depth=8,
        mtries=-1, nbins=64, nbins_cats=64, seed=-1,
        ignored_columns=None, contamination=-1.0,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown IsolationForest params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        bm = bin_frame(frame, x, nbins=p["nbins"], nbins_cats=p["nbins_cats"],
                       histogram_type="uniform")
        w = frame.valid_weights()
        n = frame.nrows
        rate = float(p["sample_rate"])
        psi = int(p["sample_size"])
        if rate > 0:
            psi = max(2, int(rate * n))
        bag_rate = min(1.0, psi / max(n, 1))
        depth = int(p["max_depth"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0x150F
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        trees = []
        for t in range(ntrees):
            key, kb, kt = jax.random.split(key, 3)
            keep = jax.random.bernoulli(kb, bag_rate, shape=w.shape)
            trees.append(_grow_random_tree(bm.bins, bm.nbins,
                                           w * keep.astype(jnp.float32), kt,
                                           depth=depth, B=bm.nbins_total))
            job.update(1.0 / ntrees, f"tree {t + 1}/{ntrees}")
        forest = stack_trees(trees)
        c_norm = float(_avg_path_correction(jnp.asarray(float(psi))))
        # training min/max TOTAL path length (sum over trees): the
        # reference normalizes scores as (max - len) / (max - min)
        # (hex/tree/isofor/IsolationForest.java:238 stats,
        # IsolationForestModel.normalizePathLength)
        tot = np.asarray(_forest_mean_length(
            forest, bm.bins, bm.nbins_total))[:n] * ntrees
        output = {"category": ModelCategory.ANOMALY, "response": None,
                  "names": list(x), "domain": None,
                  "min_path_length": int(np.floor(tot.min())) if n else 0,
                  "max_path_length": int(np.ceil(tot.max())) if n else 0}
        model = IsolationForestModel(p, output, forest, bm, c_norm)
        # training metrics straight from the path lengths already
        # computed for the min/max stats — no second forest scan
        ml = tot / max(ntrees, 1)
        mn, mx = output["min_path_length"], output["max_path_length"]
        score = ((mx - tot) / (mx - mn)) if mx > mn \
            else 2.0 ** (-ml / max(c_norm, 1e-12))
        model.training_metrics = {
            "mean_score": float(np.mean(score)) if n else 0.0,
            "mean_length": float(np.mean(ml)) if n else 0.0}
        return model
