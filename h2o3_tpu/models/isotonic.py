"""Isotonic regression — pool-adjacent-violators.

Reference: hex/isotonic/ — distributed aggregation of (x, y, w) triples
to unique-x buckets, then single-node PAV; scoring is piecewise-linear
interpolation clamped to the training x-range
(hex/isotonic/IsotonicRegressionModel.java).

TPU split: the aggregation to unique thresholds is device work
(sort/segment); PAV itself is inherently sequential and tiny (≤ number
of unique x), so it runs on the host — same split as the reference
(MRTask aggregate + driver-node PAV).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory


def _pav(x: np.ndarray, y: np.ndarray, w: np.ndarray):
    """Weighted PAV on sorted-unique x. Returns isotonic fitted values."""
    # stack-based O(n) pooling
    means, weights, counts = [], [], []
    for i in range(len(x)):
        m, wt, c = y[i], w[i], 1
        while means and means[-1] > m:
            pm, pw, pc = means.pop(), weights.pop(), counts.pop()
            m = (m * wt + pm * pw) / (wt + pw)
            wt += pw
            c += pc
        means.append(m)
        weights.append(wt)
        counts.append(c)
    out = np.empty_like(y)
    j = 0
    for m, c in zip(means, counts):
        out[j:j + c] = m
        j += c
    return out


class IsotonicRegressionModel(Model):
    algo = "isotonicregression"

    def __init__(self, params, output, thresholds_x, thresholds_y):
        super().__init__(params, output)
        self.tx = thresholds_x
        self.ty = thresholds_y

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        xname = self.output["names"][0]
        x = np.asarray(frame.col(xname).numeric_view())[: frame.nrows]
        xc = np.clip(x, self.tx[0], self.tx[-1])
        pred = np.interp(xc, self.tx, self.ty)
        pred[np.isnan(x)] = np.nan
        if str(self.params.get("out_of_bounds", "clip")).lower() == "na":
            pred[(x < self.tx[0]) | (x > self.tx[-1])] = np.nan
        return {"predict": pred}

    def model_performance(self, frame: Frame):
        y = self.output["response"]
        pred = self._score_raw(frame)["predict"]
        yv = np.asarray(frame.col(y).numeric_view())[: frame.nrows]
        ok = ~(np.isnan(pred) | np.isnan(yv))
        import jax.numpy as jnp
        return mm.regression_metrics(jnp.asarray(np.where(ok, pred, 0.0)),
                                     jnp.asarray(np.where(ok, yv, 0.0)),
                                     jnp.asarray(ok.astype(np.float32)))


class IsotonicRegressionEstimator(ModelBuilder):
    """h2o-py H2OIsotonicRegressionEstimator-compatible surface."""

    algo = "isotonicregression"

    DEFAULTS = dict(
        out_of_bounds="clip", weights_column=None, ignored_columns=None,
        nfolds=0, fold_column=None, fold_assignment="auto", seed=-1,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown Isotonic params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        if len(x) != 1:
            raise ValueError("IsotonicRegression takes exactly one feature")
        p = self.params
        n = frame.nrows
        xv = np.asarray(frame.col(x[0]).numeric_view())[:n]
        yv = np.asarray(frame.col(y).numeric_view())[:n]
        w = np.asarray(frame.valid_weights())[:n]
        if p.get("weights_column"):
            w = w * np.nan_to_num(
                np.asarray(frame.col(p["weights_column"]).numeric_view())[:n])
        ok = ~(np.isnan(xv) | np.isnan(yv)) & (w > 0)
        xv, yv, w = xv[ok], yv[ok], w[ok]
        # aggregate duplicates to unique x (device-sized data is fine on
        # host here; the reference also funnels to the driver node)
        order = np.argsort(xv, kind="stable")
        xs, ys, ws = xv[order], yv[order], w[order]
        ux, inv = np.unique(xs, return_inverse=True)
        wy = np.bincount(inv, weights=ws * ys)
        ww = np.bincount(inv, weights=ws)
        ymean = wy / np.maximum(ww, 1e-12)
        fitted = _pav(ux, ymean, ww)
        job.update(1.0, "pav done")
        output = {"category": ModelCategory.REGRESSION, "response": y,
                  "names": list(x), "domain": None,
                  "thresholds_x": ux.tolist(), "thresholds_y": fitted.tolist()}
        model = IsotonicRegressionModel(p, output, ux, fitted)
        model.training_metrics = model.model_performance(frame)
        return model
