"""PSVM — kernel SVM via Incomplete Cholesky Factorization.

Reference: hex/psvm/PSVM.java:24 (~2100 LoC) — the Chang et al. "PSVM:
Parallelizing Support Vector Machines on Distributed Computers" recipe:
approximate the Gaussian-kernel Gram matrix K ≈ V·Vᵀ with a rank-r
incomplete Cholesky factorization (hex/psvm/psvm/IncompleteCholesky),
then solve the regularized problem on the factorization; predictions and
support-vector stats mirror ModelMetricsBinomial + svs_count/bsv_count
outputs.

TPU redesign: ICF runs as r pivot steps, each one fused row-kernel +
rank-1 update over the row-sharded data (the per-step argmax/psum are
the only collectives); the solve is an L2-SVM Newton iteration in the
r-dimensional ICF feature space — smooth, so a handful of [r × r]
cho_solves on the MXU replace the reference's interior-point method.
Scoring maps a new row x into ICF space via k(x, pivots)·L⁻ᵀ.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models import register
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   adapt_domain)
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.psvm")


def _rbf_rows(X, rows, gamma):
    """K(X, rows) for Gaussian kernel, [N, m]."""
    d2 = (jnp.sum(X * X, axis=1)[:, None]
          + jnp.sum(rows * rows, axis=1)[None, :]
          - 2.0 * X @ rows.T)
    return jnp.exp(-gamma * jnp.maximum(d2, 0.0))


def icf(X, w_valid, gamma: float, rank: int):
    """Incomplete Cholesky of the RBF Gram matrix (IncompleteCholesky.java
    role): returns V [N, r] with K ≈ V Vᵀ, pivot row indices, and L
    (= V[pivots]) for out-of-sample mapping."""
    N = X.shape[0]
    diag = jnp.where(w_valid > 0, 1.0, 0.0)   # K(x,x) = 1 for RBF
    V = jnp.zeros((N, rank), jnp.float32)
    pivots = []
    for j in range(rank):
        piv = int(jnp.argmax(diag))
        dmax = float(diag[piv])
        if dmax <= 1e-8:
            rank = j
            break
        pivots.append(piv)
        kcol = _rbf_rows(X, X[piv][None, :], gamma)[:, 0]
        vj = (kcol - V[:, :j] @ V[piv, :j]) / jnp.sqrt(dmax)
        vj = jnp.where(w_valid > 0, vj, 0.0)
        V = V.at[:, j].set(vj)
        diag = jnp.maximum(diag - vj * vj, 0.0)
    return V[:, :rank], np.asarray(pivots, np.int64), rank


@partial(jax.jit, static_argnames=())
def _newton_step(w_b, V1, y, cw):
    """One Newton step on the smooth L2-SVM primal in ICF space:
    min 0.5 wᵀw + Σ cwᵢ max(0, 1 - yᵢ fᵢ)²,  f = V1 @ [w; b]."""
    f = V1 @ w_b
    xi = 1.0 - y * f
    act = (xi > 0).astype(jnp.float32) * cw
    # gradient and (Gauss-Newton) Hessian
    r = w_b.at[-1].set(0.0)                       # don't regularize bias
    g = r - 2.0 * V1.T @ (act * y * xi)
    H = (jnp.eye(w_b.shape[0]).at[-1, -1].set(1e-6)
         + 2.0 * V1.T @ (act[:, None] * V1))
    delta = jax.scipy.linalg.solve(H, g, assume_a="pos")
    return w_b - delta, jnp.sum(act * xi * xi) + 0.5 * jnp.sum(r * r)


class PSVMModel(Model):
    algo = "psvm"

    def __init__(self, params, output, w_b: np.ndarray, pivot_rows: np.ndarray,
                 Linv_t: np.ndarray, gamma: float, di_stats: dict,
                 features: List[str]):
        super().__init__(params, output)
        self.w_b = w_b                 # [r+1] weights + bias in ICF space
        self.pivot_rows = pivot_rows   # [r, P] standardized pivot rows
        self.Linv_t = Linv_t           # [r, r] L^{-T} for feature mapping
        self.gamma = gamma
        self.di_stats = di_stats
        self.features = features

    def _decision(self, frame: Frame) -> np.ndarray:
        di = build_datainfo(frame, self.features, standardize=True,
                            use_all_factor_levels=True,
                            stats_override=self.di_stats)
        k = _rbf_rows(di.X, jnp.asarray(self.pivot_rows), self.gamma)
        phi = k @ jnp.asarray(self.Linv_t)
        f = phi @ jnp.asarray(self.w_b[:-1]) + self.w_b[-1]
        return np.asarray(f)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        f = self._decision(frame)[: frame.nrows]
        pred = (f >= 0).astype(np.int32)
        p1 = 1.0 / (1.0 + np.exp(-np.clip(f, -30, 30)))
        return {"predict": pred, "decision_function": f,
                "p0": 1.0 - p1, "p1": p1}

    def model_performance(self, frame: Frame):
        f = self._decision(frame)
        y = adapt_domain(frame.col(self.output["response"]),
                         self.output["domain"])
        n = frame.nrows
        npad = len(f)
        y = np.pad(y, (0, npad - n), constant_values=-1)
        w = np.asarray(frame.valid_weights()) * (y >= 0)
        # squash the decision value through a sigmoid for AUC/logloss
        p = 1.0 / (1.0 + np.exp(-np.clip(f, -30, 30)))
        return mm.binomial_metrics(jnp.asarray(p.astype(np.float32)),
                                   jnp.asarray(np.maximum(y, 0).astype(np.float32)),
                                   jnp.asarray(w.astype(np.float32)))


@register
class PSVMEstimator(ModelBuilder):
    """h2o-py H2OSupportVectorMachineEstimator surface
    (h2o-py/h2o/estimators/psvm.py)."""

    algo = "psvm"

    DEFAULTS = dict(
        hyper_param=1.0, kernel_type="gaussian", gamma=-1.0,
        rank_ratio=-1.0, positive_weight=1.0, negative_weight=1.0,
        sv_threshold=1e-4, max_iterations=200, ignored_columns=None,
        seed=-1, nfolds=0, fold_assignment="auto", weights_column=None,
        fold_column=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown PSVM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)
        if str(self.params["kernel_type"]).lower() != "gaussian":
            raise ValueError("only kernel_type='gaussian' is supported "
                             "(reference PSVM.java supports gaussian only)")

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        rc = frame.col(y)
        if not (rc.is_categorical and rc.cardinality == 2):
            raise ValueError("PSVM needs a binary categorical response")
        di = build_datainfo(frame, x, standardize=True,
                            use_all_factor_levels=True)
        n = frame.nrows
        npad = di.X.shape[0]
        yv = adapt_domain(rc, rc.domain)
        yv = np.pad(yv, (0, npad - n), constant_values=-1)
        w_valid = np.asarray(frame.valid_weights()) * (yv >= 0)
        if p.get("weights_column") and p["weights_column"] in frame:
            wc = frame.col(p["weights_column"]).to_numpy()
            wc = np.pad(np.where(np.isnan(wc), 0.0, wc), (0, npad - n))
            w_valid = w_valid * wc
        ypm = jnp.asarray(np.where(yv == 1, 1.0, -1.0).astype(np.float32))

        gamma = float(p["gamma"])
        if gamma <= 0:
            gamma = 1.0 / max(di.P, 1)
        rr = float(p["rank_ratio"])
        rank = int(np.sqrt(n)) if rr <= 0 else max(int(n * rr), 1)
        rank = min(rank, 256, n)

        job.update(0.1, f"ICF rank {rank}")
        V, pivots, rank = icf(di.X, jnp.asarray(w_valid.astype(np.float32)),
                              gamma, rank)
        V1 = jnp.concatenate([V, jnp.ones((npad, 1), jnp.float32)], axis=1)
        V1 = V1 * jnp.asarray(w_valid > 0, jnp.float32)[:, None]

        C = float(p["hyper_param"])
        cw = jnp.asarray(np.where(yv == 1, C * float(p["positive_weight"]),
                                  C * float(p["negative_weight"]))
                         .astype(np.float32)) * jnp.asarray(
            w_valid.astype(np.float32))
        w_b = jnp.zeros((rank + 1,), jnp.float32)
        last = np.inf
        for it in range(int(p["max_iterations"])):
            w_b, obj = _newton_step(w_b, V1, ypm, cw)
            obj = float(obj)
            job.update(0.8 / int(p["max_iterations"]), f"newton {it}")
            if abs(last - obj) < 1e-7 * max(abs(obj), 1.0):
                break
            last = obj

        # support vectors from the L2-SVM KKT: alpha_i = 2 cw_i ξ_i
        f = np.asarray(V1 @ w_b)
        xi = np.maximum(1.0 - np.where(yv == 1, 1.0, -1.0) * f, 0.0)
        alpha = 2.0 * np.asarray(cw) * xi
        sv = (alpha > float(p["sv_threshold"])) & (w_valid > 0)

        # out-of-sample feature map: phi(x) = k(x, pivots) @ L^{-T}
        L = np.asarray(V)[pivots][:, :rank]
        Linv_t = np.linalg.solve(L.astype(np.float64),
                                 np.eye(rank)).T.astype(np.float32)
        pivot_rows = np.asarray(di.X)[pivots]

        output = {"category": ModelCategory.BINOMIAL, "response": y,
                  "names": list(x), "domain": rc.domain, "nclasses": 2,
                  "svs_count": int(sv.sum()),
                  "bsv_count": int(((alpha > 0) & (xi >= 1.0)).sum()),
                  "rank": rank, "gamma": gamma,
                  "default_threshold": 0.5}
        model = PSVMModel(p, output, np.asarray(w_b), pivot_rows, Linv_t,
                          gamma, stats_of(di), list(x))
        model.training_metrics = model.model_performance(frame)
        return model
